"""Chaos ladder: kill a worker node mid-run across the four workload
shapes the repo benchmarks (transfer / pipeline / sebulba / serving) and
prove the resilience stack end to end:

  * every rung COMPLETES CORRECTLY after the kill — lost objects are
    reconstructed from lineage (or retried) transparently at get() time;
  * the chaos run's wall clock stays within 3x the no-fault baseline of
    the same workload (recovery is re-execution, not a hang);
  * recovery cost is visible per phase in the head timeline
    (`python -m ray_tpu timeline`): recover.detect / recover.reconstruct
    windows from the lineage plane, reconcile.replace /
    reconcile.recovered from the autoscaler reconciler;
  * a dedicated reconcile rung kills a provider-launched node and asserts
    the reconciler turns the node_dead alert into a create_node within
    two heartbeat intervals, with the alert-id -> create causality
    recorded.

Modes (same ladder contract as the other aux benches):
  --measure   full ladder: baseline + chaos per rung, one combined
              artifact under benchmarks/results/
  --smoke     fast tier-1 gate: one kill-mid-run rung + the reconcile
              rung, correctness asserts only (wall-clock ratios are for
              --measure; a loaded CI box makes them flaky)
  (no flag)   self-orchestrating parent (bench.run_aux_ladder)

Never imports jax — faults live in the control/data planes.
"""

import json
import os
import signal
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# keep ray_tpu.init() from importing jax for chip discovery
os.environ.setdefault("RAY_TPU_NUM_CHIPS", "0")

BLOCK_KB = int(os.environ.get("RAY_TPU_CHAOS_LADDER_KB", 2048))
TASK_S = float(os.environ.get("RAY_TPU_CHAOS_LADDER_TASK_S", 0.15))
SLOWDOWN_BUDGET = 3.0


def _wait_for(pred, timeout, msg):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.1)
    raise TimeoutError("timed out waiting for " + msg)


class _Cluster:
    """Head in-process + one worker-node agent subprocess (the
    chain_bench topology: two controllers, two shm arenas, one cluster)."""

    def __init__(self, head_cpus=2, node_cpus=2):
        import ray_tpu
        self.ray = ray_tpu
        ray_tpu.init(num_cpus=head_cpus, resources={"head_node": 1.0},
                     cluster_port=0)
        addr = ray_tpu.cluster_address()
        env = dict(os.environ)
        env.pop("RAY_TPU_ARENA", None)  # the node is its own session
        env.pop("RAY_TPU_ADDRESS", None)
        self.node = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.node_main",
             "--address", addr, "--num-cpus", str(node_cpus),
             "--resources", '{"worker_node": 1}'],
            env=env, stdin=subprocess.DEVNULL, start_new_session=True)
        _wait_for(lambda: len(ray_tpu.nodes()) == 2, 60, "node registration")
        self.node_id = next(r["node_id"] for r in ray_tpu.nodes()
                            if r["resources"].get("worker_node"))

    def kill_node(self):
        """SIGKILL the node's whole process group: agent + its workers die
        uncleanly, the head sees the TCP RST and fails over."""
        os.killpg(self.node.pid, signal.SIGKILL)
        _wait_for(lambda: len(self.ray.nodes()) == 1, 40, "death detection")

    def soft_affinity(self):
        """Prefer the node while alive, fall back to the head once it is
        dead — so reconstruction always has somewhere feasible to run."""
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy)
        return NodeAffinitySchedulingStrategy(node_id=self.node_id, soft=True)

    def close(self):
        if self.node.poll() is None:
            os.killpg(self.node.pid, signal.SIGKILL)
            self.node.wait(timeout=10)
        self.ray.shutdown()


# ------------------------------------------------------------------- rungs
#
# Each rung parks intermediate results on the worker node, optionally
# SIGKILLs it mid-run (after half the results are consumed), then verifies
# every final value — identical math in baseline and chaos runs.

def _rung_transfer(cl, kill):
    """transfer_bench shape: blocks produced on the node, pulled one by
    one to the driver over the data plane; the kill lands between pulls,
    so later gets() reconstruct instead of pulling."""
    import numpy as np
    ray = cl.ray
    n_blocks, n = 6, BLOCK_KB * 1024 // 8
    strat = cl.soft_affinity()

    @ray.remote(num_cpus=0.5)
    def produce(i):
        time.sleep(TASK_S)
        return np.full(n, float(i))

    refs = [produce.options(scheduling_strategy=strat).remote(i)
            for i in range(n_blocks)]
    for i, ref in enumerate(refs):
        if kill and i == n_blocks // 2:
            cl.kill_node()
        out = ray.get(ref, timeout=120)
        assert out.shape == (n,) and float(out[0]) == float(i), (i, out[:3])
    return n_blocks


def _rung_pipeline(cl, kill):
    """pipeline_bench shape: two dependent stages per lane on the node,
    folded on the head — the kill loses BOTH stages' outputs, so recovery
    walks the lineage recursively (stage2 needs stage1 re-run first)."""
    import numpy as np
    ray = cl.ray
    lanes, n = 4, BLOCK_KB * 1024 // 8
    strat = cl.soft_affinity()

    @ray.remote(num_cpus=0.5)
    def stage1(i):
        time.sleep(TASK_S)
        return np.full(n, float(i))

    @ray.remote(num_cpus=0.5)
    def stage2(a):
        time.sleep(TASK_S / 2)
        return a * 2.0 + 1.0

    @ray.remote(resources={"head_node": 0.01})
    def fold(a):
        return float(a[0]) + float(a[-1])

    outs = [stage2.options(scheduling_strategy=strat).remote(
        stage1.options(scheduling_strategy=strat).remote(i))
        for i in range(lanes)]
    finals = []
    for i, ref in enumerate(outs):
        if kill and i == lanes // 2:
            cl.kill_node()
        finals.append(ray.get(fold.remote(ref), timeout=120))
    assert finals == [2.0 * (2.0 * i + 1.0) for i in range(lanes)], finals
    return lanes


def _rung_sebulba(cl, kill):
    """sebulba shape: rollout batches produced on the node (actor-side of
    the RL pipeline), a learner step on the head folds each batch; the
    kill lands between learner steps, so later batches reconstruct."""
    import numpy as np
    ray = cl.ray
    batches, per_batch, n = 4, 2, BLOCK_KB * 1024 // 8
    strat = cl.soft_affinity()

    @ray.remote(num_cpus=0.5)
    def rollout(b, j):
        time.sleep(TASK_S)
        return np.full(n, float(b * per_batch + j))

    @ray.remote(resources={"head_node": 0.01})
    def learn(*trajs):
        return sum(float(t[0]) for t in trajs)

    plan = [[rollout.options(scheduling_strategy=strat).remote(b, j)
             for j in range(per_batch)] for b in range(batches)]
    total = 0.0
    for b, batch in enumerate(plan):
        if kill and b == batches // 2:
            cl.kill_node()
        total += ray.get(learn.remote(*batch), timeout=120)
    expect = float(sum(range(batches * per_batch)))
    assert total == expect, (total, expect)
    return batches


def _rung_serving(cl, kill):
    """serving shape: a stream of small requests routed at the node; the
    kill lands while requests are IN FLIGHT, so the dead node's running
    tasks are retried rather than reconstructed (results are inline)."""
    ray = cl.ray
    n_req = 24
    strat = cl.soft_affinity()

    @ray.remote(num_cpus=0.5)
    def request(i):
        time.sleep(TASK_S / 3)
        return i * i

    refs = [request.options(scheduling_strategy=strat).remote(i)
            for i in range(n_req)]
    if kill:
        cl.kill_node()  # immediately: most requests still queued/running
    got = ray.get(refs, timeout=120)
    assert got == [i * i for i in range(n_req)], got
    return n_req


def _rung_spill(cl, kill):
    """tiered-memory shape (ISSUE 19): the head's store is caught
    mid-ladder — every driver-owned block force-demoted to the disk tier —
    when the worker node dies. Driver-owned blocks must come back via
    restore-from-disk, node-held blocks via lineage reconstruction; the
    run must never hang, and the pressure loop must never have demoted a
    prefetch-pinned object."""
    import asyncio

    import numpy as np

    from ray_tpu import api
    from ray_tpu.util import metrics
    ray = cl.ray
    n_blocks, n = 4, BLOCK_KB * 1024 // 8
    strat = cl.soft_affinity()

    @ray.remote(num_cpus=0.5)
    def produce(i):
        time.sleep(TASK_S)
        return np.full(n, float(i))

    node_refs = [produce.options(scheduling_strategy=strat).remote(i)
                 for i in range(n_blocks)]
    puts = [ray.put(np.full(n, 100.0 + i)) for i in range(n_blocks)]
    ray.wait(node_refs, num_returns=n_blocks, timeout=120)

    rt = api._runtime
    rt.client.flush()

    async def demote_all():
        c = rt.controller
        for _ in range(300):
            if all(c.objects.get(r.id) is not None
                   and c.objects[r.id].location == "shm" for r in puts):
                break
            await asyncio.sleep(0.02)
        c._spill_down(0, pressure=True)
        return [c.objects[r.id].location for r in puts]

    locs = asyncio.run_coroutine_threadsafe(demote_all(), rt.loop).result(60)
    assert all(loc == "spilled" for loc in locs), locs

    sc0 = metrics.spill_counters()
    if kill:
        cl.kill_node()
    # restore-from-disk: driver-owned blocks come back bit-identical
    for i, got in enumerate(ray.get(puts, timeout=120)):
        assert float(got[0]) == 100.0 + i and got.shape == (n,), (i, got[:3])
    # lineage: node-held blocks reconstruct (or were already shipped)
    for i, got in enumerate(ray.get(node_refs, timeout=120)):
        assert float(got[0]) == float(i) and got.shape == (n,), (i, got[:3])
    sc1 = metrics.spill_counters()
    assert sc1["restored_objects"] - sc0["restored_objects"] >= n_blocks, (
        sc0, sc1)
    assert sc1["pinned_demotions"] == 0, sc1
    return 2 * n_blocks


_RUNGS = [("transfer", _rung_transfer), ("pipeline", _rung_pipeline),
          ("sebulba", _rung_sebulba), ("serving", _rung_serving),
          ("spill", _rung_spill)]


def _recovery_windows(node_id=None, prefix=None):
    """Pull the recovery-phase spans out of the head timeline — the same
    events `python -m ray_tpu timeline` exports (cat == "recovery").
    The trace ring is process-wide, so filter to this rung's dead node
    (or span-name prefix) to keep each record self-describing."""
    from ray_tpu import api
    out = []
    for ev in api.timeline():
        if ev.get("cat") != "recovery":
            continue
        args = ev.get("args") or {}
        if node_id is not None and args.get("node_id") != node_id:
            continue
        if prefix is not None and not str(ev.get("name", "")).startswith(prefix):
            continue
        out.append({"name": ev.get("name"),
                    "dur_s": round(ev.get("dur", 0) / 1e6, 4),
                    "args": args})
    return out


def _run_rung(name, fn, kill):
    from ray_tpu.util import metrics
    recon0 = metrics._counter_total("reconstructions_total")
    cl = _Cluster()
    try:
        t0 = time.perf_counter()
        units = fn(cl, kill)
        wall = time.perf_counter() - t0
        rec = {"wall_s": round(wall, 3), "units": units, "killed": kill}
        if kill:
            rec["recovery_windows"] = _recovery_windows(node_id=cl.node_id)
            rec["reconstructions"] = (
                metrics._counter_total("reconstructions_total") - recon0)
            # process-lifetime transfer totals (retry/deadline visibility)
            rec["transfer_totals"] = metrics.transfer_counters()
        return rec
    finally:
        cl.close()


def _rung_reconcile():
    """Alert-driven replacement: a provider-launched node is SIGKILLed;
    the head reconciler must consume the node_dead alert and create_node a
    replacement within two heartbeat intervals, with the causality chain
    (alert id -> terminate_dead -> replace -> recovered) on record."""
    import ray_tpu
    from ray_tpu._private import state
    from ray_tpu._private.cluster import HEARTBEAT_S
    from ray_tpu.autoscaler import SubprocessNodeProvider, sdk

    ray_tpu.init(num_cpus=2, resources={"head_node": 1.0}, cluster_port=0)
    provider = SubprocessNodeProvider(
        cpus_per_node=2.0, extra_resources={"worker_node": 1.0})
    try:
        sdk.set_node_provider(provider, max_nodes=2)
        ctrl = state.global_client().controller
        assert ctrl.reconciler is not None, "reconciler not installed"
        handle = provider.create_node({"CPU": 2.0}, ray_tpu.cluster_address())
        ctrl._provider_nodes[handle] = {"CPU": 2.0}  # as _create would
        _wait_for(lambda: len(ray_tpu.nodes()) == 2, 60, "node registration")
        dead_pid = provider.pid_of(handle)

        t_kill = time.time()
        os.killpg(dead_pid, signal.SIGKILL)
        _wait_for(lambda: len(ray_tpu.nodes()) == 1, 10 * HEARTBEAT_S,
                  "death detection")
        # replacement registered = back to 2 live nodes with a NEW agent pid
        _wait_for(lambda: len(ray_tpu.nodes()) == 2, 30 * HEARTBEAT_S,
                  "replacement node registration")
        _wait_for(lambda: any(e["action"] == "recovered"
                              for e in ctrl.reconciler.status()["events"]),
                  15 * HEARTBEAT_S, "reconciler recovered record")

        st = ctrl.reconciler.status()
        events = st["events"]
        alert = next(ev for ev in ctrl.health.alerts.events()
                     if ev["kind"] == "node_dead")
        replace = next(e for e in events if e["action"] == "replace")
        recovered = next(e for e in events if e["action"] == "recovered")
        assert replace["alert_id"] == alert["id"], (replace, alert)
        assert recovered["alert_id"] == alert["id"], (recovered, alert)
        assert any(e["action"] == "terminate_dead" and e["handle"] == handle
                   for e in events), events
        replace_latency = replace["ts"] - alert["ts"]
        assert replace_latency <= 2 * HEARTBEAT_S, (
            f"replacement took {replace_latency:.2f}s "
            f"(> 2 heartbeats = {2 * HEARTBEAT_S}s)")
        return {"heartbeat_s": HEARTBEAT_S,
                "detect_s": round(alert["ts"] - t_kill, 3),
                "replace_latency_s": round(replace_latency, 3),
                "recovered_latency_s": round(recovered["ts"] - alert["ts"], 3),
                "replacements": st["replacements"],
                "events": events,
                "recovery_windows": _recovery_windows(prefix="reconcile.")}
    finally:
        provider.shutdown()
        ray_tpu.shutdown()


def _rung_fleet():
    """Serve-fleet rung (ISSUE 20): SIGKILL one of three replicas under
    load. Gates — zero failed requests (the handle retries on a survivor),
    bounded p99 during the chaos burst, and after one handle refresh
    interval the controller has pruned the corpse so no request pays a
    died-retry again."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.controller import get_controller
    from ray_tpu.util import metrics

    ray_tpu.init(num_cpus=6, cluster_port=0)
    try:
        @serve.deployment(num_replicas=3, max_ongoing_requests=8)
        class Echo:
            def ping(self, i):
                time.sleep(0.01)
                return i

        h = serve.run(Echo.bind(), name="chaos-fleet")
        hp = h.options(method_name="ping")

        def burst(n):
            t0 = time.time()
            resps = [hp.remote(i) for i in range(n)]
            failed, lats = 0, []
            for i, r in enumerate(resps):
                t1 = time.time()
                try:
                    assert r.result(timeout_s=60) == i
                except Exception:  # noqa: BLE001 - counted by the gate
                    failed += 1
                lats.append(time.time() - t1)
            lats.sort()
            return {"n": n, "failed": failed, "wall_s": time.time() - t0,
                    "p99_s": round(lats[int(len(lats) * 0.99)
                                        if len(lats) > 1 else -1], 4)}

        base = burst(40)
        ctrl = get_controller()
        reps = ray_tpu.get(ctrl.get_replicas.remote("chaos-fleet", "Echo"))
        victim_pid = ray_tpu.get(reps[0].stats.remote())["pid"]
        os.kill(victim_pid, signal.SIGKILL)
        chaos = burst(40)
        d_mid = metrics.serve_fleet_counters()["died_retries"]
        # > handle refresh TTL (0.5s) + death-report round trip: every
        # handle's next pick must come from the pruned survivor list
        time.sleep(0.8)
        steady = burst(30)
        d_end = metrics.serve_fleet_counters()["died_retries"]
        survivors = len(ray_tpu.get(
            ctrl.get_replicas.remote("chaos-fleet", "Echo")))
        rec = {"baseline": base, "chaos": chaos, "steady": steady,
               "died_retries": round(d_mid),
               "died_retries_after_refresh": round(d_end - d_mid),
               "survivors": survivors}
        assert chaos["failed"] == 0 and steady["failed"] == 0, rec
        assert d_mid >= 1, rec                       # the kill was felt
        assert rec["died_retries_after_refresh"] == 0, rec  # corpse pruned
        assert survivors == 2, rec
        assert chaos["p99_s"] <= max(5 * base["p99_s"], 2.0), rec
        serve.shutdown()
        return rec
    finally:
        ray_tpu.shutdown()


# ------------------------------------------------------------------- modes

def run_ladder(rungs=None):
    out = {}
    for name, fn in (rungs or _RUNGS):
        base = _run_rung(name, fn, kill=False)
        chaos = _run_rung(name, fn, kill=True)
        slowdown = round(chaos["wall_s"] / max(base["wall_s"], 1e-9), 2)
        out[name] = {"baseline": base, "chaos": chaos,
                     "slowdown": slowdown,
                     "ok": slowdown <= SLOWDOWN_BUDGET}
    out["reconcile"] = _rung_reconcile()
    out["fleet"] = _rung_fleet()
    return out


def measure():
    from bench import _INIT_SENTINEL, _write_result_artifact
    print(f"{_INIT_SENTINEL} backend=chaos", file=sys.stderr, flush=True)
    rec = {"bench": "chaos_ladder", "backend": "chaos",
           "block_kb": BLOCK_KB, "task_s": TASK_S,
           "slowdown_budget": SLOWDOWN_BUDGET}
    rec.update(run_ladder())
    rec["artifact"] = _write_result_artifact("chaos_ladder", rec)
    print(json.dumps(rec))


def smoke():
    """Tier-1 chaos gate: one kill-mid-run rung must complete correctly
    (reconstruction) and the reconciler must replace a killed provider
    node — correctness only, no wall-clock ratios."""
    rec = {"bench": "chaos_ladder_smoke"}
    rec["transfer"] = _run_rung("transfer", _rung_transfer, kill=True)
    assert rec["transfer"]["reconstructions"] >= 1, rec
    # kill-mid-spill (ISSUE 19): restore-from-disk + lineage, never hangs
    rec["spill"] = _run_rung("spill", _rung_spill, kill=True)
    rec["reconcile"] = _rung_reconcile()
    # serve-fleet kill (ISSUE 20): re-route on survivor, corpse pruned
    # within one refresh interval, zero failed requests
    rec["fleet"] = _rung_fleet()
    print(json.dumps(rec))


if __name__ == "__main__":
    if "--measure" in sys.argv[1:]:
        measure()
    elif "--smoke" in sys.argv[1:]:
        smoke()
    else:
        from bench import run_aux_ladder
        sys.exit(run_aux_ladder(os.path.abspath(__file__)))
