#!/usr/bin/env bash
# Build every native control-plane extension ahead of time (the Python
# bindings also build on-demand; this script exists for CI images and for a
# visible one-shot "does the toolchain work" check).
#
#   shm_store      src/shm_store.cpp      — shared-memory object store arena
#   sched_queue    src/sched_queue.cpp    — ready-queue index
#   frame_codec    src/frame_codec.cpp    — wire-frame scanner/validator
#   obj_directory  src/obj_directory.cpp  — id-sharded object/actor directory
#
# Each target goes through its Python binding's _compile() so the cache key
# (mtime vs the cached .so under ray_tpu/_native/_build/) and the compiler
# flags stay defined in exactly one place. Exit code is the number of
# targets that failed; RAY_TPU_NATIVE=0 environments still pass --check.
set -u
cd "$(dirname "$0")/.."

MODE="${1:-build}"

python - "$MODE" <<'EOF'
import sys

MODULES = [
    ("shm_store", "ray_tpu._native.store"),
    ("sched_queue", "ray_tpu._native.schedq"),
    ("frame_codec", "ray_tpu._native.codec"),
    ("obj_directory", "ray_tpu._native.objdir"),
]

failed = 0
for name, modpath in MODULES:
    try:
        mod = __import__(modpath, fromlist=["_compile"])
        so = mod._compile()
        print(f"  [ok] {name:14s} -> {so}")
    except Exception as e:  # noqa: BLE001 - report and count
        failed += 1
        msg = str(e).replace("\n", " ")[:200]
        print(f"  [FAIL] {name:14s} {msg}")

if sys.argv[1] == "check" and failed:
    print(f"{failed} native target(s) unavailable "
          f"(pure-Python fallbacks will be used)")
sys.exit(failed)
EOF
