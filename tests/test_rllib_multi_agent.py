"""RLlib multi-agent basics (VERDICT r1 #6; reference:
rllib/env/multi_agent_env.py): MultiAgentEnv protocol, policy mapping,
shared + independent learner modes, per-policy metrics."""

import numpy as np
import pytest

from ray_tpu.rllib.multi_agent import (MultiAgentBatch, MultiAgentEnv,
                                       MultiAgentEnvRunner, module_specs_for)


class MatchGame(MultiAgentEnv):
    """Cooperative 2-agent game: both see the same random target in {0,1};
    each gets +1 for picking the target, and a +1 bonus each when BOTH do.
    Optimal joint return = 4/step; random play averages 1.5/step."""

    def __init__(self, episode_len=16, seed=0):
        import gymnasium as gym
        self.possible_agents = ["a0", "a1"]
        self.observation_spaces = {
            a: gym.spaces.Box(-1.0, 1.0, (2,), np.float32)
            for a in self.possible_agents}
        self.action_spaces = {a: gym.spaces.Discrete(2)
                              for a in self.possible_agents}
        self.episode_len = episode_len
        self._rng = np.random.default_rng(seed)
        self._t = 0
        self._target = 0

    def _obs(self):
        onehot = np.zeros(2, np.float32)
        onehot[self._target] = 1.0
        return {a: onehot.copy() for a in self.possible_agents}

    def reset(self, *, seed=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        self._target = int(self._rng.integers(2))
        return self._obs(), {a: {} for a in self.possible_agents}

    def step(self, action_dict):
        correct = {a: int(action_dict[a]) == self._target
                   for a in self.possible_agents}
        bonus = 1.0 if all(correct.values()) else 0.0
        rewards = {a: float(correct[a]) + bonus for a in self.possible_agents}
        self._t += 1
        self._target = int(self._rng.integers(2))
        done = self._t >= self.episode_len
        terms = {a: done for a in self.possible_agents}
        terms["__all__"] = done
        truncs = {a: False for a in self.possible_agents}
        truncs["__all__"] = False
        return self._obs(), rewards, terms, truncs, \
            {a: {} for a in self.possible_agents}


def _runner(mapping, rollout_len=32):
    from ray_tpu.rllib.rl_module import RLModule
    env_creator = lambda: MatchGame()
    specs = module_specs_for(MatchGame(), mapping, hiddens=(32,))
    modules = {pid: RLModule(s) for pid, s in specs.items()}
    return MultiAgentEnvRunner(env_creator, policy_mapping_fn=mapping,
                               modules=modules, rollout_len=rollout_len)


def test_runner_shapes_and_per_policy_batches():
    mapping = lambda aid: aid  # independent: one policy per agent
    runner = _runner(mapping)
    params = runner.init_params()
    ma_batch, metrics = runner.sample(params)
    assert isinstance(ma_batch, MultiAgentBatch)
    assert sorted(ma_batch.keys()) == ["a0", "a1"]
    for pid in ("a0", "a1"):
        b = ma_batch[pid]
        assert b["obs"].shape == (32, 1, 2)
        assert b["rewards"].shape == (32, 1)
        assert b["bootstrap_value"].shape == (1,)
    assert ma_batch.env_steps() == 32
    assert ma_batch.agent_steps() == 64
    assert metrics["episodes_this_iter"] == 2  # 32 steps / 16-step episodes


def test_shared_policy_batches_agents_together():
    mapping = lambda aid: "shared"
    runner = _runner(mapping)
    params = runner.init_params()
    ma_batch, _ = runner.sample(params)
    assert sorted(ma_batch.keys()) == ["shared"]
    assert ma_batch["shared"]["obs"].shape == (32, 2, 2)  # both agents rows


def test_unknown_policy_mapping_raises():
    with pytest.raises(KeyError, match="not in"):
        from ray_tpu.rllib.rl_module import RLModule
        specs = module_specs_for(MatchGame(), lambda a: "p", hiddens=(16,))
        MultiAgentEnvRunner(lambda: MatchGame(),
                            policy_mapping_fn=lambda a: "other",
                            modules={"p": RLModule(specs["p"])})


def _train_ppo(mapping, policies, iters=10):
    from ray_tpu.rllib.algorithms.ppo import PPOConfig
    algo = (PPOConfig()
            .environment(lambda: MatchGame())
            .multi_agent(policies=policies, policy_mapping_fn=mapping)
            .training(train_batch_size=256, minibatch_size=64,
                      num_epochs=4, lr=1e-2, entropy_coeff=0.01)
            .env_runners(rollout_fragment_length=64)
            .build())
    best, last = -np.inf, None
    for _ in range(iters):
        result = algo.train()
        best = max(best, result.get("episode_return_mean", -np.inf))
        last = result
    algo.stop()
    return best, last


def test_ppo_multi_agent_shared_mode_learns():
    best, last = _train_ppo(lambda aid: "shared", ["shared"])
    assert sorted(last["learner"].keys()) == ["shared"]
    assert np.isfinite(last["learner"]["shared"]["total_loss"])
    # optimal 4/step * 16 steps = 64; random ~24. Demand clear improvement.
    assert best > 40, f"shared-mode PPO failed to learn: best={best}"


def test_ppo_multi_agent_independent_mode_learns():
    best, last = _train_ppo(lambda aid: aid, ["a0", "a1"])
    assert sorted(last["learner"].keys()) == ["a0", "a1"]
    for pid in ("a0", "a1"):
        assert np.isfinite(last["learner"][pid]["total_loss"])
    assert best > 40, f"independent-mode PPO failed to learn: best={best}"
