"""Util belt tests: ActorPool, Queue, Pool, metrics, tpu topology, state API,
timeline export, CLI."""

import time

import pytest


def test_actor_pool_ordered_and_unordered(ray_session):
    ray = ray_session
    from ray_tpu.util import ActorPool

    @ray.remote
    class Worker:
        def double(self, x):
            return x * 2

    pool = ActorPool([Worker.remote(), Worker.remote()])
    out = list(pool.map(lambda a, v: a.double.remote(v), range(6)))
    assert out == [0, 2, 4, 6, 8, 10]  # submission order preserved

    out = sorted(pool.map_unordered(lambda a, v: a.double.remote(v), range(6)))
    assert out == [0, 2, 4, 6, 8, 10]

    # submit/get_next with backpressure past pool size
    for i in range(5):
        pool.submit(lambda a, v: a.double.remote(v), i)
    got = [pool.get_next(timeout=60) for _ in range(5)]
    assert got == [0, 2, 4, 6, 8]


def test_queue_basics(ray_session):
    from ray_tpu.util import Queue
    from ray_tpu.util.queue import Empty, Full

    q = Queue(maxsize=2)
    q.put(1)
    q.put(2)
    with pytest.raises(Full):
        q.put(3, block=False)
    assert q.qsize() == 2 and q.full()
    assert q.get() == 1
    assert q.get() == 2
    assert q.empty()
    with pytest.raises(Empty):
        q.get(block=False)
    with pytest.raises(Empty):
        q.get(timeout=0.1)
    q.put_nowait_batch([7, 8])
    assert q.get_nowait_batch(2) == [7, 8]
    q.shutdown()


def test_queue_shared_between_tasks(ray_session):
    ray = ray_session
    from ray_tpu.util import Queue

    q = Queue()

    @ray.remote
    def producer(queue, n):
        for i in range(n):
            queue.put(i)
        return "done"

    assert ray.get(producer.remote(q, 3), timeout=60) == "done"
    assert [q.get(timeout=10) for _ in range(3)] == [0, 1, 2]
    q.shutdown()


def test_multiprocessing_pool(ray_session):
    from ray_tpu.util.multiprocessing import Pool

    with Pool() as p:
        assert p.map(lambda x: x * x, range(5)) == [0, 1, 4, 9, 16]
        r = p.apply_async(lambda a, b: a + b, (2, 3))
        assert r.get(timeout=60) == 5
        assert p.apply(lambda: 7) == 7
        assert p.starmap(lambda a, b: a * b, [(2, 3), (4, 5)]) == [6, 20]
        assert sorted(p.imap_unordered(lambda x: x + 1, range(4))) == [1, 2, 3, 4]


def test_metrics():
    from ray_tpu.util import metrics

    metrics.clear_registry()
    c = metrics.Counter("requests", "total requests", ("route",))
    c.inc()
    c.inc(2, tags={"route": "/a"})
    with pytest.raises(ValueError):
        c.inc(-1)

    g = metrics.Gauge("inflight")
    g.set(5)
    g.dec()

    h = metrics.Histogram("latency", boundaries=[0.1, 1.0])
    for v in (0.05, 0.5, 5.0):
        h.observe(v)

    snap = {m["name"]: m for m in metrics.collect()}
    assert snap["requests"]["values"][()] == 1
    assert snap["requests"]["values"][(("route", "/a"),)] == 2
    assert snap["inflight"]["values"][()] == 4
    assert snap["latency"]["buckets"][()] == [1, 1, 1]
    assert snap["latency"]["count"][()] == 3
    metrics.clear_registry()


def test_tpu_topology(monkeypatch):
    from ray_tpu.util import tpu

    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5e-16")
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host0,host1")
    monkeypatch.setenv("TPU_WORKER_ID", "1")
    monkeypatch.setenv("TPU_NAME", "my-slice")
    topo = tpu.slice_topology()
    assert topo["generation"] == "v5e"
    assert topo["num_chips"] == 16
    assert topo["num_hosts"] == 2
    assert topo["chips_per_host"] == 8
    assert topo["worker_id"] == 1
    assert topo["pod_name"] == "my-slice"
    assert tpu.mesh_shape_for_slice(tp=4) == (4, 4)

    # v4 counts cores in the accelerator string
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v4-8")
    assert tpu.get_num_chips_in_slice() == 4


def test_state_api(ray_session):
    ray = ray_session
    from ray_tpu.util import state as state_api

    @ray.remote
    class Pinger:
        def ping(self):
            return "pong"

    p = Pinger.options(name="state-test-actor").remote()
    ray.get(p.ping.remote(), timeout=60)

    actors = state_api.list_actors(filters=[("name", "=", "state-test-actor")])
    assert len(actors) == 1 and actors[0]["state"] == "ALIVE"
    assert state_api.summarize_actors().get("ALIVE", 0) >= 1
    tasks = state_api.list_tasks()
    assert any(t["name"].endswith("ping") for t in tasks)
    objs = state_api.summarize_objects()
    assert objs["count"] >= 1
    nodes = state_api.list_nodes()
    assert nodes and nodes[0]["alive"]
    ray.kill(p)


def test_timeline_export(ray_session, tmp_path):
    ray = ray_session

    @ray.remote
    def traced():
        return 1

    ray.get([traced.remote() for _ in range(3)])
    out = str(tmp_path / "trace.json")
    ray.timeline(out)
    import json
    with open(out) as f:
        events = json.load(f)
    assert isinstance(events, list) and len(events) >= 3
    assert all("ts" in e and "dur" in e for e in events
               if e.get("ph") == "X")


def test_cli_topology(monkeypatch, capsys):
    from ray_tpu.__main__ import main

    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5e-8")
    main(["topology"])
    out = capsys.readouterr().out
    assert '"generation": "v5e"' in out


def test_actor_pool_mixed_ordered_unordered(ray_session):
    """get_next after get_next_unordered consumed an out-of-order seq must
    skip the gap, not spin (r5 review): 3 tasks, take one unordered, then
    drain the rest in order."""
    import ray_tpu as ray
    from ray_tpu.util import ActorPool

    @ray.remote
    class A:
        def echo(self, v):
            return v

    pool = ActorPool([A.remote() for _ in range(3)])
    for v in (10, 11, 12):
        pool.submit(lambda a, v: a.echo.remote(v), v)
    first = pool.get_next_unordered(timeout=60)
    rest = []
    while pool.has_next():
        rest.append(pool.get_next(timeout=60))
    assert sorted([first] + rest) == [10, 11, 12]
    assert rest == sorted(rest)  # ordered drain stays in submission order
