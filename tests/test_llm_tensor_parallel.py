"""Tensor-parallel LLM serving (LLMConfig.tp; BASELINE config #3 — one
inference replica spanning a TPU slice). GSPMD partitions the same jitted
prefill/decode programs over a {"tp"} mesh; params shard via llama_rules,
the KV cache on its kv-head axis. Equivalence is asserted in float32 —
with bf16 activations the tp all-reduce's different summation order flips
near-tied argmaxes of an untrained model (expected, not a bug)."""

import asyncio

import pytest


def _run(coro):
    return asyncio.run(coro)


def _make(tp, **kw):
    from ray_tpu.serve.llm import LLMConfig, LLMServer
    return LLMServer(LLMConfig(preset="tiny", max_batch_slots=2,
                               max_seq_len=128, tp=tp,
                               dtype="float32", param_dtype="float32", **kw))


def test_tp_matches_single_device_greedy():
    prompt = [5, 6, 7, 8] * 4
    ref = _run(_make(1).generate(prompt, max_tokens=20))["tokens"]
    tp = _run(_make(2).generate(prompt, max_tokens=20))["tokens"]
    assert tp == ref


def test_tp2_and_concurrent_requests():
    prompt = [9, 3, 9, 3, 9, 3]
    srv = _make(2)
    ref = _make(1)

    async def pair(s):
        return await asyncio.gather(
            s.generate(prompt, max_tokens=12),
            s.generate(list(reversed(prompt)), max_tokens=12,
                       temperature=0.7))

    a = _run(pair(srv))
    b = _run(pair(ref))
    assert a[0]["tokens"] == b[0]["tokens"]      # greedy request exact
    assert len(a[1]["tokens"]) == 12             # sampled request completes


def test_tp_composes_with_speculation():
    """Speculative decoding is dense-path XLA, so it GSPMD-partitions the
    same way — greedy output must match the unsharded plain server."""
    prompt = [5, 6, 7, 8] * 4
    ref = _run(_make(1).generate(prompt, max_tokens=20))["tokens"]
    spec_tp = _make(2, speculate=4)
    out = _run(spec_tp.generate(prompt, max_tokens=20))["tokens"]
    assert out == ref
    st = spec_tp.stats()["speculation"]
    assert st["spec_ticks"] + st["decode_ticks"] > 0


def test_tp_validation():
    from ray_tpu.serve.llm import LLMConfig, LLMServer
    with pytest.raises(ValueError, match="paged"):
        LLMServer(LLMConfig(preset="tiny", tp=2, paged=True))
    with pytest.raises(ValueError, match="n_kv_heads"):
        # tiny has 2 kv heads; tp=3 cannot shard them
        LLMServer(LLMConfig(preset="tiny", tp=3))


def test_params_and_cache_born_sharded():
    """tp exists for models too big for one chip: params and KV cache
    must be allocated shard-by-shard (never staged whole on device 0),
    and each shard must hold exactly 1/tp of the kv-head axis."""
    srv = _make(2)
    kv = srv.cache.k[0]
    assert kv.sharding.spec == (None, None, "tp", None) or \
        tuple(kv.sharding.spec) == (None, None, "tp", None)
    shard = kv.addressable_shards[0]
    assert shard.data.shape[2] == kv.shape[2] // 2
    wq = None
    import jax
    for path, leaf in jax.tree_util.tree_flatten_with_path(srv.params)[0]:
        if "wq" in str(path):
            wq = leaf
            break
    assert wq is not None
    assert wq.addressable_shards[0].data.shape[-1] == wq.shape[-1] // 2
