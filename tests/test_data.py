"""Data tests (SURVEY.md §4): transform correctness vs pandas, shuffle
determinism with seed, iterator batching shapes, IO roundtrips."""

import numpy as np
import pandas as pd
import pytest

from ray_tpu import data as rd


def test_from_items_and_take():
    ds = rd.from_items([{"a": i, "b": i * 2} for i in range(10)])
    rows = ds.take(3)
    assert rows == [{"a": 0, "b": 0}, {"a": 1, "b": 2}, {"a": 2, "b": 4}]
    assert ds.count() == 10


def test_range_and_scalar_items():
    ds = rd.range(100)
    assert ds.count() == 100
    assert ds.take(2) == [{"id": 0}, {"id": 1}]
    ds2 = rd.from_items([1, 2, 3])
    assert [r["value"] for r in ds2.take_all()] == [1, 2, 3]


def test_map_filter_flat_map():
    ds = (rd.range(20)
          .map(lambda r: {"id": r["id"], "sq": r["id"] ** 2})
          .filter(lambda r: r["id"] % 2 == 0)
          .flat_map(lambda r: [{"v": r["sq"]}, {"v": -r["sq"]}]))
    vals = [r["v"] for r in ds.take_all()]
    assert vals[:4] == [0, 0, 4, -4]
    assert len(vals) == 20


def test_map_batches_formats():
    ds = rd.range(32)
    out_np = ds.map_batches(lambda b: {"x": b["id"] * 10},
                            batch_format="numpy")
    assert out_np.take(2) == [{"x": 0}, {"x": 10}]

    def pd_fn(df):
        df = df.copy()
        df["y"] = df["id"] + 1
        return df

    out_pd = ds.map_batches(pd_fn, batch_format="pandas")
    assert out_pd.take(1)[0] == {"id": 0, "y": 1}

    out_pa = ds.map_batches(lambda t: t, batch_format="pyarrow")
    assert out_pa.count() == 32


def test_column_ops():
    ds = rd.from_pandas(pd.DataFrame({"a": [1, 2], "b": [3, 4], "c": [5, 6]}))
    assert ds.select_columns(["a"]).columns() == ["a"]
    assert ds.drop_columns(["b"]).columns() == ["a", "c"]
    added = ds.add_column("d", lambda df: df["a"] + df["b"])
    assert added.take(1)[0]["d"] == 4
    renamed = ds.rename_columns({"a": "alpha"})
    assert "alpha" in renamed.columns()


def test_limit_union_zip():
    a = rd.range(10)
    b = rd.range(5).map(lambda r: {"id": r["id"] + 100})
    assert a.limit(3).count() == 3
    assert a.union(b).count() == 15
    z = rd.range(4).zip(rd.range(4).map(lambda r: {"other": r["id"] * 2}))
    row = z.take(2)[1]
    assert row == {"id": 1, "other": 2}


def test_random_shuffle_deterministic_with_seed():
    ds = rd.range(50)
    s1 = [r["id"] for r in ds.random_shuffle(seed=7).take_all()]
    s2 = [r["id"] for r in ds.random_shuffle(seed=7).take_all()]
    s3 = [r["id"] for r in ds.random_shuffle(seed=8).take_all()]
    assert s1 == s2
    assert s1 != s3
    assert sorted(s1) == list(range(50))


def test_sort_and_repartition():
    rng = np.random.default_rng(0)
    vals = rng.permutation(40)
    ds = rd.from_numpy(vals, column="x")
    out = [r["x"] for r in ds.sort("x").take_all()]
    assert out == sorted(vals.tolist())
    out_desc = [r["x"] for r in ds.sort("x", descending=True).take_all()]
    assert out_desc == sorted(vals.tolist(), reverse=True)
    assert ds.repartition(5).num_blocks() == 5


def test_groupby_aggregates_match_pandas():
    df = pd.DataFrame({"k": ["a", "b", "a", "b", "a"],
                       "v": [1.0, 2.0, 3.0, 4.0, 5.0]})
    ds = rd.from_pandas(df)
    got = {r["k"]: r["mean(v)"] for r in ds.groupby("k").mean("v").take_all()}
    want = df.groupby("k")["v"].mean().to_dict()
    assert got == want
    cnt = {r["k"]: r["count()"] for r in ds.groupby("k").count().take_all()}
    assert cnt == {"a": 3, "b": 2}
    s = {r["k"]: r["sum(v)"] for r in ds.groupby("k").sum("v").take_all()}
    assert s == df.groupby("k")["v"].sum().to_dict()


def test_splits():
    ds = rd.range(10)
    parts = ds.split(3)
    assert [p.count() for p in parts] == [4, 4, 2]
    a, b, c = ds.split_at_indices([2, 7])
    assert (a.count(), b.count(), c.count()) == (2, 5, 3)
    train, test = ds.train_test_split(0.3)
    assert (train.count(), test.count()) == (7, 3)


def test_iter_batches_shapes():
    ds = rd.range(25)
    batches = list(ds.iter_batches(batch_size=10, batch_format="numpy"))
    assert [len(b["id"]) for b in batches] == [10, 10, 5]
    batches = list(ds.iter_batches(batch_size=10, drop_last=True))
    assert [len(b["id"]) for b in batches] == [10, 10]
    # values survive re-chunking in order
    all_ids = np.concatenate([b["id"] for b in ds.iter_batches(batch_size=7)])
    np.testing.assert_array_equal(all_ids, np.arange(25))


def test_iter_device_batches():
    import jax
    ds = rd.range(16).map_batches(
        lambda b: {"x": b["id"].astype(np.float32)})
    out = list(ds.iter_device_batches(batch_size=8))
    assert len(out) == 2
    assert isinstance(out[0]["x"], jax.Array)


def test_tensor_columns_roundtrip():
    arr = np.arange(24, dtype=np.float32).reshape(6, 4)
    ds = rd.from_numpy(arr, column="feat")
    batch = ds.take_batch(6)
    np.testing.assert_array_equal(batch["feat"], arr)


def test_io_roundtrips(tmp_path):
    df = pd.DataFrame({"a": range(20), "b": [f"s{i}" for i in range(20)]})
    ds = rd.from_pandas(df)

    pq_dir = str(tmp_path / "pq")
    ds.write_parquet(pq_dir)
    back = rd.read_parquet(pq_dir)
    assert back.count() == 20
    assert back.sort("a").take(1)[0]["a"] == 0

    csv_dir = str(tmp_path / "csv")
    ds.write_csv(csv_dir)
    assert rd.read_csv(csv_dir).count() == 20

    json_dir = str(tmp_path / "js")
    ds.write_json(json_dir)
    assert rd.read_json(json_dir).count() == 20


def test_io_roundtrips_via_fs_uris(tmp_path):
    """Cloud-fs URI surface (VERDICT r4 missing #4): paths resolve through
    pyarrow.fs, proven here with file:// (same code path as gs:///s3://)."""
    df = pd.DataFrame({"a": range(12), "b": [i * 0.5 for i in range(12)]})
    ds = rd.from_pandas(df)

    uri = f"file://{tmp_path}/pq_uri"
    ds.write_parquet(uri)
    back = rd.read_parquet(uri)
    assert back.count() == 12
    assert back.sort("a").take(1)[0]["a"] == 0

    csv_uri = f"file://{tmp_path}/csv_uri"
    ds.write_csv(csv_uri)
    assert rd.read_csv(csv_uri).count() == 12

    js_uri = f"file://{tmp_path}/js_uri"
    ds.write_json(js_uri)
    assert rd.read_json(js_uri).count() == 12

    # text/binary/images resolve URIs too (r5 review: half-done surface)
    (tmp_path / "t").mkdir()
    (tmp_path / "t" / "a.txt").write_text("x\ny\n")
    assert rd.read_text(f"file://{tmp_path}/t").count() == 2
    assert rd.read_binary_files(
        f"file://{tmp_path}/t").take_all()[0]["bytes"] == b"x\ny\n"


def test_write_images_roundtrip(tmp_path, ray_session):
    """write_images (ref dataset.py:4522): HWC uint8 rows → one PNG per
    row, re-readable by read_images."""
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, (5, 10, 8, 3), dtype=np.uint8)
    rows = [{"image": imgs[i], "name": f"im{i}.png"} for i in range(5)]
    ds = rd.from_items(rows)
    out = str(tmp_path / "imgs")
    ds.write_images(out, column="image", filename_column="name")
    back = rd.read_images(out)
    assert back.count() == 5
    got = {tuple(r["image"].shape) for r in back.take_all()}
    assert got == {(10, 8, 3)}
    # default auto-naming path
    ds.write_images(str(tmp_path / "imgs2"), column="image")
    assert rd.read_images(str(tmp_path / "imgs2")).count() == 5


def test_read_text_and_binary(tmp_path):
    p = tmp_path / "f.txt"
    p.write_text("hello\nworld\n")
    ds = rd.read_text(str(p))
    assert [r["text"] for r in ds.take_all()] == ["hello", "world"]
    bin_ds = rd.read_binary_files(str(p), include_paths=True)
    row = bin_ds.take_all()[0]
    assert row["bytes"] == b"hello\nworld\n"
    assert row["path"].endswith("f.txt")


def test_lazy_execution_and_stats(tmp_path):
    marker = tmp_path / "ran"

    def spy(b):
        # file marker: visible whether the op runs inline or in a worker task
        marker.write_text("x")
        return b

    ds = rd.range(10).map_batches(spy)
    assert not marker.exists()  # nothing ran yet
    ds.count()
    assert marker.exists()  # consumption triggered execution
    assert "map_batches" in ds.stats() or "source" in ds.stats()


def test_preprocessors():
    df = pd.DataFrame({"x": [1.0, 2.0, 3.0, 4.0], "y": [10.0, 20.0, 30.0, 40.0],
                       "label": ["cat", "dog", "cat", "bird"]})
    ds = rd.from_pandas(df)

    sc = rd.StandardScaler(["x"]).fit(ds)
    out = sc.transform(ds).take_batch(4)
    np.testing.assert_allclose(out["x"].mean(), 0.0, atol=1e-6)
    np.testing.assert_allclose(out["x"].std(), 1.0, atol=1e-6)

    mm = rd.MinMaxScaler(["y"]).fit(ds)
    out = mm.transform(ds).take_batch(4)
    assert out["y"].min() == 0.0 and out["y"].max() == 1.0

    le = rd.LabelEncoder("label").fit(ds)
    out = le.transform(ds).take_batch(4)
    assert sorted(set(out["label"].tolist())) == [0, 1, 2]
    assert list(le.classes_) == ["bird", "cat", "dog"]

    cat = rd.Concatenator(["x", "y"], "features")
    out = cat.transform(ds).take_batch(4)
    assert out["features"].shape == (4, 2)

    chain = rd.Chain(rd.StandardScaler(["x"]), rd.Concatenator(["x", "y"]))
    out = chain.fit(ds).transform(ds).take_batch(4)
    assert out["concat_out"].shape == (4, 2)


def test_data_tasks_execution(ray_session):
    """Blocks flow through ray_tpu tasks when the runtime is up."""
    ds = rd.range(40, override_num_blocks=4).map_batches(
        lambda b: {"x": b["id"] * 2})
    vals = sorted(r["x"] for r in ds.take_all())
    assert vals == [i * 2 for i in range(40)]


def test_random_sample(ray_session):
    import ray_tpu.data as rdata
    ds = rdata.range(2000).repartition(4)
    n = ds.random_sample(0.25, seed=7).count()
    assert 300 < n < 700  # ~500 expected
    # deterministic under a fixed seed
    assert (ds.random_sample(0.25, seed=7).count()
            == ds.random_sample(0.25, seed=7).count())
    assert ds.random_sample(0.0).count() == 0
    assert ds.random_sample(1.0).count() == 2000
    import pytest as _pt
    with _pt.raises(ValueError):
        ds.random_sample(1.5)


def test_global_scalar_aggregates(ray_session):
    import numpy as np
    import ray_tpu.data as rdata
    vals = list(range(100))
    ds = rdata.from_items([{"id": v, "x": float(v)} for v in vals]) \
        .repartition(5)
    assert ds.sum(on="x") == float(np.sum(vals))
    assert ds.mean(on="x") == float(np.mean(vals))
    assert ds.min(on="x") == 0.0
    assert ds.max(on="x") == 99.0
    assert abs(ds.std(on="x") - float(np.std(vals, ddof=1))) < 1e-9
    # single numeric column -> on is optional
    one = rdata.from_items([{"v": float(i)} for i in range(10)])
    assert one.sum() == 45.0
    # ambiguous columns -> must name one
    import pytest as _pt
    with _pt.raises(ValueError, match="numeric"):
        ds.sum()


def test_std_no_catastrophic_cancellation(ray_session):
    """Large mean, tiny spread (timestamps ~1.7e9, std ~1): the naive
    E[x^2]-E[x]^2 form returns 0.0 here; Chan's combine must not."""
    import numpy as np
    import ray_tpu.data as rdata
    vals = 1.7e9 + np.arange(100, dtype=np.float64)
    ds = rdata.from_items([{"t": float(v)} for v in vals]).repartition(4)
    expected = float(np.std(vals, ddof=1))
    assert abs(ds.std(on="t") - expected) / expected < 1e-6


def test_random_sample_identical_blocks_decorrelated(ray_session):
    """Blocks with identical content must draw independent masks under a
    fixed seed (the executor's block index feeds the RNG)."""
    import ray_tpu.data as rdata
    ds = rdata.from_items([{"label": 0} for _ in range(4000)]) \
        .repartition(8)
    n = ds.random_sample(0.5, seed=11).count()
    # 8 identical correlated blocks would give n = 8*k (multiples of 8
    # with variance of a single 500-row draw); independent draws give a
    # binomial(4000, .5) count — check it is not a multiple of 8 AND lies
    # in the binomial 6-sigma band
    assert 1810 < n < 2190, n


def test_with_column_and_randomize_block_order():
    ds = rd.from_items([{"a": i} for i in range(12)]).repartition(4)
    ds2 = ds.with_column("b", lambda batch: batch["a"] * 3)
    assert all(r["b"] == r["a"] * 3 for r in ds2.take_all())
    # randomize_block_order: same rows, deterministic under seed
    r1 = ds.randomize_block_order(seed=7).take_all()
    r2 = ds.randomize_block_order(seed=7).take_all()
    assert r1 == r2
    assert sorted(r["a"] for r in r1) == list(range(12))


def test_split_proportionately():
    ds = rd.range(100)
    a, b, c = ds.split_proportionately([0.1, 0.3])
    assert (a.count(), b.count(), c.count()) == (10, 30, 60)
    got = [r["id"] for part in (a, b, c) for r in part.take_all()]
    assert got == list(range(100))
    with pytest.raises(ValueError):
        ds.split_proportionately([0.6, 0.5])


def test_to_pandas_and_iter_torch_batches():
    ds = rd.from_items([{"x": float(i), "y": i} for i in range(10)])
    df = ds.to_pandas()
    assert list(df["y"]) == list(range(10))
    with pytest.raises(ValueError):
        ds.to_pandas(limit=5)
    import torch
    batches = list(ds.iter_torch_batches(batch_size=4))
    assert [len(b["x"]) for b in batches] == [4, 4, 2]
    assert isinstance(batches[0]["x"], torch.Tensor)
    typed = next(iter(ds.iter_torch_batches(
        batch_size=4, dtypes={"x": torch.float64})))
    assert typed["x"].dtype == torch.float64


def test_write_tfrecords_numpy_webdataset_methods(tmp_path, ray_session):
    ds = rd.from_items(
        [{"a": i, "s": f"row{i}"} for i in range(9)]).repartition(3)
    # tfrecords: streamed one file per block, read back by read_tfrecords
    out = str(tmp_path / "tfr")
    ds.write_tfrecords(out)
    back = rd.read_tfrecords(out)
    assert sorted(r["a"] for r in back.take_all()) == list(range(9))
    # numpy: one .npy per block
    nout = tmp_path / "npy"
    ds.write_numpy(str(nout), column="a")
    arrs = [np.load(p) for p in sorted(nout.glob("*.npy"))]
    assert sorted(np.concatenate(arrs).tolist()) == list(range(9))
    # webdataset: tar shards keyed by __key__, bytes round-trip
    wds = rd.from_items(
        [{"__key__": f"k{i}", "img": bytes([i] * 4)} for i in range(4)])
    wout = str(tmp_path / "wds")
    wds.write_webdataset(wout)
    back = rd.read_webdataset(wout)
    rows = {r["__key__"]: r["img"] for r in back.take_all()}
    assert rows == {f"k{i}": bytes([i] * 4) for i in range(4)}


def test_scalar_aggregates_ignore_nulls():
    """One missing value must not poison sum/mean/std/min/max to NaN
    (reference aggregates default ignore_nulls=True)."""
    ds = rd.from_items([{"x": 1.0}, {"x": None}, {"x": 3.0}])
    assert ds.sum(on="x") == 4.0
    assert ds.mean(on="x") == 2.0
    assert ds.min(on="x") == 1.0
    assert ds.max(on="x") == 3.0
    assert abs(ds.std(on="x") - np.std([1.0, 3.0], ddof=1)) < 1e-12


def test_to_pandas_empty_and_webdataset_str_roundtrip(tmp_path):
    # empty result -> empty DataFrame, not None
    empty = rd.range(5).filter(lambda r: False)
    df = empty.to_pandas()
    assert df is not None and len(df) == 0
    # str columns round-trip without repr() quotes
    ds = rd.from_items([{"__key__": "k0", "txt": "hello"}])
    out = str(tmp_path / "wds_str")
    ds.write_webdataset(out)
    row = rd.read_webdataset(out).take_all()[0]
    assert row["txt"] == b"hello"


def test_randomize_block_order_is_lazy_on_block_op_chains():
    """The fast path permutes source thunks — no upstream execution at
    call time (the AllToAllOp fallback is only for post-barrier chains)."""
    pulled = []

    def tag(r):
        pulled.append(r["id"])
        return r

    # pure BlockOp chain over a 4-block source → thunk-permute fast path
    ds = rd.range(20, override_num_blocks=4).map(tag)
    pulled.clear()
    ro = ds.randomize_block_order(seed=1)   # must not execute anything
    assert pulled == []
    from ray_tpu.data.plan import DeferredSource
    assert isinstance(ro._plan.source, DeferredSource)
    assert sorted(r["id"] for r in ro.take_all()) == list(range(20))
    # block ORDER actually changed vs the unshuffled chain under this seed
    ids = [r["id"] for r in ro.take_all()]
    assert ids != list(range(20))


def test_write_webdataset_rejects_dotted_keys(tmp_path):
    ds = rd.from_items([{"__key__": "img.v2", "jpg": b"x"}])
    with pytest.raises(ValueError, match="__key__"):
        ds.write_webdataset(str(tmp_path / "w"))


def test_randomize_block_order_preserves_indexed_op_output():
    """Seeded random_sample derives randomness from stream position:
    appending randomize_block_order must reorder its OUTPUT, never change
    which rows were sampled (r5 review repro)."""
    base = rd.range(1000, override_num_blocks=4).random_sample(0.5, seed=7)
    want = sorted(r["id"] for r in base.take_all())
    got = sorted(r["id"] for r in
                 base.randomize_block_order(seed=1).take_all())
    assert got == want


def test_write_webdataset_rejects_slashed_keys(tmp_path):
    ds = rd.from_items([{"__key__": "a/b", "x": b"1"}])
    with pytest.raises(ValueError, match="__key__"):
        ds.write_webdataset(str(tmp_path / "w"))


def test_randomize_block_order_unseeded_reshuffles_per_epoch():
    """seed=None must draw a FRESH permutation on every execution of the
    same Dataset (epoch reshuffle), on the fast path too (r5 review: the
    memoized DeferredSource froze the first permutation forever)."""
    ds = rd.range(64, override_num_blocks=16).randomize_block_order()
    orders = {tuple(r["id"] for r in ds.take_all()) for _ in range(6)}
    assert len(orders) > 1
    assert all(sorted(o) == list(range(64)) for o in orders)


def test_write_webdataset_rejects_slashed_columns(tmp_path):
    ds = rd.from_items([{"__key__": "k0", "a/b": b"x"}])
    with pytest.raises(ValueError, match="column"):
        ds.write_webdataset(str(tmp_path / "w"))


def test_scalar_aggregates_exact_for_big_ints_and_nan_std():
    big = 2 ** 62 + 1
    ds = rd.from_items([{"x": big}, {"x": 1}])
    assert ds.sum(on="x") == big + 1          # exact, no float64 rounding
    assert ds.max(on="x") == big
    assert ds.min(on="x") == 1
    # std of a single row is undefined → nan, not 0.0
    assert np.isnan(rd.from_items([{"x": 5.0}]).std(on="x"))


def test_unseeded_random_sample_keeps_reorder_fast_path():
    """indexed only when seeded: unseeded sample + reorder must stay on
    the metadata-only DeferredSource path (r5 review)."""
    from ray_tpu.data.plan import DeferredSource
    ro = rd.range(40, override_num_blocks=4).random_sample(
        0.5).randomize_block_order(seed=1)
    assert isinstance(ro._plan.source, DeferredSource)
    # seeded sample stays on the barrier path (position-dependent)
    ro2 = rd.range(40, override_num_blocks=4).random_sample(
        0.5, seed=3).randomize_block_order(seed=1)
    assert not isinstance(ro2._plan.source, DeferredSource)


def test_sum_no_int64_wrap_within_block_and_dup_webdataset_keys(tmp_path):
    # both big rows in ONE block: int64 a.sum() would wrap to -2**63
    ds = rd.from_items([{"x": 2 ** 62}, {"x": 2 ** 62}])
    assert ds.sum(on="x") == 2 ** 63
    dup = rd.from_items([{"__key__": "k", "a": b"1"},
                         {"__key__": "k", "a": b"2"}]).repartition(1)
    with pytest.raises(ValueError, match="duplicate"):
        dup.write_webdataset(str(tmp_path / "w"))


def test_map_batches_class_udf_constructs_once_per_process(tmp_path,
                                                           ray_session):
    """Class UDFs (ref: map_batches ClassUDF actor pool): __init__ runs
    once per worker process, not once per block."""
    marker = str(tmp_path / "ctor_log")

    class AddBias:
        def __init__(self, bias):
            with open(marker, "a") as f:
                f.write(f"{__import__('os').getpid()}\n")
            self.bias = bias

        def __call__(self, batch):
            return {"id": batch["id"] + self.bias}

    ds = rd.range(40, override_num_blocks=8).map_batches(
        AddBias, fn_constructor_args=(100,))
    got = sorted(r["id"] for r in ds.take_all())
    assert got == list(range(100, 140))
    pids = open(marker).read().split()
    # one construction per distinct process that touched blocks — never
    # one per block (8 blocks were processed)
    assert len(pids) == len(set(pids))


def test_map_batches_class_udf_kwargs_inline():
    class Scale:
        def __init__(self, *, factor=1):
            self.factor = factor

        def __call__(self, batch):
            return {"id": batch["id"] * self.factor}

    ds = rd.range(5).map_batches(Scale, fn_constructor_kwargs={"factor": 3})
    assert [r["id"] for r in ds.take_all()] == [0, 3, 6, 9, 12]
