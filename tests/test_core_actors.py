"""Actor semantics tests (model: python/ray/tests/test_actor.py)."""

import time

import pytest


def test_actor_basic(ray_session):
    ray = ray_session

    @ray.remote
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def incr(self, k=1):
            self.n += k
            return self.n

        def value(self):
            return self.n

    c = Counter.remote(10)
    assert ray.get(c.incr.remote()) == 11
    assert ray.get(c.incr.remote(5)) == 16
    assert ray.get(c.value.remote()) == 16


def test_actor_method_ordering(ray_session):
    ray = ray_session

    @ray.remote
    class Log:
        def __init__(self):
            self.items = []

        def append(self, x):
            self.items.append(x)
            return len(self.items)

        def get(self):
            return self.items

    log = Log.remote()
    for i in range(10):
        log.append.remote(i)
    assert ray.get(log.get.remote()) == list(range(10))


def test_actor_handle_passing(ray_session):
    ray = ray_session

    @ray.remote
    class Store:
        def __init__(self):
            self.v = None

        def set(self, v):
            self.v = v

        def get(self):
            return self.v

    @ray.remote
    def writer(store, value):
        import ray_tpu
        ray_tpu.get(store.set.remote(value))
        return "done"

    s = Store.remote()
    assert ray.get(writer.remote(s, 99)) == "done"
    assert ray.get(s.get.remote()) == 99


def test_named_actor(ray_session):
    ray = ray_session

    @ray.remote
    class Registry:
        def ping(self):
            return "pong"

    Registry.options(name="reg1").remote()
    h = ray.get_actor("reg1")
    assert ray.get(h.ping.remote()) == "pong"

    with pytest.raises(ValueError):
        ray.get_actor("does-not-exist")


def test_actor_kill(ray_session):
    ray = ray_session

    @ray.remote
    class Victim:
        def ping(self):
            return "alive"

    v = Victim.remote()
    assert ray.get(v.ping.remote()) == "alive"
    ray.kill(v)
    time.sleep(0.5)
    with pytest.raises(ray.exceptions.ActorError):
        ray.get(v.ping.remote(), timeout=10)


def test_actor_restart(ray_session):
    ray = ray_session

    @ray.remote(max_restarts=1)
    class Phoenix:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

        def die(self):
            import os
            os._exit(1)

    p = Phoenix.remote()
    assert ray.get(p.bump.remote()) == 1
    p.die.remote()
    # state resets after restart; poll until it answers again
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            assert ray.get(p.bump.remote(), timeout=10) >= 1
            break
        except ray.exceptions.RayTpuError:
            time.sleep(0.3)
    else:
        pytest.fail("actor did not restart")


def test_actor_error_in_method(ray_session):
    ray = ray_session

    @ray.remote
    class Bad:
        def boom(self):
            raise KeyError("kaboom")

        def fine(self):
            return 1

    b = Bad.remote()
    with pytest.raises(ray.exceptions.TaskError):
        ray.get(b.boom.remote())
    # actor survives method errors
    assert ray.get(b.fine.remote()) == 1


def test_async_actor_concurrency(ray_session):
    ray = ray_session

    @ray.remote(max_concurrency=4)
    class Async:
        async def slow_echo(self, x):
            import asyncio
            await asyncio.sleep(0.4)
            return x

    a = Async.remote()
    ray.get(a.slow_echo.remote(-1))  # warm up: actor worker cold-spawn
    t0 = time.time()
    out = ray.get([a.slow_echo.remote(i) for i in range(4)])
    elapsed = time.time() - t0
    assert out == [0, 1, 2, 3]
    # concurrent: 4 × 0.4s sleeps overlap
    assert elapsed < 1.5, f"async methods did not overlap: {elapsed:.2f}s"


def test_actor_num_returns_method(ray_session):
    ray = ray_session

    @ray.remote
    class Multi:
        @ray.method(num_returns=2)
        def pair(self):
            return "a", "b"

    m = Multi.remote()
    r1, r2 = m.pair.remote()
    assert ray.get([r1, r2]) == ["a", "b"]


def test_detached_semantics_placeholder(ray_session):
    # lifetime="detached" accepted; single-driver runtime keeps it alive for
    # the session (full detach across drivers is a multi-host feature)
    ray = ray_session

    @ray.remote
    class D:
        def ok(self):
            return True

    d = D.options(lifetime="detached", name="detached1").remote()
    assert ray.get(d.ok.remote())
