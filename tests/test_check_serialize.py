"""inspect_serializability (ref: python/ray/util/check_serialize.py):
pinpoints the closure variable / attribute that breaks cloudpickle."""

import threading


def test_serializable_passes():
    from ray_tpu.util import inspect_serializability
    ok, failures = inspect_serializability(lambda x: x + 1)
    assert ok and not failures


def test_closure_culprit_named():
    from ray_tpu.util import inspect_serializability
    lock = threading.Lock()

    def task():
        return lock.acquire()

    ok, failures = inspect_serializability(task, print_file=open("/dev/null", "w"))
    assert not ok
    assert any(f.name == "lock" for f in failures)


def test_object_attribute_culprit_named():
    from ray_tpu.util import inspect_serializability

    class Holder:
        def __init__(self):
            self.fine = 42
            self.bad = threading.Lock()

    ok, failures = inspect_serializability(
        Holder(), print_file=open("/dev/null", "w"))
    assert not ok
    assert any(f.name == "bad" for f in failures)
