"""New preprocessors (ref: python/ray/data/preprocessors/{imputer,
normalizer,discretizer,encoder,hasher}.py)."""

import numpy as np
import pandas as pd
import pytest

from ray_tpu import data as rd
from ray_tpu.data import (FeatureHasher, KBinsDiscretizer, Normalizer,
                          OneHotEncoder, SimpleImputer)


def test_simple_imputer_mean_and_constant():
    ds = rd.from_pandas(pd.DataFrame({"x": [1.0, np.nan, 3.0]}))
    out = SimpleImputer(["x"], strategy="mean").fit_transform(ds).take_all()
    assert [r["x"] for r in out] == [1.0, 2.0, 3.0]
    out = SimpleImputer(["x"], strategy="constant",
                        fill_value=-1.0).transform(ds).take_all()
    assert [r["x"] for r in out] == [1.0, -1.0, 3.0]
    with pytest.raises(ValueError, match="fill_value"):
        SimpleImputer(["x"], strategy="constant")


def test_simple_imputer_most_frequent():
    ds = rd.from_pandas(pd.DataFrame({"c": ["a", "b", "a", None]}))
    out = SimpleImputer(["c"], strategy="most_frequent") \
        .fit_transform(ds).take_all()
    assert [r["c"] for r in out] == ["a", "b", "a", "a"]


def test_normalizer_matches_sklearn_def():
    df = pd.DataFrame({"a": [3.0, 0.0], "b": [4.0, 0.0]})
    ds = rd.from_pandas(df)
    out = Normalizer(["a", "b"], norm="l2").transform(ds).take_all()
    assert out[0]["a"] == pytest.approx(0.6)
    assert out[0]["b"] == pytest.approx(0.8)
    assert out[1]["a"] == 0.0   # zero row stays zero (no div-by-zero)
    l1 = Normalizer(["a", "b"], norm="l1").transform(ds).take_all()
    assert l1[0]["a"] + l1[0]["b"] == pytest.approx(1.0)


def test_kbins_uniform_and_quantile():
    vals = list(np.linspace(0, 10, 101))
    ds = rd.from_items([{"x": float(v)} for v in vals])
    uni = KBinsDiscretizer(["x"], bins=5).fit_transform(ds).take_all()
    got = [r["x"] for r in uni]
    assert min(got) == 0 and max(got) == 4
    assert got == sorted(got)          # monotone in the input
    q = KBinsDiscretizer(["x"], bins=4,
                         strategy="quantile").fit_transform(ds).take_all()
    counts = np.bincount([r["x"] for r in q])
    assert counts.min() >= 20          # near-equal mass per quantile bin


def test_one_hot_encoder_and_unseen():
    ds = rd.from_items([{"c": "a"}, {"c": "b"}, {"c": "a"}])
    enc = OneHotEncoder(["c"]).fit(ds)
    out = enc.transform(ds).take_all()
    assert list(out[0]["c_onehot"]) == [1.0, 0.0]
    assert list(out[1]["c_onehot"]) == [0.0, 1.0]
    unseen = enc.transform(rd.from_items([{"c": "zzz"}])).take_all()
    assert list(unseen[0]["c_onehot"]) == [0.0, 0.0]


def test_feature_hasher_deterministic_counts():
    ds = rd.from_items([{"toks": ["a", "b", "a"]}, {"toks": ["c"]}])
    out = FeatureHasher(["toks"], num_features=16).transform(ds).take_all()
    assert out[0]["hashed_features"].sum() == 3.0   # counts, not binary
    assert out[1]["hashed_features"].sum() == 1.0
    again = FeatureHasher(["toks"], num_features=16).transform(ds).take_all()
    assert np.array_equal(out[0]["hashed_features"],
                          again[0]["hashed_features"])


def test_one_hot_ignores_missing_and_imputer_all_missing_raises():
    ds = rd.from_pandas(pd.DataFrame({"c": ["a", None, "b"]}))
    enc = OneHotEncoder(["c"]).fit(ds)
    assert enc.categories_["c"] == ["a", "b"]   # None is not a category
    out = enc.transform(ds).take_all()
    assert list(out[1]["c_onehot"]) == [0.0, 0.0]
    empty = rd.from_pandas(pd.DataFrame({"c": [None, None]}))
    with pytest.raises(ValueError, match="no non-missing"):
        SimpleImputer(["c"], strategy="most_frequent").fit(empty)
