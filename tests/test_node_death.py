"""Node-loss lineage reconstruction (ref: object_recovery_manager.cc,
gcs_actor_manager.cc node-death paths).

Each test runs a driver subprocess that becomes a cluster head and spawns a
worker-node agent, parks objects on the node, then SIGKILLs the node's whole
process group mid-run.  The head must (a) detect the death, (b) eagerly purge
the dead node's holder entries (no lazy resurrection on a recycled
host:port), and (c) re-execute producing tasks from lineage so `get()`
returns the right bytes — or surface ObjectLostError for outputs lineage
refuses to replay (actor methods).
"""

import os
import subprocess
import sys
import textwrap

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PRELUDE = textwrap.dedent("""
    import json, os, signal, subprocess, sys, time
    import numpy as np
    import ray_tpu as ray
    from ray_tpu._private import state
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    ray.init(num_cpus=2, cluster_port=0)
    addr = ray.cluster_address()
    env = dict(os.environ)
    env.pop("RAY_TPU_ARENA", None)
    env.pop("RAY_TPU_ADDRESS", None)
    node_proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.node_main",
         "--address", addr, "--num-cpus", "2",
         "--resources", '{"worker_node": 1}'],
        env=env, stdin=subprocess.DEVNULL, start_new_session=True)

    def wait_for(pred, timeout=60, msg="condition"):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if pred():
                return
            time.sleep(0.2)
        raise TimeoutError("timed out waiting for " + msg)

    wait_for(lambda: len(ray.nodes()) == 2, 60, "node registration")

    def node_id_of():
        for row in ray.nodes():
            if row["resources"].get("worker_node"):
                return row["node_id"]
        raise AssertionError("worker node not registered")

    ctrl = state.global_client().controller
    nid = node_id_of()

    def on_node(ref):
        meta = ctrl.objects.get(ref.id)
        return meta is not None and meta.location == "remote:" + nid

    def kill_node():
        os.killpg(node_proc.pid, signal.SIGKILL)
        wait_for(lambda: len(ray.nodes()) == 1, 40, "node-death detection")
""")

_EPILOGUE = textwrap.dedent("""
    if node_proc.poll() is None:
        os.killpg(node_proc.pid, signal.SIGKILL)
        node_proc.wait(timeout=10)
    ray.shutdown()
    print("NODE_DEATH_TEST_OK", flush=True)
""")


def _run_driver(body: str, timeout=240):
    script = _PRELUDE + textwrap.dedent(body) + _EPILOGUE
    from ray_tpu.util.tpu import scrub_accel_env
    env = scrub_accel_env(dict(os.environ))
    env.pop("RAY_TPU_ARENA", None)
    env.pop("RAY_TPU_ADDRESS", None)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, "-c", script], env=env,
                       capture_output=True, text=True, timeout=timeout)
    assert r.returncode == 0, \
        f"driver failed\n--- stdout\n{r.stdout}\n--- stderr\n{r.stderr[-12000:]}"
    assert "NODE_DEATH_TEST_OK" in r.stdout


def test_node_death_reconstructs_single_and_chain():
    """SIGKILL the only holder of task outputs mid-run: get() must
    re-execute the producing tasks (single object AND a recursive
    base→derived chain) and return the right bytes, and the dead node's
    holder entries must be purged eagerly, not lazily on next touch."""
    _run_driver("""
    # soft affinity: prefers the node while alive, falls back to the head
    # once it is dead — so reconstruction has somewhere feasible to run
    strat = NodeAffinitySchedulingStrategy(node_id=nid, soft=True)

    @ray.remote(num_cpus=0.5)
    def produce(seed):
        return np.full(40_000, float(seed))   # ~320KB: shm, never inline

    @ray.remote(num_cpus=0.5)
    def double(a):
        return a * 2.0

    single = produce.options(scheduling_strategy=strat).remote(3)
    base = produce.options(scheduling_strategy=strat).remote(5)
    derived = double.options(scheduling_strategy=strat).remote(base)
    wait_for(lambda: all(on_node(r) for r in (single, base, derived)),
             60, "outputs parked on the worker node")

    kill_node()

    out = ray.get(single, timeout=120)
    assert out.shape == (40_000,) and float(out[7]) == 3.0, out[:4]
    # recursive lineage: derived's arg (base) was also lost with the node
    out2 = ray.get(derived, timeout=120)
    assert float(out2[7]) == 10.0, out2[:4]

    # the head recorded the reconstruction
    from ray_tpu.util import metrics
    assert metrics._counter_total("reconstructions_total") >= 1.0

    # eager purge: nothing in the object table still points at the corpse
    dead_loc = "remote:" + nid
    stale = [oid for oid, m in ctrl.objects.items()
             if m.location == dead_loc or nid in m.holders]
    assert not stale, stale
    # and the tombstone (pid included) is recorded for the reconciler
    assert nid in ctrl.health.dead_nodes
    assert ctrl.health.dead_nodes[nid].get("pid") == node_proc.pid
    """)


def test_node_death_actor_output_is_lost():
    """Actor method outputs are NOT replayable from lineage (re-running a
    method against rebuilt state is not idempotent): after the holding node
    dies, get() must surface ObjectLostError promptly instead of hanging."""
    _run_driver("""
    from ray_tpu.exceptions import ObjectLostError

    @ray.remote(resources={"worker_node": 0.5})
    class Counter:
        def blob(self):
            return np.ones(50_000)            # shm-sized actor output

    a = Counter.remote()
    ref = a.blob.remote()
    wait_for(lambda: on_node(ref), 60, "actor output parked on the node")

    kill_node()

    try:
        ray.get(ref, timeout=60)
        raise SystemExit("expected ObjectLostError for actor output")
    except ObjectLostError:
        pass
    """)


def test_chaos_ladder_smoke_gate():
    """Tier-1 chaos gate (tools/chaos_ladder.py --smoke): one kill-mid-run
    rung completes via reconstruction AND the reconciler replaces a killed
    provider node within two heartbeat intervals."""
    import json

    from ray_tpu.util.tpu import scrub_accel_env
    env = scrub_accel_env(dict(os.environ))
    env.pop("RAY_TPU_ARENA", None)
    env.pop("RAY_TPU_ADDRESS", None)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["RAY_TPU_BENCH_WRITE_RESULTS"] = "0"
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "chaos_ladder.py"),
         "--smoke"],
        env=env, capture_output=True, text=True, timeout=240)
    assert r.returncode == 0, \
        f"smoke failed\n--- stdout\n{r.stdout}\n--- stderr\n{r.stderr[-12000:]}"
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["transfer"]["reconstructions"] >= 1, rec
    reconcile = rec["reconcile"]
    assert reconcile["replacements"] == 1, reconcile
    assert (reconcile["replace_latency_s"]
            <= 2 * reconcile["heartbeat_s"]), reconcile
