"""Real multi-host exercise (VERDICT r2 missing #9): two OS processes join
ONE jax world via `initialize_multihost` (gloo CPU collectives standing in
for DCN) and run a computation over the GLOBAL device mesh — a collective
that cannot complete unless both processes participate.

Reference contrast: worker-group startup across nodes
(python/ray/train/v2/_internal/execution/worker_group/worker_group.py) wires
NCCL between hosts; here jax.distributed wires the runtime and the compiler
emits the cross-process collectives.
"""

import os
import socket
import subprocess
import sys
import textwrap

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = textwrap.dedent("""
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.parallel.distributed import (barrier, initialize_multihost,
                                              is_multihost, process_count)
    from ray_tpu.parallel.mesh import make_mesh

    pid, port = int(sys.argv[1]), sys.argv[2]
    assert initialize_multihost(
        coordinator_address=f"127.0.0.1:{port}", num_processes=2,
        process_id=pid) is True
    assert process_count() == 2 and is_multihost()

    # 2 local devices per process (forced host platform count) -> 4 global
    devs = jax.devices()
    assert len(devs) == 4, devs
    mesh = make_mesh({"dp": 4}, devices=devs)

    # each process contributes its own rows; the global mean needs data from
    # BOTH processes, so a wrong world would produce a wrong number or hang
    local = np.full((2, 8), float(pid + 1), np.float32)
    garr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp")), local, (4, 8))
    total = jax.jit(jnp.mean, out_shardings=NamedSharding(mesh, P()))(garr)
    assert abs(float(total) - 1.5) < 1e-6, float(total)

    barrier("end-of-test")
    print(f"MULTIHOST_OK pid={pid} mean={float(total)}", flush=True)
""")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_jax_world():
    from ray_tpu.util.tpu import scrub_accel_env

    port = _free_port()
    env = scrub_accel_env(os.environ, n_cpu_devices=2)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _CHILD, str(pid), str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=180)
        outs.append(out.decode(errors="replace"))
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {pid} failed:\n{out}"
        assert f"MULTIHOST_OK pid={pid} mean=1.5" in out, out
