"""Real multi-host exercise (VERDICT r2 missing #9): two OS processes join
ONE jax world via `initialize_multihost` (gloo CPU collectives standing in
for DCN) and run a computation over the GLOBAL device mesh — a collective
that cannot complete unless both processes participate.

Reference contrast: worker-group startup across nodes
(python/ray/train/v2/_internal/execution/worker_group/worker_group.py) wires
NCCL between hosts; here jax.distributed wires the runtime and the compiler
emits the cross-process collectives.
"""

import os
import socket
import subprocess
import sys
import textwrap

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = textwrap.dedent("""
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.parallel.distributed import (barrier, initialize_multihost,
                                              is_multihost, process_count)
    from ray_tpu.parallel.mesh import make_mesh

    pid, port = int(sys.argv[1]), sys.argv[2]
    assert initialize_multihost(
        coordinator_address=f"127.0.0.1:{port}", num_processes=2,
        process_id=pid) is True
    assert process_count() == 2 and is_multihost()

    # 2 local devices per process (forced host platform count) -> 4 global
    devs = jax.devices()
    assert len(devs) == 4, devs
    mesh = make_mesh({"dp": 4}, devices=devs)

    # each process contributes its own rows; the global mean needs data from
    # BOTH processes, so a wrong world would produce a wrong number or hang
    local = np.full((2, 8), float(pid + 1), np.float32)
    garr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp")), local, (4, 8))
    total = jax.jit(jnp.mean, out_shardings=NamedSharding(mesh, P()))(garr)
    assert abs(float(total) - 1.5) < 1e-6, float(total)

    barrier("end-of-test")
    print(f"MULTIHOST_OK pid={pid} mean={float(total)}", flush=True)
""")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_jax_world():
    from ray_tpu.util.tpu import scrub_accel_env

    port = _free_port()
    env = scrub_accel_env(os.environ, n_cpu_devices=2)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _CHILD, str(pid), str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=180)
        outs.append(out.decode(errors="replace"))
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {pid} failed:\n{out}"
        assert f"MULTIHOST_OK pid={pid} mean=1.5" in out, out


_TRAIN_CHILD = textwrap.dedent("""
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.models.llama import Llama, LlamaConfig
    from ray_tpu.ops.losses import cross_entropy
    from ray_tpu.parallel.distributed import barrier, initialize_multihost
    from ray_tpu.parallel.mesh import make_mesh

    pid, port = int(sys.argv[1]), sys.argv[2]
    initialize_multihost(coordinator_address=f"127.0.0.1:{port}",
                         num_processes=2, process_id=pid)

    cfg = LlamaConfig.tiny(dtype=jnp.float32, param_dtype=jnp.float32,
                           attn_impl="xla", max_seq_len=64)
    model = Llama(cfg)
    batch, seq = 8, 32
    tokens_np = np.asarray(
        jax.random.randint(jax.random.PRNGKey(7), (batch, seq + 1), 0,
                           cfg.vocab_size, jnp.int32))
    params0 = model.init(jax.random.PRNGKey(0), jnp.asarray(tokens_np[:, :-1]))
    opt = optax.adamw(1e-2)

    def loss_fn(p, toks):
        logits, _ = model.apply(p, toks[:, :-1])
        return cross_entropy(logits, toks[:, 1:])[0]

    def train_step(p, s, toks):
        loss, g = jax.value_and_grad(loss_fn)(p, toks)
        up, s = opt.update(g, s, p)
        return optax.apply_updates(p, up), s, loss

    # single-device reference (local math, no cross-process deps)
    ref_p, ref_s = params0, opt.init(params0)
    for _ in range(2):
        ref_p, ref_s, ref_loss = jax.jit(train_step)(ref_p, ref_s,
                                                     jnp.asarray(tokens_np))
    ref_loss = float(ref_loss)

    # distributed: dp over 4 global devices (2 per process); params
    # replicated, each process feeds ITS OWN batch quarter rows — the
    # gradient psum XLA inserts must cross the process boundary
    mesh = make_mesh({"dp": 4}, devices=jax.devices())
    repl = NamedSharding(mesh, P())
    data_sh = NamedSharding(mesh, P("dp"))
    params = jax.device_put(params0, repl)
    opt_state = jax.device_put(opt.init(params0), repl)
    local_rows = tokens_np[pid * 4:(pid + 1) * 4]
    toks = jax.make_array_from_process_local_data(
        data_sh, local_rows, (batch, seq + 1))
    step = jax.jit(train_step, out_shardings=(repl, repl, repl))
    for i in range(2):
        params, opt_state, loss = step(params, opt_state, toks)
    dist_loss = float(jax.device_get(loss))
    delta = abs(dist_loss - ref_loss)
    assert delta < 2e-4, (dist_loss, ref_loss)
    barrier("train-done")
    print(f"MULTIHOST_TRAIN_OK pid={pid} loss={dist_loss:.6f} "
          f"delta={delta:.2e}", flush=True)
""")


def test_two_process_distributed_train_step():
    """Full fwd+bwd+adamw over a mesh spanning two OS processes: loss after
    two steps matches the single-device run (grad psum rides the
    inter-process link, standing in for DCN)."""
    from ray_tpu.util.tpu import scrub_accel_env

    port = _free_port()
    env = scrub_accel_env(os.environ, n_cpu_devices=2)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _TRAIN_CHILD, str(pid), str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for pid in (0, 1)
    ]
    for pid, p in enumerate(procs):
        out, _ = p.communicate(timeout=300)
        text = out.decode(errors="replace")
        assert p.returncode == 0, f"rank {pid} failed:\n{text}"
        assert f"MULTIHOST_TRAIN_OK pid={pid}" in text, text


def test_dcn_ici_hybrid_mesh_dryrun():
    """DCN x ICI composition (VERDICT r4 weak #5): dp spans two OS
    processes over the inter-process link while fsdp spans each process's
    4 virtual devices, built by hybrid_mesh (process-granule fallback).
    The sharded train step must match the single-device baseline."""
    sys.path.insert(0, _REPO)
    import __graft_entry__ as g

    line = g._run_dcn_variant()
    assert line.startswith("DCN_DRYRUN_OK"), line
