"""Sebulba pipeline tests: ref-based replay, device-resident rollouts,
lockstep parity with sync IMPALA, off-policy gap ≥ 1 under async mode,
recompile guard, deterministic sampling, leak-free shutdown."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from ray_tpu.rllib import (IMPALAConfig, PPOConfig, ReplayActor,
                           DeviceRollout, JaxCartPole)
from ray_tpu.rllib.sebulba import _JAX_ENVS


# ------------------------------------------------------------- replay actor
def test_replay_actor_ring_fifo_and_clear():
    buf = ReplayActor(capacity=3, seed=0, mode="fifo")
    assert buf.add_refs(["r0", "r1"], [0, 0]) == 2
    assert buf.size() == 2
    # fifo hands each slot out exactly once, oldest first
    assert buf.sample_refs(1) == [("r0", 0)]
    assert buf.sample_refs(5) == [("r1", 0)]
    assert buf.sample_refs(1) == []          # queue dry
    buf.add_refs(["r2", "r3", "r4"], 1)      # scalar version broadcast
    # capacity 3: r0 and r1 ring-evicted, cursor rebased past them
    s = buf.stats()
    assert s["evicted"] == 2 and s["size"] == 3
    assert buf.sample_refs(2) == [("r2", 1), ("r3", 1)]
    assert buf.clear() == 3
    assert buf.size() == 0 and buf.sample_refs(1) == []


def test_replay_actor_deterministic_sampling_pinned():
    """Satellite: sampling is seeded from config — same seed, same index
    sequence, run after run. Pinned against the numpy PCG64 stream."""
    buf = ReplayActor(capacity=8, seed=123, mode="uniform")
    buf.add_refs([f"r{i}" for i in range(8)], list(range(8)))
    assert buf._sample_indices(4) == [0, 5, 4, 0]
    assert buf._sample_indices(4) == [7, 1, 2, 1]
    # identical seed ⇒ identical stream
    buf2 = ReplayActor(capacity=8, seed=123, mode="uniform")
    buf2.add_refs([f"r{i}" for i in range(8)], 0)
    assert buf2._sample_indices(8) == [0, 5, 4, 0, 7, 1, 2, 1]


def test_replay_actor_rejects_unknown_mode():
    with pytest.raises(ValueError):
        ReplayActor(capacity=4, mode="priority")


# --------------------------------------------------------- device-resident
def test_jax_cartpole_matches_gym_physics():
    import gymnasium as gym
    import jax.numpy as jnp
    env = gym.make("CartPole-v1")
    env.reset(seed=0)
    state = np.array([0.01, -0.02, 0.03, 0.04], np.float32)
    for action in (0, 1):
        env.unwrapped.state = state.copy()
        obs_g, rew_g, term_g, trunc_g, _ = env.step(action)
        x = jnp.asarray(state[None])
        t = jnp.zeros((1,), jnp.int32)
        x2, t2, rew_j, term_j, trunc_j = JaxCartPole.step(
            x, t, jnp.asarray([action]))
        np.testing.assert_allclose(np.asarray(x2[0]), obs_g, atol=1e-5)
        assert float(rew_j[0]) == rew_g == 1.0
        assert bool(term_j[0]) == term_g
    env.close()


def test_device_rollout_fixed_shapes_and_autoreset():
    roll = DeviceRollout("cartpole", num_envs=3, rollout_len=16, seed=5)
    assert "cartpole" in _JAX_ENVS
    roll.set_weights(roll.init_params(), version=0)
    total_done = 0
    for _ in range(6):   # random policy episodes end well inside ~96 steps
        b = roll.sample()
        assert b["obs"].shape == (16, 3, 4)
        assert b["actions"].shape == (16, 3)
        assert b["bootstrap_value"].shape == (3,)
        # bootstrap masked by the final terminal flag (EnvRunner's rule)
        term_last = np.asarray(b["terminateds"])[-1]
        boot = np.asarray(b["bootstrap_value"])
        assert np.all(boot[term_last == 1.0] == 0.0)
        total_done += int(np.asarray(b["dones"]).sum())
    assert total_done > 0
    m = roll.pop_metrics()
    assert m["episodes_this_iter"] == total_done
    assert roll.params_version == 0


def test_device_rollout_deterministic_given_seed():
    params = DeviceRollout("cartpole", num_envs=2, rollout_len=8,
                           seed=9).init_params()
    outs = []
    for _ in range(2):
        roll = DeviceRollout("cartpole", num_envs=2, rollout_len=8, seed=9)
        roll.set_weights(params, version=0)
        outs.append(roll.sample())
    np.testing.assert_array_equal(outs[0]["obs"], outs[1]["obs"])
    np.testing.assert_array_equal(outs[0]["actions"], outs[1]["actions"])


# ---------------------------------------------------------------- config api
def test_config_sebulba_builder():
    cfg = (IMPALAConfig()
           .sebulba(num_rollout_actors=3, inflight_rollouts=4,
                    replay_capacity=32, replay_mode="fifo",
                    broadcast_interval=2, max_staleness=8,
                    replay_seed=77, jax_env="cartpole"))
    assert cfg.sebulba_enabled
    assert cfg.sebulba_num_rollout_actors == 3
    assert cfg.sebulba_inflight_rollouts == 4
    assert cfg.sebulba_replay_capacity == 32
    assert cfg.sebulba_replay_mode == "fifo"
    assert cfg.sebulba_broadcast_interval == 2
    assert cfg.sebulba_max_staleness == 8
    assert cfg.sebulba_replay_seed == 77
    assert cfg.sebulba_jax_env == "cartpole"
    # default off
    assert not IMPALAConfig().sebulba_enabled


def test_sebulba_requires_vtrace_algo(ray_session):
    cfg = (PPOConfig().environment("CartPole-v1")
           .env_runners(num_envs_per_env_runner=1, rollout_fragment_length=4)
           .training(train_batch_size=8, minibatch_size=4)
           .sebulba())
    with pytest.raises(ValueError, match="sebulba"):
        cfg.build()


# ------------------------------------------------------------ observability
def test_tracing_overlap_stats_math():
    from ray_tpu.util import tracing

    def ev(name, t0, dur):
        return {"name": name, "ph": "X", "ts": t0 * 1e6, "dur": dur * 1e6}

    events = [ev("pipeline.act", 0.0, 1.0),     # [0, 1]
              ev("pipeline.act", 2.0, 1.0),     # [2, 3]
              ev("pipeline.learn", 0.5, 1.0),   # [0.5, 1.5] → 0.5s overlap
              ev("pipeline.learn", 2.5, 0.25)]  # [2.5, 2.75] → 0.25s overlap
    s = tracing.overlap_stats(events, "pipeline.act", "pipeline.learn")
    assert s["windows_a"] == 2 and s["windows_b"] == 2
    assert abs(s["busy_a_s"] - 2.0) < 1e-9
    assert abs(s["busy_b_s"] - 1.25) < 1e-9
    assert abs(s["overlap_s"] - 0.75) < 1e-9
    assert abs(s["overlap_fraction"] - 0.6) < 1e-9   # 0.75 / min(2, 1.25)
    # disjoint families → zero
    s2 = tracing.overlap_stats(events[:1] + events[3:],
                               "pipeline.act", "pipeline.learn")
    assert s2["overlap_s"] == 0.0


def test_rllib_sebulba_counters_surface():
    from ray_tpu.util import metrics
    before = metrics.rllib_sebulba_counters()
    metrics.get_or_create(metrics.Counter, "rllib_env_steps", "t").inc(40)
    metrics.get_or_create(metrics.Counter, "rllib_learner_steps", "t").inc(2)
    metrics.get_or_create(
        metrics.Gauge, "rllib_param_version", "t",
        tag_keys=("role",)).set(11, tags={"role": "learner"})
    after = metrics.rllib_sebulba_counters()
    assert after["env_steps"] - before["env_steps"] == 40
    assert after["learner_steps"] - before["learner_steps"] == 2
    assert after["param_version"] >= 11
    # the histogram read surface tolerates the metric not existing yet
    assert metrics.rllib_offpolicy_gap_summary() is None \
        or "count" in metrics.rllib_offpolicy_gap_summary()


# ------------------------------------------------------------- end to end
def _impala_cfg(seed=3, **sebulba_kwargs):
    cfg = (IMPALAConfig()
           .environment("CartPole-v1")
           .env_runners(num_env_runners=0, num_envs_per_env_runner=2,
                        rollout_fragment_length=8)
           .training(train_batch_size=16)   # == T*B → 1 update per iter
           .debugging(seed=seed))
    if sebulba_kwargs:
        cfg = cfg.sebulba(**sebulba_kwargs)
    return cfg


def _leaked_big(min_bytes=1 << 16):
    from ray_tpu._private import state
    from ray_tpu._private.health import LeakDetector
    ctl = state.global_client().controller
    det = LeakDetector(age_s=0.0, clock=lambda: time.time() + 3600.0)
    return [f for f in det.scan(ctl.objects)
            if (f.get("size") or 0) >= min_bytes]


def test_sebulba_lockstep_parity_with_sync_impala(ray_session):
    """Gap-0 anchor: lockstep sebulba (1 actor, fifo replay, blocking
    broadcast) replays the synchronous schedule exactly — identical
    params after N iterations."""
    import jax

    sync = (IMPALAConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=2,
                         rollout_fragment_length=8)
            .training(train_batch_size=16)
            .debugging(seed=3)).build()
    for _ in range(2):
        sync.train()
    w_sync = sync.get_weights()
    sync.stop()

    seb = _impala_cfg(seed=3, lockstep=True).build()
    for _ in range(2):
        r = seb.train()
    s = r["sebulba"]
    assert s["lockstep"] and s["updates"] == 2
    assert s["gap_counts"] == {0: 2}          # exact off-policy gap 0
    assert s["jit_cache_size"] == 1           # recompile guard
    w_seb = seb.get_weights()
    seb.stop()
    time.sleep(0.5)

    for a, b in zip(jax.tree_util.tree_leaves(w_sync),
                    jax.tree_util.tree_leaves(w_seb)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    assert not _leaked_big()                  # replay.clear() ran


def test_sebulba_async_offpolicy_gap_and_guard(ray_session):
    """Async mode with 2 in-flight rollouts per actor: V-trace must see
    trajectories with gap ≥ 1, the jitted update must compile exactly
    once, and shutdown must leave no pinned trajectory objects."""
    algo = _impala_cfg(seed=7, num_rollout_actors=2, inflight_rollouts=2,
                       replay_capacity=8, jax_env="cartpole").build()
    cfg = algo.config
    assert cfg.sebulba_jax_env == "cartpole"
    stats = None
    for _ in range(4):
        stats = algo.train()["sebulba"]
        if any(g >= 1 for g in stats["gap_counts"]):
            break
    assert stats["updates"] >= 1
    assert any(g >= 1 for g in stats["gap_counts"]), stats["gap_counts"]
    assert stats["jit_cache_size"] == 1, "jitted update recompiled"
    assert stats["counters"]["broadcasts"] >= 1
    replay = ray_session.get(algo._sebulba.replay.stats.remote())
    assert replay["admitted"] > 0 and replay["mode"] == "uniform"
    algo.stop()
    time.sleep(0.5)
    assert not _leaked_big()


def test_rllib_bench_sebulba_smoke_gate():
    """rllib_bench --smoke is the tier-1 hook for the whole pipeline:
    nonzero fire-and-forget broadcasts, pipeline.act/pipeline.learn span
    overlap on the head timeline, lockstep parity, leak-free shutdown."""
    bench = os.path.join(os.path.dirname(__file__), os.pardir,
                         "benchmarks", "rllib_bench.py")
    proc = subprocess.run(
        [sys.executable, bench, "--smoke"], capture_output=True, text=True,
        timeout=420, env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["smoke"] == "ok"
    assert rec["parity"]["ok"] is True
    assert rec["broadcasts_async"] > 0
    assert rec["overlap_s"] > 0
    assert rec["jit_cache_size"] == 1
    assert rec["leaked_big"] == 0


@pytest.mark.slow
def test_sebulba_appo_vtrace_path(ray_session):
    """APPO rides the same pipeline: driver-side V-trace targets under
    current params, then the clipped-surrogate update."""
    from ray_tpu.rllib import APPOConfig
    algo = (APPOConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=2,
                         rollout_fragment_length=8)
            .training(train_batch_size=16, minibatch_size=16, num_epochs=1)
            .sebulba(num_rollout_actors=1, inflight_rollouts=2)
            .debugging(seed=11)).build()
    r = algo.train()
    assert r["sebulba"]["updates"] >= 1
    assert "total_loss" in r["learner"]
    algo.stop()
