"""Compiled actor DAGs + durable workflows (VERDICT r3 missing #6 and #8;
ref: python/ray/dag/compiled_dag_node.py, python/ray/workflow/)."""

import time

import pytest


def test_compiled_dag_pipeline(ray_session):
    ray = ray_session
    from ray_tpu.dag import InputNode

    @ray.remote
    class Stage:
        def __init__(self, tag):
            self.tag = tag

        def work(self, x):
            return x + [self.tag]

    a, b, c = Stage.remote("a"), Stage.remote("b"), Stage.remote("c")
    with InputNode() as inp:
        x = a.work.bind(inp)
        y = b.work.bind(x)
        out = c.work.bind(y)
    compiled = out.experimental_compile()

    assert ray.get(compiled.execute([0]), timeout=60) == [0, "a", "b", "c"]
    # repeated executions reuse the same pipeline
    refs = [compiled.execute([i]) for i in range(5)]
    outs = ray.get(refs, timeout=60)
    assert outs[4] == [4, "a", "b", "c"]


def test_compiled_dag_pipelining_overlaps(ray_session):
    """Stage A must start item 2 while stage B still runs item 1: total
    wall time for 3 items through 2 stages of d seconds each is ~(3+1)*d,
    not 6*d serial."""
    ray = ray_session
    from ray_tpu.dag import InputNode

    D = 0.4

    @ray.remote
    class Slow:
        def work(self, x):
            time.sleep(D)
            return x + 1

    a, b = Slow.remote(), Slow.remote()
    with InputNode() as inp:
        out = b.work.bind(a.work.bind(inp))
    compiled = out.experimental_compile()
    ray.get(compiled.execute(0), timeout=60)  # warm both actors

    t0 = time.time()
    refs = [compiled.execute(i) for i in range(3)]
    outs = ray.get(refs, timeout=60)
    elapsed = time.time() - t0
    assert outs == [2, 3, 4]
    # serial would be 6*D=2.4s; pipelined floor is 4*D=1.6s. 3x slack for
    # the 1-core box, but still must beat serial.
    assert elapsed < 6 * D * 0.95, elapsed


def test_compiled_dag_multi_output_and_input_access(ray_session):
    ray = ray_session
    from ray_tpu.dag import InputNode, MultiOutputNode

    @ray.remote
    class Math:
        def add(self, a, b):
            return a + b

        def mul(self, a, b):
            return a * b

    m = Math.remote()
    with InputNode() as inp:
        s = m.add.bind(inp[0], inp[1])
        p = m.mul.bind(inp[0], inp[1])
        dag = MultiOutputNode([s, p])
    compiled = dag.experimental_compile()
    got = ray.get(compiled.execute((3, 4)), timeout=60)
    assert got == [7, 12]


def test_workflow_run_and_resume(ray_session, tmp_path, monkeypatch):
    ray = ray_session
    from ray_tpu import workflow

    calls_file = tmp_path / "calls.txt"

    @ray.remote
    def load(x):
        with open(calls_file, "a") as f:
            f.write(f"load:{x}\n")
        return list(range(x))

    @ray.remote
    def square(xs):
        with open(calls_file, "a") as f:
            f.write("square\n")
        return [v * v for v in xs]

    @ray.remote
    def total(xs):
        with open(calls_file, "a") as f:
            f.write("total\n")
        return sum(xs)

    wid = f"wf_test_{time.time_ns()}"
    dag = total.bind(square.bind(load.bind(5)))
    out = workflow.run(dag, workflow_id=wid)
    assert out == 0 + 1 + 4 + 9 + 16
    assert workflow.get_status(wid) == "SUCCESSFUL"

    # re-run with same id: every step journaled -> zero new calls
    calls_before = calls_file.read_text().count("\n")
    dag2 = total.bind(square.bind(load.bind(5)))
    assert workflow.run(dag2, workflow_id=wid) == 30
    assert calls_file.read_text().count("\n") == calls_before

    # finished workflows answer resume() without a DAG
    assert workflow.resume(wid) == 30
    assert any(w["workflow_id"] == wid for w in workflow.list_all())
    workflow.delete(wid)


def test_workflow_failure_then_resume_skips_done_steps(ray_session, tmp_path):
    ray = ray_session
    from ray_tpu import workflow

    marker = tmp_path / "fail_once"
    marker.write_text("fail")
    loads = tmp_path / "loads.txt"

    @ray.remote
    def produce():
        with open(loads, "a") as f:
            f.write("produce\n")
        return 21

    @ray.remote
    def flaky(x):
        import os
        if os.path.exists(marker):
            raise RuntimeError("transient failure")
        return x * 2

    wid = f"wf_fail_{time.time_ns()}"
    dag = flaky.bind(produce.bind())
    with pytest.raises(Exception):
        workflow.run(dag, workflow_id=wid)
    assert workflow.get_status(wid) == "FAILED"

    marker.unlink()  # the transient cause clears
    dag2 = flaky.bind(produce.bind())
    assert workflow.resume(wid, dag2) == 42
    # produce() ran once total: its journaled result was reused
    assert loads.read_text().count("produce") == 1
    assert workflow.get_status(wid) == "SUCCESSFUL"
    workflow.delete(wid)


def test_workflow_continuation_recursion(ray_session):
    """A step returning workflow.continuation(sub_dag) tail-calls it; deep
    tail-recursion works because long journal keys collapse to digests."""
    ray = ray_session
    from ray_tpu import workflow

    @ray.remote
    def fac(n, acc=1):
        if n <= 1:
            return acc
        return workflow.continuation(fac.bind(n - 1, acc * n))

    wid = f"wf_cont_{time.time_ns()}"
    assert workflow.run(fac.bind(12), workflow_id=wid) == 479001600
    assert workflow.get_status(wid) == "SUCCESSFUL"
    # finished workflow answers resume() without a DAG (terminal = root step)
    assert workflow.resume(wid) == 479001600
    workflow.delete(wid)


def test_workflow_continuation_deep_chain(ray_session):
    """Tail-call chains are trampolined, not recursed: a 1200-deep chain
    (well past Python's default 1000 recursion limit) completes."""
    ray = ray_session
    from ray_tpu import workflow

    @ray.remote
    def count(n, acc=0):
        if n == 0:
            return acc
        return workflow.continuation(count.bind(n - 1, acc + 1))

    wid = f"wf_deep_{time.time_ns()}"
    assert workflow.run(count.bind(1200), workflow_id=wid) == 1200
    assert workflow.resume(wid) == 1200
    workflow.delete(wid)


def test_workflow_continuation_resume_skips_parent(ray_session, tmp_path):
    """Crash INSIDE a continuation: resume must not re-run the step that
    produced it (the continuation DAG itself is journaled)."""
    ray = ray_session
    from ray_tpu import workflow

    marker = tmp_path / "fail_once"
    marker.write_text("x")
    calls = tmp_path / "calls.txt"

    @ray.remote
    def finisher(x):
        import os
        with open(calls, "a") as f:
            f.write("finisher\n")
        if os.path.exists(marker):
            raise RuntimeError("transient")
        return x + 1

    @ray.remote
    def starter():
        with open(calls, "a") as f:
            f.write("starter\n")
        return workflow.continuation(finisher.bind(41))

    wid = f"wf_cont_fail_{time.time_ns()}"
    with pytest.raises(Exception):
        workflow.run(starter.bind(), workflow_id=wid)
    assert workflow.get_status(wid) == "FAILED"

    marker.unlink()
    assert workflow.resume(wid, starter.bind()) == 42
    text = calls.read_text()
    # starter ran exactly once: the journaled continuation was replayed
    assert text.count("starter") == 1
    assert text.count("finisher") == 2
    workflow.delete(wid)


def test_workflow_continuation_mid_dag(ray_session):
    """A continuation produced by a NON-terminal step resolves before its
    dependents observe the value."""
    ray = ray_session
    from ray_tpu import workflow

    @ray.remote
    def expand(n):
        # dynamic shape: decided at runtime, not when the DAG was built
        return workflow.continuation(tally.bind(list(range(n))))

    @ray.remote
    def tally(xs):
        return sum(xs)

    @ray.remote
    def double(x):
        return 2 * x

    wid = f"wf_cont_mid_{time.time_ns()}"
    out = workflow.run(double.bind(expand.bind(5)), workflow_id=wid)
    assert out == 2 * (0 + 1 + 2 + 3 + 4)
    workflow.delete(wid)
