"""Prefill/Decode disaggregation (VERDICT r4 missing #3; ref:
python/ray/llm/_internal/serve/serving_patterns/prefill_decode/pd_server.py).

Greedy decoding is deterministic, so the strongest correctness check is
exact token equality: a PD pipeline (separate prefill + decode engines,
KV shipped between them) must produce byte-identical generations to one
colocated engine with the same weights."""

import asyncio

import numpy as np
import pytest


def _cfg(**kw):
    from ray_tpu.serve.llm import LLMConfig
    return LLMConfig(preset="tiny", max_batch_slots=4, max_seq_len=128,
                     paged=True, page_size=16, prefill_chunk=32,
                     prefix_cache=False, seed=3, **kw)


@pytest.fixture(scope="module")
def servers():
    from ray_tpu.serve.llm import LLMServer
    from ray_tpu.serve.pd import PDServer, PrefillServer
    plain = LLMServer(_cfg())
    prefill = PrefillServer(_cfg())
    pd = PDServer(_cfg(), prefill=prefill)
    return plain, prefill, pd


def test_prefill_kv_shapes(servers):
    _, prefill, _ = servers
    out = asyncio.run(prefill.prefill_kv(list(range(2, 39))))
    mc = prefill.model_cfg
    assert out["prompt_len"] == 37
    assert out["k"].shape == (mc.n_layers, mc.n_kv_heads, 37, mc.head_dim)
    assert out["v"].shape == out["k"].shape
    assert isinstance(out["token"], int)
    # the prefill slot was released — nothing leaks
    assert prefill.stats()["active"] == 0
    assert prefill.stats()["free_slots"] == 4


def test_pd_matches_colocated_greedy(servers):
    plain, _, pd = servers
    prompts = [list(range(5, 25)), [7, 3, 11] * 9, list(range(60, 100))]

    async def gen(server, p):
        return await server.generate(p, max_tokens=12)

    for p in prompts:
        ref = asyncio.run(gen(plain, p))
        got = asyncio.run(gen(pd, p))
        assert got["tokens"] == ref["tokens"], (p[:4], got, ref)
    assert pd.pd_requests == len(prompts)
    assert pd.stats()["pd_requests"] == len(prompts)


def test_pd_concurrent_requests(servers):
    plain, _, pd = servers

    async def many(server):
        outs = await asyncio.gather(*[
            server.generate([i + 2, i + 5, i + 9], max_tokens=8)
            for i in range(6)])
        return [o["tokens"] for o in outs]

    assert asyncio.run(many(pd)) == asyncio.run(many(plain))
    # all slots/pages returned on both engines
    for s in (plain, pd):
        st = s.stats()
        assert st["active"] == 0 and st["free_slots"] == 4
        assert st["pages_in_use"] == 0


def test_pd_logprobs_and_eos(servers):
    plain, _, pd = servers
    p = list(range(30, 50))

    async def gen(server):
        return await server.generate(p, max_tokens=6, logprobs=True)

    ref = asyncio.run(gen(plain))
    got = asyncio.run(gen(pd))
    assert got["tokens"] == ref["tokens"]
    np.testing.assert_allclose(got["logprobs"], ref["logprobs"],
                               rtol=1e-4, atol=1e-5)

    # eos on the FIRST (prefill-produced) token truncates to empty
    eos = ref["tokens"][0]
    got_eos = asyncio.run(pd.generate(p, max_tokens=6, eos_id=eos))
    assert got_eos["tokens"] == []


def test_pd_requires_paged():
    from ray_tpu.serve.llm import LLMConfig
    from ray_tpu.serve.pd import PrefillServer
    with pytest.raises(ValueError, match="paged"):
        server = PrefillServer(LLMConfig(preset="tiny", paged=False,
                                         max_seq_len=64))
        asyncio.run(server.prefill_kv([1, 2, 3]))
