"""Prefill/Decode disaggregation (VERDICT r4 missing #3; ref:
python/ray/llm/_internal/serve/serving_patterns/prefill_decode/pd_server.py).

Greedy decoding is deterministic, so the strongest correctness check is
exact token equality: a PD pipeline (separate prefill + decode engines,
KV shipped between them) must produce byte-identical generations to one
colocated engine with the same weights."""

import asyncio

import numpy as np
import pytest


def _cfg(**kw):
    from ray_tpu.serve.llm import LLMConfig
    base = dict(preset="tiny", max_batch_slots=4, max_seq_len=128,
                paged=True, page_size=16, prefill_chunk=32,
                prefix_cache=False, seed=3)
    base.update(kw)
    return LLMConfig(**base)


@pytest.fixture(scope="module")
def servers():
    from ray_tpu.serve.llm import LLMServer
    from ray_tpu.serve.pd import PDServer, PrefillServer
    plain = LLMServer(_cfg())
    prefill = PrefillServer(_cfg())
    pd = PDServer(_cfg(), prefill=prefill)
    return plain, prefill, pd


def test_prefill_kv_shapes(servers):
    _, prefill, _ = servers
    out = asyncio.run(prefill.prefill_kv(list(range(2, 39))))
    mc = prefill.model_cfg
    assert out["prompt_len"] == 37
    assert out["k"].shape == (mc.n_layers, mc.n_kv_heads, 37, mc.head_dim)
    assert out["v"].shape == out["k"].shape
    assert isinstance(out["token"], int)
    # the prefill slot was released — nothing leaks
    assert prefill.stats()["active"] == 0
    assert prefill.stats()["free_slots"] == 4


def test_pd_matches_colocated_greedy(servers):
    plain, _, pd = servers
    prompts = [list(range(5, 25)), [7, 3, 11] * 9, list(range(60, 100))]

    async def gen(server, p):
        return await server.generate(p, max_tokens=12)

    for p in prompts:
        ref = asyncio.run(gen(plain, p))
        got = asyncio.run(gen(pd, p))
        assert got["tokens"] == ref["tokens"], (p[:4], got, ref)
    assert pd.pd_requests == len(prompts)
    assert pd.stats()["pd_requests"] == len(prompts)


def test_pd_concurrent_requests(servers):
    plain, _, pd = servers

    async def many(server):
        outs = await asyncio.gather(*[
            server.generate([i + 2, i + 5, i + 9], max_tokens=8)
            for i in range(6)])
        return [o["tokens"] for o in outs]

    assert asyncio.run(many(pd)) == asyncio.run(many(plain))
    # all slots/pages returned on both engines
    for s in (plain, pd):
        st = s.stats()
        assert st["active"] == 0 and st["free_slots"] == 4
        assert st["pages_in_use"] == 0


def test_pd_logprobs_and_eos(servers):
    plain, _, pd = servers
    p = list(range(30, 50))

    async def gen(server):
        return await server.generate(p, max_tokens=6, logprobs=True)

    ref = asyncio.run(gen(plain))
    got = asyncio.run(gen(pd))
    assert got["tokens"] == ref["tokens"]
    np.testing.assert_allclose(got["logprobs"], ref["logprobs"],
                               rtol=1e-4, atol=1e-5)

    # eos on the FIRST (prefill-produced) token truncates to empty
    eos = ref["tokens"][0]
    got_eos = asyncio.run(pd.generate(p, max_tokens=6, eos_id=eos))
    assert got_eos["tokens"] == []


def test_pd_requires_paged():
    from ray_tpu.serve.llm import LLMConfig
    from ray_tpu.serve.pd import PrefillServer
    with pytest.raises(ValueError, match="paged"):
        server = PrefillServer(LLMConfig(preset="tiny", paged=False,
                                         max_seq_len=64))
        asyncio.run(server.prefill_kv([1, 2, 3]))


# ------------------- streaming data plane (zero-copy KV-page shipment) ---

def _no_arrays(x, where=""):
    """Control frames must carry metadata only — any ndarray in a header
    or segment dict means KV bytes went back into the RPC plane."""
    if isinstance(x, np.ndarray):
        raise AssertionError(f"ndarray leaked into control frame at {where}")
    if isinstance(x, dict):
        for k, v in x.items():
            _no_arrays(v, f"{where}.{k}")
    elif isinstance(x, (list, tuple)):
        for i, v in enumerate(x):
            _no_arrays(v, f"{where}[{i}]")


def test_stream_frames_carry_no_kv_bytes(servers):
    _, prefill, _ = servers

    async def drive():
        header = await prefill.prefill_begin(list(range(2, 39)))
        _no_arrays(header, "header")
        have, done = 0, False
        while not done:
            res = await prefill.prefill_wait(header["ship_id"], have)
            _no_arrays(res, "wait")
            have += len(res["segments"])
            done = res["done"]
        assert have >= 1
        await prefill.prefill_drop(header["ship_id"])
        return header

    header = asyncio.run(drive())
    assert header["total_pages"] == 3 and header["prompt_len"] == 37
    # slot released; drop freed every segment
    assert prefill.stats()["active"] == 0


def test_stream_suffix_install_parity():
    """Prefix-cache on both sides: the second request sharing 2 leading
    pages must ship only its suffix AND still decode bit-identically."""
    from ray_tpu.serve.llm import LLMServer
    from ray_tpu.serve.pd import PDServer, PrefillServer
    from ray_tpu.util import metrics as _metrics

    ref = LLMServer(_cfg(prefix_cache=True))
    prefill = PrefillServer(_cfg(prefix_cache=True), params=ref.params)
    pd = PDServer(_cfg(prefix_cache=True), params=ref.params,
                  prefill=prefill)

    p1 = list(range(5, 42))               # 37 tokens -> 3 pages
    p2 = p1[:32] + [91, 92, 93, 94, 95]   # shares the first 2 pages

    async def both(server):
        a = await server.generate(p1, max_tokens=8)
        b = await server.generate(p2, max_tokens=8)
        return a["tokens"], b["tokens"]

    before = _metrics.kv_ship_counters()
    got = asyncio.run(both(pd))
    want = asyncio.run(both(ref))
    assert got == want
    after = _metrics.kv_ship_counters()
    # the shared prefix pages were never shipped for p2
    assert after["saved_pages"] - before["saved_pages"] >= 2
    assert after["pages"] - before["pages"] <= 4  # 3 (p1) + 1 suffix (p2)


def test_stream_forced_remote_pull(servers, monkeypatch):
    """RAY_TPU_KV_ATTACH=0 forbids the same-host shm attach, forcing the
    KVDataServer + parallel_fetch ranged-transfer path."""
    from ray_tpu.util import metrics as _metrics
    plain, _, pd = servers
    monkeypatch.setenv("RAY_TPU_KV_ATTACH", "0")
    p = list(range(11, 53))
    before = _metrics.kv_ship_counters()
    got = asyncio.run(pd.generate(p, max_tokens=10))
    ref = asyncio.run(plain.generate(p, max_tokens=10))
    assert got["tokens"] == ref["tokens"]
    after = _metrics.kv_ship_counters()
    assert after["stream_pulls"] - before["stream_pulls"] >= 1
    assert after["attach_hits"] == before["attach_hits"]


def test_legacy_rpc_handoff_escape_hatch(servers, monkeypatch):
    """RAY_TPU_KV_SHIP=0 restores the whole-KV-over-RPC hand-off."""
    from ray_tpu.util import metrics as _metrics
    plain, _, pd = servers
    monkeypatch.setenv("RAY_TPU_KV_SHIP", "0")
    p = [9, 8, 7] * 8
    before = _metrics.kv_ship_counters()
    got = asyncio.run(pd.generate(p, max_tokens=9))
    ref = asyncio.run(plain.generate(p, max_tokens=9))
    assert got["tokens"] == ref["tokens"]
    # the streaming plane was bypassed entirely
    assert _metrics.kv_ship_counters()["segments"] == before["segments"]


def test_serving_bench_smoke_gate():
    """Tier-1 hook for the serving bench's --smoke mode: a subprocess PD
    round trip on CPU must ship KV through the streaming plane (counters
    nonzero) with zero KV bytes in the RPC control frames."""
    import json
    import os
    import subprocess
    import sys
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, os.path.join(root, "benchmarks",
                                      "serving_bench.py"), "--smoke"],
        capture_output=True, text=True, timeout=420, env=env)
    assert p.returncode == 0, (p.stdout, p.stderr)
    rec = json.loads(p.stdout.strip().splitlines()[-1])
    assert rec["smoke"] == "ok"
    assert rec["kv_ship"]["bytes"] > 0 and rec["kv_ship"]["pages"] > 0
    assert rec["kv_ship"]["rpc_fallback_bytes"] == 0


def test_pd_slo_histograms_tagged(servers):
    """PD requests must land in the serving SLO histograms under path=pd
    (the colocated path records path=local) — satellite of the streaming
    rework: TTFT/TPOT were previously never observed for PD."""
    from ray_tpu.util import metrics as _metrics
    _, _, pd = servers
    asyncio.run(pd.generate(list(range(40, 70)), max_tokens=8))

    def series_tags(name):
        m = _metrics._registry.get(name)
        assert m is not None, f"{name} not registered"
        return [dict(k) for k in m.snapshot()["count"]]

    assert any(t.get("path") == "pd" for t in series_tags("serve_ttft_s"))
    assert any(t.get("path") == "pd" for t in series_tags("serve_tpot_ms"))
