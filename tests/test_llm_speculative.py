"""Prompt-lookup speculative decoding (serve/llm.py speculate=K).

Reference contrast: the reference configures draft-MODEL speculation
through its vLLM engine wrappers; here the draft is the continuation of
the newest n-gram match in the request's own context, verified in one
[B, K+1] forward — no draft model, exact for greedy requests.
"""

import asyncio

import numpy as np
import pytest


def _run(coro):
    return asyncio.run(coro)


def _make(speculate, **kw):
    from ray_tpu.serve.llm import LLMConfig, LLMServer
    return LLMServer(LLMConfig(preset="tiny", max_batch_slots=2,
                               max_seq_len=128, speculate=speculate, **kw))


def test_lookup_draft():
    from ray_tpu.serve.llm import LLMServer
    ctx = [1, 2, 3, 9, 9, 1, 2, 3]
    assert LLMServer._lookup_draft(ctx, 2, 3) == [9, 9]
    assert LLMServer._lookup_draft(ctx, 4, 3) == [9, 9, 1, 2]
    assert LLMServer._lookup_draft([1, 2, 3], 2, 3) == []      # too short
    assert LLMServer._lookup_draft([4, 5, 6, 7], 2, 3) == []   # no match


def test_speculative_matches_plain_greedy():
    """The headline property: speculate=K must produce EXACTLY the tokens
    plain greedy decode produces — acceptance means draft == argmax
    target, so divergence anywhere is a bug, not noise."""
    # a repetitive prompt so the n-gram lookup actually fires
    prompt = [5, 6, 7, 8, 5, 6, 7, 8, 5, 6, 7, 8]
    plain = _make(0)
    out_plain = _run(plain.generate(prompt, max_tokens=24))
    spec = _make(4)
    out_spec = _run(spec.generate(prompt, max_tokens=24))
    assert out_spec["tokens"] == out_plain["tokens"]
    st = spec.stats()["speculation"]
    assert st["spec_ticks"] + st["decode_ticks"] > 0


def test_speculative_accepts_on_forced_repetition():
    """With an untrained tiny model the argmax sequence often cycles;
    drive a case where acceptance provably occurs by checking the
    accounting only when spec ticks ran, and the exactness test above
    pins correctness either way."""
    prompt = [3, 4, 3, 4, 3, 4, 3, 4]
    spec = _make(4)
    out = _run(spec.generate(prompt, max_tokens=30))
    assert len(out["tokens"]) == 30
    st = spec.stats()["speculation"]
    assert st["drafted"] >= 0 and st["accepted"] <= st["drafted"]


def test_speculative_logprobs_match_plain():
    prompt = [5, 6, 7, 8, 5, 6, 7, 8]
    plain = _make(0)
    a = _run(plain.generate(prompt, max_tokens=12, logprobs=True))
    spec = _make(4)
    b = _run(spec.generate(prompt, max_tokens=12, logprobs=True))
    assert b["tokens"] == a["tokens"]
    np.testing.assert_allclose(b["logprobs"], a["logprobs"],
                               rtol=2e-2, atol=2e-2)


def test_speculative_sampled_slots_advance_one_per_tick():
    """temperature>0 slots must keep the exact sampling policy (one
    categorical token per tick) while greedy slots speculate."""
    prompt = [5, 6, 7, 8, 5, 6, 7, 8]
    spec = _make(4)

    async def both():
        g = spec.generate(prompt, max_tokens=10)
        s = spec.generate(prompt, max_tokens=10, temperature=1.0)
        return await asyncio.gather(g, s)

    out_g, out_s = _run(both())
    assert len(out_g["tokens"]) == 10
    assert len(out_s["tokens"]) == 10


def test_speculative_rejects_paged():
    from ray_tpu.serve.llm import LLMConfig, LLMServer
    with pytest.raises(ValueError, match="speculate"):
        LLMServer(LLMConfig(preset="tiny", paged=True, speculate=4))


def test_speculative_eos_mid_window():
    """An eos accepted inside the speculative window must terminate the
    request at the eos, not emit the rest of the window."""
    prompt = [5, 6, 7, 8, 5, 6, 7, 8]
    plain = _make(0)
    ref = _run(plain.generate(prompt, max_tokens=24))["tokens"]
    eos = ref[len(ref) // 2]   # a token greedy decode provably emits
    spec = _make(4)
    out = _run(spec.generate(prompt, max_tokens=24, eos_id=eos))
    want = ref[:ref.index(eos)]
    assert out["tokens"] == want


def test_incremental_index_matches_reference_lookup():
    """The engine's per-slot n-gram index must agree with the unit-tested
    scan (_lookup_draft) on every prefix of a random sequence."""
    import random

    from ray_tpu.serve.llm import LLMServer

    rng = random.Random(0)
    seq = [rng.randrange(5) for _ in range(300)]
    n, K = 3, 4
    index, ctx = {}, []
    for tok in seq:
        ctx.append(tok)
        L = len(ctx)
        if L > n:
            index[tuple(ctx[L - 1 - n:L - 1])] = L - 1
        if L > n:
            pos = index.get(tuple(ctx[-n:]))
            via_index = ctx[pos:pos + K] if pos is not None else []
            assert via_index == LLMServer._lookup_draft(ctx, K, n)


def test_spec_skipped_while_prefill_row_near_cap():
    """The verify forward writes K+1 KV entries on EVERY row, including
    mid-prefill ones: a prefilling row within K+1 of max_seq_len must
    force a plain-decode tick (clamped writes silently corrupt KV)."""
    from ray_tpu.serve.llm import _PrefillJob, _Slot
    import asyncio as aio

    spec = _make(4)
    slot = spec._make_slot(8, 4, None, False, 0.0, None, None, False,
                           prompt_ids=[5, 6, 7, 8] * 2)
    slot.generated = [5, 6]
    spec._active[0] = slot
    assert spec._spec_drafts() is not None     # speculation viable
    stuck = spec._make_slot(126, 4, None, False, 0.0, None, None, False)
    job = _PrefillJob(slot_idx=1, slot=stuck,
                      prompt=np.arange(126, dtype=np.int32),
                      pos=126 - 1)              # 125 + 5 > 128
    spec._prefill_q.append(job)
    assert spec._spec_drafts() is None         # guard forces plain decode
    spec._prefill_q.clear()
    spec._active.clear()


def test_accept_rate_never_exceeds_one():
    prompt = [3, 4] * 8
    spec = _make(4, spec_ngram=2)
    _run(spec.generate(prompt, max_tokens=40))
    st = spec.stats()["speculation"]
    assert 0.0 <= st["accept_rate"] <= 1.0
    assert st["accepted"] <= st["drafted"]
