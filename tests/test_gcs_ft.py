"""GCS fault tolerance (ref: reference GCS FT — gcs_server restarts and
re-reads its Redis tables). Named sessions journal detached actors and
spilled objects; a NEW controller process on the same session restores both.
The first process dies with os._exit (no clean shutdown) to simulate a
crash."""

import json
import os
import pickle
import subprocess
import sys
import textwrap
import uuid

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD_A = textwrap.dedent("""
    import json, os, sys
    import numpy as np
    import ray_tpu as ray

    session = sys.argv[1]
    ray.init(num_cpus=2, session_name=session,
             object_store_memory=4 * 1024 * 1024)

    # three 2MB objects against a 4MB store: capacity pressure spills the
    # oldest unpinned ones (the first two) to disk
    a = np.arange(500_000, dtype=np.float32)          # ~2MB
    ref_a = ray.put(a)
    ref_b = ray.put(a * 2.0)
    ref_c = ray.put(a * 3.0)

    @ray.remote
    class Survivor:
        def __init__(self, tag):
            self.tag = tag
            self.calls = 0
        def ping(self):
            self.calls += 1
            return (self.tag, self.calls)

    s = Survivor.options(name="survivor", lifetime="detached").remote("v1")
    assert ray.get(s.ping.remote()) == ("v1", 1)

    print(json.dumps({"ref_a": ref_a.id, "ref_b": ref_b.id}), flush=True)
    os._exit(0)  # crash: no atexit shutdown, workers orphaned
""")

_CHILD_B = textwrap.dedent("""
    import json, sys
    import numpy as np
    import ray_tpu as ray

    session, ref_a = sys.argv[1], sys.argv[2]
    ray.init(num_cpus=2, session_name=session)

    # spilled object from the dead session resolves by id
    got = ray.get(ray.object_ref_from_id(ref_a), timeout=60)
    np.testing.assert_allclose(got, np.arange(500_000, dtype=np.float32))

    # detached actor was restored from its creation spec (fresh state)
    s = ray.get_actor("survivor")
    assert ray.get(s.ping.remote(), timeout=60) == ("v1", 1)
    print("GCS_RESTORE_OK", flush=True)
    ray.shutdown()
""")


def _run(code, *args):
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["RAY_TPU_NUM_CHIPS"] = "0"
    env.pop("RAY_TPU_ADDRESS", None)  # never attach to the test session
    return subprocess.run([sys.executable, "-c", code, *args],
                          env=env, capture_output=True, timeout=300)


def test_named_session_restores_actor_and_spilled_object():
    session = f"gcsft-{uuid.uuid4().hex[:8]}"
    ra = _run(_CHILD_A, session)
    assert ra.returncode == 0, ra.stdout.decode() + ra.stderr.decode()
    ids = json.loads(ra.stdout.decode().strip().splitlines()[-1])

    rb = _run(_CHILD_B, session, ids["ref_a"])
    out = rb.stdout.decode() + rb.stderr.decode()
    assert rb.returncode == 0, out
    assert "GCS_RESTORE_OK" in out


def test_journal_fold_last_write_wins():
    from ray_tpu._private.gcs import GcsJournal, fold
    import tempfile

    d = tempfile.mkdtemp()
    j = GcsJournal(d)
    j.record("detached_actor", actor_id="a1", spec=None, options=None)
    j.record("spilled", object_id="o1", path="/x", size=1, meta_len=0)
    j.record("actor_dead", actor_id="a1")
    j.record("spilled", object_id="o2", path="/y", size=2, meta_len=0)
    j.record("object_gone", object_id="o1")
    j.close()
    actors, objects = fold(GcsJournal(d).load())
    assert actors == {}
    assert list(objects) == ["o2"]


def test_torn_tail_frame_dropped():
    from ray_tpu._private.gcs import GcsJournal, fold
    import tempfile

    d = tempfile.mkdtemp()
    j = GcsJournal(d)
    j.record("spilled", object_id="o1", path="/x", size=1, meta_len=0)
    j.close()
    with open(os.path.join(d, "gcs.journal"), "ab") as f:
        # a genuinely half-written pickle frame (crash mid-write): real
        # frame bytes truncated, not a printable stand-in
        f.write(pickle.dumps({"kind": "spilled", "object_id": "o2"})[:7])
    _actors, objects = fold(GcsJournal(d).load())
    assert list(objects) == ["o1"]
