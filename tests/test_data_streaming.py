"""Streaming executor: task-parallel pipeline with byte-budget backpressure
(VERDICT r2 #3; ref: ray.data streaming_executor + backpressure_policy)."""

import numpy as np
import pytest


def _mk(ray, n_blocks=20, rows_per_block=100):
    import ray_tpu.data as rdata
    return rdata.range(n_blocks * rows_per_block, override_num_blocks=n_blocks)


def test_streaming_map_matches_expected(ray_session):
    ray = ray_session
    ds = _mk(ray).map_batches(lambda b: {"id": b["id"] * 2})
    got = sorted(r["id"] for r in ds.take_all())
    assert got == [2 * i for i in range(2000)]


def test_streaming_shuffle_is_permutation_and_deterministic(ray_session):
    ray = ray_session
    ds = _mk(ray, n_blocks=30)
    s1 = [r["id"] for r in ds.random_shuffle(seed=11).take_all()]
    s2 = [r["id"] for r in ds.random_shuffle(seed=11).take_all()]
    s3 = [r["id"] for r in ds.random_shuffle(seed=12).take_all()]
    assert sorted(s1) == list(range(3000))
    assert s1 == s2
    assert s1 != s3
    assert s1 != list(range(3000))


def test_backpressure_bounds_queue_memory(ray_session):
    """100-block map+shuffle pipeline must hold queued bytes near the
    configured budget instead of materializing the dataset."""
    ray = ray_session
    import ray_tpu.data as rdata

    n_blocks, rows = 100, 2000  # ~16KB/block of int64 -> ~1.6MB total
    ds = rdata.range(n_blocks * rows, override_num_blocks=n_blocks)
    ds = ds.map_batches(lambda b: {"id": b["id"], "pad": b["id"] * 3})
    ds = ds.random_shuffle(seed=5)
    plan = ds._plan
    plan.op_budget = 64 << 10  # 64KB: a few blocks per queue

    total = 0
    for batch in ds.iter_batches(batch_size=1000, batch_format="numpy"):
        total += len(batch["id"])
    assert total == n_blocks * rows
    ex = plan.last_executor
    assert ex is not None
    # Bounded by ~a window (budget + one in-flight wave of ~32KB blocks) per
    # operator — ~2 windows of real residency — and far below the ~4.8MB that
    # full materialization of source+map+shuffle outputs would hold.
    window = plan.op_budget + 8 * 32 * 1024
    assert ex.peak_accounted_bytes < 3 * window, ex.peak_accounted_bytes
    assert ex.peak_accounted_bytes < (4_800_000) // 4, ex.peak_accounted_bytes


def test_streaming_then_barrier_sort(ray_session):
    ray = ray_session
    ds = _mk(ray, n_blocks=10).map_batches(lambda b: {"id": b["id"]})
    ds = ds.random_shuffle(seed=3).sort("id")
    got = [r["id"] for r in ds.take_all()]
    assert got == list(range(1000))


def test_two_same_named_stages_run_distinct_fns(ray_session):
    """Code-review regression: remote-fn cache keyed by stage name alone made
    a second map_batches silently re-run the first's function."""
    ray = ray_session
    import ray_tpu.data as rdata
    ds = (rdata.range(200, override_num_blocks=4)
          .map_batches(lambda b: {"id": b["id"] * 2})
          .random_shuffle(seed=1)
          .map_batches(lambda b: {"id": b["id"] + 1}))
    got = sorted(r["id"] for r in ds.take_all())
    assert got == [2 * i + 1 for i in range(200)]


def test_streaming_shuffle_stable_across_runs(ray_session):
    """Code-review regression: parts must reduce in block order, not map-task
    completion order, or a fixed seed gives different outputs run-to-run."""
    ray = ray_session
    runs = []
    for _ in range(3):
        ds = _mk(ray, n_blocks=16).random_shuffle(seed=21)
        runs.append([r["id"] for r in ds.take_all()])
    assert runs[0] == runs[1] == runs[2]


def test_streaming_sort_range_partitioned(ray_session):
    """Sort runs as sampled range partitioning (VERDICT r3 weak #1): output
    equals pandas, and driver-gated queues stay bounded — no process ever
    concatenates the dataset (barrier refs wait in the spillable store)."""
    import pandas as pd
    import ray_tpu.data as rdata

    n_blocks, rows = 40, 1000
    rng = np.random.default_rng(3)
    vals = rng.integers(0, 1_000_000, n_blocks * rows)
    ds = rdata.from_pandas(
        pd.DataFrame({"v": vals, "pad": vals * 7})).repartition(n_blocks)
    plan_budget = 64 << 10
    ds2 = ds.sort("v")
    ds2._plan.op_budget = plan_budget

    got = [r["v"] for r in ds2.take_all()]
    want = sorted(vals.tolist())
    assert got == want
    ex = ds2._plan.last_executor
    assert ex is not None
    # driver-gated queue bytes bounded near the budget, not the dataset
    assert ex.peak_accounted_bytes < 6 * plan_budget, ex.peak_accounted_bytes

    # descending
    got_d = [r["v"] for r in ds.sort("v", descending=True).take_all()]
    assert got_d == want[::-1]


def test_streaming_groupby_exact_and_sorted(ray_session):
    """Groupby range-partitions on the key: per-partition aggregation is
    exact (each key in one partition) and output is globally key-sorted."""
    import pandas as pd
    import ray_tpu.data as rdata

    rng = np.random.default_rng(7)
    keys = rng.integers(0, 97, 20_000)
    vals = rng.standard_normal(20_000)
    ds = rdata.from_pandas(
        pd.DataFrame({"k": keys, "x": vals})).repartition(25)

    out = ds.groupby("k").mean("x").take_all()
    got = {r["k"]: r["mean(x)"] for r in out}
    want = pd.DataFrame({"k": keys, "x": vals}).groupby("k")["x"].mean()
    assert set(got) == set(want.index)
    for k, v in want.items():
        assert abs(got[k] - v) < 1e-9
    assert [r["k"] for r in out] == sorted(got)  # range order -> key-sorted

    counts = {r["k"]: r["count()"] for r in ds.groupby("k").count().take_all()}
    want_c = pd.Series(keys).value_counts()
    assert counts == {int(k): int(v) for k, v in want_c.items()}


def test_streaming_repartition_preserves_order(ray_session):
    import ray_tpu.data as rdata

    ds = rdata.range(5000, override_num_blocks=13).repartition(7)
    blocks = ds.to_block_list()
    assert len(blocks) == 7
    ids = [i for b in blocks for i in b.column("id").to_pylist()]
    assert ids == list(range(5000))  # row order preserved across re-blocking
    assert [b.num_rows for b in blocks] == [715] * 6 + [710]


def test_streaming_split_eager_variants(ray_session):
    """split/split_at_indices/train_test_split run through the streaming
    shuffle (no driver concat) and preserve order + exact boundaries."""
    import ray_tpu.data as rdata

    ds = rdata.range(1000, override_num_blocks=9)
    a, b, c = ds.split_at_indices([100, 450])
    assert [r["id"] for r in a.take_all()] == list(range(100))
    assert [r["id"] for r in b.take_all()] == list(range(100, 450))
    assert [r["id"] for r in c.take_all()] == list(range(450, 1000))

    parts = ds.split(3)
    ids = [r["id"] for p in parts for r in p.take_all()]
    assert ids == list(range(1000))

    eq = ds.split(3, equal=True)
    sizes = [len(p.take_all()) for p in eq]
    assert sizes == [334, 334, 332] or sizes == [333, 333, 333], sizes

    tr, te = ds.train_test_split(0.2)
    assert len(tr.take_all()) == 800 and len(te.take_all()) == 200
    assert [r["id"] for r in te.take_all()] == list(range(800, 1000))
