"""Legacy tune.run / Trainable / registry (ref: python/ray/tune/tune.py,
tune/trainable/trainable.py, tune/registry.py)."""

import pytest

from ray_tpu import tune


def test_run_function_trainable(ray_session):
    def trainable(config):
        tune.report({"score": config["x"] * 2})

    analysis = tune.run(trainable, config={"x": tune.grid_search([1, 3])},
                        metric="score", mode="max")
    assert analysis.best_result["score"] == 6
    assert analysis.best_config["x"] == 3
    assert len(analysis.trials) == 2
    assert "score" in analysis.dataframe().columns


def test_run_class_trainable_with_stop(ray_session):
    class Counter(tune.Trainable):
        def setup(self, config):
            self.base = config.get("base", 0)

        def step(self):
            return {"value": self.base + self.iteration}

    analysis = tune.run(Counter, config={"base": tune.grid_search([0, 10])},
                        stop={"training_iteration": 3},
                        metric="value", mode="max")
    # 3 iterations: last value = base + 2
    assert analysis.best_result["value"] == 12
    assert analysis.best_result["training_iteration"] == 3


def test_registered_trainable_and_env(ray_session):
    def trainable(config):
        tune.report({"v": 1})

    tune.register_trainable("my_trainable", trainable)
    analysis = tune.run("my_trainable", metric="v", mode="max")
    assert analysis.best_result["v"] == 1
    with pytest.raises(ValueError, match="unknown trainable"):
        tune.run("nope", metric="v")

    import gymnasium as gym
    made = []

    def creator(env_config):
        made.append(env_config)
        return gym.make("CartPole-v1")

    tune.register_env("my_cartpole", creator)
    from ray_tpu.rllib.env_runner import EnvRunner
    r = EnvRunner("my_cartpole", num_envs=1, rollout_len=8,
                  env_config={"difficulty": 2})
    r.set_weights(r.init_params())
    batch = r.sample()
    assert made and made[0] == {"difficulty": 2}
    assert len(batch["obs"]) == 8


def test_create_scheduler_and_searcher():
    from ray_tpu.tune.schedulers import ASHAScheduler
    s = tune.create_scheduler("asha")
    assert isinstance(s, ASHAScheduler)
    with pytest.raises(ValueError, match="unknown scheduler"):
        tune.create_scheduler("bogus")
    assert tune.create_searcher("random") is None


def test_registered_env_reaches_remote_runners(ray_session):
    """register_env + num_env_runners>0: the creator must resolve
    DRIVER-side and pickle into the runner actors (their process-local
    registry is empty — r5 review)."""
    import gymnasium as gym

    from ray_tpu.rllib import PPOConfig

    def creator(env_config):
        assert env_config.get("tag") == "remote"
        return gym.make("CartPole-v1")

    tune.register_env("remote_cartpole", creator)
    algo = (PPOConfig()
            .environment("remote_cartpole", env_config={"tag": "remote"})
            .env_runners(num_env_runners=1, num_envs_per_env_runner=1,
                         rollout_fragment_length=16)
            .training(train_batch_size=16, minibatch_size=16, num_epochs=1)
            .build())
    try:
        result = algo.train()
        assert result["num_env_steps_sampled_this_iter"] > 0
    finally:
        algo.stop()


def test_stop_callable_two_arg_signature(ray_session):
    def trainable(config):
        for i in range(10):
            tune.report({"i": i})

    seen = []

    def stop(trial_id, result):   # the reference's two-arg signature
        seen.append(trial_id)
        return result["i"] >= 2

    analysis = tune.run(trainable, stop=stop, metric="i", mode="max")
    assert seen and analysis.best_result["i"] <= 9


def test_resources_per_trial_does_not_leak_to_registered(ray_session):
    def trainable(config):
        tune.report({"v": 1})

    tune.register_trainable("shared_t", trainable)
    tune.run("shared_t", metric="v", mode="max",
             resources_per_trial={"cpu": 1})
    assert not hasattr(trainable, "_tune_resources")
