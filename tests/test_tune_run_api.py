"""Legacy tune.run / Trainable / registry (ref: python/ray/tune/tune.py,
tune/trainable/trainable.py, tune/registry.py)."""

import pytest

from ray_tpu import tune


def test_run_function_trainable(ray_session):
    def trainable(config):
        tune.report({"score": config["x"] * 2})

    analysis = tune.run(trainable, config={"x": tune.grid_search([1, 3])},
                        metric="score", mode="max")
    assert analysis.best_result["score"] == 6
    assert analysis.best_config["x"] == 3
    assert len(analysis.trials) == 2
    assert "score" in analysis.dataframe().columns


def test_run_class_trainable_with_stop(ray_session):
    class Counter(tune.Trainable):
        def setup(self, config):
            self.base = config.get("base", 0)

        def step(self):
            return {"value": self.base + self.iteration}

    analysis = tune.run(Counter, config={"base": tune.grid_search([0, 10])},
                        stop={"training_iteration": 3},
                        metric="value", mode="max")
    # 3 iterations: last value = base + 2
    assert analysis.best_result["value"] == 12
    assert analysis.best_result["training_iteration"] == 3


def test_registered_trainable_and_env(ray_session):
    def trainable(config):
        tune.report({"v": 1})

    tune.register_trainable("my_trainable", trainable)
    analysis = tune.run("my_trainable", metric="v", mode="max")
    assert analysis.best_result["v"] == 1
    with pytest.raises(ValueError, match="unknown trainable"):
        tune.run("nope", metric="v")

    import gymnasium as gym
    made = []

    def creator(env_config):
        made.append(env_config)
        return gym.make("CartPole-v1")

    tune.register_env("my_cartpole", creator)
    from ray_tpu.rllib.env_runner import EnvRunner
    r = EnvRunner("my_cartpole", num_envs=1, rollout_len=8,
                  env_config={"difficulty": 2})
    r.set_weights(r.init_params())
    batch = r.sample()
    assert made and made[0] == {"difficulty": 2}
    assert len(batch["obs"]) == 8


def test_create_scheduler_and_searcher():
    from ray_tpu.tune.schedulers import ASHAScheduler
    s = tune.create_scheduler("asha")
    assert isinstance(s, ASHAScheduler)
    with pytest.raises(ValueError, match="unknown scheduler"):
        tune.create_scheduler("bogus")
    assert tune.create_searcher("random") is None


def test_registered_env_reaches_remote_runners(ray_session):
    """register_env + num_env_runners>0: the creator must resolve
    DRIVER-side and pickle into the runner actors (their process-local
    registry is empty — r5 review)."""
    import gymnasium as gym

    from ray_tpu.rllib import PPOConfig

    def creator(env_config):
        assert env_config.get("tag") == "remote"
        return gym.make("CartPole-v1")

    tune.register_env("remote_cartpole", creator)
    algo = (PPOConfig()
            .environment("remote_cartpole", env_config={"tag": "remote"})
            .env_runners(num_env_runners=1, num_envs_per_env_runner=1,
                         rollout_fragment_length=16)
            .training(train_batch_size=16, minibatch_size=16, num_epochs=1)
            .build())
    try:
        result = algo.train()
        assert result["num_env_steps_sampled_this_iter"] > 0
    finally:
        algo.stop()


def test_stop_callable_two_arg_signature(ray_session):
    def trainable(config):
        for i in range(10):
            tune.report({"i": i})

    seen = []

    def stop(trial_id, result):   # the reference's two-arg signature
        seen.append(trial_id)
        return result["i"] >= 2

    analysis = tune.run(trainable, stop=stop, metric="i", mode="max")
    assert seen and analysis.best_result["i"] <= 9


def test_resources_per_trial_does_not_leak_to_registered(ray_session):
    def trainable(config):
        tune.report({"v": 1})

    tune.register_trainable("shared_t", trainable)
    tune.run("shared_t", metric="v", mode="max",
             resources_per_trial={"cpu": 1})
    assert not hasattr(trainable, "_tune_resources")


def test_class_trainable_checkpoints(ray_session, tmp_path):
    """checkpoint_freq wires Trainable.save_checkpoint into the loop;
    best_checkpoint is a real directory with the saved state."""
    import json
    import os

    class Ck(tune.Trainable):
        def step(self):
            return {"v": self.iteration}

        def save_checkpoint(self, d):
            with open(os.path.join(d, "state.json"), "w") as f:
                json.dump({"iteration": self.iteration}, f)

    analysis = tune.run(Ck, stop={"training_iteration": 4},
                        checkpoint_freq=2, metric="v", mode="max",
                        storage_path=str(tmp_path))
    ck = analysis.best_checkpoint
    assert ck is not None
    state = json.load(open(os.path.join(ck.path, "state.json")))
    assert state["iteration"] in (2, 4)


def test_stop_callable_one_required_arg_with_default(ray_session):
    def trainable(config):
        for i in range(6):
            tune.report({"i": i})

    def stop(result, verbose=False):   # one REQUIRED arg
        return result["i"] >= 1

    analysis = tune.run(trainable, stop=stop, metric="i", mode="max")
    assert analysis.best_result["i"] <= 5


def test_class_udf_state_not_shared_across_pipelines(ray_session):
    from ray_tpu import data as rd

    class Accum:
        def __init__(self):
            self.seen = 0

        def __call__(self, batch):
            self.seen += len(batch["id"])
            return {"seen": __import__("numpy").full(len(batch["id"]),
                                                     self.seen)}

    # one block → one worker → one instance sees all 4 rows
    a = rd.range(4, override_num_blocks=1).map_batches(Accum).take_all()
    b = rd.range(4, override_num_blocks=1).map_batches(Accum).take_all()
    # pipeline B starts from fresh state: a leak would accumulate to 8
    assert max(r["seen"] for r in a) == 4
    assert max(r["seen"] for r in b) == 4


def test_class_udf_fresh_state_on_reconsumption(ray_session):
    """A lazy Dataset consumed twice must give the stateful UDF a FRESH
    instance per execution (r5 review: the build-time cache key let run 2
    continue run 1's state)."""
    from ray_tpu import data as rd

    class Accum2:
        def __init__(self):
            self.seen = 0

        def __call__(self, batch):
            self.seen += len(batch["id"])
            return {"seen": __import__("numpy").full(len(batch["id"]),
                                                     self.seen)}

    ds = rd.range(4, override_num_blocks=1).map_batches(Accum2)
    first = max(r["seen"] for r in ds.take_all())
    second = max(r["seen"] for r in ds.take_all())
    assert first == 4 and second == 4


def test_ctor_args_with_non_class_udf_raises():
    from ray_tpu import data as rd
    with pytest.raises(ValueError, match="CLASS UDF"):
        rd.range(4).map_batches(lambda b: b, fn_constructor_args=(1,))


def test_class_trainable_resume_continues_iterations(tmp_path):
    """load_checkpoint + the iteration sidecar: a resumed class trainable
    continues its training_iteration sequence and budget (r5 review: it
    rewound to 1 and overran the stop criterion)."""
    import json
    import os

    from ray_tpu.train import session as _session
    from ray_tpu.train.checkpoint import Checkpoint
    from ray_tpu.tune.experiment import Trainable, _class_to_function

    class Ck(Trainable):
        def step(self):
            return {"v": self.iteration}

        def save_checkpoint(self, d):
            pass

    # a checkpoint recorded at iteration 2
    ckdir = tmp_path / "ck"
    ckdir.mkdir()
    json.dump({"iteration": 2}, open(ckdir / "_trainable_meta.json", "w"))

    reported = []
    ctx = _session.TrainContext(trial_name="t", trial_id="t",
                                trial_dir=str(tmp_path))
    _session.init_session(ctx, checkpoint=Checkpoint(str(ckdir)),
                          report_fn=lambda m, c: reported.append(m))
    try:
        _class_to_function(Ck, max_iters=4)({})
    finally:
        _session.shutdown_session()
    # resumed at iter 2: exactly 2 MORE steps, numbered 3 and 4
    assert [m["training_iteration"] for m in reported] == [3, 4]
