"""Dashboard observability HTTP surface: /api/metrics scrape, /api/timeline
Chrome trace export, and malformed-request handling (ISSUE 6 satellite;
ref: python/ray/dashboard REST routes + metrics agent scrape port)."""

import json
import socket
import urllib.error
import urllib.request

import pytest


@pytest.fixture(scope="module")
def dash(ray_session):
    from ray_tpu.dashboard import start_dashboard
    _actor, port = start_dashboard(port=0)
    return ray_session, f"http://127.0.0.1:{port}"


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as r:
        return r.headers, r.read()


def test_metrics_prometheus_exposition(dash):
    """Every util.metrics series renders as well-formed Prometheus text:
    one TYPE line per metric, counter/gauge samples, histogram buckets
    with cumulative counts and a +Inf terminator."""
    ray, base = dash
    ray.get(ray.remote(lambda: 1).remote())  # touch the control plane

    hdrs, body = _get(base, "/api/metrics")
    assert hdrs["Content-Type"].startswith("text/plain")
    text = body.decode()

    # cluster gauges synthesized from controller state
    assert "# TYPE ray_tpu_workers gauge" in text
    assert "ray_tpu_resource_total{resource=\"CPU\"}" in text
    # controller-registry series fetched over the state RPC: the head
    # counts async result applications, so a completed task must show up
    assert "# TYPE result_async_tasks counter" in text

    # structural invariants: every sample line's metric name has a TYPE
    typed = {ln.split()[2] for ln in text.splitlines()
             if ln.startswith("# TYPE")}
    for ln in text.splitlines():
        if not ln or ln.startswith("#"):
            continue
        name = ln.split("{")[0].split()[0]
        base_name = name
        for suf in ("_bucket", "_sum", "_count"):
            if name.endswith(suf) and name[:-len(suf)] in typed:
                base_name = name[:-len(suf)]
        assert base_name in typed, f"sample without TYPE: {ln}"

    # /metrics is an alias of /api/metrics
    _, body2 = _get(base, "/metrics")
    assert b"# TYPE ray_tpu_workers gauge" in body2


def test_timeline_chrome_trace(dash):
    """/api/timeline returns Chrome trace_event JSON: per-task phase spans
    ("X" events, microsecond ts/dur) carrying the derived trace id."""
    ray, base = dash

    @ray.remote
    def traced(x):
        return x + 1

    refs = [traced.remote(i) for i in range(4)]
    assert ray.get(refs) == [1, 2, 3, 4]

    hdrs, body = _get(base, "/api/timeline")
    assert hdrs["Content-Type"].startswith("application/json")
    events = json.loads(body)
    assert isinstance(events, list)

    phase_evs = [e for e in events if e.get("cat") == "task_phase"]
    assert phase_evs, "no task_phase events in the timeline"
    by_task = {}
    for e in phase_evs:
        assert e["ph"] == "X" and "ts" in e and e["dur"] >= 0
        args = e["args"]
        assert args["trace_id"] and args["task_id"]
        by_task.setdefault(args["task_id"], set()).add(args["phase"])
    # at least one completed task shows the full queued/exec/publish split
    assert any({"queued", "exec", "publish"} <= ph
               for ph in by_task.values()), by_task
    # default sampling derives the trace id from the task id itself
    assert any(e["args"]["trace_id"] == e["args"]["task_id"]
               for e in phase_evs)


def test_task_state_rows_carry_phases(dash):
    """The state API surfaces per-task phase durations (get_task parity)."""
    ray, base = dash
    ray.get(ray.remote(lambda: "ok").remote())
    _, body = _get(base, "/api/tasks")
    rows = json.loads(body)
    done = [r for r in rows if r.get("phases")]
    assert done, rows[:3]
    ph = done[0]["phases"]
    assert {"queued", "exec", "publish"} <= set(ph)
    assert all(v >= 0 for v in ph.values())


def test_unknown_route_is_404_json(dash):
    _, base = dash
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(base, "/api/nonsense")
    assert ei.value.code == 404
    assert "no route" in json.loads(ei.value.read())["error"]


def test_bad_job_body_is_400(dash):
    _, base = dash
    req = urllib.request.Request(
        base + "/api/jobs/", data=b"{not json", method="POST",
        headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=30)
    assert ei.value.code == 400
    assert "invalid JSON" in json.loads(ei.value.read())["error"]


def test_malformed_http_request_is_400(dash):
    """A parseable request line with a garbage Content-Length must produce
    a 400, not a hung connection or a traceback page."""
    _, base = dash
    host, port = base[len("http://"):].split(":")
    with socket.create_connection((host, int(port)), timeout=30) as s:
        s.sendall(b"GET /api/version HTTP/1.1\r\n"
                  b"Content-Length: banana\r\n\r\n")
        s.settimeout(30)
        data = s.recv(4096)
    assert data.startswith(b"HTTP/1.1 400"), data[:200]
