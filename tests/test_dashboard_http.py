"""Dashboard observability HTTP surface: /api/metrics scrape, /api/timeline
Chrome trace export, and malformed-request handling (ISSUE 6 satellite;
ref: python/ray/dashboard REST routes + metrics agent scrape port)."""

import json
import socket
import urllib.error
import urllib.request

import pytest


@pytest.fixture(scope="module")
def dash(ray_session):
    from ray_tpu.dashboard import start_dashboard
    _actor, port = start_dashboard(port=0)
    return ray_session, f"http://127.0.0.1:{port}"


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as r:
        return r.headers, r.read()


def test_metrics_prometheus_exposition(dash):
    """Every util.metrics series renders as well-formed Prometheus text:
    one TYPE line per metric, counter/gauge samples, histogram buckets
    with cumulative counts and a +Inf terminator."""
    ray, base = dash
    ray.get(ray.remote(lambda: 1).remote())  # touch the control plane

    hdrs, body = _get(base, "/api/metrics")
    assert hdrs["Content-Type"].startswith("text/plain")
    text = body.decode()

    # cluster gauges synthesized from controller state
    assert "# TYPE ray_tpu_workers gauge" in text
    assert "ray_tpu_resource_total{resource=\"CPU\"}" in text
    # controller-registry series fetched over the state RPC: the head
    # counts async result applications, so a completed task must show up;
    # counters carry the conformant _total suffix
    assert "# TYPE result_async_tasks_total counter" in text
    assert "# TYPE result_async_tasks counter" not in text

    # structural invariants: every sample line's metric name has a TYPE
    typed = {ln.split()[2] for ln in text.splitlines()
             if ln.startswith("# TYPE")}
    for ln in text.splitlines():
        if not ln or ln.startswith("#"):
            continue
        name = ln.split("{")[0].split()[0]
        base_name = name
        for suf in ("_bucket", "_sum", "_count"):
            if name.endswith(suf) and name[:-len(suf)] in typed:
                base_name = name[:-len(suf)]
        assert base_name in typed, f"sample without TYPE: {ln}"

    # /metrics is an alias of /api/metrics
    _, body2 = _get(base, "/metrics")
    assert b"# TYPE ray_tpu_workers gauge" in body2


def test_timeline_chrome_trace(dash):
    """/api/timeline returns Chrome trace_event JSON: per-task phase spans
    ("X" events, microsecond ts/dur) carrying the derived trace id."""
    ray, base = dash

    @ray.remote
    def traced(x):
        return x + 1

    refs = [traced.remote(i) for i in range(4)]
    assert ray.get(refs) == [1, 2, 3, 4]

    hdrs, body = _get(base, "/api/timeline")
    assert hdrs["Content-Type"].startswith("application/json")
    events = json.loads(body)
    assert isinstance(events, list)

    phase_evs = [e for e in events if e.get("cat") == "task_phase"]
    assert phase_evs, "no task_phase events in the timeline"
    by_task = {}
    for e in phase_evs:
        assert e["ph"] == "X" and "ts" in e and e["dur"] >= 0
        args = e["args"]
        assert args["trace_id"] and args["task_id"]
        by_task.setdefault(args["task_id"], set()).add(args["phase"])
    # at least one completed task shows the full queued/exec/publish split
    assert any({"queued", "exec", "publish"} <= ph
               for ph in by_task.values()), by_task
    # default sampling derives the trace id from the task id itself
    assert any(e["args"]["trace_id"] == e["args"]["task_id"]
               for e in phase_evs)


def test_task_state_rows_carry_phases(dash):
    """The state API surfaces per-task phase durations (get_task parity)."""
    ray, base = dash
    ray.get(ray.remote(lambda: "ok").remote())
    _, body = _get(base, "/api/tasks")
    rows = json.loads(body)
    done = [r for r in rows if r.get("phases")]
    assert done, rows[:3]
    ph = done[0]["phases"]
    assert {"queued", "exec", "publish"} <= set(ph)
    assert all(v >= 0 for v in ph.values())


def _parse_prometheus(text):
    """Minimal text-format 0.0.4 parser: returns (types, samples) where
    samples is [(name, {label: value}, float)]. Raises on malformed lines
    — the round-trip test feeds it nasty label values."""
    import re
    types = {}
    samples = []
    label_re = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

    def unescape(v):
        out, i = [], 0
        while i < len(v):
            if v[i] == "\\" and i + 1 < len(v):
                out.append({"n": "\n", "\\": "\\", '"': '"'}
                           .get(v[i + 1], v[i + 1]))
                i += 2
            else:
                out.append(v[i])
                i += 1
        return "".join(out)

    for ln in text.splitlines():
        if not ln:
            continue
        if ln.startswith("# TYPE "):
            _, _, name, mtype = ln.split(None, 3)
            assert name not in types, f"duplicate TYPE for {name}"
            types[name] = mtype
            continue
        if ln.startswith("#"):
            continue
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})? (\S+)$", ln)
        assert m, f"unparseable sample line: {ln!r}"
        name, _, labels_raw, value = m.groups()
        labels = {}
        if labels_raw:
            consumed = ",".join(f'{k}="{v}"'
                                for k, v in label_re.findall(labels_raw))
            assert consumed == labels_raw, f"bad label syntax: {labels_raw!r}"
            labels = {k: unescape(v) for k, v in label_re.findall(labels_raw)}
        samples.append((name, labels, float(value)))
    return types, samples


def test_prometheus_label_escaping_round_trip():
    """Nasty label values (backslash, quote, newline) survive render →
    parse; HELP/TYPE appear once per family even across merged registries;
    counters get the _total suffix exactly once."""
    from ray_tpu.dashboard import _prometheus_text

    nasty = 'a\\b"c\nd'
    snaps = [
        {"type": "counter", "name": "rt_evil", "description": 'has "quotes"',
         "values": {(("tag", nasty),): 3.0}},
        # same family from a second registry: samples merge, no second TYPE
        {"type": "counter", "name": "rt_evil", "description": 'has "quotes"',
         "values": {(("tag", "plain"),): 1.0}},
        # already-suffixed counter must not become _total_total
        {"type": "counter", "name": "rt_done_total", "description": "",
         "values": {(): 2.0}},
        {"type": "gauge", "name": "rt_gauge", "description": "",
         "values": {(("node", "n\\1"),): 7.5}},
        {"type": "histogram", "name": "rt_hist", "description": "h",
         "boundaries": [1.0, 2.0], "buckets": {(("k", 'q"v'),): [1, 2, 3]},
         "sum": {(("k", 'q"v'),): 9.0}, "count": {(("k", 'q"v'),): 6}},
    ]
    text = _prometheus_text(snaps)
    types, samples = _parse_prometheus(text)
    assert types["rt_evil_total"] == "counter"
    assert "rt_evil" not in types
    assert types["rt_done_total"] == "counter"
    assert "rt_done_total_total" not in types
    assert text.count("# TYPE rt_evil_total") == 1
    assert text.count("# HELP rt_evil_total") == 1
    by_name = {}
    for name, labels, value in samples:
        by_name.setdefault(name, []).append((labels, value))
    # the escaped value round-trips to the original bytes
    assert ({"tag": nasty}, 3.0) in by_name["rt_evil_total"]
    assert ({"tag": "plain"}, 1.0) in by_name["rt_evil_total"]
    assert ({"node": "n\\1"}, 7.5) in by_name["rt_gauge"]
    # histogram structure: cumulative buckets + +Inf terminator
    buckets = by_name["rt_hist_bucket"]
    assert ({"k": 'q"v', "le": "1.0"}, 1.0) in buckets
    assert ({"k": 'q"v', "le": "2.0"}, 3.0) in buckets
    assert ({"k": 'q"v', "le": "+Inf"}, 6.0) in buckets
    assert by_name["rt_hist_sum"] == [({"k": 'q"v'}, 9.0)]
    assert by_name["rt_hist_count"] == [({"k": 'q"v'}, 6.0)]


def test_live_scrape_parses_clean(dash):
    """The real /api/metrics payload round-trips through the parser: every
    line well-formed, every TYPE unique, every counter family _total."""
    ray, base = dash
    ray.get(ray.remote(lambda: 1).remote())
    _, body = _get(base, "/api/metrics")
    types, samples = _parse_prometheus(body.decode())
    assert samples
    for name, mtype in types.items():
        if mtype == "counter":
            assert name.endswith("_total"), name


def test_cluster_health_endpoint(dash):
    """/api/cluster aggregates per-node health rows + alerts + leaks."""
    ray, base = dash
    ray.get(ray.remote(lambda: 1).remote())
    _, body = _get(base, "/api/cluster")
    health = json.loads(body)
    assert {"ts", "nodes", "resources", "queue", "alerts", "leaks"} \
        <= set(health)
    head = health["nodes"][0]
    assert head["is_head"] and head["alive"]
    assert {"queue_depth", "workers_busy", "workers_idle", "store_used",
            "store_capacity", "store_objects"} <= set(head)
    assert head["store_capacity"] > 0


def test_alerts_endpoint(dash):
    """/api/alerts serves the chronological alert event list (empty or
    not, always a JSON list)."""
    _, base = dash
    hdrs, body = _get(base, "/api/alerts")
    assert hdrs["Content-Type"].startswith("application/json")
    events = json.loads(body)
    assert isinstance(events, list)
    for ev in events:
        assert {"id", "ts", "kind", "key", "severity", "message"} <= set(ev)


def test_unknown_route_is_404_json(dash):
    _, base = dash
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(base, "/api/nonsense")
    assert ei.value.code == 404
    assert ei.value.headers["Content-Type"].startswith("application/json")
    assert "no route" in json.loads(ei.value.read())["error"]


def test_handler_exception_is_500_json(dash):
    """A handler exception surfaces as a JSON 500 (the /api/_boom test
    hook raises), not a dropped connection or a text/plain traceback."""
    _, base = dash
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(base, "/api/_boom")
    assert ei.value.code == 500
    assert ei.value.headers["Content-Type"].startswith("application/json")
    payload = json.loads(ei.value.read())
    assert "RuntimeError" in payload["error"]
    assert "boom" in payload["traceback"]


def test_bad_job_body_is_400(dash):
    _, base = dash
    req = urllib.request.Request(
        base + "/api/jobs/", data=b"{not json", method="POST",
        headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=30)
    assert ei.value.code == 400
    assert "invalid JSON" in json.loads(ei.value.read())["error"]


def test_malformed_http_request_is_400(dash):
    """A parseable request line with a garbage Content-Length must produce
    a 400, not a hung connection or a traceback page."""
    _, base = dash
    host, port = base[len("http://"):].split(":")
    with socket.create_connection((host, int(port)), timeout=30) as s:
        s.sendall(b"GET /api/version HTTP/1.1\r\n"
                  b"Content-Length: banana\r\n\r\n")
        s.settimeout(30)
        data = s.recv(4096)
    assert data.startswith(b"HTTP/1.1 400"), data[:200]
