"""Serve production surface: multiplexing, request cancellation/timeouts,
declarative config deploy (VERDICT r3 missing #3; ref: serve/multiplex.py,
serve request cancellation, serve/schema.py + `serve deploy`)."""

import asyncio
import json
import os
import textwrap
import time

import pytest


@pytest.fixture(scope="module")
def serve_app():
    import ray_tpu
    import ray_tpu.serve as serve
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield serve
    serve.shutdown()


def test_multiplexed_lru_and_affinity(serve_app):
    serve = serve_app
    import ray_tpu

    @serve.deployment
    class MultiModel:
        def __init__(self):
            self.loads = []

        @serve.multiplexed(max_num_models_per_replica=2)
        async def get_model(self, model_id: str):
            self.loads.append(model_id)
            return {"id": model_id, "scale": len(model_id)}

        async def __call__(self, x):
            mid = serve.get_multiplexed_model_id()
            model = await self.get_model(mid)
            return {"model": model["id"], "out": x * model["scale"],
                    "loads": list(self.loads)}

    serve.run(MultiModel.bind(), name="mux", route_prefix="/mux")
    h = serve.get_deployment_handle("MultiModel", "mux")

    # same model twice: loaded once (LRU hit)
    r1 = h.options(multiplexed_model_id="aa").remote(1).result(timeout_s=60)
    r2 = h.options(multiplexed_model_id="aa").remote(2).result(timeout_s=60)
    assert r1["model"] == "aa" and r1["out"] == 2
    assert r2["out"] == 4
    assert r2["loads"].count("aa") == 1

    # third model evicts the LRU one; re-requesting it reloads
    h.options(multiplexed_model_id="bbb").remote(1).result(timeout_s=60)
    h.options(multiplexed_model_id="cccc").remote(1).result(timeout_s=60)
    r5 = h.options(multiplexed_model_id="aa").remote(5).result(timeout_s=60)
    assert r5["out"] == 10
    assert r5["loads"].count("aa") == 2  # evicted by cccc, reloaded
    serve.delete("mux")


def test_request_cancellation_frees_slot(serve_app):
    serve = serve_app
    import ray_tpu

    @serve.deployment(max_ongoing_requests=1)
    class Slow:
        def __init__(self):
            self.cancelled = 0

        async def hang(self):
            try:
                await asyncio.sleep(300)
            except asyncio.CancelledError:
                self.cancelled += 1
                raise
            return "never"

        async def quick(self):
            return {"cancelled": self.cancelled}

    serve.run(Slow.bind(), name="slow", route_prefix="/slow")
    h = serve.get_deployment_handle("Slow", "slow")

    resp = h.options(method_name="hang").remote()
    time.sleep(1.0)  # the hang is in flight on the replica
    resp.cancel()
    with pytest.raises(Exception) as ei:
        resp.result(timeout_s=60)
    assert "ancel" in type(ei.value).__name__ or "ancel" in str(ei.value)
    # the replica slot freed: a quick call completes and saw the cancel
    out = h.options(method_name="quick").remote().result(timeout_s=60)
    assert out == {"cancelled": 1}
    serve.delete("slow")


def test_handle_timeout_cancels(serve_app):
    serve = serve_app

    @serve.deployment
    class Sleepy:
        async def __call__(self):
            await asyncio.sleep(300)

    serve.run(Sleepy.bind(), name="sleepy", route_prefix="/sleepy")
    h = serve.get_deployment_handle("Sleepy", "sleepy")
    t0 = time.time()
    with pytest.raises(TimeoutError):
        h.options(timeout_s=2).remote().result()
    assert time.time() - t0 < 30
    serve.delete("sleepy")


def test_config_deploy_roundtrip(serve_app, tmp_path):
    """YAML config → deploy_config → live app with overrides applied; and
    build_app_config emits a config that re-deploys the same app."""
    serve = serve_app
    import sys
    import yaml

    # a real importable module for import_path resolution
    mod_dir = tmp_path / "cfgmod"
    mod_dir.mkdir()
    (mod_dir / "myapp.py").write_text(textwrap.dedent("""
        import ray_tpu.serve as serve

        @serve.deployment
        class Echo:
            def __init__(self, prefix="x"):
                self.prefix = prefix
                self.cfg = {}
            def reconfigure(self, user_config):
                self.cfg = dict(user_config)
            def __call__(self, request):
                return {"prefix": self.prefix, "cfg": self.cfg}

        app = Echo.bind("hello")

        def builder(prefix="built"):
            return Echo.bind(prefix)
    """))
    sys.path.insert(0, str(mod_dir))
    try:
        cfg = {
            "applications": [
                {"name": "a1", "import_path": "myapp:app",
                 "route_prefix": "/a1",
                 "deployments": [{"name": "Echo", "num_replicas": 2,
                                  "user_config": {"beam": 4}}]},
                {"name": "a2", "import_path": "myapp:builder",
                 "args": {"prefix": "fromargs"}},
            ]}
        cfg_path = tmp_path / "serve.yaml"
        cfg_path.write_text(yaml.safe_dump(cfg))

        handles = serve.deploy_config(str(cfg_path), start_http=False)
        assert set(handles) == {"a1", "a2"}
        out1 = handles["a1"].remote(None).result(timeout_s=60)
        assert out1 == {"prefix": "hello", "cfg": {"beam": 4}}
        out2 = handles["a2"].remote(None).result(timeout_s=60)
        assert out2["prefix"] == "fromargs"
        st = serve.status()
        assert st["a1:Echo"]["replicas"] == 2, st

        # build emits a config that round-trips
        import myapp
        built = serve.build_app_config(myapp.app, "myapp:app", name="a3",
                                       route_prefix="/a3")
        handles3 = serve.deploy_config(built, start_http=False)
        assert handles3["a3"].remote(None).result(timeout_s=60)["prefix"] == "hello"
        for name in ("a1", "a2", "a3"):
            serve.delete(name)
    finally:
        sys.path.remove(str(mod_dir))


def test_grpc_ingress_roundtrip(serve_app):
    """gRPC ingress: unary + streaming + healthz over a real channel
    (VERDICT r3 missing #3; ref: serve gRPC proxy)."""
    import pickle

    import grpc
    serve = serve_app

    @serve.deployment
    class Calc:
        def __call__(self, x):
            return {"doubled": x * 2}

        def gen(self, n):
            for i in range(n):
                yield {"i": i}

    serve.run(Calc.bind(), name="calc", route_prefix="/calc")
    serve.start(http_options={"port": 0}, grpc_options={"port": 0})
    port = serve.grpc_port()
    assert port

    ch = grpc.insecure_channel(f"127.0.0.1:{port}")
    predict = ch.unary_unary("/ray_tpu.serve.Ingress/Predict")
    out = pickle.loads(predict(
        pickle.dumps({"app": "calc", "args": (21,)}), timeout=60))
    assert out == {"doubled": 42}

    healthz = ch.unary_unary("/ray_tpu.serve.Ingress/Healthz")
    assert healthz(b"", timeout=30) == b"ok"

    apps = ch.unary_unary("/ray_tpu.serve.Ingress/ListApplications")
    assert "calc" in pickle.loads(apps(b"", timeout=30))

    stream = ch.unary_stream("/ray_tpu.serve.Ingress/PredictStream")
    items = [pickle.loads(b) for b in stream(
        pickle.dumps({"app": "calc", "method": "gen", "args": (3,)}),
        timeout=60)]
    assert items == [{"i": 0}, {"i": 1}, {"i": 2}]

    # errors surface as INTERNAL with the replica traceback
    with pytest.raises(grpc.RpcError) as ei:
        predict(pickle.dumps({"app": "nope", "args": ()}), timeout=30)
    assert ei.value.code() == grpc.StatusCode.INTERNAL
    ch.close()
    serve.delete("calc")


def test_multiplex_eviction_spares_in_use_models():
    """LRU eviction must not unload a model a live request still holds
    (r4 ADVICE): leases bound to the calling task defer eviction until the
    request drains, temporarily overflowing the cap instead."""
    import asyncio

    from ray_tpu.serve.multiplex import _ModelCache

    unloaded = []

    class Model:
        def __init__(self, mid):
            self.mid = mid

        def unload(self):
            unloaded.append(self.mid)

    async def scenario():
        cache = _ModelCache(lambda owner, mid: Model(mid), max_models=1)
        release_a = asyncio.Event()
        a_model = {}

        async def long_request_on_a():
            a_model["m"] = await cache.get_model(None, "A")
            await release_a.wait()
            return a_model["m"].mid

        t1 = asyncio.ensure_future(long_request_on_a())
        await asyncio.sleep(0.05)
        assert "A" in cache.models

        # B loads while A is leased: A must NOT be unloaded under t1
        release_b = asyncio.Event()

        async def long_request_on_b():
            m = await cache.get_model(None, "B")
            await release_b.wait()
            return m.mid

        t2 = asyncio.ensure_future(long_request_on_b())
        await asyncio.sleep(0.05)
        assert unloaded == [], unloaded          # A survived (leased)
        assert len(cache.models) == 2            # temporary overflow

        release_a.set()
        assert await t1 == "A"
        await asyncio.sleep(0.05)  # lease-drain eviction task runs
        assert unloaded == ["A"]                 # A drained first → evicted
        assert list(cache.models) == ["B"]
        release_b.set()
        assert await t2 == "B"

    asyncio.run(scenario())
