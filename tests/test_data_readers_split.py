"""Binary readers + streaming_split (VERDICT r3 missing #4; ref:
read_api.py:1147 read_images, :1974 read_tfrecords, dataset.py:2043
streaming_split)."""

import os
import tarfile

import numpy as np
import pytest


def test_read_images_roundtrip(tmp_path, ray_session):
    from PIL import Image
    import ray_tpu.data as rdata

    rng = np.random.default_rng(0)
    for i in range(4):
        arr = rng.integers(0, 255, (13 + i, 17, 3), np.uint8)
        Image.fromarray(arr).save(tmp_path / f"im_{i}.png")

    ds = rdata.read_images(str(tmp_path), size=(16, 12), include_paths=True)
    rows = ds.take_all()
    assert len(rows) == 4
    for r in rows:
        assert r["image"].shape == (12, 16, 3)
        assert r["image"].dtype == np.uint8
        assert os.path.basename(r["path"]).startswith("im_")

    # uniform originals round-trip exactly (no resize)
    exact = np.arange(4 * 5 * 3, dtype=np.uint8).reshape(4, 5, 3)
    Image.fromarray(exact).save(tmp_path / "exact.png")
    got = rdata.read_images(str(tmp_path / "exact.png")).take_all()[0]["image"]
    np.testing.assert_array_equal(got, exact)


def test_read_tfrecords_roundtrip(tmp_path, ray_session):
    import ray_tpu.data as rdata

    rows = [
        {"name": b"alpha", "score": 1.5, "ids": [1, 2, 3]},
        {"name": b"beta", "score": -2.25, "ids": [40]},
        {"name": b"gamma", "score": 0.0, "ids": [-7, 1 << 40]},
    ]
    path = str(tmp_path / "data.tfrecord")
    rdata.write_tfrecords(rows, path)

    got = rdata.read_tfrecords(path).take_all()
    assert len(got) == 3
    for want, have in zip(rows, got):
        assert have["name"] == want["name"]
        assert abs(have["score"] - want["score"]) < 1e-6
        # mixed arities stay lists for the whole column
        assert list(have["ids"]) == list(want["ids"]), (have["ids"], want)


def test_read_webdataset(tmp_path, ray_session):
    import ray_tpu.data as rdata

    shard = tmp_path / "shard-000.tar"
    with tarfile.open(shard, "w") as tar:
        for i in range(3):
            for ext, payload in (("jpg", b"IMG%d" % i), ("cls", b"%d" % i)):
                p = tmp_path / f"sample{i}.{ext}"
                p.write_bytes(payload)
                tar.add(p, arcname=f"sample{i}.{ext}")

    rows = rdata.read_webdataset(str(shard)).take_all()
    assert [r["__key__"] for r in rows] == ["sample0", "sample1", "sample2"]
    assert rows[1]["jpg"] == b"IMG1" and rows[1]["cls"] == b"1"


def test_streaming_split_disjoint_and_complete(ray_session):
    import ray_tpu.data as rdata

    ds = rdata.range(4000, override_num_blocks=16).map_batches(
        lambda b: {"id": b["id"], "sq": b["id"] ** 2})
    its = ds.streaming_split(2)
    a = [r["id"] for r in its[0].iter_rows()]
    b = [r["id"] for r in its[1].iter_rows()]
    assert not (set(a) & set(b))               # disjoint
    assert sorted(a + b) == list(range(4000))  # complete


def test_streaming_split_two_train_workers_disjoint(ray_session):
    """The dp-ingest pattern: each train worker consumes its own iterator
    from ONE shared stream and sees a disjoint half of the data."""
    ray = ray_session
    import ray_tpu.data as rdata

    ds = rdata.range(2000, override_num_blocks=8)
    its = ds.streaming_split(2, equal=True)

    @ray.remote
    def train_worker(it, rank):
        seen = []
        for batch in it.iter_batches(batch_size=128):
            seen.extend(int(x) for x in batch["id"])
        return rank, seen

    out = ray.get([train_worker.remote(its[i], i) for i in range(2)],
                  timeout=180)
    seen = {rank: ids for rank, ids in out}
    assert not (set(seen[0]) & set(seen[1]))
    assert sorted(seen[0] + seen[1]) == list(range(2000))
    # equal=True: block-granular balance (8 blocks -> 4/4)
    assert len(seen[0]) == len(seen[1]) == 1000


def test_tfrecord_crc32c_check_value():
    """The TFRecord masks are real CRC-32C (Castagnoli): TF's RecordReader
    verifies them and rejected our zlib.crc32 files as corrupt (r4 ADVICE).
    0xE3069283 is the standard crc32c check value for b'123456789'."""
    from ray_tpu.data.readers import _crc32c, _masked_crc
    assert _crc32c(b"123456789") == 0xE3069283
    assert _crc32c(b"") == 0
    # mask formula from tensorflow/core/lib/hash/crc32c.h
    crc = _crc32c(b"hello")
    assert _masked_crc(b"hello") == (((crc >> 15) | (crc << 17))
                                     + 0xA282EAD8) & 0xFFFFFFFF


def test_tfrecord_reader_rejects_corrupt_crc(tmp_path, ray_session):
    import pytest as _pytest

    from ray_tpu import data as rdata
    path = str(tmp_path / "bad.tfrecord")
    rdata.write_tfrecords([{"x": 1}], path)
    blob = bytearray(open(path, "rb").read())
    blob[-13] ^= 0xFF  # flip a payload byte; trailing data-crc now lies
    open(path, "wb").write(bytes(blob))
    with _pytest.raises(Exception, match="crc mismatch"):
        rdata.read_tfrecords(path).take_all()


def test_tfrecord_legacy_zlib_files_still_read(tmp_path, ray_session):
    """Files written by the pre-r5 writer (zlib.crc32 masks) load with a
    warning instead of stranding user data behind the new verification."""
    import struct
    import warnings

    from ray_tpu import data as rdata
    from ray_tpu.data.readers import _encode_example, _masked_crc_legacy
    path = str(tmp_path / "legacy.tfrecord")
    with open(path, "wb") as f:  # replica of the old writer
        data = _encode_example({"x": 7})
        f.write(struct.pack("<Q", len(data)))
        f.write(struct.pack("<I", _masked_crc_legacy(struct.pack("<Q", len(data)))))
        f.write(data)
        f.write(struct.pack("<I", _masked_crc_legacy(data)))
    rows = rdata.read_tfrecords(path).take_all()  # executes in a worker
    assert rows[0]["x"] == 7
    # warning is emitted where the frames are parsed (worker above, local
    # here) — assert it on a local parse
    from ray_tpu.data.readers import _iter_tfrecord_frames
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert len(list(_iter_tfrecord_frames(path))) == 1
    assert any("legacy zlib-crc32" in str(x.message) for x in w)
