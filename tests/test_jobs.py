"""Job submission + dashboard HTTP surface (VERDICT r2 #4; ref:
python/ray/job_submission/, python/ray/dashboard/modules/job/)."""

import json
import re
import sys
import urllib.request

import pytest


@pytest.fixture()
def job_client(ray_session):
    from ray_tpu.job_submission import JobSubmissionClient
    return JobSubmissionClient()


def test_submit_and_succeed(job_client):
    jid = job_client.submit_job(
        entrypoint=f"{sys.executable} -c \"print('hello from job')\"")
    status = job_client.wait_until_finished(jid, timeout_s=120)
    assert status.value == "SUCCEEDED"
    assert "hello from job" in job_client.get_job_logs(jid)


def test_failing_job_reports_failed(job_client):
    jid = job_client.submit_job(
        entrypoint=f"{sys.executable} -c \"import sys; sys.exit(3)\"")
    status = job_client.wait_until_finished(jid, timeout_s=120)
    assert status.value == "FAILED"
    assert job_client.get_job_info(jid).exit_code == 3


def test_stop_long_job(job_client):
    jid = job_client.submit_job(
        entrypoint=f"{sys.executable} -c \"import time; time.sleep(600)\"")
    assert job_client.get_job_status(jid).value == "RUNNING"
    assert job_client.stop_job(jid)
    status = job_client.wait_until_finished(jid, timeout_s=60)
    assert status.value == "STOPPED"


def test_job_attaches_to_session_and_runs_tasks(job_client, ray_session):
    """The submitted driver joins THIS session (init(address='auto')) and its
    tasks run on the session's workers."""
    ray = ray_session
    script = (
        "import ray_tpu as ray; ray.init(address='auto');"
        "f = ray.remote(lambda x: x * 3);"
        "print('result:', ray.get(f.remote(14), timeout=120));"
        "ray.shutdown()"
    )
    jid = job_client.submit_job(entrypoint=f"{sys.executable} -c \"{script}\"")
    status = job_client.wait_until_finished(jid, timeout_s=180)
    logs = job_client.get_job_logs(jid)
    assert status.value == "SUCCEEDED", logs
    assert "result: 42" in logs


def test_tail_streams_logs(job_client):
    import base64
    script = ('import time\n'
              'for i in range(5):\n'
              '    print("tick", i, flush=True)\n'
              '    time.sleep(0.1)\n')
    b64 = base64.b64encode(script.encode()).decode()
    jid = job_client.submit_job(
        entrypoint=(f"{sys.executable} -u -c "
                    f"\"import base64; exec(base64.b64decode('{b64}'))\""))
    out = "".join(job_client.tail_job_logs(jid))
    assert all(f"tick {i}" in out for i in range(5))


def test_dashboard_http_surface(ray_session):
    from ray_tpu.dashboard import start_dashboard
    _actor, port = start_dashboard(port=0)
    base = f"http://127.0.0.1:{port}"

    def get(path):
        with urllib.request.urlopen(base + path, timeout=30) as r:
            return json.loads(r.read())

    assert "session" in get("/api/version")
    assert get("/api/nodes")[0]["alive"]
    status = get("/api/cluster_status")
    assert "CPU" in status["total_resources"]
    assert isinstance(get("/api/actors"), list)

    # job lifecycle over HTTP
    from ray_tpu.job_submission import JobSubmissionClient
    http_client = JobSubmissionClient(base)
    jid = http_client.submit_job(
        entrypoint=f"{sys.executable} -c \"print('via http')\"")
    status = http_client.wait_until_finished(jid, timeout_s=120)
    assert status.value == "SUCCEEDED"
    assert "via http" in http_client.get_job_logs(jid)
    assert any(j.submission_id == jid for j in http_client.list_jobs())


def test_cli_job_submit_roundtrip(tmp_path):
    """`python -m ray_tpu job submit` end-to-end in a fresh session."""
    import os
    import subprocess
    env = {**os.environ, "RAY_TPU_NUM_CHIPS": "0", "JAX_PLATFORMS": "cpu"}
    env.pop("RAY_TPU_ADDRESS", None)  # force a local ephemeral session
    r = subprocess.run(
        [sys.executable, "-m", "ray_tpu", "job", "submit", "--",
         sys.executable, "-c", "print(6*7)"],
        capture_output=True, text=True, timeout=300, env=env,
        cwd="/root/repo")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "42" in r.stdout
    assert "SUCCEEDED" in r.stdout


def test_prometheus_rendering_unit():
    from ray_tpu.dashboard import _prometheus_text
    from ray_tpu.util.metrics import Counter, Gauge, Histogram, clear_registry, collect

    clear_registry()
    c = Counter("dash_test_total", "requests", tag_keys=("route",))
    c.inc(3, tags={"route": "/x"})
    Gauge("dash_test_gauge").set(1.5)
    h = Histogram("dash_test_latency", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(5.0)
    text = _prometheus_text(collect())
    clear_registry()
    assert 'dash_test_total{route="/x"} 3.0' in text
    assert "# TYPE dash_test_gauge gauge" in text
    assert 'dash_test_latency_bucket{le="+Inf"} 2' in text
    assert "dash_test_latency_count 2" in text


def test_dashboard_metrics_and_autoscaler_endpoints(ray_session):
    """Scrape surface: cluster gauges from controller state (per-process
    registries cannot cross the actor boundary; the reference similarly
    aggregates through its metrics agent)."""
    from ray_tpu.dashboard import start_dashboard

    _actor, port = start_dashboard(port=0)
    base = f"http://127.0.0.1:{port}"

    with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
        assert r.headers["Content-Type"].startswith("text/plain")
        text = r.read().decode()
    assert re.search(r'ray_tpu_resource_total\{resource="CPU"\} ', text)
    assert "# TYPE ray_tpu_workers gauge" in text
    assert "ray_tpu_object_store_capacity_bytes " in text

    # /api/metrics serves the same Prometheus text exposition as /metrics
    # (every util.metrics series, controller registry merged in)
    with urllib.request.urlopen(base + "/api/metrics", timeout=30) as r:
        assert r.headers["Content-Type"].startswith("text/plain")
        api_text = r.read().decode()
    assert "# TYPE ray_tpu_workers gauge" in api_text

    with urllib.request.urlopen(base + "/api/autoscaler", timeout=30) as r:
        auto = json.loads(r.read())
    assert "pool_workers" in auto and "max_workers" in auto

    with urllib.request.urlopen(base + "/api/placement_groups", timeout=30) as r:
        assert isinstance(json.loads(r.read()), list)


def test_dashboard_serves_web_ui(ray_session):
    from ray_tpu.dashboard import start_dashboard
    _actor, port = start_dashboard(port=0)
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/", timeout=30) as r:
        assert r.headers["Content-Type"].startswith("text/html")
        html = r.read().decode()
    assert "ray_tpu dashboard" in html and "/api/cluster_status" in html
