"""Radix prefix index over KV pages (ISSUE 19 tentpole, half 1).

  * two prompts share tree nodes up to their exact divergence point (CoW:
    common spine borrowed read-only, diverging suffix gets private pages)
  * eviction is leaf-first and never frees a page a live slot borrows
  * demote→restore round-trips the page payload bit-identically, and a
    restored chain counts as cached tokens (prefill skips it)
  * KVPageStash (the serve-side shm→disk rung) round-trips k/v pages
    bit-identically through both tiers
  * RAY_TPU_RADIX=0 falls back to the flat PageManager
"""

import numpy as np
import pytest

from ray_tpu.serve.radix_cache import (RadixPageManager, make_page_manager,
                                       radix_enabled)

PS = 4  # tokens per page


def _mgr(num_pages=16, slots=8, max_seq=16, **hooks):
    return RadixPageManager(num_pages, PS, slots, max_seq, True, **hooks)


def _prompt(*pages, tail=1):
    """Token ids for len(pages) full pages plus `tail` extra tokens."""
    toks = []
    for p in pages:
        toks.extend(range(p * 100, p * 100 + PS))
    toks.extend(range(9000, 9000 + tail))
    return toks


# ---------------------------------------------------------------- branching

def test_branch_prefixes_share_cow():
    """B borrows exactly A's common-prefix page; its diverging suffix gets
    fresh private pages (the branch point IS the copy-on-write point)."""
    m = _mgr()
    a = _prompt(1, 2)            # pages [1xx][2xx] + tail
    row, cached = m.allocate_prefix(0, a, len(a))
    assert cached == 0           # cold tree: everything prefills
    m.register_prefix(0, a)

    b = _prompt(1, 7)            # shares page [1xx], diverges at [7xx]
    row_b, cached_b = m.allocate_prefix(1, b, len(b))
    assert cached_b == PS        # one shared page of tokens
    assert m.tables[1][0] == m.tables[0][0]       # same physical page
    assert m.tables[1][1] != m.tables[0][1]       # private past the branch
    assert m.shared_page_count(1) == 1

    # exact full-prefix re-hit: all FULL pages cached, tail still prefills
    row_c, cached_c = m.allocate_prefix(2, a, len(a))
    assert cached_c == 2 * PS
    assert m.tables[2][:2] == m.tables[0][:2]
    assert m.prefix_hit_tokens == PS + 2 * PS


def test_register_then_free_keeps_pages_published():
    """free() decrefs borrowed pages back to the LRU, not the free list —
    the tree still resolves the prefix for the next request."""
    m = _mgr()
    a = _prompt(1, 2)
    m.allocate_prefix(0, a, len(a))
    m.register_prefix(0, a)
    m.free(0)
    _, cached = m.allocate_prefix(1, a, len(a))
    assert cached == 2 * PS


# ----------------------------------------------------------------- eviction

def test_eviction_spares_borrowed_pages():
    """Pool pressure evicts only unpinned published pages; a page a live
    slot borrows (and the whole chain under it) survives."""
    m = _mgr(num_pages=8)  # page 0 reserved -> 7 usable
    a = _prompt(1, 2)
    m.allocate_prefix(0, a, len(a))  # 3 pages
    m.register_prefix(0, a)

    b = _prompt(1, 2)                # borrows both published pages, 1 fresh
    _, cached = m.allocate_prefix(1, b, len(b))
    assert cached == 2 * PS
    m.free(0)  # slot 0's refs drop; pages stay pinned by slot 1's borrow

    c = _prompt(8, 9, tail=2 * PS)   # 4 pages: every remaining free page
    m.allocate_prefix(2, c, 4 * PS)
    # slot 1's borrowed chain is untouched and still resolves
    assert m.tables[1][0] is not None
    m.free(2)
    m.free(1)
    _, cached2 = m.allocate_prefix(3, a, len(a))
    assert cached2 == 2 * PS  # chain survived the pressure


def test_eviction_is_leaf_first():
    """The deepest refcount-0 node goes first; an interior page is never
    freed while a resident descendant still needs it for prefix walks."""
    m = _mgr(num_pages=8)
    a = _prompt(1, 2, 3)
    m.allocate_prefix(0, a, len(a))
    m.register_prefix(0, a)
    root_page, mid_page, leaf_page = m.tables[0][:3]
    m.free(0)

    assert m._evict_to_free(len(m.free_pages) + 1)
    assert leaf_page in m.free_pages          # leaf evicted...
    assert root_page in m._node_of and mid_page in m._node_of  # ...spine not

    # without a demotion plane the evicted leaf is a hole: the walk stops
    # at the last resident page
    _, cached = m.allocate_prefix(1, a, len(a))
    assert cached == 2 * PS


# ---------------------------------------------------------- demote / restore

def test_demote_restore_bit_identical():
    """An evicted page's payload is extracted at demotion and restored
    bit-identically into a fresh pool page on the next matching request —
    cached tokens include the restored pages."""
    device = {}          # fake device cache: page id -> payload
    stash = {}           # fake store: handle -> payload copy
    seq = iter(range(10 ** 6))

    def demote(pid, node):
        h = next(seq)
        stash[h] = device.pop(pid).copy()
        return h

    def restore(h, pid):
        device[pid] = stash[h].copy()
        return True

    def drop(h):
        stash.pop(h, None)

    m = _mgr(num_pages=8, demote_cb=demote, restore_cb=restore, drop_cb=drop)
    a = _prompt(1, 2)
    m.allocate_prefix(0, a, len(a))
    for pid in m.tables[0]:
        device[pid] = np.random.default_rng(pid).normal(size=(PS, 8))
    payloads = [device[pid].copy() for pid in m.tables[0][:2]]
    m.register_prefix(0, a)
    m.free(0)

    # drain the pool: 7 pages needed -> every published page demotes
    big = _prompt(8, 9, 10, tail=4 * PS)
    m.allocate_prefix(1, big, 7 * PS)
    assert m.demoted_pages >= 2
    m.free(1)

    _, cached = m.allocate_prefix(2, a, len(a))
    assert cached == 2 * PS               # restored pages ARE cached tokens
    assert m.restored_pages == 2
    for want, pid in zip(payloads, m.tables[2][:2]):
        np.testing.assert_array_equal(device[pid], want)


def test_restore_failure_truncates_match():
    """A failed restore degrades to a shorter cached prefix — the request
    prefills from the break instead of erroring."""
    def demote(pid, node):
        return "h"

    calls = []

    def restore(h, pid):
        calls.append(pid)
        return False

    m = _mgr(num_pages=8, demote_cb=demote, restore_cb=restore)
    a = _prompt(1, 2)
    m.allocate_prefix(0, a, len(a))
    m.register_prefix(0, a)
    m.free(0)
    big = _prompt(8, 9, 10, tail=4 * PS)
    m.allocate_prefix(1, big, 7 * PS)
    m.free(1)

    _, cached = m.allocate_prefix(2, a, len(a))
    assert calls and cached == 0          # restore refused -> full prefill
    m.register_prefix(2, a)               # fresh prefill re-publishes
    m.free(2)
    _, cached2 = m.allocate_prefix(3, a, len(a))
    assert cached2 == 2 * PS


# -------------------------------------------------------------- KVPageStash

def test_kv_page_stash_roundtrip_two_tiers(monkeypatch):
    """put → (budget pressure: shm → disk) → get promotes and round-trips
    bit-identically; tier gauges track both rungs."""
    monkeypatch.delenv("RAY_TPU_ARENA", raising=False)
    from ray_tpu.serve.kv_transfer import KVPageStash

    one_page = 2 * 2 * 3 * PS * 8 * 4    # k+v, [L=2,Kh=3,ps,D=8] float32
    stash = KVPageStash(budget_bytes=one_page + 16)  # fits ONE page in shm
    try:
        rng = np.random.default_rng(0)
        k1 = rng.normal(size=(2, 3, PS, 8)).astype(np.float32)
        v1 = rng.normal(size=(2, 3, PS, 8)).astype(np.float32)
        h1 = stash.put(k1, v1)
        k2, v2 = k1 * 2, v1 * 2
        h2 = stash.put(k2, v2)           # budget: h1 spills to disk
        ts = stash.tier_stats()
        assert ts["disk_objects"] == 1 and ts["shm_objects"] == 1, ts

        gk, gv = stash.get(h1)           # disk -> shm promotion
        np.testing.assert_array_equal(gk, k1)
        np.testing.assert_array_equal(gv, v1)
        gk2, gv2 = stash.get(h2)
        np.testing.assert_array_equal(gk2, k2)
        np.testing.assert_array_equal(gv2, v2)
        stash.drop(h1)
        stash.drop(h2)
    finally:
        stash.close()


# ------------------------------------------------------------- escape hatch

def test_radix_escape_hatch(monkeypatch):
    from ray_tpu.ops.paged_attention import PageManager

    monkeypatch.setenv("RAY_TPU_RADIX", "0")
    assert not radix_enabled()
    m = make_page_manager(16, PS, 8, 16)
    assert type(m) is PageManager
    monkeypatch.setenv("RAY_TPU_RADIX", "1")
    m2 = make_page_manager(16, PS, 8, 16)
    assert isinstance(m2, RadixPageManager)
    # prefix_cache=False always means the flat manager
    m3 = make_page_manager(16, PS, 8, 16, prefix_cache=False)
    assert type(m3) is PageManager
