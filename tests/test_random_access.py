"""RandomAccessDataset (ref: python/ray/data/random_access_dataset.py):
sorted-block routing, worker-side binary search, batched multiget."""

import numpy as np
import pytest

from ray_tpu import data as rd


@pytest.fixture(scope="module")
def rad(ray_session):
    rng = np.random.default_rng(0)
    keys = rng.permutation(60)
    ds = rd.from_items([{"k": int(k), "v": int(k) * 10} for k in keys])
    return ds.repartition(5).to_random_access_dataset("k", num_workers=2)


def test_get_async_hit_and_miss(rad, ray_session):
    import ray_tpu
    assert ray_tpu.get(rad.get_async(17)) == {"k": 17, "v": 170}
    assert ray_tpu.get(rad.get_async(0)) == {"k": 0, "v": 0}
    assert ray_tpu.get(rad.get_async(59)) == {"k": 59, "v": 590}
    assert ray_tpu.get(rad.get_async(-5)) is None    # below lower bound
    assert ray_tpu.get(rad.get_async(1000)) is None  # above upper bound


def test_multiget_order_and_misses(rad, ray_session):
    keys = [3, 999, 41, -1, 12, 12]
    out = rad.multiget(keys)
    assert out[0] == {"k": 3, "v": 30}
    assert out[1] is None
    assert out[2] == {"k": 41, "v": 410}
    assert out[3] is None
    assert out[4] == out[5] == {"k": 12, "v": 120}


def test_multiget_all_keys(rad, ray_session):
    out = rad.multiget(list(range(60)))
    assert all(out[i] == {"k": i, "v": i * 10} for i in range(60))


def test_stats_renders(rad, ray_session):
    s = rad.stats()
    assert "Num workers: 2" in s
