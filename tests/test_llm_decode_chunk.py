"""Fused multi-token decode parity (the r6 tentpole): a lax.scan chunk of
N on-device steps must be BIT-IDENTICAL to N per-step ticks — tokens,
logprobs, stream-queue contents — for both the row KVCache and the
PagedKVCache, including EOS hit mid-chunk, max_tokens hit mid-chunk, and
a slot finishing while its batch neighbors continue. The chunk fn splits
the PRNG key once per step exactly like the host loop did, so parity is
structural, not approximate.

Servers are memoized per (chunk, paged) and reused across tests: greedy
decode never consumes the sample key, so outputs are state-independent,
and reuse keeps the jit-variant compile bill paid once (tier-1 runs
against a wall clock). Only the SAMPLED parity test builds fresh servers
— it is exactly the test where key state matters.
"""

import asyncio

import numpy as np
import pytest

PROMPTS = [[1, 2, 3, 4, 5], [7, 8, 9], [11, 12, 13, 14]]

_SERVERS = {}
_BASE = {}


def _server(chunk, paged=False, fresh=False):
    from ray_tpu.serve.llm import LLMConfig, LLMServer
    cfg = dict(preset="tiny", max_batch_slots=4, max_seq_len=128,
               decode_chunk=chunk, seed=0)
    if paged:
        cfg.update(paged=True, page_size=16)
    if fresh:
        return LLMServer(LLMConfig(**cfg))
    key = (chunk, paged)
    if key not in _SERVERS:
        _SERVERS[key] = LLMServer(LLMConfig(**cfg))
    return _SERVERS[key]


def _gen(srv, prompts, **kw):
    """Concurrent generates (admission order == list order)."""
    async def go():
        return await asyncio.gather(*[srv.generate(list(p), **kw)
                                      for p in prompts])
    return asyncio.run(go())


def _base(paged):
    """Per-step (chunk=1) greedy reference: tokens + logprobs."""
    if paged not in _BASE:
        _BASE[paged] = _gen(_server(1, paged), PROMPTS, max_tokens=12,
                            logprobs=True)
    return _BASE[paged]


@pytest.mark.parametrize("paged", [False, True], ids=["dense", "paged"])
@pytest.mark.parametrize("chunk", [4, 8])
def test_greedy_parity(chunk, paged):
    got = _gen(_server(chunk, paged), PROMPTS, max_tokens=12,
               logprobs=True)
    for a, b in zip(_base(paged), got):
        assert a["tokens"] == b["tokens"]
        assert a["logprobs"] == b["logprobs"]  # bit-identical, not approx


def test_sampled_parity_dense():
    """Same seed → same key-split stream → identical SAMPLED tokens,
    regardless of how the steps are partitioned into chunks. Fresh servers:
    this is the one test where consumed key state would skew the compare."""
    kw = dict(max_tokens=10, temperature=1.3, top_p=0.9)
    base = _gen(_server(1, fresh=True), PROMPTS, **kw)
    got = _gen(_server(8, fresh=True), PROMPTS, **kw)
    for a, b in zip(base, got):
        assert a["tokens"] == b["tokens"]


@pytest.mark.parametrize("paged", [False, True], ids=["dense", "paged"])
def test_eos_mid_chunk(paged):
    """Pick an EOS id the greedy stream emits at a non-chunk-boundary step;
    the chunked server must stop at exactly the same token."""
    ref = _base(paged)[0]["tokens"]
    eos = ref[5]  # inside the second chunk of 4, mid-chunk for 8 too
    stop = ref.index(eos)
    for chunk in (1, 4, 8):
        out = _gen(_server(chunk, paged), [PROMPTS[0]], max_tokens=12,
                   eos_id=eos, logprobs=True)[0]["tokens"]
        assert out == ref[:stop], (chunk, out)


@pytest.mark.parametrize("paged", [False, True], ids=["dense", "paged"])
def test_mixed_budgets_slot_finishes_while_others_run(paged):
    """Slots with max_tokens 3/8/13 share the batch: the short one stops
    mid-chunk (termination masked in-scan) while its neighbors keep
    decoding to their own budgets."""
    budgets = [3, 8, 13]

    def run(chunk):
        srv = _server(chunk, paged)
        async def go():
            return await asyncio.gather(*[
                srv.generate(list(p), max_tokens=mt, logprobs=True)
                for p, mt in zip(PROMPTS, budgets)])
        return asyncio.run(go())

    base = run(1)
    for a, mt in zip(base, budgets):
        assert len(a["tokens"]) == mt
    for chunk in (4, 8):
        got = run(chunk)
        for a, b in zip(base, got):
            assert a["tokens"] == b["tokens"]
            assert a["logprobs"] == b["logprobs"]


def test_stream_queue_parity():
    """generate_stream consumers see the same tokens in the same order —
    the chunked loop flushes each slot's queue per chunk, in token order.
    (Queue flushing is host-side and cache-agnostic; dense covers it.)"""
    def run(chunk):
        srv = _server(chunk)
        async def drain(p):
            return [t async for t in srv.generate_stream(list(p),
                                                         max_tokens=9)]
        async def go():
            return await asyncio.gather(*[drain(p) for p in PROMPTS])
        return asyncio.run(go())

    base = run(1)
    assert all(len(s) == 9 for s in base)
    assert run(8) == base


def test_decode_stats_record_amortization():
    """stats()['decode'] proves the sync amortization: steady-state chunks
    of 8 push tokens_per_sync well above 1, and the adaptive loop used
    chunk 1 only while the prefill queue was non-empty."""
    d = _server(8).stats()["decode"]
    assert d["host_syncs"] < d["tokens"]
    assert d["tokens_per_sync"] > 1.0
    assert d["host_syncs_per_token"] <= 0.5
    assert 8 in d["chunk_sizes"]          # steady-state ran full chunks
    assert 1 in d["chunk_sizes"]          # prefill-overlap ticks stayed at 1
    assert d["chunk_ms_avg"] >= 0.0


def test_seq_capacity_terminates_in_scan():
    """Unit probe of the jitted chunk: a slot whose cache row has only 2
    positions of room must stop after 2 steps even though its token budget
    allows 8 — the max-seq-len rung of the in-scan termination mask."""
    import jax.numpy as jnp

    srv = _server(8)
    B = srv.config.max_batch_slots
    mask = np.zeros((B,), bool)
    mask[0] = True
    cache, toks, n_valid, logps, key = srv._decode_chunk(
        srv.params, srv.cache, jnp.asarray(np.full((B,), 3, np.int32)),
        jnp.asarray(mask), srv._sample_key,
        jnp.zeros((B,), np.float32), jnp.ones((B,), np.float32),
        jnp.zeros((B,), np.int32), jnp.full((B,), -1, np.int32),
        jnp.full((B,), 8, np.int32),          # budget: 8 tokens allowed
        jnp.asarray(np.where(mask, 2, 0).astype(np.int32)),  # room: 2
        False, 8)
    srv.cache, srv._sample_key = cache, key   # old cache was donated
    n_valid = np.asarray(n_valid)
    assert int(n_valid[0]) == 2
    assert all(int(n_valid[i]) == 0 for i in range(1, B))


def test_reconfigure_decode_chunk():
    """The serve user_config hook retunes the chunk length in place (the
    jit cache just gains a variant) — and parity still holds. Runs LAST in
    this file: it mutates the shared chunk-1 server's config."""
    srv = _server(1)
    srv.reconfigure({"decode_chunk": 8})
    assert srv.config.decode_chunk == 8
    got = _gen(srv, PROMPTS, max_tokens=12, logprobs=True)
    for a, b in zip(_base(False), got):
        assert a["tokens"] == b["tokens"]
    with pytest.raises(ValueError):
        srv.reconfigure({"decode_chunk": 0})
