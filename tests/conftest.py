"""Test env: virtual 8-device CPU mesh, no TPU dependency (SURVEY.md §4)."""

import os

# Must be set before jax is imported anywhere in the test process. Force cpu
# even if the environment exports JAX_PLATFORMS=axon (the real TPU): the test
# suite is hardware-independent; TPU-only tests are marked `tpu` and opt back
# in via RAY_TPU_TEST_TPU=1.
if not os.environ.get("RAY_TPU_TEST_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    # this image auto-imports jax at interpreter startup (sitecustomize), so
    # the env var alone is read too late — update the live config before the
    # backend initializes
    import sys
    if "jax" in sys.modules:
        sys.modules["jax"].config.update("jax_platforms", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("RAY_TPU_NUM_CHIPS", "0")
# workers inherit this env, so jax-in-worker also sees the cpu mesh

import pytest


def pytest_report_header(config):
    """One visible line per native control-plane target: built or skipped
    (tools/build_native.sh is the standalone spelling of the same check).
    Tests exercise both paths — native when available, the pure-Python
    fallbacks always — so a toolchain-less box still runs green, it just
    says so here instead of silently testing half the matrix."""
    rows = []
    for name, modpath in [("shm_store", "ray_tpu._native.store"),
                          ("sched_queue", "ray_tpu._native.schedq"),
                          ("frame_codec", "ray_tpu._native.codec"),
                          ("obj_directory", "ray_tpu._native.objdir")]:
        try:
            mod = __import__(modpath, fromlist=["_compile"])
            mod._compile()
            rows.append(f"{name}=built")
        except Exception as e:  # noqa: BLE001 - the skip itself is the signal
            rows.append(f"{name}=SKIP({str(e)[:60].strip()})")
    return "native control plane: " + " ".join(rows)


@pytest.fixture(scope="session")
def ray_session():
    import ray_tpu
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield ray_tpu
    ray_tpu.shutdown()


@pytest.fixture(autouse=True)
def _reset_observability():
    """Isolate tracing state between tests: a test that flips RAY_TPU_TRACE*
    or fills the span ring must not leak into the next one. The metrics
    registry is intentionally NOT cleared here — session-scoped components
    (controller, dashboard) hold live Metric objects across tests and
    clear_registry() would orphan them; tests that need a clean registry
    call clear_registry() themselves."""
    yield
    from ray_tpu.util import tracing
    tracing.clear()
    tracing.refresh()
