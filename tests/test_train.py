"""Train layer tests (SURVEY.md §4: end-to-end tiny fits, checkpoint/resume,
failure recovery, keep-N policy)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu import train
from ray_tpu.train import (Checkpoint, CheckpointConfig, FailureConfig,
                           JaxTrainer, RunConfig, ScalingConfig)


def _linreg_loop(config):
    """Tiny linear-regression fit that reports loss and checkpoints params."""
    key = jax.random.PRNGKey(0)
    w = jnp.zeros((3,))
    x = jax.random.normal(key, (64, 3))
    y = x @ jnp.array([1.0, -2.0, 0.5])

    start = 0
    ckpt = train.get_checkpoint()
    if ckpt is not None:
        state = ckpt.to_state()
        w = jnp.asarray(state["w"])
        start = int(state["step"])

    @jax.jit
    def step(w):
        def loss_fn(w):
            return jnp.mean((x @ w - y) ** 2)
        loss, g = jax.value_and_grad(loss_fn)(w)
        return w - 0.1 * g, loss

    for i in range(start, config["steps"]):
        w, loss = step(w)
        if config.get("fail_at") is not None and i == config["fail_at"] \
                and not os.environ.get("_RT_FAILED_ONCE"):
            os.environ["_RT_FAILED_ONCE"] = "1"
            raise RuntimeError("injected failure")
        train.report(
            {"loss": float(loss), "step": i},
            checkpoint=Checkpoint.from_state(
                {"w": np.asarray(w), "step": i + 1}))


def test_fit_end_to_end(tmp_path):
    trainer = JaxTrainer(
        _linreg_loop,
        train_loop_config={"steps": 40},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="linreg", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["loss"] < 1e-2
    assert len(result.metrics_history) == 40
    assert result.checkpoint is not None
    state = result.checkpoint.to_state()
    assert state["step"] == 40
    np.testing.assert_allclose(
        np.asarray(state["w"]), [1.0, -2.0, 0.5], atol=0.05)


def test_failure_recovery_resumes_from_checkpoint(tmp_path):
    os.environ.pop("_RT_FAILED_ONCE", None)
    trainer = JaxTrainer(
        _linreg_loop,
        train_loop_config={"steps": 10, "fail_at": 5},
        run_config=RunConfig(
            name="failrec", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=1)),
    )
    result = trainer.fit()
    os.environ.pop("_RT_FAILED_ONCE", None)
    assert result.error is None
    # Ran 0..4 (failed at 5 before report), resumed from step-5 ckpt, 5..9.
    steps = [m["step"] for m in result.metrics_history]
    assert steps[-1] == 9
    assert result.checkpoint.to_state()["step"] == 10


def test_failure_exhausted_returns_error(tmp_path):
    def always_fail(config):
        raise ValueError("boom")

    trainer = JaxTrainer(
        always_fail,
        run_config=RunConfig(name="fail", storage_path=str(tmp_path),
                             failure_config=FailureConfig(max_failures=1)),
    )
    result = trainer.fit()
    assert isinstance(result.error, ValueError)


def test_keep_n_checkpoints(tmp_path):
    trainer = JaxTrainer(
        _linreg_loop,
        train_loop_config={"steps": 8},
        run_config=RunConfig(
            name="keepn", storage_path=str(tmp_path),
            checkpoint_config=CheckpointConfig(num_to_keep=3)),
    )
    result = trainer.fit()
    exp = result.path
    kept = [d for d in os.listdir(exp) if d.startswith("checkpoint_")]
    assert len(kept) == 3
    # Latest survives.
    assert result.checkpoint.to_state()["step"] == 8


def test_keep_best_by_score(tmp_path):
    def loop(config):
        for i, score in enumerate([1.0, 5.0, 2.0, 4.0]):
            train.report({"score": score},
                         checkpoint=Checkpoint.from_state({"i": i, "s": score}))

    trainer = JaxTrainer(
        loop,
        run_config=RunConfig(
            name="best", storage_path=str(tmp_path),
            checkpoint_config=CheckpointConfig(
                num_to_keep=2, checkpoint_score_attribute="score",
                checkpoint_score_order="max")),
    )
    result = trainer.fit()
    scores = sorted(c.to_state()["s"] for c, _ in result.best_checkpoints)
    assert scores == [4.0, 5.0]


def test_stop_criteria(tmp_path):
    def loop(config):
        for i in range(100):
            train.report({"acc": i / 10.0})

    trainer = JaxTrainer(
        loop,
        run_config=RunConfig(name="stop", storage_path=str(tmp_path),
                             stop={"acc": 0.5}),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["acc"] == 0.5
    assert len(result.metrics_history) == 6  # acc 0.0 .. 0.5


def test_session_context_and_datasets(tmp_path):
    seen = {}

    def loop(config):
        ctx = train.get_context()
        seen["world"] = (ctx.get_world_size(), ctx.get_world_rank())
        seen["data"] = list(train.get_dataset_shard("train"))
        train.report({"ok": 1})

    JaxTrainer(
        loop,
        datasets={"train": [1, 2, 3]},
        run_config=RunConfig(name="sess", storage_path=str(tmp_path)),
        # the loop mutates a driver closure — needs in-process execution
        use_worker_actor=False,
    ).fit()
    assert seen["world"] == (1, 0)
    assert seen["data"] == [1, 2, 3]


def test_report_outside_session_raises():
    with pytest.raises(RuntimeError):
        train.report({"x": 1})


def test_checkpoint_roundtrip_pytree(tmp_path):
    state = {"params": {"w": np.arange(6.0).reshape(2, 3)},
             "step": np.asarray(7)}
    ckpt = Checkpoint.from_state(state, path=str(tmp_path / "ck"))
    restored = ckpt.to_state()
    np.testing.assert_array_equal(restored["params"]["w"],
                                  state["params"]["w"])
    assert int(np.asarray(restored["step"])) == 7
    ckpt.set_metadata({"note": "hi"})
    assert Checkpoint.from_directory(ckpt.path).get_metadata()["note"] == "hi"


def test_iter_device_batches_overlap():
    batches = [{"x": np.full((4,), i, np.float32)} for i in range(5)]
    out = list(train.iter_device_batches(iter(batches), prefetch=2))
    assert len(out) == 5
    assert isinstance(out[0]["x"], jax.Array)
    np.testing.assert_array_equal(np.asarray(out[3]["x"]), batches[3]["x"])


def test_iter_device_batches_with_sharding():
    from ray_tpu.parallel import local_cpu_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = local_cpu_mesh(8, {"dp": 8})
    sh = NamedSharding(mesh, P("dp"))
    batches = [np.arange(16, dtype=np.float32) for _ in range(3)]
    out = list(train.iter_device_batches(iter(batches), sharding=sh))
    assert out[0].sharding == sh


def test_shard_datasets_respects_data_config():
    """DataConfig drives which datasets split across ranks (ref:
    train/_internal/data_config.py); others replicate."""
    from ray_tpu import data as rd
    from ray_tpu.train.config import DataConfig
    from ray_tpu.train.worker_group import _shard_datasets

    ds = {"train": rd.range(8), "val": rd.range(4)}
    # default: split ALL, EQUAL shards (unequal counts would deadlock
    # per-batch SPMD collectives)
    r0 = _shard_datasets(ds, None, world_size=2, world_rank=0)
    r1 = _shard_datasets(ds, None, world_size=2, world_rank=1)
    assert r0["train"].count() == r1["train"].count() == 4
    assert r0["val"].count() == r1["val"].count() == 2
    ids0 = {r["id"] for r in r0["train"].take_all()}
    ids1 = {r["id"] for r in r1["train"].take_all()}
    assert ids0.isdisjoint(ids1)
    # selective: only "train" splits, "val" replicates
    cfg = DataConfig(datasets_to_split=["train"])
    s0 = _shard_datasets(ds, cfg, world_size=2, world_rank=0)
    assert s0["val"].count() == 4
    assert s0["train"].count() < 8
    # single worker: untouched
    assert _shard_datasets(ds, None, 1, 0)["train"].count() == 8
    # strings / iterables replicate, never .split()
    mixed = _shard_datasets({"path": "gs://b/d", "train": rd.range(4)},
                            None, 2, 0)
    assert mixed["path"] == "gs://b/d"

    # driver-side presplit: one split, equal shards, replicated extras
    from ray_tpu.train.worker_group import presplit_datasets
    per_rank = presplit_datasets(
        {"train": rd.range(9), "note": "x"}, None, 2)
    assert len(per_rank) == 2
    assert per_rank[0]["train"].count() == per_rank[1]["train"].count() == 4
    assert per_rank[0]["note"] == per_rank[1]["note"] == "x"
