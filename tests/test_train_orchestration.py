"""Train worker orchestration (VERDICT r1 #2): the loop runs in a
restartable actor; a killed worker process resumes from the last on-disk
checkpoint via the actor restart path (not an in-process try/except), and
num_workers>1 without a jax.distributed world fails loudly."""

import os

import numpy as np
import pytest

from ray_tpu.train import (Checkpoint, FailureConfig, JaxTrainer, RunConfig,
                           ScalingConfig)


def _make_crashy_loop():
    """Counts iterations via checkpoints; hard-kills its own process once at
    iteration `die_at` (SIGKILL semantics — no Python except path can catch
    it, so recovery MUST come from actor restart + on-disk checkpoint).
    Built inside a function so cloudpickle serializes it by value — workers
    can't import pytest's top-level test module."""

    def _crashy_loop(config):
        import os
        from ray_tpu import train
        from ray_tpu.train import Checkpoint

        start = 1
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            start = ckpt.to_state()["it"] + 1
        flag = config["flag"]
        for it in range(start, config["steps"] + 1):
            if it == config["die_at"] and not os.path.exists(flag):
                with open(flag, "w") as f:
                    f.write("died")
                os._exit(1)  # simulates OOM-kill / segfault of the worker
            train.report({"it": it},
                         checkpoint=Checkpoint.from_state({"it": it}))

    return _crashy_loop


def test_actor_kill_mid_run_resumes_from_checkpoint(ray_session, tmp_path):
    _crashy_loop = _make_crashy_loop()
    flag = str(tmp_path / "died_once")
    trainer = JaxTrainer(
        _crashy_loop,
        train_loop_config={"steps": 6, "die_at": 4, "flag": flag},
        run_config=RunConfig(
            name="crashrec", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=2)),
        use_worker_actor=True,
    )
    result = trainer.fit()
    assert os.path.exists(flag), "loop never reached the crash point"
    assert result.error is None
    # iterations 1..3 before the crash, resumed at 4 (from ckpt it=3), ran to 6
    its = [m["it"] for m in result.metrics_history]
    assert its == [1, 2, 3, 4, 5, 6], its
    assert result.checkpoint.to_state()["it"] == 6


def test_actor_path_plain_fit(ray_session, tmp_path):
    def loop(config):
        from ray_tpu import train
        for i in range(3):
            train.report({"loss": 1.0 / (i + 1)})

    result = JaxTrainer(
        loop,
        run_config=RunConfig(name="plain", storage_path=str(tmp_path)),
        use_worker_actor=True,
    ).fit()
    assert result.error is None
    assert len(result.metrics_history) == 3
    assert result.metrics["loss"] == pytest.approx(1 / 3)


def test_num_workers_without_world_fails_loudly(tmp_path):
    trainer = JaxTrainer(
        lambda config: None,  # in-process path: no pickling involved
        scaling_config=ScalingConfig(num_workers=4),
        run_config=RunConfig(name="nw", storage_path=str(tmp_path)),
        use_worker_actor=False,
    )
    with pytest.raises(ValueError, match="num_workers=4"):
        trainer.fit()
