"""LoggingConfig (ref: python/ray/_private/ray_logging/logging_config.py):
driver + every spawned worker get the session's log encoding/level."""

import json
import logging

import pytest


def test_json_formatter_shape():
    from ray_tpu.logging_config import JsonFormatter
    rec = logging.LogRecord("my.logger", logging.WARNING, "f.py", 12,
                            "hello %s", ("world",), None)
    rec.job_id = "j-1"
    out = json.loads(JsonFormatter(("job_id",)).format(rec))
    assert out["levelname"] == "WARNING"
    assert out["name"] == "my.logger"
    assert out["message"] == "hello world"
    assert out["job_id"] == "j-1"


def test_env_round_trip(monkeypatch):
    from ray_tpu.logging_config import LoggingConfig
    cfg = LoggingConfig(encoding="JSON", log_level="DEBUG",
                        additional_log_standard_attrs=("job_id",))
    monkeypatch.setenv("RAY_TPU_LOGGING_CONFIG", cfg.to_env())
    back = LoggingConfig.from_env()
    assert back == cfg
    monkeypatch.setenv("RAY_TPU_LOGGING_CONFIG", "{corrupt")
    assert LoggingConfig.from_env() is None  # never kills a worker


def test_invalid_encoding_rejected():
    from ray_tpu.logging_config import LoggingConfig
    with pytest.raises(ValueError, match="encoding"):
        LoggingConfig(encoding="YAML")


def test_apply_is_idempotent():
    from ray_tpu.logging_config import LoggingConfig
    root = logging.getLogger()
    before = list(root.handlers)
    try:
        LoggingConfig(log_level="DEBUG").apply()
        LoggingConfig(log_level="INFO").apply()
        ours = [h for h in root.handlers
                if getattr(h, "_ray_tpu_logging", False)]
        assert len(ours) == 1
        assert ours[0].level == logging.INFO
    finally:
        root.handlers = before


def test_workers_inherit_logging_config(tmp_path):
    """Worker-side integration: a task reports its root logger state —
    level and formatter class must match the driver's config."""
    import subprocess
    import sys
    script = tmp_path / "drv.py"
    script.write_text("""
import logging
import ray_tpu

ray_tpu.init(num_cpus=1, logging_config=ray_tpu.LoggingConfig(
    encoding="JSON", log_level="DEBUG"))

@ray_tpu.remote
def probe():
    root = logging.getLogger()
    ours = [h for h in root.handlers
            if getattr(h, "_ray_tpu_logging", False)]
    return (root.getEffectiveLevel(),
            type(ours[0].formatter).__name__ if ours else None)

level, fmt = ray_tpu.get(probe.remote())
assert level == logging.DEBUG, level
assert fmt == "JsonFormatter", fmt
ray_tpu.shutdown()
print("LOGCFG-OK")
""")
    env = {"RAY_TPU_NUM_CHIPS": "0", "PYTHONPATH":
           __import__("os").path.dirname(__import__("os").path.dirname(
               __import__("os").path.abspath(__file__)))}
    import os as _os
    full = dict(_os.environ)
    full.update(env)
    out = subprocess.run([sys.executable, str(script)], env=full,
                         capture_output=True, text=True, timeout=120)
    assert "LOGCFG-OK" in out.stdout, out.stderr[-2000:]


def test_stale_config_not_inherited_by_next_session(tmp_path):
    """init(logging_config)->shutdown->init() must not leak the prior
    session's published config into the new session's workers (r5
    review: the env var survived shutdown)."""
    import os
    import subprocess
    import sys
    script = tmp_path / "drv2.py"
    script.write_text("""
import logging
import ray_tpu

ray_tpu.init(num_cpus=1, logging_config=ray_tpu.LoggingConfig(
    encoding="JSON", log_level="DEBUG"))
ray_tpu.shutdown()
ray_tpu.init(num_cpus=1)   # NO logging_config: nothing may leak

@ray_tpu.remote
def probe():
    root = logging.getLogger()
    return [h for h in root.handlers
            if getattr(h, "_ray_tpu_logging", False)] == []

assert ray_tpu.get(probe.remote()) is True
ray_tpu.shutdown()
print("NO-LEAK-OK")
""")
    full = dict(os.environ)
    full["RAY_TPU_NUM_CHIPS"] = "0"
    full["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    out = subprocess.run([sys.executable, str(script)], env=full,
                         capture_output=True, text=True, timeout=180)
    assert "NO-LEAK-OK" in out.stdout, out.stderr[-2000:]
