"""Parallel chunked transfer data plane (PR 7 tentpole, transfer layer).

parallel_fetch is driven against stub asyncio object-data servers speaking
the ranged wire form (`GET <oid> <offset> <length>`) and a REAL pershm
StoreClient — asserting zero-copy landing correctness, mid-stream death
redistribution across holders, total-failure abort, and the writable-buffer
store API. Batched get ordering/dedup runs an actual single-process
runtime in a subprocess.
"""

import asyncio
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from ray_tpu._private.object_store import StoreClient  # noqa: E402


def _store():
    # per-segment backend: no arena/native toolchain required
    os.environ.pop("RAY_TPU_ARENA", None)
    return StoreClient()


async def _stub_holder(blob, mode="ok"):
    """One fake ObjectDataServer. Modes: ok | half (send half the range,
    then hang up) | refuse (close right after the header)."""

    async def handler(reader, writer):
        try:
            await reader.readline()          # RTPU1 <token>
            parts = (await reader.readline()).decode().split()
            if len(parts) != 4 or parts[0] != "GET":
                return
            off, ln = int(parts[2]), int(parts[3])
            if mode == "refuse":
                return
            payload = blob[off:off + ln]
            if mode == "half":
                payload = payload[:max(len(payload) // 2, 1)]
            writer.write(f"OK {ln}\n".encode())
            writer.write(payload)
            await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            writer.close()

    server = await asyncio.start_server(handler, "127.0.0.1", 0)
    port = server.sockets[0].getsockname()[1]
    return server, f"127.0.0.1:{port}"


def _blob(n):
    return bytes(range(256)) * (n // 256)


def test_parallel_fetch_lands_bytes_intact(monkeypatch):
    monkeypatch.setenv("RAY_TPU_TRANSFER_STREAMS", "4")
    from ray_tpu._private.node_agent import parallel_fetch
    size = 8 << 20
    blob = _blob(size)
    store = _store()

    async def main():
        server, addr = await _stub_holder(blob)
        async with server:
            return await parallel_fetch([addr], "obj-intact", size, 7,
                                        ["nested-1"], store, timeout=30)

    r = asyncio.run(main())
    try:
        assert r == {"oid": "obj-intact", "enc": "direct", "size": size,
                     "meta_len": 7, "contained": ["nested-1"]}
        assert store.read_range("obj-intact", 0, size) == blob
        # spot-check an interior slice (each stream landed its own range)
        assert store.read_range("obj-intact", size // 2 - 3, 6) == \
            blob[size // 2 - 3:size // 2 + 3]
    finally:
        store.delete_segment("obj-intact")


def test_parallel_fetch_redistributes_dead_stream(monkeypatch):
    """Streams assigned to a holder that dies mid-range get their tails
    re-pulled from the surviving holder; the transfer still completes and
    the retry counter records the redistribution."""
    monkeypatch.setenv("RAY_TPU_TRANSFER_STREAMS", "4")
    from ray_tpu._private.node_agent import parallel_fetch
    from ray_tpu.util import metrics
    size = 8 << 20
    blob = _blob(size)
    store = _store()

    async def main():
        bad_server, bad = await _stub_holder(blob, mode="half")
        good_server, good = await _stub_holder(blob)
        async with bad_server, good_server:
            return await parallel_fetch([bad, good], "obj-redist", size, 0,
                                        [], store, timeout=30)

    before = metrics.transfer_counters()["retries"]
    r = asyncio.run(main())
    try:
        assert r is not None and r["enc"] == "direct"
        assert store.read_range("obj-redist", 0, size) == blob
        assert metrics.transfer_counters()["retries"] > before
    finally:
        store.delete_segment("obj-redist")


def test_parallel_fetch_sole_holder_transient_reset(monkeypatch):
    """With a single holder the tail retries against the same address —
    covers a transient connection reset rather than a dead node."""
    monkeypatch.setenv("RAY_TPU_TRANSFER_STREAMS", "2")
    from ray_tpu._private.node_agent import parallel_fetch
    size = 8 << 20
    blob = _blob(size)
    store = _store()
    flaky = {"n": 0}

    async def handler(reader, writer):
        try:
            await reader.readline()
            parts = (await reader.readline()).decode().split()
            off, ln = int(parts[2]), int(parts[3])
            payload = blob[off:off + ln]
            flaky["n"] += 1
            if flaky["n"] == 1:  # first connection dies halfway
                payload = payload[:ln // 2]
            writer.write(f"OK {ln}\n".encode())
            writer.write(payload)
            await writer.drain()
        finally:
            writer.close()

    async def main():
        server = await asyncio.start_server(handler, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        async with server:
            return await parallel_fetch([f"127.0.0.1:{port}"], "obj-flaky",
                                        size, 0, [], store, timeout=30)

    r = asyncio.run(main())
    try:
        assert r is not None
        assert store.read_range("obj-flaky", 0, size) == blob
    finally:
        store.delete_segment("obj-flaky")


def test_parallel_fetch_total_failure_aborts_segment(monkeypatch):
    """Every holder refusing → None (caller falls back to the staged
    uplink) and the preallocated segment is aborted, not leaked."""
    monkeypatch.setenv("RAY_TPU_TRANSFER_STREAMS", "4")
    from ray_tpu._private.node_agent import parallel_fetch
    size = 8 << 20
    store = _store()

    async def main():
        server, addr = await _stub_holder(b"", mode="refuse")
        async with server:
            return await parallel_fetch([addr], "obj-dead", size, 0, [],
                                        store, timeout=10)

    assert asyncio.run(main()) is None
    assert not store.exists("obj-dead")


def test_parallel_fetch_no_holders_is_none():
    from ray_tpu._private.node_agent import parallel_fetch
    store = _store()
    assert asyncio.run(parallel_fetch([], "obj-x", 1024, 0, [], store)) is None
    assert asyncio.run(
        parallel_fetch(["127.0.0.1:1"], "obj-x", 0, 0, [], store)) is None


def test_small_objects_use_one_stream(monkeypatch):
    """Below _PARALLEL_MIN a single range stream does the whole blob — no
    parallelism tax on small objects."""
    monkeypatch.setenv("RAY_TPU_TRANSFER_STREAMS", "8")
    from ray_tpu._private import node_agent
    from ray_tpu.util import metrics
    size = 1 << 20  # < _PARALLEL_MIN
    blob = _blob(size)
    store = _store()

    async def main():
        server, addr = await _stub_holder(blob)
        async with server:
            return await node_agent.parallel_fetch([addr], "obj-small", size,
                                                   0, [], store, timeout=30)

    before = metrics.transfer_counters()["streams"]
    r = asyncio.run(main())
    try:
        assert r is not None
        assert store.read_range("obj-small", 0, size) == blob
        assert metrics.transfer_counters()["streams"] == before + 1
    finally:
        store.delete_segment("obj-small")


def test_writable_buffer_seal_and_abort():
    store = _store()
    h = store.create_writable("obj-wb", 64)
    h.view[:64] = b"x" * 64
    h.seal()
    assert store.read_range("obj-wb", 0, 64) == b"x" * 64
    store.delete_segment("obj-wb")

    h2 = store.create_writable("obj-wb2", 64)
    h2.abort()
    assert not store.exists("obj-wb2")


def test_transfer_knobs(monkeypatch):
    from ray_tpu._private import node_agent as na
    monkeypatch.delenv("RAY_TPU_TRANSFER_STREAMS", raising=False)
    monkeypatch.delenv("RAY_TPU_TRANSFER_SYNC", raising=False)
    assert na.transfer_streams() == 4
    assert na.use_parallel_transfer()
    monkeypatch.setenv("RAY_TPU_TRANSFER_STREAMS", "1")
    assert not na.use_parallel_transfer()
    monkeypatch.setenv("RAY_TPU_TRANSFER_STREAMS", "6")
    assert na.transfer_streams() == 6
    assert na.use_parallel_transfer()
    monkeypatch.setenv("RAY_TPU_TRANSFER_SYNC", "1")
    assert not na.use_parallel_transfer()


def test_batched_get_ordering_and_dedup():
    """get(list) preserves caller order including duplicate refs, and the
    descriptor fetch dedups oids under the hood."""
    script = (
        "import os; os.environ.setdefault('RAY_TPU_NUM_CHIPS', '0')\n"
        "import numpy as np\n"
        "import ray_tpu\n"
        "ray_tpu.init(num_cpus=2)\n"
        "refs = [ray_tpu.put(i * 100) for i in range(8)]\n"
        "dup = [refs[3], refs[1], refs[3], refs[5], refs[1]]\n"
        "assert ray_tpu.get(dup) == [300, 100, 300, 500, 100]\n"
        "@ray_tpu.remote\n"
        "def make(i):\n"
        "    return np.full(2048, i)\n"
        "trefs = [make.remote(i) for i in range(16)]\n"
        "vals = ray_tpu.get(trefs + [trefs[0]], timeout=60)\n"
        "assert [int(v[0]) for v in vals] == list(range(16)) + [0]\n"
        "ray_tpu.shutdown()\n"
        "print('BATCHED_OK')\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", script], cwd=REPO, env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "BATCHED_OK" in out.stdout
