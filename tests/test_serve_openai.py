"""OpenAI-compatible serving surface e2e (reference: python/ray/llm/
_internal/serve/core/ingress/ingress.py): /v1/models, /v1/completions,
/v1/chat/completions (unary + SSE stream), /tokenize, /detokenize, and
OpenAI-shaped error bodies — all over real HTTP through the proxy."""

import http.client
import json

import pytest


def _req(port, method, path, body=None, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request(method, path, body=body, headers=headers or {})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, dict(resp.getheaders()), data


def _sse_events(data: bytes):
    events = []
    for block in data.decode().split("\n\n"):
        for line in block.splitlines():
            if line.startswith("data: "):
                events.append(line[len("data: "):])
    return events


@pytest.fixture(scope="module")
def openai_port(ray_session):
    from ray_tpu import serve
    from ray_tpu.serve.llm import LLMConfig

    app = serve.build_openai_app({
        "tiny-lm": LLMConfig(preset="tiny", max_batch_slots=2,
                             max_seq_len=128, temperature=0.0,
                             model_overrides={"vocab_size": 260}),
    })
    serve.run(app, name="openai", route_prefix="/")
    port = serve.start(http_options={"port": 0})
    yield port
    serve.shutdown()


def test_models_list_and_card(openai_port):
    status, _h, data = _req(openai_port, "GET", "/v1/models")
    assert status == 200
    out = json.loads(data)
    assert out["object"] == "list"
    assert [m["id"] for m in out["data"]] == ["tiny-lm"]

    status, _h, data = _req(openai_port, "GET", "/v1/models/tiny-lm")
    assert status == 200
    assert json.loads(data)["id"] == "tiny-lm"

    status, _h, data = _req(openai_port, "GET", "/v1/models/nope")
    assert status == 404
    assert json.loads(data)["error"]["code"] == "model_not_found"


def test_completions_unary(openai_port):
    status, headers, data = _req(
        openai_port, "POST", "/v1/completions",
        body=json.dumps({"model": "tiny-lm", "prompt": "hello",
                         "max_tokens": 8}),
        headers={"Content-Type": "application/json"})
    assert status == 200
    assert headers["Content-Type"] == "application/json"
    out = json.loads(data)
    assert out["object"] == "text_completion"
    assert out["model"] == "tiny-lm"
    choice = out["choices"][0]
    assert choice["finish_reason"] in ("stop", "length")
    assert isinstance(choice["text"], str)
    assert out["usage"]["prompt_tokens"] == 5   # byte tokenizer: len("hello")
    assert out["usage"]["completion_tokens"] <= 8
    assert out["usage"]["total_tokens"] == (
        out["usage"]["prompt_tokens"] + out["usage"]["completion_tokens"])


def test_completions_greedy_deterministic(openai_port):
    body = json.dumps({"model": "tiny-lm", "prompt": "abc",
                       "max_tokens": 6, "temperature": 0.0})
    outs = set()
    for _ in range(2):
        _s, _h, data = _req(openai_port, "POST", "/v1/completions", body=body,
                            headers={"Content-Type": "application/json"})
        outs.add(json.loads(data)["choices"][0]["text"])
    assert len(outs) == 1   # greedy: identical both times


def test_chat_completions_unary(openai_port):
    status, _h, data = _req(
        openai_port, "POST", "/v1/chat/completions",
        body=json.dumps({"model": "tiny-lm", "max_tokens": 8, "messages": [
            {"role": "system", "content": "be brief"},
            {"role": "user", "content": "hi"}]}),
        headers={"Content-Type": "application/json"})
    assert status == 200
    out = json.loads(data)
    assert out["object"] == "chat.completion"
    msg = out["choices"][0]["message"]
    assert msg["role"] == "assistant"
    assert isinstance(msg["content"], str)
    assert out["usage"]["prompt_tokens"] > 0


def test_completions_stream_sse(openai_port):
    status, headers, data = _req(
        openai_port, "POST", "/v1/completions",
        body=json.dumps({"model": "tiny-lm", "prompt": "xy",
                         "max_tokens": 6, "stream": True}),
        headers={"Content-Type": "application/json"})
    assert status == 200
    assert headers["Content-Type"].startswith("text/event-stream")
    events = _sse_events(data)
    assert events[-1] == "[DONE]"
    chunks = [json.loads(e) for e in events[:-1]]
    assert all(c["object"] == "text_completion" for c in chunks)
    # last data chunk carries the finish_reason, earlier ones carry text
    assert chunks[-1]["choices"][0]["finish_reason"] in ("stop", "length")
    streamed = "".join(c["choices"][0]["text"] for c in chunks)
    assert isinstance(streamed, str)


def test_chat_stream_role_then_deltas(openai_port):
    status, _h, data = _req(
        openai_port, "POST", "/v1/chat/completions",
        body=json.dumps({"model": "tiny-lm", "max_tokens": 6, "stream": True,
                         "messages": [{"role": "user", "content": "go"}]}),
        headers={"Content-Type": "application/json"})
    assert status == 200
    events = _sse_events(data)
    assert events[-1] == "[DONE]"
    chunks = [json.loads(e) for e in events[:-1]]
    assert all(c["object"] == "chat.completion.chunk" for c in chunks)
    assert chunks[0]["choices"][0]["delta"] == {"role": "assistant"}
    assert chunks[-1]["choices"][0]["finish_reason"] in ("stop", "length")


def test_stream_vs_unary_same_text(openai_port):
    """Greedy streaming must produce exactly the unary text."""
    req = {"model": "tiny-lm", "prompt": "zz", "max_tokens": 6,
           "temperature": 0.0}
    _s, _h, data = _req(openai_port, "POST", "/v1/completions",
                        body=json.dumps(req),
                        headers={"Content-Type": "application/json"})
    unary_text = json.loads(data)["choices"][0]["text"]
    _s, _h, data = _req(openai_port, "POST", "/v1/completions",
                        body=json.dumps({**req, "stream": True}),
                        headers={"Content-Type": "application/json"})
    chunks = [json.loads(e) for e in _sse_events(data)[:-1]]
    assert "".join(c["choices"][0]["text"] for c in chunks) == unary_text


def test_tokenize_detokenize_roundtrip(openai_port):
    text = "héllo ✓"
    status, _h, data = _req(
        openai_port, "POST", "/tokenize",
        body=json.dumps({"model": "tiny-lm", "prompt": text}),
        headers={"Content-Type": "application/json"})
    assert status == 200
    out = json.loads(data)
    assert out["count"] == len(out["tokens"])
    status, _h, data = _req(
        openai_port, "POST", "/detokenize",
        body=json.dumps({"model": "tiny-lm", "tokens": out["tokens"]}),
        headers={"Content-Type": "application/json"})
    assert status == 200
    assert json.loads(data)["prompt"] == text


def test_openai_error_shapes(openai_port):
    # unknown model
    status, _h, data = _req(
        openai_port, "POST", "/v1/completions",
        body=json.dumps({"model": "missing", "prompt": "x"}),
        headers={"Content-Type": "application/json"})
    assert status == 404
    assert json.loads(data)["error"]["type"] == "invalid_request_error"
    # bad JSON
    status, _h, data = _req(openai_port, "POST", "/v1/completions",
                            body="{nope", headers={})
    assert status == 400
    # n > 1 unsupported
    status, _h, data = _req(
        openai_port, "POST", "/v1/completions",
        body=json.dumps({"model": "tiny-lm", "prompt": "x", "n": 3}),
        headers={"Content-Type": "application/json"})
    assert status == 400
    assert "n > 1" in json.loads(data)["error"]["message"]


def test_stop_strings_unary():
    """Stop sequences cut the text and set finish_reason=stop (no HTTP:
    exercises the ingress directly for a crisp fixture)."""
    import asyncio

    from ray_tpu.serve.llm import LLMConfig
    from ray_tpu.serve.openai_api import OpenAIIngress

    ing = OpenAIIngress({"m": LLMConfig(
        preset="tiny", max_batch_slots=2, max_seq_len=64,
        model_overrides={"vocab_size": 260})})

    async def run():
        toks = await ing._generate(ing._engines["m"], ing._tok.encode("ab"),
                                   max_tokens=8, eos_id=None)
        full = ing._tok.decode(toks["tokens"])
        if len(full) < 2:
            pytest.skip("model generated too little text to split")
        stop = full[1]
        resp = await ing._completion_unary(
            {"model": "m", "prompt": "ab", "max_tokens": 8, "stop": stop},
            chat=False)
        out = json.loads(resp.content)
        choice = out["choices"][0]
        assert stop not in choice["text"]
        assert choice["text"] == full.split(stop)[0]
        assert choice["finish_reason"] == "stop"

    asyncio.run(run())


def test_byte_tokenizer_incremental_decoder_multibyte():
    from ray_tpu.serve.openai_api import ByteTokenizer, _IncrementalDecoder

    tok = ByteTokenizer()
    text = "a✓b€c"
    ids = tok.encode(text)
    assert tok.decode(ids) == text
    dec = _IncrementalDecoder(tok)
    # feeding byte-by-byte must never emit a replacement char
    out = "".join(dec.push(t) for t in ids) + dec.flush()
    assert out == text
    assert "�" not in out


def test_unary_over_stream_path_says_connection_close(openai_port):
    status, headers, _d = _req(
        openai_port, "POST", "/v1/completions",
        body=json.dumps({"model": "tiny-lm", "prompt": "q",
                         "max_tokens": 2}),
        headers={"Content-Type": "application/json"})
    assert status == 200
    # the proxy closes after a unary answer from a generator ingress; the
    # header must say so or pooling clients reuse a dead socket
    assert headers.get("Connection") == "close"


def test_byte_tokenizer_ignores_out_of_range_ids():
    from ray_tpu.serve.openai_api import ByteTokenizer

    tok = ByteTokenizer()
    # id 300 (vocab larger than 260) must not raise, just contribute nothing
    assert tok.decode([tok.encode("a")[0], 300, tok.encode("b")[0]]) == "ab"


def test_stream_stop_releases_slot_early():
    """A stop-string hit mid-stream must free the engine slot promptly, not
    keep decoding to max_tokens (slot + KV pages held for a finished
    request)."""
    import asyncio

    from ray_tpu.serve.llm import LLMConfig
    from ray_tpu.serve.openai_api import OpenAIIngress

    ing = OpenAIIngress({"m": LLMConfig(
        preset="tiny", max_batch_slots=2, max_seq_len=128,
        model_overrides={"vocab_size": 260})})
    eng = ing._engines["m"]

    async def run():
        toks = await eng.generate(ing._tok.encode("ab"), max_tokens=4)
        full = ing._tok.decode(toks["tokens"])
        if not full:
            pytest.skip("model generated nothing to stop on")
        stop = full[0]   # stops on the very first generated char
        chunks = []
        async for item in ing._completion_stream(
                {"model": "m", "prompt": "ab", "max_tokens": 100,
                 "stream": True, "stop": stop}, chat=False):
            chunks.append(item)
        assert chunks[-1]["choices"][0]["finish_reason"] == "stop"
        # the slot must come free LONG before 100 tokens of decode
        for _ in range(100):
            if len(eng._free) == eng.config.max_batch_slots:
                return
            await asyncio.sleep(0.05)
        raise AssertionError(
            f"slot not released after stop: free={len(eng._free)} "
            f"active={list(eng._active)}")

    asyncio.run(run())


def test_embeddings_endpoint(openai_port):
    status, _h, data = _req(
        openai_port, "POST", "/v1/embeddings",
        body=json.dumps({"model": "tiny-lm", "input": ["hello", "world"]}),
        headers={"Content-Type": "application/json"})
    assert status == 200
    out = json.loads(data)
    assert out["object"] == "list"
    assert len(out["data"]) == 2
    v0, v1 = (d["embedding"] for d in out["data"])
    assert len(v0) == len(v1) > 0
    assert out["usage"]["prompt_tokens"] == 10  # byte tokenizer
    # same text embeds identically, different text differs
    status, _h, data = _req(
        openai_port, "POST", "/v1/embeddings",
        body=json.dumps({"model": "tiny-lm", "input": "hello"}),
        headers={"Content-Type": "application/json"})
    again = json.loads(data)["data"][0]["embedding"]
    assert again == pytest.approx(v0)
    assert v0 != pytest.approx(v1)
    # bad input shape -> 400
    status, _h, _d = _req(
        openai_port, "POST", "/v1/embeddings",
        body=json.dumps({"model": "tiny-lm", "input": 42}),
        headers={"Content-Type": "application/json"})
    assert status == 400


def test_embeddings_empty_input_is_400(openai_port):
    status, _h, data = _req(
        openai_port, "POST", "/v1/embeddings",
        body=json.dumps({"model": "tiny-lm", "input": ["ok", ""]}),
        headers={"Content-Type": "application/json"})
    assert status == 400
    assert "empty" in json.loads(data)["error"]["message"]
