"""Expressions API (ref: python/ray/data/expressions.py col/lit trees
consumed by with_column/filter)."""

import numpy as np
import pandas as pd
import pytest

from ray_tpu import data as rd
from ray_tpu.data import col, lit


def test_arithmetic_matches_pandas():
    df = pd.DataFrame({"x": [1.0, 2.0, 3.0], "y": [10.0, 20.0, 30.0]})
    ds = rd.from_pandas(df)
    out = ds.with_column("z", (col("x") + lit(5)) * col("y")).to_pandas()
    pd.testing.assert_series_equal(out["z"], ((df.x + 5) * df.y),
                                   check_names=False)
    out2 = ds.with_column("w", 2 * col("x") - col("y") / 10).to_pandas()
    pd.testing.assert_series_equal(out2["w"], 2 * df.x - df.y / 10,
                                   check_names=False)


def test_filter_expression_vectorized():
    ds = rd.range(100)
    got = sorted(r["id"] for r in
                 ds.filter((col("id") > 10) & (col("id") % 7 == 0)).take_all())
    assert got == [i for i in range(100) if i > 10 and i % 7 == 0]
    neg = ds.filter(~(col("id") < 95)).take_all()
    assert sorted(r["id"] for r in neg) == [95, 96, 97, 98, 99]


def test_alias_and_repr_and_structural_equality():
    e = (col("x") + lit(5)) * col("y")
    assert repr(e) == "((col('x') + lit(5)) * col('y'))"
    assert e.structurally_equals((col("x") + lit(5)) * col("y"))
    assert not e.structurally_equals((col("x") - lit(5)) * col("y"))
    a = e.alias("z")
    assert a.name == "z"
    df = pd.DataFrame({"x": [1.0], "y": [2.0]})
    assert float(a.eval(df).iloc[0]) == 12.0


def test_missing_column_raises_with_names():
    ds = rd.from_pandas(pd.DataFrame({"x": [1]}))
    with pytest.raises(Exception, match="nope"):
        ds.with_column("z", col("nope") + 1).to_pandas()


def test_python_bool_ops_raise_not_silently_drop():
    """`and`/`or`/`not` on expressions would silently drop a side (Python
    truthiness); they must raise like numpy arrays do (r5 review repro:
    (a) and (b) returned only b)."""
    with pytest.raises(TypeError, match="truth value"):
        bool(col("x") > 1)
    with pytest.raises(TypeError, match="truth value"):
        (col("id") > 5) and (col("id") < 3)  # noqa: B015


def test_reflected_operators_complete():
    df = pd.DataFrame({"x": [2.0, 3.0]})
    ds = rd.from_pandas(df)
    assert [r["z"] for r in
            ds.with_column("z", 2 ** col("x")).take_all()] == [4.0, 8.0]
    assert [r["z"] for r in
            ds.with_column("z", 10 % col("x")).take_all()] == [0.0, 1.0]
    assert [r["z"] for r in
            ds.with_column("z", 7 // col("x")).take_all()] == [3.0, 2.0]
    got = rd.range(6).filter(True & (col("id") > 3)).take_all()
    assert sorted(r["id"] for r in got) == [4, 5]
