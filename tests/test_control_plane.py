"""Pipelined control plane (PR 2): client-derived return ids, fire-and-forget
submit, batched refcount/put frames.

The contract: batching is TRANSPARENT. Every blocking control RPC flushes
buffered deltas first, so a decref can never overtake the put/submit that
created the id — and pipelined submit costs ≤ 1 blocking controller round
trip per N tasks (the perf claim benchmarked by benchmarks/core_bench.py).
"""

import gc
import json
import os
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _client():
    from ray_tpu._private import state
    return state.global_client()


def _controller():
    return _client().controller


def _wait_for(cond, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return cond()


# ------------------------------------------------------------- pipelining

def test_pipelined_submit_single_roundtrip(ray_session):
    """50 driver-side submits must not block on the controller: the specs go
    fire-and-forget, so the round-trip counter moves ≤ 1 across the loop."""
    ray = ray_session
    from ray_tpu.util import metrics

    @ray.remote
    def f(i):
        return i * 2

    ray.get(f.remote(0))  # warm the worker pool outside the counted window
    rt0 = metrics.control_roundtrips_total()
    refs = [f.remote(i) for i in range(50)]
    submit_rt = metrics.control_roundtrips_total() - rt0
    assert submit_rt <= 1, f"50 pipelined submits cost {submit_rt} round trips"
    assert ray.get(refs, timeout=60) == [i * 2 for i in range(50)]


def test_return_ids_are_client_derived(ray_session):
    """Refs exist before the controller has seen the spec, named by
    ids.object_id_for_return(task_id, index)."""
    ray = ray_session

    @ray.remote(num_returns=3)
    def three():
        return 1, 2, 3

    refs = three.remote()
    assert [r.id.rsplit("-", 1)[1] for r in refs] == ["ret0", "ret1", "ret2"]
    task_id = refs[0].id[len("obj-"):-len("-ret0")]
    from ray_tpu._private import ids
    assert [r.id for r in refs] == [
        ids.object_id_for_return(task_id, i) for i in range(3)]
    assert ray.get(refs, timeout=60) == [1, 2, 3]


def test_submit_error_surfaces_through_ref(ray_session):
    """Fire-and-forget submit has no reply to carry a validation error; it
    must land in the ref's descriptor and raise from get()."""
    import pytest
    ray = ray_session

    @ray.remote(num_cpus=10_000)
    def impossible():
        return 1

    ref = impossible.remote()
    with pytest.raises(ValueError):
        ray.get(ref, timeout=30)


def test_worker_fanout_single_roundtrip(ray_session):
    """WorkerClient.submit is fire-and-forget over the unix socket too."""
    ray = ray_session

    @ray.remote
    def fanout(m):
        import ray_tpu
        from ray_tpu.util import metrics

        @ray_tpu.remote
        def child(i):
            return i + 100

        rt0 = metrics.control_roundtrips_total()
        refs = [child.remote(i) for i in range(m)]
        submit_rt = metrics.control_roundtrips_total() - rt0
        return submit_rt, ray_tpu.get(refs)

    submit_rt, vals = ray.get(fanout.remote(20), timeout=60)
    assert submit_rt <= 1, f"20 worker submits cost {submit_rt} round trips"
    assert vals == [i + 100 for i in range(20)]


# ------------------------------------------------- refcount batch ordering

def test_put_then_immediate_del_as_task_arg(ray_session):
    """put → pass ref as task arg → drop the local ref at once. The decref
    rides a batch BEHIND the put registration and the submit, and the
    task's arg pin keeps the object alive until it runs."""
    ray = ray_session

    @ray.remote
    def total(a):
        return int(a.sum())

    arr = np.arange(64 * 1024, dtype=np.int64)  # shm-sized, not inline
    want = int(arr.sum())
    ref = ray.put(arr)
    fut = total.remote(ref)
    del ref
    gc.collect()
    assert ray.get(fut, timeout=60) == want


def test_put_and_decref_same_batch_applies_in_order(ray_session):
    """A put and its decref-to-zero coalesced into one flush must apply
    in order: register first, then evict — never a dangling decref."""
    ray = ray_session
    ctl = _controller()
    ref = ray.put(b"x" * 128)
    oid = ref.id
    del ref
    gc.collect()
    _client().flush()
    assert _wait_for(lambda: oid not in ctl.objects), \
        "decref-to-zero must evict once the batch lands"


def test_incref_racing_timer_flush(ray_session):
    """Explicit increfs split across timer flushes still net out exactly:
    the object survives while any balance remains, and eviction happens
    only after the final decref lands."""
    ray = ray_session
    ctl = _controller()
    client = _client()
    ref = ray.put(b"y" * 256)
    oid = ref.id
    for _ in range(3):
        client.incref(oid)
    time.sleep(0.05)  # > flush interval: the timer fires mid-sequence
    for _ in range(3):
        client.decref(oid)
    client.flush()
    time.sleep(0.05)
    assert oid in ctl.objects, "balanced incref/decref must not evict"
    assert ray.get(ref, timeout=30) == b"y" * 256
    del ref
    gc.collect()
    client.flush()
    assert _wait_for(lambda: oid not in ctl.objects)


def test_contained_ref_survives_inner_del(ray_session):
    """An inner ref serialized into an outer put stays reachable through the
    outer object even when the local inner handle drops — containment
    pinning must order correctly through the batched frames."""
    ray = ray_session
    inner = ray.put(np.full(2048, 7, dtype=np.int32))
    outer = ray.put({"nested": inner})
    del inner
    gc.collect()
    _client().flush()
    time.sleep(0.05)
    got = ray.get(ray.get(outer, timeout=30)["nested"], timeout=30)
    assert int(got.sum()) == 7 * 2048


def test_shutdown_flushes_pending_deltas():
    """Driver shutdown right after dropping refs: the pending decrefs must
    drain cleanly before the controller stops (exit 0, no hang)."""
    script = (
        "import os; os.environ.setdefault('RAY_TPU_NUM_CHIPS', '0')\n"
        "import ray_tpu\n"
        "ray_tpu.init(num_cpus=2)\n"
        "refs = [ray_tpu.put(bytes([i]) * 512) for i in range(64)]\n"
        "del refs\n"
        "import gc; gc.collect()\n"
        "ray_tpu.shutdown()\n"
        "print('CLEAN')\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", script], cwd=REPO, env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "CLEAN" in out.stdout


# ------------------------------------------------------- bench smoke hooks

def test_core_bench_smoke():
    """core_bench --smoke is the tier-1 control-plane invariant check:
    pipelined submit ≤ 1 round trip per N tasks, driver and worker side."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "core_bench.py"),
         "--smoke"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=180)
    assert out.returncode == 0, (out.stdout[-1000:], out.stderr[-2000:])
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["submit_roundtrips"] <= 1
    assert rec["fanout"]["submit_rt"] <= 1


def test_transfer_bench_smoke():
    """transfer_bench --smoke is the tier-1 data-plane invariant check:
    parallel fetch lands bytes intact, batched get preserves order, and
    owner-tagged pipeline maps hit their block's node ≥ 90% of the time
    while moving ~no block bytes across nodes."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "benchmarks", "transfer_bench.py"), "--smoke"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=240)
    assert out.returncode == 0, (out.stdout[-1000:], out.stderr[-2000:])
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["pipeline"]["locality_hit_rate"] >= 0.9
    assert rec["pipeline"]["cross_node_block_bytes"] < (1 << 20)


def test_sync_submit_escape_hatch():
    """RAY_TPU_SYNC_SUBMIT=1 restores the blocking control plane end to end
    (the core_bench baseline mode must stay a faithful fallback)."""
    script = (
        "import os; os.environ.setdefault('RAY_TPU_NUM_CHIPS', '0')\n"
        "import ray_tpu\n"
        "from ray_tpu.util import metrics\n"
        "@ray_tpu.remote\n"
        "def f(i): return i\n"
        "ray_tpu.init(num_cpus=2)\n"
        "ray_tpu.get(f.remote(0))\n"
        "rt0 = metrics.control_roundtrips_total()\n"
        "refs = [f.remote(i) for i in range(10)]\n"
        "rt = metrics.control_roundtrips_total() - rt0\n"
        "assert rt >= 10, f'sync mode must block per submit, got {rt}'\n"
        "assert ray_tpu.get(refs) == list(range(10))\n"
        "r = ray_tpu.put(b'z' * 100)\n"
        "assert ray_tpu.get(r) == b'z' * 100\n"
        "ray_tpu.shutdown()\n"
        "print('SYNC_OK')\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu", RAY_TPU_SYNC_SUBMIT="1")
    out = subprocess.run([sys.executable, "-c", script], cwd=REPO, env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "SYNC_OK" in out.stdout
