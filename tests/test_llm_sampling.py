"""LLM engine sampling + prefix caching (VERDICT r4 missing #3; ref:
/root/reference/python/ray/llm/_internal/serve/engines/sglang/
sglang_engine.py:90 — top_p/logprobs served per request; vLLM/sglang
automatic prefix caching).
"""

import asyncio

import numpy as np
import pytest


def _run(coro):
    return asyncio.run(coro)


@pytest.fixture(scope="module")
def server():
    from ray_tpu.serve.llm import LLMConfig, LLMServer
    return LLMServer(LLMConfig(preset="tiny", max_batch_slots=2,
                               max_seq_len=128))


def test_top_p_restricts_support(server):
    """With a peaked distribution and small top_p, sampling must never draw
    outside the nucleus; with top_p=1 it ranges wider."""
    import jax
    import jax.numpy as jnp

    logits = jnp.asarray(np.array([[5.0, 4.9, -5.0, -5.0, -6.0, -8.0]]
                                  * 2, np.float32))
    # reuse the server's jitted single-row sampler for a direct unit probe
    draws_tight, draws_wide = set(), set()
    for i in range(200):
        key = jax.random.PRNGKey(i)
        tok, _ = server._sample_first(logits[0], key, jnp.float32(1.0),
                                      jnp.float32(0.6), jnp.int32(0))
        draws_tight.add(int(tok))
        tok2, _ = server._sample_first(logits[0], key, jnp.float32(5.0),
                                       jnp.float32(1.0), jnp.int32(0))
        draws_wide.add(int(tok2))
    # nucleus at p=0.6: tokens {0, 1} carry ~essentially all needed mass
    assert draws_tight <= {0, 1}, draws_tight
    assert len(draws_wide) > 2, draws_wide  # hot temp, full support


def test_top_k_and_greedy(server):
    import jax
    import jax.numpy as jnp

    logits = jnp.asarray(np.array([3.0, 2.9, 2.8, -9.0], np.float32))
    draws = set()
    for i in range(100):
        tok, _ = server._sample_first(logits, jax.random.PRNGKey(i),
                                      jnp.float32(2.0), jnp.float32(1.0),
                                      jnp.int32(2))
        draws.add(int(tok))
    assert draws <= {0, 1}, draws  # top-k=2 support
    tok, logp = server._sample_first(logits, jax.random.PRNGKey(0),
                                     jnp.float32(0.0), jnp.float32(1.0),
                                     jnp.int32(0))
    assert int(tok) == 0  # temp 0 → argmax
    # logprob is the raw-distribution log-softmax of the chosen token
    want = float(jax.nn.log_softmax(logits)[0])
    assert abs(float(logp) - want) < 1e-5


def test_generate_returns_logprobs(server):
    out = _run(server.generate([5, 6, 7], max_tokens=6, logprobs=True))
    assert len(out["logprobs"]) == len(out["tokens"]) == 6
    assert all(lp <= 0.0 for lp in out["logprobs"])


def test_per_request_params_mix(server):
    """Greedy and hot-temperature requests share the batch: greedy stays
    deterministic while its neighbor samples."""
    async def go():
        a, b = await asyncio.gather(
            server.generate([1, 2, 3, 4], max_tokens=8, temperature=0.0),
            server.generate([1, 2, 3, 4], max_tokens=8, temperature=3.0,
                            top_p=0.95))
        c = await server.generate([1, 2, 3, 4], max_tokens=8,
                                  temperature=0.0)
        return a, b, c

    a, b, c = _run(go())
    assert a["tokens"] == c["tokens"]  # greedy reproducible
    assert len(b["tokens"]) == 8


# ---------------------------------------------------------------- prefix cache

def _paged_server(prefix_cache=True, **kw):
    from ray_tpu.serve.llm import LLMConfig, LLMServer
    return LLMServer(LLMConfig(preset="tiny", max_batch_slots=2,
                               max_seq_len=256, paged=True, page_size=16,
                               prefix_cache=prefix_cache, **kw))


def test_prefix_cache_hits_and_matches_uncached():
    """Second request with the same prompt skips its full prompt pages
    (hit counters prove it) and produces IDENTICAL greedy output."""
    srv = _paged_server()
    prompt = list(range(40))  # 2.5 pages of 16 → 2 full pages cacheable
    out1 = _run(srv.generate(prompt, max_tokens=8))
    s1 = srv.stats()
    assert s1["prefix_hit_tokens"] == 0
    assert s1["prefix_cached_pages"] == 2
    out2 = _run(srv.generate(prompt, max_tokens=8))
    s2 = srv.stats()
    assert s2["prefix_hit_tokens"] == 32  # both full pages reused
    assert out2["tokens"] == out1["tokens"]
    # a fresh unrelated prompt misses but still works
    out3 = _run(srv.generate([99, 98, 97], max_tokens=4))
    assert len(out3["tokens"]) == 4


def test_prefix_cache_shared_prefix_divergent_tails():
    """Requests sharing only a prefix reuse exactly the shared full pages;
    divergent tails don't cross-contaminate (outputs match a no-cache
    server run of the same prompts)."""
    base = list(range(32))  # 2 full pages
    p1 = base + [70, 71, 72]
    p2 = base + [80, 81]
    srv = _paged_server(prefix_cache=True)
    a1 = _run(srv.generate(p1, max_tokens=6))
    a2 = _run(srv.generate(p2, max_tokens=6))
    assert srv.stats()["prefix_hit_tokens"] == 32  # p2 reused base pages
    ref = _paged_server(prefix_cache=False)
    b1 = _run(ref.generate(p1, max_tokens=6))
    b2 = _run(ref.generate(p2, max_tokens=6))
    assert a1["tokens"] == b1["tokens"]
    assert a2["tokens"] == b2["tokens"]


def test_prefix_cache_eviction_under_pressure():
    """A small pool evicts LRU refcount-0 cached pages instead of failing
    admission; live borrowers are never evicted."""
    from ray_tpu.ops.paged_attention import PageManager
    mgr = PageManager(num_pages=9, page_size=4, batch_slots=2,
                      max_pages_per_seq=8, prefix_cache=True)
    # slot 0: prompt of 12 tokens (3 pages, all full→2 registerable... use 13)
    prompt = list(range(13))  # 3 full pages + 1 partial? 13/4 = 3 full
    row, cached = mgr.allocate_prefix(0, prompt, 16)  # 4 pages
    assert cached == 0
    mgr.register_prefix(0, prompt)
    assert mgr.cached_pages == 3
    mgr.free(0)
    assert mgr.cached_pages == 3  # parked in LRU, not freed
    # repeat prompt: hits
    row, cached = mgr.allocate_prefix(0, prompt, 16)
    assert cached == 12
    mgr.free(0)
    # pool pressure: a big unrelated request forces eviction of cached pages
    row2, cached2 = mgr.allocate_prefix(1, list(range(100, 128)), 32)  # 8 pages
    assert cached2 == 0
    assert mgr.cached_pages < 3  # some cache evicted to make room
    mgr.free(1)


def test_prefix_cache_never_shares_partial_pages():
    from ray_tpu.ops.paged_attention import PageManager
    mgr = PageManager(num_pages=16, page_size=8, batch_slots=2,
                      max_pages_per_seq=8, prefix_cache=True)
    row, cached = mgr.allocate_prefix(0, list(range(8)), 16)
    # 8 tokens = exactly 1 full page, but the LAST token must prefill →
    # nothing shareable on a later identical prompt beyond page 0... and
    # even page 0 can't be fully consumed by a same-length prompt:
    mgr.register_prefix(0, list(range(8)))
    assert mgr.cached_pages == 1
    row2, cached2 = mgr.allocate_prefix(1, list(range(8)), 16)
    assert cached2 == 0  # full coverage would leave 0 tokens to prefill
    mgr.free(0)
    mgr.free(1)


def test_paged_multichunk_prefill_matches_dense():
    """Regression for the r4 latent bug prefix caching exposed: paged
    prefill chunks 2+ attended only within their own chunk (chunk-local
    causal mask), never reading back cached pages — any paged prompt
    longer than prefill_chunk decoded from corrupt KV. Greedy outputs must
    match the dense engine for a 3-chunk prompt."""
    from ray_tpu.serve.llm import LLMConfig, LLMServer
    prompt = [(7 * i + 3) % 250 for i in range(90)]  # 3 chunks of 32
    paged = LLMServer(LLMConfig(preset="tiny", max_batch_slots=2,
                                max_seq_len=256, paged=True, page_size=16,
                                prefill_chunk=32, prefix_cache=False))
    dense = LLMServer(LLMConfig(preset="tiny", max_batch_slots=2,
                                max_seq_len=256, prefill_chunk=32))
    a = _run(paged.generate(prompt, max_tokens=8))
    b = _run(dense.generate(prompt, max_tokens=8))
    assert a["tokens"] == b["tokens"], (a["tokens"], b["tokens"])


def test_prefix_pages_survive_concurrent_decode():
    """r5 review finding: while another request is actively DECODING, a
    prefix-hit admission must not let the per-tick KV write (which touches
    every row at its recorded length) land garbage in a SHARED page. The
    slot's length now points past the cached prefix from admission on, so
    the stray write hits a fresh page that prefill overwrites."""
    srv = _paged_server()
    prompt = list(range(40))

    async def go():
        async def busy_stream():
            toks = []
            async for t in srv.generate_stream(list(range(200, 230)),
                                               max_tokens=60):
                toks.append(t)
            return toks

        ta = asyncio.create_task(busy_stream())
        await asyncio.sleep(0.2)          # stream is decoding
        out1 = await srv.generate(prompt, max_tokens=6)   # registers pages
        out2 = await srv.generate(prompt, max_tokens=6)   # prefix hit, mid-decode
        await ta
        return out1, out2

    out1, out2 = _run(go())
    assert srv.stats()["prefix_hit_tokens"] >= 32
    assert out2["tokens"] == out1["tokens"]
    # cached pages still clean after all the concurrent traffic
    out3 = _run(srv.generate(prompt, max_tokens=6))
    assert out3["tokens"] == out1["tokens"]


def test_lru_eviction_spares_borrowed_prefix_pages():
    """Under pool pressure the LRU evicts PARKED (refcount-0) cached pages
    only; prefix pages a live slot borrowed are pinned — off the LRU —
    and must survive the eviction intact (the PD decode path depends on
    this: shipped-suffix installs scatter around borrowed leading pages)."""
    from ray_tpu.ops.paged_attention import PageManager
    mgr = PageManager(num_pages=11, page_size=4, batch_slots=3,
                      max_pages_per_seq=8, prefix_cache=True)
    a = list(range(9))             # 2 full pages registerable
    b = list(range(50, 59))
    for slot, p in ((0, a), (1, b)):
        _, cached = mgr.allocate_prefix(slot, p, 9)
        assert cached == 0
        mgr.register_prefix(slot, p)
        mgr.free(slot)
    assert mgr.cached_pages == 4   # both prompts parked in the LRU

    # borrow A's pages: pinned for slot 0, popped from the LRU
    _, cached = mgr.allocate_prefix(0, a, 12)
    assert cached == 8
    assert mgr.shared_page_count(0) == 2
    assert len(mgr.table_slice(0, 0, 3)) == 3  # PD extraction unit works
    with pytest.raises(IndexError):
        mgr.table_slice(0, 2, 5)   # past the allocation

    # pressure: of the 10 usable pages (page 0 is the padding sentinel),
    # slot 0 holds A's 2 borrowed + 1 fresh and B's 2 sit parked → 5
    # free. A 7-page request must evict BOTH of B's parked pages; A's
    # are borrowed, hence pinned and untouchable.
    _, cached2 = mgr.allocate_prefix(1, list(range(100, 128)), 28)
    assert cached2 == 0
    assert mgr.cached_pages == 2   # A still cached, B gone
    mgr.free(1)
    mgr.free(0)

    # A survived eviction and is reusable; B must miss
    _, hit = mgr.allocate_prefix(0, a, 9)
    assert hit == 8
    mgr.free(0)
    _, miss = mgr.allocate_prefix(1, b, 9)
    assert miss == 0
    mgr.free(1)
