"""GcpTpuNodeProvider (VERDICT r4 next #8; ref:
python/ray/autoscaler/_private/gcp/node_provider.py, tpu_command_runner.py).

Unit-level: slice topology parsing + the dry-run gcloud contract.
End-to-end (cluster driver): a fake v5e-8 "TPU node" is provisioned through
the autoscaler seam and a num_tpus actor schedules onto it.
"""

import pytest


def test_slice_info_topology():
    from ray_tpu.autoscaler import slice_info
    # v5e counts chips, 8 per host
    assert slice_info("v5litepod-8") == {"chips": 8, "hosts": 1,
                                         "chips_per_host": 8}
    assert slice_info("v5litepod-16") == {"chips": 16, "hosts": 2,
                                          "chips_per_host": 8}
    assert slice_info("v5litepod-4") == {"chips": 4, "hosts": 1,
                                         "chips_per_host": 4}
    # v4/v5p count TensorCores (2/chip), 4 chips per host
    assert slice_info("v4-8") == {"chips": 4, "hosts": 1,
                                  "chips_per_host": 4}
    assert slice_info("v4-32") == {"chips": 16, "hosts": 4,
                                   "chips_per_host": 4}
    assert slice_info("v5p-8") == {"chips": 4, "hosts": 1,
                                   "chips_per_host": 4}
    assert slice_info("v6e-8") == {"chips": 8, "hosts": 1,
                                   "chips_per_host": 8}
    with pytest.raises(ValueError):
        slice_info("h100-8")
    with pytest.raises(ValueError):
        slice_info("v5litepod")


def test_dry_run_gcloud_contract():
    """The real-mode provisioning contract is testable without cloud
    access: dry_run records the exact gcloud invocations."""
    from ray_tpu.autoscaler import GcloudTpuApi, GcpTpuNodeProvider
    api = GcloudTpuApi("proj-x", "us-central2-b", dry_run=True)
    provider = GcpTpuNodeProvider(project="proj-x", zone="us-central2-b",
                                  accelerator_type="v5litepod-8", api=api)
    assert provider.tpus_per_node == 8.0
    handle = provider.create_node({}, "10.0.0.1:7777")
    create = api.commands[-1]
    assert create[:6] == ["gcloud", "compute", "tpus", "tpu-vm", "create",
                          handle]
    assert "--accelerator-type" in create
    assert create[create.index("--accelerator-type") + 1] == "v5litepod-8"
    # the script travels via --metadata-from-file (--metadata would split
    # its JSON on commas); dry-run keeps the script text in api.scripts
    assert "--metadata-from-file" in create
    script = api.scripts[handle]
    assert "node_main" in script and "10.0.0.1:7777" in script
    assert '"num_tpus": 8' in script
    assert provider.non_terminated_nodes() == [handle]
    provider.terminate_node(handle)
    assert provider.non_terminated_nodes() == []
    assert api.commands[-2][:5] == ["gcloud", "compute", "tpus", "tpu-vm",
                                    "delete"]


def test_pidless_real_api_uses_marker_drain():
    """Real gcloud mode can't map agent pids; the provider must say so
    (pids_of → None) and expose the marker the head drains promises with,
    or launched capacity double-counts forever (r5 review finding)."""
    from ray_tpu.autoscaler import GcloudTpuApi, GcpTpuNodeProvider
    api = GcloudTpuApi("p", "z", dry_run=True)
    provider = GcpTpuNodeProvider(accelerator_type="v5litepod-16", api=api)
    assert provider.pids_of("anything") is None
    assert provider.pid_of("anything") is None
    assert provider.registration_marker == "accelerator_type:v5litepod-16"
    assert provider.hosts_per_node == 2.0


def test_multihost_slice_launches_one_agent_per_host():
    """v5litepod-16 = 2 hosts → the fake API must start 2 agents, each
    advertising 8 chips (the reference treats the pod as one node whose
    command runner fans out to every host)."""
    from ray_tpu.autoscaler.gcp_tpu import FakeTpuApi, _startup_script

    class SpyApi(FakeTpuApi):
        def __init__(self):
            super().__init__()
            self.spawned = []

        def create(self, name, accelerator_type, runtime_version, script):
            # don't actually spawn; record what would be
            import re
            from ray_tpu.autoscaler import slice_info
            info = slice_info(accelerator_type)
            self.spawned.append((name, info["hosts"],
                                 info["chips_per_host"]))
            self._slices[name] = []

    from ray_tpu.autoscaler import GcpTpuNodeProvider
    api = SpyApi()
    provider = GcpTpuNodeProvider(accelerator_type="v5litepod-16", api=api)
    provider.create_node({}, "127.0.0.1:1")
    assert api.spawned == [("ray-tpu-v5litepod-16-1", 2, 8)]
    script = _startup_script("127.0.0.1:1", 8, "v5litepod-16")
    assert "--address 127.0.0.1:1" in script
