"""Paged attention: pallas kernel (interpret mode) == XLA reference ==
dense decode attention; page pool write/read round-trip; allocator
bookkeeping. (Ref contrast: vLLM PagedAttention CUDA kernel tests.)"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.attention import decode_attention
from ray_tpu.ops.paged_attention import (PagedKVCache, PageManager,
                                         paged_attention,
                                         paged_attention_reference,
                                         write_tokens)


def _random_paged(b, kh, g, d, page, max_pages, lengths, seed=0):
    """Build a pool + tables where each row's pages hold random K/V."""
    rng = np.random.default_rng(seed)
    pool = b * max_pages + 1
    k_pages = rng.normal(size=(kh, pool, page, d)).astype(np.float32)
    v_pages = rng.normal(size=(kh, pool, page, d)).astype(np.float32)
    # deliberately scrambled page assignment (fragmentation)
    perm = rng.permutation(np.arange(1, pool))
    tables = np.zeros((b, max_pages), np.int32)
    used = 0
    for i in range(b):
        need = -(-lengths[i] // page)
        tables[i, :need] = perm[used:used + need]
        used += need
    q = rng.normal(size=(b, kh * g, d)).astype(np.float32)
    return (jnp.array(q), jnp.array(k_pages), jnp.array(v_pages),
            jnp.array(tables), jnp.array(lengths, dtype=jnp.int32))


@pytest.mark.parametrize("g", [1, 4])
def test_kernel_matches_reference_fragmented(g):
    b, kh, d, page, max_pages = 3, 2, 64, 8, 4
    lengths = np.array([1, 13, 32])
    q, kp, vp, tbl, lens = _random_paged(b, kh, g, d, page, max_pages, lengths)
    out_k = paged_attention(q, kp, vp, tbl, lens, interpret=True)
    out_r = paged_attention_reference(q, kp, vp, tbl, lens)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_r),
                               atol=2e-5, rtol=2e-5)


def test_reference_matches_dense_decode():
    """Contiguous pages == the model's dense decode_attention oracle."""
    b, kh, g, d, page, max_pages = 2, 2, 1, 32, 4, 8
    s_max = page * max_pages
    rng = np.random.default_rng(1)
    lengths = np.array([5, 29])
    k_cache = rng.normal(size=(b, s_max, kh, d)).astype(np.float32)
    v_cache = rng.normal(size=(b, s_max, kh, d)).astype(np.float32)
    q = rng.normal(size=(b, kh * g, d)).astype(np.float32)

    # lay the same cache out as contiguous per-row pages
    pool = b * max_pages + 1
    k_pages = np.zeros((kh, pool, page, d), np.float32)
    v_pages = np.zeros((kh, pool, page, d), np.float32)
    tables = np.zeros((b, max_pages), np.int32)
    nxt = 1
    for i in range(b):
        for p in range(max_pages):
            k_pages[:, nxt] = k_cache[i, p * page:(p + 1) * page].transpose(1, 0, 2)
            v_pages[:, nxt] = v_cache[i, p * page:(p + 1) * page].transpose(1, 0, 2)
            tables[i, p] = nxt
            nxt += 1

    out_p = paged_attention_reference(
        jnp.array(q), jnp.array(k_pages), jnp.array(v_pages),
        jnp.array(tables), jnp.array(lengths, dtype=jnp.int32))
    # decode_attention takes tokens-BEFORE-the-chunk and attends <= L;
    # paged lengths are inclusive counts, hence the -1
    out_d = decode_attention(
        jnp.array(q)[:, None], jnp.array(k_cache), jnp.array(v_cache),
        jnp.array(lengths - 1, dtype=jnp.int32))
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_d)[:, 0],
                               atol=2e-5, rtol=2e-5)


def test_write_tokens_roundtrip():
    l, b, kh, d, page = 2, 2, 2, 8, 4
    cache = PagedKVCache.init(l, kh, d, num_pages=16, page_size=page,
                              batch_slots=b, max_pages_per_seq=3,
                              dtype=jnp.float32)
    mgr = PageManager(16, page, b, 3)
    rows = [mgr.allocate(0, 6), mgr.allocate(1, 3)]
    cache = cache.replace(block_tables=jnp.array(rows, jnp.int32))

    rng = np.random.default_rng(2)
    # prefill: row 0 writes 6 tokens, row 1 writes 3; row 1's positions 3-5
    # are padding that lands on reserved page 0 (table entry 0) harmlessly
    k_new = rng.normal(size=(l, b, 6, kh, d)).astype(np.float32)
    v_new = rng.normal(size=(l, b, 6, kh, d)).astype(np.float32)
    positions = np.stack([np.arange(6), np.arange(6)])
    cache = write_tokens(cache, jnp.array(k_new), jnp.array(v_new),
                         jnp.array(positions, dtype=jnp.int32))

    # read back through the tables: row 0 position 5 -> page 5//4=1, off 1
    tbl = np.array(cache.block_tables)
    got = np.asarray(cache.k_pages)[0, :, tbl[0, 5 // page], 5 % page]
    np.testing.assert_allclose(got, k_new[0, 0, 5])
    got1 = np.asarray(cache.v_pages)[1, :, tbl[1, 0], 2]
    np.testing.assert_allclose(got1, v_new[1, 1, 2])


def test_page_manager_alloc_extend_free():
    mgr = PageManager(num_pages=8, page_size=4, batch_slots=2,
                      max_pages_per_seq=4)
    assert mgr.can_fit(16) and not mgr.can_fit(100)
    row = mgr.allocate(0, 5)  # 2 pages
    assert len([p for p in row if p]) == 2 and mgr.pages_in_use == 2
    row = mgr.extend(0, 9)    # 3rd page
    assert len([p for p in row if p]) == 3
    row2 = mgr.allocate(1, 16)  # 4 pages
    assert mgr.pages_in_use == 7
    with pytest.raises(MemoryError):
        mgr.extend(1, 17)  # pool exhausted (only page 0 reserved left)
    mgr.free(0)
    assert mgr.pages_in_use == 4
    mgr.free(1)
    assert mgr.pages_in_use == 0


def test_model_paged_decode_matches_dense():
    """Greedy generation through the Llama decode path must be identical
    with the paged cache and the dense KVCache (same params, same prompt)."""
    from ray_tpu.models.llama import KVCache, Llama, LlamaConfig

    cfg = LlamaConfig.tiny(dtype=jnp.float32, param_dtype=jnp.float32,
                           max_seq_len=32)
    model = Llama(cfg)
    prompt = jnp.array([[3, 1, 4, 1, 5, 9, 2, 6, 5]], jnp.int32)
    P, steps = prompt.shape[1], 6
    params = model.init(jax.random.PRNGKey(0), prompt)

    def greedy_dense():
        cache = KVCache.init(cfg, 1, cfg.max_seq_len)
        logits, cache = model.apply(params, prompt, cache=cache)
        toks = [int(jnp.argmax(logits[0, -1]))]
        for _ in range(steps - 1):
            logits, cache = model.apply(
                params, jnp.array([[toks[-1]]], jnp.int32), cache=cache)
            toks.append(int(jnp.argmax(logits[0, -1])))
        return toks

    def greedy_paged():
        page = 4
        mgr = PageManager(num_pages=16, page_size=page, batch_slots=1,
                          max_pages_per_seq=8)
        row = mgr.allocate(0, P + steps)
        cache = PagedKVCache.init(
            cfg.n_layers, cfg.n_kv_heads, cfg.head_dim, num_pages=16,
            page_size=page, batch_slots=1, max_pages_per_seq=8,
            dtype=jnp.float32)
        cache = cache.replace(block_tables=jnp.array([row], jnp.int32))
        logits, cache = model.apply(params, prompt, cache=cache)
        toks = [int(jnp.argmax(logits[0, -1]))]
        for _ in range(steps - 1):
            logits, cache = model.apply(
                params, jnp.array([[toks[-1]]], jnp.int32), cache=cache)
            toks.append(int(jnp.argmax(logits[0, -1])))
        return toks

    assert greedy_dense() == greedy_paged()


@pytest.mark.tpu
def test_kernel_on_tpu_hardware():
    """Real-TPU lowering of the paged kernel vs the XLA reference (run with
    RAY_TPU_TEST_TPU=1 on hardware; validated manually on v5e)."""
    import os
    if not os.environ.get("RAY_TPU_TEST_TPU"):
        pytest.skip("no TPU opt-in")
    # includes a tiny-head case (kh*g = 2 < the 8-row sublane tile)
    for kh, g in ((2, 4), (2, 1)):
        b, d, page, max_pages = 4, 64, 16, 8
        lengths = np.array([1, 37, 100, 128])
        q, kp, vp, tbl, lens = _random_paged(b, kh, g, d, page, max_pages,
                                             lengths)
        qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, kp, vp))
        out_k = jax.jit(paged_attention)(qb, kb, vb, tbl, lens)
        out_r = paged_attention_reference(qb, kb, vb, tbl, lens)
        np.testing.assert_allclose(np.asarray(out_k, np.float32),
                                   np.asarray(out_r, np.float32), atol=2e-2)
