"""Collective + sharding tests on the virtual 8-device CPU mesh (SURVEY.md §4)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")


def test_make_mesh_wildcard():
    from ray_tpu.parallel import make_mesh

    mesh = make_mesh({"dp": -1, "tp": 2})
    assert mesh.shape["tp"] == 2
    assert mesh.shape["dp"] == 4
    # axis order: dp outer, tp inner
    assert mesh.axis_names == ("dp", "tp")


def test_make_mesh_errors():
    from ray_tpu.parallel import make_mesh

    with pytest.raises(ValueError):
        make_mesh({"dp": 3, "tp": 3})  # 9 != 8
    with pytest.raises(ValueError):
        make_mesh({"dp": -1, "tp": -1})


def test_xla_allreduce_matches_numpy():
    from ray_tpu.parallel import collective as col

    col.destroy_collective_group("t1")
    g = col.init_collective_group(8, 0, backend="xla", group_name="t1", axis="dp")
    x = np.arange(32, dtype=np.float32).reshape(8, 4)
    out = np.asarray(g.allreduce(x))
    # psum over dp: every shard row-block summed; result replicated = col-sum tiled
    expected = np.tile(x.reshape(8, 1, 4).sum(0), (8, 1))
    np.testing.assert_allclose(out, expected)
    col.destroy_collective_group("t1")


def test_xla_allgather_identity():
    from ray_tpu.parallel import collective as col

    col.destroy_collective_group("t2")
    g = col.init_collective_group(8, 0, backend="xla", group_name="t2", axis="dp")
    x = np.random.rand(8, 3).astype(np.float32)
    out = np.asarray(g.allgather(x))
    np.testing.assert_allclose(out, x)  # tiled all-gather of shards == original
    col.destroy_collective_group("t2")


def test_xla_reducescatter():
    from ray_tpu.parallel import collective as col

    col.destroy_collective_group("t3")
    g = col.init_collective_group(8, 0, backend="xla", group_name="t3", axis="dp")
    # axis-0 blocks are the per-rank tensors: rank r contributes blocks[r]
    x = np.random.rand(64).astype(np.float32)
    blocks = x.reshape(8, 8)
    out = np.asarray(g.reducescatter(x))
    np.testing.assert_allclose(out, blocks.sum(axis=0), rtol=1e-5)
    col.destroy_collective_group("t3")


def test_xla_alltoall_and_reduce():
    from ray_tpu.parallel import collective as col

    col.destroy_collective_group("t4")
    g = col.init_collective_group(8, 0, backend="xla", group_name="t4", axis="dp")
    x = np.arange(64, dtype=np.float32)
    blocks = x.reshape(8, 8)
    out = np.asarray(g.alltoall(x)).reshape(8, 8)
    np.testing.assert_allclose(out, blocks.T)  # block transpose
    red = np.asarray(g.reduce(np.ones(8, np.float32), dst_rank=3))
    np.testing.assert_allclose(red, np.full(8, 8.0))
    import pytest as _pytest
    with _pytest.raises(NotImplementedError):
        g.send(np.ones(2), 1)
    col.destroy_collective_group("t4")


def test_in_jit_collectives_shard_map():
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax import shard_map
    from ray_tpu.parallel import make_mesh, xla_ops

    mesh = make_mesh({"dp": 8})

    def step(x):
        local_sum = x.sum()
        total = xla_ops.psum(local_sum, "dp")
        idx = xla_ops.axis_index("dp").reshape(1)  # rank-1 so P("dp") applies
        shifted = xla_ops.ppermute_shift(x, "dp", 1)
        return total, idx, shifted

    f = jax.jit(shard_map(step, mesh=mesh, in_specs=P("dp"),
                          out_specs=(P(), P("dp"), P("dp"))))
    x = jnp.arange(16.0).reshape(8, 2)
    total, idx, shifted = f(x)
    assert float(total[()] if total.ndim == 0 else total) == float(x.sum())
    np.testing.assert_array_equal(np.asarray(idx), np.arange(8))
    # ring shift moves shard i to position (i+1) % 8
    np.testing.assert_allclose(np.asarray(shifted), np.roll(np.asarray(x), 1, axis=0))


def test_sharding_rules_llama():
    from jax.sharding import PartitionSpec as P
    from ray_tpu.parallel import ShardingRules, llama_rules, make_mesh

    rules = llama_rules()
    assert rules.spec_for("layers/0/attn/wq/kernel") == P(("fsdp",), ("tp",))
    assert rules.spec_for("layers/0/mlp/w_down/kernel") == P(("tp",), ("fsdp",))
    assert rules.spec_for("layers/0/attn_norm/scale") == P()
    assert rules.spec_for("unknown/param") == P()


def test_shard_tree_places_params():
    import jax.numpy as jnp
    from ray_tpu.parallel import ShardingRules, make_mesh, shard_tree
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh({"fsdp": 4, "tp": 2})
    rules = ShardingRules([(r"w", P("fsdp", "tp"))])
    tree = {"w": jnp.zeros((8, 4)), "b": jnp.zeros((4,))}
    sharded = shard_tree(tree, mesh, rules)
    assert sharded["w"].sharding.spec == P("fsdp", "tp")
    # rule engine clips/filters: bias replicated
    assert sharded["b"].sharding.is_fully_replicated


def test_rules_portable_across_meshes():
    """The same rule table works on a tp-only mesh (axes filtered)."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from ray_tpu.parallel import llama_rules, make_mesh, shard_tree

    mesh = make_mesh({"tp": 8})  # no fsdp axis
    tree = {"wq": {"kernel": jnp.zeros((16, 8))}}
    sharded = shard_tree(tree, mesh, llama_rules())
    spec = sharded["wq"]["kernel"].sharding.spec
    assert spec == P(None, "tp")


def test_host_collective_group_across_actors(ray_session):
    """gloo-equivalent: 2 CPU actors allreduce through the rendezvous actor."""
    ray = ray_session

    @ray.remote
    class Member:
        def __init__(self, rank, world):
            self.rank, self.world = rank, world

        def _init_collective(self, world_size, rank, backend, group_name):
            from ray_tpu.parallel import collective as col
            col.destroy_collective_group(group_name)
            col.init_collective_group(world_size, rank, backend, group_name)
            return True

        def do_allreduce(self, x):
            from ray_tpu.parallel import collective as col
            return col.allreduce(np.asarray(x, dtype=np.float32), group_name="g2")

        def do_broadcast(self, x):
            from ray_tpu.parallel import collective as col
            return col.broadcast(x if x is not None else None, src_rank=0,
                                 group_name="g2")

    m0, m1 = Member.remote(0, 2), Member.remote(1, 2)
    from ray_tpu.parallel.collective import create_collective_group
    create_collective_group([m0, m1], 2, [0, 1], backend="host", group_name="g2")
    r0 = m0.do_allreduce.remote([1.0, 2.0])
    r1 = m1.do_allreduce.remote([10.0, 20.0])
    out0, out1 = ray.get([r0, r1], timeout=60)
    np.testing.assert_allclose(out0, [11.0, 22.0])
    np.testing.assert_allclose(out1, [11.0, 22.0])


def test_host_p2p_and_routing_bypass_rendezvous(ray_session):
    """VERDICT r4 weak #2: p2p send/recv and routing collectives must not
    funnel payload bytes through the one rendezvous actor. Payloads ride
    the object store (node-to-node direct across hosts); the actor sees
    only ref envelopes — proven by its own byte accounting."""
    ray = ray_session
    MB = 1 << 20

    @ray.remote
    class Member:
        def __init__(self, rank, world):
            self.rank, self.world = rank, world

        def _init_collective(self, world_size, rank, backend, group_name):
            from ray_tpu.parallel import collective as col
            col.destroy_collective_group(group_name)
            col.init_collective_group(world_size, rank, backend, group_name)
            return True

        def exchange(self):
            import numpy as np
            from ray_tpu.parallel import collective as col
            g = col._get("gp2p")
            big = np.full(MB // 4, self.rank + 1, np.float32)  # 1 MB
            if self.rank == 0:
                g.send(big, dst_rank=1)
                got = g.recv(src_rank=1)
            else:
                got = g.recv(src_rank=0)
                g.send(big, dst_rank=0)
            assert got.nbytes == MB and got[0] == 2 - self.rank
            gathered = g.allgather(big)
            assert [int(a[0]) for a in gathered] == [1, 2]
            bcast = g.broadcast(big if self.rank == 0 else None, src_rank=0)
            assert int(bcast[0]) == 1
            mine = g.alltoall([big[: MB // 8], big[: MB // 8]])
            assert len(mine) == 2
            return True

    m0, m1 = Member.remote(0, 2), Member.remote(1, 2)
    from ray_tpu.parallel.collective import create_collective_group
    create_collective_group([m0, m1], 2, [0, 1], backend="host",
                            group_name="gp2p")
    assert all(ray.get([m0.exchange.remote(), m1.exchange.remote()],
                       timeout=120))
    rdv = ray.get_actor("_rtpu_collective_gp2p")
    seen = ray.get(rdv.stats.remote(), timeout=60)
    # ~5 MB of payload moved; the actor must have seen only envelopes
    assert seen["p2p"] < 64 * 1024, seen
    assert seen["collective"] < 64 * 1024, seen


# ------------------------------------------------------------------ pipeline
def test_pipeline_matches_sequential():
    import jax
    import jax.numpy as jnp
    from ray_tpu.parallel.mesh import make_mesh
    from ray_tpu.parallel.pipeline import (make_microbatches, pipeline_apply,
                                           shard_pipeline_params,
                                           stack_stage_params)

    devices = jax.devices()[:4]
    mesh = make_mesh({"pp": 4}, devices=devices)
    S, d = 4, 8
    key = jax.random.PRNGKey(0)
    stage_params = [
        {"w": jax.random.normal(jax.random.fold_in(key, i), (d, d)) / d,
         "b": jnp.ones((d,)) * 0.1}
        for i in range(S)]

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    batch = jax.random.normal(key, (16, d))
    mbs = make_microbatches(batch, 8)  # [8, 2, d]
    stacked = shard_pipeline_params(stack_stage_params(stage_params), mesh)
    out = pipeline_apply(stage_fn, stacked, mbs, mesh)

    # sequential reference
    ref = batch
    for p in stage_params:
        ref = stage_fn(p, ref)
    ref = ref.reshape(8, 2, d)
    import numpy as np
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_single_microbatch():
    import jax
    import jax.numpy as jnp
    from ray_tpu.parallel.mesh import make_mesh
    from ray_tpu.parallel.pipeline import (pipeline_apply,
                                           shard_pipeline_params,
                                           stack_stage_params)

    mesh = make_mesh({"pp": 2}, devices=jax.devices()[:2])
    stages = [{"c": jnp.asarray(1.0)}, {"c": jnp.asarray(10.0)}]

    def stage_fn(p, x):
        return x + p["c"]

    xs = jnp.zeros((1, 4))
    out = pipeline_apply(
        stage_fn, shard_pipeline_params(stack_stage_params(stages), mesh),
        xs, mesh)
    import numpy as np
    np.testing.assert_allclose(np.asarray(out), np.full((1, 4), 11.0))


def test_pipeline_fewer_microbatches_than_stages():
    # M < S: the schedule still runs M+S-1 ticks with the mb index clamped;
    # outputs must match the sequential reference for every microbatch
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ray_tpu.parallel.mesh import make_mesh
    from ray_tpu.parallel.pipeline import (make_microbatches, pipeline_apply,
                                           shard_pipeline_params,
                                           stack_stage_params)

    S, M, d = 4, 2, 8
    mesh = make_mesh({"pp": S}, devices=jax.devices()[:S])
    key = jax.random.PRNGKey(3)
    stage_params = [
        {"w": jax.random.normal(jax.random.fold_in(key, i), (d, d)) / d}
        for i in range(S)]

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"])

    batch = jax.random.normal(key, (M * 2, d))
    mbs = make_microbatches(batch, M)
    out = pipeline_apply(
        stage_fn,
        shard_pipeline_params(stack_stage_params(stage_params), mesh),
        mbs, mesh)
    ref = batch
    for p in stage_params:
        ref = stage_fn(p, ref)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(ref.reshape(M, 2, d)),
                               rtol=1e-5, atol=1e-6)


def test_make_microbatches_remainder_error():
    import jax.numpy as jnp
    import pytest
    from ray_tpu.parallel.pipeline import make_microbatches

    batch = jnp.zeros((10, 4))
    with pytest.raises(ValueError) as ei:
        make_microbatches(batch, 4)
    # the message must carry the offending shapes, not just "bad input"
    msg = str(ei.value)
    assert "10" in msg and "(10, 4)" in msg and "num_microbatches=4" in msg
    with pytest.raises(ValueError, match=">= 1"):
        make_microbatches(batch, 0)
    # exact division still works, including the M == B edge
    assert make_microbatches(batch, 10).shape == (10, 1, 4)
    assert make_microbatches(batch, 2).shape == (2, 5, 4)
