"""runtime_env: env_vars / py_modules / working_dir / pip venv isolation.

Model: python/ray/tests/test_runtime_env.py + runtime_env/pip.py semantics —
a task or actor declares its environment and the cluster builds it (cached by
content hash) before dispatching work to a worker constructed for it.
"""

import os
import sys
import textwrap

import pytest

import ray_tpu
from ray_tpu.exceptions import RuntimeEnvSetupError


def test_env_vars_applied_and_isolated(ray_session):
    ray = ray_session

    @ray.remote
    def read_env():
        return os.environ.get("RT_TEST_VAR")

    # default env: variable absent
    assert ray.get(read_env.remote()) is None
    # runtime_env worker: variable present
    val = ray.get(read_env.options(
        runtime_env={"env_vars": {"RT_TEST_VAR": "hello"}}).remote())
    assert val == "hello"
    # and the default-env worker pool stays clean afterwards
    assert ray.get(read_env.remote()) is None


def test_py_modules_injected(ray_session, tmp_path):
    ray = ray_session
    mod = tmp_path / "rtenv_mod"
    mod.mkdir()
    (mod / "__init__.py").write_text("MAGIC = 1234\n")

    @ray.remote
    def use_module():
        import rtenv_mod
        return rtenv_mod.MAGIC

    with pytest.raises(Exception):
        # not importable without the runtime_env
        ray.get(use_module.remote())
    got = ray.get(use_module.options(
        runtime_env={"py_modules": [str(mod)]}).remote())
    assert got == 1234


def test_working_dir_staged_and_cwd(ray_session, tmp_path):
    ray = ray_session
    wd = tmp_path / "proj"
    wd.mkdir()
    (wd / "data.txt").write_text("payload-42")

    @ray.remote
    def read_rel():
        with open("data.txt") as f:
            return f.read()

    got = ray.get(read_rel.options(
        runtime_env={"working_dir": str(wd)}).remote())
    assert got == "payload-42"


def test_actor_runtime_env(ray_session, tmp_path):
    ray = ray_session
    mod = tmp_path / "rtenv_actor_mod"
    mod.mkdir()
    (mod / "__init__.py").write_text("WHO = 'actor-env'\n")

    @ray.remote
    class EnvActor:
        def who(self):
            import rtenv_actor_mod
            return (rtenv_actor_mod.WHO, os.environ.get("RT_ACTOR_VAR"))

    a = EnvActor.options(runtime_env={
        "py_modules": [str(mod)],
        "env_vars": {"RT_ACTOR_VAR": "set"},
    }).remote()
    assert ray.get(a.who.remote()) == ("actor-env", "set")
    ray.kill(a)


def test_bad_py_modules_fails_task(ray_session):
    ray = ray_session

    @ray.remote
    def f():
        return 1

    ref = f.options(
        runtime_env={"py_modules": ["/nonexistent/path/xyz"]}).remote()
    with pytest.raises(RuntimeEnvSetupError):
        ray.get(ref, timeout=30)


def test_unsupported_key_fails_task(ray_session):
    ray = ray_session

    @ray.remote
    def f():
        return 1

    ref = f.options(runtime_env={"conda": {"name": "nope"}}).remote()
    with pytest.raises(RuntimeEnvSetupError):
        ray.get(ref, timeout=30)


def test_pip_venv_local_package(ray_session, tmp_path):
    """Offline pip: install a local package into the per-env venv and import
    it from a task (no network: --no-index --no-build-isolation)."""
    ray = ray_session
    pkg = tmp_path / "rtenvpip"
    pkg.mkdir()
    (pkg / "pyproject.toml").write_text(textwrap.dedent("""\
        [build-system]
        requires = ["setuptools"]
        build-backend = "setuptools.build_meta"
        [project]
        name = "rtenv-pip-pkg"
        version = "0.0.1"
        [tool.setuptools]
        py-modules = ["rtenv_pip_mod"]
    """))
    (pkg / "rtenv_pip_mod.py").write_text("ANSWER = 4242\n")

    @ray.remote
    def use_pkg():
        import rtenv_pip_mod
        return rtenv_pip_mod.ANSWER, sys.prefix

    ans, prefix = ray.get(use_pkg.options(runtime_env={
        "pip": {"packages": [str(pkg)],
                "pip_install_options": ["--no-index", "--no-build-isolation"]},
    }).remote(), timeout=180)
    assert ans == 4242
    # the task really ran under the per-env venv interpreter
    assert f"{os.sep}runtime_envs{os.sep}" in prefix


def test_edited_py_module_restaged_on_resubmit(ray_session, tmp_path):
    """Editing user code then resubmitting with the SAME runtime_env dict
    must pick up the new content (stat digest folds into the env key)."""
    ray = ray_session
    mod = tmp_path / "rtenv_edit_mod"
    mod.mkdir()
    (mod / "__init__.py").write_text("V = 1\n")

    @ray.remote
    def read_v():
        import rtenv_edit_mod
        return rtenv_edit_mod.V

    renv = {"py_modules": [str(mod)]}  # reused dict, like real user code
    assert ray.get(read_v.options(runtime_env=renv).remote()) == 1
    (mod / "__init__.py").write_text("V = 2\n")
    assert ray.get(read_v.options(runtime_env=renv).remote()) == 2
