"""C++ scheduler ready-queue (src/sched_queue.cpp) vs the Python oracle:
randomized equivalence, FIFO fairness, pool accounting, and the O(signatures)
scaling claim. (Ref contrast: raylet ClusterTaskManager per-class queues.)"""

import random
import time

import pytest

from ray_tpu._native.schedq import PyReadyQueue, ReadyQueue


def _pair():
    try:
        cq = ReadyQueue()
    except RuntimeError as e:
        pytest.skip(f"native build unavailable: {e}")
    return cq, PyReadyQueue()


def test_fifo_fairness_across_signatures():
    cq, pq = _pair()
    for q in (cq, pq):
        q.set_pool(0, {"CPU": 4.0})
        s_small = q.register_sig(0, {"CPU": 1.0})
        s_big = q.register_sig(0, {"CPU": 3.0})
        q.push(1, s_big)
        q.push(2, s_small)
        # both fit; seq 1 (earlier) must win even though its demand is larger
        seq, sig = q.next_dispatchable()
        assert (seq, sig) == (1, s_big)
        q.adjust(0, {"CPU": 3.0}, -1)
        q.pop_task(1)
        # only 1 CPU left: the big sig no longer fits, small does
        seq, sig = q.next_dispatchable()
        assert (seq, sig) == (2, s_small)
    cq.close()


def test_mask_and_remove():
    cq, pq = _pair()
    for q in (cq, pq):
        q.set_pool(0, {"CPU": 2.0})
        a = q.register_sig(0, {"CPU": 1.0})
        b = q.register_sig(0, {"CPU": 1.0})
        q.push(10, a)
        q.push(11, b)
        seq, _ = q.next_dispatchable(sig_mask=[False, True])
        assert seq == 11
        q.remove(11)  # cancelled while queued
        seq, _ = q.next_dispatchable(sig_mask=[False, True])
        assert seq == -1
        assert q.pending() == 1
        seq, _ = q.next_dispatchable()
        assert seq == 10
    cq.close()


def test_randomized_equivalence():
    cq, pq = _pair()
    rng = random.Random(0)
    resources = ["CPU", "TPU", "mem"]
    for q in (cq, pq):
        q.set_pool(0, {"CPU": 8.0, "TPU": 2.0, "mem": 100.0})
        q.set_pool(1, {"CPU": 2.0})
    sigs = []
    for _ in range(6):
        pool = rng.choice([0, 0, 0, 1])
        need = {r: rng.choice([0.5, 1.0, 2.0])
                for r in rng.sample(resources if pool == 0 else ["CPU"],
                                    1 if pool else rng.randint(1, 3))}
        sigs.append((cq.register_sig(pool, need), pq.register_sig(pool, need),
                     pool, need))
    seq = 0
    live = {}
    for step in range(500):
        op = rng.random()
        if op < 0.45:
            i = rng.randrange(len(sigs))
            seq += 1
            cq.push(seq, sigs[i][0])
            pq.push(seq, sigs[i][1])
            live[seq] = i
        elif op < 0.55 and live:
            victim = rng.choice(list(live))
            del live[victim]
            cq.remove(victim)
            pq.remove(victim)
        else:
            got_c = cq.next_dispatchable()
            got_p = pq.next_dispatchable()
            assert got_c[0] == got_p[0], (step, got_c, got_p)
            if got_c[0] != -1:
                i = live.pop(got_c[0])
                _, _, pool, need = sigs[i]
                for q in (cq, pq):
                    q.adjust(pool, need, -1)
                    q.pop_task(got_c[0])
                # release later with 30% probability to vary pool state
                if rng.random() < 0.7:
                    for q in (cq, pq):
                        q.adjust(pool, need, +1)
        assert cq.pending() == pq.pending(), step
    cq.close()


def test_scaling_scan_is_per_signature_not_per_task():
    """10k queued tasks in 3 signatures: next_dispatchable stays ~O(sigs)."""
    try:
        q = ReadyQueue()
    except RuntimeError as e:
        pytest.skip(f"native build unavailable: {e}")
    q.set_pool(0, {"CPU": 1.0})
    sigs = [q.register_sig(0, {"CPU": 1.0}) for _ in range(3)]
    for i in range(10_000):
        q.push(i, sigs[i % 3])
    t0 = time.perf_counter()
    for _ in range(1_000):
        seq, _sig = q.next_dispatchable()
        assert seq != -1
    dt = time.perf_counter() - t0
    # 1000 scans over 10k pending tasks in well under a second (the Python
    # deque rescan was ~10k iterations per scan)
    assert dt < 1.0, dt
    q.close()


def test_missing_pool_never_fits_both_backends():
    cq, pq = _pair()
    for q in (cq, pq):
        q.set_pool(0, {"CPU": 1.0})
        s_zero = q.register_sig(99, {})      # pool 99 never registered
        s_cpu = q.register_sig(0, {"CPU": 1.0})
        q.push(1, s_zero)
        q.push(2, s_cpu)
        seq, sig = q.next_dispatchable()
        assert (seq, sig) == (2, s_cpu)      # zero-demand sig must NOT win
        q.remove_pool(0)
        seq, _ = q.next_dispatchable()
        assert seq == -1
    cq.close()


def test_randomized_schedule_batch_equivalence():
    """ISSUE 17: the BATCHED native pass (sq_schedule — feasibility,
    idle-worker match, claim, all under one GIL release) against the Python
    oracle. Seeded submit / complete / worker-death sequences must produce
    identical decision lists, identical barrier points (mode-2 actor
    creations), identical pool accounting, and identical pending counts."""
    cq, pq = _pair()
    rng = random.Random(17)
    pools = {0: {"CPU": 8.0, "TPU": 2.0}, 1: {"CPU": 4.0}}
    for q in (cq, pq):
        for pid, avail in pools.items():
            q.set_pool(pid, dict(avail))
    # sig -> (id, pool, need, idle bucket, mode); one barrier signature
    # (mode 2: actor creation the Python side must handle itself)
    sigs = []
    for k in range(8):
        pool = rng.choice([0, 0, 1])
        need = {"CPU": rng.choice([0.5, 1.0, 2.0])}
        if pool == 0 and rng.random() < 0.4:
            need["TPU"] = 1.0
        cs = cq.register_sig(pool, need)
        ps = pq.register_sig(pool, need)
        assert cs == ps
        sigs.append((cs, pool, need, pool, 2 if k == 5 else 1))
    seq = 0
    idle = [3, 2]
    running = []  # (seq, sig index) holding a claim + a worker
    for step in range(400):
        r = rng.random()
        if r < 0.45:
            i = rng.randrange(len(sigs))
            seq += 1
            cq.push(seq, sigs[i][0])
            pq.push(seq, sigs[i][0])
        elif r < 0.60 and running:
            # completion: claim released, the worker returns to its bucket
            _s, i = running.pop(rng.randrange(len(running)))
            _, pool, need, bucket, _ = sigs[i]
            for q in (cq, pq):
                q.adjust(pool, need, +1)
            idle[bucket] += 1
        elif r < 0.65:
            # node death: pool 1 vanishes wholesale (its running tasks and
            # idle workers die with it), then a replacement registers with
            # full capacity — both backends see the identical sequence
            for q in (cq, pq):
                q.remove_pool(1)
            running = [(s, i) for (s, i) in running if sigs[i][1] != 1]
            idle[1] = 0
            for q in (cq, pq):
                q.set_pool(1, dict(pools[1]))
            idle[1] = 2
        else:
            modes = [m for (_, _, _, _, m) in sigs]
            buckets = [-1 if m == 2 else b for (_, _, _, b, m) in sigs]
            got_c = cq.schedule_batch(modes, buckets, list(idle))
            got_p = pq.schedule_batch(modes, buckets, list(idle))
            assert got_c == got_p, (step, got_c, got_p)
            decisions, bsig, bseq = got_c
            for s, g in decisions:
                idle[sigs[g][3]] -= 1
                running.append((s, g))
            if bsig != -1:
                # the controller pops + claims barrier tasks in Python (a
                # creation dispatches to a freshly spawned worker, so no
                # idle decrement) — mirror that on both backends
                _, pool, need, _bucket, _ = sigs[bsig]
                for q in (cq, pq):
                    q.pop_task(bseq)
                    q.adjust(pool, need, -1)
                running.append((bseq, bsig))
        for pid in pools:
            for res in ("CPU", "TPU"):
                assert abs(cq.pool_avail(pid, res)
                           - pq.pool_avail(pid, res)) < 1e-6, (step, pid, res)
        assert cq.pending() == pq.pending(), step
    cq.close()
