"""DreamerV3 (VERDICT r4 missing #5; ref: rllib/algorithms/dreamerv3/)."""

import numpy as np
import pytest


def test_symlog_twohot_roundtrip():
    import jax.numpy as jnp
    from ray_tpu.rllib.algorithms.dreamerv3 import (_bins, symexp, symlog,
                                                    twohot)
    x = jnp.asarray([-100.0, -1.5, 0.0, 0.3, 7.0, 500.0])
    np.testing.assert_allclose(symexp(symlog(x)), x, rtol=1e-5, atol=1e-5)
    # twohot of symlog decodes back through the bin expectation
    bins = _bins()
    enc = twohot(symlog(x), bins)
    np.testing.assert_allclose(np.sum(np.asarray(enc), -1), 1.0, atol=1e-5)
    dec = symexp(jnp.sum(enc * bins, -1))
    np.testing.assert_allclose(dec, x, rtol=2e-2, atol=1e-2)


def test_sequence_replay_windows():
    from ray_tpu.rllib.algorithms.dreamerv3 import _SequenceReplay
    rep = _SequenceReplay(capacity=100, seed=0)
    rows = {"obs": np.arange(50, dtype=np.float32)[:, None],
            "is_first": np.zeros(50, np.float32)}
    rep.add(rows)
    assert len(rep) == 50
    s = rep.sample(4, 8)
    assert s["obs"].shape == (4, 8, 1)
    # windows are contiguous runs of the flat store
    for b in range(4):
        d = np.diff(s["obs"][b, :, 0])
        np.testing.assert_allclose(d, 1.0)


def test_sequence_replay_never_straddles_ring_seam():
    """After wraparound, windows must stay contiguous in TIME — a raw-index
    window crossing the write pointer would stitch the newest rows onto the
    oldest (r5 review finding)."""
    from ray_tpu.rllib.algorithms.dreamerv3 import _SequenceReplay
    rep = _SequenceReplay(capacity=32, seed=0)
    for start in range(0, 80, 10):   # 80 rows through a 32-slot ring
        rep.add({"obs": np.arange(start, start + 10,
                                  dtype=np.float32)[:, None]})
    assert len(rep) == 32
    s = rep.sample(64, 6)
    for b in range(64):
        d = np.diff(s["obs"][b, :, 0])
        np.testing.assert_allclose(d, 1.0, err_msg=str(s["obs"][b, :, 0]))


@pytest.mark.parametrize("env", ["CartPole-v1", "Pendulum-v1"])
def test_dreamerv3_trains(env):
    from ray_tpu.rllib import DreamerV3Config
    algo = (DreamerV3Config()
            .environment(env)
            .training(deter=64, stoch=4, classes=4,
                      model={"hiddens": (64, 64)},
                      batch_size_B=4, batch_length_T=12, horizon=5,
                      rollout_fragment_length=64,
                      num_steps_sampled_before_learning_starts=128)
            .debugging(seed=3)
            .build())
    learned = False
    for _ in range(4):
        result = algo.train()
        assert result["num_env_steps_sampled_this_iter"] == 64
        if "learner" in result:
            learned = True
            lm = result["learner"]
            for k in ("wm_loss", "wm_recon", "wm_kl_dyn", "actor_loss",
                      "critic_loss", "imagined_return"):
                assert np.isfinite(lm[k]), (k, lm)
            assert lm["return_scale"] > 0
    assert learned


def test_dreamerv3_world_model_learns_dynamics():
    """On a deterministic env the recon loss must drop markedly as the RSSM
    fits the transition structure."""
    from ray_tpu.rllib import DreamerV3Config
    algo = (DreamerV3Config()
            .environment("CartPole-v1")
            .training(deter=64, stoch=4, classes=4,
                      model={"hiddens": (64, 64)},
                      batch_size_B=8, batch_length_T=16, horizon=5,
                      rollout_fragment_length=128,
                      num_steps_sampled_before_learning_starts=128,
                      train_intensity=8)
            .debugging(seed=1)
            .build())
    first, last = None, None
    for _ in range(6):
        result = algo.train()
        lm = result.get("learner")
        if lm:
            if first is None:
                first = lm["wm_recon"]
            last = lm["wm_recon"]
    assert first is not None
    assert last < first * 0.7, (first, last)


def test_dreamerv3_weight_roundtrip():
    from ray_tpu.rllib import DreamerV3Config
    import jax
    mk = lambda seed: (DreamerV3Config().environment("CartPole-v1")
                       .training(deter=32, stoch=4, classes=4,
                                 model={"hiddens": (32,)},
                                 rollout_fragment_length=8,
                                 num_steps_sampled_before_learning_starts=4,
                                 batch_size_B=2, batch_length_T=6, horizon=3)
                       .debugging(seed=seed).build())
    a, b = mk(0), mk(1)
    a.train()
    b.set_weights(a.get_weights())
    la = jax.tree_util.tree_leaves(a.weights["wm"])
    lb = jax.tree_util.tree_leaves(b.weights["wm"])
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
