"""Serve tests (SURVEY.md §4): batching coalescing, router choice,
autoscale decisions, deployment e2e + composition + streaming."""

import asyncio

import numpy as np
import pytest

from ray_tpu import serve
from ray_tpu.serve.controller import decide_num_replicas
from ray_tpu.serve.deployment import AutoscalingConfig


# ------------------------------------------------------------------ pure units
def test_autoscale_decision_math():
    auto = AutoscalingConfig(min_replicas=1, max_replicas=10,
                             target_ongoing_requests=2.0)
    assert decide_num_replicas(0, 3, auto) == 1      # idle → min
    assert decide_num_replicas(6, 3, auto) == 3      # 6/2 = 3 → hold
    assert decide_num_replicas(20, 3, auto) == 10    # clamp to max
    assert decide_num_replicas(5, 2, auto) == 3      # ceil(5/2)
    assert decide_num_replicas(100, 0, auto) == 10   # demand from zero
    zero = AutoscalingConfig(min_replicas=0, max_replicas=5,
                             target_ongoing_requests=2.0)
    assert decide_num_replicas(0, 0, zero) == 0      # no flap at zero
    assert decide_num_replicas(0, 1, zero) == 0      # idle scales to zero


def test_batch_coalesces():
    from ray_tpu.serve.batching import batch

    calls = []

    @batch(max_batch_size=4, batch_wait_timeout_s=0.05)
    async def handler(items):
        calls.append(list(items))
        return [i * 10 for i in items]

    async def main():
        return await asyncio.gather(*[handler(i) for i in range(4)])

    out = asyncio.run(main())
    assert out == [0, 10, 20, 30]
    assert len(calls) == 1 and sorted(calls[0]) == [0, 1, 2, 3]


def test_batch_timeout_flush():
    from ray_tpu.serve.batching import batch

    calls = []

    @batch(max_batch_size=100, batch_wait_timeout_s=0.02)
    async def handler(items):
        calls.append(list(items))
        return [i + 1 for i in items]

    async def main():
        return await asyncio.gather(handler(1), handler(2))

    assert sorted(asyncio.run(main())) == [2, 3]
    assert len(calls) == 1  # flushed by timer, not size


def test_batch_error_propagates():
    from ray_tpu.serve.batching import batch

    @batch(max_batch_size=2, batch_wait_timeout_s=0.01)
    async def handler(items):
        raise RuntimeError("bad batch")

    async def main():
        with pytest.raises(RuntimeError, match="bad batch"):
            await asyncio.gather(handler(1), handler(2))

    asyncio.run(main())


def test_router_prefers_less_loaded():
    from ray_tpu.serve.handle import DeploymentHandle

    h = DeploymentHandle("d")
    h._replicas = ["r0", "r1", "r2"]
    h._inflight = {0: 10, 1: 0, 2: 10}
    picks = [h._pick_replica() for _ in range(50)]
    # p2c: replica 1 wins every comparison it appears in (~2/3 of draws)
    assert picks.count(1) > 20


# ------------------------------------------------------------------ e2e actors
@pytest.fixture(scope="module")
def serve_session():
    import ray_tpu
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    serve.shutdown()


def test_deployment_end_to_end(serve_session):
    @serve.deployment(num_replicas=2)
    class Doubler:
        def __call__(self, x):
            return x * 2

        def plus(self, x, y=0):
            return x + y

    handle = serve.run(Doubler.bind(), name="e2e")
    assert handle.remote(21).result(timeout_s=60) == 42
    assert handle.options(method_name="plus").remote(1, y=2).result(
        timeout_s=60) == 3
    # attribute sugar routes to the method
    assert handle.plus.remote(5, y=5).result(timeout_s=60) == 10
    serve.delete("e2e")


def test_composition_handle_in_deployment(serve_session):
    @serve.deployment
    class Adder:
        def __init__(self, offset):
            self.offset = offset

        def __call__(self, x):
            return x + self.offset

    @serve.deployment
    class Ingress:
        def __init__(self, adder):
            self.adder = adder

        async def __call__(self, x):
            resp = self.adder.remote(x)
            return (await resp) * 10

    handle = serve.run(Ingress.bind(Adder.bind(7)), name="comp")
    assert handle.remote(3).result(timeout_s=60) == 100
    serve.delete("comp")


def test_streaming_deployment(serve_session):
    @serve.deployment
    class Streamer:
        def stream(self, n):
            for i in range(n):
                yield i * i

    handle = serve.run(Streamer.bind(), name="stream")
    sh = handle.options(method_name="stream", stream=True)
    out = list(sh.remote(4))
    assert out == [0, 1, 4, 9]
    serve.delete("stream")


def test_function_deployment_and_user_config(serve_session):
    @serve.deployment
    def square(x):
        return x * x

    handle = serve.run(square.bind(), name="fn")
    assert handle.remote(9).result(timeout_s=60) == 81
    serve.delete("fn")


def test_batched_deployment(serve_session):
    @serve.deployment
    class Batched:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.1)
        async def handle_batch(self, items):
            self.batch_sizes.append(len(items))
            return [i + 100 for i in items]

        async def __call__(self, x):
            return await self.handle_batch(x)

        def get_sizes(self):
            return self.batch_sizes

    handle = serve.run(Batched.bind(), name="batched")
    responses = [handle.remote(i) for i in range(8)]
    results = sorted(r.result(timeout_s=60) for r in responses)
    assert results == [100 + i for i in range(8)]
    sizes = handle.get_sizes.remote().result(timeout_s=60)
    assert max(sizes) > 1, f"no coalescing happened: {sizes}"
    serve.delete("batched")


def test_autoscaling_scales_up(serve_session):
    import time

    @serve.deployment(autoscaling_config={"min_replicas": 1,
                                          "max_replicas": 3,
                                          "target_ongoing_requests": 1})
    class Slow:
        async def __call__(self):
            await asyncio.sleep(1.0)
            return "ok"

    handle = serve.run(Slow.bind(), name="auto", _autoscale_interval_s=0.3)
    responses = [handle.remote() for _ in range(6)]
    deadline = time.time() + 30
    n = 1
    while time.time() < deadline:
        from ray_tpu.serve.controller import get_controller
        import ray_tpu
        n = ray_tpu.get(get_controller().num_replicas.remote("auto", "Slow"))
        if n > 1:
            break
        time.sleep(0.3)
    assert n > 1, "autoscaler never scaled up"
    for r in responses:
        assert r.result(timeout_s=60) == "ok"
    serve.delete("auto")


# ------------------------------------------------------------------ LLM serving
def test_llm_continuous_batching():
    """Two requests admitted at different times share the jitted decode."""
    from ray_tpu.serve.llm import LLMConfig, LLMServer

    srv = LLMServer(LLMConfig(preset="tiny", max_batch_slots=2,
                              max_seq_len=64))

    async def main():
        r1 = asyncio.create_task(srv.generate([1, 2, 3], max_tokens=6))
        await asyncio.sleep(0.05)  # r2 joins mid-flight
        r2 = asyncio.create_task(srv.generate([4, 5], max_tokens=4))
        out1, out2 = await asyncio.gather(r1, r2)
        return out1, out2

    out1, out2 = asyncio.run(main())
    assert len(out1["tokens"]) == 6
    assert len(out2["tokens"]) == 4
    assert all(0 <= t < 256 for t in out1["tokens"])
    assert out1["ttft_s"] > 0
    assert srv.stats()["requests"] == 2
    assert srv.stats()["active"] == 0


def test_llm_greedy_deterministic():
    """Same prompt twice → same greedy tokens (decode == decode)."""
    from ray_tpu.serve.llm import LLMConfig, LLMServer

    srv = LLMServer(LLMConfig(preset="tiny", max_batch_slots=2,
                              max_seq_len=64, temperature=0.0))

    async def gen():
        return await srv.generate([7, 8, 9, 10], max_tokens=5)

    a = asyncio.run(gen())
    b = asyncio.run(gen())
    assert a["tokens"] == b["tokens"]


def test_llm_streaming():
    from ray_tpu.serve.llm import LLMConfig, LLMServer

    srv = LLMServer(LLMConfig(preset="tiny", max_batch_slots=2,
                              max_seq_len=64))

    async def main():
        toks = []
        async for t in srv.generate_stream([3, 1, 4], max_tokens=5):
            toks.append(t)
        return toks

    toks = asyncio.run(main())
    assert len(toks) == 5


def test_redeploy_same_app(serve_session):
    """serve.run twice on the same app must replace replicas, not crash."""
    @serve.deployment
    class V:
        def __init__(self, v):
            self.v = v

        def __call__(self):
            return self.v

    h = serve.run(V.bind(1), name="redeploy")
    assert h.remote().result(timeout_s=60) == 1
    h2 = serve.run(V.bind(2), name="redeploy")
    assert h2.remote().result(timeout_s=60) == 2
    serve.delete("redeploy")


def test_llm_paged_matches_dense_and_frees_pages():
    """Paged-KV mode (ops/paged_attention block tables) produces the SAME
    greedy tokens as the dense-slot cache, pages are reserved at admission
    and fully returned after completion."""
    from ray_tpu.serve.llm import LLMConfig, LLMServer

    # f32 end to end: the two attention implementations differ only by
    # reduction order, so greedy argmax stays tie-free and comparable
    common = dict(preset="tiny", max_batch_slots=2, max_seq_len=64,
                  temperature=0.0, seed=7, param_dtype="float32",
                  dtype="float32")
    dense = LLMServer(LLMConfig(**common))
    paged = LLMServer(LLMConfig(**common, paged=True, page_size=8),
                      params=dense.params)

    async def both(srv):
        r1 = asyncio.create_task(srv.generate([1, 2, 3], max_tokens=6))
        await asyncio.sleep(0.05)  # second request joins mid-decode
        r2 = asyncio.create_task(srv.generate([9, 8, 7, 6, 5], max_tokens=5))
        return await asyncio.gather(r1, r2)

    d1, d2 = asyncio.run(both(dense))
    p1, p2 = asyncio.run(both(paged))
    assert p1["tokens"] == d1["tokens"]
    assert p2["tokens"] == d2["tokens"]
    st = paged.stats()
    assert st["pages_in_use"] == 0 and st["active"] == 0


def test_llm_paged_pool_backpressure():
    """A pool too small for both requests serializes them instead of
    corrupting pages: the second admits only after the first frees."""
    from ray_tpu.serve.llm import LLMConfig, LLMServer

    # each request needs ceil((3+12)/8)=2 pages; pool holds 2 usable pages
    srv = LLMServer(LLMConfig(preset="tiny", max_batch_slots=2,
                              max_seq_len=64, paged=True, page_size=8,
                              num_pages=3))

    async def main():
        r1 = asyncio.create_task(srv.generate([1, 2, 3], max_tokens=12))
        await asyncio.sleep(0.05)
        r2 = asyncio.create_task(srv.generate([4, 5, 6], max_tokens=12))
        return await asyncio.gather(r1, r2)

    out1, out2 = asyncio.run(main())
    assert len(out1["tokens"]) == 12 and len(out2["tokens"]) == 12
    st = srv.stats()
    assert st["pages_in_use"] == 0 and st["requests"] == 2


def test_llm_paged_infeasible_request_raises():
    """A request that can never fit the page pool fails fast, not hangs."""
    from ray_tpu.serve.llm import LLMConfig, LLMServer

    srv = LLMServer(LLMConfig(preset="tiny", max_batch_slots=2,
                              max_seq_len=64, paged=True, page_size=8,
                              num_pages=3))

    async def main():
        await srv.generate(list(range(30)), max_tokens=30)

    with pytest.raises(ValueError, match="KV pages"):
        asyncio.run(main())


def test_llm_chunked_prefill_keeps_decode_flowing():
    """A long prompt must not stall active streams: its prefill runs in
    chunks interleaved with decode ticks (VERDICT r3 weak #6). Structural
    check: the short request's stream keeps producing tokens BETWEEN the
    long request's admission and its first token."""
    from ray_tpu.serve.llm import LLMConfig, LLMServer

    srv = LLMServer(LLMConfig(preset="tiny", max_batch_slots=2,
                              max_seq_len=640, prefill_chunk=32))
    long_prompt = list(range(1, 200))  # 199 tokens -> 7 chunks of 32

    async def main():
        tokens_before_long_first = []
        long_first = asyncio.Event()

        async def short_stream():
            n = 0
            async for _t in srv.generate_stream([1, 2, 3], max_tokens=400):
                n += 1
                if not long_first.is_set():
                    tokens_before_long_first.append(n)
            return n

        async def long_req():
            await asyncio.sleep(0.2)  # let the short stream get going
            mark = len(tokens_before_long_first)
            out = await srv.generate(long_prompt, max_tokens=4)
            long_first.set()
            return out, mark

        s_task = asyncio.create_task(short_stream())
        (out, mark) = (await long_req())
        n_total = await s_task
        return out, mark, tokens_before_long_first, n_total

    out, mark, before, n_total = asyncio.run(main())
    assert len(out["tokens"]) == 4
    # the short stream advanced during the long prefill: with 7 chunks the
    # engine must have run >= 5 decode ticks in between (3x slack for the
    # 1-core box: each tick = one [B,1] forward, each chunk = one [1,32])
    produced_during_prefill = (before[-1] if before else 0) - mark
    assert produced_during_prefill >= 5, (mark, before[-12:], n_total)


def test_replica_context_and_app_handle(serve_session):
    """get_replica_context inside a replica + get_app_handle routing to
    the app's ingress (ref: serve.get_replica_context/get_app_handle)."""
    import ray_tpu
    from ray_tpu import serve

    @serve.deployment
    class WhoAmI:
        def __init__(self):
            # callable in __init__ already (context set before user ctor)
            self.ctx = serve.get_replica_context()

        def __call__(self):
            ctx = serve.get_replica_context()
            return (ctx.app_name, ctx.deployment, ctx.replica_tag,
                    self.ctx.replica_tag)

    serve.run(WhoAmI.bind(), name="whoami")
    h = serve.get_app_handle("whoami")
    app, dep, tag, ctor_tag = h.remote().result()
    assert app == "whoami" and dep == "WhoAmI"
    assert tag.startswith("WhoAmI#") and ctor_tag == tag
    with pytest.raises(ValueError, match="no running serve application"):
        serve.get_app_handle("nope")
    with pytest.raises(RuntimeError, match="replica"):
        serve.get_replica_context()   # driver side: not in a replica
    serve.delete("whoami")


def test_run_many_http_options_shutdown_async(serve_session):
    import asyncio

    import ray_tpu
    from ray_tpu import serve

    @serve.deployment
    def alpha():
        return "a"

    @serve.deployment
    def beta():
        return "b"

    h1, h2 = serve.run_many([("many_a", alpha.bind()),
                             ("many_b", beta.bind())])
    assert h1.remote().result() == "a"
    assert h2.remote().result() == "b"
    port = serve.start(http_options=serve.HTTPOptions(port=0))
    assert isinstance(port, int) and port > 0

    async def drive():
        await serve.shutdown_async()
    asyncio.run(drive())
    # everything torn down: a fresh status() finds no apps
    assert serve.status() == {}
