"""Cross-host control plane (model: python/ray/tests/test_multi_node.py).

Each test runs a DRIVER SUBPROCESS that becomes a cluster head
(init(cluster_port=0)) and spawns a worker-node agent subprocess
(python -m ray_tpu._private.node_main) — two controllers, two shm arenas,
one cluster. The drivers assert head↔node behavior: registration,
placement (custom resource / NodeAffinity / SPREAD / overflow), dep
shipping, lazy result pulls, remote actors, and node-death failover.
"""

import os
import subprocess
import sys
import textwrap

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PRELUDE = textwrap.dedent("""
    import json, os, signal, subprocess, sys, time
    import numpy as np
    import ray_tpu as ray

    ray.init(num_cpus=2, cluster_port=0)
    addr = ray.cluster_address()
    assert addr and ":" in addr, addr
    env = dict(os.environ)
    env.pop("RAY_TPU_ARENA", None)   # the node is its own session
    env.pop("RAY_TPU_ADDRESS", None)
    node_proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.node_main",
         "--address", addr, "--num-cpus", "2",
         "--resources", '{"worker_node": 1}'],
        env=env, stdin=subprocess.DEVNULL, start_new_session=True)

    def wait_for(pred, timeout=60, msg="condition"):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if pred():
                return
            time.sleep(0.2)
        raise TimeoutError("timed out waiting for " + msg)

    wait_for(lambda: len(ray.nodes()) == 2, 60, "node registration")

    def node_id_of():
        for row in ray.nodes():
            if row["resources"].get("worker_node"):
                return row["node_id"]
        raise AssertionError("worker node not registered")
""")

_EPILOGUE = textwrap.dedent("""
    if node_proc.poll() is None:
        os.killpg(node_proc.pid, signal.SIGKILL)
        node_proc.wait(timeout=10)
    ray.shutdown()
    print("CLUSTER_TEST_OK", flush=True)
""")


def _run_driver(body: str, timeout=240):
    script = _PRELUDE + textwrap.dedent(body) + _EPILOGUE
    from ray_tpu.util.tpu import scrub_accel_env
    # scrub the accelerator-plugin env (PALLAS_AXON_*): the driver
    # subprocess compiles jax on CPU, and the image's sitecustomize
    # plugin hook hangs first compile whenever the TPU relay is wedged
    # (observed r5: this test timed out for exactly that reason while
    # passing with a clean PYTHONPATH)
    env = scrub_accel_env(dict(os.environ))
    env.pop("RAY_TPU_ARENA", None)
    env.pop("RAY_TPU_ADDRESS", None)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, "-c", script], env=env,
                       capture_output=True, text=True, timeout=timeout)
    assert r.returncode == 0, f"driver failed\n--- stdout\n{r.stdout}\n--- stderr\n{r.stderr[-12000:]}"
    assert "CLUSTER_TEST_OK" in r.stdout


def test_cluster_placement_and_objects():
    """Registration, cluster resources, custom-resource + NodeAffinity
    placement, lazy pull of a large remote result, dep shipping head→node,
    SPREAD across hosts, DEFAULT overflow when the head is full."""
    _run_driver("""
    rows = ray.nodes()
    assert sum(1 for r in rows if r.get("is_head")) == 1
    assert ray.cluster_resources().get("CPU") == 4.0
    assert ray.cluster_resources().get("worker_node") == 1.0

    # custom resource: must run on the node (worker's parent == node agent)
    @ray.remote(resources={"worker_node": 0.1})
    def where():
        return os.getppid()
    assert ray.get(where.remote(), timeout=120) == node_proc.pid

    # hard NodeAffinity to the node
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy
    nid = node_id_of()

    @ray.remote
    def where2():
        return os.getppid()
    strat = NodeAffinitySchedulingStrategy(node_id=nid, soft=False)
    assert ray.get(where2.options(scheduling_strategy=strat).remote(),
                   timeout=120) == node_proc.pid

    # hard affinity to a nonexistent node fails fast
    bad = NodeAffinitySchedulingStrategy(node_id="node-nope", soft=False)
    try:
        ray.get(where2.options(scheduling_strategy=bad).remote(), timeout=30)
        raise SystemExit("expected hard-affinity failure")
    except Exception as e:
        assert "not alive" in str(e), e

    # large result: bytes stay on the node until this get pulls them
    @ray.remote(resources={"worker_node": 0.1})
    def big():
        return np.arange(300_000, dtype=np.int64)
    out = ray.get(big.remote(), timeout=120)
    assert out.shape == (300_000,) and int(out[12345]) == 12345

    # dep shipping: a large driver-put array consumed on the node
    x = np.random.default_rng(0).standard_normal(200_000)
    ref = ray.put(x)

    @ray.remote(resources={"worker_node": 0.1})
    def total(a):
        return float(a.sum())
    assert abs(ray.get(total.remote(ref), timeout=120) - float(x.sum())) < 1e-6

    # chained refs across hosts: node-produced ref consumed by a head task
    @ray.remote(resources={"worker_node": 0.1})
    def produce():
        return np.ones(100_000)

    @ray.remote(num_cpus=0.1)
    def consume(a):
        return float(a.sum())
    assert ray.get(consume.remote(produce.remote()), timeout=120) == 100_000.0

    # SPREAD reaches both hosts
    @ray.remote(num_cpus=0.1)
    def where3():
        return os.getppid()
    hosts = set(ray.get([where3.options(scheduling_strategy="SPREAD").remote()
                         for _ in range(8)], timeout=120))
    assert len(hosts) == 2, hosts

    # DEFAULT overflow: 4 concurrent 1-cpu holds over 2+2 cpus overlap
    @ray.remote(num_cpus=1)
    def hold():
        time.sleep(1.5)
        return os.getppid()
    t0 = time.time()
    hosts = ray.get([hold.remote() for _ in range(4)], timeout=120)
    elapsed = time.time() - t0
    assert len(set(hosts)) == 2, hosts
    assert elapsed < 30, elapsed  # sanity: they at least overlapped somewhat
    """)


def test_cluster_remote_actors_and_failover():
    """Remote actor lifecycle (create/mutate/ship-ref/kill), infeasible
    demand spanning the cluster, and node-death failover: in-flight task
    retries on the head, remote objects reconstruct from lineage, the dead
    node leaves nodes()."""
    _run_driver("""
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy
    nid = node_id_of()

    @ray.remote
    class Acc:
        def __init__(self):
            self.vals = []
        def add(self, v):
            self.vals.append(float(np.asarray(v).sum()))
            return len(self.vals)
        def host(self):
            return os.getppid()
        def total(self):
            return sum(self.vals)

    a = Acc.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=nid, soft=False)).remote()
    assert ray.get(a.host.remote(), timeout=120) == node_proc.pid
    assert ray.get(a.add.remote(1.0), timeout=60) == 1
    big = ray.put(np.ones(100_000))
    assert ray.get(a.add.remote(big), timeout=60) == 2
    assert ray.get(a.total.remote(), timeout=60) == 1.0 + 100_000.0

    ray.kill(a)
    try:
        ray.get(a.total.remote(), timeout=60)
        raise SystemExit("expected ActorDiedError")
    except ray.exceptions.ActorDiedError:
        pass

    # a 3-cpu demand fits neither host alone; it queues (feasible: the node
    # could host it if sized up) rather than failing — here we only check
    # the 2-cpu per-host demand fails nowhere and a >cluster demand fails
    @ray.remote(num_cpus=2)
    def two():
        return "ok"
    assert ray.get(two.remote(), timeout=120) == "ok"

    # node-produced object survives node death via lineage reconstruction
    @ray.remote(resources={"worker_node": 0.1}, max_retries=2)
    def produce():
        return np.full(120_000, 7.0)
    ref = produce.remote()
    # wait until the result is registered (remote location) but NOT pulled
    wait_for(lambda: ray.wait([ref], num_returns=1, timeout=0.1)[0] == [ref],
             120, "remote result ready")

    os.killpg(node_proc.pid, signal.SIGKILL)
    node_proc.wait(timeout=15)
    wait_for(lambda: len(ray.nodes()) == 1, 60, "node removal")

    # the bytes lived only on the dead node: get() must reconstruct via
    # lineage. The task demands a worker_node resource that no longer
    # exists, so reconstruction correctly FAILS as infeasible-now — use a
    # second, head-runnable producer for the success path:
    @ray.remote(max_retries=2)
    def produce2():
        return np.full(50_000, 3.0)
    ref2 = produce2.remote()
    assert float(ray.get(ref2, timeout=120).sum()) == 150000.0

    # cluster totals shrink back to the head
    assert ray.cluster_resources().get("CPU") == 2.0
    assert ray.cluster_resources().get("worker_node") is None
    """)


def test_autoscaler_node_provider():
    """request_resources beyond the cluster's capacity launches worker
    nodes through the NodeProvider seam; they register and become
    schedulable (VERDICT r3 item 10)."""
    _run_driver("""
    from ray_tpu.autoscaler import sdk, SubprocessNodeProvider

    provider = SubprocessNodeProvider(cpus_per_node=2.0,
                                      extra_resources={"provider_node": 1})
    sdk.set_node_provider(provider, max_nodes=2)

    # head has 2 CPUs (+ the manual node's 2): ask for 8 → 2 launches
    out = sdk.request_resources(num_cpus=8)
    assert len(out["launched_nodes"]) == 2, out
    wait_for(lambda: len(ray.nodes()) == 4, 90, "provider nodes registering")
    assert ray.cluster_resources()["CPU"] == 8.0
    assert ray.cluster_resources()["provider_node"] == 2.0

    # a repeated identical request must not double-launch
    out2 = sdk.request_resources(num_cpus=8)
    assert out2["launched_nodes"] == [], out2

    # provider nodes actually run work
    @ray.remote(resources={"provider_node": 0.1})
    def where():
        return os.getppid()
    hosts = set(ray.get([where.remote() for _ in range(4)], timeout=120))
    assert len(hosts) >= 1 and os.getpid() not in hosts

    st = sdk.status()
    assert st["nodes"] == 4 and len(st["provider_nodes"]) == 2

    provider.shutdown()
    wait_for(lambda: len(ray.nodes()) == 2, 60, "provider nodes leaving")
    """)


def test_cluster_placement_groups_span_nodes():
    """STRICT_SPREAD bundles land on different hosts; tasks bound to a
    bundle run on its host; removal frees both sides (closes the r3
    'placement groups beyond one node' gap)."""
    _run_driver("""
    from ray_tpu.util import (PlacementGroupSchedulingStrategy,
                              placement_group, remove_placement_group)

    pg = ray.util.placement_group([{"CPU": 1}, {"CPU": 1}],
                                  strategy="STRICT_SPREAD")
    ray.get(pg.ready(), timeout=60)

    @ray.remote(num_cpus=1)
    def where():
        return os.getppid()

    hosts = []
    for i in range(2):
        strat = PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=i)
        hosts.append(ray.get(
            where.options(scheduling_strategy=strat).remote(), timeout=120))
    assert len(set(hosts)) == 2, hosts        # one bundle per host
    assert node_proc.pid in hosts             # one of them is the node

    # bundle resources are reserved on the node: its mirror drops by 1 CPU
    node_row = next(r for r in ray.nodes()
                    if r["resources"].get("worker_node"))
    assert node_row["available"].get("CPU", 0) <= 1.0 + 1e-9, node_row

    remove_placement_group(pg)
    wait_for(lambda: next(
        r for r in ray.nodes() if r["resources"].get("worker_node")
    )["available"].get("CPU", 0) >= 2.0 - 1e-9, 30, "node bundle release")

    # STRICT_PACK of 2x1CPU fits a single host; PACK prefers the head
    pg2 = ray.util.placement_group([{"CPU": 1}, {"CPU": 1}],
                                   strategy="STRICT_PACK")
    ray.get(pg2.ready(), timeout=60)
    hosts2 = []
    for i in range(2):
        strat = PlacementGroupSchedulingStrategy(
            placement_group=pg2, placement_group_bundle_index=i)
        hosts2.append(ray.get(
            where.options(scheduling_strategy=strat).remote(), timeout=120))
    assert len(set(hosts2)) == 1, hosts2
    remove_placement_group(pg2)

    # 3 bundles over 2 hosts: STRICT_SPREAD fails fast
    try:
        ray.util.placement_group([{"CPU": 0.5}] * 3, strategy="STRICT_SPREAD")
        raise SystemExit("expected STRICT_SPREAD infeasibility")
    except ValueError:
        pass
    """)


def test_direct_node_to_node_transfer():
    """A ~100MB array produced on node A and consumed on node B moves
    producer→consumer over the data plane, NEVER staging in the head store
    (VERDICT r4 missing #1; ref object_manager.cc Push/Pull). Counters
    prove the path: head staged_bytes stays 0, B reports direct_pull_bytes
    and A direct_serve_bytes ≥ the blob size, and the head's own store
    usage never grows by the blob."""
    _run_driver("""
    # second worker node: "node_b" resource pins the consumer there
    node2_proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.node_main",
         "--address", addr, "--num-cpus", "2",
         "--resources", '{"node_b": 1}'],
        env=env, stdin=subprocess.DEVNULL, start_new_session=True)
    try:
        wait_for(lambda: len(ray.nodes()) == 3, 60, "node B registration")

        N = 13_000_000  # ~104 MB of float64
        @ray.remote(resources={"worker_node": 0.1})
        def produce():
            return np.arange(N, dtype=np.float64)

        @ray.remote(resources={"node_b": 0.1})
        def consume(a):
            return float(a[12345]) + float(a[-1])

        ref = produce.remote()
        # TWO consumers share the dep: one transfer (deduped pull), two
        # balanced decrefs — a refcount underflow here would evict the
        # local copy and fail the third consume below
        got = ray.get([consume.remote(ref), consume.remote(ref)],
                      timeout=240)
        assert got == [12345.0 + (N - 1)] * 2, got
        got3 = ray.get(consume.remote(ref), timeout=240)
        assert got3 == 12345.0 + (N - 1), got3

        rows = {r.get("node_id"): r for r in ray.nodes()}
        head_row = next(r for r in rows.values() if r.get("is_head"))
        assert head_row["staged_bytes"] == 0, head_row
        # the blob never landed in the head store (head holds only small
        # control objects)
        assert head_row["object_store_used"] < 50_000_000, head_row

        blob = N * 8
        def counters_reported():
            rows = [r for r in ray.nodes() if not r.get("is_head")]
            pulled = sum(r.get("direct_pull_bytes", 0) for r in rows)
            served = sum(r.get("direct_serve_bytes", 0) for r in rows)
            return pulled >= blob and served >= blob
        wait_for(counters_reported, 30, "data-plane counters via heartbeat")
    finally:
        if node2_proc.poll() is None:
            os.killpg(node2_proc.pid, signal.SIGKILL)
            node2_proc.wait(timeout=10)
    """)


def test_node_death_by_heartbeat_silence():
    """A node that stops heartbeating WITHOUT closing its TCP connection
    (SIGSTOP: no FIN/RST — models a partition/half-open link) is declared
    dead by the head's liveness sweep and failed over; TCP-EOF-only death
    detection left it alive forever (r4 ADVICE medium). Ref:
    gcs_heartbeat_manager.cc num_heartbeats_timeout."""
    _run_driver("""
    os.kill(node_proc.pid, signal.SIGSTOP)  # freeze: socket stays open
    try:
        wait_for(lambda: len(ray.nodes()) == 1, 40,
                 "heartbeat-silence node death")
        # cluster resources no longer include the frozen node
        assert ray.cluster_resources().get("worker_node") is None
    finally:
        os.kill(node_proc.pid, signal.SIGCONT)
    """)


def test_gcp_tpu_provider_scales_up_fake_v5e():
    """A TPU-pod-shaped provider (VERDICT r4 next #8): requesting num_tpus
    beyond cluster capacity launches a fake v5e-8 through the provider seam;
    its host agent registers carrying num_tpus=8 and a num_tpus actor
    schedules onto it."""
    _run_driver("""
    from ray_tpu.autoscaler import (FakeTpuApi, GcpTpuNodeProvider, sdk)

    provider = GcpTpuNodeProvider(accelerator_type="v5litepod-8",
                                  api=FakeTpuApi(env=env))
    sdk.set_node_provider(provider, max_nodes=2)

    # no TPUs anywhere yet → the request must launch exactly one slice
    out = sdk.request_resources(bundles=[{"num_tpus": 8}])
    assert len(out["launched_nodes"]) == 1, out
    assert out["target_tpus"] == 8.0
    wait_for(lambda: ray.cluster_resources().get("num_tpus", 0) == 8.0,
             90, "fake TPU slice registering")
    assert ray.cluster_resources()["accelerator_type:v5litepod-8"] == 1.0

    # repeated identical request: capacity is met, no double-launch
    out2 = sdk.request_resources(bundles=[{"num_tpus": 8}])
    assert out2["launched_nodes"] == [], out2

    # a num_tpus actor lands on the fake slice host, not the head
    @ray.remote(resources={"num_tpus": 8})
    class TpuWorker:
        def where(self):
            return os.getppid()
    w = TpuWorker.remote()
    assert ray.get(w.where.remote(), timeout=120) != os.getpid()

    provider.shutdown()
    wait_for(lambda: ray.cluster_resources().get("num_tpus", 0) == 0,
             60, "fake slice leaving")
    """, timeout=300)


def test_rllib_env_runners_spread_across_nodes():
    """BASELINE config #5 shape (VERDICT r4 next #7): PPO's EnvRunner actors
    SPREAD across head + worker node feed the head-resident learner. The
    runners' node_info proves one lives under each host's worker pool, and
    training still converges metrics end-to-end through the cluster plane."""
    _run_driver("""
    from ray_tpu.rllib import PPOConfig

    algo = (PPOConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                         rollout_fragment_length=32,
                         scheduling_strategy="SPREAD")
            .training(train_batch_size=128, minibatch_size=64, num_epochs=1,
                      lr=3e-4)
            .debugging(seed=0)
            .build())
    try:
        infos = ray.get([r.node_info.remote() for r in algo._runner_handles],
                        timeout=180)
        # one runner under EACH host's worker pool (different parent procs)
        assert len({i["ppid"] for i in infos}) == 2, infos
        for _ in range(2):
            result = algo.train()
            assert np.isfinite(result["learner"]["total_loss"]), result
            assert result["num_env_steps_sampled_this_iter"] > 0
    finally:
        algo.stop()
    """, timeout=360)


def test_trainer_orchestrates_spmd_across_nodes():
    """Trainer.fit(ScalingConfig(num_workers=2)) composes the cluster plane
    with SPMD training (VERDICT r4 missing #2): the trainer itself places
    one TrainWorker per node agent (PG STRICT_SPREAD on a node-only
    resource), rank 0 allocates the jax.distributed coordinator, and the
    two ranks train as ONE 16-device world — losses match the closed-form
    single-process math, and per-rank marker files prove each worker ran
    under a DIFFERENT node agent. No pre-exported jax.distributed env."""
    _run_driver("""
    import tempfile
    node2_proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.node_main",
         "--address", addr, "--num-cpus", "2",
         "--resources", '{"worker_node": 1}'],
        env=env, stdin=subprocess.DEVNULL, start_new_session=True)
    try:
        wait_for(lambda: len(ray.nodes()) == 3, 60, "node B registration")

        from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig
        tmp = tempfile.mkdtemp(prefix="rtpu-spmd-")

        def loop(config):
            import os as _os
            import jax
            import numpy as _np
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P
            from ray_tpu import train
            from ray_tpu.parallel.mesh import make_mesh

            ctx = train.get_context()
            rank, size = ctx.get_world_rank(), ctx.get_world_size()
            with open(_os.path.join(config["tmp"], f"rank_{rank}.txt"),
                      "w") as f:
                f.write(str(_os.getppid()))
            devs = jax.devices()
            assert len(devs) == 16, devs  # 2 procs x 8 forced cpu devices
            mesh = make_mesh({"dp": 16}, devices=devs)
            X = _np.arange(16, dtype=_np.float32).reshape(16, 1) / 16.0
            Y = 2.0 * X
            lo, hi = rank * 8, rank * 8 + 8
            sh = NamedSharding(mesh, P("dp"))
            gx = jax.make_array_from_process_local_data(sh, X[lo:hi], (16, 1))
            gy = jax.make_array_from_process_local_data(sh, Y[lo:hi], (16, 1))

            def loss_fn(w, gx, gy):
                # global arrays must be ARGUMENTS under jit (closing over
                # non-addressable-device arrays is rejected)
                return jnp.mean((w * gx - gy) ** 2)

            vg = jax.jit(jax.value_and_grad(loss_fn))
            w = jnp.float32(0.0)
            for _ in range(3):
                loss, g = vg(w, gx, gy)
                w = w - 0.5 * g
                train.report({"loss": float(loss)})

        trainer = JaxTrainer(
            loop, train_loop_config={"tmp": tmp},
            scaling_config=ScalingConfig(
                num_workers=2, use_tpu=False,
                resources_per_worker={"worker_node": 0.1}),
            run_config=RunConfig(name="spmd", storage_path=tmp))
        res = trainer.fit()
        assert res.error is None, (res.error, getattr(res, "path", None))

        # closed form: loss_k = (w_k-2)^2 * mean(X^2), w_{k+1} = w_k - lr*g
        X = np.arange(16, dtype=np.float32).reshape(16, 1) / 16.0
        mx2 = float(np.mean(X ** 2))
        w, lr = 0.0, 0.5
        expected = []
        for _ in range(3):
            expected.append((w - 2.0) ** 2 * mx2)
            w -= lr * 2.0 * (w - 2.0) * mx2
        losses = [m["loss"] for m in res.metrics_history]
        assert len(losses) == 3, res.metrics_history
        for got, want in zip(losses, expected):
            assert abs(got - want) < 1e-4 * max(1.0, want), (losses, expected)

        # spread proof: each rank ran under a DIFFERENT node agent
        ppids = set()
        for r in (0, 1):
            with open(os.path.join(tmp, f"rank_{r}.txt")) as f:
                ppids.add(int(f.read()))
        assert ppids == {node_proc.pid, node2_proc.pid}, (
            ppids, node_proc.pid, node2_proc.pid)
    finally:
        if node2_proc.poll() is None:
            os.killpg(node2_proc.pid, signal.SIGKILL)
            node2_proc.wait(timeout=10)
    """, timeout=360)
