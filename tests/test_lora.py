"""LoRA adapters (models/lora.py): exact no-op at init, adapter-only
training with the base frozen, merge-for-serving. Reference contrast:
the reference's PEFT path monkey-patches torch Linears; ours is a pure
function of (params, adapter) differentiated w.r.t. the adapter."""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from ray_tpu.models import (Llama, LlamaConfig, apply_lora, init_lora,
                            lora_param_count, lora_targets, merge_lora)


@pytest.fixture(scope="module")
def base():
    cfg = LlamaConfig.tiny(dtype=jnp.float32, param_dtype=jnp.float32,
                           attn_impl="xla")
    model = Llama(cfg)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 16)))
    params = model.init(jax.random.PRNGKey(0), tokens)
    return cfg, model, params, tokens


def test_targets_cover_attn_and_ffn(base):
    _, _, params, _ = base
    targets = lora_targets(params)
    assert any("wq/kernel" in t for t in targets)
    assert any("w_down/kernel" in t for t in targets)
    # embeddings / norms / lm_head are NOT adapted by default
    assert not any("embed" in t or "norm" in t or "lm_head" in t
                   for t in targets)


def test_init_is_exact_noop(base):
    """b=0 at init → effective == base, bit-for-bit."""
    cfg, model, params, tokens = base
    lora = init_lora(jax.random.PRNGKey(1), params, rank=4)
    eff = apply_lora(params, lora)
    ref, _ = model.apply(params, tokens)
    out, _ = model.apply(eff, tokens)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))


def test_adapter_is_tiny(base):
    _, _, params, _ = base
    lora = init_lora(jax.random.PRNGKey(1), params, rank=4)
    n_base = sum(x.size for x in jax.tree_util.tree_leaves(params))
    assert lora_param_count(lora) < n_base / 5


def test_train_adapter_base_frozen(base):
    """Gradient flows through apply_lora into the factors only; the loss
    decreases while the base params never change."""
    cfg, model, params, tokens = base
    lora = init_lora(jax.random.PRNGKey(1), params, rank=8, alpha=16.0)
    opt = optax.adam(1e-2)
    opt_state = opt.init(lora)

    def loss_fn(lora, tokens):
        logits, _ = model.apply(apply_lora(params, lora), tokens[:, :-1])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        tgt = jax.nn.one_hot(tokens[:, 1:], cfg.vocab_size)
        return -jnp.mean(jnp.sum(tgt * logp, -1))

    @jax.jit
    def step(lora, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(lora, tokens)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(lora, updates), opt_state, loss

    base_snapshot = jax.tree_util.tree_map(np.asarray, params)
    losses = []
    for _ in range(12):
        lora, opt_state, loss = step(lora, opt_state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.05, losses
    # the base tree was never touched
    for (p1, l1), (p2, l2) in zip(
            jax.tree_util.tree_flatten_with_path(base_snapshot)[0],
            jax.tree_util.tree_flatten_with_path(params)[0]):
        np.testing.assert_array_equal(l1, np.asarray(l2))
    # b is no longer zero — training actually moved the adapter
    any_b = next(iter(lora["factors"].values()))["b"]
    assert float(jnp.abs(any_b).sum()) > 0


def test_merge_equals_functional(base):
    cfg, model, params, tokens = base
    lora = init_lora(jax.random.PRNGKey(2), params, rank=4)
    # give the adapter real content
    lora["factors"] = jax.tree_util.tree_map(
        lambda x: x + 0.01, lora["factors"])
    merged = merge_lora(params, lora)
    out_f, _ = model.apply(apply_lora(params, lora), tokens)
    out_m, _ = model.apply(merged, tokens)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_m),
                               atol=1e-6)
    # merged differs from base (the adapter does something)
    out_b, _ = model.apply(params, tokens)
    assert not np.allclose(np.asarray(out_b), np.asarray(out_m))


def test_merged_adapter_serves_as_model():
    """A fine-tuned adapter becomes a servable OpenAI model id: merge into
    the base and hand the merged tree to the engine (the multiplex LRU is
    the per-adapter cache in production)."""
    import asyncio

    from ray_tpu.serve.llm import LLMConfig as ServeConfig, LLMServer

    cfg = LlamaConfig.tiny(dtype=jnp.float32, param_dtype=jnp.float32,
                           attn_impl="xla", max_seq_len=64)
    model = Llama(cfg)
    tokens = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    lora = init_lora(jax.random.PRNGKey(1), params, rank=4)
    merged = merge_lora(params, lora)

    srv = LLMServer(ServeConfig(preset="tiny", max_batch_slots=2,
                                max_seq_len=64,
                                model_overrides={
                                    "dtype": jnp.float32,
                                    "param_dtype": jnp.float32,
                                    "attn_impl": "xla"}),
                    params=merged)
    out = asyncio.run(srv.generate([1, 2, 3], max_tokens=4))
    assert len(out["tokens"]) == 4


def test_lora_model_id_in_openai_app(ray_session):
    """(config, merged_params) registers an adapter as its own OpenAI
    model id next to the base."""
    import http.client
    import json

    from ray_tpu import serve
    from ray_tpu.serve.llm import LLMConfig as ServeConfig

    cfg = LlamaConfig.tiny(dtype=jnp.float32, param_dtype=jnp.float32,
                           attn_impl="xla", max_seq_len=64)
    model = Llama(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    merged = merge_lora(params, init_lora(jax.random.PRNGKey(1), params,
                                          rank=4))
    sc = ServeConfig(preset="tiny", max_batch_slots=2, max_seq_len=64,
                     model_overrides={"dtype": jnp.float32,
                                      "param_dtype": jnp.float32,
                                      "attn_impl": "xla"})
    app = serve.build_openai_app({"base": (sc, params),
                                  "base:my-adapter": (sc, merged)})
    serve.run(app, name="lora-oai", route_prefix="/")
    port = serve.start(http_options={"port": 0})
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        conn.request("GET", "/v1/models")
        resp = conn.getresponse()
        ids = [m["id"] for m in json.loads(resp.read())["data"]]
        assert ids == ["base", "base:my-adapter"]
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        conn.request("POST", "/v1/completions", json.dumps(
            {"model": "base:my-adapter", "prompt": "hi", "max_tokens": 3}),
            {"Content-Type": "application/json"})
        resp = conn.getresponse()
        out = json.loads(resp.read())
        assert resp.status == 200 and out["model"] == "base:my-adapter"
    finally:
        serve.shutdown()


def test_mismatched_adapter_raises():
    """Factors addressed against a different tree must raise, never
    silently serve the bare base under the adapter's name."""
    cfg = LlamaConfig.tiny(dtype=jnp.float32, param_dtype=jnp.float32,
                           attn_impl="xla")
    model = Llama(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    lora = init_lora(jax.random.PRNGKey(1), params, rank=4)
    # simulate an adapter trained against a differently-rooted tree
    lora["factors"] = {"wrong/root/" + k: v
                      for k, v in lora["factors"].items()}
    with pytest.raises(ValueError, match="no param path"):
        apply_lora(params, lora)


def test_lora_opt_mask_protects_scale_from_adamw_decay(base):
    """stop_gradient zeroes scale's grad, but adamw's DECOUPLED weight
    decay still shrinks every optimizer-visible leaf; optax.masked with
    lora_opt_mask must keep scale exactly fixed while factors update."""
    import optax

    from ray_tpu.models import lora_opt_mask

    _, model, params, tokens = base
    lora = init_lora(jax.random.PRNGKey(1), params, rank=4, alpha=16.0)
    opt = optax.masked(optax.adamw(1e-2, weight_decay=0.1),
                       lora_opt_mask(lora))
    state = opt.init(lora)

    def loss_fn(lo):
        logits, _ = model.apply(apply_lora(params, lo), tokens)
        return jnp.mean(logits.astype(jnp.float32) ** 2)

    before = float(lora["scale"])
    for _ in range(3):
        g = jax.grad(loss_fn)(lora)
        updates, state = opt.update(g, state, lora)
        lora = optax.apply_updates(lora, updates)
    assert float(lora["scale"]) == before
    # factors actually moved (the mask didn't freeze everything)
    any_a = next(iter(lora["factors"].values()))["a"]
    assert float(jnp.abs(any_a).sum()) > 0.0
