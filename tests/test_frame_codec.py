"""Wire-format tests for the native frame codec (ISSUE 14 tentpole 1).

The golden tests pin the v1 byte layout byte-for-byte: if any of them break,
the wire format changed and VERSION must be bumped + negotiation handled —
editing the expected bytes here is never the fix. The remaining tests cover
roundtrips for every opcode, the native/python scanner equivalence, the
pickle fallback for inexpressible payloads, first-byte sniffing in
protocol._decode, and version negotiation incl. the RAY_TPU_NATIVE=0 hatch.
"""

import pickle
import struct

import pytest

from ray_tpu._native import codec, objdir
from ray_tpu._private import protocol
from ray_tpu._private.task_spec import TaskSpec


def _enc(entries):
    data = codec.encode("batch", {"entries": entries})
    assert data is not None, f"codec refused expressible entries: {entries!r}"
    return data


def _roundtrip(entries):
    kind, payload = codec.decode(_enc(entries))
    assert kind == "batch"
    return payload["entries"]


# ------------------------------------------------------------ golden frames

def test_golden_refdeltas_frame():
    # incref/decref runs on obj- ids fold into ONE refdeltas entry whose body
    # is the packed delta-run layout: repeat{u8 op | u16 idlen | id}.
    data = _enc([("incref", "obj-a"), ("decref", "obj-a"),
                 ("decref", "obj-b")])
    expect = bytes.fromhex(
        "c30101"            # magic 0xC3 | version 1 | kind batch
        "01000000"          # nentries = 1 (u32 LE)
        "01"                # opcode 1 = refdeltas
        "18000000"          # body_len = 24
        "010500" "6f626a2d61"   # INCREF | len 5 | "obj-a"
        "020500" "6f626a2d61"   # DECREF | len 5 | "obj-a"
        "020500" "6f626a2d62")  # DECREF | len 5 | "obj-b"
    assert data == expect


def test_golden_put_frame():
    data = _enc([("put", "obj-z", 12, 4096, b"hi", ["obj-c1"])])
    expect = bytes.fromhex(
        "c30101" "01000000"
        "02"                # opcode 2 = put
        "24000000"          # body_len = 36
        "0500" "6f626a2d7a"     # str "obj-z"
        "0c000000"              # meta_len = 12 (u32)
        "0010000000000000"      # size = 4096 (u64)
        "01" "02000000" "6869"  # inline present | len 2 | "hi"
        "0100"                  # 1 contained id
        "0600" "6f626a2d6331")  # str "obj-c1"
    assert data == expect


def test_golden_actor_incref_frame():
    data = _enc([("actor_incref", "actor-7")])
    expect = bytes.fromhex(
        "c30101" "01000000"
        "03"                # opcode 3 = actor_incref
        "09000000"          # body_len = 9
        "0700" "6163746f722d37")  # str "actor-7"
    assert data == expect


def test_golden_header_constants():
    assert codec.MAGIC == 0xC3
    assert codec.VERSION == 1
    assert codec.KIND_BATCH == 1
    # opcode numbering is wire ABI — reordering breaks cross-version peers
    assert (codec.OP_REFDELTAS, codec.OP_PUT, codec.OP_ACTOR_INCREF,
            codec.OP_ACTOR_DECREF, codec.OP_OPEN_STREAM,
            codec.OP_CLOSE_STREAM, codec.OP_TASK_DONE, codec.OP_SUBMIT,
            codec.OP_INCREF_ONE, codec.OP_DECREF_ONE) == tuple(range(1, 11))


def test_golden_fold_preserves_order():
    # put-before-decref ordering must survive folding: the run is split
    # around the put, not hoisted across it.
    data = _enc([("incref", "obj-a"),
                 ("put", "obj-p", 0, 0, None, []),
                 ("decref", "obj-a")])
    (n,) = struct.unpack_from("<I", data, 3)
    assert n == 3
    ops = [op for op, _, _ in codec._scan_py(data)]
    assert ops == [codec.OP_REFDELTAS, codec.OP_PUT, codec.OP_REFDELTAS]


# --------------------------------------------------------------- roundtrips

def test_roundtrip_put_and_refs():
    entries = [("put", "obj-z", 12, 4096, b"inline", ["obj-c1", "obj-c2"]),
               ("incref", "obj-z"), ("decref", "obj-c1")]
    out = _roundtrip(entries)
    assert out[0] == ("put", "obj-z", 12, 4096, b"inline",
                      ["obj-c1", "obj-c2"])
    # the ref run comes back as one packed refdeltas entry the controller
    # hands straight to the directory
    assert out[1][0] == "refdeltas"
    assert objdir.pack_deltas([(objdir.INCREF, "obj-z"),
                               (objdir.DECREF, "obj-c1")]) == out[1][1]


def test_roundtrip_task_done():
    entries = [("task_done", "task-1",
                [("obj-r0", 8, 100, None, []),
                 ("obj-r1", 3, 7, b"\x00\x01", ["obj-n"])],
                None, None, None)]
    assert _roundtrip(entries) == entries


def test_roundtrip_task_done_error_and_spans():
    err = ValueError("boom")
    span = {"task_id": "task-2", "t0": 1.5}
    spans = [{"name": "exec"}]
    (out,) = _roundtrip([("task_done", "task-2", [], err, span, spans)])
    assert out[0] == "task_done" and out[1] == "task-2" and out[2] == []
    assert type(out[3]) is ValueError and out[3].args == ("boom",)
    assert out[4] == span and out[5] == spans


def test_roundtrip_submit_plain():
    spec = TaskSpec(task_id="task-9", fn_blob=b"\x80blob",
                    args=[("v", b"payload"), ("ref", "obj-a")],
                    kwargs={"k": ("v", b"vv")},
                    num_returns=2, resources={"CPU": 1.0, "TPU": 0.5},
                    max_retries=3, retry_exceptions=False, name="f")
    (out,) = _roundtrip([("submit", spec, ["obj-r0", "obj-r1"])])
    assert out[0] == "submit" and out[2] == ["obj-r0", "obj-r1"]
    got = out[1]
    for f in ("task_id", "fn_blob", "args", "kwargs", "num_returns",
              "resources", "max_retries", "retry_exceptions", "name"):
        assert getattr(got, f) == getattr(spec, f), f


def test_roundtrip_submit_extras_and_streaming():
    # non-default rare fields ride the pickled extras blob
    spec = TaskSpec(task_id="task-a", fn_blob=None, num_returns="streaming",
                    actor_id="actor-1", method_name="step",
                    scheduling_strategy="SPREAD", runtime_env={"env_vars": {}},
                    generator_backpressure=4, parent_task_id="task-p",
                    job_id="job-1", trace_id="tr", parent_span_id=7,
                    nested_refs=["obj-n"])
    (out,) = _roundtrip([("submit", spec, [])])
    got = out[1]
    for f in ("num_returns", "actor_id", "method_name", "scheduling_strategy",
              "runtime_env", "generator_backpressure", "parent_task_id",
              "job_id", "trace_id", "parent_span_id", "nested_refs"):
        assert getattr(got, f) == getattr(spec, f), f


def test_roundtrip_stream_and_actor_ops():
    entries = [("open_stream", "task-s"), ("close_stream", "task-s"),
               ("actor_incref", "actor-1"), ("actor_decref", "actor-1"),
               ("incref", "act-x"), ("decref", "act-x")]
    # act-x doesn't start with obj- so the incref/decref stay scalar entries
    assert _roundtrip(entries) == entries


def test_roundtrip_empty_batch():
    assert _roundtrip([]) == []


# --------------------------------------------------------- pickle fallback

def test_encode_refuses_non_batch_kinds():
    assert codec.encode("register", {"worker_id": "w"}) is None
    assert codec.encode("batch", {"entries": [], "extra": 1}) is None


def test_encode_refuses_inexpressible_entries():
    # unknown entry op
    assert codec.encode("batch", {"entries": [("mystery", "x")]}) is None
    # oversized id blows the u16 length field
    assert codec.encode(
        "batch", {"entries": [("incref", "act-" + "x" * 70000)]}) is None
    # non-bool retry_exceptions has no fixed layout
    spec = TaskSpec(task_id="t", fn_blob=None, retry_exceptions=(ValueError,))
    assert codec.encode("batch", {"entries": [("submit", spec, [])]}) is None


def test_protocol_encode_falls_back_to_pickle():
    # codec_on + inexpressible payload → pickled bytes (first byte 0x80)
    data = protocol._encode("batch", {"entries": [("mystery", 1)]}, True)
    assert data[0] == 0x80
    assert pickle.loads(data) == ("batch", {"entries": [("mystery", 1)]})
    # codec off → always pickle, even for codec-able payloads
    data = protocol._encode("batch", {"entries": [("incref", "obj-a")]}, False)
    assert data[0] == 0x80


def test_protocol_decode_sniffs_first_byte():
    raw = _enc([("decref", "obj-a")])
    assert raw[0] == codec.MAGIC
    kind, payload = protocol._decode(raw)
    assert kind == "batch" and payload["entries"][0][0] == "refdeltas"
    # pickle frames (0x80...) still decode through pickle
    assert protocol._decode(pickle.dumps(("ping", {"x": 1}), protocol=5)) \
        == ("ping", {"x": 1})


def test_frame_bytes_matches_send_encoding():
    framed = protocol.frame_bytes("batch", {"entries": [("incref", "obj-a")]},
                                  codec_on=True)
    (n,) = struct.unpack_from("<I", framed, 0)
    body = framed[4:]
    assert len(body) == n
    assert body == _enc([("incref", "obj-a")])


# ------------------------------------------------- native scanner parity

def test_scan_native_matches_python():
    if not codec.native_available():
        pytest.skip("no toolchain: python scanner is the only implementation")
    lib = codec._load()
    frames = [
        _enc([]),
        _enc([("incref", "obj-a"), ("decref", "obj-b")]),
        _enc([("put", "obj-z", 12, 4096, b"hi", ["obj-c1"]),
              ("task_done", "task-1", [("obj-r0", 8, 100, None, [])],
               None, None, None),
              ("open_stream", "task-1")]),
    ]
    for data in frames:
        assert codec._scan_native(lib, data) == codec._scan_py(data)


@pytest.mark.parametrize("mutate", [
    lambda d: d[:-1],                       # truncated body
    lambda d: d[:3] + b"\xff\xff\xff\xff" + d[7:],  # nentries lies
    lambda d: d[:7] + b"\x63" + d[8:],      # opcode out of range
    lambda d: d + b"\x00",                  # trailing garbage
])
def test_malformed_frames_rejected_by_both_scanners(mutate):
    data = mutate(_enc([("incref", "obj-a"), ("decref", "obj-b")]))
    with pytest.raises(ValueError):
        codec._scan_py(data)
    if codec.native_available():
        with pytest.raises(ValueError):
            codec._scan_native(codec._load(), data)


def test_fc_version_matches_python_version():
    if not codec.native_available():
        pytest.skip("no toolchain")
    assert codec._load().fc_version() == codec.VERSION


# ------------------------------------------------------------- negotiation

def test_negotiate_takes_min():
    assert codec.wire_version() in (0, codec.VERSION)
    if codec.wire_version() == codec.VERSION:
        assert codec.negotiate(1) == 1
        assert codec.negotiate(99) == codec.VERSION
    assert codec.negotiate(0) == 0
    assert codec.negotiate(None) == 0
    assert codec.negotiate("garbage") == 0


def test_native_disabled_forces_pickle(monkeypatch):
    monkeypatch.setenv("RAY_TPU_NATIVE", "0")
    assert codec.native_disabled()
    assert codec.wire_version() == 0
    assert codec.negotiate(1) == 0
    # decode stays available even when disabled: a peer may still be
    # mid-handshake and no frame may ever be dropped
    raw = _enc([("incref", "obj-a")])
    kind, payload = codec.decode(raw)
    assert kind == "batch" and payload["entries"][0][0] == "refdeltas"
