"""Native/Python object-directory equivalence tests (ISSUE 14 tentpole 2 +
satellite c).

PyObjectDirectory is the executable spec: randomized op sequences —
register / holder churn / refcount deltas / evict(erase) / node death — must
drive the C++ ObjectDirectory to byte-identical snapshot() state and
identical apply_deltas() verdicts at every checkpoint. The native side skips
cleanly on a toolchain-less box (conftest's report header says so); the
Python side always runs, so the fallback path is tested everywhere.
"""

import random

import pytest

from ray_tpu._native import objdir
from ray_tpu._native.objdir import (DECREF, F_EVICTABLE, F_RELEASED, INCREF,
                                    PyObjectDirectory)

NSHARDS = 8

_LOCATIONS = ["pending", "shm", "inline", "spilled", "error",
              "remote:node-2", "plasma://custom"]


def _pair():
    """(native, oracle) — or skip when the toolchain can't build the .so."""
    if not objdir.available():
        pytest.skip("no toolchain: native obj_directory unavailable")
    return objdir.ObjectDirectory(NSHARDS), PyObjectDirectory(NSHARDS)


def _both(fn):
    nat, py = _pair()
    try:
        assert fn(nat) == fn(py)
    finally:
        nat.close()


# ------------------------------------------------------------- scalar ops

def test_register_get_set_roundtrip():
    def run(d):
        d.register("obj-a", refcount=2, pinned=1, size=100, location="shm")
        out = [d.contains("obj-a"), d.contains("obj-b"), d.count(),
               d.refcount("obj-a"), d.pinned("obj-a"), d.size("obj-a"),
               d.location("obj-a")]
        d.set_refcount("obj-a", 5)
        d.set_pinned("obj-a", 0)
        d.set_size("obj-a", 4096)
        d.set_location("obj-a", "remote:node-9")
        out += [d.refcount("obj-a"), d.pinned("obj-a"), d.size("obj-a"),
                d.location("obj-a"), d.total_bytes()]
        # missing ids answer None/False everywhere, never raise
        out += [d.refcount("obj-nope"), d.pinned("obj-nope"),
                d.size("obj-nope"), d.location("obj-nope"),
                d.add_refcount("obj-nope", 1), d.erase("obj-nope")]
        out += [d.add_refcount("obj-a", -2), d.erase("obj-a"), d.count()]
        return out
    _both(run)


def test_location_codes_roundtrip():
    def run(d):
        for i, loc in enumerate(_LOCATIONS):
            d.register(f"obj-{i}", location=loc)
        return [d.location(f"obj-{i}") for i in range(len(_LOCATIONS))]
    _both(run)


def test_holder_ops():
    def run(d):
        d.register("obj-a")
        out = [d.add_holder("obj-a", "node-1"),      # True
               d.add_holder("obj-a", "node-1"),      # dup -> False
               d.add_holder("obj-a", "node-2"),
               d.add_holder("obj-missing", "node-1"),  # no entry -> False
               sorted(d.holders("obj-a")),
               d.remove_holder("obj-a", "node-1"),
               d.remove_holder("obj-a", "node-1"),   # gone -> False
               d.holders("obj-a"), d.holders("obj-missing")]
        d.add_holder("obj-a", "node-3")
        d.clear_holders("obj-a")
        out.append(d.holders("obj-a"))
        return out
    _both(run)


def test_drop_node_touch_count():
    def run(d):
        for i in range(6):
            d.register(f"obj-{i}")
            d.add_holder(f"obj-{i}", "node-dead" if i % 2 else "node-ok")
        touched = d.drop_node("node-dead")
        return [touched, [d.holders(f"obj-{i}") for i in range(6)]]
    _both(run)


# ------------------------------------------------------------- delta runs

def test_apply_deltas_flags():
    def run(d):
        d.register("obj-a", refcount=1, pinned=0)   # -> released + evictable
        d.register("obj-b", refcount=2, pinned=1)   # -> released, pinned
        d.register("obj-c", refcount=1)             # inc then dec: net zero
        packed = objdir.pack_deltas([
            (DECREF, "obj-a"),
            (DECREF, "obj-b"), (DECREF, "obj-b"),
            (INCREF, "obj-c"), (DECREF, "obj-c"),
            (DECREF, "obj-ghost"),                  # unknown id: ignored
        ])
        return d.apply_deltas(packed)
    nat, py = _pair()
    try:
        res_nat, res_py = run(nat), run(py)
        assert res_nat == res_py
        by_id = dict((oid, (flags, rc)) for oid, flags, rc in res_py)
        assert by_id["obj-a"] == (F_RELEASED | F_EVICTABLE, 0)
        assert by_id["obj-b"] == (F_RELEASED, 0)       # pinned blocks evict
        assert by_id["obj-c"] == (0, 1)                # never crossed zero
        assert "obj-ghost" not in by_id
    finally:
        nat.close()


def test_apply_deltas_released_once():
    # F_RELEASED fires on the FIRST crossing to <= 0 only; later oscillation
    # around zero reports rc but not the flag again
    def run(d):
        d.register("obj-a", refcount=1)
        first = d.apply_deltas(objdir.pack_deltas([(DECREF, "obj-a")]))
        second = d.apply_deltas(objdir.pack_deltas(
            [(INCREF, "obj-a"), (DECREF, "obj-a")]))
        return [first, second]
    nat, py = _pair()
    try:
        out = run(py)
        assert run(nat) == out
        assert out[0] == [("obj-a", F_RELEASED | F_EVICTABLE, 0)]
        assert out[1] == [("obj-a", F_EVICTABLE, 0)]
    finally:
        nat.close()


def test_apply_deltas_empty_and_malformed():
    nat, py = _pair()
    try:
        assert nat.apply_deltas(b"") == py.apply_deltas(b"") == []
        for bad in (b"\x01", b"\x01\x05\x00ob", b"\x07\x03\x00abc"):
            with pytest.raises(ValueError):
                py.apply_deltas(bad)
            with pytest.raises(ValueError):
                nat.apply_deltas(bad)
    finally:
        nat.close()


def test_pack_unpack_delta_layouts():
    packed = objdir.pack_deltas([(INCREF, "obj-a"), (DECREF, "obj-bb")])
    assert packed == b"\x01\x05\x00obj-a\x02\x06\x00obj-bb"
    # output layout: u8 flags | i64 rc LE | u16 idlen | id
    blob = (b"\x03" + (0).to_bytes(8, "little", signed=True)
            + b"\x05\x00obj-a")
    assert objdir.unpack_delta_result(blob) == [("obj-a", 3, 0)]


# --------------------------------------------------- randomized equivalence

def _random_op(rng, nat, py, ids, nodes):
    """Apply one random mutation to BOTH directories; return any comparable
    result pair (they must match)."""
    oid = rng.choice(ids)
    roll = rng.random()
    if roll < 0.25:
        rc = rng.randint(-1, 4)
        pin = rng.randint(0, 2)
        size = rng.randint(0, 1 << 20)
        loc = rng.choice(_LOCATIONS)
        nat.register(oid, rc, pin, size, loc)
        py.register(oid, rc, pin, size, loc)
        return None
    if roll < 0.40:  # packed delta run over several ids
        run = [(rng.choice((INCREF, DECREF)), rng.choice(ids))
               for _ in range(rng.randint(1, 8))]
        packed = objdir.pack_deltas(run)
        return nat.apply_deltas(packed), py.apply_deltas(packed)
    if roll < 0.50:
        delta = rng.choice((-2, -1, 1, 2))
        return nat.add_refcount(oid, delta), py.add_refcount(oid, delta)
    if roll < 0.60:
        node = rng.choice(nodes)
        return nat.add_holder(oid, node), py.add_holder(oid, node)
    if roll < 0.68:
        node = rng.choice(nodes)
        return nat.remove_holder(oid, node), py.remove_holder(oid, node)
    if roll < 0.74:  # evict
        return nat.erase(oid), py.erase(oid)
    if roll < 0.80:
        pin = rng.randint(0, 2)
        nat.set_pinned(oid, pin)
        py.set_pinned(oid, pin)
        return None
    if roll < 0.86:
        size = rng.randint(0, 1 << 16)
        nat.set_size(oid, size)
        py.set_size(oid, size)
        return None
    if roll < 0.92:
        loc = rng.choice(_LOCATIONS)
        nat.set_location(oid, loc)
        py.set_location(oid, loc)
        return None
    if roll < 0.97:
        v = rng.randint(-1, 5)
        nat.set_refcount(oid, v)
        py.set_refcount(oid, v)
        return None
    node = rng.choice(nodes)  # node death
    return nat.drop_node(node), py.drop_node(node)


@pytest.mark.parametrize("seed", [0, 1, 2, 1337])
def test_randomized_equivalence(seed):
    nat, py = _pair()
    rng = random.Random(seed)
    ids = [f"obj-{i}" for i in range(40)]
    nodes = [f"node-{i}" for i in range(5)]
    try:
        for step in range(600):
            pair = _random_op(rng, nat, py, ids, nodes)
            if pair is not None:
                assert pair[0] == pair[1], f"seed={seed} step={step}"
            if step % 100 == 99:
                assert nat.snapshot() == py.snapshot(), \
                    f"state diverged: seed={seed} step={step}"
        assert nat.snapshot() == py.snapshot()
        assert nat.count() == py.count()
        assert nat.total_bytes() == py.total_bytes()
        assert [nat.shard_count(i) for i in range(NSHARDS)] \
            == [py.shard_count(i) for i in range(NSHARDS)]
    finally:
        nat.close()


def test_sharding_spreads_ids():
    # fnv1a over a few hundred ids should touch most of the shards — the
    # whole point of the per-shard locks
    d = PyObjectDirectory(16)
    for i in range(400):
        d.register(f"obj-{i:04d}")
    occupied = sum(1 for i in range(16) if d.shard_count(i) > 0)
    assert occupied >= 12


# ----------------------------------------------------------- factory paths

def test_make_directory_escape_hatch(monkeypatch):
    monkeypatch.setenv("RAY_TPU_NATIVE", "0")
    assert isinstance(objdir.make_object_directory(), PyObjectDirectory)


def test_make_directory_native_when_available(monkeypatch):
    monkeypatch.delenv("RAY_TPU_NATIVE", raising=False)
    d = objdir.make_object_directory()
    try:
        if objdir.available():
            assert isinstance(d, objdir.ObjectDirectory)
            assert d.nshards == objdir.NUM_SHARDS
        else:
            assert isinstance(d, PyObjectDirectory)
    finally:
        d.close()


def test_directory_singleton_reset():
    objdir.reset_directory()
    d1 = objdir.get_directory()
    assert objdir.get_directory() is d1
    objdir.reset_directory()
    d2 = objdir.get_directory()
    assert d2 is not d1
    objdir.reset_directory()
