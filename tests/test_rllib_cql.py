"""CQL + offline data path (VERDICT r2 #8; ref: rllib/algorithms/cql/cql.py)."""

import numpy as np
import pytest


def _pendulum_dataset(n_steps=3000, seed=0):
    """Offline experience from a simple energy-based Pendulum controller —
    mediocre-but-informative data, the offline-RL setting."""
    import gymnasium as gym
    env = gym.make("Pendulum-v1")
    rng = np.random.default_rng(seed)
    obs_l, act_l, rew_l, nxt_l, done_l = [], [], [], [], []
    obs, _ = env.reset(seed=seed)
    for _ in range(n_steps):
        cos_th, sin_th, vel = obs
        # swing toward upright with noise; decent but far from optimal
        a = np.clip(-1.0 * sin_th - 0.3 * vel + rng.normal(0, 0.4), -2, 2)
        action = np.asarray([a], np.float32)
        nxt, r, term, trunc, _ = env.step(action)
        obs_l.append(obs)
        act_l.append(action)
        rew_l.append(r)
        nxt_l.append(nxt)
        done_l.append(float(term))
        obs = nxt
        if term or trunc:
            obs, _ = env.reset()
    env.close()
    from ray_tpu.rllib import sample_batch as SB
    return {SB.OBS: np.asarray(obs_l, np.float32),
            SB.ACTIONS: np.asarray(act_l, np.float32),
            SB.REWARDS: np.asarray(rew_l, np.float32),
            SB.NEXT_OBS: np.asarray(nxt_l, np.float32),
            SB.TERMINATEDS: np.asarray(done_l, np.float32)}


def test_offline_dataset_roundtrip():
    from ray_tpu.rllib.offline import (as_sample_batch,
                                       dataset_to_sample_batch,
                                       sample_batch_to_dataset)
    from ray_tpu.rllib.sample_batch import SampleBatch
    data = _pendulum_dataset(n_steps=200)
    ds = sample_batch_to_dataset(SampleBatch(data))
    back = dataset_to_sample_batch(ds)
    for k, v in data.items():
        np.testing.assert_allclose(back[k], v, rtol=1e-6)
    # Dataset accepted directly as offline_data
    b = as_sample_batch(ds)
    assert b[next(iter(data))].shape == data[next(iter(data))].shape


def test_cql_trains_and_stays_conservative():
    from ray_tpu.rllib import CQLConfig
    data = _pendulum_dataset(n_steps=2000)
    algo = (CQLConfig()
            .environment("Pendulum-v1")
            .offline_data_source(data)
            .training(lr=3e-4, train_batch_size=256, cql_alpha=1.0,
                      num_cql_actions=4, train_intensity=10, bc_iters=10)
            .evaluation(evaluation_duration=2)
            .debugging(seed=7)
            .build())
    penalties = []
    for _ in range(4):
        result = algo.train()
        learner = result["learner"]
        assert np.isfinite(learner["critic_loss"]), learner
        assert np.isfinite(learner["actor_loss"]), learner
        penalties.append(learner["cql_penalty"])
    assert all(np.isfinite(p) for p in penalties), penalties
    ev = algo.evaluate()
    assert ev["episodes_this_iter"] == 2
    assert np.isfinite(ev["episode_return_mean"])

    # ablation: with the penalty OFF, the conservative gap (logsumexp Q over
    # sampled actions minus Q on data) ends up larger — the regularizer is
    # demonstrably doing its job
    ablation = (CQLConfig()
                .environment("Pendulum-v1")
                .offline_data_source(data)
                .training(lr=3e-4, train_batch_size=256, cql_alpha=0.0,
                          num_cql_actions=4, train_intensity=10, bc_iters=10)
                .debugging(seed=7)
                .build())
    gap_off = None
    for _ in range(4):
        gap_off = ablation.train()["learner"]["cql_penalty"]
    assert penalties[-1] < gap_off, (penalties[-1], gap_off)


def test_cql_not_worse_than_bc_smoke():
    """d4rl-style smoke comparison on the same dataset (generous slack: 4
    training iterations on 2k transitions is a smoke test, not a paper)."""
    from ray_tpu.rllib import BCConfig, CQLConfig
    data = _pendulum_dataset(n_steps=2000)

    bc = (BCConfig().environment("Pendulum-v1")
          .offline_data_source(data)
          .training(lr=1e-3, train_batch_size=256)
          .evaluation(evaluation_duration=3)
          .debugging(seed=7).build())
    for _ in range(8):
        bc.train()
    bc_ret = bc.evaluate().get("episode_return_mean", -1e9)

    cql = (CQLConfig().environment("Pendulum-v1")
           .offline_data_source(data)
           .training(lr=3e-4, train_batch_size=256, cql_alpha=1.0,
                     num_cql_actions=4, train_intensity=20, bc_iters=20)
           .evaluation(evaluation_duration=3)
           .debugging(seed=7).build())
    for _ in range(8):
        cql.train()
    cql_ret = cql.evaluate()["episode_return_mean"]
    # Pendulum returns ~[-1800, 0]; CQL should be in BC's league or better
    assert cql_ret > bc_ret - 400, (cql_ret, bc_ret)
