"""Client-owned small objects (ISSUE 17; reference: Ray ownership model,
src/ray/core_worker/reference_count.cc): the submitting driver/worker owns
return objects under the inline threshold, their descriptors are pushed back
to the owner's local table, and a driver-local chain costs ZERO blocking
controller round trips. Ownership transfers to the head on owner death —
the write-behind cache already holds every descriptor, so the object stays
resolvable.
"""

import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _controller():
    from ray_tpu._private import state
    return state.global_client().controller


def _wait_for(cond, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return cond()


# ------------------------------------------------------ zero-roundtrip chain

def test_driver_local_chain_zero_roundtrips(ray_session):
    """f.remote(f.remote(...)) where every link is small: the driver owns
    each return, descriptors arrive over the in-process sink, and get()
    serves from the local ownership table — the whole submit+get sequence
    moves the blocking round-trip counter by ZERO (the ISSUE 17 acceptance
    invariant, also asserted by core_bench's ownership section)."""
    ray = ray_session
    from ray_tpu.util import metrics

    @ray.remote
    def inc(x):
        return x + 1

    ray.get(inc.remote(0))  # warm the pool outside the counted window
    rt0 = metrics.control_roundtrips_total()
    lg0 = metrics.control_local_gets_total()
    ref = inc.remote(0)
    for _ in range(4):
        ref = inc.remote(ref)
    assert ray.get(ref, timeout=60) == 5
    rt = metrics.control_roundtrips_total() - rt0
    assert rt == 0, f"owned chain cost {rt} blocking round trips (want 0)"
    assert metrics.control_local_gets_total() - lg0 >= 1, (
        "get() did not serve from the local ownership table")


def test_owned_descriptor_rides_the_spec(ray_session):
    """An owned small ref passed as a task arg carries its inline descriptor
    INSIDE the TaskSpec (spec.owned_inline) so the consuming worker never
    round-trips back to the owner for the bytes."""
    ray = ray_session

    @ray.remote
    def make():
        return 41

    @ray.remote
    def add_one(x):
        return x + 1

    ref = make.remote()
    assert ray.get(add_one.remote(ref), timeout=60) == 42


# ------------------------------------------------------ owner-death transfer

def test_owner_death_transfers_to_head(ray_session):
    """A worker that put() an object owns it; when the worker dies the
    controller clears meta.owner (the head's write-behind cache becomes
    authoritative) and the object must still resolve from the driver."""
    ray = ray_session

    @ray.remote
    def make_owned():
        import os as _os
        import ray_tpu
        return ray_tpu.put(b"owned-payload"), _os.getpid()

    inner, pid = ray.get(make_owned.remote(), timeout=60)
    ctrl = _controller()
    meta = ctrl.objects.get(inner.id)
    assert meta is not None, "worker put was not registered at the head"
    assert meta.owner not in (None, "driver"), (
        f"worker put should be worker-owned, got owner={meta.owner!r}")
    os.kill(pid, signal.SIGKILL)
    assert _wait_for(lambda: ctrl.objects[inner.id].owner is None), (
        "ownership did not transfer to the head after owner death")
    assert ray.get(inner, timeout=60) == b"owned-payload"


# ------------------------------------------------------------- escape hatch

def test_ownership_disabled_hatch():
    """RAY_TPU_OWNERSHIP=0 restores head-owned-everything: no local table,
    chains still correct (the behavioral escape hatch the docs promise)."""
    code = """
import os
os.environ["RAY_TPU_OWNERSHIP"] = "0"
os.environ.setdefault("RAY_TPU_NUM_CHIPS", "0")
import ray_tpu
ray_tpu.init(num_cpus=2)
from ray_tpu._private import state
assert state.global_client()._owned is None, "ownership table should be off"

@ray_tpu.remote
def inc(x):
    return x + 1

ref = inc.remote(0)
for _ in range(3):
    ref = inc.remote(ref)
assert ray_tpu.get(ref, timeout=60) == 4
ray_tpu.shutdown()
print("HATCH-OK")
"""
    out = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO, capture_output=True,
        text=True, timeout=180,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    assert "HATCH-OK" in out.stdout


# ------------------------------------------------- GC-reentrant owned table

def test_owned_table_survives_gc_reentrant_decref():
    """The owned table's lock is taken by ObjectRef.__del__ → decref. GC can
    fire inside any allocation made while the lock is already held on the
    SAME thread (waiter() creating its Event, refcount bumps), so the lock
    must be reentrant — a plain Lock deadlocks the whole client there."""
    from ray_tpu._private.client import _OwnedTable

    t = _OwnedTable()
    t.add_pending(["oid-a", "oid-b"])

    # non-blocking double-acquire: RLock says True, a plain Lock says False
    # (and the real failure mode is an untestable infinite hang)
    assert t._lock.acquire(blocking=False)
    try:
        nested = t._lock.acquire(blocking=False)
        assert nested, "owned-table lock must be reentrant (GC-time decref)"
        t._lock.release()
        # what a mid-waiter GC actually does: drop an unrelated ref while
        # the outer frame still holds the lock
        t.decref("oid-b")
    finally:
        t._lock.release()

    assert t.peek("oid-b") is None and t.peek("oid-a") is None
    desc, ev = t.waiter("oid-a")
    assert desc is None and ev is not None
    t.resolve([("oid-a", "inline", b"x", 1, 1)])
    assert ev.is_set() and t.peek("oid-a") == ("inline", b"x")
