"""RLlib tests (SURVEY.md §4): loss math golden tests, GAE/V-trace vs naive
reference, distribution numerics, PPO learns CartPole smoke."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops import losses
from ray_tpu.rllib import (EnvRunner, ModuleSpec, PPO, PPOConfig, RLModule,
                           SampleBatch)
from ray_tpu.rllib import sample_batch as SB
from ray_tpu.rllib.connectors import RunningMeanStd, compute_gae
from ray_tpu.rllib.distributions import Categorical, DiagGaussian


# ---------------------------------------------------------------- math golden
def _naive_gae(rewards, values, dones, gamma, lam):
    T = len(rewards)
    adv = np.zeros(T)
    acc = 0.0
    for t in reversed(range(T)):
        nd = 1.0 - dones[t]
        delta = rewards[t] + gamma * values[t + 1] * nd - values[t]
        acc = delta + gamma * lam * nd * acc
        adv[t] = acc
    return adv, adv + values[:-1]


def test_gae_matches_naive():
    rng = np.random.default_rng(0)
    T = 37
    rewards = rng.normal(size=T)
    values = rng.normal(size=T + 1)
    dones = (rng.random(T) < 0.1).astype(np.float64)
    adv, tgt = losses.gae(jnp.asarray(rewards), jnp.asarray(values),
                          jnp.asarray(dones), 0.97, 0.9)
    nadv, ntgt = _naive_gae(rewards, values, dones, 0.97, 0.9)
    np.testing.assert_allclose(np.asarray(adv), nadv, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(tgt), ntgt, rtol=1e-5)


def _naive_vtrace(blp, tlp, rewards, values, dones, gamma, rho_bar, c_bar):
    T = len(rewards)
    rhos = np.exp(tlp - blp)
    crho = np.minimum(rho_bar, rhos)
    cs = np.minimum(c_bar, rhos)
    vs_minus = np.zeros(T)
    acc = 0.0
    for t in reversed(range(T)):
        nd = 1.0 - dones[t]
        delta = crho[t] * (rewards[t] + gamma * values[t + 1] * nd - values[t])
        acc = delta + gamma * cs[t] * nd * acc
        vs_minus[t] = acc
    vs = vs_minus + values[:-1]
    vs_next = np.concatenate([vs[1:], values[-1:]])
    pg = crho * (rewards + gamma * vs_next * (1 - dones) - values[:-1])
    return vs, pg


def test_vtrace_matches_naive():
    rng = np.random.default_rng(1)
    T = 23
    blp, tlp = rng.normal(size=T) * 0.3, rng.normal(size=T) * 0.3
    rewards = rng.normal(size=T)
    values = rng.normal(size=T + 1)
    dones = (rng.random(T) < 0.15).astype(np.float64)
    out = losses.vtrace(jnp.asarray(tlp - tlp + blp), jnp.asarray(tlp),
                        jnp.asarray(rewards), jnp.asarray(values),
                        jnp.asarray(dones), 0.99, 1.0, 1.0)
    nvs, npg = _naive_vtrace(blp, tlp, rewards, values, dones, 0.99, 1.0, 1.0)
    np.testing.assert_allclose(np.asarray(out.vs), nvs, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out.pg_advantages), npg,
                               rtol=1e-4, atol=1e-5)


def test_ppo_surrogate_golden():
    logp = jnp.asarray([0.0, -0.1, 0.4])
    old = jnp.asarray([0.0, 0.0, 0.0])
    adv = jnp.asarray([1.0, 1.0, -1.0])
    loss, clip_frac = losses.ppo_surrogate(logp, old, adv, clip=0.2)
    ratio = np.exp(np.asarray(logp))
    # elementwise min(ratio*adv, clip(ratio)*adv): for the negative-advantage
    # ratio>1+clip case the UNCLIPPED term is smaller (pessimistic bound)
    expect = -np.mean([min(r * a, np.clip(r, 0.8, 1.2) * a)
                       for r, a in zip(ratio, np.asarray(adv))])
    np.testing.assert_allclose(float(loss), expect, rtol=1e-6)
    assert float(clip_frac) == pytest.approx(1 / 3)


# --------------------------------------------------------------- distributions
def test_categorical_numerics():
    logits = jnp.log(jnp.asarray([[0.2, 0.3, 0.5]]))
    d = Categorical(logits)
    np.testing.assert_allclose(float(d.log_prob(jnp.asarray([2]))[0]),
                               np.log(0.5), rtol=1e-5)
    np.testing.assert_allclose(
        float(d.entropy()[0]),
        -(0.2 * np.log(0.2) + 0.3 * np.log(0.3) + 0.5 * np.log(0.5)),
        rtol=1e-5)
    assert int(d.mode()[0]) == 2
    samples = d.sample(jax.random.PRNGKey(0))
    assert samples.shape == (1,)


def test_diag_gaussian_numerics():
    d = DiagGaussian(jnp.zeros((1, 2)), jnp.zeros((1, 2)))
    # standard normal at 0: logp = -0.5*log(2π) per dim
    np.testing.assert_allclose(float(d.log_prob(jnp.zeros((1, 2)))[0]),
                               -np.log(2 * np.pi), rtol=1e-5)
    np.testing.assert_allclose(float(d.entropy()[0]),
                               2 * 0.5 * np.log(2 * np.pi * np.e), rtol=1e-5)
    other = DiagGaussian(jnp.zeros((1, 2)), jnp.zeros((1, 2)))
    np.testing.assert_allclose(float(d.kl(other)[0]), 0.0, atol=1e-6)


def test_running_mean_std():
    rng = np.random.default_rng(2)
    rms = RunningMeanStd(shape=(3,))
    data = rng.normal(loc=2.0, scale=3.0, size=(1000, 3))
    for chunk in np.split(data, 10):
        rms.update(chunk)
    np.testing.assert_allclose(rms.mean, data.mean(0), rtol=1e-6)
    np.testing.assert_allclose(rms.var, data.var(0), rtol=1e-4)


# ------------------------------------------------------------------ env runner
def test_env_runner_shapes_and_metrics():
    runner = EnvRunner("CartPole-v1", num_envs=4, rollout_len=50, seed=3)
    runner.set_weights(runner.init_params())
    batch = runner.sample()
    assert batch[SB.OBS].shape == (50, 4, 4)
    assert batch[SB.ACTIONS].shape == (50, 4)
    assert batch[SB.REWARDS].shape == (50, 4)
    assert batch[SB.BOOTSTRAP_VALUE].shape == (4,)
    m = runner.pop_metrics()
    assert m["episodes_this_iter"] > 0  # random policy ends episodes fast
    assert m["episode_return_mean"] > 0
    runner.close()


def test_compute_gae_batch_shapes():
    T, B = 8, 3
    batch = SampleBatch({
        SB.REWARDS: np.ones((T, B), np.float32),
        SB.VF_PREDS: np.zeros((T, B), np.float32),
        SB.BOOTSTRAP_VALUE: np.zeros(B, np.float32),
        SB.DONES: np.zeros((T, B), np.float32),
    })
    batch = compute_gae(batch, gamma=1.0, lam=1.0)
    # undiscounted, zero values: advantage at t = T - t remaining rewards
    np.testing.assert_allclose(batch[SB.ADVANTAGES][:, 0],
                               np.arange(T, 0, -1), rtol=1e-6)


def test_sample_batch_flatten_minibatch():
    b = SampleBatch({"x": np.arange(24).reshape(6, 4)})
    flat = b.flatten()
    assert flat["x"].shape == (24,)
    mbs = list(flat.minibatches(10))
    assert [m["x"].shape[0] for m in mbs] == [10, 10]


# -------------------------------------------------------------------- learning
@pytest.mark.slow
def test_ppo_learns_cartpole():
    config = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=8,
                     rollout_fragment_length=64)
        .training(lr=3e-4, train_batch_size=512, minibatch_size=128,
                  num_epochs=6, entropy_coeff=0.01)
        .debugging(seed=0)
    )
    algo = config.build()
    best = 0.0
    for _ in range(20):
        result = algo.train()
        best = max(best, result.get("episode_return_mean", 0.0))
        if best > 80.0:
            break
    algo.stop()
    assert best > 80.0, f"PPO failed to learn CartPole (best={best})"


def test_algorithm_checkpoint_roundtrip(tmp_path):
    config = (PPOConfig().environment("CartPole-v1")
              .env_runners(rollout_fragment_length=16)
              .training(train_batch_size=32, minibatch_size=16, num_epochs=1))
    algo = config.build()
    algo.train()
    ckpt = algo.save(str(tmp_path / "ck"))
    w0 = jax.tree_util.tree_leaves(algo.get_weights())[0]

    algo2 = config.copy().build()
    algo2.restore(ckpt)
    w1 = jax.tree_util.tree_leaves(algo2.get_weights())[0]
    np.testing.assert_array_equal(np.asarray(w0), np.asarray(w1))
    assert algo2.iteration == algo.iteration
    algo.stop()
    algo2.stop()


@pytest.mark.slow
def test_ppo_with_actor_env_runners(ray_session):
    """EnvRunners as ray_tpu actors: weights ship via the object store."""
    config = (PPOConfig().environment("CartPole-v1")
              .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                           rollout_fragment_length=32)
              .training(train_batch_size=128, minibatch_size=64, num_epochs=2))
    algo = config.build()
    r1 = algo.train()
    r2 = algo.train()
    assert r2["num_env_steps_sampled_this_iter"] >= 128
    assert "episode_return_mean" in r2 or r2["episodes_this_iter"] >= 0
    algo.stop()
