"""Ops numerics on the CPU mesh (SURVEY.md §4: pallas == XLA reference;
ring == dense; losses vs naive python)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

# jax moved shard_map out of experimental in 0.5.x; support the 0.4.x
# toolchain baked into this image too
try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

from ray_tpu.ops.attention import apply_rope, decode_attention, mha_reference
from ray_tpu.ops.flash_attention import flash_attention
from ray_tpu.ops.ring_attention import ring_attention
from ray_tpu.ops import losses
from ray_tpu.parallel.mesh import local_cpu_mesh


def _qkv(B=2, T=128, H=4, Kh=2, D=32, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return (jax.random.normal(ks[0], (B, T, H, D), dtype),
            jax.random.normal(ks[1], (B, T, Kh, D), dtype),
            jax.random.normal(ks[2], (B, T, Kh, D), dtype))


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_fwd_matches_reference(self, causal):
        q, k, v = _qkv()
        ref = mha_reference(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal, block_q=64, block_kv=64)
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_grads_match_reference(self):
        q, k, v = _qkv(T=128)
        gf = jax.grad(lambda *a: jnp.sum(
            flash_attention(*a, causal=True, block_q=64, block_kv=64) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: jnp.sum(
            mha_reference(*a, causal=True) ** 2), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(a, b, atol=5e-4)

    def test_mqa(self):
        q, k, v = _qkv(H=4, Kh=1)
        np.testing.assert_allclose(
            flash_attention(q, k, v, block_q=64, block_kv=64),
            mha_reference(q, k, v), atol=2e-5)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        mesh = local_cpu_mesh(4, {"sp": 4})
        q, k, v = _qkv(B=2, T=64, H=4, Kh=2, D=16)
        ring = shard_map(
            lambda a, b, c: ring_attention(a, b, c, axis_name="sp", causal=causal),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"))(q, k, v)
        ref = mha_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(ring, ref, atol=2e-5)


class TestRope:
    def test_norm_preserved(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 4, 32))
        pos = jnp.broadcast_to(jnp.arange(16)[None], (2, 16))
        y = apply_rope(x, pos)
        np.testing.assert_allclose(
            jnp.linalg.norm(y, axis=-1), jnp.linalg.norm(x, axis=-1), rtol=1e-5)

    def test_relative_property(self):
        # <rope(q,m), rope(k,n)> depends only on m-n
        d = 32
        q = jax.random.normal(jax.random.PRNGKey(1), (1, 1, 1, d))
        k = jax.random.normal(jax.random.PRNGKey(2), (1, 1, 1, d))
        def dot_at(m, n):
            qm = apply_rope(q, jnp.array([[m]]))
            kn = apply_rope(k, jnp.array([[n]]))
            return float(jnp.sum(qm * kn))
        assert abs(dot_at(5, 3) - dot_at(10, 8)) < 1e-4


class TestDecodeAttention:
    def test_masked_cache_matches_dense(self):
        B, S, H, Kh, D = 2, 32, 4, 2, 16
        q = jax.random.normal(jax.random.PRNGKey(0), (B, 1, H, D))
        k = jax.random.normal(jax.random.PRNGKey(1), (B, S, Kh, D))
        v = jax.random.normal(jax.random.PRNGKey(2), (B, S, Kh, D))
        n = 20  # tokens already in cache; q is the token at position n
        out = decode_attention(q, k, v, jnp.full((B,), n, jnp.int32))
        ref = mha_reference(q, k[:, :n + 1], v[:, :n + 1], causal=False)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_chunked_queries_causal(self):
        """T>1 chunk: query j only sees cache slots ≤ lengths+j."""
        B, S, T, H, Kh, D = 1, 16, 4, 2, 2, 8
        q = jax.random.normal(jax.random.PRNGKey(0), (B, T, H, D))
        k = jax.random.normal(jax.random.PRNGKey(1), (B, S, Kh, D))
        v = jax.random.normal(jax.random.PRNGKey(2), (B, S, Kh, D))
        n = 5
        out = decode_attention(q, k, v, jnp.array([n], jnp.int32))
        for j in range(T):
            ref = mha_reference(q[:, j:j + 1], k[:, :n + j + 1], v[:, :n + j + 1],
                                causal=False)
            np.testing.assert_allclose(out[:, j:j + 1], ref, atol=1e-5)


class TestLosses:
    def test_cross_entropy_uniform(self):
        logits = jnp.zeros((4, 8, 16))
        labels = jnp.zeros((4, 8), jnp.int32)
        loss, m = losses.cross_entropy(logits, labels)
        np.testing.assert_allclose(loss, np.log(16), rtol=1e-5)
        assert m["tokens"] == 32

    def test_cross_entropy_mask(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 8))
        labels = jnp.ones((2, 4), jnp.int32)
        mask = jnp.array([[1, 1, 0, 0], [1, 0, 0, 0]], jnp.float32)
        loss, m = losses.cross_entropy(logits, labels, mask=mask)
        # equals mean over the 3 unmasked tokens
        full = -jax.nn.log_softmax(logits)[..., 1]
        expect = (full[0, 0] + full[0, 1] + full[1, 0]) / 3
        np.testing.assert_allclose(loss, expect, rtol=1e-5)

    def test_gae_vs_naive(self):
        T = 7
        rng = np.random.RandomState(0)
        r = rng.randn(T).astype(np.float32)
        val = rng.randn(T + 1).astype(np.float32)
        done = np.array([0, 0, 1, 0, 0, 0, 0], np.float32)
        gamma, lam = 0.9, 0.8
        adv, tgt = losses.gae(jnp.array(r), jnp.array(val), jnp.array(done), gamma, lam)
        expect = np.zeros(T, np.float32)
        acc = 0.0
        for t in reversed(range(T)):
            nd = 1.0 - done[t]
            delta = r[t] + gamma * val[t + 1] * nd - val[t]
            acc = delta + gamma * lam * nd * acc
            expect[t] = acc
        np.testing.assert_allclose(adv, expect, rtol=1e-4)
        np.testing.assert_allclose(tgt, expect + val[:-1], rtol=1e-4)

    def test_vtrace_on_policy_is_gae_lambda1(self):
        # With rho=c=1 (on-policy) v-trace targets equal TD(lambda=1) returns.
        T = 5
        rng = np.random.RandomState(1)
        r = jnp.array(rng.randn(T), jnp.float32)
        val = jnp.array(rng.randn(T + 1), jnp.float32)
        done = jnp.zeros(T)
        lp = jnp.zeros(T)
        out = losses.vtrace(lp, lp, r, val, done, gamma=0.9)
        adv, tgt = losses.gae(r, val, done, gamma=0.9, lam=1.0)
        np.testing.assert_allclose(out.vs, tgt, rtol=1e-4)

    def test_ppo_surrogate_clip(self):
        lp = jnp.array([0.0, jnp.log(2.0)])
        old = jnp.zeros(2)
        adv = jnp.array([1.0, 1.0])
        loss, frac = losses.ppo_surrogate(lp, old, adv, clip=0.2)
        # ratios [1, 2] → clipped to [1, 1.2] → loss = -mean = -1.1
        np.testing.assert_allclose(loss, -1.1, rtol=1e-5)
        np.testing.assert_allclose(frac, 0.5)

    def test_huber(self):
        x = jnp.array([-2.0, 0.5, 2.0])
        np.testing.assert_allclose(losses.huber(x), [1.5, 0.125, 1.5])


class TestChunkedCrossEntropy:
    def test_matches_full_cross_entropy(self):
        from ray_tpu.ops.losses import chunked_cross_entropy, cross_entropy
        key = jax.random.PRNGKey(0)
        B, T, D, V = 2, 128, 32, 97
        hidden = jax.random.normal(key, (B, T, D), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(1), (D, V), jnp.float32) * 0.05
        labels = jax.random.randint(jax.random.PRNGKey(2), (B, T), 0, V)
        full, m_full = cross_entropy(hidden @ w, labels)
        chunked, m_chunk = chunked_cross_entropy(hidden, w, labels, chunk_size=32)
        np.testing.assert_allclose(chunked, full, rtol=1e-5)
        np.testing.assert_allclose(m_chunk["accuracy"], m_full["accuracy"], rtol=1e-5)

    def test_grads_match(self):
        from ray_tpu.ops.losses import chunked_cross_entropy, cross_entropy
        key = jax.random.PRNGKey(3)
        B, T, D, V = 2, 64, 16, 31
        hidden = jax.random.normal(key, (B, T, D), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(4), (D, V), jnp.float32) * 0.1
        labels = jax.random.randint(jax.random.PRNGKey(5), (B, T), 0, V)
        g_full = jax.grad(lambda h, w: cross_entropy(h @ w, labels)[0], argnums=(0, 1))(hidden, w)
        g_chunk = jax.grad(lambda h, w: chunked_cross_entropy(h, w, labels, chunk_size=16)[0],
                           argnums=(0, 1))(hidden, w)
        for a, b in zip(g_chunk, g_full):
            np.testing.assert_allclose(a, b, atol=1e-5)

    def test_model_return_hidden_consistent(self):
        from ray_tpu.models.llama import Llama, LlamaConfig
        cfg = LlamaConfig.tiny(max_seq_len=32)
        model = Llama(cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, cfg.vocab_size)
        params = model.init(jax.random.PRNGKey(1), tokens)
        logits, _ = model.apply(params, tokens)
        hidden, _ = model.apply(params, tokens, return_hidden=True)
        w = params["params"]["lm_head"]["kernel"]
        np.testing.assert_allclose(
            np.asarray(hidden.astype(jnp.float32)) @ np.asarray(w, dtype=np.float32),
            logits, atol=2e-2)
