"""Lineage reconstruction (VERDICT r1 #7; reference:
src/ray/core_worker/object_recovery_manager.cc:1-191): a lost object whose
creating task is known is re-executed transparently at get() time."""

import numpy as np
import pytest


def _controller():
    from ray_tpu._private import state
    return state.global_client().controller


def _zap(ref):
    """Destroy the object's backing storage, leaving the registry entry —
    simulates segment loss / eviction under memory pressure."""
    ctrl = _controller()
    ctrl.store.delete_segment(ref.id)


def test_get_reconstructs_lost_task_output(ray_session):
    ray = ray_session

    calls = {"n": 0}

    @ray.remote
    def make_array(seed):
        # >64KB so the result lands in shm, not inline
        rng = np.random.default_rng(seed)
        return rng.normal(size=(64, 256)).astype(np.float64)

    ref = make_array.remote(7)
    first = np.array(ray.get(ref), copy=True)
    _zap(ref)
    second = ray.get(ref)  # must re-execute make_array, not raise
    np.testing.assert_allclose(first, second)


def test_chained_lineage_recovers_upstream_first(ray_session):
    ray = ray_session

    @ray.remote
    def base():
        return np.arange(20_000, dtype=np.float64)

    @ray.remote
    def double(x):
        return x * 2

    b = base.remote()
    d = double.remote(b)
    expected = np.array(ray.get(d), copy=True)
    _zap(b)
    _zap(d)
    out = ray.get(d)  # recovers base, then double
    np.testing.assert_allclose(out, expected)
    np.testing.assert_allclose(np.array(ray.get(b)), np.arange(20_000) * 1.0)


def test_put_objects_are_not_reconstructable(ray_session):
    ray = ray_session
    from ray_tpu.exceptions import ObjectLostError

    ref = ray.put(np.ones(20_000))  # no creating task -> no lineage
    _zap(ref)
    with pytest.raises(ObjectLostError):
        ray.get(ref, timeout=30)


def test_actor_outputs_are_not_reconstructed(ray_session):
    ray = ray_session
    from ray_tpu.exceptions import ObjectLostError

    @ray.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return np.full(20_000, self.n)  # big enough for shm

    c = Counter.remote()
    ref = c.bump.remote()
    assert ray.get(ref)[0] == 1
    _zap(ref)
    # re-running bump() would return 2, not 1 — refuse instead of lying
    with pytest.raises(ObjectLostError):
        ray.get(ref, timeout=30)
    ray.kill(c)
