"""MPMD pipeline-parallel training over the actor fabric (train/mpmd.py):
schedule correctness vs single-process reference, parity vs the SPMD
`pipeline_apply` runner, ref-lifecycle bounds (LeakDetector), the
ship_window trace outbox, bubble_stats math, and the pipeline_bench
smoke gate."""

import json
import os
import subprocess
import sys
import time

import pytest


def _tanh_stages(num_stages, d=8, seed=0):
    import jax
    import jax.numpy as jnp
    key = jax.random.PRNGKey(seed)
    params = [
        {"w": jax.random.normal(jax.random.fold_in(key, i), (d, d)) / d,
         "b": jnp.ones((d,)) * 0.1}
        for i in range(num_stages)]

    def stage_fn(p, x):
        import jax.numpy as jnp
        return jnp.tanh(x @ p["w"] + p["b"])

    return params, stage_fn


def _mse(y, t):
    import jax.numpy as jnp
    return jnp.mean((y - t) ** 2)


def _reference_step(stage_fn, params, mbs, tgts, lr):
    """Single-process 1-step reference: mean loss over microbatches,
    grads averaged, one SGD step per stage."""
    import jax

    def full_loss(ps, x, t):
        for p in ps:
            x = stage_fn(p, x)
        return _mse(x, t)

    g = jax.grad(full_loss)
    losses = [float(full_loss(params, m, t)) for m, t in zip(mbs, tgts)]
    grads = [g(params, m, t) for m, t in zip(mbs, tgts)]
    mean_grads = jax.tree_util.tree_map(
        lambda *a: sum(a) / len(mbs), *grads)
    new_params = jax.tree_util.tree_map(
        lambda p, gg: p - lr * gg, params, mean_grads)
    return sum(losses) / len(losses), new_params


def _inputs(num_micro, mb_batch, d, seed=1):
    import jax
    import jax.numpy as jnp
    key = jax.random.PRNGKey(seed)
    mbs = [jax.random.normal(jax.random.fold_in(key, m), (mb_batch, d),
                             dtype=jnp.float32) for m in range(num_micro)]
    tgts = [jax.random.normal(jax.random.fold_in(key, 50 + m),
                              (mb_batch, d), dtype=jnp.float32) * 0.1
            for m in range(num_micro)]
    return mbs, tgts


# ------------------------------------------------------------ forward parity
def test_run_forward_matches_spmd_and_sequential(ray_session):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ray_tpu.parallel.mesh import make_mesh
    from ray_tpu.parallel.pipeline import (pipeline_apply,
                                           shard_pipeline_params,
                                           stack_stage_params)
    from ray_tpu.train.mpmd import build_pipeline

    S, d, M = 2, 8, 6
    params, stage_fn = _tanh_stages(S, d)
    mbs, _ = _inputs(M, 4, d)

    pipe = build_pipeline([stage_fn] * S, params)
    try:
        outs = pipe.run_forward(mbs)
    finally:
        pipe.shutdown()

    seq = [stage_fn(params[1], stage_fn(params[0], m)) for m in mbs]
    mesh = make_mesh({"pp": S}, devices=jax.devices()[:S])
    spmd = pipeline_apply(
        stage_fn, shard_pipeline_params(stack_stage_params(params), mesh),
        jnp.stack(mbs), mesh)

    # same jitted math on the same backend: bitwise, not just close
    assert np.array_equal(np.stack(outs), np.stack(seq))
    assert np.array_equal(np.stack(outs), np.asarray(spmd))


# -------------------------------------------------------------- 1F1B training
def test_train_step_matches_reference(ray_session):
    import numpy as np
    from ray_tpu.train.mpmd import build_pipeline, sgd

    S, d, M, lr = 2, 8, 6, 0.1
    params, stage_fn = _tanh_stages(S, d)
    mbs, tgts = _inputs(M, 4, d)

    pipe = build_pipeline([stage_fn] * S, params, loss_fn=_mse,
                          optimizer=sgd(lr))
    try:
        out = pipe.train_step(mbs, tgts)
        got_params = pipe.get_params()
    finally:
        pipe.shutdown()

    ref_loss, ref_params = _reference_step(stage_fn, params, mbs, tgts, lr)
    assert out["loss"] == pytest.approx(ref_loss, rel=1e-6)
    for got, want in zip(got_params, ref_params):
        np.testing.assert_allclose(np.asarray(got["w"]),
                                   np.asarray(want["w"]), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(got["b"]),
                                   np.asarray(want["b"]), rtol=1e-5)
    # 1F1B bounds live microbatch objects to ~S regardless of M
    assert out["stats"]["peak_live_refs"] <= S + 1, out["stats"]
    assert all(s["stash_depth"] == 0 for s in out["stats"]["stages"])


def test_train_step_fewer_microbatches_than_stages(ray_session):
    # M < S degenerates 1F1B to near-GPipe (all-warmup) but must still
    # produce the exact reference step
    from ray_tpu.train.mpmd import build_pipeline, sgd

    S, d, M, lr = 3, 8, 2, 0.1
    params, stage_fn = _tanh_stages(S, d)
    mbs, tgts = _inputs(M, 4, d)
    pipe = build_pipeline([stage_fn] * S, params, loss_fn=_mse,
                          optimizer=sgd(lr))
    try:
        out = pipe.train_step(mbs, tgts)
    finally:
        pipe.shutdown()
    ref_loss, _ = _reference_step(stage_fn, params, mbs, tgts, lr)
    assert out["loss"] == pytest.approx(ref_loss, rel=1e-6)


def test_train_step_rejects_mismatched_targets(ray_session):
    from ray_tpu.train.mpmd import build_pipeline, sgd

    params, stage_fn = _tanh_stages(2)
    mbs, tgts = _inputs(4, 2, 8)
    pipe = build_pipeline([stage_fn] * 2, params, loss_fn=_mse,
                          optimizer=sgd(0.1))
    try:
        with pytest.raises(ValueError, match="targets"):
            pipe.train_step(mbs, None)
        with pytest.raises(ValueError, match="4 microbatches but 3"):
            pipe.train_step(mbs, tgts[:3])
    finally:
        pipe.shutdown()


def test_build_pipeline_validates_lengths(ray_session):
    from ray_tpu.train.mpmd import build_pipeline

    params, stage_fn = _tanh_stages(2)
    with pytest.raises(ValueError, match="stage_params"):
        build_pipeline([stage_fn] * 2, params[:1])
    with pytest.raises(ValueError, match="node_ids"):
        build_pipeline([stage_fn] * 2, params, node_ids=["x"])


# ------------------------------------------------------------- ref lifecycle
def test_train_step_leaves_no_leaked_objects(ray_session):
    """Bounded-depth 1F1B releases every activation/grad ref: scanning the
    object table with the PR 11 LeakDetector at a far-future `now` (so ANY
    unreleased object trips it) must find nothing microbatch-sized."""
    from ray_tpu._private import state
    from ray_tpu._private.health import LeakDetector
    from ray_tpu.train.mpmd import build_pipeline, sgd

    S, d, M = 2, 64, 8
    params, stage_fn = _tanh_stages(S, d)
    mbs, tgts = _inputs(M, 64, d)  # 16 KiB activations: well above noise
    mb_bytes = 64 * d * 4

    pipe = build_pipeline([stage_fn] * S, params, loss_fn=_mse,
                          optimizer=sgd(0.1))
    try:
        pipe.train_step(mbs, tgts)
    finally:
        pipe.shutdown()
    time.sleep(0.5)  # let unpins/teardown drain through the loop thread

    ctl = state.global_client().controller
    det = LeakDetector(age_s=0.0, clock=lambda: time.time() + 3600.0)
    flagged = det.scan(ctl.objects)
    big = [f for f in flagged if (f.get("size") or 0) >= mb_bytes]
    assert not big, big


# ----------------------------------------------------------- trace shipping
def test_ship_window_outbox_drains():
    from ray_tpu.util import tracing

    os.environ["RAY_TPU_TRACE"] = "1"
    os.environ["RAY_TPU_TRACE_SAMPLE"] = "1.0"
    tracing.refresh()
    t0 = time.time()
    tracing.ship_window("pipeline.fwd", "pipeline", "tr-1", t0, t0 + 0.25,
                        tid=1234, args={"stage": 0, "mb": 3})
    shipped = tracing.take_shipped()
    assert len(shipped) == 1
    ev = shipped[0]
    assert ev["name"] == "pipeline.fwd" and ev["cat"] == "pipeline"
    assert ev["tid"] == 1234 and ev["args"]["mb"] == 3
    assert ev["dur"] == pytest.approx(0.25e6, rel=1e-3)  # µs, Chrome format
    assert tracing.take_shipped() == []  # drained
    # the window also lands in the local ring for in-process consumers
    assert any(s["name"] == "pipeline.fwd" for s in tracing.events())
    tracing.ship_window("x", "pipeline", None, t0, t0)
    tracing.clear()
    assert tracing.take_shipped() == []  # clear() empties the outbox too


# -------------------------------------------------------------- bubble math
def _win(name, tid, ts, dur, phase=None, cat="task_phase"):
    ev = {"name": name, "cat": cat, "ph": "X", "pid": 1, "tid": tid,
          "ts": ts * 1e6, "dur": dur * 1e6}
    if phase:
        ev["args"] = {"phase": phase}
    return ev


def test_bubble_stats_per_worker_fractions():
    from ray_tpu.util.tracing import bubble_stats

    events = [
        # worker 1: busy [0,1] and [3,4] over span [0,4] -> bubble 0.5
        _win("a.forward:exec", 1, 0.0, 1.0, "exec"),
        _win("a.forward:exec", 1, 3.0, 1.0, "exec"),
        # worker 2: solid [0,2] -> bubble 0
        _win("b.forward:exec", 2, 0.0, 2.0, "exec"),
        # non-exec phases and foreign categories are ignored
        _win("a.forward:xfer", 1, 1.0, 2.0, "xfer"),
        _win("other", 1, 1.0, 2.0, cat="counter"),
    ]
    stats = bubble_stats(events)
    assert stats["workers"][1]["bubble_fraction"] == pytest.approx(0.5)
    assert stats["workers"][1]["windows"] == 2
    assert stats["workers"][2]["bubble_fraction"] == pytest.approx(0.0)
    assert stats["overall"]["busy_s"] == pytest.approx(4.0)
    # name_prefix filters; extra_cats admits stage-shipped windows whole
    assert bubble_stats(events, name_prefix="zzz")["workers"] == {}
    pip = bubble_stats(
        [_win("pipeline.fwd", 9, 0.0, 1.0, cat="pipeline")],
        extra_cats=("pipeline",))
    assert pip["workers"][9]["windows"] == 1


def test_bubble_stats_merges_overlapping_windows():
    from ray_tpu.util.tracing import bubble_stats

    events = [_win("a:exec", 1, 0.0, 2.0, "exec"),
              _win("a:exec", 1, 1.0, 2.0, "exec")]  # overlap, no double count
    w = bubble_stats(events)["workers"][1]
    assert w["busy_s"] == pytest.approx(3.0)
    assert w["bubble_fraction"] == pytest.approx(0.0)


def test_timeline_bubble_cli_render():
    from ray_tpu.__main__ import _render_bubble
    from ray_tpu.util.tracing import bubble_stats

    out = _render_bubble(bubble_stats(
        [_win("a:exec", 1, 0.0, 1.0, "exec"),
         _win("a:exec", 1, 3.0, 1.0, "exec")]))
    assert "Bubble fractions" in out
    assert "50.0%" in out
    empty = _render_bubble(bubble_stats([]))
    assert "no exec-phase windows" in empty


# ---------------------------------------------------------------- smoke gate
def test_pipeline_bench_smoke_gate():
    """pipeline_bench --smoke is the tier-1 hook for the full stack: MPMD
    vs SPMD bitwise parity, stage-shipped fwd/bwd windows and nonzero
    xfer phases on the head timeline, leak-free 1F1B."""
    bench = os.path.join(os.path.dirname(__file__), os.pardir,
                         "benchmarks", "pipeline_bench.py")
    proc = subprocess.run(
        [sys.executable, bench, "--smoke"], capture_output=True, text=True,
        timeout=420, env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["smoke"] == "ok"
    assert rec["parity"]["bitwise_equal"] is True
    assert rec["xfer_windows"] > 0
