"""Core task API tests (model: python/ray/tests/test_basic.py)."""

import time

import numpy as np
import pytest


def test_simple_task(ray_session):
    ray = ray_session

    @ray.remote
    def f(x):
        return x + 1

    assert ray.get(f.remote(1)) == 2


def test_many_tasks_parallel(ray_session):
    ray = ray_session

    @ray.remote
    def sq(x):
        return x * x

    assert ray.get([sq.remote(i) for i in range(20)]) == [i * i for i in range(20)]


def test_task_chaining_refs(ray_session):
    ray = ray_session

    @ray.remote
    def double(x):
        return 2 * x

    @ray.remote
    def add(a, b):
        return a + b

    assert ray.get(add.remote(double.remote(3), double.remote(4))) == 14


def test_put_get_roundtrip(ray_session):
    ray = ray_session
    for val in [42, "hello", {"a": [1, 2]}, None, (1, "x")]:
        assert ray.get(ray.put(val)) == val


def test_put_get_numpy_zero_copy(ray_session):
    ray = ray_session
    arr = np.random.rand(100_000).astype(np.float32)
    out = ray.get(ray.put(arr))
    np.testing.assert_array_equal(out, arr)
    # zero-copy: the result aliases shared memory, so it's read-only
    assert not out.flags.writeable


def test_task_numpy_arg_and_result(ray_session):
    ray = ray_session

    @ray.remote
    def scale(a, k):
        return a * k

    arr = np.arange(50_000, dtype=np.float32)
    out = ray.get(scale.remote(ray.put(arr), 3.0))
    np.testing.assert_allclose(out, arr * 3.0)


def test_num_returns(ray_session):
    ray = ray_session

    @ray.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert ray.get([a, b, c]) == [1, 2, 3]


def test_options_override(ray_session):
    ray = ray_session

    @ray.remote
    def two():
        return 1, 2

    a, b = two.options(num_returns=2).remote()
    assert ray.get([a, b]) == [1, 2]


def test_error_propagation(ray_session):
    ray = ray_session

    @ray.remote
    def boom():
        raise ValueError("sad")

    with pytest.raises(ray.exceptions.TaskError) as ei:
        ray.get(boom.remote())
    assert "sad" in str(ei.value)
    assert isinstance(ei.value.cause, ValueError)


def test_error_through_dependency(ray_session):
    ray = ray_session

    @ray.remote
    def boom():
        raise RuntimeError("upstream")

    @ray.remote
    def consume(x):
        return x

    with pytest.raises(ray.exceptions.RayTpuError):
        ray.get(consume.remote(boom.remote()))


def test_wait_basic(ray_session):
    ray = ray_session

    @ray.remote
    def fast():
        return "fast"

    @ray.remote
    def slow():
        time.sleep(3)
        return "slow"

    f_ref, s_ref = fast.remote(), slow.remote()
    ready, rest = ray.wait([f_ref, s_ref], num_returns=1, timeout=10)
    assert ready == [f_ref] and rest == [s_ref]
    ready2, rest2 = ray.wait([s_ref], num_returns=1, timeout=15)
    assert ready2 == [s_ref]


def test_get_timeout(ray_session):
    ray = ray_session

    @ray.remote
    def hang():
        time.sleep(30)

    ref = hang.remote()
    with pytest.raises(ray.exceptions.GetTimeoutError):
        ray.get(ref, timeout=0.2)
    ray.cancel(ref, force=True)


def test_cancel_pending(ray_session):
    ray = ray_session

    @ray.remote
    def sleepy(t):
        time.sleep(t)
        return t

    # saturate the 4-cpu pool, then cancel a queued task
    running = [sleepy.remote(2) for _ in range(4)]
    queued = sleepy.remote(0)
    ray.cancel(queued)
    with pytest.raises((ray.exceptions.TaskCancelledError, ray.exceptions.TaskError)):
        ray.get(queued, timeout=15)
    ray.get(running)  # drain


def test_nested_tasks(ray_session):
    ray = ray_session

    @ray.remote
    def inner(x):
        return x * 10

    @ray.remote
    def outer(x):
        import ray_tpu
        return ray_tpu.get(inner.remote(x)) + 1

    assert ray.get(outer.remote(4)) == 41


def test_streaming_generator(ray_session):
    ray = ray_session

    @ray.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * i

    out = [ray.get(r) for r in gen.remote(5)]
    assert out == [0, 1, 4, 9, 16]


def test_retries_on_worker_crash(ray_session):
    ray = ray_session

    @ray.remote(max_retries=2)
    def flaky(path):
        import os
        if not os.path.exists(path):
            open(path, "w").close()
            os._exit(1)  # simulate worker crash on first attempt
        return "recovered"

    import tempfile
    path = tempfile.mktemp()
    assert ray.get(flaky.remote(path), timeout=60) == "recovered"


def test_runtime_context_in_task(ray_session):
    ray = ray_session

    @ray.remote
    def ctx():
        import ray_tpu
        c = ray_tpu.get_runtime_context()
        return c.get_task_id(), c.get_worker_id()

    task_id, worker_id = ray.get(ctx.remote())
    assert task_id.startswith("task-")
    assert worker_id.startswith("worker-")


def test_cluster_resources(ray_session):
    ray = ray_session
    total = ray.cluster_resources()
    assert total["CPU"] == 4.0
    avail = ray.available_resources()
    assert avail["CPU"] <= total["CPU"]


def test_large_object_shm(ray_session):
    ray = ray_session
    big = np.ones((512, 1024), dtype=np.float32)  # 2MB → shm path

    @ray.remote
    def total(a):
        return float(a.sum())

    assert ray.get(total.remote(ray.put(big))) == float(big.sum())
