"""Relational data ops (VERDICT r4 missing #4): join / unique / map_groups
ride the streaming shuffle machinery — pandas is the equivalence oracle.
Ref: /root/reference/python/ray/data/dataset.py:2893 (join), :3132 (unique),
grouped_data.py (map_groups).
"""

import numpy as np
import pandas as pd
import pytest

from ray_tpu import data as rdata


def _left_right(n_left=900, n_right=700, nkey=37, seed=0):
    rng = np.random.default_rng(seed)
    left = pd.DataFrame({
        "k": rng.integers(0, nkey, n_left),
        "k2": rng.integers(0, 3, n_left),
        "lval": rng.standard_normal(n_left).round(6),
    })
    right = pd.DataFrame({
        "k": rng.integers(0, nkey, n_right),
        "k2": rng.integers(0, 3, n_right),
        "rval": rng.standard_normal(n_right).round(6),
    })
    return left, right


def _canon(df: pd.DataFrame) -> pd.DataFrame:
    cols = sorted(df.columns)
    return (df[cols].sort_values(cols).reset_index(drop=True)
            .astype({c: "float64" for c in cols
                     if df[c].dtype.kind in "if"}))


def _ds_from_df(df: pd.DataFrame, n_blocks: int):
    import pyarrow as pa
    edges = np.linspace(0, len(df), n_blocks + 1).astype(int)
    parts = [df.iloc[a:b] for a, b in zip(edges[:-1], edges[1:])]
    return rdata.from_blocks(
        [pa.Table.from_pandas(p, preserve_index=False) for p in parts])


@pytest.mark.parametrize("how", ["inner", "left"])
@pytest.mark.parametrize("on", ["k", ["k", "k2"]])
def test_join_matches_pandas(ray_session, how, on):
    left, right = _left_right()
    lds = _ds_from_df(left, 5)
    rds = _ds_from_df(right, 4)
    got = pd.DataFrame(
        lds.join(rds, on, how=how, num_partitions=4).take_all())
    want = left.merge(right, on=on, how=how, suffixes=("", "_1"))
    assert len(got) == len(want), (len(got), len(want))
    pd.testing.assert_frame_equal(_canon(got), _canon(want))


def test_join_right_and_outer(ray_session):
    left, right = _left_right(n_left=300, n_right=250, nkey=60)
    lds = _ds_from_df(left, 3)
    rds = _ds_from_df(right, 3)
    for how in ("right", "outer"):
        got = pd.DataFrame(
            lds.join(rds, "k", how=how, num_partitions=3).take_all())
        want = left.merge(right, on="k", how=how, suffixes=("", "_1"))
        assert len(got) == len(want), (how, len(got), len(want))
        pd.testing.assert_frame_equal(_canon(got), _canon(want))


def test_join_streaming_partitions_stay_off_driver(ray_session):
    """The join must never concat-the-world: with the runtime up, side
    partitions move as refs (worker->worker); the driver-gated byte peak of
    the pairing stage stays ~one partition, not the dataset."""
    left, right = _left_right(n_left=2000, n_right=2000, nkey=101)
    lds = _ds_from_df(left, 8)
    rds = _ds_from_df(right, 8)
    ds = lds.join(rds, "k", how="inner", num_partitions=8)
    n = 0
    for blk in ds._plan.iter_blocks():  # stream, no take_all
        n += blk.num_rows
    want = left.merge(right, on="k", how="inner")
    assert n == len(want)
    # the streaming path must actually be the refs path: every pairing
    # thunk joins by REF (worker->worker bytes), not a pre-materialized
    # driver-side block — guard against silent fallback
    thunks = ds._plan.source.thunks
    assert len(thunks) == 8
    assert all("_pair_join_refs" in t.__code__.co_names for t in thunks)


def test_join_disjoint_and_empty_overlap(ray_session):
    left = pd.DataFrame({"k": [1, 2, 3], "a": [10.0, 20.0, 30.0]})
    right = pd.DataFrame({"k": [7, 8], "b": [1.0, 2.0]})
    lds = _ds_from_df(left, 2)
    rds = _ds_from_df(right, 1)
    assert lds.join(rds, "k", how="inner", num_partitions=3).take_all() == []
    got = pd.DataFrame(
        lds.join(rds, "k", how="left", num_partitions=3).take_all())
    assert len(got) == 3 and got["b"].isna().all()


def test_unique(ray_session):
    rng = np.random.default_rng(1)
    vals = rng.integers(0, 50, 1200)
    ds = _ds_from_df(pd.DataFrame({"v": vals, "w": vals * 2}), 6)
    got = ds.unique("v")
    assert sorted(got) == sorted(np.unique(vals).tolist())


def test_map_groups_matches_pandas(ray_session):
    rng = np.random.default_rng(2)
    df = pd.DataFrame({"g": rng.integers(0, 9, 400),
                       "x": rng.standard_normal(400).round(6)})
    ds = _ds_from_df(df, 5)

    def normalize(g):
        return {"g": g["g"].to_numpy(),
                "x_norm": (g["x"] - g["x"].mean()).to_numpy()}

    got = pd.DataFrame(ds.groupby("g").map_groups(normalize).take_all())
    want = df.copy()
    want["x_norm"] = df.groupby("g")["x"].transform(lambda s: s - s.mean())
    want = want[["g", "x_norm"]]
    pd.testing.assert_frame_equal(_canon(got), _canon(want))


def test_map_groups_numpy_format_and_row_lists(ray_session):
    df = pd.DataFrame({"g": [0, 0, 1, 1, 1], "x": [1.0, 3.0, 2.0, 4.0, 6.0]})
    ds = _ds_from_df(df, 2)

    def summarize(batch):  # numpy dict in, row list out
        return [{"g": int(batch["g"][0]), "mean": float(batch["x"].mean())}]

    got = sorted(ds.groupby("g").map_groups(
        summarize, batch_format="numpy").take_all(),
        key=lambda r: r["g"])
    assert got == [{"g": 0, "mean": 2.0}, {"g": 1, "mean": 4.0}]
