"""Placement groups (ref: python/ray/tests/test_placement_group.py):
reservation accounting, bundle-scoped scheduling, strategy validation,
single-node STRICT_SPREAD infeasibility, and in-task group capture."""

import pytest

from ray_tpu import util as rt_util
from ray_tpu.util import (PlacementGroupSchedulingStrategy,
                          get_current_placement_group, placement_group,
                          remove_placement_group)


def test_reserve_schedule_and_release(ray_session):
    ray = ray_session
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.wait(10)
    assert ray.get(pg.ready())
    assert pg.bundle_specs == [{"CPU": 1}, {"CPU": 1}]

    @ray.remote
    def where():
        cur = get_current_placement_group()
        return None if cur is None else (cur.id, cur.strategy, cur.bundles)

    got = ray.get(where.options(scheduling_strategy=PlacementGroupSchedulingStrategy(
        placement_group=pg, placement_group_bundle_index=1)).remote())
    assert got == (pg.id, "PACK", [{"CPU": 1}, {"CPU": 1}])
    # outside any group: None
    assert ray.get(where.remote()) is None

    # the reservation is carved out of the cluster pool and returned on remove
    total, avail_with_pg = ray.cluster_resources(), ray.available_resources()
    remove_placement_group(pg)
    import time
    deadline = time.time() + 10
    while time.time() < deadline and \
            ray.available_resources().get("CPU", 0) <= avail_with_pg.get("CPU", 0):
        time.sleep(0.05)
    assert ray.available_resources()["CPU"] == avail_with_pg["CPU"] + 2


def test_invalid_strategy_rejected(ray_session):
    with pytest.raises(ValueError, match="Invalid placement strategy"):
        placement_group([{"CPU": 1}], strategy="SCATTER")


def test_strict_spread_infeasible_on_one_node(ray_session):
    with pytest.raises(ValueError, match="STRICT_SPREAD"):
        placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    # one bundle on one node is satisfiable
    pg = placement_group([{"CPU": 1}], strategy="STRICT_SPREAD")
    remove_placement_group(pg)


def test_spread_accepted_single_node(ray_session):
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="SPREAD")
    remove_placement_group(pg)


def test_remove_pg_fails_queued_tasks(ray_session):
    """Removing a group with tasks still queued fails them loudly instead of
    wedging the scheduler (r3 review finding), and later tasks still run."""
    ray = ray_session
    pg = placement_group([{"CPU": 1}], strategy="PACK")

    @ray.remote
    def blocker():
        import time
        time.sleep(1.5)
        return "done"

    @ray.remote
    def queued():
        return "ran"

    strat = PlacementGroupSchedulingStrategy(placement_group=pg)
    running = blocker.options(scheduling_strategy=strat).remote()
    stuck = queued.options(scheduling_strategy=strat).remote()
    import time
    time.sleep(0.3)  # let blocker occupy the bundle; `stuck` stays queued
    remove_placement_group(pg)
    with pytest.raises(Exception, match="placement group|removed"):
        ray.get(stuck, timeout=60)
    # the scheduler keeps working afterwards
    assert ray.get(queued.remote(), timeout=60) == "ran"


def test_pg_churn_bounded_signatures(ray_session):
    """Creating/removing many PGs must not grow the scheduler's signature
    table unboundedly (slots retire and get reused)."""
    from ray_tpu._private import state
    ray = ray_session

    @ray.remote
    def f():
        return 1

    ctrl = state.global_client().controller
    for _ in range(25):
        pg = placement_group([{"CPU": 1}], strategy="PACK")
        strat = PlacementGroupSchedulingStrategy(placement_group=pg)
        assert ray.get(f.options(scheduling_strategy=strat).remote(),
                       timeout=60) == 1
        remove_placement_group(pg)
    # slots are reused: far fewer live entries than 25 churn rounds
    live = sum(1 for m in ctrl.ready_queue._sig_meta if not m["dead"])
    assert live < 15, live
