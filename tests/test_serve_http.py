"""Serve HTTP ingress e2e (VERDICT r1 #3): real HTTP through the asyncio
proxy — JSON round-trip, routing, 404s, streaming SSE, and drain."""

import http.client
import json

import pytest


def _req(port, method, path, body=None, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request(method, path, body=body, headers=headers or {})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, dict(resp.getheaders()), data


@pytest.fixture()
def serve_app(ray_session):
    from ray_tpu import serve
    yield serve
    serve.shutdown()


def test_http_json_roundtrip_and_routes(serve_app):
    serve = serve_app

    @serve.deployment
    class Echo:
        def __call__(self, request):
            payload = request.json()
            return {"path": request.path, "method": request.method,
                    "doubled": [2 * x for x in payload["xs"]]}

    serve.run(Echo.bind(), name="echo", route_prefix="/echo")
    port = serve.start(http_options={"port": 0})

    status, headers, data = _req(
        port, "POST", "/echo/run?x=1", body=json.dumps({"xs": [1, 2, 3]}),
        headers={"Content-Type": "application/json"})
    assert status == 200
    assert headers["Content-Type"] == "application/json"
    out = json.loads(data)
    assert out == {"path": "/run", "method": "POST", "doubled": [2, 4, 6]}

    # unknown route -> 404
    status, _, _ = _req(port, "GET", "/nope")
    assert status == 404

    # health + route table
    status, _, data = _req(port, "GET", "/-/healthz")
    assert (status, data) == (200, b"ok")
    status, _, data = _req(port, "GET", "/-/routes")
    assert status == 200
    assert json.loads(data)["/echo"] == "echo:Echo"


def test_http_streaming_sse(serve_app):
    serve = serve_app

    @serve.deployment
    class Tokens:
        def __call__(self, request):
            n = int(request.query_params.get("n", 3))
            for i in range(n):
                yield {"token": i}

    serve.run(Tokens.bind(), name="gen", route_prefix="/gen")
    port = serve.start(http_options={"port": 0})

    status, headers, data = _req(port, "GET", "/gen?n=4")
    assert status == 200
    assert headers["Content-Type"].startswith("text/event-stream")
    events = [line[len("data: "):] for line in data.decode().split("\n")
              if line.startswith("data: ")]
    assert events[-1] == "[DONE]"
    toks = [json.loads(e)["token"] for e in events[:-1]]
    assert toks == [0, 1, 2, 3]


def test_http_error_paths(serve_app):
    serve = serve_app

    @serve.deployment
    class Boom:
        def __call__(self, request):
            raise RuntimeError("kaboom")

    serve.run(Boom.bind(), name="boom", route_prefix="/boom")
    port = serve.start(http_options={"port": 0})

    status, _, data = _req(port, "GET", "/boom")
    assert status == 500
    assert b"kaboom" in data

    # malformed Content-Length -> 400
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.putrequest("POST", "/boom", skip_accept_encoding=True)
    conn.putheader("Content-Length", "abc")
    conn.endheaders()
    resp = conn.getresponse()
    assert resp.status == 400
    conn.close()


def test_http_function_deployment_and_text(serve_app):
    serve = serve_app

    @serve.deployment
    def hello(request):
        return f"hello {request.query_params.get('name', 'world')}"

    serve.run(hello.bind(), name="hello", route_prefix="/hello")
    port = serve.start(http_options={"port": 0})
    status, headers, data = _req(port, "GET", "/hello?name=tpu")
    assert status == 200
    assert data == b"hello tpu"
    assert headers["Content-Type"].startswith("text/plain")


def test_http_streaming_llm_tokens(serve_app):
    """VERDICT r1 done-criterion: a streaming LLM response over real HTTP —
    the ingress hosts the continuous-batching LLMServer (jitted decode) and
    streams generated tokens as SSE events."""
    serve = serve_app

    @serve.deployment
    class LLMIngress:
        def __init__(self):
            from ray_tpu.serve.llm import LLMConfig, LLMServer
            self.srv = LLMServer(LLMConfig(preset="tiny", max_batch_slots=2,
                                           max_seq_len=64, temperature=0.0))

        async def __call__(self, request):
            body = request.json()
            async for tok in self.srv.generate_stream(
                    body["prompt_ids"], max_tokens=body.get("max_tokens", 5)):
                yield {"token": int(tok)}

    serve.run(LLMIngress.bind(), name="llm", route_prefix="/llm")
    port = serve.start(http_options={"port": 0})

    status, headers, data = _req(
        port, "POST", "/llm", body=json.dumps({"prompt_ids": [3, 1, 4],
                                               "max_tokens": 6}),
        headers={"Content-Type": "application/json"})
    assert status == 200
    assert headers["Content-Type"].startswith("text/event-stream")
    events = [line[len("data: "):] for line in data.decode().split("\n")
              if line.startswith("data: ")]
    assert events[-1] == "[DONE]"
    toks = [json.loads(e)["token"] for e in events[:-1]]
    assert len(toks) == 6
    assert all(0 <= t < 256 for t in toks)


def test_http_chunked_request_body(serve_app):
    """Clients that stream uploads send Transfer-Encoding: chunked; the proxy
    must reassemble the body (VERDICT r2 weak #7 — previously a 411)."""
    serve = serve_app

    @serve.deployment
    class Len:
        def __call__(self, request):
            return {"n": len(request.body), "text": request.body.decode()}

    serve.run(Len.bind(), name="len", route_prefix="/len")
    port = serve.start(http_options={"port": 0})

    import socket
    s = socket.create_connection(("127.0.0.1", port), timeout=30)
    chunks = [b"hello ", b"chunked ", b"world"]
    payload = b"".join(
        hex(len(c))[2:].encode() + b"\r\n" + c + b"\r\n" for c in chunks)
    s.sendall(b"POST /len HTTP/1.1\r\nHost: x\r\n"
              b"Transfer-Encoding: chunked\r\n\r\n" + payload + b"0\r\n\r\n")
    s.settimeout(30)
    resp = b""
    while b"\r\n\r\n" not in resp:
        resp += s.recv(4096)
    head, _, body = resp.partition(b"\r\n\r\n")
    n = int([h for h in head.split(b"\r\n")
             if h.lower().startswith(b"content-length")][0].split(b":")[1])
    while len(body) < n:
        body += s.recv(4096)
    s.close()
    assert head.startswith(b"HTTP/1.1 200")
    out = json.loads(body)
    assert out == {"n": 19, "text": "hello chunked world"}


def test_http_body_size_cap(serve_app, monkeypatch):
    """An oversized body is rejected with 413 instead of buffered into proxy
    memory (advisor r3: unbounded chunked uploads)."""
    # the proxy runs in its own worker process and reads the cap from the
    # env at import; workers inherit the driver's environ
    monkeypatch.setenv("RAY_TPU_MAX_HTTP_BODY", "1024")
    serve = serve_app

    @serve.deployment
    def echo(request):
        return {"n": len(request.body)}

    serve.run(echo.bind(), name="cap", route_prefix="/cap")
    port = serve.start(http_options={"port": 0})

    import socket

    def _roundtrip(raw: bytes) -> bytes:
        s = socket.create_connection(("127.0.0.1", port), timeout=30)
        s.sendall(raw)
        s.settimeout(30)
        resp = b""
        while b"\r\n\r\n" not in resp:
            chunk = s.recv(4096)
            if not chunk:
                break
            resp += chunk
        s.close()
        return resp

    # Content-Length over the cap: rejected before reading the body
    resp = _roundtrip(b"POST /cap HTTP/1.1\r\nHost: x\r\n"
                      b"Content-Length: 99999\r\n\r\n")
    assert resp.startswith(b"HTTP/1.1 413")

    # chunked body over the cap: rejected mid-stream
    big = b"x" * 600
    payload = b"".join(
        hex(len(c))[2:].encode() + b"\r\n" + c + b"\r\n" for c in [big, big])
    resp = _roundtrip(b"POST /cap HTTP/1.1\r\nHost: x\r\n"
                      b"Transfer-Encoding: chunked\r\n\r\n" + payload +
                      b"0\r\n\r\n")
    assert resp.startswith(b"HTTP/1.1 413")

    # an in-budget request still works
    resp = _roundtrip(b"POST /cap HTTP/1.1\r\nHost: x\r\n"
                      b"Content-Length: 3\r\n\r\nabc")
    assert resp.startswith(b"HTTP/1.1 200")
