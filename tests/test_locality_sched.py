"""Locality-aware placement units (PR 7 tentpole, scheduler layer).

Drives ClusterServer.place/_default_place directly against a fake head
controller and fake NodeConn mirrors — no sockets, no workers — asserting
the scoring rules: max-resident-arg-bytes wins when resources permit,
resource-FIFO fallback otherwise, SPREAD/affinity strategies stay
authoritative, and every scored decision lands in the sched_locality_*
counters.
"""

import os
import sys
import types

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ray_tpu._private.cluster import ClusterServer, NodeConn  # noqa: E402
from ray_tpu._private.task_spec import ObjectMeta, TaskSpec  # noqa: E402
from ray_tpu.util import metrics  # noqa: E402
from ray_tpu.util.scheduling_strategies import (  # noqa: E402
    NodeAffinitySchedulingStrategy)


def _head(cpus=2.0):
    return types.SimpleNamespace(
        node_id="head", available={"CPU": cpus}, total={"CPU": cpus},
        ready_queue=[], objects={})


def _node(cs, node_id, cpus=2.0, avail=None):
    n = NodeConn(node_id=node_id, writer=None, resources={"CPU": cpus},
                 available={"CPU": cpus if avail is None else avail})
    cs.nodes[node_id] = n
    return n


def _obj(cs, oid, size, location, holders=()):
    cs.c.objects[oid] = ObjectMeta(object_id=oid, size=size,
                                   location=location, holders=list(holders))


def _spec(refs=(), cpus=1.0, strategy=None, nested=()):
    return TaskSpec(task_id="t-1", fn_blob=b"", resources={"CPU": cpus},
                    args=[("ref", r) for r in refs],
                    nested_refs=list(nested), scheduling_strategy=strategy)


def _rec(spec):
    return types.SimpleNamespace(spec=spec)


def _loc():
    return metrics.sched_locality_counters()


def test_args_resident_on_node_win_placement():
    cs = ClusterServer(_head())
    a = _node(cs, "node-a")
    _node(cs, "node-b")
    _obj(cs, "o1", 50 << 20, "remote:node-a")
    before = _loc()
    assert cs.place(_rec(_spec(refs=["o1"]))) is a
    after = _loc()
    assert after["hits"] == before["hits"] + 1
    assert after["bytes"] == before["bytes"] + (50 << 20)


def test_head_resident_args_prefer_head():
    cs = ClusterServer(_head())
    _node(cs, "node-a")
    _obj(cs, "o1", 10 << 20, "shm")
    before = _loc()
    assert cs.place(_rec(_spec(refs=["o1"]))) is None  # None = head
    assert _loc()["hits"] == before["hits"] + 1


def test_biggest_resident_bytes_wins_across_candidates():
    cs = ClusterServer(_head())
    _node(cs, "node-a")
    b = _node(cs, "node-b")
    _obj(cs, "small", 1 << 20, "remote:node-a")
    _obj(cs, "big", 30 << 20, "remote:node-b")
    assert cs.place(_rec(_spec(refs=["small", "big"]))) is b


def test_nested_refs_count_toward_locality():
    cs = ClusterServer(_head())
    a = _node(cs, "node-a")
    _obj(cs, "o1", 5 << 20, "remote:node-a")
    assert cs.place(_rec(_spec(nested=["o1"]))) is a


def test_holder_copies_are_extra_candidates():
    """Owner full → a registered secondary holder still gets the task (and
    it scores as a HIT: the copy is just as local)."""
    cs = ClusterServer(_head())
    _node(cs, "node-a", avail=0.0)  # owner: no room
    b = _node(cs, "node-b")
    _obj(cs, "o1", 20 << 20, "remote:node-a", holders=["node-b"])
    before = _loc()
    assert cs.place(_rec(_spec(refs=["o1"]))) is b
    assert _loc()["hits"] == before["hits"] + 1


def test_resource_pressure_falls_back_to_fifo_with_miss():
    """Bytes exist only on a full node → miss counted, task goes where the
    resources are."""
    cs = ClusterServer(_head())
    _node(cs, "node-a", avail=0.0)
    b = _node(cs, "node-b", cpus=4.0)
    _obj(cs, "o1", 20 << 20, "remote:node-a")
    before = _loc()
    placed = cs.place(_rec(_spec(refs=["o1"], cpus=3.0)))  # head can't fit 3
    assert placed is b
    assert _loc()["misses"] == before["misses"] + 1


def test_no_ref_args_means_no_locality_accounting():
    cs = ClusterServer(_head())
    _node(cs, "node-a")
    before = _loc()
    cs.place(_rec(_spec()))
    after = _loc()
    assert after["hits"] == before["hits"]
    assert after["misses"] == before["misses"]


def test_spread_stays_authoritative():
    """SPREAD round-robins across hosts even when every arg byte lives on
    one node."""
    cs = ClusterServer(_head())
    _node(cs, "node-a")
    _obj(cs, "o1", 40 << 20, "remote:node-a")
    targets = {id(cs.place(_rec(_spec(refs=["o1"], strategy="SPREAD"))))
               for _ in range(4)}
    assert len(targets) == 2  # head + node, not node-only


def test_user_node_affinity_pin_ignores_locality():
    cs = ClusterServer(_head())
    _node(cs, "node-a")
    b = _node(cs, "node-b")
    _obj(cs, "o1", 40 << 20, "remote:node-a")
    strat = NodeAffinitySchedulingStrategy(node_id="node-b", soft=False)
    assert cs.place(_rec(_spec(refs=["o1"], strategy=strat))) is b


def test_locality_hint_queues_at_busy_owner():
    """A merely busy hinted owner still wins — the task queues there (task
    wait ≪ block transfer); only dead/infeasible targets fall back."""
    cs = ClusterServer(_head())
    a = _node(cs, "node-a", avail=0.0)
    _node(cs, "node-b")
    _obj(cs, "o1", 20 << 20, "remote:node-a")
    strat = NodeAffinitySchedulingStrategy(node_id="node-a", soft=True,
                                           locality_hint=True)
    before = _loc()
    assert cs.place(_rec(_spec(refs=["o1"], strategy=strat))) is a
    assert _loc()["hits"] == before["hits"] + 1


def test_locality_hint_dead_target_falls_back():
    cs = ClusterServer(_head())
    a = _node(cs, "node-a")
    a.alive = False
    b = _node(cs, "node-b")
    _obj(cs, "o1", 20 << 20, "remote:node-b")
    strat = NodeAffinitySchedulingStrategy(node_id="node-a", soft=True,
                                           locality_hint=True)
    # fallback is DEFAULT, which chases the bytes to node-b
    assert cs.place(_rec(_spec(refs=["o1"], strategy=strat))) is b


def test_locality_hint_infeasible_target_falls_back():
    cs = ClusterServer(_head())
    _node(cs, "node-a", cpus=1.0, avail=1.0)
    b = _node(cs, "node-b", cpus=4.0)
    strat = NodeAffinitySchedulingStrategy(node_id="node-a", soft=True,
                                           locality_hint=True)
    assert cs.place(_rec(_spec(cpus=3.0, strategy=strat))) is b


def test_hit_rate_read_surface():
    cs = ClusterServer(_head())
    _node(cs, "node-a")
    _obj(cs, "o1", 1 << 20, "remote:node-a")
    cs.place(_rec(_spec(refs=["o1"])))
    rate = metrics.sched_locality_hit_rate()
    assert 0.0 <= rate <= 1.0
    c = metrics.sched_locality_counters()
    assert c["hits"] + c["misses"] > 0
