"""Regression tests for controller lifecycle bugs found in code review."""

import time

import pytest


def test_actor_creation_failure_resource_accounting(ray_session):
    """Actor whose __init__ raises must not double-release resources."""
    ray = ray_session
    before = ray.available_resources()

    @ray.remote(num_cpus=1)
    class Bad:
        def __init__(self):
            raise RuntimeError("born broken")

        def ping(self):
            return 1

    b = Bad.remote()
    with pytest.raises(ray.exceptions.RayTpuError):
        ray.get(b.ping.remote(), timeout=30)
    time.sleep(0.3)
    after = ray.available_resources()
    assert after["CPU"] == before["CPU"], f"{before} -> {after}"


def test_infeasible_actor_fails_fast(ray_session):
    """Methods on an infeasible actor error instead of hanging forever."""
    ray = ray_session

    @ray.remote(num_cpus=128)
    class TooBig:
        def ping(self):
            return 1

    t = TooBig.remote()
    with pytest.raises(ray.exceptions.RayTpuError):
        ray.get(t.ping.remote(), timeout=10)


def test_kill_pending_actor_stays_dead(ray_session):
    """kill() racing actor creation must not resurrect the actor."""
    ray = ray_session

    @ray.remote
    class Slow:
        def __init__(self):
            time.sleep(1.0)

        def ping(self):
            return "alive"

    s = Slow.remote()
    ray.kill(s)  # creation still spawning/in flight
    time.sleep(3.0)
    with pytest.raises(ray.exceptions.RayTpuError):
        ray.get(s.ping.remote(), timeout=10)


def test_returned_nested_ref_survives(ray_session):
    """A task returning an ObjectRef hands ownership to the caller."""
    ray = ray_session

    @ray.remote
    def make_ref():
        import ray_tpu
        import numpy as np
        return ray_tpu.put(np.arange(100_000, dtype=np.float32))

    inner_ref = ray.get(make_ref.remote())
    import gc
    gc.collect()
    time.sleep(0.5)  # let any stray decref land
    out = ray.get(inner_ref, timeout=10)
    assert out.shape == (100_000,) and float(out.sum(dtype="float64")) == float(sum(range(100_000)))


def test_wait_unknown_object_raises(ray_session):
    ray = ray_session
    from ray_tpu._private.object_ref import ObjectRef

    ghost = ObjectRef("obj-999999-deadbeefdeadbeef", owned=False)
    with pytest.raises(ray.exceptions.ObjectLostError):
        ray.wait([ghost], num_returns=1, timeout=1)


def test_repeated_wait_timeouts_no_leak(ray_session):
    """Polling-style wait() must not accumulate pending event waiters."""
    ray = ray_session

    @ray.remote
    def slow():
        time.sleep(2)
        return 1

    ref = slow.remote()
    for _ in range(20):
        ready, rest = ray.wait([ref], num_returns=1, timeout=0.05)
        if ready:
            break
    assert ray.get(ref, timeout=30) == 1
    # leak check: controller loop has no runaway pending tasks
    import asyncio
    rt = __import__("ray_tpu.api", fromlist=["_runtime"])._runtime
    n_tasks = len(asyncio.all_tasks(rt.loop)) if rt else 0
    assert n_tasks < 25, f"{n_tasks} pending asyncio tasks leaked"
