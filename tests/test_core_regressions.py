"""Regression tests for controller lifecycle bugs found in code review."""

import time

import pytest


def test_actor_creation_failure_resource_accounting(ray_session):
    """Actor whose __init__ raises must not double-release resources."""
    ray = ray_session
    before = ray.available_resources()

    @ray.remote(num_cpus=1)
    class Bad:
        def __init__(self):
            raise RuntimeError("born broken")

        def ping(self):
            return 1

    b = Bad.remote()
    with pytest.raises(ray.exceptions.RayTpuError):
        ray.get(b.ping.remote(), timeout=30)
    time.sleep(0.3)
    after = ray.available_resources()
    assert after["CPU"] == before["CPU"], f"{before} -> {after}"


def test_infeasible_actor_fails_fast(ray_session):
    """Methods on an infeasible actor error instead of hanging forever."""
    ray = ray_session

    @ray.remote(num_cpus=128)
    class TooBig:
        def ping(self):
            return 1

    t = TooBig.remote()
    with pytest.raises(ray.exceptions.RayTpuError):
        ray.get(t.ping.remote(), timeout=10)


def test_kill_pending_actor_stays_dead(ray_session):
    """kill() racing actor creation must not resurrect the actor."""
    ray = ray_session

    @ray.remote
    class Slow:
        def __init__(self):
            time.sleep(1.0)

        def ping(self):
            return "alive"

    s = Slow.remote()
    ray.kill(s)  # creation still spawning/in flight
    time.sleep(3.0)
    with pytest.raises(ray.exceptions.RayTpuError):
        ray.get(s.ping.remote(), timeout=10)


def test_returned_nested_ref_survives(ray_session):
    """A task returning an ObjectRef hands ownership to the caller."""
    ray = ray_session

    @ray.remote
    def make_ref():
        import ray_tpu
        import numpy as np
        return ray_tpu.put(np.arange(100_000, dtype=np.float32))

    inner_ref = ray.get(make_ref.remote())
    import gc
    gc.collect()
    time.sleep(0.5)  # let any stray decref land
    out = ray.get(inner_ref, timeout=10)
    assert out.shape == (100_000,) and float(out.sum(dtype="float64")) == float(sum(range(100_000)))


def test_wait_unknown_object_raises(ray_session):
    ray = ray_session
    from ray_tpu._private.object_ref import ObjectRef

    ghost = ObjectRef("obj-999999-deadbeefdeadbeef", owned=False)
    with pytest.raises(ray.exceptions.ObjectLostError):
        ray.wait([ghost], num_returns=1, timeout=1)


def test_repeated_wait_timeouts_no_leak(ray_session):
    """Polling-style wait() must not accumulate pending event waiters."""
    ray = ray_session

    @ray.remote
    def slow():
        time.sleep(2)
        return 1

    ref = slow.remote()
    for _ in range(20):
        ready, rest = ray.wait([ref], num_returns=1, timeout=0.05)
        if ready:
            break
    assert ray.get(ref, timeout=30) == 1
    # leak check: controller loop has no runaway pending tasks
    import asyncio
    rt = __import__("ray_tpu.api", fromlist=["_runtime"])._runtime
    n_tasks = len(asyncio.all_tasks(rt.loop)) if rt else 0
    assert n_tasks < 25, f"{n_tasks} pending asyncio tasks leaked"


def test_nested_ref_in_put_survives_sender_gc(ray_session):
    """A ref serialized inside another object must stay alive after the
    sender's ObjectRef is GC'd (containment pinning)."""
    import gc

    import numpy as np

    ray = ray_session
    inner = ray.put(np.arange(100))
    outer = ray.put({"payload": [inner]})
    del inner
    gc.collect()
    time.sleep(0.3)  # let the decref land at the controller
    got = ray.get(outer)["payload"][0]
    np.testing.assert_array_equal(ray.get(got), np.arange(100))


def test_ref_returned_from_task(ray_session):
    """Worker-side put ref returned as a result must survive the worker's
    frame exit (result-object containment pin)."""
    ray = ray_session

    @ray.remote
    def make():
        return ray.put(123)

    inner_ref = ray.get(make.remote(), timeout=60)
    time.sleep(0.3)
    assert ray.get(inner_ref, timeout=60) == 123


def test_nested_ref_inside_arg_value(ray_session):
    """Refs buried in inline arg values are pinned for the task lifetime."""
    import gc

    ray = ray_session

    @ray.remote
    def use(lst):
        return ray.get(lst[0]) + 1

    r = ray.put(41)
    out = use.remote([r])
    del r
    gc.collect()
    assert ray.get(out, timeout=60) == 42


def test_closure_captured_ref_pinned_for_fn_lifetime(ray_session):
    """A ref captured in a remote fn's globals must stay alive as long as the
    RemoteFunction does, even after the driver drops its own handle."""
    import gc

    ray = ray_session
    g = {}
    exec("import ray_tpu as ray\n"
         "r = ray.put(7)\n"
         "def f():\n"
         "    return ray.get(r)\n", g)
    rf = ray.remote(g["f"])
    out = rf.remote()  # builds the blob → holds the captured ref
    del g["r"]
    gc.collect()
    time.sleep(0.3)
    assert ray.get(out, timeout=60) == 7
    # second call after the driver's handle is long gone
    assert ray.get(rf.remote(), timeout=60) == 7


def test_task_storm_dispatch(ray_session):
    """Hundreds of queued tasks drain correctly through the signature-
    bucketed ready index (src/sched_queue.cpp) — ordering-independent
    results, mixed resource demands, no starvation."""
    ray = ray_session

    @ray.remote
    def tiny(i):
        return i

    @ray.remote(num_cpus=2)
    def chunky(i):
        return -i

    refs = []
    for i in range(150):
        refs.append(tiny.remote(i))
        if i % 10 == 0:
            refs.append(chunky.remote(i))
    out = ray.get(refs, timeout=240)
    expect = []
    for i in range(150):
        expect.append(i)
        if i % 10 == 0:
            expect.append(-i)
    assert out == expect


def test_zero_copy_value_survives_ref_release(ray_session):
    """Plasma pin semantics (r4): a get() value aliases arena memory and
    must stay intact after its ObjectRef is dropped and the object evicted —
    the arena zombies pinned blocks instead of recycling their bytes.
    Regression: streaming-shuffle blocks over the inline threshold silently
    swapped content when their refs died before consumption."""
    import gc
    import numpy as np
    ray = ray_session

    @ray.remote
    def make(i):
        return np.full(50_000, i, np.int64)  # ~400KB -> shm path

    vals = []
    for i in range(6):
        ref = make.remote(i)
        vals.append(ray.get(ref, timeout=60))
        del ref  # creation ref dropped -> object evictable
    gc.collect()
    # churn the arena so freed ranges would be recycled if unpinned
    churn = [ray.get(make.remote(100 + i), timeout=60) for i in range(6)]
    for i, v in enumerate(vals):
        assert (v == i).all(), f"value {i} corrupted after ref release"
    for i, v in enumerate(churn):
        assert (v == 100 + i).all()
