"""Regression: host-collective rendezvous under concurrent first-dispatch.

Round-1 bug: an async actor's first two method calls arriving on two pool
threads at once raced WorkerState.get_async_loop into creating TWO event
loops; coroutines split across loops and asyncio.Event.set() on one loop
never woke waiters on the other → allreduce hung (GetTimeoutError after 60s).
Two collective ranks hitting a fresh rendezvous actor is exactly that
pattern, so this hammers it: many fresh groups, ranks submitted
back-to-back, with background task/actor churn to load the worker pool.
"""

import numpy as np
import pytest


@pytest.mark.parametrize("world", [2, 3])
def test_host_collective_concurrent_groups(ray_session, world):
    ray = ray_session

    @ray.remote
    def churn(x):
        return x + 1

    @ray.remote
    class Member:
        def _init_collective(self, world_size, rank, backend, group_name):
            from ray_tpu.parallel import collective as col
            col.destroy_collective_group(group_name)
            col.init_collective_group(world_size, rank, backend, group_name)
            return True

        def do_allreduce(self, x, group):
            from ray_tpu.parallel import collective as col
            return col.allreduce(np.asarray(x, np.float32), group_name=group)

    from ray_tpu.parallel.collective import create_collective_group

    for it in range(6):
        group = f"stress_w{world}_{it}"
        churn_refs = [churn.remote(i) for i in range(4)]
        members = [Member.remote() for _ in range(world)]
        create_collective_group(members, world, list(range(world)),
                                backend="host", group_name=group)
        # submit all ranks back-to-back so the rendezvous actor sees them
        # nearly simultaneously (the race window)
        refs = [m.do_allreduce.remote([float(r), 1.0], group)
                for r, m in enumerate(members)]
        outs = ray.get(refs, timeout=60)
        expected = [sum(range(world)), float(world)]
        for o in outs:
            np.testing.assert_allclose(o, expected)
        assert ray.get(churn_refs, timeout=30) == [1, 2, 3, 4]
        for m in members:
            ray.kill(m)


def test_host_collective_large_payload_rides_shm(ray_session):
    """4MB allreduce payloads move through the shm arena (implicit
    large-arg put, r4) instead of double-crossing the controller socket —
    correctness here, the byte-path covered by the implicit-put plumbing
    (VERDICT r3 weak #5 characterization)."""
    world = 3
    import numpy as np
    ray = ray_session

    @ray.remote
    class Rank:
        def _init_collective(self, world_size, rank, group):
            from ray_tpu.parallel import collective as col
            col.init_collective_group(world_size, rank, "host", group)
            self.rank = rank

        def allreduce(self, shape):
            from ray_tpu.parallel import collective as col
            x = np.full(shape, float(self.rank + 1))
            out = col.allreduce(x, group_name="big")
            return float(out[0])

    ranks = [Rank.remote() for _ in range(world)]
    ray.get([r._init_collective.remote(world, i, "big")
             for i, r in enumerate(ranks)], timeout=120)
    outs = ray.get([r.allreduce.remote((512 * 1024,)) for r in ranks],
                   timeout=180)  # 4MB per rank
    want = sum(range(1, world + 1))
    assert all(abs(o - want) < 1e-9 for o in outs), outs
