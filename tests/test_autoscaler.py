"""Autoscaler hooks (ref: python/ray/autoscaler/sdk.py request_resources):
explicit demand warms the worker pool; requests overwrite; infeasible
requests are clamped and reported, not silently dropped."""

import time


def test_request_resources_warms_pool(ray_session):
    from ray_tpu.autoscaler import sdk

    res = sdk.request_resources(num_cpus=3)
    assert res["target_cpus"] == 3
    assert res["fulfilled_cpus"] == 3
    assert res["clamped"] is False
    st = sdk.status()
    assert st["pool_workers"] >= 3
    assert st["request"]["target_cpus"] == 3
    # warmed workers become idle and usable
    deadline = time.time() + 30
    while time.time() < deadline and sdk.status()["idle_workers"] < 3:
        time.sleep(0.1)
    assert sdk.status()["idle_workers"] >= 3

    ray = ray_session

    @ray.remote
    def f(x):
        return x * 2

    assert ray.get([f.remote(i) for i in range(6)]) == [0, 2, 4, 6, 8, 10]
    # clear the standing request (overwrite semantics)
    res = sdk.request_resources()
    assert res["target_cpus"] == 0
    assert sdk.status()["request"]["target_cpus"] == 0


def test_request_resources_clamped_to_host(ray_session):
    from ray_tpu.autoscaler import sdk

    res = sdk.request_resources(num_cpus=10_000)
    assert res["clamped"] is True
    assert res["fulfilled_cpus"] == sdk.status()["max_workers"]
    sdk.request_resources()  # clear


def test_request_resources_bundles(ray_session):
    from ray_tpu.autoscaler import sdk

    res = sdk.request_resources(bundles=[{"CPU": 1}, {"CPU": 2}])
    assert res["target_cpus"] == 3
    sdk.request_resources()  # clear
