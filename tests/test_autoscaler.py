"""Autoscaler hooks (ref: python/ray/autoscaler/sdk.py request_resources):
explicit demand warms the worker pool; requests overwrite; infeasible
requests are clamped and reported, not silently dropped.

Second half: the alert-driven Reconciler (ref: python/ray/autoscaler/
_private/autoscaler.py StandardAutoscaler update loop), driven entirely
with fakes and a fake clock — no cluster, no subprocesses, no sleeps."""

import time
from types import SimpleNamespace


def test_request_resources_warms_pool(ray_session):
    from ray_tpu.autoscaler import sdk

    res = sdk.request_resources(num_cpus=3)
    assert res["target_cpus"] == 3
    assert res["fulfilled_cpus"] == 3
    assert res["clamped"] is False
    st = sdk.status()
    assert st["pool_workers"] >= 3
    assert st["request"]["target_cpus"] == 3
    # warmed workers become idle and usable
    deadline = time.time() + 30
    while time.time() < deadline and sdk.status()["idle_workers"] < 3:
        time.sleep(0.1)
    assert sdk.status()["idle_workers"] >= 3

    ray = ray_session

    @ray.remote
    def f(x):
        return x * 2

    assert ray.get([f.remote(i) for i in range(6)]) == [0, 2, 4, 6, 8, 10]
    # clear the standing request (overwrite semantics)
    res = sdk.request_resources()
    assert res["target_cpus"] == 0
    assert sdk.status()["request"]["target_cpus"] == 0


def test_request_resources_clamped_to_host(ray_session):
    from ray_tpu.autoscaler import sdk

    res = sdk.request_resources(num_cpus=10_000)
    assert res["clamped"] is True
    assert res["fulfilled_cpus"] == sdk.status()["max_workers"]
    sdk.request_resources()  # clear


def test_request_resources_bundles(ray_session):
    from ray_tpu.autoscaler import sdk

    res = sdk.request_resources(bundles=[{"CPU": 1}, {"CPU": 2}])
    assert res["target_cpus"] == 3
    sdk.request_resources()  # clear


# --------------------------------------------------------------- reconciler
class _FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class _FakeProvider:
    """Records create/terminate calls and hands out deterministic pids."""

    cpus_per_node = 2.0

    def __init__(self):
        self.created = []
        self.terminated = []
        self._pids = {}
        self._n = 0

    def create_node(self, resources, address):
        self._n += 1
        h = f"fake-node-{self._n}"
        self._pids[h] = 10000 + self._n
        self.created.append(h)
        return h

    def terminate_node(self, h):
        self.terminated.append(h)
        self._pids.pop(h, None)

    def non_terminated_nodes(self):
        return list(self._pids)

    def pid_of(self, h):
        return self._pids.get(h)


def _fake_head(clock, max_nodes=4):
    """Narrow controller surface the Reconciler is written against."""
    from ray_tpu._private.health import HealthMonitor

    c = SimpleNamespace(
        node_id="node-head",
        node_provider=_FakeProvider(),
        provider_max_nodes=max_nodes,
        _provider_nodes={},
        cluster=SimpleNamespace(nodes={}, address="127.0.0.1:7777"),
        ready_queue=[])
    c.health = HealthMonitor(c, clock=clock)
    return c


def _register_node(c, node_id, pid):
    c.cluster.nodes[node_id] = SimpleNamespace(
        node_id=node_id, pid=pid, alive=True, inflight={}, actors=set())
    c.health.note_node_alive(node_id)


def _launch_provider_node(c, node_id):
    """Simulate a prior provider launch whose agent is registered+alive."""
    h = c.node_provider.create_node({"CPU": 2.0}, c.cluster.address)
    c._provider_nodes[h] = {"CPU": 2.0}
    _register_node(c, node_id, c.node_provider.pid_of(h))
    return h


def test_reconciler_replaces_dead_node_with_causality():
    """node_dead alert -> terminate the dead handle, launch a replacement,
    and record the alert-id -> create_node causality; the pending launch
    closes to `recovered` when the replacement's pid registers; the same
    alert is never consumed twice (cursor)."""
    from ray_tpu.autoscaler.reconciler import Reconciler

    clock = _FakeClock()
    c = _fake_head(clock)
    rec = Reconciler(c, clock=clock)
    h1 = _launch_provider_node(c, "node-a")
    dead_pid = c.node_provider.pid_of(h1)
    rec.tick()  # steady state: nothing to do
    assert c.node_provider.terminated == [] and rec.replacements == 0

    # the node dies: cluster marks it dead and fires the alert (the same
    # path ClusterServer._on_node_dead drives)
    clock.advance(1.0)
    c.cluster.nodes["node-a"].alive = False
    c.health.note_node_dead("node-a", host="127.0.0.1", pid=dead_pid)
    clock.advance(0.5)
    rec.tick()

    assert c.node_provider.terminated == [h1]
    assert rec.replacements == 1
    assert h1 not in c._provider_nodes
    h2 = c.node_provider.created[-1]
    assert h2 != h1 and h2 in c._provider_nodes and h2 in rec._pending

    alert = c.health.alerts.events()[-1]
    assert alert["kind"] == "node_dead" and alert["data"]["pid"] == dead_pid
    actions = [(e["action"], e["handle"], e["alert_id"]) for e in rec.events]
    assert ("terminate_dead", h1, alert["id"]) in actions
    assert ("replace", h2, alert["id"]) in actions

    # replacement registers -> pending closes with a `recovered` record
    clock.advance(2.0)
    _register_node(c, "node-b", c.node_provider.pid_of(h2))
    rec.tick()
    assert rec._pending == {}
    recovered = [e for e in rec.events if e["action"] == "recovered"]
    assert recovered and recovered[-1]["handle"] == h2
    assert recovered[-1]["alert_id"] == alert["id"]

    # cursor: re-ticking the same log must not double-replace
    rec.tick()
    assert rec.replacements == 1 and len(c.node_provider.created) == 2

    st = rec.status()
    assert st["replacements"] == 1 and st["cursor"] == alert["id"]


def test_reconciler_replace_clamped_at_max_nodes():
    """A death the provider can't absorb (slot cap, dead node wasn't a
    provider launch) records replace_clamped instead of over-launching."""
    from ray_tpu.autoscaler.reconciler import Reconciler

    clock = _FakeClock()
    c = _fake_head(clock, max_nodes=1)
    rec = Reconciler(c, clock=clock)
    _launch_provider_node(c, "node-a")  # fills the only slot
    _register_node(c, "node-x", pid=4242)  # manually-started node

    c.cluster.nodes["node-x"].alive = False
    c.health.note_node_dead("node-x", pid=4242)
    rec.tick()

    assert rec.replacements == 0
    assert len(c.node_provider.created) == 1  # no new launch
    assert c.node_provider.terminated == []   # alive handle untouched
    assert any(e["action"] == "replace_clamped" for e in rec.events)


def test_reconciler_pressure_scale_up_with_cooldown():
    """store_pressure / queue_growth alerts scale up one node, then the
    cooldown suppresses the next pressure alert until it expires."""
    from ray_tpu.autoscaler.reconciler import Reconciler

    clock = _FakeClock()
    c = _fake_head(clock)
    rec = Reconciler(c, clock=clock)

    c.health.alerts.fire("store_pressure", "node-head", "store 93% full")
    rec.tick()
    assert rec.scale_ups == 1 and len(c.node_provider.created) == 1

    # second pressure signal inside the cooldown window: suppressed
    clock.advance(1.0)
    c.health.alerts.fire("queue_growth", "node-head", "queue growing")
    rec.tick()
    assert rec.scale_ups == 1
    assert any(e["action"] == "scale_up_suppressed" for e in rec.events)

    # cooldown expires; a fresh alert scales up again
    clock.advance(15.0)
    c.health.alerts.resolve("queue_growth", "node-head")
    c.health.alerts.fire("queue_growth", "node-head", "queue growing again")
    rec.tick()
    assert rec.scale_ups == 2 and len(c.node_provider.created) == 2


def test_reconciler_idle_scale_down():
    """An idle cluster (empty ready queue, no active alerts, no pending
    launches) sheds ONE idle provider node after the idle window; busy
    signals re-arm the timer."""
    from ray_tpu.autoscaler.reconciler import Reconciler

    clock = _FakeClock()
    c = _fake_head(clock)
    rec = Reconciler(c, clock=clock)
    h1 = _launch_provider_node(c, "node-a")

    c.ready_queue.append(object())  # busy: timer must not arm
    rec.tick()
    c.ready_queue.clear()
    rec.tick()               # idle period starts NOW
    clock.advance(30.0)
    rec.tick()               # not idle long enough
    assert rec.scale_downs == 0 and c.node_provider.terminated == []

    clock.advance(45.0)      # total idle 75s > 60s default
    rec.tick()
    assert rec.scale_downs == 1 and c.node_provider.terminated == [h1]
    assert h1 not in c._provider_nodes
    assert any(e["action"] == "scale_down" for e in rec.events)

    # a node with work in flight is never the scale-down victim
    h2 = _launch_provider_node(c, "node-b")
    c.cluster.nodes["node-b"].inflight["t1"] = object()
    rec.tick()
    clock.advance(120.0)
    rec.tick()
    assert c.node_provider.terminated == [h1] and h2 in c._provider_nodes


def test_autoscale_enabled_knob(monkeypatch):
    from ray_tpu._private.controller import autoscale_enabled

    monkeypatch.delenv("RAY_TPU_AUTOSCALE", raising=False)
    assert autoscale_enabled() is True
    monkeypatch.setenv("RAY_TPU_AUTOSCALE", "0")
    assert autoscale_enabled() is False
    monkeypatch.setenv("RAY_TPU_AUTOSCALE", "1")
    assert autoscale_enabled() is True
