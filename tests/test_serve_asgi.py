"""@serve.ingress ASGI adapter (VERDICT r4 missing #7; ref:
python/ray/serve/api.py:309). No starlette/fastapi in this image, so the
tests drive a hand-rolled spec-conforming ASGI app — the adapter only
speaks the ASGI protocol, any framework rides on it."""

import asyncio
import json

import pytest

from test_serve_http import _req


def make_app(marker="v1"):
    """A minimal ASGI app: GET /hello, POST /echo (reads body), GET /meta
    (exposes scope root_path/path), 404 otherwise, chunked body response."""

    async def app(scope, receive, send):
        assert scope["type"] == "http"
        path = scope["path"]
        body = b""
        while True:
            event = await receive()
            body += event.get("body", b"")
            if not event.get("more_body"):
                break

        async def respond(status, payload, ctype=b"application/json"):
            await send({"type": "http.response.start", "status": status,
                        "headers": [(b"content-type", ctype),
                                    (b"x-marker", marker.encode())]})
            # two body events: the adapter must concatenate chunks
            await send({"type": "http.response.body", "body": payload[:3],
                        "more_body": True})
            await send({"type": "http.response.body", "body": payload[3:]})

        if scope["method"] == "GET" and path == "/hello":
            await respond(200, json.dumps({"hello": marker}).encode())
        elif scope["method"] == "POST" and path == "/echo":
            await respond(200, json.dumps(
                {"echo": body.decode(), "q": scope["query_string"].decode()}
            ).encode())
        elif path == "/meta":
            await respond(200, json.dumps(
                {"root_path": scope["root_path"], "path": path}).encode())
        else:
            await respond(404, b'{"detail": "nope"}')

    return app


def test_call_asgi_unit():
    from ray_tpu.serve import Request
    from ray_tpu.serve.asgi import call_asgi
    app = make_app()
    req = Request("POST", "/echo", query_string="a=1",
                  headers={"Content-Type": "text/plain"}, body=b"hi there")
    resp = asyncio.run(call_asgi(app, req))
    assert resp.status_code == 200
    assert json.loads(resp.content) == {"echo": "hi there", "q": "a=1"}
    assert resp.headers["x-marker"] == "v1"

    resp = asyncio.run(call_asgi(app, Request("GET", "/missing")))
    assert resp.status_code == 404


def test_ingress_requires_class():
    from ray_tpu import serve
    with pytest.raises(TypeError):
        serve.ingress(make_app())(lambda req: req)


@pytest.fixture()
def serve_app(ray_session):
    from ray_tpu import serve
    yield serve
    serve.shutdown()


def test_asgi_ingress_end_to_end(serve_app):
    serve = serve_app

    @serve.deployment
    @serve.ingress(make_app("live"))
    class Api:
        def direct(self):
            return "handle-path still works"

    serve.run(Api.bind(), name="api", route_prefix="/api")
    port = serve.start(http_options={"port": 0})

    status, headers, data = _req(port, "GET", "/api/hello")
    assert status == 200, data
    assert json.loads(data) == {"hello": "live"}
    assert headers["x-marker"] == "live"

    status, _, data = _req(port, "POST", "/api/echo?a=2", body=b"ping")
    assert status == 200
    assert json.loads(data) == {"echo": "ping", "q": "a=2"}

    # the app sees itself mounted under the route prefix
    status, _, data = _req(port, "GET", "/api/meta")
    assert json.loads(data) == {"root_path": "/api", "path": "/meta"}

    # app-level 404 (inside the deployment) is not a proxy 404
    status, _, data = _req(port, "GET", "/api/nope")
    assert status == 404 and json.loads(data) == {"detail": "nope"}

    # non-ASGI methods remain reachable over handles
    h = serve.get_deployment_handle("Api", "api")
    assert h.direct.remote().result(timeout_s=60) == \
        "handle-path still works"


def test_asgi_factory_builds_per_replica(serve_app):
    serve = serve_app

    def build():   # zero-arg factory → called replica-side
        return make_app("factory")

    @serve.deployment
    @serve.ingress(build)
    class Api2:
        pass

    serve.run(Api2.bind(), name="api2", route_prefix="/api2")
    port = serve.start(http_options={"port": 0})
    status, _, data = _req(port, "GET", "/api2/hello")
    assert status == 200 and json.loads(data) == {"hello": "factory"}
