"""Off-policy / offline algorithm tests: replay buffers, DQN, IMPALA, SAC,
APPO, BC."""

import numpy as np
import pytest

from ray_tpu.rllib import (APPOConfig, BCConfig, DQNConfig, IMPALAConfig,
                           PrioritizedReplayBuffer, ReplayBuffer, SACConfig)


# ------------------------------------------------------------------- buffers
def test_replay_buffer_ring_and_sampling():
    buf = ReplayBuffer(capacity=100, seed=0)
    buf.add_batch({"x": np.arange(150), "y": np.arange(150) * 2})
    assert len(buf) == 100
    s = buf.sample(32)
    assert s["x"].shape == (32,)
    # ring: oldest 50 evicted
    assert s["x"].min() >= 50
    np.testing.assert_array_equal(s["y"], s["x"] * 2)


def test_replay_buffer_uniformity():
    buf = ReplayBuffer(capacity=10, seed=1)
    buf.add_batch({"x": np.arange(10)})
    counts = np.zeros(10)
    for _ in range(200):
        s = buf.sample(10)
        for v in s["x"]:
            counts[v] += 1
    # each of 10 items expected 200 times ± noise
    assert counts.min() > 100 and counts.max() < 320


def test_prioritized_buffer_prefers_high_priority():
    buf = PrioritizedReplayBuffer(capacity=8, alpha=1.0, seed=2)
    buf.add_batch({"x": np.arange(8)})
    # give item 3 overwhelming priority
    buf.update_priorities(np.arange(8), np.ones(8) * 0.01)
    buf.update_priorities([3], [100.0])
    s = buf.sample(200, beta=1.0)
    frac = float(np.mean(s["x"] == 3))
    assert frac > 0.8, f"item 3 sampled only {frac:.0%}"
    assert "_weights" in s and s["_weights"].max() <= 1.0 + 1e-6


# ---------------------------------------------------------------- algorithms
@pytest.mark.slow
def test_dqn_learns_cartpole():
    config = (DQNConfig()
              .environment("CartPole-v1")
              .env_runners(num_envs_per_env_runner=4,
                           rollout_fragment_length=16)
              .training(lr=1e-3, train_batch_size=64,
                        num_steps_sampled_before_learning_starts=200,
                        target_network_update_freq=50, train_intensity=8,
                        epsilon_decay_steps=3000, dueling=True,
                        prioritized_replay=True)
              .debugging(seed=0))
    algo = config.build()
    best = 0.0
    for _ in range(40):
        r = algo.train()
        best = max(best, r.get("episode_return_mean", 0.0))
        if best > 60.0:
            break
    algo.stop()
    assert best > 60.0, f"DQN failed to learn (best={best})"


@pytest.mark.slow
def test_impala_learns_cartpole():
    config = (IMPALAConfig()
              .environment("CartPole-v1")
              .env_runners(num_envs_per_env_runner=8,
                           rollout_fragment_length=32)
              .training(lr=3e-3, train_batch_size=512, entropy_coeff=0.01)
              .debugging(seed=0))
    algo = config.build()
    best = 0.0
    for _ in range(25):
        r = algo.train()
        best = max(best, r.get("episode_return_mean", 0.0))
        if best > 60.0:
            break
    algo.stop()
    assert best > 60.0, f"IMPALA failed to learn (best={best})"


def test_sac_runs_pendulum():
    config = (SACConfig()
              .environment("Pendulum-v1")
              .env_runners(num_envs_per_env_runner=2,
                           rollout_fragment_length=32)
              .training(train_batch_size=64,
                        num_steps_sampled_before_learning_starts=64,
                        train_intensity=2)
              .debugging(seed=0))
    algo = config.build()
    r = None
    for _ in range(4):
        r = algo.train()
    algo.stop()
    assert "learner" in r, f"SAC never learned: {r}"
    lm = r["learner"]
    assert np.isfinite(lm["critic_loss"]) and np.isfinite(lm["actor_loss"])
    assert lm["alpha"] > 0


def test_appo_runs_cartpole():
    config = (APPOConfig()
              .environment("CartPole-v1")
              .env_runners(num_envs_per_env_runner=4,
                           rollout_fragment_length=32)
              .training(train_batch_size=128, minibatch_size=64,
                        num_epochs=2)
              .debugging(seed=0))
    algo = config.build()
    r = algo.train()
    algo.stop()
    assert "learner" in r
    assert np.isfinite(r["learner"]["total_loss"])


def test_bc_learns_expert_policy():
    # expert: action = 1 if obs[0] > 0 else 0
    rng = np.random.default_rng(0)
    obs = rng.normal(size=(2000, 4)).astype(np.float32)
    actions = (obs[:, 0] > 0).astype(np.int64)
    config = BCConfig().training(lr=1e-2, train_batch_size=256)
    config.offline_data_source({"obs": obs, "actions": actions})
    algo = config.build()
    acc = 0.0
    for _ in range(60):
        r = algo.train()
        acc = r["learner"].get("action_accuracy", 0.0)
        if acc > 0.95:
            break
    algo.stop()
    assert acc > 0.95, f"BC accuracy only {acc}"
