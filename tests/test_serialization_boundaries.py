"""Serialization size-class boundaries (PR 2 satellite).

Two thresholds decide an object's path and both have off-by-one blast
radius:
  * serialization._OOB_MIN_BYTES (4096): smaller pickle buffers fold
    in-band, larger ones ship out-of-band for zero-copy shm mapping
  * client._INLINE_MAX (64 KiB): packed blobs at or under travel inline in
    the (batched) put registration; larger ones land in the shm store

Exercised straddling each boundary, through pack/unpack round-trips, the
store, AND the batched put-registration path a pipelined driver uses.
"""

import numpy as np
import pytest

from ray_tpu._private import serialization
from ray_tpu._private.serialization import _OOB_MIN_BYTES


def _roundtrip(obj):
    meta, buffers, contained = serialization.dumps_oob(obj)
    packed = serialization.pack_parts(meta, buffers)
    return serialization.unpack(packed), buffers


@pytest.mark.parametrize("nbytes,expect_oob", [
    (_OOB_MIN_BYTES - 1, 0),   # one under: stays in-band
    (_OOB_MIN_BYTES, 1),       # exactly at: ships out-of-band
    (_OOB_MIN_BYTES + 1, 1),   # one over
])
def test_oob_threshold_boundary(nbytes, expect_oob):
    # numpy arrays emit PickleBuffers under protocol 5 (bytes objects don't)
    arr = np.arange(nbytes, dtype=np.uint8) % 251
    got, buffers = _roundtrip(arr)
    assert len(buffers) == expect_oob
    np.testing.assert_array_equal(got, arr)


def test_oob_mixed_sizes_one_object():
    """Small + large buffers in one container: only the large ones go OOB,
    order and contents survive the single-blob pack."""
    small = np.arange(100, dtype=np.uint8)
    big_a = np.arange(_OOB_MIN_BYTES * 2, dtype=np.uint8) % 199
    big_b = np.arange(_OOB_MIN_BYTES, dtype=np.uint8) % 97
    got, buffers = _roundtrip({"s": small, "a": big_a, "b": big_b})
    assert len(buffers) == 2
    np.testing.assert_array_equal(got["s"], small)
    np.testing.assert_array_equal(got["a"], big_a)
    np.testing.assert_array_equal(got["b"], big_b)


def test_pack_parts_exact_layout():
    """pack_parts presizes one bytearray; the frame must stay self-framing:
    u32 meta_len | meta | buffers, byte-exact."""
    meta, buffers, _ = serialization.dumps_oob(
        np.arange(_OOB_MIN_BYTES, dtype=np.uint8))
    packed = serialization.pack_parts(meta, buffers)
    assert isinstance(packed, bytearray)
    assert len(packed) == 4 + len(meta) + sum(b.nbytes for b in buffers)
    import struct
    (meta_len,) = struct.unpack_from("<I", packed, 0)
    assert meta_len == len(meta)
    assert bytes(packed[4:4 + len(meta)]) == bytes(meta)


def test_unpack_zero_copy_view():
    """unpack over a memoryview aliases the source for OOB buffers (the
    zero-copy contract get() relies on for shm segments)."""
    arr = np.arange(_OOB_MIN_BYTES * 4, dtype=np.uint8)
    meta, buffers, _ = serialization.dumps_oob(arr)
    packed = serialization.pack_parts(meta, buffers)
    got = serialization.unpack(memoryview(packed))
    np.testing.assert_array_equal(got, arr)
    assert not got.flags.writeable  # sealed-object semantics


@pytest.mark.parametrize("delta", [-1, 0, 1])
def test_inline_threshold_through_put(ray_session, delta):
    """Values straddling client._INLINE_MAX: at/below rides inline in the
    batched put registration, above lands in the shm store. Both must
    read back identically through get()."""
    ray = ray_session
    from ray_tpu._private import state
    from ray_tpu._private.client import _INLINE_MAX
    ctl = state.global_client().controller

    # calibrate pickle overhead so the PACKED size lands at the boundary
    # (probe must be OOB-sized: in-band buffers have different overhead)
    probe = np.zeros(8192, dtype=np.uint8)
    meta, bufs, _ = serialization.dumps_oob(probe)
    overhead = 4 + serialization.total_size(meta, bufs) - probe.nbytes
    n = _INLINE_MAX - overhead + delta
    arr = (np.arange(n, dtype=np.uint8) % 253)
    meta, bufs, _ = serialization.dumps_oob(arr)
    packed_size = 4 + serialization.total_size(meta, bufs)
    ref = ray.put(arr)
    state.global_client().flush()
    got = ray.get(ref, timeout=30)
    np.testing.assert_array_equal(got, arr)
    meta_rec = ctl.objects[ref.id]
    want_loc = "inline" if packed_size - 4 <= _INLINE_MAX else "shm"
    assert meta_rec.location == want_loc, (
        f"packed {packed_size - 4}B vs inline max {_INLINE_MAX}: "
        f"expected {want_loc}, got {meta_rec.location}")


def test_worker_put_through_batched_registration(ray_session):
    """A task returning a nested ref puts from the WORKER client — the
    registration rides a batched frame on the unix socket and must land
    before the driver's get resolves the inner ref."""
    ray = ray_session

    @ray.remote
    def make_nested():
        import ray_tpu
        import numpy as _np
        inner_small = ray_tpu.put(b"tiny")                       # inline put
        inner_big = ray_tpu.put(_np.ones(100_000, dtype=_np.uint8))  # shm put
        return {"small": inner_small, "big": inner_big}

    out = ray.get(make_nested.remote(), timeout=60)
    assert ray.get(out["small"], timeout=30) == b"tiny"
    big = ray.get(out["big"], timeout=30)
    assert big.shape == (100_000,) and int(big.sum()) == 100_000
