"""Dependency-prefetching dispatch (PR 8 tentpole).

The controller resolves a task's object dependencies BEFORE handing it to a
worker (ref: Ray raylet dependency manager, arXiv:1712.05889 §4.2): remote
args of queued tasks are pulled eagerly (single-flight, byte-capped), the
exec frame ships shm descriptors so `_resolve_args` materializes zero-copy
without a blocking RPC, and task results publish fire-and-forget through
the batched-frame flusher. Covered here:

  * chain-overlap smoke (chain_bench --smoke): prefetch ≥ legacy, hit ≥ 0.9
  * prefetch hit/miss counters at dispatch + the read surface
  * holder death mid-prefetch: worker falls back to the exec-time fetch
  * async result entries never reorder past a later decref in the flusher
  * RAY_TPU_PREFETCH=0 escape hatch restores the legacy path
  * single-flight dedup: client.get joins in-flight fetches; PullManager
    dedups per object id and honors the in-flight byte cap
  * actor max_concurrency sizes the worker's exec pool
"""

import asyncio
import os
import subprocess
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_script(body, env_extra=None, timeout=240):
    env = dict(os.environ, JAX_PLATFORMS="cpu", RAY_TPU_NUM_CHIPS="0")
    env.pop("RAY_TPU_ARENA", None)
    env.pop("RAY_TPU_ADDRESS", None)
    env.update(env_extra or {})
    r = subprocess.run([sys.executable, "-c", body], capture_output=True,
                       text=True, timeout=timeout, env=env, cwd=REPO)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    return r.stdout


# ------------------------------------------------------------- chain overlap

def test_chain_bench_smoke():
    """End-to-end on the two-node loopback cluster: the producer/consumer
    chain completes in both modes, dispatch hit rate ≥ 0.9 with prefetch
    on, and prefetch is not slower than legacy (the ≥1.5x claim is the
    --measure record's; smoke keeps a loose bound for loaded CI boxes)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", RAY_TPU_NUM_CHIPS="0")
    env.pop("RAY_TPU_ARENA", None)
    env.pop("RAY_TPU_ADDRESS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "chain_bench.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "chain_dp_smoke" in r.stdout


# ------------------------------------------------------ hit/miss counters

_COUNTER_SCRIPT = """
import numpy as np
import ray_tpu as ray
from ray_tpu.util import metrics

ray.init(num_cpus=2)
x = ray.put(np.arange(1 << 16))

@ray.remote
def f(a):
    return int(a[5])

assert ray.get(f.remote(x), timeout=60) == 5
c = metrics.prefetch_counters()
print("COUNTERS", c)
"""


def test_prefetch_hit_counters():
    """Dispatch-time ready-arg accounting: a shm-resident ref arg ships as
    a descriptor and counts a hit (single host: the driver process IS the
    controller, so the counters are directly readable)."""
    out = _run_script(_COUNTER_SCRIPT + """
assert c["hits"] >= 1, c
assert c["misses"] == 0, c
assert metrics.prefetch_hit_rate() == 1.0
print("HITS_OK")
""")
    assert "HITS_OK" in out


def test_prefetch_escape_hatch():
    """RAY_TPU_PREFETCH=0 restores the legacy path: no descriptors ship, no
    counters move, results still correct (the blocking-get path)."""
    out = _run_script(_COUNTER_SCRIPT + """
assert c["hits"] == 0 and c["misses"] == 0, c
print("LEGACY_OK")
""", env_extra={"RAY_TPU_PREFETCH": "0"})
    assert "LEGACY_OK" in out


# ------------------------------------------- holder death → exec-time fetch

def _fresh_store(tmp_path, monkeypatch):
    monkeypatch.delenv("RAY_TPU_ARENA", raising=False)
    from ray_tpu._private.object_store import StoreClient
    return StoreClient()


def test_resolve_args_zero_copy(tmp_path, monkeypatch):
    """A shipped shm descriptor materializes from the local store without
    touching client.get."""
    import types
    import numpy as np
    from ray_tpu._private import serialization, worker_main
    from ray_tpu._private.task_spec import TaskSpec

    store = _fresh_store(tmp_path, monkeypatch)
    try:
        val = np.arange(4096)
        meta, bufs, _ = serialization.dumps_oob(val)
        store.put_parts("oid1", meta, bufs)

        def no_get(oids, timeout=None):
            raise AssertionError("blocking get used despite descriptor")

        ws = types.SimpleNamespace(client=types.SimpleNamespace(
            store=store, get=no_get))
        spec = TaskSpec(task_id="t1", fn_blob=None, args=[("ref", "oid1")])
        args, kwargs = worker_main._resolve_args(
            ws, spec, {"oid1": ("shm", len(meta))})
        assert (args[0] == val).all()
    finally:
        store.close()


def test_resolve_args_holder_death_falls_back(tmp_path, monkeypatch):
    """The descriptor points at a segment that died under us (holder crash /
    eviction mid-prefetch): _resolve_args falls back to the blocking
    exec-time fetch instead of failing the task."""
    import types
    from ray_tpu._private import worker_main
    from ray_tpu._private.task_spec import TaskSpec

    store = _fresh_store(tmp_path, monkeypatch)
    try:
        sentinel = object()
        calls = []

        def fallback_get(oids, timeout=None):
            calls.append(list(oids))
            return [sentinel] * len(oids)

        ws = types.SimpleNamespace(client=types.SimpleNamespace(
            store=store, get=fallback_get))
        spec = TaskSpec(task_id="t2", fn_blob=None,
                        args=[("ref", "gone1")], kwargs={})
        # descriptor for a segment that was never created ≡ deleted holder
        args, _ = worker_main._resolve_args(
            ws, spec, {"gone1": ("shm", 64)})
        assert args[0] is sentinel
        assert calls == [["gone1"]]
    finally:
        store.close()


# ------------------------------------------------- async result ordering

def test_task_done_never_reorders_past_decref():
    """The worker's fire-and-forget task_done rides the same ordered flusher
    as refcount deltas: a decref appended after the result publication can
    never be applied first (put-before-decref)."""
    from ray_tpu._private.client import _DeltaFlusher

    batches = []
    f = _DeltaFlusher(lambda entries: batches.append(list(entries)))
    with f.lock:
        f.append(("put", "a1", 0, 10, b"x", None))
        f.append(("task_done", "t1", [("r1", 0, 10, b"y", None)], None),
                 urgent=True)
        assert f._urgent  # urgent: the timer flushes without the 5ms nap
        f.append(("decref", "r1"))
    f.flush()
    f.close()
    flat = [e for b in batches for e in b]
    kinds = [e[0] for e in flat]
    assert kinds.index("put") < kinds.index("task_done") < kinds.index("decref")


# ------------------------------------------------------ single-flight dedup

def test_client_get_single_flight():
    """Two threads getting the same oid share one in-flight claim: exactly
    one owns the fetch, the joiner consumes the owner's result."""
    from ray_tpu._private.client import _SingleFlight

    sf = _SingleFlight()
    owned1, joined1 = sf.claim(["o1", "o2"])
    assert owned1 == ["o1", "o2"] and not joined1
    owned2, joined2 = sf.claim(["o1", "o3"])
    assert owned2 == ["o3"] and set(joined2) == {"o1"}

    got = []
    t = threading.Thread(target=lambda: got.append(joined2["o1"].result(5)))
    t.start()
    sf.resolve("o1", ("shm", 8))
    t.join(5)
    assert got == [("shm", 8)]
    # resolved claims leave the table: the next get re-fetches
    owned3, joined3 = sf.claim(["o1"])
    assert owned3 == ["o1"] and not joined3
    sf.fail("o1", RuntimeError("x"))
    sf.resolve("o2", None)
    sf.resolve("o3", None)


def test_pull_manager_single_flight_and_cap():
    """PullManager: one fetch per object id no matter how many requesters,
    and in-flight bytes never exceed the cap — excess requests queue and
    launch as room frees."""
    from ray_tpu._private.node_agent import PullManager

    async def body():
        loop = asyncio.get_running_loop()
        pm = PullManager(loop, max_bytes=100)
        calls = []

        def fetch(oid):
            async def run():
                calls.append(oid)
                await asyncio.sleep(0.02)
                return True
            return run

        t1 = pm.request("a", 60, fetch("a"))
        t2 = pm.request("a", 60, fetch("a"))   # joins in-flight, no 2nd fetch
        assert t2 is t1
        t3 = pm.request("b", 60, fetch("b"))   # 60+60 > 100: queued
        assert t3 is None and pm.inflight_bytes == 60
        await t1
        for _ in range(50):                     # queued pull launches
            if "b" in calls:
                break
            await asyncio.sleep(0.01)
        assert calls == ["a", "b"]
        while pm.inflight_bytes:
            await asyncio.sleep(0.01)

    asyncio.run(body())


# ------------------------------------------------------- max_concurrency

_MC_SCRIPT = """
import os
import ray_tpu as ray

ray.init(num_cpus=4)

@ray.remote
class A:
    def pool_env(self):
        return os.environ.get("RAY_TPU_MAX_CONCURRENCY")

    def slow(self):
        import time
        time.sleep(0.3)
        return 1

a = ray.get_actor  # touch surface
two = A.options(max_concurrency=2).remote()
one = A.options(max_concurrency=1).remote()
assert ray.get(two.pool_env.remote(), timeout=60) == "2"
assert ray.get(one.pool_env.remote(), timeout=60) == "1"
# a max_concurrency=2 actor really overlaps two calls
import time
t0 = time.time()
refs = [two.slow.remote() for _ in range(2)]
assert ray.get(refs, timeout=60) == [1, 1]
print("MC_WALL", round(time.time() - t0, 2))
print("MC_OK")
"""


def test_actor_max_concurrency_sizes_pool():
    out = _run_script(_MC_SCRIPT)
    assert "MC_OK" in out
