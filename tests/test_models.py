"""Model tests: shapes, decode==prefill consistency, sharding-rule coverage
(SURVEY.md §4 models/ops)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from ray_tpu.models import KVCache, Llama, LlamaConfig, MLPTorso, CNNTorso, \
    llama_param_count
from ray_tpu.parallel.mesh import local_cpu_mesh
from ray_tpu.parallel.sharding import llama_rules, tree_paths


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny(dtype=jnp.float32, param_dtype=jnp.float32,
                           attn_impl="xla")
    model = Llama(cfg)
    tokens = jnp.array(np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 16)))
    params = model.init(jax.random.PRNGKey(0), tokens)
    return cfg, model, params, tokens


class TestLlama:
    def test_forward_shape(self, tiny):
        cfg, model, params, tokens = tiny
        logits, cache = model.apply(params, tokens)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert logits.dtype == jnp.float32
        assert cache is None

    def test_param_count_formula(self, tiny):
        cfg, model, params, _ = tiny
        actual = sum(x.size for x in jax.tree_util.tree_leaves(params))
        assert actual == llama_param_count(cfg)

    def test_causality(self, tiny):
        """Changing a future token must not change past logits."""
        cfg, model, params, tokens = tiny
        logits1, _ = model.apply(params, tokens)
        perturbed = tokens.at[:, 10].set((tokens[:, 10] + 1) % cfg.vocab_size)
        logits2, _ = model.apply(params, perturbed)
        np.testing.assert_allclose(logits1[:, :10], logits2[:, :10], atol=1e-5)
        assert not np.allclose(logits1[:, 10:], logits2[:, 10:])

    def test_decode_matches_prefill(self, tiny):
        """Token-by-token decode through the KV cache reproduces prefill
        logits — the core decode-path invariant (serve/llm relies on it)."""
        cfg, model, params, tokens = tiny
        prefill_logits, _ = model.apply(params, tokens)

        cache = KVCache.init(cfg, batch=2, max_len=32, dtype=jnp.float32)
        step_logits = []
        for t in range(tokens.shape[1]):
            logits, cache = model.apply(params, tokens[:, t:t + 1], cache=cache)
            step_logits.append(logits[:, 0])
        decoded = jnp.stack(step_logits, axis=1)
        np.testing.assert_allclose(decoded, prefill_logits, atol=1e-4)

    def test_chunked_prefill_matches(self, tiny):
        """Prefill in two chunks through the cache == one-shot prefill."""
        cfg, model, params, tokens = tiny
        full, _ = model.apply(params, tokens)
        cache = KVCache.init(cfg, batch=2, max_len=32, dtype=jnp.float32)
        l1, cache = model.apply(params, tokens[:, :10], cache=cache)
        l2, cache = model.apply(params, tokens[:, 10:], cache=cache)
        np.testing.assert_allclose(jnp.concatenate([l1, l2], 1), full, atol=1e-4)

    def test_remat_same_output(self):
        cfg = LlamaConfig.tiny(dtype=jnp.float32, param_dtype=jnp.float32,
                               attn_impl="xla")
        cfg_r = LlamaConfig.tiny(dtype=jnp.float32, param_dtype=jnp.float32,
                                 attn_impl="xla", remat=True)
        tokens = jnp.ones((1, 8), jnp.int32)
        p = Llama(cfg).init(jax.random.PRNGKey(0), tokens)
        l1, _ = Llama(cfg).apply(p, tokens)
        l2, _ = Llama(cfg_r).apply(p, tokens)
        np.testing.assert_allclose(l1, l2, atol=1e-5)

    def test_tied_embeddings(self):
        cfg = LlamaConfig.tiny(dtype=jnp.float32, param_dtype=jnp.float32,
                               attn_impl="xla", tie_embeddings=True)
        tokens = jnp.ones((1, 8), jnp.int32)
        params = Llama(cfg).init(jax.random.PRNGKey(0), tokens)
        flat = dict(tree_paths(params))
        assert not any("lm_head" in k for k in flat)


class TestShardingRules:
    def test_all_matrices_sharded(self, tiny):
        """Every ≥2D param must get a non-replicated spec from llama_rules —
        a silent replicate on an 8B weight is an HBM OOM on real meshes."""
        _, _, params, _ = tiny
        rules = llama_rules()
        for path, leaf in tree_paths(params):
            spec = rules.spec_for(path, leaf)
            if leaf.ndim >= 2:
                assert any(ax is not None for ax in tuple(spec)), path

    def test_sharded_apply_matches(self, tiny):
        """Params sharded over fsdp×tp mesh produce identical logits."""
        cfg, model, params, tokens = tiny
        mesh = local_cpu_mesh(4, {"fsdp": 2, "tp": 2})
        shardings = llama_rules().tree_shardings(params, mesh)
        sharded = jax.device_put(params, shardings)
        ref, _ = model.apply(params, tokens)
        out, _ = jax.jit(lambda p, t: model.apply(p, t))(sharded, tokens)
        np.testing.assert_allclose(out, ref, atol=1e-4)


class TestTorsos:
    def test_mlp(self):
        m = MLPTorso(hidden_sizes=(32, 16))
        x = jnp.ones((4, 10))
        p = m.init(jax.random.PRNGKey(0), x)
        assert m.apply(p, x).shape == (4, 16)

    def test_cnn_uint8(self):
        m = CNNTorso(channels=(8,), kernels=((3, 3),), strides=((2, 2),), hidden=32)
        x = jnp.ones((2, 16, 16, 3), jnp.uint8)
        p = m.init(jax.random.PRNGKey(0), x)
        out = m.apply(p, x)
        assert out.shape == (2, 32)
        assert out.dtype == jnp.float32
