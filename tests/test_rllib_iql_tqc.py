"""IQL + TQC (VERDICT r4 missing #5; ref: rllib/algorithms/iql/iql.py,
rllib/algorithms/tqc/tqc.py)."""

import numpy as np
import pytest

from test_rllib_cql import _pendulum_dataset


# ------------------------------------------------------------------ IQL
def test_iql_trains_offline():
    from ray_tpu.rllib import IQLConfig
    data = _pendulum_dataset(n_steps=2000)
    algo = (IQLConfig()
            .environment("Pendulum-v1")
            .offline_data_source(data)
            .training(lr=3e-4, train_batch_size=256, expectile=0.8,
                      beta=1.0, train_intensity=10)
            .evaluation(evaluation_duration=2)
            .debugging(seed=7)
            .build())
    losses = []
    for _ in range(4):
        learner = algo.train()["learner"]
        for k in ("value_loss", "critic_loss", "actor_loss"):
            assert np.isfinite(learner[k]), learner
        # AWR weights are exp(beta*adv) clipped — must stay positive+finite
        assert 0 < learner["awr_weight_mean"] < 101, learner
        losses.append(learner["value_loss"])
    ev = algo.evaluate()
    assert ev["episodes_this_iter"] == 2
    assert np.isfinite(ev["episode_return_mean"])


def test_iql_expectile_shifts_value_upward():
    """The expectile losses differ in what V converges to: tau→1 fits the
    upper envelope of Q, tau=0.5 the mean. With identical data+seed, the
    high-expectile V must sit above the symmetric-fit V."""
    from ray_tpu.rllib import IQLConfig
    import jax
    data = _pendulum_dataset(n_steps=1000)

    def mean_v(expectile):
        algo = (IQLConfig()
                .offline_data_source(data)
                .training(lr=1e-3, train_batch_size=256,
                          expectile=expectile, train_intensity=40)
                .debugging(seed=3)
                .build())
        for _ in range(4):
            algo.train()
        obs = data["obs"][:512]
        v = algo.value.apply(algo.weights["value"], obs)
        return float(np.mean(jax.device_get(v)))

    assert mean_v(0.9) > mean_v(0.5), "expectile regression had no effect"


def test_iql_weight_checkpoint_roundtrip():
    from ray_tpu.rllib import IQLConfig
    data = _pendulum_dataset(n_steps=500)
    algo = (IQLConfig().offline_data_source(data)
            .training(train_batch_size=128, train_intensity=2)
            .debugging(seed=0).build())
    algo.train()
    w = algo.get_weights()
    algo2 = (IQLConfig().offline_data_source(data)
             .training(train_batch_size=128, train_intensity=2)
             .debugging(seed=1).build())
    algo2.set_weights(w)
    import jax
    a = jax.device_get(algo.weights["value"])
    b = jax.device_get(algo2.weights["value"])
    leaves_a = jax.tree_util.tree_leaves(a)
    leaves_b = jax.tree_util.tree_leaves(b)
    for x, y in zip(leaves_a, leaves_b):
        np.testing.assert_array_equal(x, y)


# ------------------------------------------------------------------ TQC
def test_tqc_trains_online():
    from ray_tpu.rllib import TQCConfig
    algo = (TQCConfig()
            .environment("Pendulum-v1")
            .training(lr=3e-4, train_batch_size=128, n_quantiles=13,
                      n_critics=2, top_quantiles_to_drop_per_net=2,
                      num_steps_sampled_before_learning_starts=64,
                      train_intensity=2, rollout_fragment_length=32)
            .env_runners(num_env_runners=0, num_envs_per_env_runner=2)
            .debugging(seed=5)
            .build())
    learned = False
    for _ in range(6):
        result = algo.train()
        if "learner" in result:
            learned = True
            lm = result["learner"]
            assert np.isfinite(lm["critic_loss"]), lm
            assert np.isfinite(lm["actor_loss"]), lm
            assert lm["alpha"] > 0
        assert result["num_env_steps_sampled_this_iter"] > 0
    assert learned, "never reached learning_starts"


def test_tqc_truncation_lowers_target():
    """Dropping the top atoms must lower the pooled target mean — the whole
    point of TQC. Verify on the algorithm's own jitted update by comparing
    z_target_mean with drop=0 vs drop=8 on identical data+weights."""
    from ray_tpu.rllib import TQCConfig
    from ray_tpu.rllib import sample_batch as SB
    import jax

    def target_mean(drop):
        algo = (TQCConfig()
                .environment("Pendulum-v1")
                .training(lr=3e-4, train_batch_size=64, n_quantiles=11,
                          n_critics=2, top_quantiles_to_drop_per_net=drop,
                          num_steps_sampled_before_learning_starts=0,
                          train_intensity=1, rollout_fragment_length=16)
                .env_runners(num_env_runners=0)
                .debugging(seed=11)
                .build())
        rng = np.random.default_rng(11)
        batch = {SB.OBS: rng.normal(size=(64, 3)).astype(np.float32),
                 SB.ACTIONS: rng.uniform(-2, 2, (64, 1)).astype(np.float32),
                 SB.REWARDS: rng.normal(size=64).astype(np.float32),
                 SB.NEXT_OBS: rng.normal(size=(64, 3)).astype(np.float32),
                 SB.TERMINATEDS: np.zeros(64, np.float32)}
        key = jax.random.PRNGKey(0)
        _, _, metrics = algo._update(algo.weights, algo.opt_state, batch, key)
        return float(metrics["z_target_mean"])

    assert target_mean(8) < target_mean(0)


def test_tqc_ensemble_params_are_stacked():
    """The critic ensemble is one stacked pytree (leaf leading dim =
    n_critics) — the vmapped-apply design the module docstring promises."""
    from ray_tpu.rllib import TQCConfig
    import jax
    algo = (TQCConfig()
            .environment("Pendulum-v1")
            .training(n_quantiles=7, n_critics=3,
                      top_quantiles_to_drop_per_net=1,
                      rollout_fragment_length=4)
            .env_runners(num_env_runners=0)
            .debugging(seed=0)
            .build())
    for leaf in jax.tree_util.tree_leaves(algo.weights["critics"]):
        assert leaf.shape[0] == 3, leaf.shape
    obs = np.zeros((5, 3), np.float32)
    act = np.zeros((5, 1), np.float32)
    z = algo.module.z_all(algo.weights["critics"], obs, act)
    assert z.shape == (5, 3, 7)
