"""Actor-handle refcount GC + bounded controller bookkeeping.

VERDICT r2: idle dedicated actor workers accumulated forever (the asyncio-task
"leak" was 22 live worker connections for out-of-scope actors), and
`Controller.tasks`/`timeline_events` grew without bound. Reference semantics:
Ray terminates non-detached actors when every handle goes out of scope
(src/ray/gcs/gcs_server/gcs_actor_manager.cc OnActorOutOfScope) and prunes
finished task records (gcs_task_manager.h).
"""

import gc
import time

import numpy as np


def _controller():
    from ray_tpu._private import state
    return state.global_client().controller


def _wait_for(cond, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return cond()


def test_anonymous_actor_gc_reclaims_worker(ray_session):
    ray = ray_session
    ctl = _controller()

    @ray.remote
    class Counter:
        def bump(self):
            return 1

    a = Counter.remote()
    assert ray.get(a.bump.remote(), timeout=60) == 1
    aid = a._actor_id
    del a
    gc.collect()
    assert _wait_for(lambda: ctl.actors[aid].state == "DEAD"), \
        "anonymous actor must be GC'd when its last handle drops"
    assert _wait_for(lambda: not any(w.actor_id == aid and w.state != "dead"
                                     for w in ctl.workers.values())), \
        "the dedicated worker process must be reclaimed"


def test_named_actor_survives_handle_drop(ray_session):
    ray = ray_session
    ctl = _controller()

    @ray.remote
    class Keeper:
        def ping(self):
            return "pong"

    a = Keeper.options(name="gc-keeper").remote()
    assert ray.get(a.ping.remote(), timeout=60) == "pong"
    aid = a._actor_id
    del a
    gc.collect()
    time.sleep(1.0)
    assert ctl.actors[aid].state != "DEAD"
    b = ray.get_actor("gc-keeper")
    assert ray.get(b.ping.remote(), timeout=60) == "pong"
    ray.kill(b)


def test_handle_in_task_arg_keeps_actor_alive(ray_session):
    ray = ray_session
    ctl = _controller()

    @ray.remote
    class Val:
        def get(self):
            return 42

    @ray.remote
    def use(h):
        import ray_tpu
        time.sleep(0.3)  # outlive the driver's temporary handle
        return ray_tpu.get(h.get.remote(), timeout=60)

    # the driver handle is a temporary: dropped as soon as remote() returns
    tmp = Val.remote()
    aid = tmp._actor_id
    ref = use.remote(tmp)
    del tmp
    gc.collect()
    assert ray.get(ref, timeout=60) == 42
    # with no surviving handle anywhere, the actor is then collected
    assert _wait_for(lambda: ctl.actors[aid].state == "DEAD")


def test_handle_inside_put_object_pins_actor(ray_session):
    ray = ray_session
    ctl = _controller()

    @ray.remote
    class Val:
        def get(self):
            return 7

    a = Val.remote()
    aid = a._actor_id
    box = ray.put({"handle": a})
    del a
    gc.collect()
    time.sleep(0.5)
    assert ctl.actors[aid].state != "DEAD", \
        "a handle serialized into a stored object must pin the actor"
    h = ray.get(box)["handle"]
    assert ray.get(h.get.remote(), timeout=60) == 7
    del box, h
    gc.collect()
    assert _wait_for(lambda: ctl.actors[aid].state == "DEAD")


def test_pending_calls_finish_before_gc(ray_session):
    ray = ray_session

    @ray.remote
    class Slow:
        def work(self):
            time.sleep(0.5)
            return "done"

    # fire-and-drop: the in-flight call must complete, not die with the handle
    ref = Slow.remote().work.remote()
    gc.collect()
    assert ray.get(ref, timeout=60) == "done"


def test_task_records_bounded(ray_session):
    ray = ray_session
    ctl = _controller()

    @ray.remote
    def f(i):
        return i

    old = ctl.task_retention
    ctl.task_retention = 25
    try:
        refs = [f.remote(i) for i in range(120)]
        assert sum(ray.get(refs, timeout=120)) == sum(range(120))
        assert len(ctl._done_task_ids) <= 25
        assert len(ctl.lineage_specs) <= ctl.lineage_retention
        # timeline is a bounded deque
        assert ctl.timeline_events.maxlen is not None
    finally:
        ctl.task_retention = old


def test_lineage_survives_task_record_gc(ray_session):
    ray = ray_session
    ctl = _controller()

    @ray.remote
    def make(seed):
        rng = np.random.default_rng(seed)
        return rng.normal(size=(64, 256))  # >64KB: lands in shm

    ref = make.remote(3)
    first = np.array(ray.get(ref, timeout=60), copy=True)
    tid = ctl.objects[ref.id].creating_task

    @ray.remote
    def nop():
        return None

    old = ctl.task_retention
    ctl.task_retention = 0
    try:
        ray.get(nop.remote(), timeout=60)  # completion triggers a GC sweep
        assert _wait_for(lambda: tid not in ctl.tasks), "record should be pruned"
        assert tid in ctl.lineage_specs
    finally:
        ctl.task_retention = old
    # storage loss after the record is gone: slim spec still reconstructs
    ctl.store.delete_segment(ref.id)
    second = ray.get(ref, timeout=60)
    np.testing.assert_allclose(first, second)


def test_cancelled_queued_call_does_not_block_gc(ray_session):
    """Code-review regression: a cancelled PENDING method left in the actor
    queue must not defer handle-GC forever."""
    ray = ray_session
    ctl = _controller()

    @ray.remote
    class S:
        def slow(self):
            time.sleep(1.0)
            return 1

        def fast(self):
            return 2

    a = S.remote()
    aid = a._actor_id
    r1 = a.slow.remote()
    r2 = a.fast.remote()  # queued behind slow
    ray.cancel(r2)
    del a
    gc.collect()
    assert ray.get(r1, timeout=60) == 1
    assert _wait_for(lambda: aid not in ctl.actors
                     or ctl.actors[aid].state == "DEAD")


def test_dead_actor_records_pruned(ray_session):
    ray = ray_session
    ctl = _controller()

    @ray.remote
    class Tiny:
        def ping(self):
            return 0

    old = ctl.dead_actor_retention
    ctl.dead_actor_retention = 3
    try:
        for _ in range(8):
            t = Tiny.remote()
            ray.get(t.ping.remote(), timeout=60)
            ray.kill(t)
        n_dead = sum(1 for a in ctl.actors.values() if a.state == "DEAD")
        assert n_dead <= 4, f"{n_dead} dead actor records retained"
    finally:
        ctl.dead_actor_retention = old


def test_abandoned_stream_state_released(ray_session):
    """Code-review regression: a half-iterated generator that is dropped must
    not leave its StreamState resident forever."""
    ray = ray_session
    ctl = _controller()

    @ray.remote
    def gen(n):
        for i in range(n):
            yield i

    g = gen.options(num_returns="streaming").remote(5)
    tid = g.task_id
    it = iter(g)
    assert ray.get(next(it)) == 0  # consume one, then abandon
    del g, it
    gc.collect()
    assert _wait_for(lambda: tid not in ctl.streams)


def test_drained_stream_state_released(ray_session):
    ray = ray_session
    ctl = _controller()

    @ray.remote
    def gen(n):
        for i in range(n):
            yield i

    g = gen.options(num_returns="streaming").remote(4)
    tid = g.task_id
    assert [ray.get(r) for r in g] == [0, 1, 2, 3]
    del g
    gc.collect()
    assert _wait_for(lambda: tid not in ctl.streams)
