"""util.tracing core + trace propagation through the control plane
(ISSUE 6 tentpole; ref: ray's opencensus span plumbing, collapsed to a
per-process ring + id propagation inside existing frames)."""

import json
import logging
import threading

import pytest

from ray_tpu.util import tracing


# -- core ring / ids ---------------------------------------------------------

def test_ring_is_bounded_and_counts_drops(monkeypatch):
    monkeypatch.setenv("RAY_TPU_TRACE_BUFFER", "16")
    tracing.refresh()
    for i in range(40):
        tracing.record_span(f"s{i}", "test", None, i, None, 0.0, 0.0)
    assert len(tracing.events()) == 16
    # oldest spans fell off the front; the newest survive
    assert tracing.events()[-1]["name"] == "s39"
    # >= not ==: the ring is process-global and tracing is on by default,
    # so background threads of the session fixture (client flusher, late
    # actor teardown from earlier tests) may race a few spans into the
    # 16-slot ring while this loop runs
    assert tracing.summary()["dropped"] >= 40 - 16


def test_sampling_is_deterministic_and_proportional(monkeypatch):
    monkeypatch.setenv("RAY_TPU_TRACE_SAMPLE", "0.5")
    tracing.refresh()
    keys = [f"t-{i:04d}" for i in range(1000)]
    first = [tracing.trace_id_for(k) for k in keys]
    # same verdict every time — any process holding the key agrees
    assert [tracing.trace_id_for(k) for k in keys] == first
    kept = [t for t in first if t is not None]
    assert all(t in keys for t in kept)  # the key IS the id
    assert 350 < len(kept) < 650  # crc32 split lands near the rate


def test_sample_edges(monkeypatch):
    monkeypatch.setenv("RAY_TPU_TRACE_SAMPLE", "0.0")
    tracing.refresh()
    assert tracing.trace_id_for("t-x") is None
    monkeypatch.setenv("RAY_TPU_TRACE_SAMPLE", "1.0")
    tracing.refresh()
    assert tracing.trace_id_for("t-x") == "t-x"
    monkeypatch.setenv("RAY_TPU_TRACE", "0")
    tracing.refresh()
    assert tracing.trace_id_for("t-x") is None
    assert tracing.new_trace_id() is None


def test_stamp_derives_or_inherits():
    class Spec:
        task_id = "t-abc"
        trace_id = None
        parent_span_id = None

    # root submit: id derived from the task id, nothing to note (None)
    s = Spec()
    assert tracing.stamp(s) is None
    assert s.trace_id == "t-abc"

    # nested submit: the exec thread's context wins and IS returned
    tracing.set_current("t-parent", 7)
    try:
        s2 = Spec()
        assert tracing.stamp(s2) == "t-parent"
        assert s2.trace_id == "t-parent" and s2.parent_span_id == 7
    finally:
        tracing.set_current(None, None)


def test_span_context_is_thread_local():
    seen = {}

    def other():
        seen["other"] = tracing.current_trace_id()

    tracing.set_current("t-main", 1)
    try:
        t = threading.Thread(target=other)
        t.start()
        t.join()
    finally:
        tracing.set_current(None, None)
    assert seen["other"] is None


def test_drain_pops_each_span_exactly_once():
    for i in range(10):
        tracing.record_span(f"d{i}", "test", None, i, None, 0.0, 0.0)
    a = tracing.drain(4)
    b = tracing.drain()
    assert [e["name"] for e in a] == [f"d{i}" for i in range(4)]
    assert [e["name"] for e in b] == [f"d{i}" for i in range(4, 10)]
    assert tracing.drain() == []
    assert tracing.events() == []  # drained spans left the ring


def test_to_chrome_shape_is_json_serializable():
    with tracing.span("unit.op", cat="test", trace_id="t-1",
                      args={"k": "v"}):
        pass
    evs = tracing.to_chrome(tracing.events())
    x = [e for e in evs if e.get("ph") == "X"]
    assert x, evs
    e = x[0]
    assert e["name"] == "unit.op" and e["cat"] == "test"
    assert e["ts"] > 1e15  # epoch microseconds
    assert e["dur"] >= 1  # 1us floor keeps Perfetto rendering
    assert e["args"]["trace_id"] == "t-1" and e["args"]["k"] == "v"
    json.dumps(evs)  # the whole export must serialize


def test_format_timeline_expands_raw_tuples():
    from ray_tpu._private.controller import format_timeline
    entries = [
        ("_task", "f", 11, 10.0, 10.5, "t-1", "t-1"),
        ("_phases", "f", 11, "t-1", "t-1",
         [("queued", 9.0, 10.0), ("exec", 10.0, 10.4),
          ("publish", 10.4, 10.5)]),
        {"name": "shipped", "ph": "X", "pid": 9, "tid": 0,
         "ts": 1.0, "dur": 2.0},  # pre-formatted node span passes through
    ]
    evs = format_timeline(entries)
    assert [e["name"] for e in evs] == [
        "f", "f:queued", "f:exec", "f:publish", "shipped"]
    phases = [e for e in evs if e.get("cat") == "task_phase"]
    assert all(e["args"]["trace_id"] == "t-1" and e["ph"] == "X"
               for e in phases)
    assert phases[1]["dur"] == pytest.approx(0.4e6)
    json.dumps(evs)


# -- propagation through a live session --------------------------------------

def test_trace_follows_task_and_nested_child(ray_session):
    ray = ray_session

    @ray.remote
    def child():
        return tracing.current_trace_id()

    @ray.remote
    def parent():
        # the worker sets the span context around execution, so a nested
        # submit inherits THIS task's trace
        return tracing.current_trace_id(), ray.get(child.remote())

    parent_trace, child_trace = ray.get(parent.remote())
    assert parent_trace and parent_trace == child_trace

    from ray_tpu.util.state import list_tasks
    rows = {r["task_id"]: r for r in list_tasks(limit=1000)}
    traced = [r for r in rows.values() if r.get("trace_id") == parent_trace]
    assert len(traced) >= 2  # parent + nested child share one trace


# -- satellite: metrics registry thread-safety -------------------------------

def test_metrics_get_or_create_is_thread_safe():
    from ray_tpu.util import metrics
    errs = []

    def hammer():
        try:
            for _ in range(200):
                metrics.get_or_create(
                    metrics.Counter, "trace_test_race_total").inc()
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=hammer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    snap = {m["name"]: m for m in metrics.collect()}
    assert snap["trace_test_race_total"]["values"][()] == 8 * 200


# -- satellite: log records join traces --------------------------------------

def test_context_filter_stamps_trace_id(monkeypatch):
    from ray_tpu.logging_config import ContextFilter
    monkeypatch.setenv("RAY_TPU_NODE_ID", "node-7")
    rec = logging.LogRecord("t", logging.INFO, __file__, 1, "m", (), None)
    tracing.set_current("t-log", 3)
    try:
        assert ContextFilter().filter(rec) is True
    finally:
        tracing.set_current(None, None)
    assert rec.trace_id == "t-log"
    assert rec.node_id == "node-7"
    assert rec.worker_id  # env default ("driver") when unset


def test_safe_formatter_tolerates_missing_fields():
    from ray_tpu.logging_config import SafeFormatter
    fmt = SafeFormatter("%(levelname)s [trace=%(trace_id)s] %(message)s")
    rec = logging.LogRecord("t", logging.INFO, __file__, 1, "msg", (), None)
    assert fmt.format(rec) == "INFO [trace=-] msg"
