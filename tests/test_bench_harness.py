"""bench.py orchestrator resilience (VERDICT r4 weak #1: the harness turned
a transient TPU-relay wedge into a zero-data round).

Proves the four round-5 hardening properties without TPU hardware:
  (a) global budget clamps child timeouts / skips rungs when exhausted,
  (b) the init watchdog kills a child that never prints the sentinel in
      ~watchdog seconds (not the full child timeout) and a sentinel-printing
      child is NOT init-killed,
  (c) the stale sweep recognizes node_main / stray bench processes,
  (d) orchestrate emits the train JSON line before aux benches run.

Ref contrast: /root/reference/release/benchmarks wraps each workload in hard
timeouts; its run_release_test.py kills the whole anyscale job on overrun.
"""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402


@pytest.fixture(autouse=True)
def _fast_watchdog(monkeypatch):
    monkeypatch.setenv("RAY_TPU_BENCH_INIT_WATCHDOG_S", "2")
    yield


def test_watchdog_kills_wedged_child(monkeypatch):
    """A child that never prints the sentinel dies at the watchdog, not the
    hard timeout — the r4 wedged-relay mode cost 1500s per attempt."""
    t0 = time.monotonic()
    rc, out, err, reason = bench._popen_watched(
        [sys.executable, "-c", "import time; time.sleep(600)"],
        dict(os.environ), timeout=300)
    elapsed = time.monotonic() - t0
    assert reason == "init_hang"
    assert elapsed < 30  # 2s watchdog + kill + join slop (1-core box: 3x slack)


def test_watchdog_respects_sentinel(monkeypatch):
    """A child that prints the sentinel is owned by the hard timeout only."""
    # watchdog must beat the hard timeout to prove precedence, but give the
    # child generous startup slack (1-core box; 2s flaked under load)
    monkeypatch.setenv("RAY_TPU_BENCH_INIT_WATCHDOG_S", "8")
    code = ("import sys, time; print('BENCH_INIT_OK', file=sys.stderr, "
            "flush=True); time.sleep(600)")
    t0 = time.monotonic()
    rc, out, err, reason = bench._popen_watched(
        [sys.executable, "-c", code], dict(os.environ), timeout=12)
    elapsed = time.monotonic() - t0
    assert reason == "timeout"  # NOT init_hang: sentinel was seen
    assert elapsed >= 12
    assert elapsed < 90


def test_watchdog_passes_healthy_child(monkeypatch):
    # the child prints the sentinel at startup, but interpreter spawn alone
    # can exceed the fixture's 2s watchdog when the suite has the box busy
    monkeypatch.setenv("RAY_TPU_BENCH_INIT_WATCHDOG_S", "25")
    code = ("import sys; print('BENCH_INIT_OK', file=sys.stderr, flush=True); "
            "print('{\"ok\": 1}')")
    rc, out, err, reason = bench._popen_watched(
        [sys.executable, "-c", code], dict(os.environ), timeout=30)
    assert reason is None and rc == 0
    assert bench._parse_json_tail(out) == {"ok": 1}


def test_ladder_diverts_to_scrub_after_two_init_hangs(monkeypatch):
    """Init hangs skip the rung's retries (retrying a wedged relay is wasted
    budget) and two hangs divert straight to CPU scrub."""
    calls = []

    def fake_run_child(config, cpu_scrub=False):
        calls.append((config, cpu_scrub))
        if cpu_scrub:
            return {"metric": "m", "value": 1.0}, None
        return None, "init_hang"

    monkeypatch.setattr(bench, "_run_child", fake_run_child)
    result = bench.run_ladder()
    assert result == {"metric": "m", "value": 1.0}
    # one attempt per TPU rung (no retries burned on a wedge), then scrub
    assert calls == [("llama_1b", False), ("llama_125m", False),
                     ("llama_125m", True)]


def test_budget_exhausted_skips_child(monkeypatch):
    monkeypatch.setenv("RAY_TPU_BENCH_BUDGET_S", "0")
    result, reason = bench._run_child("llama_125m")
    assert result is None and reason == "budget"


def test_budget_clamps_child_timeout(monkeypatch):
    """With 500s left, a 1500s-config TPU child gets ~100s (500 minus the
    400s reserved so the CPU-scrub rung always gets its turn)."""
    monkeypatch.setenv("RAY_TPU_BENCH_BUDGET_S",
                       str(time.monotonic() - bench._T_START + 500))
    seen = {}
    real = bench._popen_watched

    def spy(cmd, env, timeout, watch_init=True):
        seen["timeout"] = timeout
        return 0, '{"metric": "m", "value": 1.0}\n', "", None

    monkeypatch.setattr(bench, "_popen_watched", spy)
    result, reason = bench._run_child("llama_1b")
    assert result is not None
    assert seen["timeout"] <= 100
    monkeypatch.setattr(bench, "_popen_watched", real)


def test_tpu_rungs_reserve_budget_for_scrub(monkeypatch):
    """With only 300s left, TPU rungs are skipped (reserve 400) but the
    CPU-scrub rung still runs — a post-sentinel compile wedge on the TPU
    rungs can never starve the always-record-SOME-number guarantee."""
    monkeypatch.setenv("RAY_TPU_BENCH_BUDGET_S",
                       str(time.monotonic() - bench._T_START + 300))
    result, reason = bench._run_child("llama_1b")
    assert result is None and reason == "budget"

    def spy(cmd, env, timeout, watch_init=True):
        return 0, '{"metric": "m_cpu", "value": 1.0}\n', "", None

    monkeypatch.setattr(bench, "_popen_watched", spy)
    result, reason = bench._run_child("llama_125m", cpu_scrub=True)
    assert result is not None


def test_stale_sweep_matches_node_and_bench_processes():
    """_kill_stale_workers kills a node_main whose head is gone and a stray
    --measure child (r4's sweep only matched worker_main and missed both)."""
    # fake node_main: argv contains the module name + a dead head address
    node = subprocess.Popen(
        [sys.executable, "-c",
         "import sys, time; time.sleep(300)",
         "ray_tpu._private.node_main", "--address", "127.0.0.1:1"],
        start_new_session=True)
    # fake stray measure child from a killed previous run
    stray = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(300)",
         "bench.py", "--measure"],
        start_new_session=True)
    try:
        deadline = time.monotonic() + 30
        bench._kill_stale_workers()
        while time.monotonic() < deadline:
            if node.poll() is not None and stray.poll() is not None:
                break
            time.sleep(0.2)
        assert node.poll() is not None, "stale node_main survived the sweep"
        assert stray.poll() is not None, "stray --measure child survived"
    finally:
        for p in (node, stray):
            if p.poll() is None:
                p.kill()
            p.wait()


def test_orchestrate_emits_train_line_before_aux(monkeypatch, capsys):
    """The headline JSON must hit stdout before any aux bench runs, and the
    merged record is the final line (r4 printed once, after aux — a kill
    during aux lost the measured number)."""
    order = []

    monkeypatch.setattr(bench, "_kill_stale_workers", lambda: None)
    monkeypatch.setattr(bench, "_sweep_orphan_shm", lambda: None)
    monkeypatch.setattr(bench, "run_ladder",
                        lambda: {"metric": "m", "value": 2.0})
    monkeypatch.setattr(bench, "_prior_value", lambda m: 1.0)

    def fake_aux(script, timeout, env_extra=None):
        order.append(script)
        return {"ok": script}

    monkeypatch.setattr(bench, "_run_aux_bench", fake_aux)
    monkeypatch.delenv("RAY_TPU_BENCH_TRAIN_ONLY", raising=False)
    bench.orchestrate()
    lines = [json.loads(l) for l in capsys.readouterr().out.strip().splitlines()]
    # first line: train headline, already valid, vs_baseline rewritten
    assert lines[0]["metric"] == "m" and lines[0]["vs_baseline"] == 2.0
    assert "serving_b8" not in lines[0]
    # aux results streamed as keyed lines, merged record last
    assert lines[-1]["serving_b8"] == {"ok": "serving_bench.py"}
    assert lines[-1]["serving_b32"] == {"ok": "serving_bench.py"}
    assert lines[-1]["rllib_ppo"] == {"ok": "rllib_bench.py"}


def test_end_to_end_fake_hang_falls_to_cpu_scrub():
    """Integration: full orchestrator vs a simulated wedged relay
    (RAY_TPU_BENCH_FAKE_HANG hangs every non-CPU child before jax import).
    The ladder must still produce an rc=0 JSON record via the CPU-scrub rung
    within the global budget — this is the exact r4 failure, replayed."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # let TPU rung children "try" the relay
    env.update({
        "RAY_TPU_BENCH_FAKE_HANG": "600",
        # big enough for a genuine CPU child to import jax + print the
        # sentinel on this 1-core box; the two wedged TPU rungs still die
        # in ~30s each instead of 2x1500s
        "RAY_TPU_BENCH_INIT_WATCHDOG_S": "30",
        "RAY_TPU_BENCH_BUDGET_S": "600",
        "RAY_TPU_BENCH_TRAIN_ONLY": "1",
    })
    t0 = time.monotonic()
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       env=env, capture_output=True, text=True, timeout=570)
    elapsed = time.monotonic() - t0
    assert r.returncode == 0, r.stderr[-2000:]
    rec = bench._parse_json_tail(r.stdout)
    assert rec is not None
    assert rec["backend"] == "cpu"
    assert rec["metric"].endswith("_cpu")
    assert rec["value"] > 0
    # 2 watchdog kills (~3s each) + CPU measure; far under the r4 2×1500s
    assert elapsed < 540


def test_late_tpu_retry_replaces_cpu_fallback(monkeypatch, capsys):
    """r5 (observed live): the relay wedges, the ladder records a CPU
    number, the relay recovers minutes later. With budget left the
    orchestrator must retry the TPU rung once and prefer its record."""
    monkeypatch.setattr(bench, "_kill_stale_workers", lambda: None)
    monkeypatch.setattr(bench, "_sweep_orphan_shm", lambda: None)
    monkeypatch.setattr(bench, "run_ladder",
                        lambda: {"metric": "m", "value": 50.0,
                                 "backend": "cpu"})
    monkeypatch.setattr(bench, "_prior_value", lambda m: None)
    monkeypatch.setattr(bench, "_remaining", lambda: 1400.0)
    slept = []
    monkeypatch.setattr(bench.time, "sleep", lambda s: slept.append(s))
    monkeypatch.setattr(
        bench, "_run_child",
        lambda cfg, cpu_scrub=False: ({"metric": "m", "value": 20000.0,
                                       "backend": "tpu"}, None))
    monkeypatch.setenv("RAY_TPU_BENCH_TRAIN_ONLY", "1")
    bench.orchestrate()
    lines = [json.loads(l)
             for l in capsys.readouterr().out.strip().splitlines()]
    assert lines[-1]["backend"] == "tpu" and lines[-1]["value"] == 20000.0
    assert slept and slept[0] <= 240


def test_late_tpu_retry_skipped_without_budget(monkeypatch, capsys):
    """1100s remaining is NOT enough: after the 240s wait and the child's
    400s scrub reserve only ~460s of child time remains vs the rung's
    1500s budget — the retry must be skipped, not attempted futilely."""
    monkeypatch.setattr(bench, "_kill_stale_workers", lambda: None)
    monkeypatch.setattr(bench, "_sweep_orphan_shm", lambda: None)
    monkeypatch.setattr(bench, "run_ladder",
                        lambda: {"metric": "m", "value": 50.0,
                                 "backend": "cpu"})
    monkeypatch.setattr(bench, "_prior_value", lambda m: None)
    monkeypatch.setattr(bench, "_remaining", lambda: 1100.0)

    def boom(cfg, cpu_scrub=False):
        raise AssertionError("retry must not run on a thin budget")

    monkeypatch.setattr(bench, "_run_child", boom)
    monkeypatch.setenv("RAY_TPU_BENCH_TRAIN_ONLY", "1")
    bench.orchestrate()
    lines = [json.loads(l)
             for l in capsys.readouterr().out.strip().splitlines()]
    assert lines[-1]["backend"] == "cpu"
