"""bench.py orchestrator resilience (VERDICT r4 weak #1: the harness turned
a transient TPU-relay wedge into a zero-data round).

Proves the four round-5 hardening properties without TPU hardware:
  (a) global budget clamps child timeouts / skips rungs when exhausted,
  (b) the init watchdog kills a child that never prints the sentinel in
      ~watchdog seconds (not the full child timeout) and a sentinel-printing
      child is NOT init-killed,
  (c) the stale sweep recognizes node_main / stray bench processes,
  (d) orchestrate emits the train JSON line before aux benches run.

Ref contrast: /root/reference/release/benchmarks wraps each workload in hard
timeouts; its run_release_test.py kills the whole anyscale job on overrun.
"""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402


@pytest.fixture(autouse=True)
def _fast_watchdog(monkeypatch):
    monkeypatch.setenv("RAY_TPU_BENCH_INIT_WATCHDOG_S", "2")
    # no test here may litter benchmarks/results/ — the artifact tests
    # opt back in against a tmp_path RESULTS_DIR
    monkeypatch.setenv("RAY_TPU_BENCH_WRITE_RESULTS", "0")
    yield


def test_watchdog_kills_wedged_child(monkeypatch):
    """A child that never prints the sentinel dies at the watchdog, not the
    hard timeout — the r4 wedged-relay mode cost 1500s per attempt."""
    t0 = time.monotonic()
    rc, out, err, reason = bench._popen_watched(
        [sys.executable, "-c", "import time; time.sleep(600)"],
        dict(os.environ), timeout=300)
    elapsed = time.monotonic() - t0
    assert reason == "init_hang"
    assert elapsed < 30  # 2s watchdog + kill + join slop (1-core box: 3x slack)


def test_watchdog_respects_sentinel(monkeypatch):
    """A child that prints the sentinel is owned by the hard timeout only."""
    # watchdog must beat the hard timeout to prove precedence, but give the
    # child generous startup slack (1-core box; 2s flaked under load)
    monkeypatch.setenv("RAY_TPU_BENCH_INIT_WATCHDOG_S", "8")
    code = ("import sys, time; print('BENCH_INIT_OK', file=sys.stderr, "
            "flush=True); time.sleep(600)")
    t0 = time.monotonic()
    rc, out, err, reason = bench._popen_watched(
        [sys.executable, "-c", code], dict(os.environ), timeout=12)
    elapsed = time.monotonic() - t0
    assert reason == "timeout"  # NOT init_hang: sentinel was seen
    assert elapsed >= 12
    assert elapsed < 90


def test_watchdog_passes_healthy_child(monkeypatch):
    # the child prints the sentinel at startup, but interpreter spawn alone
    # can exceed the fixture's 2s watchdog when the suite has the box busy
    monkeypatch.setenv("RAY_TPU_BENCH_INIT_WATCHDOG_S", "25")
    code = ("import sys; print('BENCH_INIT_OK', file=sys.stderr, flush=True); "
            "print('{\"ok\": 1}')")
    rc, out, err, reason = bench._popen_watched(
        [sys.executable, "-c", code], dict(os.environ), timeout=30)
    assert reason is None and rc == 0
    assert bench._parse_json_tail(out) == {"ok": 1}


def test_ladder_diverts_to_scrub_after_two_init_hangs(monkeypatch):
    """Init hangs skip the rung's retries (retrying a wedged relay is wasted
    budget) and two hangs divert straight to CPU scrub."""
    calls = []

    def fake_run_child(config, cpu_scrub=False):
        calls.append((config, cpu_scrub))
        if cpu_scrub:
            return {"metric": "m", "value": 1.0}, None
        return None, "init_hang"

    monkeypatch.setattr(bench, "_run_child", fake_run_child)
    result = bench.run_ladder()
    assert result == {"metric": "m", "value": 1.0}
    # one attempt per TPU rung (no retries burned on a wedge), then scrub
    assert calls == [("llama_1b", False), ("llama_125m", False),
                     ("llama_125m", True)]


def test_budget_exhausted_skips_child(monkeypatch):
    monkeypatch.setenv("RAY_TPU_BENCH_BUDGET_S", "0")
    result, reason = bench._run_child("llama_125m")
    assert result is None and reason == "budget"


def test_budget_clamps_child_timeout(monkeypatch):
    """With 500s left, a 1500s-config TPU child gets ~100s (500 minus the
    400s reserved so the CPU-scrub rung always gets its turn)."""
    monkeypatch.setenv("RAY_TPU_BENCH_BUDGET_S",
                       str(time.monotonic() - bench._T_START + 500))
    seen = {}
    real = bench._popen_watched

    def spy(cmd, env, timeout, watch_init=True):
        seen["timeout"] = timeout
        return 0, '{"metric": "m", "value": 1.0}\n', "", None

    monkeypatch.setattr(bench, "_popen_watched", spy)
    result, reason = bench._run_child("llama_1b")
    assert result is not None
    assert seen["timeout"] <= 100
    monkeypatch.setattr(bench, "_popen_watched", real)


def test_tpu_rungs_reserve_budget_for_scrub(monkeypatch):
    """With only 300s left, TPU rungs are skipped (reserve 400) but the
    CPU-scrub rung still runs — a post-sentinel compile wedge on the TPU
    rungs can never starve the always-record-SOME-number guarantee."""
    monkeypatch.setenv("RAY_TPU_BENCH_BUDGET_S",
                       str(time.monotonic() - bench._T_START + 300))
    result, reason = bench._run_child("llama_1b")
    assert result is None and reason == "budget"

    def spy(cmd, env, timeout, watch_init=True):
        return 0, '{"metric": "m_cpu", "value": 1.0}\n', "", None

    monkeypatch.setattr(bench, "_popen_watched", spy)
    result, reason = bench._run_child("llama_125m", cpu_scrub=True)
    assert result is not None


def test_stale_sweep_matches_node_and_bench_processes():
    """_kill_stale_workers kills a node_main whose head is gone and a stray
    --measure child (r4's sweep only matched worker_main and missed both)."""
    # fake node_main: argv contains the module name + a dead head address
    node = subprocess.Popen(
        [sys.executable, "-c",
         "import sys, time; time.sleep(300)",
         "ray_tpu._private.node_main", "--address", "127.0.0.1:1"],
        start_new_session=True)
    # fake stray measure child from a killed previous run
    stray = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(300)",
         "bench.py", "--measure"],
        start_new_session=True)
    try:
        deadline = time.monotonic() + 30
        bench._kill_stale_workers()
        while time.monotonic() < deadline:
            if node.poll() is not None and stray.poll() is not None:
                break
            time.sleep(0.2)
        assert node.poll() is not None, "stale node_main survived the sweep"
        assert stray.poll() is not None, "stray --measure child survived"
    finally:
        for p in (node, stray):
            if p.poll() is None:
                p.kill()
            p.wait()


def test_orchestrate_emits_train_line_before_aux(monkeypatch, capsys):
    """The headline JSON must hit stdout before any aux bench runs, and the
    merged record is the final line (r4 printed once, after aux — a kill
    during aux lost the measured number)."""
    order = []

    monkeypatch.setattr(bench, "_kill_stale_workers", lambda: None)
    monkeypatch.setattr(bench, "_sweep_orphan_shm", lambda: None)
    monkeypatch.setattr(bench, "run_ladder",
                        lambda: {"metric": "m", "value": 2.0})
    monkeypatch.setattr(bench, "_prior_value", lambda m: 1.0)

    def fake_aux(script, timeout, env_extra=None):
        order.append(script)
        return {"ok": script}

    monkeypatch.setattr(bench, "_run_aux_bench", fake_aux)
    monkeypatch.delenv("RAY_TPU_BENCH_TRAIN_ONLY", raising=False)
    bench.orchestrate()
    lines = [json.loads(l) for l in capsys.readouterr().out.strip().splitlines()]
    # first line: train headline, already valid, vs_baseline rewritten
    assert lines[0]["metric"] == "m" and lines[0]["vs_baseline"] == 2.0
    assert "serving_b8" not in lines[0]
    # aux results streamed as keyed lines, merged record last
    assert lines[-1]["serving_b8"] == {"ok": "serving_bench.py"}
    assert lines[-1]["serving_b32"] == {"ok": "serving_bench.py"}
    assert lines[-1]["rllib_ppo"] == {"ok": "rllib_bench.py"}


def test_end_to_end_fake_hang_falls_to_cpu_scrub():
    """Integration: full orchestrator vs a simulated wedged relay
    (RAY_TPU_BENCH_FAKE_HANG hangs every non-CPU child before jax import).
    The ladder must still produce an rc=0 JSON record via the CPU-scrub rung
    within the global budget — this is the exact r4 failure, replayed."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # let TPU rung children "try" the relay
    env.update({
        "RAY_TPU_BENCH_FAKE_HANG": "600",
        # big enough for a genuine CPU child to import jax + print the
        # sentinel on this 1-core box; the two wedged TPU rungs still die
        # in ~30s each instead of 2x1500s
        "RAY_TPU_BENCH_INIT_WATCHDOG_S": "30",
        "RAY_TPU_BENCH_BUDGET_S": "600",
        "RAY_TPU_BENCH_TRAIN_ONLY": "1",
        # children succeed for real here — don't litter benchmarks/results/
        "RAY_TPU_BENCH_WRITE_RESULTS": "0",
    })
    t0 = time.monotonic()
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       env=env, capture_output=True, text=True, timeout=570)
    elapsed = time.monotonic() - t0
    assert r.returncode == 0, r.stderr[-2000:]
    rec = bench._parse_json_tail(r.stdout)
    assert rec is not None
    assert rec["backend"] == "cpu"
    assert rec["metric"].endswith("_cpu")
    assert rec["value"] > 0
    # 2 watchdog kills (~3s each) + CPU measure; far under the r4 2×1500s
    assert elapsed < 540


def test_write_result_artifact_roundtrip(tmp_path, monkeypatch):
    """Successful records persist as <tag>_<UTC ts>.json under the results
    dir (r6 satellite: perf claims become committed, diffable artifacts)."""
    monkeypatch.setenv("RAY_TPU_BENCH_RESULTS_DIR", str(tmp_path))
    monkeypatch.delenv("RAY_TPU_BENCH_WRITE_RESULTS", raising=False)
    rec = {"metric": "train_tok_s", "value": 123.4}
    path = bench._write_result_artifact("llama_1b", rec)
    assert path is not None and os.path.dirname(path) == str(tmp_path)
    name = os.path.basename(path)
    assert name.startswith("llama_1b_") and name.endswith(".json")
    with open(path) as f:
        assert json.load(f) == rec


def test_write_result_artifact_kill_switch(tmp_path, monkeypatch):
    """RAY_TPU_BENCH_WRITE_RESULTS=0 disables writes — tests that spawn
    real children rely on this to keep the repo clean."""
    monkeypatch.setenv("RAY_TPU_BENCH_RESULTS_DIR", str(tmp_path))
    monkeypatch.setenv("RAY_TPU_BENCH_WRITE_RESULTS", "0")
    assert bench._write_result_artifact("x", {"v": 1}) is None
    assert not list(tmp_path.iterdir())


def test_run_child_writes_artifact_on_success(tmp_path, monkeypatch):
    """_run_child persists every successful measure record, tagging the
    CPU-scrub rung with a _cpu suffix so fallback numbers are never
    mistaken for accelerator numbers."""
    monkeypatch.setenv("RAY_TPU_BENCH_RESULTS_DIR", str(tmp_path))
    monkeypatch.delenv("RAY_TPU_BENCH_WRITE_RESULTS", raising=False)
    monkeypatch.setenv("RAY_TPU_BENCH_BUDGET_S",
                       str(time.monotonic() - bench._T_START + 3000))

    def spy(cmd, env, timeout, watch_init=True):
        return 0, '{"metric": "m_cpu", "value": 2.0}\n', "", None

    monkeypatch.setattr(bench, "_popen_watched", spy)
    result, reason = bench._run_child("llama_125m", cpu_scrub=True)
    assert result is not None and reason is None
    files = sorted(os.listdir(tmp_path))
    assert len(files) == 1 and files[0].startswith("llama_125m_cpu_")


def test_aux_ladder_falls_to_cpu_scrub(tmp_path, monkeypatch, capsys):
    """run_aux_ladder (r6 satellite: serving/rllib benches get bench.py's
    resilience): the parent prints its own sentinel immediately (no jax →
    can't wedge), the accel rung init-hangs at the watchdog, the CPU-scrub
    rung's record wins, gains backend=cpu, is persisted, and the final
    JSON line + rc 0 reach the caller."""
    monkeypatch.setenv("RAY_TPU_BENCH_RESULTS_DIR", str(tmp_path))
    monkeypatch.delenv("RAY_TPU_BENCH_WRITE_RESULTS", raising=False)
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)  # accel rung exists
    calls = []

    def fake_popen(cmd, env, timeout, watch_init=True):
        calls.append((env.get("JAX_PLATFORMS"), timeout))
        if env.get("JAX_PLATFORMS") != "cpu":
            return -9, "", "", "init_hang"          # the wedged relay
        return 0, '{"dense": {"decode_tps": 9.0}}\n', "", None

    monkeypatch.setattr(bench, "_popen_watched", fake_popen)
    rc = bench.run_aux_ladder("/x/serving_bench.py", budget_s=900.0)
    assert rc == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert lines[0].startswith(bench._INIT_SENTINEL)
    rec = json.loads(lines[-1])
    assert rec["backend"] == "cpu"
    assert rec["dense"] == {"decode_tps": 9.0}
    # rung order: inherited-env accel attempt, then the CPU scrub
    assert [c[0] for c in calls] == [None, "cpu"]
    # both rungs clamp to the per-rung ceiling (and the accel rung had
    # already reserved the CPU rung's 420s turn out of the 900s budget)
    assert all(t <= 420.0 for _, t in calls)
    files = os.listdir(tmp_path)
    assert len(files) == 1 and files[0].startswith("serving_bench_cpu_")


def test_aux_ladder_skips_accel_rung_when_scrubbed(monkeypatch, capsys):
    """In an already-CPU-scrubbed environment there is no accel rung to
    try — one child, and a record that still carries `backend`."""
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("RAY_TPU_BENCH_WRITE_RESULTS", "0")
    calls = []

    def fake_popen(cmd, env, timeout, watch_init=True):
        calls.append(env.get("JAX_PLATFORMS"))
        return 0, '{"ppo_env_steps_per_sec": 5.0}\n', "", None

    monkeypatch.setattr(bench, "_popen_watched", fake_popen)
    rc = bench.run_aux_ladder("/x/rllib_bench.py", budget_s=600.0)
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["backend"] == "cpu" and calls == ["cpu"]


def test_aux_ladder_reports_all_rungs_failed(monkeypatch, capsys):
    """Every rung failing still yields rc 0 and a final JSON line — an aux
    bench must never sink the orchestrator's round — with the per-rung
    reasons recorded for the postmortem."""
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setenv("RAY_TPU_BENCH_WRITE_RESULTS", "0")
    monkeypatch.setattr(bench, "_popen_watched",
                        lambda cmd, env, timeout, watch_init=True:
                        (-9, "", "", "init_hang"))
    rc = bench.run_aux_ladder("/x/serving_bench.py", budget_s=900.0)
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["backend"] == "none"
    assert "accel: init_hang" in rec["error"]
    assert "cpu: init_hang" in rec["error"]


@pytest.mark.slow
def test_serving_bench_wedged_relay_records_cpu_backend():
    """Integration (r6 acceptance): serving_bench.py run WITHOUT flags vs a
    simulated wedged relay must exit 0 with a final JSON record carrying
    backend=cpu — the exact r5 failure ({"error": "init_hang"}), replayed
    against the self-orchestrating ladder."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # let the accel rung "try" the relay
    env.update({
        "RAY_TPU_BENCH_FAKE_HANG": "600",
        "RAY_TPU_BENCH_INIT_WATCHDOG_S": "20",
        # > cpu_timeout_s (420) so the accel rung actually runs (it
        # reserves the CPU rung's full turn before taking its own)
        "RAY_TPU_AUX_BUDGET_S": "500",
        "RAY_TPU_BENCH_WRITE_RESULTS": "0",
        "B": "2", "MAX_TOKENS": "4", "PROMPT_LEN": "8", "ROUNDS": "1",
        "SECTIONS": "dense",
    })
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "serving_bench.py")],
        env=env, capture_output=True, text=True, timeout=540)
    assert r.returncode == 0, r.stderr[-2000:]
    rec = bench._parse_json_tail(r.stdout)
    assert rec is not None, r.stdout[-500:]
    assert rec["backend"] == "cpu"
    assert rec["dense"]["decode_tps"] > 0
    assert rec["dense"]["host_syncs_per_token"] <= 1.0


@pytest.mark.slow
def test_rllib_bench_wedged_relay_records_cpu_backend():
    """Same wedged-relay replay for rllib_bench.py."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update({
        "RAY_TPU_BENCH_FAKE_HANG": "600",
        "RAY_TPU_BENCH_INIT_WATCHDOG_S": "20",
        "RAY_TPU_AUX_BUDGET_S": "500",
        "RAY_TPU_BENCH_WRITE_RESULTS": "0",
        "BUDGET_S": "2",
        "RLLIB_BENCH_MULTINODE": "0",
    })
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "rllib_bench.py")],
        env=env, capture_output=True, text=True, timeout=540)
    assert r.returncode == 0, r.stderr[-2000:]
    rec = bench._parse_json_tail(r.stdout)
    assert rec is not None, r.stdout[-500:]
    assert rec["backend"] == "cpu"
    assert rec["ppo_env_steps_per_sec"] > 0


def test_late_tpu_retry_replaces_cpu_fallback(monkeypatch, capsys):
    """r5 (observed live): the relay wedges, the ladder records a CPU
    number, the relay recovers minutes later. With budget left the
    orchestrator must retry the TPU rung once and prefer its record."""
    monkeypatch.setattr(bench, "_kill_stale_workers", lambda: None)
    monkeypatch.setattr(bench, "_sweep_orphan_shm", lambda: None)
    monkeypatch.setattr(bench, "run_ladder",
                        lambda: {"metric": "m", "value": 50.0,
                                 "backend": "cpu"})
    monkeypatch.setattr(bench, "_prior_value", lambda m: None)
    monkeypatch.setattr(bench, "_remaining", lambda: 1400.0)
    slept = []
    monkeypatch.setattr(bench.time, "sleep", lambda s: slept.append(s))
    monkeypatch.setattr(
        bench, "_run_child",
        lambda cfg, cpu_scrub=False: ({"metric": "m", "value": 20000.0,
                                       "backend": "tpu"}, None))
    monkeypatch.setenv("RAY_TPU_BENCH_TRAIN_ONLY", "1")
    bench.orchestrate()
    lines = [json.loads(l)
             for l in capsys.readouterr().out.strip().splitlines()]
    assert lines[-1]["backend"] == "tpu" and lines[-1]["value"] == 20000.0
    assert slept and slept[0] <= 240


def test_late_tpu_retry_skipped_without_budget(monkeypatch, capsys):
    """1100s remaining is NOT enough: after the 240s wait and the child's
    400s scrub reserve only ~460s of child time remains vs the rung's
    1500s budget — the retry must be skipped, not attempted futilely."""
    monkeypatch.setattr(bench, "_kill_stale_workers", lambda: None)
    monkeypatch.setattr(bench, "_sweep_orphan_shm", lambda: None)
    monkeypatch.setattr(bench, "run_ladder",
                        lambda: {"metric": "m", "value": 50.0,
                                 "backend": "cpu"})
    monkeypatch.setattr(bench, "_prior_value", lambda m: None)
    monkeypatch.setattr(bench, "_remaining", lambda: 1100.0)

    def boom(cfg, cpu_scrub=False):
        raise AssertionError("retry must not run on a thin budget")

    monkeypatch.setattr(bench, "_run_child", boom)
    monkeypatch.setenv("RAY_TPU_BENCH_TRAIN_ONLY", "1")
    bench.orchestrate()
    lines = [json.loads(l)
             for l in capsys.readouterr().out.strip().splitlines()]
    assert lines[-1]["backend"] == "cpu"
