"""Evaluation workers + LR schedules (VERDICT r2 #5; ref:
rllib/algorithms/algorithm.py eval worker set, rllib/core/learner lr_schedule)."""

import time

import numpy as np
import pytest


def _slow_cartpole(sleep_s=0.002):
    import gymnasium as gym

    class SlowCartPole(gym.Wrapper):
        def __init__(self):
            super().__init__(gym.make("CartPole-v1"))

        def step(self, action):
            time.sleep(sleep_s)
            return self.env.step(action)

    return SlowCartPole


def test_lr_schedule_shapes():
    from ray_tpu.ops.optim import make_lr_schedule
    cos = make_lr_schedule(1e-3, {"type": "cosine", "warmup_steps": 10,
                                  "decay_steps": 100})
    assert float(cos(0)) == pytest.approx(0.0, abs=1e-8)
    assert float(cos(10)) == pytest.approx(1e-3, rel=1e-5)
    assert float(cos(100)) == pytest.approx(0.0, abs=1e-6)
    assert float(cos(55)) < 1e-3

    lin = make_lr_schedule(2e-3, {"type": "linear", "warmup_steps": 4,
                                  "decay_steps": 20, "final_lr_scale": 0.1})
    assert float(lin(4)) == pytest.approx(2e-3, rel=1e-5)
    assert float(lin(20)) == pytest.approx(2e-4, rel=1e-4)
    assert float(lin(1000)) == pytest.approx(2e-4, rel=1e-4)

    pw = make_lr_schedule(1.0, [[0, 1.0], [10, 0.5], [20, 0.0]])
    assert float(pw(5)) == pytest.approx(0.75, rel=1e-5)
    assert float(pw(15)) == pytest.approx(0.25, rel=1e-5)


def test_ppo_logs_warmup_cosine_lr(ray_session):
    from ray_tpu.rllib.algorithms.ppo import PPOConfig
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .training(lr=1e-3, train_batch_size=128, minibatch_size=64,
                      num_epochs=1,
                      lr_schedule={"type": "cosine", "warmup_steps": 3,
                                   "decay_steps": 30})
            .env_runners(rollout_fragment_length=64)
            .build())
    lrs = []
    for _ in range(3):
        result = algo.train()
        lrs.append(result["learner"]["cur_lr"])
    # warmup: lr climbs over the first updates
    assert lrs[0] < lrs[-1] <= 1e-3 + 1e-9, lrs


def test_parallel_eval_does_not_block_train(ray_session):
    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    def build(parallel, n_eval):
        return (PPOConfig()
                .environment(_slow_cartpole())
                .training(lr=1e-3, train_batch_size=64, minibatch_size=64,
                          num_epochs=1)
                .env_runners(rollout_fragment_length=32)
                .evaluation(evaluation_interval=1, evaluation_duration=10,
                            evaluation_num_env_runners=n_eval,
                            evaluation_parallel_to_training=parallel)
                .build())

    # inline baseline: evaluation blocks the iteration
    inline = build(parallel=False, n_eval=0)
    inline.train()  # warm up (env creation, jit)
    t0 = time.perf_counter()
    r_inline = inline.train()
    inline_time = time.perf_counter() - t0
    assert "evaluation" in r_inline

    par = build(parallel=True, n_eval=1)
    r1 = par.train()  # launches eval in the dedicated actor
    t0 = time.perf_counter()
    r2 = par.train()
    par_time = time.perf_counter() - t0
    assert "evaluation" not in r1
    # results attach once ready (forced at the next due interval)
    attached = ("evaluation" in r2) or ("evaluation" in par.train())
    assert attached
    # The launching iteration didn't pay the eval wall-time. Inline pays
    # ~10 slow episodes (~200+ env steps) on top of one 64-step rollout, so
    # even a generous factor keeps the assertion meaningful; the slack
    # absorbs CPU contention on 1-core CI boxes (this flaked at 2x in-suite)
    assert par_time < inline_time * 3, (par_time, inline_time)


def test_eval_metrics_from_dedicated_workers(ray_session):
    from ray_tpu.rllib.algorithms.ppo import PPOConfig
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .training(lr=1e-3, train_batch_size=64, minibatch_size=64,
                      num_epochs=1)
            .env_runners(rollout_fragment_length=32)
            .evaluation(evaluation_interval=1, evaluation_duration=4,
                        evaluation_num_env_runners=2)
            .build())
    ev = algo.evaluate()
    assert ev["episodes_this_iter"] >= 4
    assert np.isfinite(ev["episode_return_mean"])


def test_sac_eval_actors_use_module_override(ray_session):
    """Code-review regression: dedicated eval runners must be built with the
    algorithm's runner kwargs (SAC's module override), not generic ones."""
    from ray_tpu.rllib import SACConfig
    algo = (SACConfig()
            .environment("Pendulum-v1")
            .training(train_batch_size=64,
                      num_steps_sampled_before_learning_starts=64)
            .env_runners(rollout_fragment_length=16)
            .evaluation(evaluation_interval=1, evaluation_duration=1,
                        evaluation_num_env_runners=1)
            .debugging(seed=3)
            .build())
    ev = algo.evaluate()  # crashes without the module override
    assert ev["episodes_this_iter"] >= 1
