"""Cluster health signal plane (ISSUE 11): object-lifetime ledger, leak
detector, alert log, health gauges, quantile summaries, and the tracing
drop counter. Threshold/age logic is tested with a fake clock — no sleeps.
"""

import os

import pytest

from ray_tpu._private import health
from ray_tpu._private.task_spec import ObjectMeta


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


# ---------------------------------------------------------------- ledger
def test_ledger_ages_full_lifecycle():
    meta = ObjectMeta(object_id="obj-1", ts_created=100.0, ts_sealed=102.5,
                      ts_pinned=103.0, ts_released=110.0, pinned=1)
    ages = health.ledger_ages(meta, now=120.0)
    assert ages["age_s"] == 20.0
    assert ages["seal_latency_s"] == 2.5
    assert ages["sealed_age_s"] == 17.5
    assert ages["pinned_age_s"] == 17.0
    assert ages["released_age_s"] == 10.0


def test_ledger_ages_partial():
    """Unsealed / unpinned objects only report what actually happened."""
    meta = ObjectMeta(object_id="obj-2", ts_created=100.0)
    ages = health.ledger_ages(meta, now=101.0)
    assert ages == {"age_s": 1.0}
    # pinned age only reported while actually pinned (ts stamp alone is
    # not enough — _unpin clears it when the count returns to 0)
    meta.ts_pinned = 100.5
    assert "pinned_age_s" not in health.ledger_ages(meta, now=101.0)
    meta.pinned = 2
    assert health.ledger_ages(meta, now=101.0)["pinned_age_s"] == 0.5


# ---------------------------------------------------------- leak detector
def _objects(clock):
    t = clock()
    return {
        # pinned far past the age threshold → "pinned" leak
        "leak-pinned": ObjectMeta(
            object_id="leak-pinned", size=64, location="shm", refcount=1,
            pinned=2, creating_task="task-aaaa", ts_created=t - 100,
            ts_sealed=t - 99, ts_pinned=t - 50),
        # sealed long ago, refcount still held → "unreleased" leak
        "leak-unreleased": ObjectMeta(
            object_id="leak-unreleased", size=32, location="shm", refcount=1,
            pinned=0, creating_task="task-bbbb", ts_created=t - 40,
            ts_sealed=t - 39),
        # young object: not flagged
        "fresh": ObjectMeta(
            object_id="fresh", size=8, location="shm", refcount=1,
            creating_task="task-cccc", ts_created=t - 1, ts_sealed=t - 1),
        # error tombstone: never flagged regardless of age
        "errored": ObjectMeta(
            object_id="errored", size=0, location="error", refcount=1,
            ts_created=t - 500),
    }


def test_leak_detector_flags_with_owner_and_trace():
    clock = FakeClock()
    det = health.LeakDetector(age_s=10.0, clock=clock)
    leaks = {l["object_id"]: l for l in det.scan(_objects(clock))}
    assert set(leaks) == {"leak-pinned", "leak-unreleased"}
    p = leaks["leak-pinned"]
    assert p["reason"] == "pinned"
    assert p["owner_task"] == "task-aaaa"
    # default sampling derives the trace id from the task id itself
    from ray_tpu.util import tracing
    assert p["trace_id"] == tracing.trace_id_for("task-aaaa")
    assert p["ledger"]["pinned_age_s"] == 50.0
    u = leaks["leak-unreleased"]
    assert u["reason"] == "unreleased"
    assert u["owner_task"] == "task-bbbb"
    assert u["ledger"]["age_s"] == 40.0


def test_leak_detector_age_threshold_is_sharp():
    clock = FakeClock()
    det = health.LeakDetector(age_s=150.0, clock=clock)
    objs = _objects(clock)
    assert det.scan(objs) == []           # nothing older than 150s yet
    clock.advance(60.0)                   # leak-pinned created 160s ago now
    leaks = det.scan(objs)
    assert [l["object_id"] for l in leaks] == ["leak-pinned"]
    # the pinned rule (ts_pinned 110s ago) hasn't tripped — the age rule did
    assert leaks[0]["reason"] == "unreleased"
    clock.advance(60.0)                   # pinned-since now 170s ago
    leaks = {l["object_id"]: l for l in det.scan(objs)}
    assert leaks["leak-pinned"]["reason"] == "pinned"
    assert leaks["leak-unreleased"]["reason"] == "unreleased"


def test_leak_detector_env_knob(monkeypatch):
    clock = FakeClock()
    monkeypatch.setenv("RAY_TPU_LEAK_AGE_S", "20")
    det = health.LeakDetector(clock=clock)   # age from env, read per scan
    leaks = det.scan(_objects(clock))
    assert {l["object_id"] for l in leaks} == {"leak-pinned",
                                               "leak-unreleased"}
    monkeypatch.setenv("RAY_TPU_LEAK_AGE_S", "1000")
    assert det.scan(_objects(clock)) == []


# -------------------------------------------------------------- alert log
def test_alert_log_dedup_and_resolve():
    clock = FakeClock()
    log = health.AlertLog(maxlen=8, clock=clock)
    ev = log.fire("store_pressure", "node-1", "store 95% full", used=95)
    assert ev is not None and ev["data"]["used"] == 95
    # same (kind, key) while active → deduped, no second event
    assert log.fire("store_pressure", "node-1", "still full") is None
    assert log.active_count() == 1
    assert len(log.events()) == 1
    # a different key is its own alert
    assert log.fire("store_pressure", "node-2", "also full") is not None
    # resolve re-arms: the recurrence is a fresh event
    log.resolve("store_pressure", "node-1")
    clock.advance(5.0)
    ev2 = log.fire("store_pressure", "node-1", "full again")
    assert ev2 is not None and ev2["ts"] == clock()
    kinds = [(e["kind"], e["key"]) for e in log.events()]
    assert kinds == [("store_pressure", "node-1"),
                     ("store_pressure", "node-2"),
                     ("store_pressure", "node-1")]


def test_alert_log_bounded():
    log = health.AlertLog(maxlen=4, clock=FakeClock())
    for i in range(10):
        log.fire("k", f"key-{i}", f"m{i}")
    evs = log.events()
    assert len(evs) == 4
    assert [e["key"] for e in evs] == ["key-6", "key-7", "key-8", "key-9"]
    assert log.events(limit=2)[-1]["key"] == "key-9"


# ------------------------------------------------------------ queue rule
class _StubController:
    """Just enough controller for HealthMonitor.tick()."""

    def __init__(self):
        self.node_id = "head"
        self.cluster = None
        self.objects = {}

    def health_snapshot(self):
        return dict(self._snap)


def test_queue_growth_rule(monkeypatch):
    monkeypatch.setenv("RAY_TPU_ALERT_QUEUE_INTERVALS", "3")
    clock = FakeClock()
    c = _StubController()
    mon = health.HealthMonitor(c, clock=clock)
    for depth in (1, 2, 3):             # 3 samples = 2 increases: not yet
        c._snap = {"ts": clock(), "queue_depth": depth,
                   "store_used": 0, "store_capacity": 100}
        mon.tick()
    assert mon.alerts.active_count() == 0
    c._snap = {"ts": clock(), "queue_depth": 4,
               "store_used": 0, "store_capacity": 100}
    mon.tick()                          # 4 samples, strictly increasing
    assert ("queue_growth", "head") in mon.alerts.active_keys()
    c._snap = {"ts": clock(), "queue_depth": 0,
               "store_used": 0, "store_capacity": 100}
    mon.tick()                          # growth broken → resolved
    assert mon.alerts.active_count() == 0


def test_store_pressure_rule(monkeypatch):
    monkeypatch.setenv("RAY_TPU_ALERT_STORE_PCT", "90")
    clock = FakeClock()
    c = _StubController()
    mon = health.HealthMonitor(c, clock=clock)
    c._snap = {"ts": clock(), "queue_depth": 0,
               "store_used": 95, "store_capacity": 100}
    mon.tick()
    assert ("store_pressure", "head") in mon.alerts.active_keys()
    ev = mon.alerts.events()[-1]
    assert ev["severity"] == "warning" and ev["data"]["used"] == 95
    c._snap = {"ts": clock(), "queue_depth": 0,
               "store_used": 10, "store_capacity": 100}
    mon.tick()
    assert mon.alerts.active_count() == 0


def test_monitor_leak_rule_and_node_death(monkeypatch):
    monkeypatch.setenv("RAY_TPU_LEAK_AGE_S", "10")
    monkeypatch.setenv("RAY_TPU_LEAK_SCAN_S", "5")
    clock = FakeClock()
    c = _StubController()
    c._snap = {"ts": clock(), "queue_depth": 0,
               "store_used": 0, "store_capacity": 100}
    c.objects = _objects(clock)
    mon = health.HealthMonitor(c, clock=clock)
    clock.advance(6.0)                  # past the scan interval
    mon.tick()
    assert {l["object_id"] for l in mon.leaks} == {"leak-pinned",
                                                   "leak-unreleased"}
    keys = mon.alerts.active_keys()
    assert ("object_leak", "leak-pinned") in keys
    ev = next(e for e in mon.alerts.events()
              if e["key"] == "leak-pinned")
    assert ev["data"]["owner_task"] == "task-aaaa"
    assert ev["data"]["trace_id"]
    # the leaked objects get released → next scan resolves their alerts
    # (the "fresh" object's created-ts moves with the clock so it doesn't
    # age across the threshold mid-test)
    del c.objects["leak-pinned"], c.objects["leak-unreleased"]
    clock.advance(6.0)
    c.objects["fresh"].ts_created = clock()
    c.objects["fresh"].ts_sealed = clock()
    mon.tick()
    assert not any(k == "object_leak" for k, _ in mon.alerts.active_keys())

    # node death path: tombstone + critical alert, cleared on rejoin
    mon.note_node_dead("node-x", host="h1")
    assert mon.dead_nodes["node-x"]["alive"] is False
    assert ("node_dead", "node-x") in mon.alerts.active_keys()
    assert any(e["kind"] == "node_dead" and e["severity"] == "critical"
               for e in mon.alerts.events())
    mon.note_node_alive("node-x")
    assert "node-x" not in mon.dead_nodes
    assert ("node_dead", "node-x") not in mon.alerts.active_keys()


def test_monitor_disabled_by_env(monkeypatch):
    monkeypatch.setenv("RAY_TPU_HEALTH", "0")
    clock = FakeClock()
    c = _StubController()
    c._snap = {"ts": clock(), "queue_depth": 0,
               "store_used": 100, "store_capacity": 100}
    mon = health.HealthMonitor(c, clock=clock)
    mon.tick()
    assert mon.alerts.events() == []


# ------------------------------------------------------ histogram summary
def test_histogram_summary_quantiles():
    from ray_tpu.util import metrics
    name = "rt_test_summary_hist"
    h = metrics.get_or_create(metrics.Histogram, name, "t",
                              boundaries=[1.0, 2.0, 4.0],
                              tag_keys=("engine",))
    try:
        for v in (0.5, 1.5, 1.6, 3.0):
            h.observe(v, tags={"engine": "a"})
        for v in (3.5, 100.0):
            h.observe(v, tags={"engine": "b"})  # tag series merge
        s = metrics.histogram_summary(name)
        assert s["count"] == 6
        assert s["sum"] == pytest.approx(110.1)
        assert s["mean"] == pytest.approx(110.1 / 6)
        # p50: rank 3 of [1, 2, 2, 1] buckets → inside (1, 2]
        assert 1.0 <= s["p50"] <= 2.0
        # p99 lands in the overflow bucket → clamped to the top bound
        assert s["p99"] == 4.0
        assert metrics.histogram_summary("rt_never_registered") is None
    finally:
        with metrics._registry_lock:
            metrics._registry.pop(name, None)


# ------------------------------------------------- in-process store gauges
def test_head_health_snapshot_and_state_kinds(ray_session):
    """state('cluster_health') / state('alerts') flow through the same
    snapshot path as every other kind; the head row carries live store
    gauges and objects rows carry the ledger."""
    ray = ray_session
    ref = ray.put(b"y" * 4096)
    try:
        from ray_tpu.util import state as state_api
        health_view = state_api.cluster_health()
        head = health_view["nodes"][0]
        assert head["is_head"] and head["node_id"]
        assert head["store_objects"] >= 1
        assert head["store_capacity"] > 0
        assert 0 <= head["worker_occupancy"] <= 1.0
        assert isinstance(state_api.list_alerts(), list)
        rows = {o["object_id"]: o for o in state_api.list_objects(limit=10000)}
        row = rows[ref.id]
        assert row["age_s"] >= 0.0
        assert "sealed_age_s" in row            # ray.put seals immediately
    finally:
        del ref


def test_store_alloc_failure_counter(monkeypatch):
    """A failing shm allocation bumps the module counter (and the metric)
    instead of passing silently."""
    from multiprocessing import shared_memory

    from ray_tpu._private import object_store

    class _Boom:
        def __init__(self, *a, **k):
            raise OSError("no shm")

    before = object_store.alloc_failures()
    store = object_store.StoreClient.__new__(object_store.StoreClient)
    store._slab = None
    monkeypatch.setattr(shared_memory, "SharedMemory", _Boom)
    monkeypatch.setattr(object_store, "shared_memory", shared_memory,
                        raising=False)
    with pytest.raises(OSError):
        store._new_segment("obj-fail-test", 128)
    assert object_store.alloc_failures() == before + 1


# ----------------------------------------------------- tracing drop stat
def test_tracing_spans_dropped_counter(monkeypatch):
    from ray_tpu.util import metrics, tracing
    monkeypatch.setenv("RAY_TPU_TRACE", "1")
    monkeypatch.setenv("RAY_TPU_TRACE_BUFFER", "16")
    tracing.refresh()
    tracing.clear()

    def total():
        with metrics._registry_lock:
            m = metrics._registry.get("tracing_spans_dropped")
        return sum(m.snapshot()["values"].values()) if m else 0.0

    t0 = total()
    for i in range(16):
        tracing.record_span(f"s{i}", "t", None, i, None, 0.0, 0.0)
    assert tracing.summary()["dropped"] == 0
    assert total() == t0
    for i in range(5):
        tracing.record_span(f"over{i}", "t", None, i, None, 0.0, 0.0)
    assert tracing.summary()["dropped"] == 5
    assert total() == t0 + 5
    tracing.clear()
    assert tracing.summary()["dropped"] == 0
