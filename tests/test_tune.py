"""Tune tests (SURVEY.md §4): search-space sampling, ASHA pruning math,
end-to-end Tuner runs with trials as actors."""

import numpy as np
import pytest

from ray_tpu import tune
from ray_tpu.tune.schedulers import CONTINUE, STOP, PBTDecision


# ------------------------------------------------------------- search spaces
def test_search_space_sampling():
    rng = np.random.default_rng(0)
    assert tune.choice([1, 2, 3]).sample(rng) in (1, 2, 3)
    v = tune.uniform(0.0, 1.0).sample(rng)
    assert 0.0 <= v <= 1.0
    lv = tune.loguniform(1e-4, 1e-1).sample(rng)
    assert 1e-4 <= lv <= 1e-1
    ri = tune.randint(5, 10).sample(rng)
    assert 5 <= ri < 10
    q = tune.qrandint(0, 100, 10).sample(rng)
    assert q % 10 == 0
    fn = tune.sample_from(lambda: 42)
    assert fn.sample(rng) == 42


def test_basic_variant_grid_cross_product():
    space = {"a": tune.grid_search([1, 2, 3]),
             "b": tune.grid_search(["x", "y"]),
             "c": tune.uniform(0, 1),
             "d": "const"}
    gen = tune.BasicVariantGenerator(space, num_samples=2)
    assert gen.total_trials == 3 * 2 * 2
    seen = set()
    for i in range(gen.total_trials):
        cfg = gen.suggest(f"t{i}")
        seen.add((cfg["a"], cfg["b"]))
        assert 0 <= cfg["c"] <= 1 and cfg["d"] == "const"
    assert gen.suggest("extra") is None
    assert seen == {(a, b) for a in (1, 2, 3) for b in ("x", "y")}


def test_concurrency_limiter():
    gen = tune.BasicVariantGenerator({"x": tune.uniform(0, 1)}, num_samples=5)
    lim = tune.ConcurrencyLimiter(gen, max_concurrent=2)
    assert lim.suggest("t1") is not None
    assert lim.suggest("t2") is not None
    assert lim.suggest("t3") is None  # at cap
    lim.on_trial_complete("t1")
    assert lim.suggest("t3") is not None


# ----------------------------------------------------------------- schedulers
def test_asha_pruning_math():
    sched = tune.ASHAScheduler(max_t=16, grace_period=1, reduction_factor=4,
                               metric="score", mode="max")
    # 8 trials report at rung t=1 with DESCENDING scores 7..0: the first
    # sets the cutoff, everyone below it gets culled (async halving)
    decisions = {}
    for i in range(8):
        decisions[i] = sched.on_result(f"t{i}", {"training_iteration": 1,
                                                 "score": float(7 - i)})
    assert decisions[0] == CONTINUE  # best, sets the bar
    assert decisions[3] == STOP      # below the top-1/4 cutoff
    assert decisions[7] == STOP
    # horizon reached → stop regardless
    assert sched.on_result("t7", {"training_iteration": 16,
                                  "score": 100.0}) == STOP


def test_median_stopping():
    sched = tune.MedianStoppingRule(grace_period=2, min_samples_required=3)
    sched.set_properties("score", "max")
    for t in (1, 2, 3):
        assert sched.on_result("good", {"training_iteration": t,
                                        "score": 10.0}) == CONTINUE
        sched.on_result("mid", {"training_iteration": t, "score": 5.0})
        bad = sched.on_result("bad", {"training_iteration": t, "score": 1.0})
    assert bad == STOP


def test_pbt_exploit_decision():
    sched = tune.PopulationBasedTraining(
        perturbation_interval=2,
        hyperparam_mutations={"lr": [0.1, 0.01]}, seed=0)
    sched.set_properties("score", "max")
    sched.register("winner", {"lr": 0.1})
    sched.register("loser", {"lr": 0.0001})
    sched.on_result("winner", {"training_iteration": 2, "score": 10.0})
    d = sched.on_result("loser", {"training_iteration": 2, "score": 0.1})
    assert isinstance(d, PBTDecision)
    assert d.source_trial == "winner"
    assert d.new_config["lr"] in (0.1, 0.01)


# ------------------------------------------------------------------- e2e runs
def test_tuner_end_to_end(ray_session, tmp_path):
    from ray_tpu.train import RunConfig

    def quadratic(config):
        # nested def: cloudpickle ships it by value into trial actors
        for i in range(8):
            score = -(config["x"] - 3.0) ** 2 - 0.1 * i
            tune.report({"score": score, "step": i})

    tuner = tune.Tuner(
        quadratic,
        param_space={"x": tune.grid_search([0.0, 1.5, 3.0, 5.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    max_concurrent_trials=2),
        run_config=RunConfig(name="grid", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid) == 4
    assert not grid.errors
    best = grid.get_best_result()
    assert best.config["x"] == 3.0
    df = grid.get_dataframe()
    assert "config/x" in df.columns and len(df) == 4


def test_tuner_with_asha_culls(ray_session, tmp_path):
    from ray_tpu.train import RunConfig

    def slow_trainable(config):
        import time
        for i in range(1, 13):
            time.sleep(0.05)  # slow enough for the driver to act mid-trial
            tune.report({"score": config["x"], "training_iteration": i})

    # sequential trials (max_concurrent=1) make the cull deterministic: the
    # good trial populates the rungs first, so the bad one hits a cutoff
    tuner = tune.Tuner(
        slow_trainable,
        param_space={"x": tune.grid_search([4.0, 1.0])},
        tune_config=tune.TuneConfig(
            metric="score", mode="max", max_concurrent_trials=1,
            scheduler=tune.ASHAScheduler(max_t=12, grace_period=2,
                                         reduction_factor=2)),
        run_config=RunConfig(name="asha", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid) == 2
    best = grid.get_best_result()
    assert best.config["x"] == 4.0
    # the weak trial must be stopped early by the scheduler
    iters = {r.config["x"]: len(r.metrics_history) for r in grid}
    assert iters[4.0] == 12
    assert iters[1.0] < 12, f"nothing culled: {iters}"


def test_tuner_checkpoints_and_errors(ray_session, tmp_path):
    from ray_tpu.train import Checkpoint, RunConfig

    def ckpt_trainable(config):
        if config["x"] == 99:
            raise RuntimeError("doomed trial")
        for i in range(3):
            tune.report({"score": i},
                        checkpoint=Checkpoint.from_state({"i": i}))

    tuner = tune.Tuner(
        ckpt_trainable,
        param_space={"x": tune.grid_search([1, 99])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(name="ck", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid.errors) == 1
    assert "doomed" in grid.errors[0].error
    ok = [r for r in grid if not r.error][0]
    assert ok.checkpoint is not None
    assert ok.checkpoint.to_state()["i"] == 2


def test_tuner_stop_criteria(ray_session, tmp_path):
    from ray_tpu.train import RunConfig

    def forever(config):
        import time
        i = 0
        while True:
            i += 1
            time.sleep(0.01)  # pace reports so the stop lands promptly
            tune.report({"iters": i, "training_iteration": i})

    tuner = tune.Tuner(
        forever,
        param_space={},
        tune_config=tune.TuneConfig(metric="iters", mode="max"),
        run_config=RunConfig(name="stop", storage_path=str(tmp_path),
                             stop={"training_iteration": 5}),
    )
    grid = tuner.fit()
    assert not grid.errors
    assert grid[0].metrics["training_iteration"] >= 5
    assert grid[0].metrics["training_iteration"] < 500  # actually stopped


def test_tuner_restore_resumes_sweep(tmp_path):
    """Kill a sweep mid-flight; Tuner.restore keeps finished trials and
    re-runs the rest (VERDICT r3 missing #5; ref: tune Tuner.restore)."""
    import json
    import os
    import signal
    import subprocess
    import sys
    import textwrap
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    exp = tmp_path / "exp"

    script = textwrap.dedent(f"""
        import time
        import ray_tpu as ray
        from ray_tpu.tune import Tuner, TuneConfig
        from ray_tpu.train import RunConfig
        from ray_tpu import tune as _  # noqa

        ray.init(num_cpus=2)

        def trainable(config):
            from ray_tpu.train import session
            for i in range(3):
                time.sleep(config["delay"])
                session.report({{"loss": config["x"] * 10 + i}})

        tuner = Tuner(
            trainable,
            param_space={{"x": {{"grid_search": [0, 1, 2, 3, 4, 5]}},
                         "delay": 0.05}},
            tune_config=TuneConfig(metric="loss", mode="min", num_samples=1,
                                   max_concurrent_trials=1),
            run_config=RunConfig(name="exp", storage_path={str(tmp_path)!r}))
        tuner.fit()
    """)
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("RAY_TPU_ARENA", None)
    env.pop("RAY_TPU_ADDRESS", None)
    proc = subprocess.Popen([sys.executable, "-c", script], env=env,
                            stdin=subprocess.DEVNULL,
                            start_new_session=True)
    # wait until >=2 trials finished, then kill the whole sweep mid-flight
    state_path = exp / "tuner.json"
    deadline = time.time() + 180
    while time.time() < deadline:
        if state_path.exists():
            st = json.loads(state_path.read_text())
            if sum(1 for t in st["trials"]
                   if t["state"] == "TERMINATED") >= 2:
                break
        time.sleep(0.2)
    else:
        raise TimeoutError("sweep never reached 2 finished trials")
    os.killpg(proc.pid, signal.SIGKILL)
    proc.wait(timeout=15)

    st = json.loads(state_path.read_text())
    finished_before = {t["trial_id"]: t["results"] for t in st["trials"]
                       if t["state"] == "TERMINATED"}
    assert len(finished_before) >= 2

    # restore IN-PROCESS and finish the sweep
    import ray_tpu as ray  # noqa: F401 - session from the suite fixture
    from ray_tpu.train import RunConfig
    from ray_tpu.tune import TuneConfig, Tuner

    def trainable(config):
        from ray_tpu.train import session
        for i in range(3):
            session.report({"loss": config["x"] * 10 + i})

    tuner = Tuner.restore(
        str(exp), trainable,
        param_space={"x": {"grid_search": [0, 1, 2, 3, 4, 5]}, "delay": 0.05},
        tune_config=TuneConfig(metric="loss", mode="min", num_samples=1,
                               max_concurrent_trials=2),
        run_config=RunConfig(name="exp", storage_path=str(tmp_path)))
    grid = tuner.fit()

    # all 6 grid points present, finished trials kept verbatim
    by_id = {r.trial_id: r for r in grid}
    assert len(by_id) == 6, sorted(by_id)
    xs = sorted(r.config["x"] for r in grid)
    assert xs == [0, 1, 2, 3, 4, 5]
    for tid, results in finished_before.items():
        assert by_id[tid].metrics_history == results, tid
    assert not grid.errors
    assert grid.get_best_result().config["x"] == 0


def test_with_parameters_injects_object_store_refs(ray_session):
    """with_parameters (ref: tune/trainable/util.py): large constants ride
    the object store once and reach every trial as kwargs."""
    import numpy as np

    from ray_tpu import tune

    big = np.arange(1000)

    def trainable(config, data=None):
        tune.report({"loss": float(config["x"] + data.sum())})

    wrapped = tune.with_parameters(trainable, data=big)
    results = tune.Tuner(
        wrapped, param_space={"x": tune.choice([0, 1])},
        tune_config=tune.TuneConfig(num_samples=2)).fit()
    assert len(results) == 2
    want = big.sum()
    assert all(r.metrics["loss"] in (want, want + 1) for r in results)
