"""Mixture-of-experts model family (models/moe.py): routing math, capacity
drops, decode consistency, expert-parallel sharding over the `ep` mesh axis.
Reference contrast: the reference serves Mixtral-family checkpoints through
vLLM/SGLang CUDA scatter-gather; ours is GShard dense-dispatch for the MXU."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from ray_tpu.models import (KVCache, Llama, LlamaConfig, llama_param_count,
                            moe_aux_loss)
from ray_tpu.models.moe import MoEMLP
from ray_tpu.parallel.mesh import local_cpu_mesh
from ray_tpu.parallel.sharding import llama_rules, tree_paths


@pytest.fixture(scope="module")
def moe_tiny():
    cfg = LlamaConfig.moe_tiny(dtype=jnp.float32, param_dtype=jnp.float32,
                               attn_impl="xla")
    model = Llama(cfg)
    tokens = jnp.array(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 16)))
    params = model.init(jax.random.PRNGKey(0), tokens)
    return cfg, model, params, tokens


class TestMoELlama:
    def test_forward_shape_and_finite(self, moe_tiny):
        cfg, model, params, tokens = moe_tiny
        logits, cache = model.apply(params, tokens)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert np.isfinite(np.asarray(logits)).all()
        assert cache is None

    def test_param_count_formula(self, moe_tiny):
        cfg, model, params, _ = moe_tiny
        # count the "params" collection only — init also returns the sowed
        # "losses" scalars (one moe_aux per MoE layer)
        actual = sum(x.size
                     for x in jax.tree_util.tree_leaves(params["params"]))
        assert actual == llama_param_count(cfg)

    def test_moe_params_present(self, moe_tiny):
        _, _, params, _ = moe_tiny
        layer0 = params["params"]["layers_0"]
        assert "moe" in layer0 and "mlp" not in layer0
        assert layer0["moe"]["w_gate"].shape[0] == 4  # [E, d, ffn]

    def test_aux_loss_sowed(self, moe_tiny):
        cfg, model, params, tokens = moe_tiny
        (_logits, _cache), variables = model.apply(
            params, tokens, mutable=["losses"])
        aux = moe_aux_loss(variables["losses"], cfg.router_aux_weight)
        # Switch aux loss is >= 1 at balance (E * sum f_e * P_e), scaled
        assert float(aux) > 0
        # gradient of aux loss flows into the router
        def loss_fn(p):
            (_l, _c), v = model.apply(p, tokens, mutable=["losses"])
            return moe_aux_loss(v["losses"], 1.0)
        grads = jax.grad(loss_fn)(params)
        router_g = grads["params"]["layers_0"]["moe"]["router"]["kernel"]
        assert float(jnp.abs(router_g).sum()) > 0

    def test_decode_matches_prefill(self):
        """With generous capacity (no token drops in either mode), decode
        through the KV cache reproduces prefill logits — routing is
        per-token, so batching differences must not change outputs."""
        cfg = LlamaConfig.moe_tiny(dtype=jnp.float32,
                                   param_dtype=jnp.float32,
                                   attn_impl="xla", capacity_factor=8.0)
        model = Llama(cfg)
        tokens = jnp.array(
            np.random.RandomState(1).randint(0, cfg.vocab_size, (2, 12)))
        params = model.init(jax.random.PRNGKey(0), tokens)
        prefill_logits, _ = model.apply(params, tokens)
        cache = KVCache.init(cfg, batch=2, max_len=32, dtype=jnp.float32)
        steps = []
        for t in range(tokens.shape[1]):
            logits, cache = model.apply(params, tokens[:, t:t + 1],
                                        cache=cache)
            steps.append(logits[:, 0])
        np.testing.assert_allclose(jnp.stack(steps, 1), prefill_logits,
                                   atol=1e-4)

    def test_moe_every_interleaves(self):
        cfg = LlamaConfig.moe_tiny(n_layers=4, moe_every=2,
                                   dtype=jnp.float32,
                                   param_dtype=jnp.float32, attn_impl="xla")
        model = Llama(cfg)
        tokens = jnp.zeros((1, 4), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        assert "moe" in params["layers_0"] and "moe" in params["layers_2"]
        assert "mlp" in params["layers_1"] and "mlp" in params["layers_3"]


class TestMoEMLP:
    def _mk(self, E=4, K=2, cf=8.0, D=16, F=32, S=8):
        cfg = LlamaConfig.moe_tiny(d_model=D, ffn_dim=F, n_experts=E,
                                   moe_top_k=K, capacity_factor=cf,
                                   dtype=jnp.float32,
                                   param_dtype=jnp.float32)
        m = MoEMLP(cfg)
        x = jax.random.normal(jax.random.PRNGKey(2), (1, S, D))
        params = m.init(jax.random.PRNGKey(3), x)
        return m, params, x

    def test_single_expert_equals_dense_swiglu(self):
        """E=1, K=1, ample capacity: the bank must compute exactly
        silu(x·Wg) * (x·Wu) · Wd — validates dispatch/combine plumbing."""
        m, params, x = self._mk(E=1, K=1, cf=4.0)
        y = m.apply(params, x)
        p = params["params"]
        wg, wu, wd = (p["w_gate"][0], p["w_up"][0], p["w_down"][0])
        xf = x[0]
        expected = (jax.nn.silu(xf @ wg) * (xf @ wu)) @ wd
        np.testing.assert_allclose(np.asarray(y[0]), np.asarray(expected),
                                   atol=1e-5)

    def test_permutation_equivariance(self):
        """Tokens are routed independently: permuting the sequence permutes
        the output (ample capacity so priority order can't drop anyone)."""
        m, params, x = self._mk()
        perm = np.random.RandomState(4).permutation(x.shape[1])
        y = m.apply(params, x)
        y_perm = m.apply(params, x[:, perm])
        np.testing.assert_allclose(np.asarray(y[:, perm]),
                                   np.asarray(y_perm), atol=1e-5)

    def test_capacity_drops_zero_output(self):
        """Over-capacity tokens contribute zero (the Block residual carries
        them): with capacity_factor → 0, C=1 per expert, so at most E*1
        slots exist for S*K assignments and some outputs must be zero."""
        m, params, x = self._mk(E=2, K=1, cf=1e-9, S=8)
        y = np.asarray(m.apply(params, x))[0]
        row_norms = np.abs(y).sum(-1)
        assert (row_norms == 0).sum() >= 6  # 8 tokens, <= 2 slots survive
        assert (row_norms > 0).sum() >= 1

    def test_ep_sharded_apply_matches(self):
        """Experts sharded over an ep×tp mesh produce identical outputs —
        the expert-parallel path XLA compiles to all-to-alls."""
        cfg = LlamaConfig.moe_tiny(dtype=jnp.float32,
                                   param_dtype=jnp.float32,
                                   attn_impl="xla")
        model = Llama(cfg)
        tokens = jnp.array(
            np.random.RandomState(5).randint(0, cfg.vocab_size, (2, 16)))
        params = model.init(jax.random.PRNGKey(0), tokens)
        mesh = local_cpu_mesh(8, {"ep": 4, "tp": 2})
        shardings = llama_rules().tree_shardings(params, mesh)
        sharded = jax.device_put(params, shardings)
        ref, _ = model.apply(params, tokens)
        out, _ = jax.jit(lambda p, t: model.apply(p, t))(sharded, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-4)

    def test_expert_banks_get_ep_specs(self):
        cfg = LlamaConfig.moe_tiny(dtype=jnp.float32,
                                   param_dtype=jnp.float32)
        model = Llama(cfg)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 4), jnp.int32))
        rules = llama_rules()
        seen_ep = 0
        for path, leaf in tree_paths(params):
            spec = rules.spec_for(path, leaf)
            if "/moe/w_" in path:
                # PartitionSpec normalizes 1-tuples to the bare axis name
                assert tuple(spec)[0] in ("ep", ("ep",)), (path, spec)
                seen_ep += 1
            if leaf.ndim >= 2 and "router" not in path:
                assert any(ax is not None for ax in tuple(spec)), path
        assert seen_ep >= 6  # 2 layers x 3 banks


def test_moe_preset_serves():
    """A MoE checkpoint serves through the continuous-batching engine
    unchanged — preset wiring + decode path (the reference serves Mixtral
    through its vLLM/SGLang engines)."""
    import asyncio

    from ray_tpu.serve.llm import LLMConfig as ServeConfig, LLMServer

    srv = LLMServer(ServeConfig(preset="moe_tiny", max_batch_slots=2,
                                max_seq_len=64))

    async def run():
        out = await srv.generate([3, 1, 4, 1, 5], max_tokens=4)
        assert len(out["tokens"]) == 4
        assert all(0 <= t < 256 for t in out["tokens"])

    asyncio.run(run())


def test_serving_forces_dropless_capacity():
    """The engine must bump capacity_factor to E/K (dropless): a token's
    output may not depend on which other requests share the decode batch."""
    from ray_tpu.serve.llm import LLMConfig as ServeConfig, LLMServer

    srv = LLMServer(ServeConfig(preset="moe_tiny", max_batch_slots=2,
                                max_seq_len=64))
    mc = srv.model_cfg
    assert mc.capacity_factor >= mc.n_experts / mc.moe_top_k
