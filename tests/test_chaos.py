"""Fault-injection plane unit tests: the chaos injector's determinism
contract (same seed + same call order => same fault schedule), knob
parsing, runtime reconfiguration, and the retry-backoff/deadline helpers
the transfer plane retries with. No cluster needed — the injector is
process-local by design (ref: python/ray/tests/test_chaos_cluster*)."""

import numpy as np
import pytest

from ray_tpu._private.chaos import ChaosInjector, enabled


def _armed(monkeypatch):
    monkeypatch.setenv("RAY_TPU_CHAOS", "1")


def test_deterministic_draw_sequence(monkeypatch):
    """Two injectors with the same seed and config make the identical
    decision sequence — the replay anchor for failing chaos runs."""
    _armed(monkeypatch)
    cfg = {"sever_stream": 0.3, "drop_segment": 0.5}
    a = ChaosInjector(seed=42, config=cfg)
    b = ChaosInjector(seed=42, config=cfg)
    schedule = [("sever_stream" if i % 2 else "drop_segment") for i in range(40)]
    assert [a.should(p) for p in schedule] == [b.should(p) for p in schedule]
    assert a.draws == b.draws == 40
    assert a.fired == b.fired
    assert sum(a.fired.values()) > 0  # with p=0.3/0.5 over 40 draws

    # a DIFFERENT seed gives a different schedule (overwhelmingly)
    c = ChaosInjector(seed=43, config=cfg)
    assert [c.should(p) for p in schedule] != [a.should(p) for p in schedule] \
        or c.fired != a.fired


def test_unarmed_injector_is_inert(monkeypatch):
    monkeypatch.setenv("RAY_TPU_CHAOS", "0")
    inj = ChaosInjector(seed=1, config={"sever_stream": 1.0,
                                        "heartbeat_drop": 1.0,
                                        "heartbeat_delay_s": 5.0})
    assert not enabled()
    assert inj.should("sever_stream") is False
    assert inj.heartbeat_fault() == (False, 0.0)
    assert inj.draws == 0  # unarmed paths never consume the PRNG


def test_env_knob_parsing(monkeypatch):
    _armed(monkeypatch)
    monkeypatch.setenv("RAY_TPU_CHAOS_SEED", "7")
    monkeypatch.setenv("RAY_TPU_CHAOS_HEARTBEAT_DROP", "0.25")
    monkeypatch.setenv("RAY_TPU_CHAOS_HEARTBEAT_DELAY_S", "1.5")
    monkeypatch.setenv("RAY_TPU_CHAOS_SEVER_STREAM", "bogus")  # -> default 0
    inj = ChaosInjector()
    assert inj.armed and inj.seed == 7
    assert inj.config["heartbeat_drop"] == 0.25
    assert inj.config["heartbeat_delay_s"] == 1.5
    assert inj.config["sever_stream"] == 0.0


def test_heartbeat_fault_drop_and_delay(monkeypatch):
    _armed(monkeypatch)
    inj = ChaosInjector(seed=0, config={"heartbeat_drop": 1.0})
    assert inj.heartbeat_fault() == (True, 0.0)
    inj2 = ChaosInjector(seed=0, config={"heartbeat_delay_s": 0.75})
    assert inj2.heartbeat_fault() == (False, 0.75)
    assert inj2.fired["heartbeat_delay"] == 1


def test_configure_reseeds_and_snapshot(monkeypatch):
    _armed(monkeypatch)
    inj = ChaosInjector(seed=5, config={"drop_segment": 0.5})
    first = [inj.should("drop_segment") for _ in range(20)]
    snap = inj.configure(seed=5)  # re-seed -> replay the exact schedule
    assert snap["draws"] == 0
    assert [inj.should("drop_segment") for _ in range(20)] == first

    snap = inj.configure(armed=False, sever_stream=0.9)
    assert snap["armed"] is False
    assert snap["config"]["sever_stream"] == 0.9
    assert inj.should("sever_stream") is False  # disarmed at runtime


def test_drop_object_against_store(monkeypatch, ray_session):
    """drop_object deletes the shm bytes but leaves the meta — the exact
    lost-segment shape lineage reconstruction recovers from."""
    ray = ray_session
    from ray_tpu._private import state

    ctrl = state.global_client().controller

    @ray.remote
    def make():
        return np.arange(50_000, dtype=np.float64)  # shm-sized

    ref = make.remote()
    ray.get(ref, timeout=60)  # sealed into shm, registered head-side
    meta = ctrl.objects[ref.id]
    assert meta.location == "shm"
    assert ChaosInjector.drop_object(ctrl, ref.id) is True
    assert not ctrl.store.exists(ref.id)
    assert ctrl.objects[ref.id].location == "shm"  # meta survives
    # a second drop is a no-op, not an error
    assert ChaosInjector.drop_object(ctrl, ref.id) is False
    # and get() still returns the bytes via the recovery path
    out = ray.get(ref, timeout=60)
    assert out.shape == (50_000,) and float(out[123]) == 123.0


def test_retry_backoff_deterministic_and_bounded(monkeypatch):
    from ray_tpu._private.node_agent import retry_backoff_s, transfer_deadline_s

    seq = [retry_backoff_s(i, key="obj-x") for i in range(6)]
    assert seq == [retry_backoff_s(i, key="obj-x") for i in range(6)]
    assert all(0.0 <= d <= 2.0 for d in seq)
    # exponential shape: later attempts back off more until the cap
    assert seq[4] > seq[1]
    # different keys de-synchronize (jitter), same base schedule bounds
    assert [retry_backoff_s(i, key="obj-y") for i in range(6)] != seq

    monkeypatch.setenv("RAY_TPU_TRANSFER_DEADLINE_S", "12.5")
    assert transfer_deadline_s() == 12.5
    monkeypatch.setenv("RAY_TPU_TRANSFER_DEADLINE_S", "0.01")
    assert transfer_deadline_s() == 1.0  # floor
    monkeypatch.delenv("RAY_TPU_TRANSFER_DEADLINE_S")
    assert transfer_deadline_s() == 30.0


def test_reconstruct_enabled_knob(monkeypatch):
    from ray_tpu._private.controller import reconstruct_enabled

    monkeypatch.delenv("RAY_TPU_RECONSTRUCT", raising=False)
    assert reconstruct_enabled() is True
    monkeypatch.setenv("RAY_TPU_RECONSTRUCT", "0")
    assert reconstruct_enabled() is False
