"""QuasiBayesSearch validation (VERDICT r2 weak #6): the explore/exploit
sampler must actually beat pure random search on a smooth surrogate — not
just carry the name. Deterministic: fixed seeds, averaged over repeats."""

import numpy as np

from ray_tpu.tune.search import BasicVariantGenerator, QuasiBayesSearch
from ray_tpu.tune.search_space import Uniform


def _surrogate(cfg):
    # smooth unimodal bowl with optimum at (0.31, 0.73); scale > jitter noise
    return -((cfg["x"] - 0.31) ** 2 + (cfg["y"] - 0.73) ** 2)


def _run(searcher, budget):
    best = -np.inf
    for i in range(budget):
        cfg = searcher.suggest(f"t{i}")
        if cfg is None:
            break
        score = _surrogate(cfg)
        searcher.on_trial_complete(f"t{i}", {"score": score})
        best = max(best, score)
    return best


def test_quasibayes_beats_random_on_surrogate():
    space = {"x": Uniform(0.0, 1.0), "y": Uniform(0.0, 1.0)}
    budget, seeds = 32, range(12)
    qb_scores, rnd_scores = [], []
    for seed in seeds:
        qb = QuasiBayesSearch(dict(space), num_samples=budget, seed=seed,
                              metric="score", mode="max", warmup=6)
        qb_scores.append(_run(qb, budget))
        rnd = BasicVariantGenerator(dict(space), num_samples=budget, seed=seed)
        rnd_scores.append(_run(rnd, budget))
    # exploit phase should sharpen the best-found optimum on average
    assert np.mean(qb_scores) > np.mean(rnd_scores), (
        f"QuasiBayesSearch {np.mean(qb_scores):.5f} did not beat random "
        f"{np.mean(rnd_scores):.5f}")
    # and should win (or tie within noise) on a clear majority of seeds
    wins = sum(q >= r for q, r in zip(qb_scores, rnd_scores))
    assert wins >= len(qb_scores) * 0.6, (qb_scores, rnd_scores)


def test_quasibayes_handles_minimize_mode():
    space = {"x": Uniform(0.0, 1.0)}
    qb = QuasiBayesSearch(space, num_samples=16, seed=3,
                          metric="loss", mode="min", warmup=4)
    best = np.inf
    for i in range(16):
        cfg = qb.suggest(f"t{i}")
        loss = (cfg["x"] - 0.5) ** 2
        qb.on_trial_complete(f"t{i}", {"loss": loss})
        best = min(best, loss)
    assert best < 0.01
