"""Multi-learner LearnerGroup on the dp mesh axis (VERDICT r1 #5).

Reference contrast: rllib/core/learner/learner_group.py coordinates N
learner workers with explicit gradient allreduce. Here N learners are N
shards of a {'dp': N} mesh inside one jitted update, so the group must
compute the SAME update as a single learner on the concatenated batch —
that equivalence is the core correctness property, verified below on the
virtual 8-device CPU mesh.
"""

import numpy as np
import pytest

from ray_tpu.rllib import make_learner_group
from ray_tpu.rllib.learner import JaxLearner, LearnerGroup
from ray_tpu.rllib.rl_module import ModuleSpec, RLModule


class _Cfg:
    lr = 1e-2
    grad_clip = None
    num_learners = 0
    seed = 0


class _MSELearner(JaxLearner):
    """Supervised toy learner: fit obs -> target with the policy torso."""

    def compute_loss(self, params, batch):
        import jax.numpy as jnp
        dist_in, _ = self.module.forward(params, batch["obs"])
        loss = jnp.mean((dist_in - batch["target"]) ** 2)
        return loss, {"mse": loss}


def _spec():
    return ModuleSpec((4,), "continuous", 2, (16,))


def _batch(rng, n):
    return {"obs": rng.normal(size=(n, 4)).astype(np.float32),
            "target": rng.normal(size=(n, 2 * 2)).astype(np.float32)}


def _leaves(params):
    import jax
    return jax.tree_util.tree_leaves(params)


def test_two_learner_update_equals_single_learner():
    rng = np.random.default_rng(0)
    batch = _batch(rng, 32)

    cfg1 = _Cfg()
    single = make_learner_group(_MSELearner, RLModule(_spec()), cfg1, seed=0)
    assert single.num_learners == 1 and single.mesh is None

    cfg2 = _Cfg()
    cfg2.num_learners = 2
    group = make_learner_group(_MSELearner, RLModule(_spec()), cfg2, seed=0)
    assert group.num_learners == 2
    assert group.mesh.shape["dp"] == 2

    for step in range(5):
        m1 = single.learner.update_once(dict(batch))
        m2 = group.learner.update_once(dict(batch))
        np.testing.assert_allclose(float(m1["mse"]), float(m2["mse"]),
                                   rtol=1e-5)
    for a, b in zip(_leaves(single.get_weights()),
                    _leaves(group.get_weights())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_ragged_minibatch_dropped_not_crashed():
    cfg = _Cfg()
    cfg.num_learners = 4
    group = make_learner_group(_MSELearner, RLModule(_spec()), cfg, seed=0)
    rng = np.random.default_rng(1)
    metrics = group.learner.update_once(dict(_batch(rng, 30)))  # 30 % 4 != 0
    assert np.isfinite(float(metrics["mse"]))
    assert group.learner.update_once(dict(_batch(rng, 2))) == {}  # 2 < 4


def test_num_learners_over_devices_raises():
    cfg = _Cfg()
    cfg.num_learners = 1000
    with pytest.raises(ValueError, match="num_learners=1000"):
        make_learner_group(_MSELearner, RLModule(_spec()), cfg, seed=0)


def test_ppo_trains_through_two_learner_group():
    """PPO end-to-end with num_learners=2: runs, improves, finite metrics."""
    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    algo = (PPOConfig()
            .environment("CartPole-v1")
            .training(train_batch_size=512, minibatch_size=128,
                      num_epochs=2, lr=5e-3)
            .learners(num_learners=2)
            .env_runners(num_env_runners=0)
            .build())
    assert algo.learner_group.num_learners == 2
    first = None
    for _ in range(3):
        result = algo.train()
    learn = result["learner"]
    assert np.isfinite(learn["total_loss"])
    assert result["episode_return_mean"] > 0
    algo.stop()
