"""Serve fleet routing + SLO autoscaling (ISSUE 20): prefix-affinity
digest accounting (bounded, stable under demotion, deterministic scoring),
spill-to-p2c fallback, the RAY_TPU_PREFIX_AFFINITY=0 hatch, multiplex pin
rebalancing, ActorDiedError re-route onto a survivor, and the pure
SLO-overlay scale decision."""

import os
import signal
import time

import pytest

from ray_tpu import serve
from ray_tpu.serve import prefix_digest as pd
from ray_tpu.serve.controller import (aggregate_slo, decide_num_replicas_slo)
from ray_tpu.serve.deployment import AutoscalingConfig
from ray_tpu.serve.handle import DeploymentHandle
from ray_tpu.serve.multiplex import should_rebalance_pin
from ray_tpu.serve.radix_cache import RadixPageManager

PS = 4  # tokens per page


def _mgr(num_pages=64, slots=16, max_seq=16, **hooks):
    return RadixPageManager(num_pages, PS, slots, max_seq, True, **hooks)


def _prompt(*pages, tail=1):
    toks = []
    for p in pages:
        toks.extend(range(p * 100, p * 100 + PS))
    toks.extend(range(9000, 9000 + tail))
    return toks


def _publish(m, slot, toks):
    m.allocate_prefix(slot, toks, len(toks))
    m.register_prefix(slot, toks)
    m.free(slot)


# ------------------------------------------------------------- digest units

def test_digest_bounded_and_packed():
    """Digest stays <= max_bytes packed; pack() and digest_nbytes agree;
    truncation keeps the kept set prefix-closed so consecutive-match
    scoring never breaks at an artificial hole."""
    m = _mgr(num_pages=256, slots=64, max_seq=64)
    prompts = []
    for fam in range(16):
        toks = _prompt(fam * 4 + 1, fam * 4 + 2, fam * 4 + 3)
        prompts.append(toks)
        _publish(m, fam % 8, toks)
    # heat a few families so truncation has a real ranking to apply
    for _ in range(5):
        _publish(m, 0, prompts[0])
        _publish(m, 1, prompts[1])

    small = m.prefix_digest(max_bytes=256)
    assert pd.digest_nbytes(small) <= 256
    assert len(pd.pack(small)) == pd.digest_nbytes(small)
    full = m.prefix_digest(max_bytes=4096)
    assert pd.digest_nbytes(full) <= 4096
    assert len(full["entries"]) > len(small["entries"])
    for dg in (small, full):
        for toks in prompts:
            hashes = pd.prompt_chain_hashes(toks, PS)
            present = sum(1 for h in hashes if h in dg["entries"])
            assert pd.match_depth(dg, hashes) == present  # prefix-closed
    # the hottest family survived the aggressive truncation
    assert pd.match_depth(small, pd.prompt_chain_hashes(prompts[0], PS)) > 0


def test_digest_stable_under_demotion():
    """LRU-demoted (restorable) chains keep advertising in the digest —
    the router can still route to them and the replica restores from the
    stash instead of re-prefilling. Without a demotion plane the evicted
    entry drops (it really is a miss)."""
    stash = {}
    seq = iter(range(10 ** 6))

    def demote(pid, node):
        h = next(seq)
        stash[h] = True
        return h

    def restore(h, pid):
        return h in stash

    m = _mgr(num_pages=8, demote_cb=demote, restore_cb=restore)
    a = _prompt(1, 2)
    _publish(m, 0, a)
    before = m.prefix_digest()
    hashes = pd.prompt_chain_hashes(a, PS)
    assert pd.match_depth(before, hashes) == 2

    # drain the pool: published pages demote to the stash
    big = _prompt(8, 9, 10, tail=4 * PS)
    m.allocate_prefix(1, big, 7 * PS)
    assert m.demoted_pages >= 2
    m.free(1)
    after = m.prefix_digest()
    assert pd.match_depth(after, hashes) == 2      # stable under demotion

    # no demotion plane: eviction is a real discard -> digest drops it
    m2 = _mgr(num_pages=8)
    _publish(m2, 0, a)
    m2.allocate_prefix(1, big, 7 * PS)
    m2.free(1)
    assert pd.match_depth(m2.prefix_digest(), hashes) < 2


def test_digest_deterministic():
    m = _mgr()
    _publish(m, 0, _prompt(1, 2))
    _publish(m, 1, _prompt(1, 7))
    assert m.prefix_digest() == m.prefix_digest()


# ------------------------------------------------------------ router scoring

def _fake_handle(n_replicas, digests):
    h = DeploymentHandle("d")
    h._replicas = [f"r{i}" for i in range(n_replicas)]
    h._inflight = {i: 0 for i in range(n_replicas)}
    h._digests = digests
    return h


def _family_digest(tokens, hits=10):
    hashes = pd.prompt_chain_hashes(tokens, PS)
    return pd.build([(h, hits, i + 1) for i, h in enumerate(hashes)], PS)


def test_router_scoring_deterministic_and_affine():
    fam_a, fam_b = _prompt(1, 2, 3), _prompt(5, 6, 7)
    h = _fake_handle(3, {0: _family_digest(fam_a), 2: _family_digest(fam_b)})
    for _ in range(20):
        assert h._pick_replica(fam_a) == 0
        assert h._pick_replica(fam_b) == 2
    # deeper match beats shallower: replica 1 holds only fam_a's first page
    partial = _family_digest(_prompt(1, tail=0))
    h2 = _fake_handle(3, {0: _family_digest(fam_a), 1: partial})
    assert all(h2._pick_replica(fam_a) == 0 for _ in range(10))


def test_router_spills_hot_replica_to_p2c():
    fam_a = _prompt(1, 2, 3)
    h = _fake_handle(3, {0: _family_digest(fam_a)})
    assert h._pick_by_prefix(fam_a) == 0
    # affinity target's queue is spill_threshold deeper than the idlest
    h._inflight = {0: pd.spill_threshold() + 1, 1: 0, 2: 0}
    assert h._pick_by_prefix(fam_a) is None        # spilled back to p2c
    picks = {h._pick_replica(fam_a) for _ in range(40)}
    assert picks - {0}                             # p2c reaches survivors


def test_router_no_match_and_escape_hatch(monkeypatch):
    fam_a, other = _prompt(1, 2, 3), _prompt(11, 12, 13)
    h = _fake_handle(2, {0: _family_digest(fam_a)})
    assert h._pick_by_prefix(other) is None        # no digest holds it
    monkeypatch.setenv("RAY_TPU_PREFIX_AFFINITY", "0")
    h._inflight = {0: 5, 1: 0}

    def boom(_tokens):
        raise AssertionError("affinity consulted with the hatch closed")

    h._pick_by_prefix = boom
    assert h._pick_replica(fam_a) in (0, 1)        # pure p2c, no scoring


# --------------------------------------------------------- multiplex rebalance

def test_should_rebalance_pin_math():
    assert should_rebalance_pin([10, 1], 0)        # 2-replica skew works
    assert not should_rebalance_pin([3, 3], 0)     # balanced fleet holds
    assert not should_rebalance_pin([1, 0], 0)     # under min_inflight
    assert not should_rebalance_pin([5], 0)        # single replica
    assert should_rebalance_pin([9, 2, 1], 0)      # 9 > 2 * median_low(2)
    assert not should_rebalance_pin([4, 2, 3], 0)  # 4 <= 2 * 2


# ------------------------------------------------------------- SLO decisions

def _auto(**kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 8)
    kw.setdefault("target_ongoing_requests", 2.0)
    return AutoscalingConfig(**kw)


def test_decide_slo_breach_forces_upscale():
    auto = _auto(target_ttft_p99_s=0.5)
    n, why = decide_num_replicas_slo(2, 2, auto, {"ttft_p99_s": 1.2})
    assert (n, why) == (3, "slo_breach")
    # clamped at max even under breach
    n, _ = decide_num_replicas_slo(2, 8, auto, {"ttft_p99_s": 1.2})
    assert n == 8
    # TPOT breach counts too
    auto2 = _auto(target_tpot_p99_ms=20.0)
    n, why = decide_num_replicas_slo(0, 2, auto2, {"tpot_p99_ms": 80.0})
    assert (n, why) == (3, "slo_breach")


def test_decide_occupancy_forces_upscale():
    auto = _auto()
    n, why = decide_num_replicas_slo(2, 2, auto, {"occupancy_mean": 0.95})
    assert (n, why) == (3, "occupancy")


def test_decide_slo_holds_downscale_until_margin():
    auto = _auto(target_ttft_p99_s=1.0)
    # ongoing-count says shrink, but p99 is near target: hold
    n, why = decide_num_replicas_slo(1, 4, auto, {"ttft_p99_s": 0.9})
    assert (n, why) == (4, "slo_hold")
    # comfortably inside margin: the shrink goes through
    n, why = decide_num_replicas_slo(1, 4, auto, {"ttft_p99_s": 0.2})
    assert (n, why) == (1, "ongoing")
    # no snapshot at all: plain ongoing policy
    n, why = decide_num_replicas_slo(1, 4, auto, None)
    assert (n, why) == (1, "ongoing")


def test_aggregate_slo_worst_case():
    frames = [{"ttft_p99_s": 0.1, "tpot_p99_ms": 5.0, "occupancy_mean": 0.2},
              {"ttft_p99_s": 0.9, "tpot_p99_ms": None, "occupancy_mean": 0.6},
              None]
    agg = aggregate_slo(frames)
    assert agg["ttft_p99_s"] == 0.9                # one hot replica counts
    assert agg["tpot_p99_ms"] == 5.0
    assert abs(agg["occupancy_mean"] - 0.4) < 1e-9
    assert aggregate_slo([]) is None and aggregate_slo([None]) is None


def test_histogram_window_is_delta():
    from ray_tpu.util import metrics
    name = "test_fleet_window_hist"
    hist = metrics.get_or_create(metrics.Histogram, name, "t",
                                 boundaries=[1, 10, 100])
    state = {}
    hist.observe(5)
    hist.observe(5)
    w = metrics.histogram_window(name, state)
    assert w["count"] == 2
    assert metrics.histogram_window(name, state) is None   # nothing new
    hist.observe(50)
    w = metrics.histogram_window(name, state)
    assert w["count"] == 1 and w["p50"] > 10               # only the delta


# ------------------------------------------------------------------- cluster

@pytest.fixture(scope="module")
def serve_session():
    import ray_tpu
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    serve.shutdown()


# page size the canned-digest deployment advertises (any int works; the
# router recomputes prompt hashes per advertised page size)
_ADV_PS = 8
_FAMS = [list(range(0, 4 * _ADV_PS)), list(range(500, 500 + 4 * _ADV_PS))]


def test_digest_piggyback_routes_to_advertiser(serve_session):
    """End-to-end affinity: each replica advertises one prompt family via
    the stats piggyback (replica -> controller cache -> handle refresh),
    and requests carrying family tokens land on the advertising replica —
    no per-request controller chatter."""
    @serve.deployment(num_replicas=2)
    class Advertiser:
        def __init__(self):
            tag = serve.get_replica_context().replica_tag
            self._idx = int(tag.rsplit("#", 1)[1]) % 2

        def prefix_digest(self):
            hashes = pd.prompt_chain_hashes(_FAMS[self._idx], _ADV_PS)
            return pd.build([(h, 10, i + 1) for i, h in enumerate(hashes)],
                            _ADV_PS)

        def which(self, tokens):
            return self._idx

    h = serve.run(Advertiser.bind(), name="adv")
    hw = h.options(method_name="which")
    hw._refresh(force=True)
    assert hw._digests, "digests should piggyback on the refresh"
    for fam_idx in (0, 1):
        got = {hw.remote(list(_FAMS[fam_idx])).result(timeout_s=60)
               for _ in range(6)}
        assert got == {fam_idx}
    serve.delete("adv")


def test_mux_pin_rebalances_off_hot_replica(serve_session):
    """Skewed model traffic: a pin whose replica is 2x over the fleet
    median inflight is evicted and re-pinned on the idler replica."""
    from ray_tpu.util import metrics

    @serve.deployment(num_replicas=2)
    class Mux:
        def echo(self, x):
            return x

    h = serve.run(Mux.bind(), name="mux-reb")
    mh = h.options(method_name="echo", multiplexed_model_id="lora-A")
    mh._refresh(force=True)
    before = metrics.serve_fleet_counters()["mux_rebalances"]
    with mh._lock:
        mh._model_affinity["lora-A"] = 0
        mh._inflight = {0: 10, 1: 1}        # replica 0 is drowning
    assert mh.remote(7).result(timeout_s=60) == 7
    assert mh._model_affinity["lora-A"] == 1
    assert metrics.serve_fleet_counters()["mux_rebalances"] == before + 1
    serve.delete("mux-reb")


def test_replica_death_reroutes_to_survivor(serve_session):
    """Chaos kill: SIGKILL one replica's worker process mid-traffic. A
    request routed into the corpse force-refreshes the replica set and
    retries on the survivor instead of erroring (ISSUE 20 satellite)."""
    import ray_tpu
    from ray_tpu.serve.controller import get_controller
    from ray_tpu.util import metrics

    @serve.deployment(num_replicas=2)
    class Victim:
        def echo(self, x):
            return x * 2

    h = serve.run(Victim.bind(), name="death")
    ctrl = get_controller()
    reps = ray_tpu.get(ctrl.get_replicas.remote("death", "Victim"))
    pids = [ray_tpu.get(r.stats.remote(), timeout=30)["pid"] for r in reps]
    assert pids[0] != pids[1]

    he = h.options(method_name="echo")
    he._refresh(force=True)
    dead_id = getattr(reps[0], "_actor_id", None)
    dead_idx = next(i for i, r in enumerate(he._replicas)
                    if getattr(r, "_actor_id", None) == dead_id)

    os.kill(pids[0], signal.SIGKILL)
    time.sleep(0.2)
    before = metrics.serve_fleet_counters()["died_retries"]
    # pin the multiplex path straight into the corpse: without the retry
    # this request errors with ActorDiedError
    hm = h.options(method_name="echo", multiplexed_model_id="m0")
    hm._refresh(force=True)
    with hm._lock:
        hm._model_affinity["m0"] = dead_idx
    assert hm.remote(21).result(timeout_s=60) == 42
    assert metrics.serve_fleet_counters()["died_retries"] >= before + 1
    # the corpse's pin was evicted; follow-ups route clean
    assert hm._model_affinity.get("m0") != dead_idx
    for _ in range(5):
        assert he.remote(1).result(timeout_s=60) == 2
    serve.delete("death")


def test_fleet_bench_smoke_gate():
    """Tier-1 hook for the fleet bench's --smoke mode: a 3-replica CPU
    fleet must show a higher fleet prefix-cache hit rate under affinity
    routing than under the p2c baseline, keep every digest within the
    4 KiB wire bound, and the autoscale rung must scale up within two
    evaluation intervals then drain down with zero dropped requests."""
    import json
    import subprocess
    import sys
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, os.path.join(root, "benchmarks",
                                      "fleet_bench.py"), "--smoke"],
        capture_output=True, text=True, timeout=420, env=env)
    assert p.returncode == 0, (p.stdout, p.stderr)
    rec = json.loads(p.stdout.strip().splitlines()[-1])
    assert rec["smoke"] == "ok"
    assert rec["affinity"]["hit_rate"] > rec["p2c"]["hit_rate"]
    assert max(rec["affinity"]["digest_wire_bytes"].values()) <= 4096
    auto = rec["autoscale"]
    assert auto["failed"] == 0
    assert auto["reaction_intervals"] <= 2.0
    assert auto["final_replicas"] == 1
