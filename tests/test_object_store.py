"""Tiered object memory: atomic spill/restore + the spill ladder (ISSUE 19).

  * spill writes temp-then-rename: a kill mid-spill can never leave a
    truncated file at the trusted path, and a failed rename leaves the shm
    segment intact (the object is never lost to a half-spill)
  * restore round-trips bit-identically and is idempotent under concurrent
    restore: a live segment wins, the loser's file is removed, no collision
  * read_spilled_range serves slices straight from the spill file
  * the controller's background pressure loop demotes cold shm objects but
    never a prefetch-pinned/protected one (spill_pinned_demotions_total == 0)
  * a spilled task arg is restored to shm BEFORE dispatch via the
    PullManager, and the task sees correct bytes
  * a ranged pull of a spilled object is served from the spill file without
    promoting it back to shm (the spilled tier is a pull source)
"""

import asyncio
import os
import subprocess
import sys
import types

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_script(body, env_extra=None, timeout=240):
    env = dict(os.environ, JAX_PLATFORMS="cpu", RAY_TPU_NUM_CHIPS="0")
    env.pop("RAY_TPU_ARENA", None)
    env.pop("RAY_TPU_ADDRESS", None)
    env.update(env_extra or {})
    r = subprocess.run([sys.executable, "-c", body], capture_output=True,
                       text=True, timeout=timeout, env=env, cwd=REPO)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    return r.stdout


def _fresh_store(monkeypatch):
    monkeypatch.delenv("RAY_TPU_ARENA", raising=False)
    from ray_tpu._private.object_store import StoreClient
    return StoreClient()


# ------------------------------------------------------------- atomic spill

def test_spill_restore_bit_identical(monkeypatch):
    from ray_tpu._private.object_store import _spill_dir, seg_name

    store = _fresh_store(monkeypatch)
    try:
        blob = os.urandom(1 << 16)
        store.put_raw("oidA", blob)
        path = store.spill("oidA")
        assert os.path.basename(path) == seg_name("oidA")
        assert not store.exists("oidA")          # shm copy gone
        with open(path, "rb") as f:
            assert f.read() == blob              # disk copy complete
        # temp-then-rename left no residue at any point
        assert not [p for p in os.listdir(_spill_dir()) if ".tmp." in p]

        assert store.restore("oidA", path) == len(blob)
        assert bytes(store.read_raw("oidA")) == blob
        assert not os.path.exists(path)          # spill file consumed
    finally:
        store.close()


def test_spill_failed_rename_keeps_segment(monkeypatch):
    """A crash between temp-write and rename (simulated: os.replace raises)
    must leave the shm segment intact and no file — truncated or whole — at
    the trusted spill path."""
    from ray_tpu._private import object_store as os_mod

    store = _fresh_store(monkeypatch)
    try:
        blob = os.urandom(4096)
        store.put_raw("oidB", blob)
        final = os.path.join(os_mod._spill_dir(), os_mod.seg_name("oidB"))

        def boom(src, dst):
            raise OSError("disk full mid-rename")

        monkeypatch.setattr(os_mod.os, "replace", boom)
        with pytest.raises(OSError):
            store.spill("oidB")
        monkeypatch.undo()
        assert store.exists("oidB")              # segment untouched
        assert bytes(store.read_raw("oidB")) == blob
        assert not os.path.exists(final)         # no trusted-path file
        assert not [p for p in os.listdir(os_mod._spill_dir())
                    if ".tmp." in p]             # temp cleaned up
    finally:
        store.close()


def test_restore_idempotent_when_segment_live(monkeypatch):
    """Concurrent restore: the loser finds the segment already live — no
    live-segment collision, its stale file is removed, bytes unchanged."""
    store = _fresh_store(monkeypatch)
    try:
        blob = os.urandom(8192)
        store.put_raw("oidC", blob)
        path = store.spill("oidC")
        assert store.restore("oidC", path) == len(blob)   # winner

        stale = path  # the loser still holds the (now re-created) file path
        with open(stale, "wb") as f:
            f.write(blob)
        assert store.restore("oidC", stale) == len(blob)  # loser: idempotent
        assert bytes(store.read_raw("oidC")) == blob
        assert not os.path.exists(stale)
    finally:
        store.close()


def test_read_spilled_range(monkeypatch):
    from ray_tpu._private.object_store import StoreClient

    store = _fresh_store(monkeypatch)
    try:
        blob = os.urandom(1 << 15)
        store.put_raw("oidD", blob)
        path = store.spill("oidD")
        assert StoreClient.read_spilled_range(path, 100, 500) == blob[100:600]
        assert StoreClient.read_spilled_range(path, 0, 1) == blob[:1]
        assert StoreClient.read_spilled(path) == blob
        store.restore("oidD", path)
    finally:
        store.close()


# ----------------------------------------------- pressure loop + protection

_PRESSURE_SCRIPT = """
import asyncio
import numpy as np
import ray_tpu as ray
from ray_tpu import api
from ray_tpu.util import metrics

ray.init(num_cpus=2, object_store_memory=256 << 20)
val = np.arange(1 << 18, dtype=np.uint8)          # 256 KiB: above inline max
refs = [ray.put(val) for _ in range(6)]
rt = api._runtime
rt.client.flush()                                 # batched put deltas land

async def drive():
    c = rt.controller
    for _ in range(200):                          # flusher applies on-loop
        if all(c.objects.get(r.id) is not None
               and c.objects[r.id].location == "shm" for r in refs):
            break
        await asyncio.sleep(0.02)
    c.objects[refs[0].id].prefetched = True       # prefetch-pinned: spared
    c._spill_down(0, pressure=True)               # drain all unprotected shm
    c._tier_gauges()
    return {r.id: c.objects[r.id].location for r in refs}

locs = asyncio.run_coroutine_threadsafe(drive(), rt.loop).result(60)
sc = metrics.spill_counters()
assert locs[refs[0].id] == "shm", locs            # pinned object survived
assert sum(1 for l in locs.values() if l == "spilled") >= 5, locs
assert sc["pinned_demotions"] == 0, sc            # the ISSUE invariant
assert sc["pinned_skips"] >= 1, sc
assert sc["spilled_objects"] >= 5, sc
assert sc["pressure_spills"] >= 5, sc
assert sc["spill_bytes"] >= 5 * val.nbytes, sc
occ = metrics.tier_occupancy()
assert occ["disk_bytes"] >= 5 * val.nbytes, occ
assert occ["disk_objects"] >= 5, occ
# restores round-trip bit-identically through ray.get
got = ray.get(list(refs), timeout=60)
assert all((g == val).all() for g in got)
sc2 = metrics.spill_counters()
assert sc2["restored_objects"] >= 5, sc2
assert sc2["restore_bytes"] >= 5 * val.nbytes, sc2
print("PRESSURE_OK")
"""


def test_pressure_demotion_skips_pinned():
    out = _run_script(_PRESSURE_SCRIPT)
    assert "PRESSURE_OK" in out


# -------------------------------------------- restore-before-dispatch (pull)

_RESTORE_DISPATCH_SCRIPT = """
import asyncio
import numpy as np
import ray_tpu as ray
from ray_tpu import api
from ray_tpu.util import metrics

ray.init(num_cpus=2, object_store_memory=256 << 20)
val = np.arange(1 << 18, dtype=np.float32)
x = ray.put(val)
rt = api._runtime
rt.client.flush()                                 # batched put delta lands

async def spill_all():
    c = rt.controller
    for _ in range(200):
        m = c.objects.get(x.id)
        if m is not None and m.location == "shm":
            break
        await asyncio.sleep(0.02)
    c._spill_down(0, pressure=True)
    return c.objects[x.id].location

loc = asyncio.run_coroutine_threadsafe(spill_all(), rt.loop).result(60)
assert loc == "spilled", loc

@ray.remote
def f(a):
    return float(a[123])

assert ray.get(f.remote(x), timeout=120) == 123.0
sc = metrics.spill_counters()
assert sc["restored_objects"] >= 1, sc
assert sc["restore_bytes"] >= val.nbytes, sc

async def where():
    return rt.controller.objects[x.id].location

assert asyncio.run_coroutine_threadsafe(where(), rt.loop).result(30) == "shm"
print("RESTORE_DISPATCH_OK")
"""


def test_restore_before_dispatch_via_pull_manager():
    out = _run_script(_RESTORE_DISPATCH_SCRIPT)
    assert "RESTORE_DISPATCH_OK" in out


# ------------------------------------------- working set larger than arena

_OVERCOMMIT_SCRIPT = """
import numpy as np
import ray_tpu as ray
from ray_tpu.util import metrics

ray.init(num_cpus=1, object_store_memory=64 << 20)
# 72 MB burst through a 64 MB arena: puts must ride the make-room RPC
# (client retries after spill_for_put) instead of surfacing MemoryError
blobs = [np.arange(i, i + (6 << 20) // 8, dtype=np.int64) for i in range(12)]
refs = [ray.put(b) for b in blobs]
# streaming re-reads churn the ladder both directions; each must be
# bit-identical even when the read races a concurrent demotion
for i, r in enumerate(refs):
    got = ray.get(r, timeout=120)
    assert np.array_equal(got, blobs[i]), i
    del got
sc = metrics.spill_counters()
assert sc["spilled_objects"] >= 1, sc
assert sc["restored_objects"] >= 1, sc
assert sc["pinned_demotions"] == 0, sc
print("OVERCOMMIT_OK")
"""


def test_put_burst_over_capacity_rides_make_room():
    out = _run_script(_OVERCOMMIT_SCRIPT)
    assert "OVERCOMMIT_OK" in out


_REREAD_SCRIPT = """
import asyncio
import numpy as np
import ray_tpu as ray
from ray_tpu import api

ray.init(num_cpus=1, object_store_memory=256 << 20)
val = np.arange(1 << 16, dtype=np.float64)
x = ray.put(val)
rt = api._runtime
rt.client.flush()

async def demote():
    c = rt.controller
    for _ in range(200):
        m = c.objects.get(x.id)
        if m is not None and m.location == "shm":
            break
        await asyncio.sleep(0.02)
    c._spill_down(0, pressure=True)
    return c.objects[x.id].meta_len

meta_len = asyncio.run_coroutine_threadsafe(demote(), rt.loop).result(60)
# the client holds a STALE shm descriptor (as if demotion raced the read):
# _materialize must re-request the descriptor, restoring the segment
got = rt.client._materialize([x.id], [("shm", meta_len)])[0]
assert np.array_equal(got, val)
print("REREAD_OK")
"""


def test_stale_descriptor_reread_after_demotion():
    out = _run_script(_REREAD_SCRIPT)
    assert "REREAD_OK" in out


# ------------------------------------------------- spilled-tier ranged pull

class _FakeWriter:
    def __init__(self):
        self.buf = b""
        self.closed = False

    def write(self, b):
        self.buf += b

    async def drain(self):
        pass

    def close(self):
        self.closed = True


def test_serve_range_reads_spill_file_without_promotion(monkeypatch):
    """ObjectDataServer serves a ranged pull of a spilled object straight
    from the spill file — no _ensure_local, the object stays cold."""
    from ray_tpu._private.node_agent import ObjectDataServer
    from ray_tpu.util import metrics

    store = _fresh_store(monkeypatch)
    try:
        blob = os.urandom(1 << 14)
        store.put_raw("oidE", blob)
        path = store.spill("oidE")

        meta = types.SimpleNamespace(location="spilled", spill_path=path,
                                     size=len(blob), meta_len=0, contained=[])

        def no_promote(oid):
            raise AssertionError("ranged pull promoted a spilled object")

        c = types.SimpleNamespace(objects={"oidE": meta}, object_events={},
                                  store=store, _ensure_local=no_promote)
        srv = ObjectDataServer(c)
        before = metrics._counter_total("spill_range_reads_total") or 0

        w = _FakeWriter()
        asyncio.run(srv._serve_range(w, "oidE", 64, 256))
        head, _, rest = w.buf.partition(b"\n")
        assert head == b"OK 256"
        assert rest == blob[64:320]
        assert meta.location == "spilled"        # still cold
        after = metrics._counter_total("spill_range_reads_total") or 0
        assert after == before + 1

        # full-object serve also reads the file without promoting
        w2 = _FakeWriter()
        asyncio.run(srv._serve_one(w2, "oidE"))
        head2, _, rest2 = w2.buf.partition(b"\n")
        assert head2 == f"OK {len(blob)} 0".encode()
        assert rest2.partition(b"\n")[2] == blob
        assert os.path.exists(path)              # spill file untouched
        store.restore("oidE", path)
    finally:
        store.close()
