"""Llama-family decoder in flax.linen, TPU-first.

Design points (vs the reference's torch models, e.g. rllib catalog /
serve LLM replicas):
- bf16 activations, param dtype configurable (f32 master weights by default;
  the optimizer state stays f32 — mixed-precision policy lives here, not in a
  wrapper class like torch AMP).
- Param-tree paths (`embed/embedding`, `layers_N/attn/wq/kernel`, ...) are the
  contract with `ray_tpu.parallel.sharding.llama_rules()` — renaming a module
  changes how it shards.
- Attention impl is selectable: "flash" (pallas), "xla" (einsum reference),
  "ring" (sequence-parallel, needs an `sp` mesh axis), or "auto".
- Decode path uses a static-shape `KVCache` so every step hits the same
  compiled program.
- `remat=True` checkpoints each block (jax.checkpoint) — the TPU equivalent
  of activation checkpointing, trading HBM for recompute.
"""

import dataclasses
from functools import partial
from typing import Any, Optional, Tuple

import flax.linen as nn
import flax.struct
import jax
import jax.numpy as jnp

from ray_tpu.models.moe import MoEMLP
from ray_tpu.ops.attention import apply_rope, decode_attention, mha_reference
from ray_tpu.ops.flash_attention import flash_attention
from ray_tpu.ops.paged_attention import (PagedKVCache, paged_attention,
                                         paged_attention_reference,
                                         write_layer_tokens)
from ray_tpu.ops.ring_attention import ring_attention


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    head_dim: int = 128
    ffn_dim: int = 14336
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    max_seq_len: int = 8192
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16       # activations
    param_dtype: Any = jnp.float32  # master weights
    attn_impl: str = "auto"         # auto | flash | xla | ring
    sp_axis: str = "sp"             # mesh axis for ring attention
    remat: bool = False
    # ---- mixture-of-experts (Mixtral-family; models/moe.py). 0 = dense.
    # When n_experts > 0 every `moe_every`-th block's FFN becomes a
    # top-k-routed expert bank; weights carry a leading [E, ...] dim that
    # `parallel.sharding.llama_rules()` shards over the `ep` mesh axis.
    n_experts: int = 0
    moe_top_k: int = 2
    moe_every: int = 1              # 1 = every block (Mixtral layout)
    capacity_factor: float = 1.25   # per-expert token budget multiplier
    router_aux_weight: float = 0.01  # load-balance loss weight (sowed)

    # ---- presets (sizes follow the Llama family; test config is `tiny`).
    # kwargs override the preset's own values (e.g. tiny(max_seq_len=64)).
    @staticmethod
    def tiny(**kw):
        return LlamaConfig(**{**dict(
            vocab_size=256, d_model=64, n_layers=2, n_heads=4,
            n_kv_heads=2, head_dim=16, ffn_dim=128,
            max_seq_len=128, rope_theta=10000.0), **kw})

    @staticmethod
    def moe_tiny(**kw):
        """Test-scale Mixtral layout: every FFN is a 4-expert top-2 bank."""
        return LlamaConfig(**{**dict(
            vocab_size=256, d_model=64, n_layers=2, n_heads=4,
            n_kv_heads=2, head_dim=16, ffn_dim=128, max_seq_len=128,
            rope_theta=10000.0, n_experts=4, moe_top_k=2), **kw})

    @staticmethod
    def mixtral_8x7b(**kw):
        """Mixtral-8x7B shape: Llama-7B trunk, 8 experts, top-2 routing."""
        return LlamaConfig(**{**dict(
            vocab_size=32000, d_model=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, head_dim=128, ffn_dim=14336, max_seq_len=32768,
            rope_theta=1000000.0, n_experts=8, moe_top_k=2), **kw})

    @staticmethod
    def llama_125m(**kw):
        return LlamaConfig(**{**dict(
            vocab_size=32000, d_model=768, n_layers=12,
            n_heads=12, n_kv_heads=12, head_dim=64,
            ffn_dim=2048, max_seq_len=2048), **kw})

    @staticmethod
    def llama_1b(**kw):
        return LlamaConfig(**{**dict(
            vocab_size=32000, d_model=2048, n_layers=16,
            n_heads=32, n_kv_heads=8, head_dim=64,
            ffn_dim=5632, max_seq_len=4096), **kw})

    @staticmethod
    def llama_8b(**kw):
        return LlamaConfig(**kw)  # defaults above are 8B

    @staticmethod
    def llama_70b(**kw):
        return LlamaConfig(**{**dict(
            d_model=8192, n_layers=80, n_heads=64,
            n_kv_heads=8, head_dim=128, ffn_dim=28672), **kw})


class KVCache(flax.struct.PyTreeNode):
    """Static-shape per-layer K/V cache: lists of [B, Smax, Kh, D] arrays.

    `length` counts valid tokens per batch row (same for all rows in the
    simple decode loop; per-row for continuous batching in serve/llm).

    Capacity invariant (caller-enforced, host-side): length + new_tokens must
    stay <= Smax. XLA's dynamic_update_slice clamps out-of-range starts, so an
    overflowing write would silently overwrite the cache tail instead of
    erroring — drivers (serve/llm, generate loops) must stop or evict at
    capacity; a data-dependent raise can't live inside jit.

    Scan contract: the decode-step program (t == 1) is also the body of
    serve/llm's fused multi-token chunk — the cache is CARRIED through a
    lax.scan, so the step must stay shape-stable with no host callbacks,
    and the capacity invariant applies per scan step (the serve tick loop
    clamps its chunk length to the row with the most remaining room). A
    row whose length is frozen mid-scan (terminated slot) keeps taking one
    masked write per step at that frozen position — garbage past `length`
    is never readable (absolute-position mask) and is overwritten when the
    row is reused."""
    k: Tuple[jax.Array, ...]
    v: Tuple[jax.Array, ...]
    length: jax.Array  # [B] int32

    @staticmethod
    def init(cfg: LlamaConfig, batch: int, max_len: Optional[int] = None,
             dtype=None):
        max_len = max_len or cfg.max_seq_len
        dtype = dtype or cfg.dtype
        shape = (batch, max_len, cfg.n_kv_heads, cfg.head_dim)
        zeros = lambda: jnp.zeros(shape, dtype)
        return KVCache(
            k=tuple(zeros() for _ in range(cfg.n_layers)),
            v=tuple(zeros() for _ in range(cfg.n_layers)),
            length=jnp.zeros((batch,), jnp.int32))


class RMSNorm(nn.Module):
    eps: float = 1e-5
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        xf = x.astype(jnp.float32)
        normed = xf * jax.lax.rsqrt(jnp.mean(jnp.square(xf), -1, keepdims=True) + self.eps)
        return (normed * scale).astype(self.dtype)


class Attention(nn.Module):
    cfg: LlamaConfig
    layer_idx: int = 0

    @nn.compact
    def __call__(self, x, positions, cache: Optional[KVCache],
                 paged_chunk_local: bool = False):
        cfg = self.cfg
        layer_idx = self.layer_idx
        dense = partial(nn.Dense, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype,
                        kernel_init=nn.initializers.normal(0.02))
        b, t, _ = x.shape
        q = dense(cfg.n_heads * cfg.head_dim, name="wq")(x)
        k = dense(cfg.n_kv_heads * cfg.head_dim, name="wk")(x)
        v = dense(cfg.n_kv_heads * cfg.head_dim, name="wv")(x)
        q = q.reshape(b, t, cfg.n_heads, cfg.head_dim)
        k = k.reshape(b, t, cfg.n_kv_heads, cfg.head_dim)
        v = v.reshape(b, t, cfg.n_kv_heads, cfg.head_dim)
        q = apply_rope(q, positions, cfg.rope_theta)
        k = apply_rope(k, positions, cfg.rope_theta)

        new_cache_kv = None
        if isinstance(cache, PagedKVCache):
            # Paged decode/prefill (vLLM memory model, ops/paged_attention):
            # write this layer's K/V into its page slice, then attend. The
            # cache threads through the block stack; decode writes use
            # per-row dynamic_update_slice (in-place on the donated pool —
            # see write_layer_tokens: the batched scatter COPIED the pool).
            cache = write_layer_tokens(cache, layer_idx, k, v, positions)
            if t == 1:
                # decode: pallas kernel walks the block table (XLA gather
                # reference off-TPU, same numerics)
                impl = (paged_attention if jax.default_backend() == "tpu"
                        else paged_attention_reference)
                out = impl(q[:, 0], cache.k_pages[layer_idx],
                           cache.v_pages[layer_idx], cache.block_tables,
                           positions[:, -1] + 1)[:, None]
            elif paged_chunk_local:
                # FIRST chunk of a fresh row (start==0, no cached prefix —
                # the caller asserts this statically): chunk-local causal
                # attention is exact, no page gather. The hot cold-prompt
                # TTFT path; honors attn_impl like the cache=None branch.
                impl = cfg.attn_impl
                if impl in ("auto", "ring"):
                    impl = "flash" if jax.default_backend() == "tpu" else "xla"
                out = (flash_attention(q, k, v, causal=True) if impl == "flash"
                       else mha_reference(q, k, v, causal=True))
            else:
                # chunked prefill continuation: queries must see the row's
                # CACHED prefix (chunks 2+ of a long prompt, and
                # prefix-cache hits start mid-prompt), not just their own
                # chunk — chunk-local causal attention here was the r4 bug
                # that made multi-chunk paged prefill numerically wrong.
                # Gather the row's pages into contiguous KV (slot s =
                # absolute position s; the padded table's placeholder pages
                # sit past every valid query position and are masked) and
                # reuse decode_attention's absolute-position causal mask.
                # B is 1 here (row view), so the gather is one row's
                # capacity per layer.
                kp = cache.k_pages[layer_idx]      # [Kh, P, ps, D]
                vp = cache.v_pages[layer_idx]
                tb = cache.block_tables            # [B, mp]
                kh_, d_ = kp.shape[0], kp.shape[-1]
                k_all = kp[:, tb].transpose(1, 2, 3, 0, 4).reshape(
                    b, -1, kh_, d_)
                v_all = vp[:, tb].transpose(1, 2, 3, 0, 4).reshape(
                    b, -1, kh_, d_)
                out = decode_attention(q, k_all, v_all, positions[:, 0])
            new_cache_kv = cache
        elif cache is not None:
            # Decode: write current K/V at `length`, attend over the cache.
            k_cache = jax.vmap(
                lambda c, u, i: jax.lax.dynamic_update_slice_in_dim(c, u, i, 0)
            )(cache.k[layer_idx], k, cache.length)
            v_cache = jax.vmap(
                lambda c, u, i: jax.lax.dynamic_update_slice_in_dim(c, u, i, 0)
            )(cache.v[layer_idx], v, cache.length)
            out = decode_attention(q, k_cache, v_cache, cache.length)
            new_cache_kv = (k_cache, v_cache)
        else:
            impl = cfg.attn_impl
            if impl == "auto":
                impl = "flash" if jax.default_backend() == "tpu" else "xla"
            if impl == "flash":
                out = flash_attention(q, k, v, causal=True)
            elif impl == "ring":
                out = ring_attention(q, k, v, axis_name=cfg.sp_axis, causal=True)
            else:
                out = mha_reference(q, k, v, causal=True)

        out = out.reshape(b, t, cfg.n_heads * cfg.head_dim)
        return dense(cfg.d_model, name="wo")(out), new_cache_kv


class MLP(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = partial(nn.Dense, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype,
                        kernel_init=nn.initializers.normal(0.02))
        gate = dense(cfg.ffn_dim, name="w_gate")(x)
        up = dense(cfg.ffn_dim, name="w_up")(x)
        return dense(cfg.d_model, name="w_down")(nn.silu(gate) * up)


class Block(nn.Module):
    cfg: LlamaConfig
    layer_idx: int = 0

    @nn.compact
    def __call__(self, x, positions, cache, paged_chunk_local=False):
        cfg = self.cfg
        h, new_kv = Attention(cfg, self.layer_idx, name="attn")(
            RMSNorm(cfg.norm_eps, cfg.dtype, name="attn_norm")(x),
            positions, cache, paged_chunk_local)
        x = x + h
        if cfg.n_experts > 0 and self.layer_idx % cfg.moe_every == 0:
            ffn = MoEMLP(cfg, name="moe")
        else:
            ffn = MLP(cfg, name="mlp")
        x = x + ffn(RMSNorm(cfg.norm_eps, cfg.dtype, name="mlp_norm")(x))
        return x, new_kv


class Llama(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, tokens, positions=None, cache: Optional[KVCache] = None,
                 return_hidden: bool = False, paged_chunk_local: bool = False):
        """tokens [B, T] int32 → logits [B, T, V] (f32), new cache (or None).

        Prefill/train: cache=None, full causal attention. Decode: pass a
        KVCache; T is the number of new tokens (usually 1).

        `paged_chunk_local=True` (static; paged prefill only): the chunk is
        the FIRST tokens of a fresh row (start==0, no cached prefix), so
        chunk-local causal attention is exact and skips the full-row page
        gather — the hot cold-prompt path.

        `return_hidden=True` returns the final-norm hidden states [B, T, D]
        instead of logits — callers fuse the lm_head into a chunked loss
        (ops.losses.chunked_cross_entropy) to avoid materializing [B, T, V]."""
        cfg = self.cfg
        b, t = tokens.shape
        if positions is None:
            if cache is not None:
                positions = cache.length[:, None] + jnp.arange(t)[None, :]
            else:
                positions = jnp.broadcast_to(jnp.arange(t)[None, :], (b, t))

        embed = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype,
                         embedding_init=nn.initializers.normal(0.02),
                         name="embed")
        x = embed(tokens)

        block_cls = Block
        if cfg.remat and cache is None:
            block_cls = nn.remat(
                Block, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
        paged = isinstance(cache, PagedKVCache)
        new_k, new_v = [], []
        for i in range(cfg.n_layers):
            x, new_kv = block_cls(cfg, i, name=f"layers_{i}")(
                x, positions, cache, paged_chunk_local)
            if paged:
                cache = new_kv  # thread the updated page pools layer→layer
            elif new_kv is not None:
                new_k.append(new_kv[0])
                new_v.append(new_kv[1])

        x = RMSNorm(cfg.norm_eps, cfg.dtype, name="final_norm")(x)
        if return_hidden:
            new_cache = None
            if paged:
                new_cache = cache.replace(lengths=cache.lengths + t)
            elif cache is not None:
                new_cache = KVCache(k=tuple(new_k), v=tuple(new_v),
                                    length=cache.length + t)
            return x, new_cache
        if cfg.tie_embeddings:
            logits = embed.attend(x)
        else:
            logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                              param_dtype=cfg.param_dtype,
                              kernel_init=nn.initializers.normal(0.02),
                              name="lm_head")(x)
        logits = logits.astype(jnp.float32)

        new_cache = None
        if paged:
            new_cache = cache.replace(lengths=cache.lengths + t)
        elif cache is not None:
            new_cache = KVCache(k=tuple(new_k), v=tuple(new_v),
                                length=cache.length + t)
        return logits, new_cache


def _n_moe_layers(cfg: LlamaConfig) -> int:
    if cfg.n_experts <= 0:
        return 0
    return len(range(0, cfg.n_layers, cfg.moe_every))


def _attn_params(cfg: LlamaConfig) -> int:
    """Per-layer attention weights — single source for count AND flops so
    a layout change (biases, MLA, ...) can't desynchronize reported MFU
    from the real parameter count."""
    return cfg.d_model * cfg.head_dim * (cfg.n_heads * 2
                                         + cfg.n_kv_heads * 2)


def _mlp_params(cfg: LlamaConfig) -> int:
    """One dense SwiGLU FFN (also the per-expert size in an MoE bank)."""
    return 3 * cfg.d_model * cfg.ffn_dim


def llama_param_count(cfg: LlamaConfig) -> int:
    per_layer = _attn_params(cfg) + _mlp_params(cfg) + 2 * cfg.d_model
    total = cfg.n_layers * per_layer
    # MoE blocks swap the dense FFN for E experts + a router
    n_moe = _n_moe_layers(cfg)
    total += n_moe * ((cfg.n_experts - 1) * _mlp_params(cfg)
                      + cfg.d_model * cfg.n_experts)
    embed = cfg.vocab_size * cfg.d_model
    head = 0 if cfg.tie_embeddings else cfg.vocab_size * cfg.d_model
    return total + embed + head + cfg.d_model


def llama_compute_flops(cfg: LlamaConfig, batch: int, seq: int) -> float:
    """Training FLOPs per step ≈ 6·N_active·tokens + attention term
    (causal). For MoE, N_active counts top_k experts per token, not the
    full bank — the honest denominator for MFU."""
    n_moe = _n_moe_layers(cfg)
    n_dense = cfg.n_layers - n_moe
    n_active = (cfg.n_layers * _attn_params(cfg)
                + n_dense * _mlp_params(cfg)
                + n_moe * (cfg.moe_top_k * _mlp_params(cfg)
                           + cfg.d_model * cfg.n_experts))
    head = 0 if cfg.tie_embeddings else cfg.vocab_size * cfg.d_model
    n_active += head
    tokens = batch * seq
    attn = 6 * cfg.n_layers * cfg.n_heads * cfg.head_dim * batch * seq * seq  # fwd 2 matmuls + bwd, halved for causal
    return 6.0 * n_active * tokens + attn
