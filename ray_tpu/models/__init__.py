"""Flax model zoo (ray_tpu.models).

Reference contrast: the reference's model code is torch (rllib catalog
models, serve LLM replicas). Here the flagship is a bf16-first Llama-family
decoder shaped for the MXU, plus small MLP/CNN torsos for RL policies.
"""

from ray_tpu.models.llama import (
    KVCache,
    Llama,
    LlamaConfig,
    llama_compute_flops,
    llama_param_count,
)
from ray_tpu.models.lora import (apply_lora, init_lora, lora_opt_mask,
                                 lora_param_count, lora_targets, merge_lora)
from ray_tpu.models.moe import MoEMLP, moe_aux_loss
from ray_tpu.models.torsos import CNNTorso, MLPTorso

__all__ = [
    "KVCache",
    "Llama",
    "LlamaConfig",
    "llama_compute_flops",
    "llama_param_count",
    "MoEMLP",
    "moe_aux_loss",
    "apply_lora",
    "init_lora",
    "lora_opt_mask",
    "lora_param_count",
    "lora_targets",
    "merge_lora",
    "CNNTorso",
    "MLPTorso",
]
