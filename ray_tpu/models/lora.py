"""LoRA adapters for the Llama family, functional-style.

Reference parity: the reference serves LoRA checkpoints through its LLM
ingress (python/ray/llm/_internal/serve/core/ingress/ingress.py
`get_lora_model_ids` / lora_serve_utils) and fine-tunes via torch PEFT
wrappers that monkey-patch Linear modules. TPU-first re-design: no module
surgery. A LoRA adapter here is a pytree of {"a": [in, r], "b": [r, out]}
factors addressed by the SAME param paths as the base weights, and

    effective = params + scale * (a @ b)

is computed functionally inside the jitted step (`apply_lora`). XLA fuses
the rank-r expansion into the surrounding matmuls; a training step
differentiates w.r.t. the adapter tree only, so optimizer state is O(r)
— the standard JAX formulation, and the base params can stay donated /
sharded exactly as before (the delta inherits their sharding from the
einsum).

Serving: `merge_lora` folds an adapter into a copy of the base params for
zero-overhead decode; the serve multiplex cache (serve/multiplex.py) is
the LRU that holds one merged model per adapter id, mirroring the
reference's LoRA-multiplexing deployment pattern.
"""

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

# default: every attention projection + FFN matrix (2-D kernels only)
DEFAULT_TARGETS = (r"(wq|wk|wv|wo)/kernel$",
                   r"(w_gate|w_up|w_down)/kernel$")


def _path_str(path) -> str:
    """Single source for key-path stringification — init_lora and
    apply_lora MUST agree on paths or an adapter silently no-ops."""
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in path)


def _flatten_with_paths(tree) -> List[Tuple[str, Any]]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(_path_str(path), leaf) for path, leaf in flat]


def lora_targets(params, patterns: Sequence[str] = DEFAULT_TARGETS
                 ) -> List[str]:
    """Param paths an adapter will cover (2-D kernels matching patterns)."""
    pats = [re.compile(p) for p in patterns]
    return [path for path, leaf in _flatten_with_paths(params)
            if getattr(leaf, "ndim", 0) == 2
            and any(p.search(path) for p in pats)]


def init_lora(key, params, rank: int = 8, alpha: float = 16.0,
              patterns: Sequence[str] = DEFAULT_TARGETS) -> Dict[str, Any]:
    """Create an adapter tree: {"scale", "factors": {path: {"a", "b"}}}.

    `a` is gaussian, `b` zeros — the adapter starts as an exact no-op
    (effective == base), the standard LoRA init.
    """
    factors = {}
    targets = lora_targets(params, patterns)
    if not targets:
        raise ValueError(f"no params match LoRA patterns {list(patterns)}")
    keys = jax.random.split(key, len(targets))
    by_path = dict(_flatten_with_paths(params))
    for k, path in zip(keys, targets):
        w = by_path[path]
        d_in, d_out = w.shape
        factors[path] = {
            "a": (jax.random.normal(k, (d_in, rank), jnp.float32)
                  / jnp.sqrt(d_in)),
            "b": jnp.zeros((rank, d_out), jnp.float32),
        }
    return {"scale": jnp.float32(alpha / rank), "factors": factors}


def apply_lora(params, lora) -> Any:
    """effective = params + scale·(a@b) on adapted paths; jit-friendly
    (pure function of both trees — differentiate w.r.t. `lora` to train
    the adapter with the base frozen).

    Raises if any adapter factor matches no param path: a silently
    ignored factor would serve/train the bare base model under the
    adapter's name (wrong tree root, different config, renamed module)."""
    factors = lora["factors"]
    # scale is a HYPERPARAMETER (alpha/rank): stop_gradient zeroes its
    # gradient, but that alone doesn't protect it from optimizers with
    # DECOUPLED weight decay (adamw shrinks every leaf by lr·wd·leaf
    # regardless of gradient) — wrap such optimizers with
    # optax.masked(opt, lora_opt_mask(lora)) so scale is never updated
    scale = jax.lax.stop_gradient(lora["scale"])
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    param_paths = {_path_str(path) for path, _ in flat}
    orphans = set(factors) - param_paths
    if orphans:
        raise ValueError(
            f"LoRA factors match no param path (adapter built against a "
            f"different tree?): {sorted(orphans)[:4]}... "
            f"example param paths: {sorted(param_paths)[:2]}")
    leaves = []
    for path, leaf in flat:
        f = factors.get(_path_str(path))
        if f is not None:
            delta = (f["a"] @ f["b"]).astype(leaf.dtype)
            leaf = leaf + scale.astype(leaf.dtype) * delta
        leaves.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def merge_lora(params, lora) -> Any:
    """Fold the adapter into a fully-materialized NEW param tree for
    serving: every leaf is copied, so the merged tree stays valid even if
    the base tree's buffers are later donated inside a train step."""
    return jax.tree_util.tree_map(jnp.array, apply_lora(params, lora))


def lora_opt_mask(lora) -> Dict[str, Any]:
    """Boolean pytree for optax.masked / optax.multi_transform: True on
    trainable leaves (the factors), False on the scale hyperparameter.

    Needed because stop_gradient only zeroes scale's GRADIENT — an
    optimizer with decoupled weight decay (adamw) still applies
    `-lr·wd·scale` every step and silently decays alpha/rank toward 0.
    Usage: opt = optax.masked(optax.adamw(...), lora_opt_mask(lora))."""
    return {"scale": False,
            "factors": jax.tree_util.tree_map(lambda _: True,
                                              lora["factors"])}


def lora_param_count(lora) -> int:
    return sum(int(x.size)
               for x in jax.tree_util.tree_leaves(lora["factors"]))
