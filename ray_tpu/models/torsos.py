"""Small policy/value network torsos for RL (reference: rllib catalog's
torch MLP/CNN encoders). flax.linen, f32 by default — RL nets are tiny and
run on whatever device the learner holds."""

from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp


class MLPTorso(nn.Module):
    hidden_sizes: Sequence[int] = (256, 256)
    activation: Callable = nn.tanh
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = x.reshape(x.shape[0], -1)
        for i, size in enumerate(self.hidden_sizes):
            x = nn.Dense(size, dtype=self.dtype, name=f"dense_{i}",
                         kernel_init=nn.initializers.orthogonal(jnp.sqrt(2)))(x)
            x = self.activation(x)
        return x


class CNNTorso(nn.Module):
    """Conv stack for image observations; NHWC (TPU-preferred layout)."""
    channels: Sequence[int] = (32, 64, 64)
    kernels: Sequence[Tuple[int, int]] = ((8, 8), (4, 4), (3, 3))
    strides: Sequence[Tuple[int, int]] = ((4, 4), (2, 2), (1, 1))
    hidden: int = 512
    activation: Callable = nn.relu
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        if x.dtype == jnp.uint8:  # static dtype check — jit-safe
            x = x.astype(self.dtype) / 255.0
        x = x.astype(self.dtype)
        for i, (ch, k, s) in enumerate(zip(self.channels, self.kernels, self.strides)):
            x = nn.Conv(ch, k, s, dtype=self.dtype, name=f"conv_{i}")(x)
            x = self.activation(x)
        x = x.reshape(x.shape[0], -1)
        return self.activation(nn.Dense(self.hidden, dtype=self.dtype, name="proj")(x))
