"""Mixture-of-experts FFN for the Llama family (Mixtral layout).

Reference parity: the reference serves MoE checkpoints (Mixtral et al.)
through its vLLM/SGLang engines, whose CUDA kernels do scatter/gather
token routing (e.g. python/ray/llm/_internal/serve/engines/sglang/
sglang_engine.py engine wrapper). TPU-first re-design: routing is the
GShard/Switch dense-dispatch formulation — one-hot dispatch/combine
tensors contracted with einsums — because XLA turns those into large
static-shape matmuls on the MXU, while data-dependent gather/scatter
would defeat tiling. Expert weights carry a leading [E, ...] dim that
`parallel.sharding.llama_rules()` maps to the `ep` mesh axis: under pjit
the dispatch einsum becomes the token all-to-all over ICI, inserted by
the compiler (scaling-book recipe), not hand-written collectives.

Capacity: each expert processes at most C = ceil(top_k * S / E *
capacity_factor) tokens (S = B*T tokens in the step, a static shape).
Tokens over budget are dropped — their combine weight is zero and the
block's residual connection carries them through unchanged, the standard
Switch behavior.

Load balancing: the Switch aux loss E * Σ_e f_e · P_e (f_e = fraction of
tokens whose top-1 choice is e, P_e = mean router prob) is sowed into the
"losses" collection as "moe_aux"; training code collects it with
`model.apply(..., mutable=["losses"])` and adds
`cfg.router_aux_weight * mean(aux)` to the task loss.
"""

import math

import flax.linen as nn
import jax
import jax.numpy as jnp


class MoEMLP(nn.Module):
    """Top-k routed SwiGLU expert bank; drop-in for llama.MLP ([B,T,D] →
    [B,T,D])."""

    cfg: "LlamaConfig"  # noqa: F821 - llama.py owns the config class

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        E, K = cfg.n_experts, cfg.moe_top_k
        B, T, D = x.shape
        S = B * T
        F = cfg.ffn_dim
        xf = x.reshape(S, D)

        # Router runs in f32: tiny compute, and bf16 softmax noise here
        # flips expert assignments (standard practice, e.g. Mixtral).
        logits = nn.Dense(E, use_bias=False, dtype=jnp.float32,
                          param_dtype=jnp.float32,
                          kernel_init=nn.initializers.normal(0.02),
                          name="router")(xf.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)            # [S, E]
        gate_vals, gate_idx = jax.lax.top_k(probs, K)       # [S, K]
        gate_vals = gate_vals / jnp.maximum(
            gate_vals.sum(-1, keepdims=True), 1e-9)         # renormalize

        # Switch load-balance aux loss (top-1 assignment fractions)
        f_e = jnp.mean(jax.nn.one_hot(gate_idx[:, 0], E,
                                      dtype=jnp.float32), axis=0)
        p_e = jnp.mean(probs, axis=0)
        self.sow("losses", "moe_aux", E * jnp.sum(f_e * p_e))

        # Position of each (token, k) assignment inside its expert's queue,
        # k-major (all first choices claim capacity before any second
        # choice — GShard priority). Static shapes throughout.
        C = max(1, math.ceil(cfg.capacity_factor * K * S / E))
        sel = jax.nn.one_hot(gate_idx, E, dtype=jnp.int32)  # [S, K, E]
        selk = sel.transpose(1, 0, 2).reshape(K * S, E)
        pos = jnp.cumsum(selk, axis=0) - selk               # [K*S, E]
        posk = (pos.reshape(K, S, E) *
                sel.transpose(1, 0, 2)).sum(-1)             # [K, S]
        keep = (posk < C).astype(jnp.float32)               # over-budget → 0
        gates = gate_vals.T * keep                          # [K, S]

        # combine[s, e, c]: gate weight of token s at slot c of expert e
        combine = jnp.einsum(
            "ks,kse,ksc->sec", gates,
            sel.transpose(1, 0, 2).astype(jnp.float32),
            jax.nn.one_hot(posk, C, dtype=jnp.float32))
        dispatch = (combine > 0).astype(cfg.dtype)          # [S, E, C]

        # Expert bank as single [E, ...] tensors: batched einsums keep the
        # MXU busy and give the sharding engine one leading dim to slice
        # over `ep`.
        init = nn.initializers.normal(0.02)
        w_gate = self.param("w_gate", init, (E, D, F), cfg.param_dtype)
        w_up = self.param("w_up", init, (E, D, F), cfg.param_dtype)
        w_down = self.param("w_down", init, (E, F, D), cfg.param_dtype)

        expert_in = jnp.einsum("sec,sd->ecd", dispatch,
                               xf.astype(cfg.dtype))        # [E, C, D]
        h = jnp.einsum("ecd,edf->ecf", expert_in,
                       w_gate.astype(cfg.dtype))
        u = jnp.einsum("ecd,edf->ecf", expert_in,
                       w_up.astype(cfg.dtype))
        out = jnp.einsum("ecf,efd->ecd", nn.silu(h) * u,
                         w_down.astype(cfg.dtype))          # [E, C, D]
        y = jnp.einsum("sec,ecd->sd", combine.astype(cfg.dtype), out)
        return y.reshape(B, T, D)


def moe_aux_loss(losses_collection, weight: float) -> jnp.ndarray:
    """Mean sowed router aux loss × weight; 0.0 when the model is dense."""
    vals = jax.tree_util.tree_leaves(losses_collection)
    if not vals:
        return jnp.float32(0.0)
    return weight * sum(jnp.mean(v) for v in vals) / len(vals)
