"""ray_tpu — a TPU-native distributed compute framework.

The capability surface of Ray (reference: /root/reference, wingkitlee0/ray)
re-designed TPU-first: tasks/actors/objects over a single-host controller per
TPU host, XLA/ICI collectives instead of NCCL, pjit/shard_map parallelism
instead of DDP, and jax.jit compute in Train/Serve/RLlib.

This module imports no jax — workers cold-start fast; accelerator code lives
in ray_tpu.parallel / models / ops / train and is imported on use.
"""

from ._version import __version__
from ._private.object_ref import ObjectRef, ObjectRefGenerator, DynamicObjectRefGenerator
from .actor import ActorClass, ActorHandle, method, exit_actor
from .api import (
    available_resources,
    cancel,
    cluster_address,
    cluster_resources,
    get,
    get_actor,
    init,
    is_initialized,
    kill,
    nodes,
    object_ref_from_id,
    put,
    remote,
    shutdown,
    timeline,
    wait,
)
from .logging_config import LoggingConfig
from .remote_function import RemoteFunction
from .runtime_context import get_runtime_context, get_tpu_ids
from . import exceptions

__all__ = [
    "__version__",
    "ActorClass", "ActorHandle", "ObjectRef", "ObjectRefGenerator",
    "DynamicObjectRefGenerator", "RemoteFunction",
    "available_resources", "cancel", "cluster_address", "cluster_resources", "exceptions",
    "exit_actor", "get", "get_actor", "get_runtime_context", "get_tpu_ids",
    "init", "is_initialized", "kill", "LoggingConfig", "method", "nodes",
    "object_ref_from_id", "put", "remote",
    "shutdown", "timeline", "wait",
]

_LAZY_SUBMODULES = ("parallel", "models", "ops", "train", "tune", "data",
                    "serve", "rllib", "util", "dag", "workflow")


def __getattr__(name):
    if name in _LAZY_SUBMODULES:
        import importlib
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'ray_tpu' has no attribute '{name}'")
