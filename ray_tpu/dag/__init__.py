"""ray_tpu.dag — compiled actor pipelines (reference: python/ray/dag/ —
InputNode/dag_node.py bind graphs, compiled_dag_node.py
experimental_compile).

    with dag.InputNode() as inp:
        x = preproc.tokenize.bind(inp)
        y = model.infer.bind(x)
        out = postproc.detok.bind(y)
    compiled = out.experimental_compile()
    ref = compiled.execute(prompt)          # one driver round-trip
    results = [compiled.execute(p) for p in prompts]  # stages overlap

What "compiled" buys here, TPU-first instead of a CUDA-graph translation:

- ONE submission round per execute(): the whole chain is registered with
  the controller as dependency-linked tasks; intermediate values flow
  worker→worker through the shared-memory arena (zero-copy attach on the
  consumer) without the driver touching them. The reference compiles to
  pre-allocated channels for the same reason — here plasma-style shm IS
  the channel.
- PIPELINING across consecutive execute() calls for free: each actor
  serializes its own calls, so stage A works on item i+1 while stage B
  works on item i — exactly the prefill→decode / multi-stage-serve overlap
  pattern the reference gets from its compiled DAG scheduler.
- MultiOutputNode returns several leaves per execution.

Contrast: no static channel pre-allocation or per-execution buffer reuse
(the arena allocator is a lock+freelist op, measured cheap), and actor
method CANCELLATION of a whole in-flight execution is per-ref.
"""

from typing import Any, Dict, List, Optional, Tuple


class DAGNode:
    """Base: something whose value materializes per execution."""

    def experimental_compile(self, **_compat) -> "CompiledDag":
        return CompiledDag([self])

    def execute(self, *args, **kwargs):
        """Uncompiled convenience execution (reference dag_node.execute)."""
        return self.experimental_compile().execute(*args, **kwargs)


class InputNode(DAGNode):
    """The per-execution input placeholder (reference input_node.py).

    Supports attribute/index access (`inp[0]`, `inp.field`) so one input
    can fan out structured pieces to different stages. The `with` block is
    reference-API sugar — binds work the same outside it."""

    def __init__(self):
        self._accessor: Tuple = ()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return None

    def __getitem__(self, key):
        out = InputNode()
        out._accessor = self._accessor + (("item", key),)
        return out

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        out = InputNode()
        out._accessor = self._accessor + (("attr", name),)
        return out

    def _resolve(self, value):
        for kind, key in self._accessor:
            value = value[key] if kind == "item" else getattr(value, key)
        return value


class ClassMethodNode(DAGNode):
    """One actor-method invocation in the graph (reference class_node.py)."""

    def __init__(self, actor_handle, method_name: str, args: Tuple,
                 kwargs: Dict):
        self.actor = actor_handle
        self.method_name = method_name
        self.args = args
        self.kwargs = kwargs


class MultiOutputNode(DAGNode):
    """Bundle several leaves into one execution returning a list
    (reference dag/output_node.py)."""

    def __init__(self, outputs: List[DAGNode]):
        self.outputs = list(outputs)

    def experimental_compile(self, **_compat) -> "CompiledDag":
        return CompiledDag(self.outputs)


class FunctionNode(DAGNode):
    """One task invocation in a graph (reference dag function nodes — the
    substrate ray.workflow builds on). Created via RemoteFunction.bind."""

    def __init__(self, remote_fn, args: Tuple, kwargs: Dict):
        self.remote_fn = remote_fn
        self.args = args
        self.kwargs = kwargs

    @property
    def name(self) -> str:
        fn = getattr(self.remote_fn, "_fn", None)
        return getattr(fn, "__name__", "task")


class _BoundMethod:
    def __init__(self, actor_handle, method_name):
        self._actor = actor_handle
        self._name = method_name

    def bind(self, *args, **kwargs) -> ClassMethodNode:
        return ClassMethodNode(self._actor, self._name, args, kwargs)


def bind_method(actor_handle, method_name: str) -> _BoundMethod:
    """`actor.method.bind(...)` sugar lives on ActorHandle (actor.py); this
    is the functional spelling for handles from older pickles."""
    return _BoundMethod(actor_handle, method_name)


class CompiledDag:
    """A frozen pipeline: execute() submits every node's task in one pass,
    wiring outputs to inputs as ObjectRefs (deps resolve in the controller;
    values move through shm, never the driver)."""

    def __init__(self, outputs: List[DAGNode]):
        self.outputs = outputs
        self._order = self._toposort(outputs)
        self._single = len(outputs) == 1

    @staticmethod
    def _toposort(outputs: List[DAGNode]) -> List[ClassMethodNode]:
        order: List[ClassMethodNode] = []
        seen = set()

        def visit(node):
            if isinstance(node, MultiOutputNode):
                for o in node.outputs:
                    visit(o)
                return
            if isinstance(node, FunctionNode):
                raise TypeError(
                    "task .bind nodes run via ray_tpu.workflow, not compiled "
                    "actor DAGs; wrap the function in an actor (or call it "
                    "with .remote and pass the ObjectRef)")
            if not isinstance(node, ClassMethodNode) or id(node) in seen:
                return
            seen.add(id(node))
            for a in list(node.args) + list(node.kwargs.values()):
                visit(a)
            order.append(node)

        for out in outputs:
            visit(out)
        if not order:
            raise ValueError("DAG has no actor-method nodes; bind at least "
                             "one actor.method.bind(...)")
        return order

    def execute(self, *args, **kwargs):
        """Submit the whole pipeline; returns the leaf ObjectRef (or a list
        for MultiOutputNode). Call repeatedly without waiting to PIPELINE:
        each actor processes its calls in order, so consecutive executions
        overlap across stages."""
        if len(args) == 1 and not kwargs:
            dag_input = args[0]
        elif not args and kwargs:
            dag_input = kwargs
        else:
            dag_input = args
        produced: Dict[int, Any] = {}

        def encode(v):
            if isinstance(v, ClassMethodNode):
                return produced[id(v)]
            if isinstance(v, InputNode):
                return v._resolve(dag_input)
            if isinstance(v, DAGNode):  # a node kind execute can't compute
                raise TypeError(f"unsupported DAG node as argument: {v!r}")
            return v

        for node in self._order:
            call_args = tuple(encode(a) for a in node.args)
            call_kwargs = {k: encode(v) for k, v in node.kwargs.items()}
            method = getattr(node.actor, node.method_name)
            produced[id(node)] = method.remote(*call_args, **call_kwargs)
        def leaf(o):
            if isinstance(o, ClassMethodNode):
                return produced[id(o)]
            if isinstance(o, MultiOutputNode):
                return [leaf(x) for x in o.outputs]
            if isinstance(o, InputNode):
                return o._resolve(dag_input)
            raise TypeError(f"unsupported DAG output node: {o!r}")

        refs = [leaf(o) for o in self.outputs]
        return refs[0] if self._single else refs

    async def execute_async(self, *args, **kwargs):
        """Reference execute_async parity: awaitable leaf value(s)."""
        out = self.execute(*args, **kwargs)
        if self._single:
            return await out
        import asyncio
        return await asyncio.gather(*out)

    def teardown(self):
        """Reference parity no-op: nothing persistent to tear down — the
        pipeline holds only actor handles."""


__all__ = ["InputNode", "ClassMethodNode", "MultiOutputNode", "CompiledDag",
           "DAGNode", "bind_method"]
