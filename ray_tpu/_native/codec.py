"""Frame codec: packed fixed-layout encoding for high-frequency control
frames (src/frame_codec.cpp holds the native scanner; this module owns the
layouts).

The pipelined control plane ships almost all hot traffic as multi-entry
"batch" frames (client._DeltaFlusher -> controller._apply_batch): put
registrations, refcount deltas, task_done publications and pipelined
submits. This codec packs those frames as fixed-layout structs instead of
pickle:

  frame: u8 magic 0xC3 | u8 version 1 | u8 kind (1=batch, 2=exec) |
         u32 nentries | entry*
  entry: u8 opcode | u32 body_len | body

Kind 2 ("exec") is the scheduler's dispatch frame — exactly one OP_EXEC
entry carrying the TaskSpec, result oids and prefetched arg descriptors —
so the per-dispatch hot path skips pickle too (controller._dispatch sends
it codec-coded once the worker negotiated codec_ver > 0).

Pickle frames always begin 0x80 (protocol >= 2), so receivers sniff the
first byte — protocol.recv_msg/aread_msg route 0xC3 frames here and
everything else through pickle. Encoding is opportunistic: any entry the
fixed layouts can't express (exotic TaskSpec field types, oversized ids)
makes `encode` return None and the sender falls back to pickle for that
frame. Rare frame kinds (RPCs, replies, heartbeats) never come here.

Refcount runs get a special entry: consecutive incref/decref entries on
"obj-" ids pack into ONE "refdeltas" body whose byte layout is exactly what
the sharded directory's bulk od_apply_deltas consumes — the controller
hands the decoded body straight to the directory without materializing
per-id Python tuples (the decref-storm path).

Negotiation: register/register_node handshakes carry `codec_ver`; each side
uses min(its own wire_version(), the peer's). `RAY_TPU_NATIVE=0` forces
wire_version() to 0 — the all-pickle escape hatch (README, control plane).

Both implementations of the scan — the native fc_scan and the pure-Python
loop — produce/consume identical bytes; the golden tests pin the format
byte-for-byte against both.
"""

import ctypes
import os
import pickle
import struct
import subprocess
import threading
from typing import List, Optional, Tuple

from . import objdir

MAGIC = 0xC3
VERSION = 1
KIND_BATCH = 1
KIND_EXEC = 2   # dispatch frame: exactly one OP_EXEC entry

OP_REFDELTAS = 1
OP_PUT = 2
OP_ACTOR_INCREF = 3
OP_ACTOR_DECREF = 4
OP_OPEN_STREAM = 5
OP_CLOSE_STREAM = 6
OP_TASK_DONE = 7
OP_SUBMIT = 8
OP_INCREF_ONE = 9
OP_DECREF_ONE = 10
OP_EXEC = 11    # kind-2 frames only (batch frames stop at 10)

_HDR = struct.Struct("<BBBI")   # magic, version, kind, nentries
_ENT = struct.Struct("<BI")     # opcode, body_len
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "src", "frame_codec.cpp")
_BUILD_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_build")
_lock = threading.Lock()
_lib = None
_build_error: Optional[str] = None


def _compile() -> str:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    so = os.path.join(_BUILD_DIR, "libframe_codec.so")
    if os.path.exists(so) and os.path.getmtime(so) >= os.path.getmtime(_SRC):
        return so
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC,
           "-o", so + ".tmp"]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"frame_codec build failed: {proc.stderr[:2000]}")
    os.replace(so + ".tmp", so)
    return so


def _load():
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        try:
            lib = ctypes.CDLL(_compile())
        except Exception as e:  # noqa: BLE001 - fall back to the Python scan
            _build_error = str(e)
            return None
        lib.fc_version.restype = ctypes.c_int32
        lib.fc_validate.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.fc_validate.restype = ctypes.c_int64
        lib.fc_scan.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                ctypes.POINTER(ctypes.c_int64),
                                ctypes.c_int64]
        lib.fc_scan.restype = ctypes.c_int64
        lib.fc_validate_deltas.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.fc_validate_deltas.restype = ctypes.c_int64
        _lib = lib
        return _lib


def native_disabled() -> bool:
    return os.environ.get("RAY_TPU_NATIVE", "").lower() in ("0", "false", "no")


def native_available() -> bool:
    """True when the C scanner builds/loads (the wire format itself needs no
    toolchain — the Python scan speaks it identically)."""
    return _load() is not None


def wire_version() -> int:
    """Codec version this process is willing to speak on the wire. 0 means
    all-pickle (the RAY_TPU_NATIVE=0 escape hatch)."""
    return 0 if native_disabled() else VERSION


def negotiate(peer_ver) -> int:
    """Per-connection version: the min of both sides' wire_version()."""
    try:
        return min(wire_version(), int(peer_ver or 0))
    except (TypeError, ValueError):
        return 0


# ---------------------------------------------------------------- primitives

def _pstr(parts: list, s: str):
    raw = s.encode()
    if len(raw) > 0xFFFF:
        raise ValueError("string too long for u16 frame field")
    parts.append(_U16.pack(len(raw)))
    parts.append(raw)


def _pbytes_opt(parts: list, b):
    if b is None:
        parts.append(b"\x00")
    else:
        b = bytes(b)
        parts.append(b"\x01")
        parts.append(_U32.pack(len(b)))
        parts.append(b)


def _gstr(mv, pos: int) -> Tuple[str, int]:
    (n,) = _U16.unpack_from(mv, pos)
    pos += 2
    return bytes(mv[pos:pos + n]).decode(), pos + n


def _gbytes_opt(mv, pos: int):
    if mv[pos] == 0:
        return None, pos + 1
    (n,) = _U32.unpack_from(mv, pos + 1)
    pos += 5
    return bytes(mv[pos:pos + n]), pos + n


# ------------------------------------------------------------- entry bodies

def _enc_putlike(parts: list, oid, meta_len, size, inline, contained):
    """Shared body for put entries and task_done result tuples:
    str oid | u32 meta_len | u64 size | bytes? inline | u16 n | str* contained."""
    _pstr(parts, oid)
    parts.append(struct.pack("<IQ", meta_len, size))
    _pbytes_opt(parts, inline)
    contained = contained or []
    parts.append(_U16.pack(len(contained)))
    for c in contained:
        _pstr(parts, c)


def _dec_putlike(mv, pos: int):
    oid, pos = _gstr(mv, pos)
    meta_len, size = struct.unpack_from("<IQ", mv, pos)
    pos += 12
    inline, pos = _gbytes_opt(mv, pos)
    (n,) = _U16.unpack_from(mv, pos)
    pos += 2
    contained = []
    for _ in range(n):
        c, pos = _gstr(mv, pos)
        contained.append(c)
    return (oid, meta_len, size, inline, contained), pos


def _enc_spec(parts: list, spec) -> None:
    """TaskSpec fixed layout + a pickled `extras` dict for the rare fields.
    Raises on anything the layout can't express (caller falls back)."""
    _pstr(parts, spec.task_id)
    _pbytes_opt(parts, spec.fn_blob)
    args = spec.args or []
    parts.append(_U16.pack(len(args)))
    for kind, v in args:
        _enc_arg(parts, kind, v)
    kwargs = spec.kwargs or {}
    parts.append(_U16.pack(len(kwargs)))
    for k, (kind, v) in kwargs.items():
        _pstr(parts, k)
        _enc_arg(parts, kind, v)
    if spec.num_returns == "streaming":
        parts.append(b"\x01")
    else:
        parts.append(b"\x00" + struct.pack("<i", int(spec.num_returns)))
    res = spec.resources or {}
    if len(res) > 0xFF:
        raise ValueError("too many resource kinds")
    parts.append(struct.pack("<B", len(res)))
    for k, v in res.items():
        _pstr(parts, k)
        parts.append(struct.pack("<d", float(v)))
    if type(spec.retry_exceptions) is not bool:
        raise ValueError("non-bool retry_exceptions")  # rare: pickle path
    parts.append(struct.pack("<iB", int(spec.max_retries),
                             1 if spec.retry_exceptions else 0))
    _pstr(parts, spec.name or "")
    extras = {}
    for f, default in _SPEC_EXTRAS:
        v = getattr(spec, f)
        if v != default:
            extras[f] = v
    _pbytes_opt(parts, pickle.dumps(extras, protocol=5) if extras else None)


def _enc_arg(parts: list, kind, v):
    if kind == "v":
        b = bytes(v)
        parts.append(b"\x00" + _U32.pack(len(b)))
        parts.append(b)
    elif kind == "ref":
        parts.append(b"\x01")
        _pstr(parts, v)
    else:
        raise ValueError(f"unknown arg kind {kind!r}")


def _dec_arg(mv, pos: int):
    tag = mv[pos]
    pos += 1
    if tag == 0:
        (n,) = _U32.unpack_from(mv, pos)
        pos += 4
        return ("v", bytes(mv[pos:pos + n])), pos + n
    oid, pos = _gstr(mv, pos)
    return ("ref", oid), pos


# TaskSpec fields outside the fixed layout, shipped as a pickled dict only
# when they differ from their defaults (plain tasks pay ~1 byte).
_SPEC_EXTRAS = (
    ("actor_id", None), ("method_name", None), ("is_actor_creation", False),
    ("scheduling_strategy", None), ("placement_group_id", None),
    ("placement_group_bundle_index", -1), ("runtime_env", None),
    ("generator_backpressure", 0), ("parent_task_id", None), ("job_id", None),
    ("trace_id", None), ("parent_span_id", None), ("nested_refs", []),
    ("owner_id", None), ("owned_inline", None),
)


def _dec_spec(mv, pos: int):
    from ray_tpu._private.task_spec import TaskSpec
    task_id, pos = _gstr(mv, pos)
    fn_blob, pos = _gbytes_opt(mv, pos)
    (nargs,) = _U16.unpack_from(mv, pos)
    pos += 2
    args = []
    for _ in range(nargs):
        a, pos = _dec_arg(mv, pos)
        args.append(a)
    (nkw,) = _U16.unpack_from(mv, pos)
    pos += 2
    kwargs = {}
    for _ in range(nkw):
        k, pos = _gstr(mv, pos)
        a, pos = _dec_arg(mv, pos)
        kwargs[k] = a
    if mv[pos] == 1:
        num_returns = "streaming"
        pos += 1
    else:
        (num_returns,) = struct.unpack_from("<i", mv, pos + 1)
        pos += 5
    nres = mv[pos]
    pos += 1
    resources = {}
    for _ in range(nres):
        k, pos = _gstr(mv, pos)
        (v,) = struct.unpack_from("<d", mv, pos)
        pos += 8
        resources[k] = v
    max_retries, retry_exc = struct.unpack_from("<iB", mv, pos)
    pos += 5
    name, pos = _gstr(mv, pos)
    extras_blob, pos = _gbytes_opt(mv, pos)
    spec = TaskSpec(task_id=task_id, fn_blob=fn_blob, args=args, kwargs=kwargs,
                    num_returns=num_returns, resources=resources,
                    max_retries=max_retries, retry_exceptions=bool(retry_exc),
                    name=name)
    if extras_blob:
        for k, v in pickle.loads(extras_blob).items():
            setattr(spec, k, v)
    return spec, pos


def _enc_exec(parts: list, payload: dict) -> None:
    """Exec-frame body: spec | u16 n | str* result_oids | u8 has_descs |
    [u16 n | (str oid | u8 tag | inline bytes / u32 shm meta_len)*].
    Raises on desc kinds outside inline/shm (caller falls back to pickle)."""
    _enc_spec(parts, payload["spec"])
    oids = payload["result_oids"]
    parts.append(_U16.pack(len(oids)))
    for oid in oids:
        _pstr(parts, oid)
    descs = payload.get("arg_descs")
    if descs is None:
        parts.append(b"\x00")
        return
    parts.append(b"\x01")
    parts.append(_U16.pack(len(descs)))
    for oid, (kind, v) in descs.items():
        _pstr(parts, oid)
        if kind == "inline":
            b = bytes(v)
            parts.append(b"\x00" + _U32.pack(len(b)))
            parts.append(b)
        elif kind == "shm":
            parts.append(b"\x01" + _U32.pack(int(v)))
        else:
            raise ValueError(f"no exec layout for desc kind {kind!r}")


def _dec_exec(mv) -> dict:
    spec, pos = _dec_spec(mv, 0)
    (n,) = _U16.unpack_from(mv, pos)
    pos += 2
    oids = []
    for _ in range(n):
        oid, pos = _gstr(mv, pos)
        oids.append(oid)
    out = {"spec": spec, "result_oids": oids}
    has_descs = mv[pos]
    pos += 1
    if has_descs:
        (nd,) = _U16.unpack_from(mv, pos)
        pos += 2
        descs = {}
        for _ in range(nd):
            oid, pos = _gstr(mv, pos)
            tag = mv[pos]
            pos += 1
            if tag == 0:
                (ln,) = _U32.unpack_from(mv, pos)
                pos += 4
                descs[oid] = ("inline", bytes(mv[pos:pos + ln]))
                pos += ln
            else:
                (ml,) = _U32.unpack_from(mv, pos)
                pos += 4
                descs[oid] = ("shm", ml)
        out["arg_descs"] = descs
    return out


def _enc_entry(e) -> Tuple[int, bytes]:
    op = e[0]
    parts: list = []
    if op == "put":
        _enc_putlike(parts, e[1], e[2], e[3], e[4], e[5])
        return OP_PUT, b"".join(parts)
    if op == "task_done":
        _pstr(parts, e[1])
        results = e[2] or []
        parts.append(_U16.pack(len(results)))
        for r in results:
            _enc_putlike(parts, r[0], r[1], r[2], r[3],
                         r[4] if len(r) > 4 else None)
        error = e[3]
        _pbytes_opt(parts, pickle.dumps(error, protocol=5)
                    if error is not None else None)
        span = e[4] if len(e) > 4 else None
        _pbytes_opt(parts, pickle.dumps(span, protocol=5)
                    if span is not None else None)
        spans = e[5] if len(e) > 5 else None
        _pbytes_opt(parts, pickle.dumps(spans, protocol=5)
                    if spans else None)
        return OP_TASK_DONE, b"".join(parts)
    if op == "submit":
        _enc_spec(parts, e[1])
        oids = e[2]
        parts.append(_U16.pack(len(oids)))
        for oid in oids:
            _pstr(parts, oid)
        return OP_SUBMIT, b"".join(parts)
    if op == "refdeltas":
        return OP_REFDELTAS, bytes(e[1])
    single = {"actor_incref": OP_ACTOR_INCREF, "actor_decref": OP_ACTOR_DECREF,
              "open_stream": OP_OPEN_STREAM, "close_stream": OP_CLOSE_STREAM,
              "incref": OP_INCREF_ONE, "decref": OP_DECREF_ONE}.get(op)
    if single is None:
        raise ValueError(f"no fixed layout for batch entry {op!r}")
    _pstr(parts, e[1])
    return single, b"".join(parts)


def _dec_entry(opcode: int, body):
    mv = memoryview(body)
    if opcode == OP_REFDELTAS:
        return ("refdeltas", bytes(mv))
    if opcode == OP_PUT:
        (oid, meta_len, size, inline, contained), _ = _dec_putlike(mv, 0)
        return ("put", oid, meta_len, size, inline, contained)
    if opcode == OP_TASK_DONE:
        task_id, pos = _gstr(mv, 0)
        (n,) = _U16.unpack_from(mv, pos)
        pos += 2
        results = []
        for _ in range(n):
            r, pos = _dec_putlike(mv, pos)
            results.append(r)
        err_blob, pos = _gbytes_opt(mv, pos)
        span_blob, pos = _gbytes_opt(mv, pos)
        spans_blob, pos = _gbytes_opt(mv, pos)
        return ("task_done", task_id, results,
                pickle.loads(err_blob) if err_blob else None,
                pickle.loads(span_blob) if span_blob else None,
                pickle.loads(spans_blob) if spans_blob else None)
    if opcode == OP_SUBMIT:
        spec, pos = _dec_spec(mv, 0)
        (n,) = _U16.unpack_from(mv, pos)
        pos += 2
        oids = []
        for _ in range(n):
            oid, pos = _gstr(mv, pos)
            oids.append(oid)
        return ("submit", spec, oids)
    name = {OP_ACTOR_INCREF: "actor_incref", OP_ACTOR_DECREF: "actor_decref",
            OP_OPEN_STREAM: "open_stream", OP_CLOSE_STREAM: "close_stream",
            OP_INCREF_ONE: "incref", OP_DECREF_ONE: "decref"}[opcode]
    sid, _ = _gstr(mv, 0)
    return (name, sid)


# ----------------------------------------------------------------- frame API

def fold_refdeltas(entries):
    """Collapse consecutive incref/decref entries on plain object ids into
    packed ("refdeltas", bytes) entries — order among entries is preserved,
    so put-before-decref still holds. Used by the wire encoder AND by the
    driver's local batch post, so the controller's bulk directory path runs
    for both transports."""
    out = []
    run = []
    for e in entries:
        op = e[0]
        if op in ("incref", "decref") and e[1].startswith("obj-"):
            run.append((objdir.INCREF if op == "incref" else objdir.DECREF,
                        e[1]))
            continue
        if run:
            out.append(("refdeltas", objdir.pack_deltas(run)))
            run = []
        out.append(e)
    if run:
        out.append(("refdeltas", objdir.pack_deltas(run)))
    return out


def encode(kind: str, payload: dict) -> Optional[bytes]:
    """Encode a frame, or None when `kind`/payload has no fixed layout (the
    sender then pickles — the negotiated fallback)."""
    if kind == "exec":
        if not ({"spec", "result_oids"} <= set(payload)
                <= {"spec", "result_oids", "arg_descs"}):
            return None
        try:
            body_parts: list = []
            _enc_exec(body_parts, payload)
            body = b"".join(body_parts)
            return b"".join([_HDR.pack(MAGIC, VERSION, KIND_EXEC, 1),
                             _ENT.pack(OP_EXEC, len(body)), body])
        except Exception:  # noqa: BLE001 - opportunistic: odd specs pickle
            return None
    if kind != "batch" or set(payload) != {"entries"}:
        return None
    try:
        entries = fold_refdeltas(payload["entries"])
        parts = [_HDR.pack(MAGIC, VERSION, KIND_BATCH, len(entries))]
        for e in entries:
            opcode, body = _enc_entry(e)
            parts.append(_ENT.pack(opcode, len(body)))
            parts.append(body)
        return b"".join(parts)
    except Exception:  # noqa: BLE001 - opportunistic: odd payloads pickle
        return None


def _scan_py(data) -> List[Tuple[int, int, int]]:
    mv = memoryview(data)
    if len(mv) < 7 or mv[0] != MAGIC:
        raise ValueError("not a codec frame")
    if mv[1] != VERSION:
        raise ValueError(f"unsupported codec version {mv[1]}")
    kind = mv[2]
    if kind not in (KIND_BATCH, KIND_EXEC):
        raise ValueError(f"unknown codec frame kind {kind}")
    (n,) = _U32.unpack_from(mv, 3)
    if kind == KIND_EXEC and n != 1:
        raise ValueError("malformed codec frame")
    pos = 7
    out = []
    for _ in range(n):
        if pos + 5 > len(mv):
            raise ValueError("malformed codec frame")
        opcode, blen = _ENT.unpack_from(mv, pos)
        pos += 5
        op_ok = (1 <= opcode <= OP_DECREF_ONE if kind == KIND_BATCH
                 else opcode == OP_EXEC)
        if not op_ok or pos + blen > len(mv):
            raise ValueError("malformed codec frame")
        out.append((opcode, pos, blen))
        pos += blen
    if pos != len(mv):
        raise ValueError("malformed codec frame")
    return out


def _scan_native(lib, data) -> List[Tuple[int, int, int]]:
    if len(data) < 7 or data[0] != MAGIC:
        raise ValueError("not a codec frame")
    (n,) = _U32.unpack_from(data, 3)
    # bound the result allocation by what the frame could possibly hold
    # (>=5 bytes per entry) BEFORE trusting n — a lying header must not
    # drive a multi-GB ctypes array
    if n > (len(data) - 7) // 5:
        raise ValueError("malformed codec frame")
    arr = (ctypes.c_int64 * (3 * max(n, 1)))()
    r = lib.fc_scan(bytes(data), len(data), arr, n)
    if r < 0:
        raise ValueError(f"malformed codec frame (fc_scan {r})")
    return [(arr[i * 3], arr[i * 3 + 1], arr[i * 3 + 2]) for i in range(r)]


def decode(data):
    """Decode a 0xC3 frame into the same (kind, payload) shape pickle
    produces. Works with or without the native scanner (RAY_TPU_NATIVE=0
    disables the C library but a peer may still be mid-handshake — decoding
    stays available so no frame is ever dropped)."""
    data = bytes(data)
    lib = None if native_disabled() else _load()
    items = _scan_native(lib, data) if lib is not None else _scan_py(data)
    mv = memoryview(data)
    if data[2] == KIND_EXEC:
        op, off, ln = items[0]
        return ("exec", _dec_exec(mv[off:off + ln]))
    entries = [_dec_entry(op, mv[off:off + ln]) for op, off, ln in items]
    return ("batch", {"entries": entries})


def is_codec_frame(data) -> bool:
    return len(data) > 0 and data[0] == MAGIC
