"""ctypes binding for the C++ scheduler ready-queue (src/sched_queue.cpp).

`ReadyQueue` is the controller-facing API: tasks are pushed with a
scheduling signature (pool, resource demand), `next_dispatchable()` returns
the earliest task whose demand fits its pool (optionally masked by
signature), and claims/releases keep the C++ pool mirror in sync with the
controller's dict accounting. `PyReadyQueue` is the semantically identical
pure-Python fallback used when the toolchain is unavailable (and as the
oracle in the equivalence tests).

Build: on-demand g++, cached next to the source keyed by mtime — same
recipe as the shm store binding (_native/store.py).
"""

import ctypes
import os
import subprocess
import threading
from typing import Dict, List, Optional, Tuple

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "src", "sched_queue.cpp")
_BUILD_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_build")
_lock = threading.Lock()
_lib = None
_build_error: Optional[str] = None


def _compile() -> str:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    so = os.path.join(_BUILD_DIR, "libsched_queue.so")
    if os.path.exists(so) and os.path.getmtime(so) >= os.path.getmtime(_SRC):
        return so
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC,
           "-o", so + ".tmp"]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"sched_queue build failed: {proc.stderr[:2000]}")
    os.replace(so + ".tmp", so)
    return so


def _load():
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        try:
            lib = ctypes.CDLL(_compile())
        except Exception as e:  # noqa: BLE001 - fall back to Python queue
            _build_error = str(e)
            return None
        lib.sq_create.restype = ctypes.c_void_p
        lib.sq_destroy.argtypes = [ctypes.c_void_p]
        lib.sq_set_pool.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                    ctypes.POINTER(ctypes.c_int32),
                                    ctypes.POINTER(ctypes.c_double),
                                    ctypes.c_int32]
        lib.sq_remove_pool.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.sq_adjust.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                  ctypes.c_int32, ctypes.c_double]
        lib.sq_register_sig.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                        ctypes.POINTER(ctypes.c_int32),
                                        ctypes.POINTER(ctypes.c_double),
                                        ctypes.c_int32]
        lib.sq_register_sig.restype = ctypes.c_int32
        lib.sq_retire_sig.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.sq_push.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32]
        lib.sq_remove.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.sq_pending.argtypes = [ctypes.c_void_p]
        lib.sq_pending.restype = ctypes.c_int64
        lib.sq_pending_sig.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.sq_pending_sig.restype = ctypes.c_int64
        lib.sq_next.argtypes = [ctypes.c_void_p,
                                ctypes.POINTER(ctypes.c_uint8),
                                ctypes.c_int32,
                                ctypes.POINTER(ctypes.c_int32)]
        lib.sq_next.restype = ctypes.c_int64
        lib.sq_schedule.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_uint8),
                                    ctypes.POINTER(ctypes.c_int32),
                                    ctypes.c_int32,
                                    ctypes.POINTER(ctypes.c_int32),
                                    ctypes.c_int32,
                                    ctypes.POINTER(ctypes.c_int64),
                                    ctypes.POINTER(ctypes.c_int32),
                                    ctypes.c_int32,
                                    ctypes.POINTER(ctypes.c_int64)]
        lib.sq_schedule.restype = ctypes.c_int64
        lib.sq_pop_task.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.sq_pool_avail.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                      ctypes.c_int32]
        lib.sq_pool_avail.restype = ctypes.c_double
        _lib = lib
        return _lib


def _vecs(demand: Dict[int, float]):
    n = len(demand)
    rids = (ctypes.c_int32 * n)(*demand.keys())
    amts = (ctypes.c_double * n)(*demand.values())
    return rids, amts, n


class ReadyQueue:
    """C++-backed signature-bucketed ready queue."""

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native sched_queue unavailable: {_build_error}")
        self._lib = lib
        self._h = lib.sq_create()
        self._interned: Dict[str, int] = {}

    def close(self):
        if self._h is not None:
            self._lib.sq_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass

    # -- resource-name interning (C side works on int32 ids) ----------------
    def rid(self, name: str) -> int:
        if name not in self._interned:
            self._interned[name] = len(self._interned)
        return self._interned[name]

    def _demand_ids(self, need: Dict[str, float]) -> Dict[int, float]:
        return {self.rid(k): float(v) for k, v in need.items()}

    # -- pools --------------------------------------------------------------
    def set_pool(self, pool_id: int, avail: Dict[str, float]):
        rids, amts, n = _vecs(self._demand_ids(avail))
        self._lib.sq_set_pool(self._h, pool_id, rids, amts, n)

    def remove_pool(self, pool_id: int):
        self._lib.sq_remove_pool(self._h, pool_id)

    def adjust(self, pool_id: int, need: Dict[str, float], sign: float):
        for rid, amt in self._demand_ids(need).items():
            self._lib.sq_adjust(self._h, pool_id, rid, sign * amt)

    def pool_avail(self, pool_id: int, resource: str) -> float:
        return self._lib.sq_pool_avail(self._h, pool_id, self.rid(resource))

    # -- signatures / tasks -------------------------------------------------
    def register_sig(self, pool_id: int, need: Dict[str, float]) -> int:
        rids, amts, n = _vecs(self._demand_ids(need))
        return self._lib.sq_register_sig(self._h, pool_id, rids, amts, n)

    def retire_sig(self, sig_id: int):
        self._lib.sq_retire_sig(self._h, sig_id)

    def push(self, task_seq: int, sig_id: int):
        self._lib.sq_push(self._h, task_seq, sig_id)

    def remove(self, task_seq: int):
        self._lib.sq_remove(self._h, task_seq)

    def pending(self) -> int:
        return self._lib.sq_pending(self._h)

    def pending_sig(self, sig_id: int) -> int:
        return self._lib.sq_pending_sig(self._h, sig_id)

    def next_dispatchable(self, sig_mask: Optional[List[bool]] = None
                          ) -> Tuple[int, int]:
        """(task_seq, sig_id) of the earliest fitting task, or (-1, -1)."""
        out_sig = ctypes.c_int32(-1)
        if sig_mask is None:
            seq = self._lib.sq_next(self._h, None, 0, ctypes.byref(out_sig))
        else:
            mask = (ctypes.c_uint8 * len(sig_mask))(*[1 if m else 0
                                                      for m in sig_mask])
            seq = self._lib.sq_next(self._h, mask, len(sig_mask),
                                    ctypes.byref(out_sig))
        return seq, out_sig.value

    def pop_task(self, task_seq: int):
        self._lib.sq_pop_task(self._h, task_seq)

    def schedule_batch(self, sig_modes: List[int], sig_buckets: List[int],
                       bucket_idle: List[int], max_out: int = 1024
                       ) -> Tuple[List[Tuple[int, int]], int, int]:
        """Batched scheduling pass under a single GIL release.

        sig_modes[i]: 0 skip, 1 plain (needs idle worker in its bucket),
        2 python-handled barrier (actor creation). sig_buckets[i] indexes
        bucket_idle (idle-worker count per (tpu, env) class; -1 for mode 2).
        Pops + claims every decision natively. Returns
        (decisions [(seq, sig), ...], barrier_sig, barrier_seq) where
        barrier_sig == -1 means the pass ran to exhaustion.
        """
        n = len(sig_modes)
        modes = (ctypes.c_uint8 * n)(*sig_modes)
        buckets = (ctypes.c_int32 * n)(*sig_buckets)
        nb = len(bucket_idle)
        idle = (ctypes.c_int32 * max(nb, 1))(*bucket_idle)
        out_seqs = (ctypes.c_int64 * max_out)()
        out_sigs = (ctypes.c_int32 * max_out)()
        barrier = (ctypes.c_int64 * 2)(-1, -1)
        cnt = self._lib.sq_schedule(self._h, modes, buckets, n, idle, nb,
                                    out_seqs, out_sigs, max_out, barrier)
        decisions = [(out_seqs[i], out_sigs[i]) for i in range(cnt)]
        return decisions, int(barrier[0]), int(barrier[1])


class PyReadyQueue:
    """Pure-Python mirror of ReadyQueue (fallback + test oracle)."""

    _EPS = 1e-9

    def __init__(self):
        self._pools: Dict[int, Dict[str, float]] = {}
        self._sigs: List[Tuple[int, Dict[str, float], List[int]]] = []
        self._free_sigs: List[int] = []
        self._live: Dict[int, int] = {}   # sig -> live count
        self._alive: Dict[int, int] = {}  # seq -> sig

    def close(self):
        pass

    def rid(self, name: str) -> int:  # parity no-op
        return 0

    def set_pool(self, pool_id, avail):
        self._pools[pool_id] = dict(avail)

    def remove_pool(self, pool_id):
        self._pools.pop(pool_id, None)

    def adjust(self, pool_id, need, sign):
        pool = self._pools.setdefault(pool_id, {})
        for k, v in need.items():
            pool[k] = pool.get(k, 0.0) + sign * float(v)

    def pool_avail(self, pool_id, resource):
        return self._pools.get(pool_id, {}).get(resource, 0.0)

    def register_sig(self, pool_id, need):
        if self._free_sigs:
            sig = self._free_sigs.pop()
            self._sigs[sig] = (pool_id, dict(need), [])
        else:
            self._sigs.append((pool_id, dict(need), []))
            sig = len(self._sigs) - 1
        self._live[sig] = 0
        return sig

    def retire_sig(self, sig_id):
        for seq in self._sigs[sig_id][2]:
            self._alive.pop(seq, None)
        self._sigs[sig_id] = (self._sigs[sig_id][0], {}, [])
        self._live[sig_id] = 0
        self._free_sigs.append(sig_id)

    def push(self, task_seq, sig_id):
        self._sigs[sig_id][2].append(task_seq)
        self._alive[task_seq] = sig_id
        self._live[sig_id] += 1

    def remove(self, task_seq):
        sig = self._alive.pop(task_seq, None)
        if sig is not None:
            self._live[sig] -= 1

    def pending(self):
        return len(self._alive)

    def pending_sig(self, sig_id):
        return self._live.get(sig_id, 0)

    def _fits(self, pool_id, need):
        # absent pool -> never fits (MUST match sq_next's pools.find skip,
        # even for zero-demand signatures)
        pool = self._pools.get(pool_id)
        if pool is None:
            return False
        return all(pool.get(k, 0.0) + self._EPS >= v for k, v in need.items())

    def next_dispatchable(self, sig_mask=None):
        best = (-1, -1)
        for i, (pool_id, need, fifo) in enumerate(self._sigs):
            if sig_mask is not None and i < len(sig_mask) and not sig_mask[i]:
                continue
            while fifo and fifo[0] not in self._alive:
                fifo.pop(0)
            if not fifo:
                continue
            if best[0] != -1 and fifo[0] >= best[0]:
                continue
            if self._fits(pool_id, need):
                best = (fifo[0], i)
        return best

    def pop_task(self, task_seq):
        sig = self._alive.pop(task_seq, None)
        if sig is not None:
            self._live[sig] -= 1
            try:
                self._sigs[sig][2].remove(task_seq)
            except ValueError:
                pass

    def schedule_batch(self, sig_modes, sig_buckets, bucket_idle,
                       max_out=1024):
        # semantically identical to sq_schedule (see ReadyQueue) — the
        # randomized equivalence tests drive both with the same sequences
        idle = list(bucket_idle)
        decisions = []
        while len(decisions) < max_out:
            best_seq, best_sig = -1, -1
            for i, (pool_id, need, fifo) in enumerate(self._sigs):
                if i >= len(sig_modes):
                    break
                mode = sig_modes[i]
                if not mode:
                    continue
                while fifo and fifo[0] not in self._alive:
                    fifo.pop(0)
                if not fifo:
                    continue
                if best_seq != -1 and fifo[0] >= best_seq:
                    continue
                if mode == 1:
                    b = sig_buckets[i]
                    if b < 0 or b >= len(idle) or idle[b] <= 0:
                        continue
                if not self._fits(pool_id, need):
                    continue
                best_seq, best_sig = fifo[0], i
            if best_seq == -1:
                return decisions, -1, -1
            if sig_modes[best_sig] == 2:
                return decisions, best_sig, best_seq
            pool_id, need, fifo = self._sigs[best_sig]
            fifo.pop(0)
            self._alive.pop(best_seq, None)
            self._live[best_sig] -= 1
            pool = self._pools.setdefault(pool_id, {})
            for k, v in need.items():
                pool[k] = pool.get(k, 0.0) - float(v)
            idle[sig_buckets[best_sig]] -= 1
            decisions.append((best_seq, best_sig))
        return decisions, -1, -1


def make_ready_queue():
    """ReadyQueue if the native build works, else PyReadyQueue."""
    if os.environ.get("RAY_TPU_NO_NATIVE_SCHEDQ"):
        return PyReadyQueue()
    try:
        return ReadyQueue()
    except RuntimeError:
        return PyReadyQueue()
