"""ctypes binding for the id-sharded object/actor directory
(src/obj_directory.cpp).

`ObjectDirectory` holds the counter state of the control plane — refcount,
pin count, size, location, holder set — keyed by id-hash shard with a lock
per shard, so heartbeat holds-object updates, prefetch location lookups and
decref storms stop serializing on one GIL-bound dict. The controller's
ObjectMeta delegates its counter fields here (task_spec.py); the rich Python
state (inline bytes, errors, asyncio events) stays on the meta.

`apply_deltas` consumes a packed incref/decref run — the same byte layout
the frame codec ships as a "refdeltas" batch entry — in one GIL-releasing
call and reports which ids were newly released / became evictable.

`PyObjectDirectory` is the semantically identical pure-Python fallback used
when the toolchain is unavailable (and as the oracle in the equivalence
tests, tests/test_objdir.py). Build: on-demand g++ cached next to the
source keyed by mtime — same recipe as the sched-queue binding.
"""

import ctypes
import os
import struct
import subprocess
import threading
from typing import Dict, List, Optional, Tuple

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "src", "obj_directory.cpp")
_BUILD_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_build")
_lock = threading.Lock()
_lib = None        # PyDLL handle: scalar ops, GIL held
_bulk_lib = None   # CDLL handle: bulk ops, GIL released
_build_error: Optional[str] = None

NUM_SHARDS = int(os.environ.get("RAY_TPU_OBJDIR_SHARDS", "16"))

_MISSING_I64 = -(1 << 63)
_MISSING_I32 = -(1 << 31)

# location string <-> (code, node) mapping; code 6 round-trips any string
# this module doesn't know about (forward compatibility)
_LOC_CODES = {"pending": 0, "shm": 1, "inline": 2, "spilled": 3, "error": 4}
_LOC_NAMES = {v: k for k, v in _LOC_CODES.items()}

INCREF = 1
DECREF = 2
F_RELEASED = 1   # apply_deltas flag: refcount first crossed to <= 0
F_EVICTABLE = 2  # apply_deltas flag: refcount <= 0 and pinned == 0


def _loc_to_pair(location: str) -> Tuple[int, str]:
    code = _LOC_CODES.get(location)
    if code is not None:
        return code, ""
    if location.startswith("remote:"):
        return 5, location.split(":", 1)[1]
    return 6, location


def _pair_to_loc(code: int, node: str) -> str:
    if code == 5:
        return f"remote:{node}"
    if code == 6:
        return node
    return _LOC_NAMES.get(code, "pending")


def pack_deltas(ops) -> bytes:
    """Pack (op, id) pairs — op INCREF/DECREF — into the shared delta-run
    byte layout: repeat{ u8 op | u16 idlen LE | id utf8 }."""
    parts = []
    for op, oid in ops:
        raw = oid.encode()
        parts.append(struct.pack("<BH", op, len(raw)))
        parts.append(raw)
    return b"".join(parts)


def unpack_delta_result(buf) -> List[Tuple[str, int, int]]:
    """Inverse of apply_deltas' output: [(id, flags, final_refcount), ...] —
    one record per touched id so callers can sync mirror caches in the same
    pass that collects eviction verdicts."""
    out = []
    pos = 0
    mv = memoryview(buf)
    while pos < len(mv):
        flags, rc, n = struct.unpack_from("<BqH", mv, pos)
        pos += 11
        out.append((bytes(mv[pos:pos + n]).decode(), flags, rc))
        pos += n
    return out


def _compile() -> str:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    so = os.path.join(_BUILD_DIR, "libobj_directory.so")
    if os.path.exists(so) and os.path.getmtime(so) >= os.path.getmtime(_SRC):
        return so
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC,
           "-o", so + ".tmp"]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"obj_directory build failed: {proc.stderr[:2000]}")
    os.replace(so + ".tmp", so)
    return so


def _load():
    global _lib, _bulk_lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        try:
            so = _compile()
            # Two handles over the same .so: PyDLL keeps the GIL for the
            # sub-microsecond scalar ops (a GIL release per tiny call just
            # invites a thread switch on the controller loop's hot path);
            # CDLL releases it for the bulk ops (apply_deltas, snapshot,
            # drop_node) where other threads can do real work meanwhile.
            lib = ctypes.PyDLL(so)
            blib = ctypes.CDLL(so)
        except Exception as e:  # noqa: BLE001 - fall back to Python directory
            _build_error = str(e)
            return None
        c = ctypes
        blib.od_drop_node.argtypes = [c.c_void_p, c.c_char_p]
        blib.od_drop_node.restype = c.c_int64
        blib.od_apply_deltas.argtypes = [c.c_void_p, c.c_char_p, c.c_int64,
                                         c.c_char_p, c.c_int64]
        blib.od_apply_deltas.restype = c.c_int64
        blib.od_snapshot.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
        blib.od_snapshot.restype = c.c_int64
        _bulk_lib = blib
        lib.od_create.restype = c.c_void_p
        lib.od_create.argtypes = [c.c_int32]
        lib.od_destroy.argtypes = [c.c_void_p]
        lib.od_nshards.argtypes = [c.c_void_p]
        lib.od_nshards.restype = c.c_int32
        lib.od_register.argtypes = [c.c_void_p, c.c_char_p, c.c_int64,
                                    c.c_int32, c.c_int64, c.c_int32,
                                    c.c_char_p]
        for name in ("od_erase", "od_contains"):
            fn = getattr(lib, name)
            fn.argtypes = [c.c_void_p, c.c_char_p]
            fn.restype = c.c_int32
        lib.od_count.argtypes = [c.c_void_p]
        lib.od_count.restype = c.c_int64
        lib.od_shard_count.argtypes = [c.c_void_p, c.c_int32]
        lib.od_shard_count.restype = c.c_int64
        lib.od_total_bytes.argtypes = [c.c_void_p]
        lib.od_total_bytes.restype = c.c_int64
        lib.od_get_refcount.argtypes = [c.c_void_p, c.c_char_p]
        lib.od_get_refcount.restype = c.c_int64
        lib.od_set_refcount.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
        lib.od_add_refcount.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
        lib.od_add_refcount.restype = c.c_int64
        lib.od_get_pinned.argtypes = [c.c_void_p, c.c_char_p]
        lib.od_get_pinned.restype = c.c_int32
        lib.od_set_pinned.argtypes = [c.c_void_p, c.c_char_p, c.c_int32]
        lib.od_get_size.argtypes = [c.c_void_p, c.c_char_p]
        lib.od_get_size.restype = c.c_int64
        lib.od_set_size.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
        lib.od_set_location.argtypes = [c.c_void_p, c.c_char_p, c.c_int32,
                                        c.c_char_p]
        lib.od_get_location.argtypes = [c.c_void_p, c.c_char_p, c.c_char_p,
                                        c.c_int32]
        lib.od_get_location.restype = c.c_int32
        lib.od_add_holder.argtypes = [c.c_void_p, c.c_char_p, c.c_char_p]
        lib.od_add_holder.restype = c.c_int32
        lib.od_remove_holder.argtypes = [c.c_void_p, c.c_char_p, c.c_char_p]
        lib.od_remove_holder.restype = c.c_int32
        lib.od_clear_holders.argtypes = [c.c_void_p, c.c_char_p]
        lib.od_get_holders.argtypes = [c.c_void_p, c.c_char_p, c.c_char_p,
                                       c.c_int64]
        lib.od_get_holders.restype = c.c_int64
        _lib = lib
        return _lib


class ObjectDirectory:
    """C++-backed id-sharded directory."""

    def __init__(self, nshards: int = NUM_SHARDS):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native obj_directory unavailable: {_build_error}")
        self._lib = lib
        self._blib = _bulk_lib
        self._h = lib.od_create(nshards)
        self.nshards = lib.od_nshards(self._h)

    def close(self):
        if self._h is not None:
            self._lib.od_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass

    def register(self, oid: str, refcount: int = 1, pinned: int = 0,
                 size: int = 0, location: str = "pending"):
        code, node = _loc_to_pair(location)
        self._lib.od_register(self._h, oid.encode(), refcount, pinned, size,
                              code, node.encode())

    def erase(self, oid: str) -> bool:
        return bool(self._lib.od_erase(self._h, oid.encode()))

    def contains(self, oid: str) -> bool:
        return bool(self._lib.od_contains(self._h, oid.encode()))

    def count(self) -> int:
        return self._lib.od_count(self._h)

    def shard_count(self, i: int) -> int:
        return self._lib.od_shard_count(self._h, i)

    def total_bytes(self) -> int:
        return self._lib.od_total_bytes(self._h)

    def refcount(self, oid: str) -> Optional[int]:
        v = self._lib.od_get_refcount(self._h, oid.encode())
        return None if v == _MISSING_I64 else v

    def set_refcount(self, oid: str, v: int):
        self._lib.od_set_refcount(self._h, oid.encode(), v)

    def add_refcount(self, oid: str, delta: int) -> Optional[int]:
        v = self._lib.od_add_refcount(self._h, oid.encode(), delta)
        return None if v == _MISSING_I64 else v

    def pinned(self, oid: str) -> Optional[int]:
        v = self._lib.od_get_pinned(self._h, oid.encode())
        return None if v == _MISSING_I32 else v

    def set_pinned(self, oid: str, v: int):
        self._lib.od_set_pinned(self._h, oid.encode(), v)

    def size(self, oid: str) -> Optional[int]:
        v = self._lib.od_get_size(self._h, oid.encode())
        return None if v == _MISSING_I64 else v

    def set_size(self, oid: str, v: int):
        self._lib.od_set_size(self._h, oid.encode(), v)

    def set_location(self, oid: str, location: str):
        code, node = _loc_to_pair(location)
        self._lib.od_set_location(self._h, oid.encode(), code, node.encode())

    def location(self, oid: str) -> Optional[str]:
        buf = ctypes.create_string_buffer(512)
        r = self._lib.od_get_location(self._h, oid.encode(), buf, 512)
        if r < 0:
            return None
        code, n = r & 0xFF, r >> 8
        return _pair_to_loc(code, buf.raw[:n].decode())

    def add_holder(self, oid: str, node: str) -> bool:
        return bool(self._lib.od_add_holder(self._h, oid.encode(),
                                            node.encode()))

    def remove_holder(self, oid: str, node: str) -> bool:
        return bool(self._lib.od_remove_holder(self._h, oid.encode(),
                                               node.encode()))

    def clear_holders(self, oid: str):
        self._lib.od_clear_holders(self._h, oid.encode())

    def holders(self, oid: str) -> List[str]:
        cap = 1024
        while True:
            buf = ctypes.create_string_buffer(cap)
            r = self._lib.od_get_holders(self._h, oid.encode(), buf, cap)
            if r == -1:
                return []
            if r >= 0:
                if r == 0:
                    return []
                return buf.raw[:r].decode().split("\n")
            cap = -r  # -need - 1 => need + 1 bytes

    def drop_node(self, node: str) -> int:
        return self._blib.od_drop_node(self._h, node.encode())

    def apply_deltas(self, packed) -> List[Tuple[str, int, int]]:
        packed = bytes(packed)
        if not packed:
            return []
        # output records are 8 bytes wider than input records (the i64
        # final refcount rides along); min input record is 3 bytes
        cap = 4 * len(packed) + 16
        out = ctypes.create_string_buffer(cap)
        r = self._blib.od_apply_deltas(self._h, packed, len(packed), out, cap)
        if r == -1:
            raise ValueError("malformed delta run")
        if r == -2:  # can't happen given the cap above, but stay safe
            raise RuntimeError("delta result buffer too small")
        return unpack_delta_result(out.raw[:r])

    def snapshot(self) -> bytes:
        cap = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(cap)
            r = self._blib.od_snapshot(self._h, buf, cap)
            if r >= 0:
                return buf.raw[:r]
            cap = -r  # -need - 1


class _PyEntry:
    __slots__ = ("refcount", "pinned", "size", "loc", "loc_node", "holders",
                 "released")

    def __init__(self, refcount=1, pinned=0, size=0, loc=0, loc_node=""):
        self.refcount = refcount
        self.pinned = pinned
        self.size = size
        self.loc = loc
        self.loc_node = loc_node
        self.holders: List[str] = []
        self.released = 1 if refcount <= 0 else 0


class PyObjectDirectory:
    """Pure-Python mirror of ObjectDirectory (fallback + test oracle):
    same sharding, same per-shard locks, byte-identical snapshot()."""

    def __init__(self, nshards: int = NUM_SHARDS):
        self.nshards = max(nshards, 1)
        self._shards: List[Dict[str, _PyEntry]] = [
            {} for _ in range(self.nshards)]
        self._locks = [threading.Lock() for _ in range(self.nshards)]

    def close(self):
        pass

    @staticmethod
    def _fnv1a(raw: bytes) -> int:
        h = 1469598103934665603
        for b in raw:
            h = ((h ^ b) * 1099511628211) & 0xFFFFFFFFFFFFFFFF
        return h

    def _shard(self, oid: str):
        i = self._fnv1a(oid.encode()) % self.nshards
        return self._shards[i], self._locks[i]

    def register(self, oid, refcount=1, pinned=0, size=0, location="pending"):
        code, node = _loc_to_pair(location)
        m, lk = self._shard(oid)
        with lk:
            m[oid] = _PyEntry(refcount, pinned, size, code, node)

    def erase(self, oid) -> bool:
        m, lk = self._shard(oid)
        with lk:
            return m.pop(oid, None) is not None

    def contains(self, oid) -> bool:
        m, lk = self._shard(oid)
        with lk:
            return oid in m

    def count(self) -> int:
        return sum(len(m) for m in self._shards)

    def shard_count(self, i) -> int:
        if i < 0 or i >= self.nshards:
            return -1
        return len(self._shards[i])

    def total_bytes(self) -> int:
        total = 0
        for m, lk in zip(self._shards, self._locks):
            with lk:
                total += sum(e.size for e in m.values())
        return total

    def refcount(self, oid):
        m, lk = self._shard(oid)
        with lk:
            e = m.get(oid)
            return None if e is None else e.refcount

    def set_refcount(self, oid, v):
        m, lk = self._shard(oid)
        with lk:
            e = m.get(oid)
            if e is None:
                return
            if v <= 0 and e.refcount > 0:
                e.released = 1
            e.refcount = v

    def add_refcount(self, oid, delta):
        m, lk = self._shard(oid)
        with lk:
            e = m.get(oid)
            if e is None:
                return None
            if e.refcount > 0 and e.refcount + delta <= 0:
                e.released = 1
            e.refcount += delta
            return e.refcount

    def pinned(self, oid):
        m, lk = self._shard(oid)
        with lk:
            e = m.get(oid)
            return None if e is None else e.pinned

    def set_pinned(self, oid, v):
        m, lk = self._shard(oid)
        with lk:
            e = m.get(oid)
            if e is not None:
                e.pinned = v

    def size(self, oid):
        m, lk = self._shard(oid)
        with lk:
            e = m.get(oid)
            return None if e is None else e.size

    def set_size(self, oid, v):
        m, lk = self._shard(oid)
        with lk:
            e = m.get(oid)
            if e is not None:
                e.size = v

    def set_location(self, oid, location):
        code, node = _loc_to_pair(location)
        m, lk = self._shard(oid)
        with lk:
            e = m.get(oid)
            if e is not None:
                e.loc, e.loc_node = code, node

    def location(self, oid):
        m, lk = self._shard(oid)
        with lk:
            e = m.get(oid)
            if e is None:
                return None
            return _pair_to_loc(e.loc, e.loc_node)

    def add_holder(self, oid, node) -> bool:
        m, lk = self._shard(oid)
        with lk:
            e = m.get(oid)
            if e is None or node in e.holders:
                return False
            e.holders.append(node)
            return True

    def remove_holder(self, oid, node) -> bool:
        m, lk = self._shard(oid)
        with lk:
            e = m.get(oid)
            if e is None or node not in e.holders:
                return False
            e.holders.remove(node)
            return True

    def clear_holders(self, oid):
        m, lk = self._shard(oid)
        with lk:
            e = m.get(oid)
            if e is not None:
                e.holders = []

    def holders(self, oid):
        m, lk = self._shard(oid)
        with lk:
            e = m.get(oid)
            return [] if e is None else list(e.holders)

    def drop_node(self, node) -> int:
        touched = 0
        for m, lk in zip(self._shards, self._locks):
            with lk:
                for e in m.values():
                    if node in e.holders:
                        e.holders.remove(node)
                        touched += 1
        return touched

    def apply_deltas(self, packed):
        packed = bytes(packed)
        mv = memoryview(packed)
        order: List[str] = []
        touched: List[str] = []
        pos = 0
        while pos < len(mv):
            if pos + 3 > len(mv):
                raise ValueError("malformed delta run")
            op, idlen = struct.unpack_from("<BH", mv, pos)
            pos += 3
            if pos + idlen > len(mv) or op not in (INCREF, DECREF):
                raise ValueError("malformed delta run")
            oid = bytes(mv[pos:pos + idlen]).decode()
            pos += idlen
            m, lk = self._shard(oid)
            with lk:
                e = m.get(oid)
                if e is None:
                    continue
                delta = 1 if op == INCREF else -1
                was = e.released
                if e.refcount > 0 and e.refcount + delta <= 0:
                    e.released = 1
                e.refcount += delta
                if not was and e.released:
                    order.append(oid)
            touched.append(oid)
        newly = set(order)
        out = []
        seen = set()
        for oid in touched:
            if oid in seen:
                continue
            seen.add(oid)
            m, lk = self._shard(oid)
            with lk:
                e = m.get(oid)
                if e is None:
                    continue
                flags = 0
                if oid in newly:
                    flags |= F_RELEASED
                if e.refcount <= 0 and e.pinned == 0:
                    flags |= F_EVICTABLE
                out.append((oid, flags, e.refcount))
        return out

    def snapshot(self) -> bytes:
        all_entries = {}
        for m, lk in zip(self._shards, self._locks):
            with lk:
                all_entries.update(m)
        parts = []
        for oid in sorted(all_entries):
            e = all_entries[oid]
            raw = oid.encode()
            node = e.loc_node.encode()
            parts.append(struct.pack("<H", len(raw)))
            parts.append(raw)
            parts.append(struct.pack("<qiqBH", e.refcount, e.pinned, e.size,
                                     e.loc, len(node)))
            parts.append(node)
            hs = sorted(e.holders)
            parts.append(struct.pack("<BH", e.released, len(hs)))
            for hv in hs:
                hraw = hv.encode()
                parts.append(struct.pack("<H", len(hraw)))
                parts.append(hraw)
        return b"".join(parts)


def native_disabled() -> bool:
    return os.environ.get("RAY_TPU_NATIVE", "").lower() in ("0", "false", "no")


def available() -> bool:
    """True when the native directory builds/loads on this machine."""
    return _load() is not None


def make_object_directory(nshards: int = NUM_SHARDS):
    """ObjectDirectory if the native build works, else PyObjectDirectory.
    `RAY_TPU_NATIVE=0` forces the Python fallback (escape hatch documented
    in README's control-plane section)."""
    if native_disabled():
        return PyObjectDirectory(nshards)
    try:
        return ObjectDirectory(nshards)
    except RuntimeError:
        return PyObjectDirectory(nshards)


# Per-process singleton: ObjectMeta property accessors and the controller's
# bulk delta path must hit the SAME directory instance.
_dir = None
_dir_lock = threading.Lock()


def get_directory():
    global _dir
    if _dir is None:
        with _dir_lock:
            if _dir is None:
                _dir = make_object_directory()
    return _dir


def reset_directory():
    """Drop the process singleton (tests only — a fresh session must not see
    a directory populated by a previous one).

    The old instance must NOT be close()d here: a controller constructed
    earlier in the process keeps its own reference and would be left calling
    into a destroyed native handle. __del__ frees the handle once the last
    reference drops.
    """
    global _dir
    with _dir_lock:
        _dir = None
