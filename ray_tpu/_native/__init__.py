"""Native components (C++, ctypes-bound). Built on demand with g++; every
module here degrades gracefully to a pure-python fallback when the toolchain
is missing."""
