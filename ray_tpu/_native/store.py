"""ctypes binding for the C++ slab store (src/shm_store.cpp).

Build: on-demand `g++ -O2 -shared -fPIC`, cached next to the source keyed by
mtime. The arena is one POSIX shm segment; `SlabStore.view(offset, size)`
returns a zero-copy memoryview into it.
"""

import ctypes
import os
import subprocess
import threading
from typing import Optional

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "src", "shm_store.cpp")
_BUILD_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_build")
_lock = threading.Lock()
_lib = None
_build_error: Optional[str] = None


def _compile() -> Optional[str]:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    so = os.path.join(_BUILD_DIR, "libshm_store.so")
    if (os.path.exists(so)
            and os.path.getmtime(so) >= os.path.getmtime(_SRC)):
        return so
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC,
           "-o", so + ".tmp", "-lpthread", "-lrt"]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"shm_store build failed: {proc.stderr[:2000]}")
    os.replace(so + ".tmp", so)
    return so


def _load():
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        try:
            so = _compile()
            lib = ctypes.CDLL(so)
        except Exception as e:  # noqa: BLE001 - toolchain missing → fallback
            _build_error = str(e)
            return None
        lib.rt_store_open.restype = ctypes.c_void_p
        lib.rt_store_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                      ctypes.c_int]
        lib.rt_store_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.rt_store_alloc.restype = ctypes.c_int64
        lib.rt_store_alloc.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_uint64]
        lib.rt_store_lookup.restype = ctypes.c_int64
        lib.rt_store_lookup.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.POINTER(ctypes.c_uint64)]
        lib.rt_store_free.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rt_store_lookup_pin.restype = ctypes.c_int64
        lib.rt_store_lookup_pin.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                            ctypes.POINTER(ctypes.c_uint64)]
        lib.rt_store_unpin.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.rt_store_release_pins.restype = ctypes.c_int
        lib.rt_store_release_pins.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.rt_store_used.restype = ctypes.c_uint64
        lib.rt_store_used.argtypes = [ctypes.c_void_p]
        lib.rt_store_num_objects.restype = ctypes.c_uint64
        lib.rt_store_num_objects.argtypes = [ctypes.c_void_p]
        lib.rt_store_capacity.restype = ctypes.c_uint64
        lib.rt_store_capacity.argtypes = [ctypes.c_void_p]
        lib.rt_store_base.restype = ctypes.c_void_p
        lib.rt_store_base.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


class SlabStore:
    """One process's view of a shared arena."""

    def __init__(self, name: str, capacity: int = 0, create: bool = False):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native store unavailable: {_build_error}")
        self._lib = lib
        self.name = name
        self._h = lib.rt_store_open(name.encode(), capacity, 1 if create else 0)
        if not self._h:
            raise OSError(f"could not open shm arena {name!r}")
        self._base = lib.rt_store_base(self._h)

    # -- allocation ----------------------------------------------------------
    def alloc(self, key: str, size: int) -> int:
        off = self._lib.rt_store_alloc(self._h, key.encode(), size)
        if off < 0:
            raise MemoryError(
                f"arena full allocating {size} bytes for {key} "
                f"(used {self.used()}/{self.capacity()})")
        return off

    def lookup(self, key: str):
        size = ctypes.c_uint64()
        off = self._lib.rt_store_lookup(self._h, key.encode(),
                                        ctypes.byref(size))
        if off < 0:
            return None
        return off, size.value

    def free(self, key: str) -> bool:
        return self._lib.rt_store_free(self._h, key.encode()) == 0

    def lookup_pin(self, key: str):
        """Atomically look up AND pin: the block's memory stays valid (even
        across free) until the matching `unpin(offset)`."""
        size = ctypes.c_uint64()
        off = self._lib.rt_store_lookup_pin(self._h, key.encode(),
                                            ctypes.byref(size))
        if off < 0:
            return None
        return off, size.value

    def unpin(self, offset: int) -> None:
        if self._h:
            self._lib.rt_store_unpin(self._h, offset)

    def release_pins(self, pid: int) -> int:
        """Drop every pin held by `pid` (plasma disconnect-cleanup parity);
        returns how many were released."""
        if self._h:
            return self._lib.rt_store_release_pins(self._h, pid)
        return 0

    # -- zero-copy access ----------------------------------------------------
    def view(self, offset: int, size: int) -> memoryview:
        buf = (ctypes.c_ubyte * size).from_address(self._base + offset)
        return memoryview(buf).cast("B")

    def write(self, offset: int, data) -> None:
        mv = self.view(offset, len(data) if hasattr(data, "__len__")
                       else data.nbytes)
        mv[:] = data

    # -- stats ---------------------------------------------------------------
    def used(self) -> int:
        return self._lib.rt_store_used(self._h)

    def num_objects(self) -> int:
        return self._lib.rt_store_num_objects(self._h)

    def capacity(self) -> int:
        return self._lib.rt_store_capacity(self._h)

    def close(self, unlink: bool = False):
        if self._h:
            self._lib.rt_store_close(self._h, 1 if unlink else 0)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass
