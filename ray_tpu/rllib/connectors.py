"""Connectors — batch post/pre-processing between env runners and learner
(reference: rllib/connectors; GAE in rllib/evaluation/postprocessing.py).

The advantage math runs as a jitted scan over the time axis (ops.losses.gae
handles [T] and [T, B]) instead of the reference's per-episode python loops —
rollout batches keep static [T, B] shapes so nothing recompiles.
"""

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.ops.losses import gae as _gae
from . import sample_batch as SB
from .sample_batch import SampleBatch


@jax.jit
def _gae_jit(rewards, values_tb1, dones, gamma, lam):
    return _gae(rewards, values_tb1, dones, gamma, lam)


def compute_gae(batch: SampleBatch, gamma: float = 0.99,
                lam: float = 0.95) -> SampleBatch:
    """Add ADVANTAGES and VALUE_TARGETS to a [T, B] rollout batch.

    Needs VF_PREDS [T, B], BOOTSTRAP_VALUE [B] (value of the obs after the
    last step, zeroed where terminated), DONES [T, B].
    """
    rewards = jnp.asarray(batch[SB.REWARDS], jnp.float32)
    vf = jnp.asarray(batch[SB.VF_PREDS], jnp.float32)
    boot = jnp.asarray(batch[SB.BOOTSTRAP_VALUE], jnp.float32)
    dones = jnp.asarray(batch[SB.DONES], jnp.float32)
    values = jnp.concatenate([vf, boot[None]], axis=0)  # [T+1, B]
    adv, targets = _gae_jit(rewards, values, dones, gamma, lam)
    batch[SB.ADVANTAGES] = np.asarray(adv)
    batch[SB.VALUE_TARGETS] = np.asarray(targets)
    return batch


def standardize_advantages(batch: SampleBatch) -> SampleBatch:
    adv = np.asarray(batch[SB.ADVANTAGES])
    batch[SB.ADVANTAGES] = (adv - adv.mean()) / (adv.std() + 1e-8)
    return batch


class RunningMeanStd:
    """Streaming obs normalizer (reference: rllib MeanStdFilter)."""

    def __init__(self, shape):
        self.mean = np.zeros(shape, np.float64)
        self.var = np.ones(shape, np.float64)
        self.count = 1e-4

    def update(self, x: np.ndarray):
        x = x.reshape((-1,) + self.mean.shape)
        b_mean, b_var, b_count = x.mean(0), x.var(0), x.shape[0]
        delta = b_mean - self.mean
        tot = self.count + b_count
        self.mean = self.mean + delta * b_count / tot
        m_a = self.var * self.count
        m_b = b_var * b_count
        self.var = (m_a + m_b + np.square(delta) * self.count * b_count / tot) / tot
        self.count = tot

    def normalize(self, x: np.ndarray) -> np.ndarray:
        return ((x - self.mean) / np.sqrt(self.var + 1e-8)).astype(np.float32)

    def state(self):
        return {"mean": self.mean, "var": self.var, "count": self.count}

    def set_state(self, s):
        self.mean, self.var, self.count = s["mean"], s["var"], s["count"]
