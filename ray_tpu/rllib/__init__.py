"""ray_tpu.rllib — TPU-native RL library (reference: rllib/).

Stack: AlgorithmConfig/Algorithm drive iterations; EnvRunners collect CPU
rollouts (inline or as ray_tpu actors); a jax Learner runs the whole SGD
update as one jitted program on the TPU.
"""

from .algorithm import Algorithm, AlgorithmConfig
from .algorithms import (APPO, APPOConfig, BC, BCConfig, CQL, CQLConfig, DQN,
                         DQNConfig, DreamerV3, DreamerV3Config, IMPALA,
                         IMPALAConfig, IQL, IQLConfig, MARWIL, MARWILConfig,
                         PPO, PPOConfig, SAC, SACConfig, TQC, TQCConfig)
from .buffers import PrioritizedReplayBuffer, ReplayActor, ReplayBuffer
from .env_runner import EnvRunner
from .learner import JaxLearner, LearnerGroup, make_learner_group
from .rl_module import ModuleSpec, RLModule
from .sample_batch import SampleBatch
from .sebulba import (DeviceRollout, JaxCartPole, RolloutActor,
                      SebulbaPipeline)

__all__ = [
    "Algorithm", "AlgorithmConfig", "EnvRunner", "JaxLearner",
    "LearnerGroup", "ModuleSpec", "RLModule", "SampleBatch",
    "ReplayBuffer", "PrioritizedReplayBuffer", "ReplayActor",
    "SebulbaPipeline", "RolloutActor", "DeviceRollout", "JaxCartPole",
    "PPO", "PPOConfig", "APPO", "APPOConfig", "DQN", "DQNConfig",
    "IMPALA", "IMPALAConfig", "SAC", "SACConfig", "BC", "BCConfig",
    "MARWIL", "MARWILConfig", "CQL", "CQLConfig", "IQL", "IQLConfig",
    "TQC", "TQCConfig", "DreamerV3", "DreamerV3Config",
]
