"""ray_tpu.rllib — TPU-native RL library (reference: rllib/).

Stack: AlgorithmConfig/Algorithm drive iterations; EnvRunners collect CPU
rollouts (inline or as ray_tpu actors); a jax Learner runs the whole SGD
update as one jitted program on the TPU.
"""

from .algorithm import Algorithm, AlgorithmConfig
from .algorithms.ppo import PPO, PPOConfig
from .env_runner import EnvRunner
from .learner import JaxLearner, LearnerGroup
from .rl_module import ModuleSpec, RLModule
from .sample_batch import SampleBatch

__all__ = [
    "Algorithm", "AlgorithmConfig", "PPO", "PPOConfig", "EnvRunner",
    "JaxLearner", "LearnerGroup", "ModuleSpec", "RLModule", "SampleBatch",
]
