"""Jax Learner stack (reference: rllib/core/learner/learner.py +
torch_learner.py).

The TPU-native inversion of the reference design: instead of a torch module
wrapped in DDP with NCCL allreduce, a Learner owns params on the default
device (the TPU chip) and its whole update — loss, backward, optimizer — is
ONE jitted function with donated params/opt-state. Scaling out is a mesh
(`dp` axis) instead of extra learner processes: batches get a dp sharding and
XLA inserts the gradient psum.
"""

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from .rl_module import RLModule
from .sample_batch import SampleBatch


class JaxLearner:
    """Base learner: subclass and implement `compute_loss`."""

    def __init__(self, module: RLModule, config, mesh=None, seed: int = 0):
        import jax
        import optax

        self.module = module
        self.config = config
        self.mesh = mesh
        self._metrics_keys = None

        from ray_tpu.ops.optim import make_optimizer
        self.optimizer, self._lr_schedule = make_optimizer(
            lr=getattr(config, "lr", 3e-4),
            lr_schedule=getattr(config, "lr_schedule", None),
            optimizer=getattr(config, "optimizer", "adam"),
            grad_clip=getattr(config, "grad_clip", None),
            weight_decay=getattr(config, "weight_decay", 0.0))
        self._num_updates = 0

        self.params = self.module.init(jax.random.PRNGKey(seed))
        self.opt_state = self.optimizer.init(self.params)
        self._data_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            repl = NamedSharding(mesh, P())
            self.params = jax.device_put(self.params, repl)
            self.opt_state = jax.device_put(self.opt_state, repl)
            self._data_sharding = NamedSharding(mesh, P("dp"))

        def _update(params, opt_state, batch):
            def loss_fn(p):
                return self.compute_loss(p, batch)
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(lambda a, u: a + u, params, updates)
            metrics["total_loss"] = loss
            metrics["grad_norm"] = optax.global_norm(grads)
            return params, opt_state, metrics

        self._update = jax.jit(_update, donate_argnums=(0, 1))

    # -- to implement --------------------------------------------------------
    def compute_loss(self, params, batch) -> Tuple[Any, Dict]:
        raise NotImplementedError

    # -- update api ----------------------------------------------------------
    def update_once(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """One jitted SGD step on a (already minibatched) batch."""
        import jax
        if self._data_sharding is not None:
            dp = self.mesh.shape.get("dp", 1)
            rows = min(v.shape[0] for v in batch.values())
            if rows % dp:
                # dp sharding needs a divisible leading dim; drop the
                # remainder rows (reference drops ragged minibatches too)
                keep = rows - rows % dp
                if keep == 0:
                    return {}
                batch = {k: v[:keep] for k, v in batch.items()}
            batch = jax.device_put(batch, self._data_sharding)
        self.params, self.opt_state, metrics = self._update(
            self.params, self.opt_state, batch)
        metrics["cur_lr"] = float(self._lr_schedule(self._num_updates))
        self._num_updates += 1
        return metrics

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        """Full update (subclasses may do epochs/minibatches); returns host
        metrics averaged over SGD steps."""
        return _host_metrics([self.update_once(dict(batch))])

    def jit_cache_size(self) -> int:
        """Compiled-variant count of the jitted update — the recompile
        guard. Fixed-shape [T, B] batches (the contract env_runner.py
        documents) mean exactly ONE entry across a whole run; a second
        entry is a shape/dtype leak that silently recompiles on the hot
        path (sebulba asserts ==1 after every pipeline run)."""
        try:
            return int(self._update._cache_size())
        except Exception:  # noqa: BLE001 - private jax API moved
            return -1

    # -- weights -------------------------------------------------------------
    def get_weights(self):
        import jax
        return jax.device_get(self.params)

    def set_weights(self, params):
        import jax
        self.params = jax.device_put(params)
        self.opt_state = self.optimizer.init(self.params)

    def get_state(self):
        import jax
        return {"params": jax.device_get(self.params),
                "opt_state": jax.device_get(self.opt_state)}

    def set_state(self, state):
        import jax
        self.params = jax.device_put(state["params"])
        self.opt_state = jax.device_put(state["opt_state"])


def _host_metrics(steps) -> Dict[str, float]:
    import jax
    if not steps:
        return {}
    host = [jax.device_get(m) for m in steps]
    return {k: float(np.mean([m[k] for m in host])) for k in host[0]}


class LearnerGroup:
    """N logical learners (reference: rllib/core/learner/learner_group.py,
    which coordinates N learner workers with NCCL gradient allreduce).

    TPU-native inversion: N learners = N shards of the `dp` mesh axis inside
    ONE jitted update. Params/opt-state are replicated over the mesh, each
    batch is dp-sharded, and XLA inserts the gradient psum the reference
    does by hand — so the group IS the mesh, and "2 learners" computes
    bit-for-bit the same update as 1 learner on the concatenated batch
    (verified by tests/test_rllib_learner_group.py). Multi-host extends the
    same mesh over jax.distributed processes rather than adding RPC workers.
    """

    def __init__(self, learner: JaxLearner, num_learners: int = 1):
        self.learner = learner
        self.num_learners = max(num_learners, 1)

    @property
    def mesh(self):
        return self.learner.mesh

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        return self.learner.update(batch)

    # reference-API alias
    def update_from_batch(self, batch) -> Dict[str, float]:
        return self.update(batch)

    def foreach_learner(self, fn: Callable) -> list:
        """Reference parity: apply fn to each learner. All logical learners
        share one process/params here, so one call covers the group."""
        return [fn(self.learner)]

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, w):
        self.learner.set_weights(w)

    def get_state(self):
        return self.learner.get_state()

    def set_state(self, state):
        self.learner.set_state(state)


def make_learner_group(learner_cls, module: RLModule, config,
                       seed: int = 0) -> LearnerGroup:
    """Build a LearnerGroup from AlgorithmConfig.num_learners: 0/1 → a plain
    local learner; N>1 → one learner on a {'dp': N} mesh (each mesh shard is
    a 'learner'; grads psum over dp by XLA sharding propagation)."""
    n = max(getattr(config, "num_learners", 0), 1)
    mesh = None
    if n > 1:
        import jax

        from ..parallel.mesh import make_mesh
        if n > len(jax.devices()):
            raise ValueError(
                f"num_learners={n} but only {len(jax.devices())} devices "
                f"visible; a learner is a dp-mesh shard (set "
                f"XLA_FLAGS=--xla_force_host_platform_device_count for CPU "
                f"testing)")
        mesh = make_mesh({"dp": n}, devices=jax.devices()[:n])
    learner = learner_cls(module, config, mesh=mesh, seed=seed)
    return LearnerGroup(learner, num_learners=n)
