"""Multi-agent RL (reference: rllib/env/multi_agent_env.py:1-807 +
rllib/policy/policy_map.py + multi-agent episode handling).

Protocol: a MultiAgentEnv steps ALL live agents simultaneously with dict
observations/actions keyed by agent id (the reference's simultaneous-action
subset — turn-based envs can no-op absent agents). A policy_mapping_fn
assigns each agent to a policy id; "shared" vs "independent" learning are
just different mappings (all→one policy / one policy per agent).

TPU-native collection: per policy, the runner stacks that policy's agents
into one [T, k] rollout and runs ONE jitted explore_step per env step per
policy (agents of a policy are batch rows — no per-agent Python forward).
Training updates each policy's learner with its own [T, k] batch; under a
multi-learner group those updates ride the dp mesh like single-agent PPO.
"""

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from . import sample_batch as SB
from .rl_module import ModuleSpec, RLModule
from .sample_batch import SampleBatch


class MultiAgentEnv:
    """Base class (reference: ray.rllib.env.MultiAgentEnv).

    Subclasses define:
      possible_agents: list of agent ids
      observation_spaces / action_spaces: {agent_id: gymnasium.Space}
      reset(seed=None) -> (obs_dict, info_dict)
      step(action_dict) -> (obs, rewards, terminateds, truncateds, infos),
        each a per-agent dict; terminateds/truncateds carry "__all__".
    """

    possible_agents: List[str] = []
    observation_spaces: Dict[str, Any] = {}
    action_spaces: Dict[str, Any] = {}

    def reset(self, *, seed: Optional[int] = None
              ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        raise NotImplementedError

    def step(self, action_dict: Dict[str, Any]):
        raise NotImplementedError

    def get_observation_space(self, agent_id: str):
        return self.observation_spaces[agent_id]

    def get_action_space(self, agent_id: str):
        return self.action_spaces[agent_id]


class MultiAgentBatch:
    """{policy_id: SampleBatch([T, k])} + env step count (reference:
    rllib/policy/sample_batch.py MultiAgentBatch)."""

    def __init__(self, policy_batches: Dict[str, SampleBatch],
                 env_steps: int):
        self.policy_batches = policy_batches
        self._env_steps = env_steps

    def env_steps(self) -> int:
        return self._env_steps

    def agent_steps(self) -> int:
        return sum(b[SB.REWARDS].size for b in self.policy_batches.values())

    def __getitem__(self, policy_id: str) -> SampleBatch:
        return self.policy_batches[policy_id]

    def keys(self):
        return self.policy_batches.keys()


class MultiAgentEnvRunner:
    """Collects [T, k]-shaped per-policy rollouts from one MultiAgentEnv.

    All of a policy's agents are rows of one batched forward — the jitted
    explore_step runs once per policy per env step regardless of how many
    agents share it.
    """

    def __init__(self, env_creator: Callable[[], MultiAgentEnv], *,
                 policy_mapping_fn: Callable[[str], str],
                 modules: Dict[str, RLModule],
                 rollout_len: int = 200, explore: bool = True, seed: int = 0):
        self.env = env_creator()
        self.policy_mapping_fn = policy_mapping_fn
        self.modules = modules
        self.rollout_len = rollout_len
        self.explore = explore
        self._seed = seed
        self._step_count = 0
        self.agents = list(self.env.possible_agents)
        # stable agent order per policy → fixed batch rows, no recompiles
        self.policy_agents: Dict[str, List[str]] = {}
        for aid in self.agents:
            pid = policy_mapping_fn(aid)
            if pid not in modules:
                raise KeyError(f"policy_mapping_fn({aid!r}) -> {pid!r} not in "
                               f"policies {sorted(modules)}")
            self.policy_agents.setdefault(pid, []).append(aid)
        self._jit = {}
        self._obs: Optional[Dict[str, Any]] = None
        self._ep_return = 0.0
        self._ep_len = 0
        self._completed: List[Dict] = []

    def init_params(self) -> Dict[str, Any]:
        import jax
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            return {pid: jax.device_get(m.init(jax.random.PRNGKey(
                self._seed + i)))
                    for i, (pid, m) in enumerate(sorted(self.modules.items()))}

    def _ensure_jit(self):
        import jax
        if self._jit:
            return
        self._cpu = jax.local_devices(backend="cpu")[0]
        for pid, module in self.modules.items():
            def explore(params, obs, key, _m=module):
                return _m.explore_step(params, obs, key)

            def infer(params, obs, _m=module):
                a, v = _m.inference_step(params, obs)
                return a, np.zeros(1, np.float32), v

            def values(params, obs, _m=module):
                _, v = _m.forward(params, obs)
                return v

            self._jit[pid] = (
                jax.jit(explore if self.explore else
                        (lambda p, o, k, _i=infer: _i(p, o))),
                jax.jit(values))

    def _stack_obs(self, obs: Dict[str, Any], pid: str) -> np.ndarray:
        return np.stack([np.asarray(obs[a], np.float32)
                         for a in self.policy_agents[pid]])

    def sample(self, params_per_policy: Dict[str, Any]
               ) -> Tuple[MultiAgentBatch, Dict]:
        import jax
        self._ensure_jit()
        T = self.rollout_len
        if self._obs is None:
            self._obs, _ = self.env.reset(seed=self._seed)

        bufs = {}
        for pid, agents in self.policy_agents.items():
            k = len(agents)
            obs_shape = np.asarray(self._obs[agents[0]]).shape
            bufs[pid] = {
                SB.OBS: np.empty((T, k) + obs_shape, np.float32),
                SB.ACTIONS: None,
                SB.REWARDS: np.zeros((T, k), np.float32),
                SB.DONES: np.zeros((T, k), np.float32),
                "terms": np.zeros((T, k), np.float32),
                SB.LOGP: np.zeros((T, k), np.float32),
                SB.VF_PREDS: np.zeros((T, k), np.float32),
            }

        key = jax.random.PRNGKey(self._seed ^ 0x5eed)
        with jax.default_device(self._cpu):
            for t in range(T):
                self._step_count += 1
                k = jax.random.fold_in(key, self._step_count)
                action_dict = {}
                for pid, agents in self.policy_agents.items():
                    ob = self._stack_obs(self._obs, pid)
                    a, logp, v = self._jit[pid][0](params_per_policy[pid],
                                                   ob, k)
                    a = np.asarray(a)
                    b = bufs[pid]
                    if b[SB.ACTIONS] is None:
                        b[SB.ACTIONS] = np.empty((T,) + a.shape, a.dtype)
                    b[SB.OBS][t] = ob
                    b[SB.ACTIONS][t] = a
                    b[SB.LOGP][t] = np.asarray(logp)
                    b[SB.VF_PREDS][t] = np.asarray(v)
                    for i, aid in enumerate(agents):
                        action_dict[aid] = a[i]
                obs, rew, term, trunc, _info = self.env.step(action_dict)
                done_all = bool(term.get("__all__", False)
                                or trunc.get("__all__", False))
                for pid, agents in self.policy_agents.items():
                    b = bufs[pid]
                    for i, aid in enumerate(agents):
                        b[SB.REWARDS][t, i] = rew.get(aid, 0.0)
                        agent_term = bool(term.get(aid, False))
                        b["terms"][t, i] = float(agent_term)
                        b[SB.DONES][t, i] = float(agent_term or done_all or
                                                  bool(trunc.get(aid, False)))
                self._ep_return += float(sum(rew.values()))
                self._ep_len += 1
                if done_all:
                    self._completed.append({"return": self._ep_return,
                                            "len": self._ep_len})
                    self._ep_return, self._ep_len = 0.0, 0
                    obs, _ = self.env.reset()
                self._obs = obs

            batches = {}
            for pid, agents in self.policy_agents.items():
                b = bufs[pid]
                boot = np.asarray(self._jit[pid][1](
                    params_per_policy[pid], self._stack_obs(self._obs, pid)))
                boot = boot * (1.0 - b["terms"][-1])
                terms = b.pop("terms")
                del terms
                b[SB.BOOTSTRAP_VALUE] = boot
                batches[pid] = SampleBatch(b)

        metrics = self._metrics()
        return MultiAgentBatch(batches, env_steps=T), metrics

    def _metrics(self) -> Dict:
        eps = self._completed
        self._completed = []
        if not eps:
            return {"episodes_this_iter": 0}
        rets = [e["return"] for e in eps]
        lens = [e["len"] for e in eps]
        return {"episodes_this_iter": len(eps),
                "episode_return_mean": float(np.mean(rets)),
                "episode_return_max": float(np.max(rets)),
                "episode_return_min": float(np.min(rets)),
                "episode_len_mean": float(np.mean(lens))}


def module_specs_for(env: MultiAgentEnv, policy_mapping_fn: Callable,
                     hiddens=(256, 256)) -> Dict[str, ModuleSpec]:
    """One ModuleSpec per policy from a representative agent's spaces."""
    specs = {}
    for aid in env.possible_agents:
        pid = policy_mapping_fn(aid)
        if pid not in specs:
            specs[pid] = ModuleSpec.from_spaces(
                env.get_observation_space(aid), env.get_action_space(aid),
                hiddens=hiddens)
    return specs
