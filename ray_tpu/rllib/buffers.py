"""Replay buffers (reference: rllib/utils/replay_buffers/*).

Numpy ring storage on host (CPU RAM is the right home for a million
transitions; sampled minibatches ship to the TPU per update). Prioritized
sampling uses a segment tree like the reference's implementation.

`ReplayActor` is the sebulba-pipeline variant: it never touches trajectory
BYTES, only object-store refs. Rollout actors seal [T, B] trajectory
objects into their local store; the driver forwards the refs here
(wrapped in a list so the fabric's top-level-arg resolution leaves them
as refs); the learner fetches sampled refs straight from the producing
node's store — trajectory data never passes through the driver or this
actor.
"""

from typing import Dict, List, Optional

import numpy as np


class ReplayBuffer:
    """Uniform FIFO ring over dict-of-array transitions."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self.rng = np.random.default_rng(seed)
        self._store: Optional[Dict[str, np.ndarray]] = None
        self._idx = 0
        self._size = 0

    def __len__(self):
        return self._size

    def add_batch(self, batch: Dict[str, np.ndarray]):
        n = len(next(iter(batch.values())))
        if self._store is None:
            self._store = {
                k: np.empty((self.capacity,) + np.asarray(v).shape[1:],
                            np.asarray(v).dtype)
                for k, v in batch.items()}
        for k, v in batch.items():
            v = np.asarray(v)
            idx = (self._idx + np.arange(n)) % self.capacity
            self._store[k][idx] = v
        self._idx = (self._idx + n) % self.capacity
        self._size = min(self._size + n, self.capacity)

    def add(self, **transition):
        self.add_batch({k: np.asarray([v]) for k, v in transition.items()})

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self.rng.integers(0, self._size, size=batch_size)
        return {k: v[idx] for k, v in self._store.items()}


class ReplayActor:
    """Ref-based trajectory replay for the sebulba pipeline (deployed as a
    ray_tpu actor; plain-class methods so it is also directly testable
    in-process).

    Admission: ``add_refs([refs], versions)`` — each slot holds an
    ObjectRef (a BORROW: the deserialized copy increfs, so the trajectory
    object stays alive in its producer's store exactly as long as the
    slot does) plus the params version stamped at collection time.
    Ring eviction drops the oldest slot's ref, releasing the object.

    Sampling: ``sample_refs(k)`` returns (ref, version) pairs WITHOUT
    fetching any data. Two modes:

    * ``uniform`` — seeded ``np.random.default_rng`` draws (deterministic
      given the config seed: sebulba runs are reproducible, and the
      regression test pins an exact index sequence);
    * ``fifo`` — each trajectory is handed out exactly once, oldest
      first (the lockstep/parity mode: replay degenerates to a queue and
      the pipeline replays the synchronous schedule exactly).
    """

    def __init__(self, capacity: int, seed: int = 0, mode: str = "uniform"):
        if mode not in ("uniform", "fifo"):
            raise ValueError(f"unknown replay mode {mode!r}")
        self.capacity = capacity
        self.mode = mode
        self.rng = np.random.default_rng(seed)
        self._slots: List[tuple] = []   # (ref, version) — insertion order
        self._next = 0                  # fifo cursor
        self._admitted = 0
        self._evicted = 0
        self._sampled = 0

    def ping(self) -> bool:
        return True

    def add_refs(self, refs, versions) -> int:
        """Admit trajectory refs (driver passes them wrapped in a list so
        they arrive as refs, not values). Returns current size."""
        if not isinstance(versions, (list, tuple)):
            versions = [versions] * len(refs)
        for ref, v in zip(refs, versions):
            self._slots.append((ref, int(v)))
            self._admitted += 1
        while len(self._slots) > self.capacity:
            self._slots.pop(0)          # drop → borrow decref → release
            self._evicted += 1
            self._next = max(self._next - 1, 0)
        return len(self._slots)

    def _sample_indices(self, k: int) -> List[int]:
        """The deterministic core: next k slot indices for this mode.
        Split out so tests can pin the sequence without the actor round
        trip."""
        n = len(self._slots)
        if self.mode == "fifo":
            avail = n - self._next
            take = min(k, avail)
            idx = list(range(self._next, self._next + take))
            self._next += take
            return idx
        if n == 0:
            return []
        return [int(i) for i in self.rng.integers(0, n, size=k)]

    def sample_refs(self, k: int) -> List[tuple]:
        """Up to k (ref, version) pairs (fewer in fifo mode when the queue
        runs dry; empty when nothing is admitted yet). The refs serialize
        back to the caller as refs — no trajectory bytes move."""
        idx = self._sample_indices(k)
        self._sampled += len(idx)
        return [self._slots[i] for i in idx]

    def size(self) -> int:
        return len(self._slots) if self.mode == "uniform" \
            else len(self._slots) - self._next

    def clear(self) -> int:
        """Drop every held ref (leak-free shutdown: the driver awaits this
        before releasing the actor handle, so no trajectory object stays
        pinned by a dying borrower)."""
        n = len(self._slots)
        del self._slots[:]
        self._next = 0
        return n

    def stats(self) -> Dict:
        return {"size": len(self._slots), "capacity": self.capacity,
                "mode": self.mode, "admitted": self._admitted,
                "evicted": self._evicted, "sampled": self._sampled,
                "fifo_cursor": self._next}


class _SumTree:
    def __init__(self, capacity: int):
        self.n = 1
        while self.n < capacity:
            self.n *= 2
        self.tree = np.zeros(2 * self.n, np.float64)

    def set(self, idx, value):
        i = idx + self.n
        self.tree[i] = value
        i //= 2
        while i >= 1:
            self.tree[i] = self.tree[2 * i] + self.tree[2 * i + 1]
            i //= 2

    def total(self) -> float:
        return float(self.tree[1])

    def find(self, prefix: float) -> int:
        """Index whose cumulative sum interval contains `prefix`."""
        i = 1
        while i < self.n:
            left = self.tree[2 * i]
            if prefix < left:
                i = 2 * i
            else:
                prefix -= left
                i = 2 * i + 1
        return i - self.n


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional PER (Schaul et al. 2016; reference:
    prioritized_replay_buffer.py): P(i) ∝ p_i^α, IS weights w_i ∝
    (N·P(i))^-β normalized by max."""

    def __init__(self, capacity: int, alpha: float = 0.6, seed: int = 0):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.tree = _SumTree(capacity)
        self.max_priority = 1.0

    def add_batch(self, batch: Dict[str, np.ndarray]):
        n = len(next(iter(batch.values())))
        start = self._idx
        super().add_batch(batch)
        for j in range(n):
            self.tree.set((start + j) % self.capacity,
                          self.max_priority ** self.alpha)

    def sample(self, batch_size: int, beta: float = 0.4):
        total = self.tree.total()
        prefixes = self.rng.uniform(0, total, size=batch_size)
        idx = np.array([min(self.tree.find(p), self._size - 1)
                        for p in prefixes])
        probs = np.array([self.tree.tree[i + self.tree.n] for i in idx]) / total
        weights = (self._size * np.maximum(probs, 1e-12)) ** (-beta)
        weights = weights / weights.max()
        out = {k: v[idx] for k, v in self._store.items()}
        out["_weights"] = weights.astype(np.float32)
        out["_indices"] = idx
        return out

    def update_priorities(self, indices, priorities):
        for i, p in zip(np.asarray(indices), np.asarray(priorities)):
            p = float(abs(p)) + 1e-6
            self.max_priority = max(self.max_priority, p)
            self.tree.set(int(i), p ** self.alpha)
