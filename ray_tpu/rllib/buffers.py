"""Replay buffers (reference: rllib/utils/replay_buffers/*).

Numpy ring storage on host (CPU RAM is the right home for a million
transitions; sampled minibatches ship to the TPU per update). Prioritized
sampling uses a segment tree like the reference's implementation.
"""

from typing import Dict, Optional

import numpy as np


class ReplayBuffer:
    """Uniform FIFO ring over dict-of-array transitions."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self.rng = np.random.default_rng(seed)
        self._store: Optional[Dict[str, np.ndarray]] = None
        self._idx = 0
        self._size = 0

    def __len__(self):
        return self._size

    def add_batch(self, batch: Dict[str, np.ndarray]):
        n = len(next(iter(batch.values())))
        if self._store is None:
            self._store = {
                k: np.empty((self.capacity,) + np.asarray(v).shape[1:],
                            np.asarray(v).dtype)
                for k, v in batch.items()}
        for k, v in batch.items():
            v = np.asarray(v)
            idx = (self._idx + np.arange(n)) % self.capacity
            self._store[k][idx] = v
        self._idx = (self._idx + n) % self.capacity
        self._size = min(self._size + n, self.capacity)

    def add(self, **transition):
        self.add_batch({k: np.asarray([v]) for k, v in transition.items()})

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self.rng.integers(0, self._size, size=batch_size)
        return {k: v[idx] for k, v in self._store.items()}


class _SumTree:
    def __init__(self, capacity: int):
        self.n = 1
        while self.n < capacity:
            self.n *= 2
        self.tree = np.zeros(2 * self.n, np.float64)

    def set(self, idx, value):
        i = idx + self.n
        self.tree[i] = value
        i //= 2
        while i >= 1:
            self.tree[i] = self.tree[2 * i] + self.tree[2 * i + 1]
            i //= 2

    def total(self) -> float:
        return float(self.tree[1])

    def find(self, prefix: float) -> int:
        """Index whose cumulative sum interval contains `prefix`."""
        i = 1
        while i < self.n:
            left = self.tree[2 * i]
            if prefix < left:
                i = 2 * i
            else:
                prefix -= left
                i = 2 * i + 1
        return i - self.n


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional PER (Schaul et al. 2016; reference:
    prioritized_replay_buffer.py): P(i) ∝ p_i^α, IS weights w_i ∝
    (N·P(i))^-β normalized by max."""

    def __init__(self, capacity: int, alpha: float = 0.6, seed: int = 0):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.tree = _SumTree(capacity)
        self.max_priority = 1.0

    def add_batch(self, batch: Dict[str, np.ndarray]):
        n = len(next(iter(batch.values())))
        start = self._idx
        super().add_batch(batch)
        for j in range(n):
            self.tree.set((start + j) % self.capacity,
                          self.max_priority ** self.alpha)

    def sample(self, batch_size: int, beta: float = 0.4):
        total = self.tree.total()
        prefixes = self.rng.uniform(0, total, size=batch_size)
        idx = np.array([min(self.tree.find(p), self._size - 1)
                        for p in prefixes])
        probs = np.array([self.tree.tree[i + self.tree.n] for i in idx]) / total
        weights = (self._size * np.maximum(probs, 1e-12)) ** (-beta)
        weights = weights / weights.max()
        out = {k: v[idx] for k, v in self._store.items()}
        out["_weights"] = weights.astype(np.float32)
        out["_indices"] = idx
        return out

    def update_priorities(self, indices, priorities):
        for i, p in zip(np.asarray(indices), np.asarray(priorities)):
            p = float(abs(p)) + 1e-6
            self.max_priority = max(self.max_priority, p)
            self.tree.set(int(i), p ** self.alpha)
