"""RLModule — policy/value network + action-distribution glue (reference:
rllib/core/rl_module/rl_module.py + catalog).

A module is a flax net mapping obs → (dist inputs, value). The catalog picks
the torso (MLP for flat obs, CNN for image obs) and the head for the action
space (Discrete → Categorical logits; Box → mean + learned log_std).
"""

import dataclasses
from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models.torsos import CNNTorso, MLPTorso
from .distributions import Categorical, DiagGaussian


@dataclasses.dataclass
class ModuleSpec:
    """What the catalog derived from the spaces (pickles cleanly to actors)."""
    obs_shape: Tuple[int, ...]
    action_kind: str          # "discrete" | "continuous"
    action_dim: int
    hiddens: Sequence[int] = (256, 256)
    use_cnn: bool = False
    free_log_std: bool = True

    @staticmethod
    def from_spaces(obs_space, action_space, hiddens=(256, 256)) -> "ModuleSpec":
        import gymnasium as gym
        obs_shape = tuple(obs_space.shape)
        use_cnn = len(obs_shape) == 3
        if isinstance(action_space, gym.spaces.Discrete):
            return ModuleSpec(obs_shape, "discrete", int(action_space.n),
                              hiddens, use_cnn)
        if isinstance(action_space, gym.spaces.Box):
            return ModuleSpec(obs_shape, "continuous",
                              int(np.prod(action_space.shape)), hiddens, use_cnn)
        raise ValueError(f"unsupported action space {action_space}")


class PolicyValueNet(nn.Module):
    """Shared-torso actor-critic net: obs → (dist_inputs, value)."""
    spec: ModuleSpec

    @nn.compact
    def __call__(self, obs):
        spec = self.spec
        torso = CNNTorso() if spec.use_cnn else MLPTorso(spec.hiddens)
        z = torso(obs)
        out_dim = (spec.action_dim if spec.action_kind == "discrete"
                   else spec.action_dim)
        dist_in = nn.Dense(out_dim, name="pi",
                           kernel_init=nn.initializers.orthogonal(0.01))(z)
        if spec.action_kind == "continuous" and spec.free_log_std:
            log_std = self.param("log_std", nn.initializers.zeros,
                                 (spec.action_dim,), jnp.float32)
            dist_in = jnp.concatenate(
                [dist_in, jnp.broadcast_to(log_std, dist_in.shape)], -1)
        value = nn.Dense(1, name="vf",
                         kernel_init=nn.initializers.orthogonal(1.0))(z)[..., 0]
        return dist_in, value


class RLModule:
    """Bundles net defs + dist construction; stateless (params passed in)."""

    def __init__(self, spec: ModuleSpec):
        self.spec = spec
        self.net = PolicyValueNet(spec)

    def init(self, key) -> Any:
        obs = jnp.zeros((1,) + self.spec.obs_shape, jnp.float32)
        return self.net.init(key, obs)

    def dist(self, dist_inputs: jax.Array):
        if self.spec.action_kind == "discrete":
            return Categorical(dist_inputs)
        mean, log_std = jnp.split(dist_inputs, 2, axis=-1)
        return DiagGaussian(mean, log_std)

    def forward(self, params, obs) -> Tuple[jax.Array, jax.Array]:
        """obs [..., *obs_shape] → (dist_inputs, value); flattens leading dims
        so [T, B, ...] rollouts work without reshaping at call sites."""
        lead = obs.shape[: obs.ndim - len(self.spec.obs_shape)]
        flat = obs.reshape((-1,) + self.spec.obs_shape)
        dist_in, value = self.net.apply(params, flat)
        return (dist_in.reshape(lead + dist_in.shape[1:]),
                value.reshape(lead))

    def explore_step(self, params, obs, key):
        """One acting step: sample action, return (action, logp, value)."""
        dist_in, value = self.forward(params, obs)
        dist = self.dist(dist_in)
        action = dist.sample(key)
        return action, dist.log_prob(action), value

    def inference_step(self, params, obs):
        dist_in, value = self.forward(params, obs)
        return self.dist(dist_in).mode(), value
