"""AlgorithmConfig + Algorithm base (reference:
rllib/algorithms/algorithm_config.py, rllib/algorithms/algorithm.py).

Same builder API (`config.environment(...).training(...).env_runners(...)`)
and `algo.train()` iteration loop. Execution differs TPU-first: the learner's
update is one jitted program on the chip; env runners are CPU processes —
inline objects for `num_env_runners=0`, ray_tpu actors otherwise.
"""

import copy
import time
from typing import Any, Callable, Dict, List, Optional, Type, Union

import numpy as np

from ray_tpu.train.checkpoint import Checkpoint
from .env_runner import EnvRunner
from .rl_module import ModuleSpec
from .sample_batch import SampleBatch


class AlgorithmConfig:
    algo_class: Optional[Type["Algorithm"]] = None

    def __init__(self):
        # environment
        self.env: Union[str, Callable, None] = None
        self.env_config: Dict = {}
        # env runners
        self.num_env_runners = 0
        self.num_envs_per_env_runner = 1
        self.rollout_fragment_length = 200
        self.explore = True
        # placement of remote runner actors across the cluster (reference:
        # env runners are plain actors the scheduler SPREADs over nodes —
        # BASELINE config #5 "TPU learner + CPU rollout actors on workers")
        self.env_runner_scheduling_strategy = None   # e.g. "SPREAD"
        self.env_runner_resources: Dict = {}         # e.g. {"worker_node": 0.1}
        # training
        self.lr = 3e-4
        self.gamma = 0.99
        self.train_batch_size = 4000
        self.minibatch_size = 128
        self.num_epochs = 1
        self.grad_clip: Optional[float] = None
        self.model: Dict = {"hiddens": (256, 256)}
        # learners
        self.num_learners = 0
        self.num_tpus_per_learner = 0
        # multi-agent (None = single-agent)
        self.policies = None
        self.policy_mapping_fn = None
        # training extras (see ops/optim.py)
        self.lr_schedule = None
        self.optimizer = "adam"
        # evaluation
        self.evaluation_interval = 0
        self.evaluation_duration = 5
        self.evaluation_num_env_runners = 0
        self.evaluation_parallel_to_training = False
        # sebulba pipeline (async rollout→replay→learner; rllib/sebulba.py)
        self.sebulba_enabled = False
        self.sebulba_num_rollout_actors = 2
        self.sebulba_inflight_rollouts = 2
        self.sebulba_replay_capacity = 64
        self.sebulba_replay_mode = "uniform"   # or "fifo"
        self.sebulba_sample_batch_count = 1    # trajectories per update
        self.sebulba_min_replay = 1
        self.sebulba_broadcast_interval = 1    # updates per param broadcast
        self.sebulba_max_staleness = None      # drop samples older than this
        self.sebulba_lockstep = False          # sync-parity schedule
        self.sebulba_replay_seed = None        # defaults to config.seed
        self.sebulba_jax_env = None            # e.g. "cartpole" (device path)
        # misc
        self.seed = 0
        self.framework_str = "jax"

    # -- builder sections (each returns self, reference-style) ---------------
    def environment(self, env=None, *, env_config=None, **_):
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = dict(env_config)
        return self

    def env_runners(self, *, num_env_runners=None, num_envs_per_env_runner=None,
                    rollout_fragment_length=None, explore=None,
                    scheduling_strategy=None, resources=None, **_):
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if explore is not None:
            self.explore = explore
        if scheduling_strategy is not None:
            self.env_runner_scheduling_strategy = scheduling_strategy
        if resources is not None:
            self.env_runner_resources = dict(resources)
        return self

    def training(self, *, lr=None, gamma=None, train_batch_size=None,
                 minibatch_size=None, num_epochs=None, grad_clip=None,
                 lr_schedule=None, optimizer=None, model=None, **kwargs):
        if lr is not None:
            self.lr = lr
        if lr_schedule is not None:
            # dict spec (cosine/linear/constant + warmup) or reference-style
            # [[step, lr], ...] pairs — see ops/optim.make_lr_schedule
            self.lr_schedule = lr_schedule
        if optimizer is not None:
            self.optimizer = optimizer
        if gamma is not None:
            self.gamma = gamma
        if train_batch_size is not None:
            self.train_batch_size = train_batch_size
        if minibatch_size is not None:
            self.minibatch_size = minibatch_size
        if num_epochs is not None:
            self.num_epochs = num_epochs
        if grad_clip is not None:
            self.grad_clip = grad_clip
        if model is not None:
            self.model.update(model)
        for k, v in kwargs.items():  # algorithm-specific keys land as attrs
            setattr(self, k, v)
        return self

    def learners(self, *, num_learners=None, num_tpus_per_learner=None, **_):
        if num_learners is not None:
            self.num_learners = num_learners
        if num_tpus_per_learner is not None:
            self.num_tpus_per_learner = num_tpus_per_learner
        return self

    def multi_agent(self, *, policies=None, policy_mapping_fn=None, **_):
        """Reference: AlgorithmConfig.multi_agent(policies={...},
        policy_mapping_fn=fn). `policies` is a set/list of policy ids;
        policy_mapping_fn(agent_id) -> policy_id. All agents mapping to one
        policy = shared/parameter-sharing mode; one policy per agent =
        independent learners."""
        if policies is not None:
            self.policies = sorted(policies)
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self

    def evaluation(self, *, evaluation_interval=None, evaluation_duration=None,
                   evaluation_num_env_runners=None,
                   evaluation_parallel_to_training=None, **_):
        if evaluation_interval is not None:
            self.evaluation_interval = evaluation_interval
        if evaluation_duration is not None:
            self.evaluation_duration = evaluation_duration
        if evaluation_num_env_runners is not None:
            self.evaluation_num_env_runners = evaluation_num_env_runners
        if evaluation_parallel_to_training is not None:
            self.evaluation_parallel_to_training = evaluation_parallel_to_training
        return self

    def sebulba(self, *, enabled: bool = True, num_rollout_actors=None,
                inflight_rollouts=None, replay_capacity=None,
                replay_mode=None, sample_batch_count=None, min_replay=None,
                broadcast_interval=None, max_staleness=None, lockstep=None,
                replay_seed=None, jax_env=None, **_):
        """Run collection through the sebulba pipeline (Podracer,
        arXiv:2104.06272): device-resident/actor rollouts → ref-based
        replay → async V-trace learner with versioned fire-and-forget
        param broadcast. Only off-policy-tolerant algorithms (IMPALA,
        APPO) accept it."""
        self.sebulba_enabled = bool(enabled)
        if num_rollout_actors is not None:
            self.sebulba_num_rollout_actors = num_rollout_actors
        if inflight_rollouts is not None:
            self.sebulba_inflight_rollouts = inflight_rollouts
        if replay_capacity is not None:
            self.sebulba_replay_capacity = replay_capacity
        if replay_mode is not None:
            self.sebulba_replay_mode = replay_mode
        if sample_batch_count is not None:
            self.sebulba_sample_batch_count = sample_batch_count
        if min_replay is not None:
            self.sebulba_min_replay = min_replay
        if broadcast_interval is not None:
            self.sebulba_broadcast_interval = broadcast_interval
        if max_staleness is not None:
            self.sebulba_max_staleness = max_staleness
        if lockstep is not None:
            self.sebulba_lockstep = lockstep
        if replay_seed is not None:
            self.sebulba_replay_seed = replay_seed
        if jax_env is not None:
            self.sebulba_jax_env = jax_env
        return self

    def framework(self, framework: str = "jax", **_):
        if framework not in ("jax", "tf2", "torch"):
            raise ValueError(framework)
        self.framework_str = framework
        return self

    def resources(self, **_):
        return self

    def debugging(self, *, seed=None, **_):
        if seed is not None:
            self.seed = seed
        return self

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def build(self, env=None) -> "Algorithm":
        if env is not None:
            self.env = env
        if self.algo_class is None:
            raise ValueError("config has no algo_class; use a subclass "
                             "like PPOConfig")
        return self.algo_class(self.copy())

    # alias matching the reference's newer naming
    build_algo = build


class Algorithm:
    """Iteration driver: `train()` = collect → learn → metrics."""

    def __init__(self, config: AlgorithmConfig):
        self.config = config
        self.iteration = 0
        self._env_steps_iter = 0
        self._env_steps_total = 0
        self._timers: Dict[str, float] = {}
        self._runner_handles: List = []
        self._local_runner: Optional[EnvRunner] = None
        self._eval_handles: List = []       # dedicated evaluation actors
        self._local_eval_runner: Optional[EnvRunner] = None  # cached inline
        self._pending_eval = None           # in-flight parallel eval refs
        self.setup(config)
        self._setup_eval_runners()
        self._sebulba = None
        if getattr(config, "sebulba_enabled", False):
            from .sebulba import SebulbaPipeline
            self._sebulba = SebulbaPipeline(self, config)

    # -- runner fleet --------------------------------------------------------
    def _make_runner_kwargs(self) -> Dict[str, Any]:
        cfg = self.config
        env = cfg.env
        if isinstance(env, str):
            # resolve registered names HERE (driver), where register_env
            # ran: remote runner actors are fresh processes whose own
            # registry is empty — the callable must ship by value
            from .env_runner import resolve_env_creator
            env = resolve_env_creator(env, cfg.env_config)
        return dict(
            env_creator=env,
            num_envs=cfg.num_envs_per_env_runner,
            rollout_len=cfg.rollout_fragment_length,
            explore=cfg.explore,
            seed=cfg.seed,
            gamma=cfg.gamma,
        )

    def _setup_runners(self):
        cfg = self.config
        if cfg.num_env_runners <= 0:
            self._local_runner = EnvRunner(**self._make_runner_kwargs())
            return
        import ray_tpu
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        decorator = {"num_cpus": 1}
        if cfg.env_runner_resources:
            decorator["resources"] = dict(cfg.env_runner_resources)
        if cfg.env_runner_scheduling_strategy is not None:
            decorator["scheduling_strategy"] = \
                cfg.env_runner_scheduling_strategy
        RemoteRunner = ray_tpu.remote(**decorator)(EnvRunner)
        self._runner_handles = [
            RemoteRunner.remote(**{**self._make_runner_kwargs(),
                                   "seed": cfg.seed + i})
            for i in range(cfg.num_env_runners)]
        # a local runner only to derive the module spec (no sampling)
        self._local_runner = EnvRunner(**{**self._make_runner_kwargs(),
                                          "num_envs": 1, "rollout_len": 2})

    def _sample_all(self, weights) -> (SampleBatch, Dict):
        import ray_tpu
        if self._runner_handles:
            wref = ray_tpu.put(weights)
            batches = ray_tpu.get(
                [r.sample.remote(wref) for r in self._runner_handles])
            metrics = ray_tpu.get(
                [r.pop_metrics.remote() for r in self._runner_handles])
            batch = SampleBatch.concat(batches)
            self._env_steps_iter += batch.count
            return batch, _merge_runner_metrics(metrics)
        b = self._local_runner.sample(weights)
        self._env_steps_iter += b.count
        return b, self._local_runner.pop_metrics()

    # -- to implement --------------------------------------------------------
    def setup(self, config: AlgorithmConfig):
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    # -- public api ----------------------------------------------------------
    # algorithms whose evaluate() cannot run on a generic EnvRunner (custom
    # weight layouts / multi-agent) opt out of the dedicated-actor path
    _supports_eval_actors = True
    # the sebulba pipeline replays data collected under OLDER params, so
    # only algorithms with an off-policy correction (V-trace) opt in
    _supports_sebulba = False

    def _sebulba_update(self, batch: SampleBatch) -> Dict[str, float]:
        """One learner update on a replay-sampled [T, B] batch — the
        sebulba pipeline's learn stage. Algorithms needing driver-side
        preprocessing (APPO's V-trace targets) override this."""
        return self.learner_group.update(batch)

    def _eval_runner_kwargs(self) -> Dict[str, Any]:
        """Same construction as the training runners (module overrides from
        SAC/DQN ride along) but greedy and single-env."""
        kw = self._make_runner_kwargs()
        kw.update(num_envs=1, explore=False)
        return kw

    def _setup_eval_runners(self):
        """Dedicated evaluation EnvRunner actors (reference: Algorithm's
        evaluation worker set, rllib/algorithms/algorithm.py). Zero runners =
        a cached inline runner (no per-interval env re-creation)."""
        cfg = self.config
        if (not cfg.evaluation_interval or cfg.evaluation_num_env_runners <= 0
                or not self._supports_eval_actors or cfg.policies):
            return
        import ray_tpu
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        RemoteRunner = ray_tpu.remote(num_cpus=1)(EnvRunner)
        self._eval_handles = [
            RemoteRunner.remote(**{**self._eval_runner_kwargs(),
                                   "seed": cfg.seed + 10_000 + i})
            for i in range(cfg.evaluation_num_env_runners)]

    def _eval_due(self) -> bool:
        return bool(self.config.evaluation_interval and
                    self.iteration % self.config.evaluation_interval == 0)

    def train(self) -> Dict[str, Any]:
        import math
        t0 = time.perf_counter()
        self._env_steps_iter = 0
        result = (self._sebulba.training_step() if self._sebulba is not None
                  else self.training_step())
        self.iteration += 1
        result.setdefault("training_iteration", self.iteration)
        # env-step accounting (ref: num_env_steps_sampled_* in result dicts)
        self._env_steps_total = getattr(self, "_env_steps_total", 0) \
            + self._env_steps_iter
        result.setdefault("num_env_steps_sampled_this_iter",
                          self._env_steps_iter)
        result.setdefault("num_env_steps_sampled_lifetime",
                          self._env_steps_total)
        due = self._eval_due()
        # a parallel evaluation launched during an earlier iteration attaches
        # to the first result where it's finished (forced if a new one is due)
        if self._pending_eval is not None:
            import ray_tpu
            ready, _ = ray_tpu.wait(self._pending_eval,
                                    num_returns=len(self._pending_eval),
                                    timeout=None if due else 0.0)
            if len(ready) == len(self._pending_eval):
                metrics = ray_tpu.get(self._pending_eval)
                result["evaluation"] = _merge_runner_metrics(metrics)
                self._pending_eval = None
        if due:
            parallel = (self._eval_handles and
                        self.config.evaluation_parallel_to_training)
            if parallel and self._pending_eval is None:
                import ray_tpu
                wref = ray_tpu.put(self.get_weights())
                per = math.ceil(self.config.evaluation_duration /
                                len(self._eval_handles))
                self._pending_eval = [h.run_eval.remote(wref, per)
                                      for h in self._eval_handles]
            elif not parallel:
                result["evaluation"] = self.evaluate()
        result["time_this_iter_s"] = time.perf_counter() - t0
        return result

    def evaluate(self) -> Dict[str, Any]:
        """Greedy-policy episodes (blocking). Uses the dedicated eval actors
        when configured; otherwise a cached inline runner (VERDICT r2 weak #5:
        no fresh env per interval)."""
        import math
        cfg = self.config
        if self._eval_handles:
            import ray_tpu
            wref = ray_tpu.put(self.get_weights())
            per = math.ceil(cfg.evaluation_duration / len(self._eval_handles))
            metrics = ray_tpu.get([h.run_eval.remote(wref, per)
                                   for h in self._eval_handles])
            return _merge_runner_metrics(metrics)
        if self._local_eval_runner is None:
            self._local_eval_runner = EnvRunner(
                **{**self._eval_runner_kwargs(), "seed": cfg.seed + 10_000})
        runner = self._local_eval_runner
        runner.set_weights(self.get_weights())
        start = runner.num_completed_episodes()
        while runner.num_completed_episodes() - start < cfg.evaluation_duration:
            runner.sample()
        return runner.pop_metrics()

    def get_weights(self):
        raise NotImplementedError

    def set_weights(self, weights):
        raise NotImplementedError

    def get_state(self) -> Dict:
        return {"weights": self.get_weights(), "iteration": self.iteration,
                "config_class": type(self.config).__name__}

    def set_state(self, state: Dict):
        self.set_weights(state["weights"])
        self.iteration = state.get("iteration", 0)

    def save(self, path: Optional[str] = None) -> Checkpoint:
        return Checkpoint.from_state(self.get_state(), path=path)

    def restore(self, ckpt: Union[str, Checkpoint]):
        if isinstance(ckpt, str):
            ckpt = Checkpoint.from_directory(ckpt)
        self.set_state(ckpt.to_state())

    def stop(self):
        if getattr(self, "_sebulba", None) is not None:
            self._sebulba.shutdown()
            self._sebulba = None
        if self._local_runner:
            self._local_runner.close()
        for h in self._runner_handles:
            try:
                import ray_tpu
                ray_tpu.kill(h)
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass


def _merge_runner_metrics(ms: List[Dict]) -> Dict:
    out: Dict[str, Any] = {"episodes_this_iter": sum(
        m.get("episodes_this_iter", 0) for m in ms)}
    means = [m for m in ms if "episode_return_mean" in m]
    if means:
        out["episode_return_mean"] = float(np.mean(
            [m["episode_return_mean"] for m in means]))
        out["episode_return_max"] = float(np.max(
            [m["episode_return_max"] for m in means]))
        out["episode_return_min"] = float(np.min(
            [m["episode_return_min"] for m in means]))
        out["episode_len_mean"] = float(np.mean(
            [m["episode_len_mean"] for m in means]))
    return out
