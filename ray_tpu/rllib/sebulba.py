"""Sebulba-style anytime RL pipeline (Podracer architectures,
arXiv:2104.06272; Sebulba is the actor-learner decomposition, Anakin the
single-program variant).

Three roles, fully decoupled, built on the actor fabric:

* ``RolloutActor`` — runs a single jitted act+env-step loop and seals
  fixed-shape [T, B] trajectory objects directly into its local object
  store. Two rollout backends share one surface: the gymnasium
  ``EnvRunner`` (CPU vector envs, per-step jitted policy) and
  ``DeviceRollout`` (a pure-jax env where the WHOLE T-step unroll is one
  ``lax.scan`` on the accelerator — the Anakin-style device-resident
  path). Every trajectory is stamped with the params VERSION it was
  collected under.

* ``ReplayActor`` (rllib/buffers.py) — admits and samples trajectories
  as object-store REFS. Trajectory bytes never pass through the driver
  or the replay actor: the driver forwards refs in, the learner fetches
  sampled refs straight from the producing node's store.

* ``SebulbaPipeline`` — the driver-side learner loop. It keeps each
  rollout actor saturated with in-flight sample calls, admits finished
  trajectories to replay, prefetch-overlaps the next sampled batch with
  the current jitted update, and publishes versioned params via
  fire-and-forget broadcast. ``learner_version - trajectory_version`` is
  the EXACT off-policy gap the V-trace correction is accounting for
  (observed into the ``rllib_offpolicy_gap`` histogram).

Determinism: replay sampling is seeded from the config
(``sebulba_replay_seed``, default ``config.seed``) and rollout RNG is a
counter-folded key, so a pipeline run is reproducible. ``lockstep`` mode
(1 actor, 1 in-flight rollout, fifo replay, blocking broadcast every
update) degenerates the async pipeline into the exact synchronous
IMPALA schedule — the parity anchor the tests pin against the sync path.

Observability: rollout and learn stages ship ``pipeline.act`` /
``pipeline.learn`` spans through the worker outbox (util/tracing.py), so
``python -m ray_tpu timeline`` shows the rollout/replay/learn overlap;
``tracing.overlap_stats`` quantifies it and the bench gate asserts it.
"""

import math
import os
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.actor import method
from . import sample_batch as SB
from .algorithm import _merge_runner_metrics
from .buffers import ReplayActor
from .env_runner import EnvRunner
from .rl_module import ModuleSpec, RLModule
from .sample_batch import SampleBatch

__all__ = ["JaxCartPole", "DeviceRollout", "RolloutActor", "SebulbaPipeline"]


# ---------------------------------------------------------------------------
# device-resident rollouts
# ---------------------------------------------------------------------------

class JaxCartPole:
    """CartPole-v1 as pure jax functions (classic-control physics,
    Barto-Sutton-Anderson '83) so an entire rollout can live inside one
    jitted ``lax.scan`` — state is [B, 4] arrays, auto-reset is a
    ``where`` on the done mask. Matches gymnasium's SAME_STEP autoreset
    semantics: the obs recorded at step t is the pre-step obs, and a
    finished env's NEXT obs is the reset obs."""

    GRAV, MASSCART, MASSPOLE = 9.8, 1.0, 0.1
    TOTAL_MASS = MASSCART + MASSPOLE
    LENGTH = 0.5                       # half the pole's length
    POLEMASS_LENGTH = MASSPOLE * LENGTH
    FORCE_MAG, TAU = 10.0, 0.02
    X_LIM = 2.4
    THETA_LIM = 12 * 2 * math.pi / 360
    MAX_STEPS = 500

    @staticmethod
    def spec() -> ModuleSpec:
        return ModuleSpec((4,), "discrete", 2)

    @staticmethod
    def reset(key, batch: int):
        import jax
        import jax.numpy as jnp
        x = jax.random.uniform(key, (batch, 4), minval=-0.05, maxval=0.05)
        return x.astype(jnp.float32), jnp.zeros((batch,), jnp.int32)

    @staticmethod
    def observe(x):
        return x

    @classmethod
    def step(cls, x, t, action):
        import jax.numpy as jnp
        pos, vel, theta, theta_dot = x[:, 0], x[:, 1], x[:, 2], x[:, 3]
        force = jnp.where(action == 1, cls.FORCE_MAG, -cls.FORCE_MAG)
        costh, sinth = jnp.cos(theta), jnp.sin(theta)
        temp = (force + cls.POLEMASS_LENGTH * theta_dot ** 2 * sinth) \
            / cls.TOTAL_MASS
        theta_acc = (cls.GRAV * sinth - costh * temp) / (
            cls.LENGTH * (4.0 / 3.0
                          - cls.MASSPOLE * costh ** 2 / cls.TOTAL_MASS))
        x_acc = temp - cls.POLEMASS_LENGTH * theta_acc * costh / cls.TOTAL_MASS
        pos = pos + cls.TAU * vel
        vel = vel + cls.TAU * x_acc
        theta = theta + cls.TAU * theta_dot
        theta_dot = theta_dot + cls.TAU * theta_acc
        x2 = jnp.stack([pos, vel, theta, theta_dot], axis=1)
        t2 = t + 1
        term = (jnp.abs(pos) > cls.X_LIM) | (jnp.abs(theta) > cls.THETA_LIM)
        trunc = (t2 >= cls.MAX_STEPS) & ~term
        return x2, t2, jnp.ones_like(pos), term, trunc


_JAX_ENVS = {"cartpole": JaxCartPole}


class DeviceRollout:
    """EnvRunner-shaped rollout producer whose whole [T, B] unroll is ONE
    jitted ``lax.scan`` over (explore_step → env.step → autoreset) on the
    default device. Emits the same fixed-shape SampleBatch columns as
    EnvRunner, so the learner (and its recompile guard) can't tell the
    backends apart."""

    def __init__(self, env_cls, *, num_envs: int = 1, rollout_len: int = 200,
                 seed: int = 0, module=None, **_):
        if isinstance(env_cls, str):
            env_cls = _JAX_ENVS[env_cls]
        self.env_cls = env_cls
        self.num_envs = num_envs
        self.rollout_len = rollout_len
        self.params = None
        self.params_version = -1
        self._seed = seed
        self._calls = 0
        self._state = None            # (x, t) device arrays
        self._unroll = None
        self.module = module if module is not None else RLModule(env_cls.spec())
        self._ep_return = np.zeros(num_envs, np.float64)
        self._ep_len = np.zeros(num_envs, np.int64)
        self._completed: List[Dict] = []

    # same surface as EnvRunner -------------------------------------------
    def set_weights(self, params, version: Optional[int] = None):
        self.params = params
        if version is not None:
            self.params_version = int(version)
            from ray_tpu.util import metrics
            metrics.get_or_create(
                metrics.Gauge, "rllib_param_version",
                "params version in use (learner: published; "
                "rollout: received)", tag_keys=("role",)).set(
                    self.params_version, tags={"role": "rollout"})

    def get_spec(self) -> ModuleSpec:
        return self.module.spec

    def init_params(self):
        import jax
        return jax.device_get(self.module.init(jax.random.PRNGKey(self._seed)))

    def _ensure_jit(self):
        if self._unroll is not None:
            return
        import jax
        import jax.numpy as jnp
        env, module, T, B = self.env_cls, self.module, self.rollout_len, \
            self.num_envs

        def unroll(params, x, t, key):
            def body(carry, k):
                x, t = carry
                k_act, k_reset = jax.random.split(k)
                obs = env.observe(x)
                a, logp, v = module.explore_step(params, obs, k_act)
                x2, t2, rew, term, trunc = env.step(x, t, a)
                done = jnp.logical_or(term, trunc)
                xr, tr = env.reset(k_reset, B)
                x2 = jnp.where(done[:, None], xr, x2)
                t2 = jnp.where(done, tr, t2)
                return (x2, t2), (obs, a, rew,
                                  done.astype(jnp.float32),
                                  term.astype(jnp.float32), logp, v)

            keys = jax.random.split(key, T)
            (x, t), cols = jax.lax.scan(body, (x, t), keys)
            obs, act, rew, done, term, logp, vf = cols
            # bootstrap value of the post-rollout state; a terminated env's
            # state is already the reset state (SAME_STEP) and its future
            # return is 0, so mask by the final terminal flag — exactly
            # EnvRunner's rule
            _, boot = module.forward(params, env.observe(x))
            boot = boot * (1.0 - term[-1])
            return (x, t), (obs, act, rew, done, term, logp, vf, boot)

        self._unroll = jax.jit(unroll)

    def sample(self, params=None) -> SampleBatch:
        import jax
        if params is not None:
            self.params = params
        assert self.params is not None, "set_weights() before sample()"
        self._ensure_jit()
        if self._state is None:
            self._state = self.env_cls.reset(
                jax.random.PRNGKey(self._seed), self.num_envs)
        key = jax.random.fold_in(
            jax.random.PRNGKey(self._seed ^ 0x5eed), self._calls)
        self._calls += 1
        self._state, cols = self._unroll(self.params, self._state[0],
                                         self._state[1], key)
        obs, act, rew, done, term, logp, vf, boot = (
            np.asarray(c) for c in jax.device_get(cols))
        for tr in range(rew.shape[0]):          # episode metrics, host side
            self._ep_return += rew[tr]
            self._ep_len += 1
            for i in np.nonzero(done[tr])[0]:
                self._completed.append({"return": float(self._ep_return[i]),
                                        "len": int(self._ep_len[i])})
                self._ep_return[i] = 0.0
                self._ep_len[i] = 0
        return SampleBatch({
            SB.OBS: obs, SB.ACTIONS: act, SB.REWARDS: rew, SB.DONES: done,
            SB.TERMINATEDS: term, SB.LOGP: logp, SB.VF_PREDS: vf,
            SB.BOOTSTRAP_VALUE: boot,
        })

    def pop_metrics(self) -> Dict:
        eps, self._completed = self._completed, []
        if not eps:
            return {"episodes_this_iter": 0}
        rets = [e["return"] for e in eps]
        lens = [e["len"] for e in eps]
        return {"episodes_this_iter": len(eps),
                "episode_return_mean": float(np.mean(rets)),
                "episode_return_max": float(np.max(rets)),
                "episode_return_min": float(np.min(rets)),
                "episode_len_mean": float(np.mean(lens))}

    def num_completed_episodes(self) -> int:
        return len(self._completed)

    def close(self):
        self._state = None


# ---------------------------------------------------------------------------
# rollout actor
# ---------------------------------------------------------------------------

def _rollout_backend(runner_kwargs: Dict[str, Any], jax_env):
    if jax_env is not None:
        return DeviceRollout(jax_env, num_envs=runner_kwargs["num_envs"],
                             rollout_len=runner_kwargs["rollout_len"],
                             seed=runner_kwargs.get("seed", 0))
    return EnvRunner(**runner_kwargs)


class RolloutActor:
    """One saturated act+step loop deployed as a ray_tpu actor.

    ``sample_traj`` is declared ``num_returns=2``: the [T, B] trajectory
    object stays in THIS worker's store (the driver only ever holds its
    ref and forwards it to replay) while the small info dict — version,
    step count — travels back by value for the driver's accounting."""

    def __init__(self, runner_kwargs: Dict[str, Any], index: int = 0,
                 jax_env=None):
        self.index = index
        self._params = None
        self._version = -1
        self._impl = _rollout_backend(runner_kwargs, jax_env)

    def ping(self) -> int:
        return self.index

    def get_spec(self) -> ModuleSpec:
        return self._impl.get_spec()

    def init_params(self):
        return self._impl.init_params()

    def node_info(self) -> Dict:
        import socket
        return {"pid": os.getpid(), "ppid": os.getppid(),
                "hostname": socket.gethostname(), "actor": self.index}

    def set_weights(self, params, version: int):
        """Fire-and-forget broadcast target — the learner never waits on
        the ack (except in lockstep mode)."""
        self._params = params
        self._version = int(version)
        self._impl.set_weights(params, version)

    @method(num_returns=2)
    def sample_traj(self):
        from ray_tpu.util import metrics, tracing
        t0 = time.time()
        batch = self._impl.sample(self._params)
        t1 = time.time()
        steps = int(np.asarray(batch[SB.REWARDS]).size)
        metrics.get_or_create(
            metrics.Counter, "rllib_env_steps",
            "env steps collected by sebulba rollout actors").inc(steps)
        tracing.ship_window("pipeline.act", "rllib", None, t0, t1,
                            tid=os.getpid(),
                            args={"actor": self.index,
                                  "version": self._version})
        traj = dict(batch)
        traj["version"] = self._version
        traj["actor"] = self.index
        info = {"version": self._version, "steps": steps,
                "actor": self.index, "dur_s": t1 - t0}
        return traj, info

    def pop_metrics(self) -> Dict:
        return self._impl.pop_metrics()

    def close(self):
        self._impl.close()


# ---------------------------------------------------------------------------
# pipeline
# ---------------------------------------------------------------------------

class SebulbaPipeline:
    """Driver-side orchestrator: saturate rollouts, admit refs to replay,
    prefetch-overlap sampled batches with the jitted update, broadcast
    versioned params fire-and-forget."""

    def __init__(self, algo, config):
        import ray_tpu
        if not getattr(algo, "_supports_sebulba", False):
            raise ValueError(
                f"{type(algo).__name__} does not support the sebulba "
                f"pipeline; it needs an off-policy-tolerant (V-trace) "
                f"update — use IMPALA or APPO")
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self.algo = algo
        self.cfg = config
        self.lockstep = bool(getattr(config, "sebulba_lockstep", False))
        n = 1 if self.lockstep else max(
            1, int(getattr(config, "sebulba_num_rollout_actors", 2)))
        self.inflight_per_actor = 1 if self.lockstep else max(
            1, int(getattr(config, "sebulba_inflight_rollouts", 2)))
        self.broadcast_interval = 1 if self.lockstep else max(
            1, int(getattr(config, "sebulba_broadcast_interval", 1)))
        self.sample_count = 1 if self.lockstep else max(
            1, int(getattr(config, "sebulba_sample_batch_count", 1)))
        mode = "fifo" if self.lockstep else str(
            getattr(config, "sebulba_replay_mode", "uniform"))
        self.min_replay = max(1, int(getattr(config, "sebulba_min_replay", 1)))
        self.max_staleness = getattr(config, "sebulba_max_staleness", None)
        replay_seed = getattr(config, "sebulba_replay_seed", None)
        if replay_seed is None:
            replay_seed = config.seed

        decorator: Dict[str, Any] = {"num_cpus": 1}
        if getattr(config, "env_runner_resources", None):
            decorator["resources"] = dict(config.env_runner_resources)
        if getattr(config, "env_runner_scheduling_strategy", None) is not None:
            decorator["scheduling_strategy"] = \
                config.env_runner_scheduling_strategy
        RemoteRollout = ray_tpu.remote(**decorator)(RolloutActor)
        kw = algo._make_runner_kwargs()
        jax_env = getattr(config, "sebulba_jax_env", None)
        self.actors = [
            RemoteRollout.remote({**kw, "seed": config.seed + i},
                                 index=i, jax_env=jax_env)
            for i in range(n)]
        RemoteReplay = ray_tpu.remote(num_cpus=1)(ReplayActor)
        self.replay = RemoteReplay.remote(
            int(getattr(config, "sebulba_replay_capacity", 64)),
            seed=int(replay_seed), mode=mode)
        ray_tpu.get([a.ping.remote() for a in self.actors]
                    + [self.replay.ping.remote()])

        self.version = 0            # params version currently published
        self.updates = 0
        self._broadcasts = 0
        self._broadcasts_async = 0  # fire-and-forget (no ack awaited)
        self._env_steps_total = 0
        self._replay_admitted = 0
        self._stale_dropped = 0
        self._gap_counts: Dict[int, int] = {}   # off-policy gap → updates
        self._last_learn: Dict[str, float] = {}
        self._inflight: Dict[str, tuple] = {}   # info-ref id → (iref, tref, i)
        self._pending_sample = None             # in-flight sample_refs ref
        self._fetching = None                   # (future, versions)
        self._pool = ThreadPoolExecutor(1, thread_name_prefix="sebulba-fetch")
        self._closed = False

        # actors are useless until they hold v0 weights — this one
        # broadcast blocks; steady-state broadcasts are fire-and-forget
        self._broadcast(block=True)
        if not self.lockstep:
            for i in range(len(self.actors)):
                for _ in range(self.inflight_per_actor):
                    self._submit(i)

    # -- rollout side -------------------------------------------------------
    def _submit(self, idx: int):
        tref, iref = self.actors[idx].sample_traj.remote()
        self._inflight[iref.id] = (iref, tref, idx)

    def _reap(self, block: bool) -> int:
        """Admit finished rollouts to replay (refs only — the trajectory
        object never leaves the producing node) and resubmit. Returns env
        steps admitted."""
        import ray_tpu
        if not self._inflight:
            return 0
        irefs = [e[0] for e in self._inflight.values()]
        ready, _ = ray_tpu.wait(irefs, num_returns=len(irefs), timeout=0.0)
        if not ready and block:
            ready, _ = ray_tpu.wait(irefs, num_returns=1, timeout=0.05)
        steps = 0
        from ray_tpu.util import metrics
        for iref in ready:
            iref, tref, idx = self._inflight.pop(iref.id)
            info = ray_tpu.get(iref)
            # wrapped in a list → arrives at the replay actor as a REF
            self.replay.add_refs.remote([tref], [int(info["version"])])
            del tref
            self._replay_admitted += 1
            steps += int(info["steps"])
            self._submit(idx)
        if steps:
            metrics.get_or_create(
                metrics.Counter, "rllib_env_steps",
                "env steps collected by sebulba rollout actors").inc(steps)
        self._env_steps_total += steps
        return steps

    # -- learner side -------------------------------------------------------
    def _request_sample(self):
        if self._pending_sample is None \
                and self._replay_admitted >= self.min_replay:
            self._pending_sample = self.replay.sample_refs.remote(
                self.sample_count)

    def _start_fetch(self, block: bool) -> bool:
        """Pending sample resolved → hand the refs to the fetch thread so
        trajectory bytes stream in while the driver thread runs the jitted
        update (the prefetch overlap)."""
        import ray_tpu
        if self._fetching is not None or self._pending_sample is None:
            return False
        if not block:
            ready, _ = ray_tpu.wait([self._pending_sample], num_returns=1,
                                    timeout=0.0)
            if not ready:
                return False
        pairs = ray_tpu.get(self._pending_sample)
        self._pending_sample = None
        if not pairs:
            return False            # replay dry (fifo) — retry after admits
        refs = [p[0] for p in pairs]
        versions = [int(p[1]) for p in pairs]
        self._fetching = (self._pool.submit(ray_tpu.get, refs), versions)
        return True

    def _learn_turn(self, block: bool = False) -> bool:
        """Advance the learner state machine; True if an update ran."""
        while True:
            if self._fetching is not None:
                fut, versions = self._fetching
                if not fut.done() and not block:
                    return False
                trajs = fut.result()
                self._fetching = None
                # queue the NEXT sample before updating, so its fetch
                # overlaps this update
                self._request_sample()
                self._start_fetch(block=False)
                self._apply_update(trajs, versions)
                return True
            self._request_sample()
            if self._pending_sample is None:
                return False        # replay below min_replay — keep reaping
            if not self._start_fetch(block=block):
                if not block:
                    return False
                if self._pending_sample is None and self._fetching is None:
                    return False    # sampled empty — caller reaps more

    def _apply_update(self, trajs: List[Dict], versions: List[int]):
        from ray_tpu.util import metrics, tracing
        gap = self.version - min(versions)
        self._gap_counts[gap] = self._gap_counts.get(gap, 0) + 1
        metrics.get_or_create(
            metrics.Histogram, "rllib_offpolicy_gap",
            "learner_version - trajectory_version at update time (the "
            "off-policy gap V-trace corrects)",
            boundaries=(0.5, 1.5, 2.5, 4.5, 8.5, 16.5)).observe(float(gap))
        if self.max_staleness is not None and gap > self.max_staleness:
            self._stale_dropped += len(trajs)
            metrics.get_or_create(
                metrics.Counter, "rllib_stale_dropped",
                "replay samples dropped for exceeding "
                "sebulba_max_staleness").inc(len(trajs))
            return
        cols = [SampleBatch({k: v for k, v in t.items()
                             if k not in ("version", "actor")})
                for t in trajs]
        batch = cols[0] if len(cols) == 1 else SampleBatch.concat(cols, axis=1)
        t0 = time.time()
        self._last_learn = self.algo._sebulba_update(batch)
        t1 = time.time()
        self.updates += 1
        self.version += 1
        tracing.ship_window("pipeline.learn", "rllib", None, t0, t1,
                            tid=os.getpid(),
                            args={"version": self.version, "gap": gap})
        metrics.get_or_create(
            metrics.Counter, "rllib_learner_steps",
            "sebulba learner updates").inc()
        metrics.get_or_create(
            metrics.Gauge, "rllib_param_version",
            "params version in use (learner: published; rollout: received)",
            tag_keys=("role",)).set(self.version, tags={"role": "learner"})
        if self.updates % self.broadcast_interval == 0:
            self._broadcast(block=self.lockstep)

    def _broadcast(self, block: bool = False):
        import ray_tpu
        from ray_tpu.util import metrics
        wref = ray_tpu.put(self.algo.get_weights())
        acks = [a.set_weights.remote(wref, self.version) for a in self.actors]
        del wref
        self._broadcasts += 1
        if not block:
            self._broadcasts_async += 1
        metrics.get_or_create(
            metrics.Counter, "rllib_broadcasts",
            "sebulba param broadcasts (fire-and-forget except lockstep)",
            tag_keys=("kind",)).inc(
                1, tags={"kind": "blocking" if block else "async"})
        if block:
            ray_tpu.get(acks)

    # -- iteration ----------------------------------------------------------
    def training_step(self) -> Dict[str, Any]:
        import ray_tpu
        target = self.cfg.train_batch_size
        steps = self._step_lockstep(target) if self.lockstep \
            else self._step_async(target)
        self.algo._env_steps_iter += steps
        rms = ray_tpu.get([a.pop_metrics.remote() for a in self.actors])
        result = _merge_runner_metrics(rms)
        result["num_env_steps_sampled_this_iter"] = steps
        result["learner"] = dict(self._last_learn)
        result["sebulba"] = self.stats(remote=False)
        return result

    def _step_async(self, target: int) -> int:
        steps = 0
        updates_before = self.updates
        while steps < target:
            steps += self._reap(block=True)
            self._learn_turn(block=False)
        # an iteration must learn at least once (replay has ≥1 admission
        # by now, so a blocking turn can only stall on a dry fifo — reap
        # keeps feeding it)
        while self.updates == updates_before:
            if not self._learn_turn(block=True):
                self._reap(block=True)
        return steps

    def _step_lockstep(self, target: int) -> int:
        """Strictly sequential schedule: sample → admit → replay(fifo) →
        fetch → update → blocking broadcast. Reproduces the synchronous
        IMPALA iteration exactly (the parity anchor)."""
        import ray_tpu
        steps = 0
        from ray_tpu.util import metrics
        while steps < target:
            tref, iref = self.actors[0].sample_traj.remote()
            info = ray_tpu.get(iref)
            self.replay.add_refs.remote([tref], [int(info["version"])])
            del tref
            self._replay_admitted += 1
            steps += int(info["steps"])
            self._env_steps_total += int(info["steps"])
            metrics.get_or_create(
                metrics.Counter, "rllib_env_steps",
                "env steps collected by sebulba rollout actors").inc(
                    int(info["steps"]))
            pairs = ray_tpu.get(self.replay.sample_refs.remote(1))
            trajs = ray_tpu.get([p[0] for p in pairs])
            self._apply_update(trajs, [int(p[1]) for p in pairs])
        return steps

    # -- introspection ------------------------------------------------------
    def stats(self, remote: bool = True) -> Dict[str, Any]:
        from ray_tpu.util import metrics
        s: Dict[str, Any] = {
            "version": self.version, "updates": self.updates,
            "broadcasts": self._broadcasts,
            "broadcasts_async": self._broadcasts_async,
            "env_steps": self._env_steps_total,
            "replay_admitted": self._replay_admitted,
            "stale_dropped": self._stale_dropped,
            "gap_counts": dict(self._gap_counts),
            "num_rollout_actors": len(self.actors),
            "inflight": len(self._inflight),
            "lockstep": self.lockstep,
            "jit_cache_size": self.algo.learner.jit_cache_size(),
            "counters": metrics.rllib_sebulba_counters(),
            "offpolicy_gap": metrics.rllib_offpolicy_gap_summary(),
        }
        if remote and self.replay is not None:
            import ray_tpu
            s["replay"] = ray_tpu.get(self.replay.stats.remote())
        return s

    # -- teardown -----------------------------------------------------------
    def shutdown(self):
        """Leak-free stop: drain in-flight work, await the replay actor's
        clear() (its slot borrows must drop BEFORE the handle does), then
        release every handle."""
        if self._closed:
            return
        self._closed = True
        import ray_tpu
        try:
            if self._inflight:
                irefs = [e[0] for e in self._inflight.values()]
                ray_tpu.wait(irefs, num_returns=len(irefs), timeout=30.0)
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass
        self._inflight.clear()
        if self._fetching is not None:
            try:
                self._fetching[0].result(timeout=30)
            except Exception:  # noqa: BLE001
                pass
            self._fetching = None
        if self._pending_sample is not None:
            try:
                ray_tpu.get(self._pending_sample)
            except Exception:  # noqa: BLE001
                pass
            self._pending_sample = None
        self._pool.shutdown(wait=True)
        try:
            if self.replay is not None:
                ray_tpu.get(self.replay.clear.remote())
        except Exception:  # noqa: BLE001
            pass
        self.replay = None
        self.actors = []
