"""Action distributions (reference: rllib/models/torch/torch_distributions.py).

Pure-jnp, usable inside jit on TPU and on the CPU inference path in
EnvRunners. Each distribution is a thin struct over its parameters; methods
are vectorized over leading batch dims.
"""

from typing import Tuple

import jax
import jax.numpy as jnp


class Categorical:
    def __init__(self, logits: jax.Array):
        self.logits = logits  # [..., n]

    def sample(self, key) -> jax.Array:
        return jax.random.categorical(key, self.logits, axis=-1)

    def mode(self) -> jax.Array:
        return jnp.argmax(self.logits, axis=-1)

    def log_prob(self, x: jax.Array) -> jax.Array:
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return jnp.take_along_axis(logp, x[..., None].astype(jnp.int32), -1)[..., 0]

    def entropy(self) -> jax.Array:
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return -jnp.sum(jnp.exp(logp) * logp, axis=-1)

    def kl(self, other: "Categorical") -> jax.Array:
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        logq = jax.nn.log_softmax(other.logits, axis=-1)
        return jnp.sum(jnp.exp(logp) * (logp - logq), axis=-1)


class DiagGaussian:
    def __init__(self, mean: jax.Array, log_std: jax.Array):
        self.mean = mean
        self.log_std = jnp.broadcast_to(log_std, mean.shape)

    def sample(self, key) -> jax.Array:
        return self.mean + jnp.exp(self.log_std) * jax.random.normal(
            key, self.mean.shape, self.mean.dtype)

    def mode(self) -> jax.Array:
        return self.mean

    def log_prob(self, x: jax.Array) -> jax.Array:
        var = jnp.exp(2 * self.log_std)
        ll = -0.5 * (jnp.square(x - self.mean) / var
                     + 2 * self.log_std + jnp.log(2 * jnp.pi))
        return jnp.sum(ll, axis=-1)

    def entropy(self) -> jax.Array:
        return jnp.sum(self.log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e), axis=-1)

    def kl(self, other: "DiagGaussian") -> jax.Array:
        var, ovar = jnp.exp(2 * self.log_std), jnp.exp(2 * other.log_std)
        return jnp.sum(other.log_std - self.log_std
                       + (var + jnp.square(self.mean - other.mean)) / (2 * ovar)
                       - 0.5, axis=-1)


class SquashedGaussian:
    """tanh-squashed gaussian for SAC (bounded continuous actions)."""

    def __init__(self, mean: jax.Array, log_std: jax.Array,
                 low: float = -1.0, high: float = 1.0):
        self.base = DiagGaussian(mean, jnp.clip(log_std, -20.0, 2.0))
        self.low, self.high = low, high

    def _squash(self, u):
        t = jnp.tanh(u)
        return self.low + (t + 1.0) * 0.5 * (self.high - self.low)

    def sample_and_log_prob(self, key) -> Tuple[jax.Array, jax.Array]:
        u = self.base.sample(key)
        a = self._squash(u)
        # log det of tanh + affine correction, numerically-stable softplus form
        correction = jnp.sum(
            2.0 * (jnp.log(2.0) - u - jax.nn.softplus(-2.0 * u)), axis=-1)
        scale = jnp.log((self.high - self.low) * 0.5 + 1e-8)
        logp = self.base.log_prob(u) - correction - scale * u.shape[-1]
        return a, logp

    def log_prob(self, a: jax.Array) -> jax.Array:
        """Density of a squashed action (inverse-tanh change of variables);
        needed by offline losses (CQL bc warmstart, BC on SAC data)."""
        # unsquash: a -> u = atanh(2*(a-low)/(high-low) - 1), clipped inside
        # the open interval so atanh stays finite on boundary actions
        t = 2.0 * (a - self.low) / (self.high - self.low) - 1.0
        t = jnp.clip(t, -1.0 + 1e-6, 1.0 - 1e-6)
        u = jnp.arctanh(t)
        correction = jnp.sum(
            2.0 * (jnp.log(2.0) - u - jax.nn.softplus(-2.0 * u)), axis=-1)
        scale = jnp.log((self.high - self.low) * 0.5 + 1e-8)
        return self.base.log_prob(u) - correction - scale * u.shape[-1]

    def mode(self) -> jax.Array:
        return self._squash(self.base.mean)
