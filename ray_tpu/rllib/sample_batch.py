"""SampleBatch — the rollout data container (reference:
rllib/policy/sample_batch.py).

A dict of numpy/jax arrays with standard column names. Rollout batches are
[T, B, ...] (time-major: the GAE scan runs over axis 0 without transposes);
`flatten()` collapses to [T*B, ...] for SGD minibatching. All shapes are
static per config so the learner's jitted update never recompiles.
"""

from typing import Dict, Iterator, List

import numpy as np

OBS = "obs"
NEXT_OBS = "next_obs"
ACTIONS = "actions"
REWARDS = "rewards"
TERMINATEDS = "terminateds"
TRUNCATEDS = "truncateds"
DONES = "dones"
LOGP = "action_logp"
VF_PREDS = "vf_preds"
ADVANTAGES = "advantages"
VALUE_TARGETS = "value_targets"
BOOTSTRAP_VALUE = "bootstrap_value"


class SampleBatch(dict):
    @property
    def count(self) -> int:
        for v in self.values():
            if hasattr(v, "shape") and v.ndim >= 1:
                return int(np.prod(v.shape[:1]))
        return 0

    def flatten(self) -> "SampleBatch":
        """[T, B, ...] → [T*B, ...] (skips scalar entries)."""
        out = SampleBatch()
        for k, v in self.items():
            v = np.asarray(v)
            out[k] = v.reshape((-1,) + v.shape[2:]) if v.ndim >= 2 else v
        return out

    def shuffle(self, rng: np.random.Generator) -> "SampleBatch":
        n = self.count
        perm = rng.permutation(n)
        return SampleBatch({k: np.asarray(v)[perm] if np.asarray(v).ndim >= 1
                            else v for k, v in self.items()})

    def minibatches(self, size: int) -> Iterator["SampleBatch"]:
        n = self.count
        for i in range(0, n - size + 1, size):
            yield SampleBatch({k: np.asarray(v)[i:i + size]
                               if np.asarray(v).ndim >= 1 else v
                               for k, v in self.items()})

    @staticmethod
    def concat(batches: List["SampleBatch"], axis: int = 1) -> "SampleBatch":
        """Concat rollouts from several runners along the env/batch axis."""
        if len(batches) == 1:
            return batches[0]
        keys = batches[0].keys()
        out = SampleBatch()
        for k in keys:
            vs = [np.asarray(b[k]) for b in batches]
            out[k] = (np.concatenate(vs, axis=axis if vs[0].ndim > axis else 0)
                      if vs[0].ndim >= 1 else vs[0])
        return out

    def to_device(self, sharding=None):
        import jax
        arrs = {k: np.asarray(v) for k, v in self.items()}
        return (jax.device_put(arrs, sharding) if sharding is not None
                else jax.device_put(arrs))
