"""Offline RL data path (reference: rllib/offline/ — JsonReader/DatasetReader
feeding SampleBatches).

Bridges `ray_tpu.data` Datasets and SampleBatch: recorded experience lives in
parquet/arrow blocks (streamed, spillable) and trains offline algorithms
(BC/MARWIL/CQL) without an environment. Multi-dim columns (obs, actions) are
flattened per row for arrow and restored from a stored shape column.
"""

from typing import Dict, Optional, Union

import numpy as np

from . import sample_batch as SB
from .sample_batch import SampleBatch

_COLS = (SB.OBS, SB.ACTIONS, SB.REWARDS, SB.NEXT_OBS, SB.TERMINATEDS)


def sample_batch_to_dataset(batch: SampleBatch, num_blocks: int = 8):
    """Flatten a SampleBatch into a ray_tpu.data Dataset (row-per-timestep)."""
    import ray_tpu.data as rdata

    cols: Dict[str, np.ndarray] = {}
    shapes: Dict[str, tuple] = {}
    n = None
    for k in _COLS:
        if k not in batch:
            continue
        v = np.asarray(batch[k])
        if n is None:
            n = len(v)
        elif len(v) != n:
            raise ValueError(f"column {k!r} has {len(v)} rows, expected {n} "
                             f"(pass per-timestep columns, already flat)")
        shapes[k] = v.shape[1:]
        cols[k] = v.reshape(len(v), -1) if v.ndim > 1 else v
    rows = []
    for i in range(n):
        row = {}
        for k, v in cols.items():
            val = v[i]
            row[k] = val.tolist() if val.ndim else val.item()
        rows.append(row)
    ds = rdata.from_items(rows)
    ds._offline_shapes = shapes  # advisory; parquet round-trips lose it
    return ds


def dataset_to_sample_batch(ds, shapes: Optional[Dict[str, tuple]] = None
                            ) -> SampleBatch:
    """Materialize a ray_tpu.data Dataset into one SampleBatch."""
    import pyarrow as pa

    shapes = shapes or getattr(ds, "_offline_shapes", {})
    tables = list(ds._plan.iter_blocks())
    whole = pa.concat_tables(tables) if len(tables) > 1 else tables[0]
    out = {}
    for k in whole.column_names:
        col = whole[k].to_pylist()
        arr = np.asarray(col, dtype=np.float32)
        shape = shapes.get(k)
        if shape:
            arr = arr.reshape((len(arr),) + tuple(shape))
        out[k] = arr
    return SampleBatch(out)


def as_sample_batch(data: Union[SampleBatch, dict, object]) -> SampleBatch:
    """Accept SampleBatch | dict of arrays | ray_tpu.data Dataset."""
    if isinstance(data, SampleBatch):
        return data
    if isinstance(data, dict):
        return SampleBatch({k: np.asarray(v) for k, v in data.items()})
    if hasattr(data, "_plan"):  # duck-typed Dataset
        return dataset_to_sample_batch(data)
    raise TypeError(f"unsupported offline data {type(data)!r}")
