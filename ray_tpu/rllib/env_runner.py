"""EnvRunner — rollout collection on CPU vector envs (reference:
rllib/env/single_agent_env_runner.py).

Runs gymnasium vector envs and the policy's CPU forward (jax on the host
platform — the TPU stays dedicated to the learner). Emits fixed-shape
[T, B] SampleBatches so the learner's jitted update never recompiles —
this shape contract is load-bearing: the sebulba pipeline asserts the
learner's jit cache holds exactly one entry across a whole run
(`JaxLearner.jit_cache_size`). Deployable as a ray_tpu actor
(`num_env_runners > 0`) or called inline.

Weights may carry a params VERSION (`set_weights(params, version=n)`):
the async sebulba pipeline stamps every trajectory with the version it
was collected under, giving the learner the exact off-policy gap its
V-trace correction is accounting for.
"""

import functools
from typing import Callable, Dict, List, Optional, Union

import numpy as np

from . import sample_batch as SB
from .rl_module import ModuleSpec, RLModule
from .sample_batch import SampleBatch


def _make_vector_env(env_creator, num_envs: int):
    import gymnasium as gym
    try:  # classic semantics: reset obs returned in the same step as done
        from gymnasium.vector import AutoresetMode
        return gym.vector.SyncVectorEnv(
            [env_creator for _ in range(num_envs)],
            autoreset_mode=AutoresetMode.SAME_STEP)
    except (ImportError, TypeError):
        return gym.vector.SyncVectorEnv([env_creator for _ in range(num_envs)])


def resolve_env_creator(name: str, env_config: Optional[dict] = None):
    """String env → callable, DRIVER-side: tune.register_env names win
    over gym ids (ref: rllib resolves through tune/registry.py before
    gym.make). Must run where the registration happened — the returned
    CALLABLE then pickles by value into remote runner actors, whose own
    process-local registry is empty. Each invocation hands the creator a
    fresh dict copy (vector envs call it N times; a creator that pops
    keys must not corrupt its siblings' config)."""
    from ray_tpu.tune.registry import get_env_creator
    registered = get_env_creator(name)
    if registered is not None:
        return lambda: registered(dict(env_config or {}))
    import gymnasium as gym
    return functools.partial(gym.make, name, **(env_config or {}))


class EnvRunner:
    def __init__(self, env_creator: Union[str, Callable], *,
                 num_envs: int = 1, rollout_len: int = 200,
                 module_spec: Optional[ModuleSpec] = None,
                 module=None, explore: bool = True, seed: int = 0,
                 gamma: float = 0.99, record_next_obs: bool = False,
                 env_config: Optional[dict] = None):
        if isinstance(env_creator, str):
            env_creator = resolve_env_creator(env_creator, env_config)
        self.envs = _make_vector_env(env_creator, num_envs)
        self.num_envs = num_envs
        self.rollout_len = rollout_len
        self.explore = explore
        self.record_next_obs = record_next_obs  # off-policy algos need (s, s')
        spec = module_spec or ModuleSpec.from_spaces(
            self.envs.single_observation_space, self.envs.single_action_space)
        # custom module (e.g. Q-network policies) must expose the RLModule
        # interface: init/forward/explore_step/inference_step + .spec
        self.module = module if module is not None else RLModule(spec)
        self.params = None
        self.params_version = -1  # -1 = never versioned (sync path)
        self._step_count = 0
        self._seed = seed
        # episode bookkeeping for metrics
        self._ep_return = np.zeros(num_envs, np.float64)
        self._ep_len = np.zeros(num_envs, np.int64)
        self._completed: List[Dict] = []
        self._obs = None
        self._jit_explore = None
        self._jit_values = None

    # -- weights ------------------------------------------------------------
    def set_weights(self, params, version: Optional[int] = None):
        self.params = params
        if version is not None:
            self.params_version = int(version)
            from ray_tpu.util import metrics
            metrics.get_or_create(
                metrics.Gauge, "rllib_param_version",
                "params version in use (learner: published; "
                "rollout: received)", tag_keys=("role",)).set(
                    self.params_version, tags={"role": "rollout"})

    def get_spec(self) -> ModuleSpec:
        return self.module.spec

    def init_params(self):
        """Fresh params (used when the runner bootstraps the algorithm)."""
        import jax
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            return jax.device_get(self.module.init(jax.random.PRNGKey(self._seed)))

    # -- rollouts -----------------------------------------------------------
    def _ensure_jit(self):
        import jax
        if self._jit_explore is None:
            # acting runs on host CPU — the TPU belongs to the learner
            self._cpu = jax.local_devices(backend="cpu")[0]

            def explore(params, obs, key):
                return self.module.explore_step(params, obs, key)

            def infer(params, obs):
                a, v = self.module.inference_step(params, obs)
                return a, jax.numpy.zeros(v.shape), v

            def values(params, obs):
                _, v = self.module.forward(params, obs)
                return v

            self._jit_explore = jax.jit(explore if self.explore else
                                        lambda p, o, k: infer(p, o))
            self._jit_values = jax.jit(values)

    def sample(self, params=None) -> SampleBatch:
        """Collect one [T, B] rollout continuing from the last state."""
        import jax
        if params is not None:
            self.params = params
        assert self.params is not None, "set_weights() before sample()"
        self._ensure_jit()
        if self._obs is None:
            self._obs, _ = self.envs.reset(seed=self._seed)

        key = jax.random.PRNGKey(self._seed ^ 0x5eed)
        with jax.default_device(self._cpu):  # acting stays off the TPU
            return self._rollout(key)

    def _rollout(self, key):
        import jax
        T, B = self.rollout_len, self.num_envs
        obs_buf = np.empty((T, B) + self.envs.single_observation_space.shape,
                           np.float32)
        next_obs_buf = (np.empty_like(obs_buf) if self.record_next_obs
                        else None)
        actions_buf = None
        rewards = np.empty((T, B), np.float32)
        dones = np.empty((T, B), np.float32)
        terms = np.empty((T, B), np.float32)
        logps = np.empty((T, B), np.float32)
        vfs = np.empty((T, B), np.float32)
        obs = self._obs
        for t in range(T):
            self._step_count += 1
            k = jax.random.fold_in(key, self._step_count)
            action, logp, value = self._jit_explore(
                self.params, obs.astype(np.float32), k)
            action = np.asarray(action)
            if actions_buf is None:
                actions_buf = np.empty((T, B) + action.shape[1:], action.dtype)
            next_obs, rew, term, trunc, _info = self.envs.step(action)
            obs_buf[t] = obs
            if next_obs_buf is not None:
                next_obs_buf[t] = next_obs
            actions_buf[t] = action
            rewards[t] = rew
            terms[t] = term
            dones[t] = np.logical_or(term, trunc)
            logps[t] = np.asarray(logp)
            vfs[t] = np.asarray(value)
            # metrics
            self._ep_return += rew
            self._ep_len += 1
            for i in np.nonzero(dones[t])[0]:
                self._completed.append({"return": float(self._ep_return[i]),
                                        "len": int(self._ep_len[i])})
                self._ep_return[i] = 0.0
                self._ep_len[i] = 0
            obs = next_obs
        self._obs = obs

        # bootstrap value of the state after the last step; zero if that env
        # terminated there (SAME_STEP autoreset → obs is the reset obs, and a
        # terminal state's future return is 0 anyway)
        boot = np.asarray(self._jit_values(self.params, obs.astype(np.float32)))
        boot = boot * (1.0 - terms[-1])

        out = SampleBatch({
            SB.OBS: obs_buf, SB.ACTIONS: actions_buf, SB.REWARDS: rewards,
            SB.DONES: dones, SB.TERMINATEDS: terms, SB.LOGP: logps,
            SB.VF_PREDS: vfs, SB.BOOTSTRAP_VALUE: boot,
        })
        if next_obs_buf is not None:
            out[SB.NEXT_OBS] = next_obs_buf
        return out

    def run_eval(self, params, num_episodes: int) -> Dict:
        """Sample until `num_episodes` complete; returns episode metrics.
        One remote call per eval round so a dedicated evaluation actor runs
        fully in parallel with training (reference: eval worker set)."""
        self.set_weights(params)
        self._completed = []
        while len(self._completed) < num_episodes:
            self.sample()
        return self.pop_metrics()

    # -- metrics ------------------------------------------------------------
    def node_info(self) -> Dict:
        """Where this runner lives — lets drivers/tests verify cluster
        placement (multi-node SPREAD, BASELINE config #5 shape)."""
        import os
        return {"pid": os.getpid(), "ppid": os.getppid(),
                "hostname": __import__("socket").gethostname()}

    def num_completed_episodes(self) -> int:
        return len(self._completed)

    def pop_metrics(self) -> Dict:
        eps = self._completed
        self._completed = []
        if not eps:
            return {"episodes_this_iter": 0}
        rets = [e["return"] for e in eps]
        lens = [e["len"] for e in eps]
        return {
            "episodes_this_iter": len(eps),
            "episode_return_mean": float(np.mean(rets)),
            "episode_return_max": float(np.max(rets)),
            "episode_return_min": float(np.min(rets)),
            "episode_len_mean": float(np.mean(lens)),
        }

    def close(self):
        self.envs.close()
