"""Shared helpers for the offline continuous-control algorithms (CQL, IQL).

Both load a (obs, actions, rewards, next_obs, terminateds) dataset, infer a
continuous ModuleSpec + action bounds from it, and evaluate by rolling the
squashed-gaussian actor's mode in a real env — factored here so the logic
can't drift between them."""

from typing import Dict, Tuple

import numpy as np

from .. import sample_batch as SB
from ..offline import as_sample_batch
from ..rl_module import ModuleSpec


def load_continuous_dataset(config) -> Tuple[Dict[str, np.ndarray], int,
                                             ModuleSpec, float, float]:
    """Returns (data, n_rows, spec, action_low, action_high)."""
    batch = as_sample_batch(config.offline_data)
    data = {k: np.asarray(batch[k]) for k in
            (SB.OBS, SB.ACTIONS, SB.REWARDS, SB.NEXT_OBS, SB.TERMINATEDS)}
    acts = data[SB.ACTIONS]
    if acts.ndim == 1:
        acts = acts[:, None]
        data[SB.ACTIONS] = acts
    obs_shape = data[SB.OBS].shape[1:]
    low = (config.action_low if config.action_low is not None
           else float(acts.min()))
    high = (config.action_high if config.action_high is not None
            else float(acts.max()))
    spec = ModuleSpec(obs_shape, "continuous", acts.shape[-1],
                      tuple(config.model.get("hiddens", (256, 256))))
    return data, len(data[SB.OBS]), spec, low, high


def make_offline_optimizer(config, weights, net_keys):
    """One optax optimizer shared by the per-net opt_states (CQL: q nets +
    actor + alpha; IQL: q nets + actor + value). Returns (opt, schedule_fn,
    opt_state)."""
    from ray_tpu.ops.optim import make_optimizer
    opt, sched = make_optimizer(
        lr=config.lr, lr_schedule=getattr(config, "lr_schedule", None),
        optimizer=getattr(config, "optimizer", "adam"),
        grad_clip=getattr(config, "grad_clip", None))
    return opt, sched, {k: opt.init(weights[k]) for k in net_keys}


def offline_training_step(algo, step_once) -> Dict:
    """Shared minibatch SGD loop: `step_once(minibatch, update_index)` runs
    the algo's jitted update and returns (weights, opt_state, metrics).
    cur_lr reports the lr of the LAST update applied (schedule evaluated at
    the pre-increment count, same convention as JaxLearner)."""
    import jax
    cfg = algo.config
    last = {}
    lr_used = float(algo._lr_schedule(algo._updates))
    for _ in range(cfg.train_intensity):
        idx = algo._rng.integers(0, algo._n, size=cfg.train_batch_size)
        mb = {k: v[idx] for k, v in algo._data.items()}
        lr_used = float(algo._lr_schedule(algo._updates))
        algo.weights, algo.opt_state, last = step_once(mb, algo._updates)
        algo._updates += 1
    learner = {k: float(v) for k, v in jax.device_get(last).items()}
    learner["cur_lr"] = lr_used
    return {"learner": learner, "num_env_steps_sampled_this_iter": 0}


def evaluate_continuous(algo) -> Dict:
    """Mode-policy rollout evaluation for SACModule-weight-layout algos."""
    import jax
    cfg = algo.config
    if cfg.env is None:
        return {}
    import gymnasium as gym
    env = gym.make(cfg.env) if isinstance(cfg.env, str) else cfg.env()
    # compile once per algo instance, not per evaluate() call
    infer = algo.__dict__.get("_eval_infer")
    if infer is None:
        infer = algo._eval_infer = jax.jit(algo.module.inference_step)
    rets, lens = [], []
    for ep in range(cfg.evaluation_duration):
        obs, _ = env.reset(seed=cfg.seed + 10_000 + ep)
        ret, n, done = 0.0, 0, False
        while not done:
            a, _ = infer(algo.weights, obs[None].astype(np.float32))
            a = np.clip(np.asarray(a)[0], algo.module.low, algo.module.high)
            obs, r, term, trunc, _ = env.step(a)
            ret += float(r)
            n += 1
            done = term or trunc
        rets.append(ret)
        lens.append(n)
    env.close()
    return {"episodes_this_iter": len(rets),
            "episode_return_mean": float(np.mean(rets)),
            "episode_return_max": float(np.max(rets)),
            "episode_return_min": float(np.min(rets)),
            "episode_len_mean": float(np.mean(lens))}
