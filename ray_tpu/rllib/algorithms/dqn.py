"""DQN + Double/Dueling (reference: rllib/algorithms/dqn/*).

TPU framing: the whole TD update (online+target forward, huber, adam) is one
jitted program; the target network params travel as an explicit input so the
periodic sync is just a host-side pointer swap, never a retrace.
"""

from typing import Dict

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models.torsos import CNNTorso, MLPTorso
from ray_tpu.ops.losses import huber
from .. import sample_batch as SB
from ..algorithm import Algorithm, AlgorithmConfig, _merge_runner_metrics
from ..buffers import PrioritizedReplayBuffer, ReplayBuffer
from ..rl_module import ModuleSpec
from ..sample_batch import SampleBatch


class QNet(nn.Module):
    spec: ModuleSpec
    dueling: bool = False

    @nn.compact
    def __call__(self, obs):
        spec = self.spec
        torso = CNNTorso() if spec.use_cnn else MLPTorso(spec.hiddens)
        z = torso(obs)
        if self.dueling:
            adv = nn.Dense(spec.action_dim, name="adv")(z)
            val = nn.Dense(1, name="val")(z)
            return val + adv - adv.mean(axis=-1, keepdims=True)
        return nn.Dense(spec.action_dim, name="q")(z)


class DQNModule:
    """Epsilon-greedy acting over a Q-net; RLModule-compatible surface."""

    def __init__(self, spec: ModuleSpec, dueling: bool = False):
        if spec.action_kind != "discrete":
            raise ValueError("DQN needs a discrete action space")
        self.spec = spec
        self.net = QNet(spec, dueling)

    def init(self, key):
        obs = jnp.zeros((1,) + self.spec.obs_shape, jnp.float32)
        return {"params": self.net.init(key, obs), "epsilon": jnp.asarray(1.0)}

    def _q(self, weights, obs):
        lead = obs.shape[: obs.ndim - len(self.spec.obs_shape)]
        flat = obs.reshape((-1,) + self.spec.obs_shape)
        q = self.net.apply(weights["params"], flat)
        return q.reshape(lead + (self.spec.action_dim,))

    def forward(self, weights, obs):
        q = self._q(weights, obs)
        return q, q.max(axis=-1)

    def explore_step(self, weights, obs, key):
        q = self._q(weights, obs)
        greedy = q.argmax(axis=-1)
        k1, k2 = jax.random.split(key)
        random_a = jax.random.randint(k1, greedy.shape, 0,
                                      self.spec.action_dim)
        take_random = jax.random.uniform(k2, greedy.shape) < weights["epsilon"]
        action = jnp.where(take_random, random_a, greedy)
        return action, jnp.zeros(action.shape), q.max(axis=-1)

    def inference_step(self, weights, obs):
        q = self._q(weights, obs)
        return q.argmax(axis=-1), q.max(axis=-1)


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = DQN
        self.lr = 5e-4
        self.train_batch_size = 32
        self.replay_buffer_capacity = 50_000
        self.num_steps_sampled_before_learning_starts = 1000
        self.target_network_update_freq = 500   # in SGD steps
        self.train_intensity = 1                # SGD steps per env step batch
        self.double_q = True
        self.dueling = False
        self.prioritized_replay = False
        self.per_alpha = 0.6
        self.per_beta = 0.4
        self.epsilon_start = 1.0
        self.epsilon_end = 0.02
        self.epsilon_decay_steps = 10_000
        self.rollout_fragment_length = 4
        self.grad_clip = 10.0


class DQN(Algorithm):
    def setup(self, config: DQNConfig):
        from ..env_runner import EnvRunner
        # probe the spaces first: runners need the Q-module at construction
        probe = EnvRunner(env_creator=config.env, num_envs=1, rollout_len=2,
                          env_config=config.env_config)
        spec = probe.get_spec()
        probe.close()
        self.module = DQNModule(spec, dueling=config.dueling)
        self._setup_runners()
        key = jax.random.PRNGKey(config.seed)
        self.weights = self.module.init(key)
        self.target_params = self.weights["params"]
        import optax
        tx = [optax.clip_by_global_norm(config.grad_clip)] \
            if config.grad_clip else []
        self.opt = optax.chain(*tx, optax.adam(config.lr))
        self.opt_state = self.opt.init(self.weights["params"])
        buf_cls = (PrioritizedReplayBuffer if config.prioritized_replay
                   else ReplayBuffer)
        kw = {"alpha": config.per_alpha} if config.prioritized_replay else {}
        self.buffer = buf_cls(config.replay_buffer_capacity,
                              seed=config.seed, **kw)
        self.env_steps = 0
        self.sgd_steps = 0
        self._build_update()

    def _make_runner_kwargs(self):
        kw = super()._make_runner_kwargs()
        kw["module"] = DQNModule(self.module.spec,
                                 dueling=self.config.dueling)
        kw["record_next_obs"] = True
        return kw

    def _build_update(self):
        cfg = self.config
        net = self.module.net
        gamma = cfg.gamma
        double_q = cfg.double_q

        def td_loss(params, target_params, batch):
            q = net.apply(params, batch[SB.OBS])
            q_taken = jnp.take_along_axis(
                q, batch[SB.ACTIONS][:, None].astype(jnp.int32), -1)[:, 0]
            q_next_t = net.apply(target_params, batch[SB.NEXT_OBS])
            if double_q:
                a_star = net.apply(params, batch[SB.NEXT_OBS]).argmax(-1)
                q_next = jnp.take_along_axis(
                    q_next_t, a_star[:, None], -1)[:, 0]
            else:
                q_next = q_next_t.max(-1)
            target = batch[SB.REWARDS] + gamma * (
                1.0 - batch[SB.TERMINATEDS]) * q_next
            td = q_taken - jax.lax.stop_gradient(target)
            w = batch.get("_weights", jnp.ones_like(td))
            loss = jnp.mean(w * huber(td))
            return loss, {"td_abs": jnp.abs(td), "qmean": q_taken.mean()}

        def update(params, target_params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(
                td_loss, has_aux=True)(params, target_params, batch)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            import optax
            params = optax.apply_updates(params, updates)
            aux["loss"] = loss
            return params, opt_state, aux

        self._update = jax.jit(update, donate_argnums=(2,))

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(self.env_steps / max(cfg.epsilon_decay_steps, 1), 1.0)
        return cfg.epsilon_start + frac * (cfg.epsilon_end - cfg.epsilon_start)

    def training_step(self) -> Dict:
        cfg = self.config
        self.weights = {"params": self.weights["params"],
                        "epsilon": jnp.asarray(self._epsilon())}
        batch, rm = self._sample_all(jax.device_get(self.weights))
        flat = batch.flatten()
        self.env_steps += flat.count
        self.buffer.add_batch({
            SB.OBS: flat[SB.OBS], SB.ACTIONS: flat[SB.ACTIONS],
            SB.REWARDS: flat[SB.REWARDS], SB.NEXT_OBS: flat[SB.NEXT_OBS],
            SB.TERMINATEDS: flat[SB.TERMINATEDS]})

        metrics: Dict = _merge_runner_metrics([rm])
        metrics["num_env_steps_sampled_this_iter"] = flat.count
        metrics["epsilon"] = float(self._epsilon())
        if self.env_steps < cfg.num_steps_sampled_before_learning_starts:
            return metrics

        losses = []
        for _ in range(cfg.train_intensity):
            if cfg.prioritized_replay:
                sample = self.buffer.sample(cfg.train_batch_size,
                                            beta=cfg.per_beta)
                indices = sample.pop("_indices")
            else:
                sample = self.buffer.sample(cfg.train_batch_size)
                indices = None
            params, self.opt_state, aux = self._update(
                self.weights["params"], self.target_params,
                self.opt_state, sample)
            self.weights["params"] = params
            self.sgd_steps += 1
            if indices is not None:
                self.buffer.update_priorities(
                    indices, np.asarray(aux["td_abs"]))
            losses.append(float(aux["loss"]))
            if self.sgd_steps % cfg.target_network_update_freq == 0:
                self.target_params = self.weights["params"]
        metrics["learner"] = {"loss": float(np.mean(losses)),
                              "sgd_steps": self.sgd_steps}
        return metrics

    def get_weights(self):
        return jax.device_get(self.weights)

    def set_weights(self, weights):
        self.weights = weights
        self.target_params = weights["params"]
