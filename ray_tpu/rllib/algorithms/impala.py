"""IMPALA (reference: rllib/algorithms/impala/*) — V-trace actor-critic.

Off-policy correction comes from `ops.losses.vtrace` (scan-based, vmapped
over the env axis), so stale-weights rollouts from many runners stay usable.
The whole [T, B] sequence updates in ONE jitted step — no minibatching, per
the reference's learner.
"""

from typing import Dict

import jax
import jax.numpy as jnp

from ray_tpu.ops.losses import vtrace
from .. import sample_batch as SB
from ..algorithm import Algorithm, AlgorithmConfig, _merge_runner_metrics
from ..learner import JaxLearner, _host_metrics, make_learner_group
from ..rl_module import RLModule
from ..sample_batch import SampleBatch


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = IMPALA
        self.lr = 5e-4
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.vtrace_clip_rho = 1.0
        self.vtrace_clip_c = 1.0
        self.grad_clip = 40.0
        self.rollout_fragment_length = 50
        self.train_batch_size = 500


class IMPALALearner(JaxLearner):
    def compute_loss(self, params, batch):
        cfg = self.config
        # [T, B] sequences
        dist_in, values = self.module.forward(params, batch[SB.OBS])
        dist = self.module.dist(dist_in)
        target_logp = dist.log_prob(batch[SB.ACTIONS])

        values_tb1 = jnp.concatenate(
            [values, batch[SB.BOOTSTRAP_VALUE][None]], axis=0)  # [T+1, B]
        vt = jax.vmap(
            lambda blp, tlp, r, v, d: vtrace(
                blp, tlp, r, v, d, cfg.gamma,
                cfg.vtrace_clip_rho, cfg.vtrace_clip_c),
            in_axes=1, out_axes=1,
        )(batch[SB.LOGP], jax.lax.stop_gradient(target_logp),
          batch[SB.REWARDS], values_tb1, batch[SB.DONES])

        pg_loss = -jnp.mean(target_logp * jax.lax.stop_gradient(
            vt.pg_advantages))
        vf_loss = 0.5 * jnp.mean(
            jnp.square(values - jax.lax.stop_gradient(vt.vs)))
        entropy = jnp.mean(dist.entropy())
        loss = (pg_loss + cfg.vf_loss_coeff * vf_loss
                - cfg.entropy_coeff * entropy)
        return loss, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                      "entropy": entropy}

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        keys = (SB.OBS, SB.ACTIONS, SB.LOGP, SB.REWARDS, SB.DONES,
                SB.BOOTSTRAP_VALUE)
        return _host_metrics([self.update_once({k: batch[k] for k in keys})])


class IMPALA(Algorithm):
    # V-trace already corrects for stale behavior policies, so replayed
    # sebulba trajectories (gap ≥ 1) are exactly the intended input
    _supports_sebulba = True

    def setup(self, config: IMPALAConfig):
        self._setup_runners()
        spec = self._local_runner.get_spec()
        self.learner_group = make_learner_group(IMPALALearner, RLModule(spec),
                                                config, seed=config.seed)
        self.learner = self.learner_group.learner

    def training_step(self) -> Dict:
        cfg = self.config
        weights = self.learner.get_weights()
        timesteps = 0
        metrics_list = []
        learn = {}
        while timesteps < cfg.train_batch_size:
            batch, rm = self._sample_all(weights)
            metrics_list.append(rm)
            timesteps += batch[SB.REWARDS].size
            learn = self.learner.update(batch)  # learn per rollout arrival
        result = _merge_runner_metrics(metrics_list)
        result["num_env_steps_sampled_this_iter"] = timesteps
        result["learner"] = learn
        return result

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, weights):
        self.learner.set_weights(weights)
