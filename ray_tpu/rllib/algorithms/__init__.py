from .ppo import PPO, PPOConfig

__all__ = ["PPO", "PPOConfig"]
