from .appo import APPO, APPOConfig
from .bc import BC, BCConfig, MARWIL, MARWILConfig
from .cql import CQL, CQLConfig
from .dqn import DQN, DQNConfig
from .dreamerv3 import DreamerV3, DreamerV3Config
from .impala import IMPALA, IMPALAConfig
from .iql import IQL, IQLConfig
from .ppo import PPO, PPOConfig
from .sac import SAC, SACConfig
from .tqc import TQC, TQCConfig

__all__ = ["PPO", "PPOConfig", "APPO", "APPOConfig", "DQN", "DQNConfig",
           "IMPALA", "IMPALAConfig", "SAC", "SACConfig", "BC", "BCConfig",
           "MARWIL", "MARWILConfig", "CQL", "CQLConfig", "IQL", "IQLConfig",
           "TQC", "TQCConfig", "DreamerV3", "DreamerV3Config"]
