"""DreamerV3 — model-based RL (reference: rllib/algorithms/dreamerv3/
dreamerv3.py, dreamerv3_learner.py, dreamerv3_rl_module.py; paper
arXiv:2301.04104).

Learns a Recurrent State-Space Model (RSSM) world model from replayed
sequences, then trains actor+critic entirely inside imagined rollouts:
- RSSM: deterministic GRU path h_t, discrete stochastic latent z_t
  (stoch x classes categorical with straight-through gradients and 1%
  uniform mixing), posterior q(z|h,embed) vs prior p(z|h) with
  KL-balancing (dyn 0.5 / rep 0.1) and free bits,
- symlog-MSE observation reconstruction, twohot-symlog reward head,
  Bernoulli continue head,
- imagination: H-step rollout under the actor from every posterior state,
  lambda-returns, percentile-normalized REINFORCE actor loss + entropy,
  twohot critic with an EMA slow-critic regularizer.

tpu-first: the observe pass, the imagination rollout, and the backward
lambda-return recursion are all `lax.scan`s inside ONE jitted update — no
python loops over time; the reference's torch learner steps the GRU in a
python for-loop (dreamerv3/torch/models/sequence_model.py).

Env interaction is an inline recurrent loop (the actor carries (h, z)
across env steps), so this algorithm opts out of the generic stateless
EnvRunner fleet the same way CQL does.
"""

from typing import Dict

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..algorithm import Algorithm, AlgorithmConfig


# ----------------------------------------------------------- symlog / twohot
def symlog(x):
    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x):
    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


def twohot(x, bins):
    """Encode scalars as weight over the two nearest bins. x: [...], bins
    [K] ascending → [..., K]."""
    k = bins.shape[0]
    idx = jnp.sum((bins[None, :] <= x[..., None]).astype(jnp.int32), -1) - 1
    idx = jnp.clip(idx, 0, k - 2)
    lo, hi = bins[idx], bins[idx + 1]
    w_hi = jnp.clip((x - lo) / jnp.maximum(hi - lo, 1e-8), 0.0, 1.0)
    oh_lo = jax.nn.one_hot(idx, k) * (1.0 - w_hi)[..., None]
    oh_hi = jax.nn.one_hot(idx + 1, k) * w_hi[..., None]
    return oh_lo + oh_hi


def _bins(k=255, lo=-20.0, hi=20.0):
    return jnp.linspace(lo, hi, k)


# ------------------------------------------------------------------- modules
class _MLP(nn.Module):
    sizes: tuple
    out: int

    @nn.compact
    def __call__(self, x):
        for s in self.sizes:
            x = nn.silu(nn.LayerNorm()(nn.Dense(s)(x)))
        return nn.Dense(self.out)(x)


class _WorldModel(nn.Module):
    """Encoder + RSSM + decoder/reward/continue heads for vector obs."""
    obs_dim: int
    action_dim: int
    deter: int
    stoch: int
    classes: int
    hiddens: tuple
    reward_bins: int = 255

    def setup(self):
        z_dim = self.stoch * self.classes
        self.encoder = _MLP(self.hiddens, self.hiddens[-1])
        self.gru = nn.GRUCell(features=self.deter)
        self.img_in = _MLP((self.hiddens[-1],), self.hiddens[-1])
        self.prior_net = _MLP((self.hiddens[-1],), z_dim)
        self.post_net = _MLP((self.hiddens[-1],), z_dim)
        self.decoder = _MLP(self.hiddens, self.obs_dim)
        self.reward_head = _MLP(self.hiddens, self.reward_bins)
        self.cont_head = _MLP(self.hiddens, 1)

    def __call__(self, obs, a_prev, is_first):
        """Init-only path: touches every submodule so one init() creates all
        params. obs [B,T,obs], a_prev [B,T,A], is_first [B,T]."""
        embed = self.embed(obs)
        b = obs.shape[0]
        h = jnp.zeros((b, self.deter))
        z = jnp.zeros((b, self.stoch * self.classes))
        key = self.make_rng("sample")
        h, z, _, _ = self.obs_step(h, z, a_prev[:, 0], embed[:, 0],
                                   is_first[:, 0], key)
        return self.heads(self.feat(h, z))

    # -- latent utilities
    def _logits(self, raw):
        lg = raw.reshape(raw.shape[:-1] + (self.stoch, self.classes))
        # 1% uniform mixing keeps KL finite and gradients alive
        probs = 0.99 * jax.nn.softmax(lg, -1) + 0.01 / self.classes
        return jnp.log(probs)

    def _sample(self, logits, key):
        idx = jax.random.categorical(key, logits)
        oh = jax.nn.one_hot(idx, self.classes)
        probs = jax.nn.softmax(logits, -1)
        st = oh + probs - jax.lax.stop_gradient(probs)   # straight-through
        return st.reshape(st.shape[:-2] + (self.stoch * self.classes,))

    def feat(self, h, z):
        return jnp.concatenate([h, z], -1)

    # -- one posterior (observe) step: carry (h, z_prev) over time
    def obs_step(self, h, z_prev, a_prev, embed, is_first, key):
        h = jnp.where(is_first[..., None], 0.0, h)
        z_prev = jnp.where(is_first[..., None], 0.0, z_prev)
        a_prev = jnp.where(is_first[..., None], 0.0, a_prev)
        x = self.img_in(jnp.concatenate([z_prev, a_prev], -1))
        h = self.gru(h, x)[1]
        prior_logits = self._logits(self.prior_net(h))
        post_logits = self._logits(
            self.post_net(jnp.concatenate([h, embed], -1)))
        z = self._sample(post_logits, key)
        return h, z, prior_logits, post_logits

    # -- one prior (imagine) step
    def img_step(self, h, z, a, key):
        x = self.img_in(jnp.concatenate([z, a], -1))
        h = self.gru(h, x)[1]
        prior_logits = self._logits(self.prior_net(h))
        z = self._sample(prior_logits, key)
        return h, z

    def embed(self, obs):
        return self.encoder(symlog(obs))

    def heads(self, feat):
        recon = self.decoder(feat)
        reward_logits = self.reward_head(feat)
        cont_logit = self.cont_head(feat)[..., 0]
        return recon, reward_logits, cont_logit

    def reward(self, feat):
        probs = jax.nn.softmax(self.reward_head(feat), -1)
        return symexp(jnp.sum(probs * _bins(self.reward_bins), -1))

    def cont(self, feat):
        return jax.nn.sigmoid(self.cont_head(feat)[..., 0])


class _Actor(nn.Module):
    action_dim: int
    discrete: bool
    hiddens: tuple

    @nn.compact
    def __call__(self, feat):
        out = self.action_dim if self.discrete else 2 * self.action_dim
        return _MLP(self.hiddens, out)(feat)


class _Critic(nn.Module):
    hiddens: tuple
    bins: int = 255

    @nn.compact
    def __call__(self, feat):
        return _MLP(self.hiddens, self.bins)(feat)


def _critic_value(logits, bins):
    return symexp(jnp.sum(jax.nn.softmax(logits, -1) * bins, -1))


# -------------------------------------------------------------------- config
class DreamerV3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = DreamerV3
        # model scale (reference model_size="XS" analog —
        # dreamerv3.py `model_size` presets)
        self.deter = 256
        self.stoch = 8
        self.classes = 8
        self.model = {"hiddens": (256, 256)}
        # world-model loss
        self.free_nats = 1.0
        self.kl_dyn_scale = 0.5
        self.kl_rep_scale = 0.1
        self.wm_lr = 1e-4
        # actor-critic (imagination)
        self.horizon = 15
        self.gamma = 0.997
        self.lambda_ = 0.95
        self.ac_lr = 3e-5
        self.entropy_scale = 3e-4
        self.critic_ema_decay = 0.98
        self.critic_ema_scale = 1.0
        self.return_norm_decay = 0.99
        # replay / schedule
        self.batch_size_B = 8
        self.batch_length_T = 24
        self.replay_capacity = 50_000
        self.rollout_fragment_length = 64   # env steps collected per iter
        self.num_steps_sampled_before_learning_starts = 512
        self.train_intensity = 1            # updates per training_step


# ---------------------------------------------------------- sequence replay
class _SequenceReplay:
    """Flat transition store with is_first markers; samples [B, T] windows
    uniformly (windows may span episode boundaries — obs_step resets on
    is_first, same contract as the reference's episode replay)."""

    def __init__(self, capacity, seed):
        self.capacity = capacity
        self._store = None
        self._n = 0
        self._ptr = 0
        self._rng = np.random.default_rng(seed)

    def add(self, rows: Dict[str, np.ndarray]):
        m = len(next(iter(rows.values())))
        if self._store is None:
            self._store = {k: np.zeros((self.capacity,) + v.shape[1:],
                                       v.dtype) for k, v in rows.items()}
        for k, v in rows.items():
            idx = (self._ptr + np.arange(m)) % self.capacity
            self._store[k][idx] = v
        self._ptr = (self._ptr + m) % self.capacity
        self._n = min(self._n + m, self.capacity)

    def __len__(self):
        return self._n

    def sample(self, b, t):
        # sample in LOGICAL (time) order so no window straddles the ring's
        # write seam: logical 0 is the oldest row (raw _ptr once wrapped)
        base = self._ptr if self._n == self.capacity else 0
        starts = self._rng.integers(0, self._n - t + 1, size=b)
        idx = (base + starts[:, None] + np.arange(t)[None, :]) % self.capacity
        return {k: v[idx] for k, v in self._store.items()}


# ----------------------------------------------------------------- algorithm
class DreamerV3(Algorithm):
    _supports_eval_actors = False

    def setup(self, config: DreamerV3Config):
        import gymnasium as gym
        env = (gym.make(config.env) if isinstance(config.env, str)
               else config.env())
        self._env = env
        obs_space = env.observation_space
        act_space = env.action_space
        self._discrete = hasattr(act_space, "n")
        obs_dim = int(np.prod(obs_space.shape))
        action_dim = (int(act_space.n) if self._discrete
                      else int(np.prod(act_space.shape)))
        if not self._discrete:
            self._act_low = np.asarray(act_space.low, np.float32)
            self._act_high = np.asarray(act_space.high, np.float32)
        hiddens = tuple(config.model.get("hiddens", (256, 256)))
        self.wm = _WorldModel(obs_dim, action_dim, config.deter,
                              config.stoch, config.classes, hiddens)
        self.actor = _Actor(action_dim, self._discrete, hiddens)
        self.critic = _Critic(hiddens)

        key = jax.random.PRNGKey(config.seed)
        k_wm, k_a, k_c, self._act_key = jax.random.split(key, 4)
        z_dim = config.stoch * config.classes
        feat0 = jnp.zeros((1, config.deter + z_dim))
        obs0 = jnp.zeros((1, 1, obs_dim))
        a0 = jnp.zeros((1, 1, action_dim))
        first0 = jnp.ones((1, 1))
        self.weights = {
            "wm": self.wm.init({"params": k_wm, "sample": k_wm},
                               obs0, a0, first0),
            "actor": self.actor.init(k_a, feat0),
            "critic": self.critic.init(k_c, feat0),
        }
        self.weights["critic_ema"] = jax.tree_util.tree_map(
            jnp.copy, self.weights["critic"])
        import optax
        self.wm_opt = optax.chain(optax.clip_by_global_norm(1000.0),
                                  optax.adam(config.wm_lr))
        self.ac_opt = optax.chain(optax.clip_by_global_norm(100.0),
                                  optax.adam(config.ac_lr))
        self.opt_state = {
            "wm": self.wm_opt.init(self.weights["wm"]),
            "actor": self.ac_opt.init(self.weights["actor"]),
            "critic": self.ac_opt.init(self.weights["critic"])}
        # return-normalization EMA of (p95 - p5)
        self.ret_scale = jnp.asarray(1.0)
        self.replay = _SequenceReplay(config.replay_capacity, config.seed)
        self.env_steps = 0
        self._updates = 0
        # recurrent acting state
        self._h = np.zeros(config.deter, np.float32)
        self._z = np.zeros(z_dim, np.float32)
        self._a_prev = np.zeros(action_dim, np.float32)
        self._obs, _ = env.reset(seed=config.seed)
        self._is_first = True
        self._r_arrival = 0.0
        self._ep_ret = 0.0
        self._ep_len = 0
        import collections
        self._ep_returns = collections.deque(maxlen=100)
        self._ep_lens = collections.deque(maxlen=100)
        self._build_fns()

    # ------------------------------------------------------------- jit: act
    def _build_fns(self):
        cfg = self.config
        wm, actor, critic = self.wm, self.actor, self.critic
        discrete = self._discrete
        bins = _bins()

        def act(w, h, z, a_prev, obs, is_first, key):
            k_post, k_act = jax.random.split(key)
            embed = wm.apply(w["wm"], obs[None], method=_WorldModel.embed)
            h, z, _, _ = wm.apply(
                w["wm"], h[None], z[None], a_prev[None], embed,
                jnp.asarray([is_first], jnp.float32), k_post,
                method=_WorldModel.obs_step)
            feat = jnp.concatenate([h, z], -1)
            out = actor.apply(w["actor"], feat)
            if discrete:
                a_idx = jax.random.categorical(k_act, out[0])
                a = jax.nn.one_hot(a_idx, out.shape[-1])
            else:
                d = out.shape[-1] // 2
                mean, log_std = out[0, :d], jnp.clip(out[0, d:], -5, 2)
                a = jnp.tanh(mean + jnp.exp(log_std) *
                             jax.random.normal(k_act, (d,)))
            return h[0], z[0], a

        self._act = jax.jit(act)

        # --------------------------------------------------------- jit: update
        B, T, H = cfg.batch_size_B, cfg.batch_length_T, cfg.horizon
        gamma, lam = cfg.gamma, cfg.lambda_

        def wm_loss(wp, batch, key):
            obs, act_seq = batch["obs"], batch["action"]
            rew, cont = batch["reward"], 1.0 - batch["is_terminated"]
            is_first = batch["is_first"]
            embed = wm.apply(wp, obs, method=_WorldModel.embed)  # [B,T,E]
            z_dim = cfg.stoch * cfg.classes
            h0 = jnp.zeros((B, cfg.deter))
            z0 = jnp.zeros((B, z_dim))
            # previous action at step t is act[t-1] (zero at t=0)
            a_prev = jnp.concatenate(
                [jnp.zeros_like(act_seq[:, :1]), act_seq[:, :-1]], 1)
            keys = jax.random.split(key, T)

            def step(carry, xs):
                h, z = carry
                a_p, emb, first, k = xs
                h, z, prior_lg, post_lg = wm.apply(
                    wp, h, z, a_p, emb, first, k,
                    method=_WorldModel.obs_step)
                return (h, z), (h, z, prior_lg, post_lg)

            xs = (jnp.moveaxis(a_prev, 0, 1), jnp.moveaxis(embed, 0, 1),
                  jnp.moveaxis(is_first, 0, 1), keys)
            _, (hs, zs, prior_lg, post_lg) = jax.lax.scan(
                step, (h0, z0), xs)
            hs = jnp.moveaxis(hs, 0, 1)          # [B,T,deter]
            zs = jnp.moveaxis(zs, 0, 1)
            prior_lg = jnp.moveaxis(prior_lg, 0, 1)
            post_lg = jnp.moveaxis(post_lg, 0, 1)
            feat = jnp.concatenate([hs, zs], -1)
            recon, rlogits, clogit = wm.apply(wp, feat,
                                              method=_WorldModel.heads)
            recon_loss = jnp.mean(
                jnp.sum(jnp.square(recon - symlog(obs)), -1))
            rtarget = twohot(symlog(rew), bins)
            reward_loss = -jnp.mean(jnp.sum(
                rtarget * jax.nn.log_softmax(rlogits, -1), -1))
            cont_loss = jnp.mean(
                jnp.maximum(clogit, 0) - clogit * cont +
                jnp.log1p(jnp.exp(-jnp.abs(clogit))))

            def kl(p_lg, q_lg):
                # KL(post||prior) per latent, summed over stoch dims
                return jnp.sum(jnp.sum(
                    jnp.exp(p_lg) * (p_lg - q_lg), -1), -1)

            # free bits clip PER STATE, before the mean — clipping the mean
            # would zero ALL KL gradients once the average dips under the
            # threshold, letting outlier states' priors drift unpenalized
            dyn = jnp.mean(jnp.maximum(
                cfg.free_nats, kl(jax.lax.stop_gradient(post_lg), prior_lg)))
            rep = jnp.mean(jnp.maximum(
                cfg.free_nats, kl(post_lg, jax.lax.stop_gradient(prior_lg))))
            loss = (recon_loss + reward_loss + cont_loss +
                    cfg.kl_dyn_scale * dyn + cfg.kl_rep_scale * rep)
            metrics = {"wm_recon": recon_loss, "wm_reward": reward_loss,
                       "wm_cont": cont_loss, "wm_kl_dyn": dyn,
                       "wm_kl_rep": rep}
            return loss, (hs, zs, metrics)

        def actor_dist(ap, feat, key):
            out = actor.apply(ap, feat)
            if discrete:
                logp_all = jax.nn.log_softmax(out, -1)
                a_idx = jax.random.categorical(key, out)
                a = jax.nn.one_hot(a_idx, out.shape[-1])
                logp = jnp.sum(a * logp_all, -1)
                ent = -jnp.sum(jnp.exp(logp_all) * logp_all, -1)
            else:
                d = out.shape[-1] // 2
                mean, log_std = out[..., :d], jnp.clip(out[..., d:], -5, 2)
                eps = jax.random.normal(key, mean.shape)
                pre = mean + jnp.exp(log_std) * eps
                a = jnp.tanh(pre)
                base = (-0.5 * jnp.square(eps) - log_std -
                        0.5 * jnp.log(2 * jnp.pi))
                logp = jnp.sum(base - jnp.log1p(-jnp.square(a) + 1e-6), -1)
                ent = jnp.sum(log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e), -1)
            return a, logp, ent

        def actor_logp_ent(ap, feat, a):
            """Log-prob of GIVEN actions under the actor at feat — the
            REINFORCE estimator needs the rollout's own actions, not a fresh
            sample (a fresh sample's score is independent of the advantage
            and its expected gradient is zero)."""
            out = actor.apply(ap, feat)
            if discrete:
                logp_all = jax.nn.log_softmax(out, -1)
                logp = jnp.sum(a * logp_all, -1)
                ent = -jnp.sum(jnp.exp(logp_all) * logp_all, -1)
            else:
                d = out.shape[-1] // 2
                mean, log_std = out[..., :d], jnp.clip(out[..., d:], -5, 2)
                a_c = jnp.clip(a, -1 + 1e-6, 1 - 1e-6)
                pre = jnp.arctanh(a_c)
                base = (-0.5 * jnp.square((pre - mean) / jnp.exp(log_std))
                        - log_std - 0.5 * jnp.log(2 * jnp.pi))
                logp = jnp.sum(base - jnp.log1p(-jnp.square(a_c) + 1e-6), -1)
                ent = jnp.sum(log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e), -1)
            return logp, ent

        def update(w, opt_state, ret_scale, batch, key):
            import optax
            k_wm, k_img = jax.random.split(key)
            (wl, (hs, zs, wm_metrics)), gw = jax.value_and_grad(
                wm_loss, has_aux=True)(w["wm"], batch, k_wm)
            uw, opt_wm = self.wm_opt.update(gw, opt_state["wm"], w["wm"])
            wm_p = optax.apply_updates(w["wm"], uw)

            # ---- imagination from every posterior state
            start_h = jax.lax.stop_gradient(hs.reshape(B * T, -1))
            start_z = jax.lax.stop_gradient(zs.reshape(B * T, -1))

            def img(carry, k):
                h, z = carry
                k1, k2 = jax.random.split(k)
                feat = jnp.concatenate([h, z], -1)
                a, logp, ent = actor_dist(w["actor"], feat, k1)
                h2, z2 = wm.apply(wm_p, h, z, a, k2,
                                  method=_WorldModel.img_step)
                return (h2, z2), (feat, a, logp, ent, h2, z2)

            keys = jax.random.split(k_img, H)
            _, (feats, acts, _logps, ents, hs_i, zs_i) = jax.lax.scan(
                img, (start_h, start_z), keys)
            # feats[t] is the state the action at t was taken FROM
            last_feat = jnp.concatenate([hs_i[-1], zs_i[-1]], -1)
            all_feats = jnp.concatenate([feats, last_feat[None]], 0)  # [H+1,N,F]
            rewards = wm.apply(wm_p, all_feats[1:],
                               method=_WorldModel.reward)        # r after act
            conts = wm.apply(wm_p, all_feats[1:],
                             method=_WorldModel.cont)
            v_logits = critic.apply(w["critic"], all_feats)
            values = _critic_value(v_logits, bins)                # [H+1,N]
            disc = gamma * conts

            def lam_ret(carry, xs):
                r, d, v_next = xs
                ret = r + d * ((1 - lam) * v_next + lam * carry)
                return ret, ret

            _, rets = jax.lax.scan(
                lam_ret, values[-1],
                (rewards[::-1], disc[::-1], values[1:][::-1]))
            rets = rets[::-1]                                     # [H,N]

            # ---- actor (REINFORCE on normalized advantage)
            flat_rets = rets.reshape(-1)
            p95 = jnp.percentile(flat_rets, 95)
            p5 = jnp.percentile(flat_rets, 5)
            new_scale = (cfg.return_norm_decay * ret_scale +
                         (1 - cfg.return_norm_decay) * (p95 - p5))
            denom = jnp.maximum(1.0, new_scale)
            # weight imagined steps by survival probability
            live = jnp.concatenate(
                [jnp.ones_like(conts[:1]),
                 jnp.cumprod(conts[:-1], 0)], 0)
            adv = jax.lax.stop_gradient((rets - values[:-1]) / denom)

            def actor_loss(ap):
                logp, ent = actor_logp_ent(
                    ap, jax.lax.stop_gradient(feats),
                    jax.lax.stop_gradient(acts))
                return -jnp.mean(live * (logp * adv +
                                         cfg.entropy_scale * ent))

            la, ga = jax.value_and_grad(actor_loss)(w["actor"])
            ua, opt_a = self.ac_opt.update(ga, opt_state["actor"],
                                           w["actor"])
            actor_p = optax.apply_updates(w["actor"], ua)

            # ---- critic (twohot CE to lambda returns + EMA regularizer)
            tgt = jax.lax.stop_gradient(twohot(symlog(rets), bins))
            feats_sg = jax.lax.stop_gradient(feats)
            ema_logits = critic.apply(w["critic_ema"], feats_sg)
            ema_tgt = jax.lax.stop_gradient(jax.nn.softmax(ema_logits, -1))

            def critic_loss(cp):
                lg = critic.apply(cp, feats_sg)
                logp = jax.nn.log_softmax(lg, -1)
                ce = -jnp.sum(tgt * logp, -1)
                reg = -jnp.sum(ema_tgt * logp, -1)
                return jnp.mean(live * (ce + cfg.critic_ema_scale * reg))

            lc, gc = jax.value_and_grad(critic_loss)(w["critic"])
            uc, opt_c = self.ac_opt.update(gc, opt_state["critic"],
                                           w["critic"])
            critic_p = optax.apply_updates(w["critic"], uc)
            ema_p = jax.tree_util.tree_map(
                lambda e, c: cfg.critic_ema_decay * e +
                (1 - cfg.critic_ema_decay) * c,
                w["critic_ema"], critic_p)

            new_w = {"wm": wm_p, "actor": actor_p, "critic": critic_p,
                     "critic_ema": ema_p}
            new_opt = {"wm": opt_wm, "actor": opt_a, "critic": opt_c}
            metrics = dict(wm_metrics)
            metrics.update({"wm_loss": wl, "actor_loss": la,
                            "critic_loss": lc,
                            "imagined_return": jnp.mean(rets),
                            "return_scale": new_scale,
                            "actor_entropy": jnp.mean(ents)})
            return new_w, new_opt, new_scale, metrics

        self._update = jax.jit(update, donate_argnums=(0, 1))

    # ------------------------------------------------------------ collection
    def _collect(self, n_steps):
        """Arrival convention (matches the reference's episode replay): each
        row is an OBSERVATION with the reward received on arriving at it, the
        action chosen FROM it, and whether it is terminal. Terminal arrival
        observations get their own row (zero action) — that is the only way
        the continue head ever sees a terminal example."""
        rows = {"obs": [], "action": [], "reward": [], "is_first": [],
                "is_terminated": []}

        def emit(obs, action, reward, is_first, is_terminal):
            rows["obs"].append(obs)
            rows["action"].append(action.astype(np.float32))
            rows["reward"].append(np.float32(reward))
            rows["is_first"].append(np.float32(is_first))
            rows["is_terminated"].append(np.float32(is_terminal))

        for _ in range(n_steps):
            self._act_key, k = jax.random.split(self._act_key)
            obs = np.asarray(self._obs, np.float32).reshape(-1)
            h, z, a = self._act(self.weights, self._h, self._z,
                                self._a_prev, obs,
                                float(self._is_first), k)
            self._h, self._z = np.asarray(h), np.asarray(z)
            a = np.asarray(a)
            if self._discrete:
                env_a = int(np.argmax(a))
            else:
                # tanh output in [-1,1] → env bounds
                env_a = (self._act_low + (a + 1) / 2 *
                         (self._act_high - self._act_low))
            nxt, r, term, trunc, _ = self._env.step(env_a)
            emit(obs, a, self._r_arrival, self._is_first, False)
            self._r_arrival = float(r)
            self._ep_ret += float(r)
            self._ep_len += 1
            self._a_prev = a.astype(np.float32)
            self._is_first = False
            self._obs = nxt
            if term or trunc:
                # final arrival row: reward of the last action, terminal flag
                # only for true termination (truncation may bootstrap)
                emit(np.asarray(nxt, np.float32).reshape(-1),
                     np.zeros_like(self._a_prev), r, False, term)
                self._ep_returns.append(self._ep_ret)
                self._ep_lens.append(self._ep_len)
                self._ep_ret, self._ep_len = 0.0, 0
                self._obs, _ = self._env.reset()
                self._is_first = True
                self._r_arrival = 0.0
                # fresh buffers: np.asarray over a jax array is read-only
                self._h = np.zeros_like(self._h)
                self._z = np.zeros_like(self._z)
                self._a_prev = np.zeros_like(self._a_prev)
        self.env_steps += n_steps
        self._env_steps_iter += n_steps   # base-class lifetime accounting
        return {k: np.stack(v) for k, v in rows.items()}

    # -------------------------------------------------------------- training
    def training_step(self) -> Dict:
        cfg = self.config
        self.replay.add(self._collect(cfg.rollout_fragment_length))
        metrics = {"num_env_steps_sampled_this_iter":
                   cfg.rollout_fragment_length,
                   "num_env_steps_sampled": self.env_steps}
        if self._ep_returns:
            metrics["episode_return_mean"] = float(
                np.mean(list(self._ep_returns)[-20:]))
            metrics["episode_len_mean"] = float(
                np.mean(list(self._ep_lens)[-20:]))
        if (self.env_steps < cfg.num_steps_sampled_before_learning_starts or
                len(self.replay) < cfg.batch_length_T + 1):
            return metrics
        last = {}
        for _ in range(cfg.train_intensity):
            batch = self.replay.sample(cfg.batch_size_B, cfg.batch_length_T)
            batch = {k: jnp.asarray(v) for k, v in batch.items()}
            key = jax.random.PRNGKey(self.config.seed * 7919 + self._updates)
            self.weights, self.opt_state, self.ret_scale, last = \
                self._update(self.weights, self.opt_state, self.ret_scale,
                             batch, key)
            self._updates += 1
        metrics["learner"] = {k: float(v) for k, v in
                              jax.device_get(last).items()}
        return metrics

    def evaluate(self) -> Dict:
        # the training env loop IS the policy rollout; report recent returns
        if not self._ep_returns:
            return {}
        recent = list(self._ep_returns)[-self.config.evaluation_duration:]
        return {"episodes_this_iter": len(recent),
                "episode_return_mean": float(np.mean(recent))}

    def stop(self):
        # this algorithm owns its env directly (no EnvRunner fleet closes it)
        try:
            self._env.close()
        except Exception:
            pass
        super().stop()

    def get_weights(self):
        return jax.device_get(self.weights)

    def set_weights(self, weights):
        self.weights = weights
