"""BC / MARWIL (reference: rllib/algorithms/bc, rllib/algorithms/marwil).

Offline: learns from a recorded SampleBatch / ray_tpu.data Dataset of
(obs, actions[, rewards...]) — no env interaction. beta=0 is pure behavior
cloning; beta>0 weights log-likelihood by exponentiated advantages (MARWIL).
"""

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import sample_batch as SB
from ..algorithm import Algorithm, AlgorithmConfig
from ..learner import JaxLearner, _host_metrics, make_learner_group
from ..rl_module import ModuleSpec, RLModule
from ..sample_batch import SampleBatch


class BCConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = BC
        self.lr = 1e-3
        self.beta = 0.0                  # 0 → BC; >0 → MARWIL
        self.offline_data = None         # SampleBatch | dict | data.Dataset
        self.train_batch_size = 256
        self.moving_average_sqd_adv_norm = 100.0

    def offline_data_source(self, data):
        self.offline_data = data
        return self


class BCLearner(JaxLearner):
    def compute_loss(self, params, batch):
        cfg = self.config
        dist_in, values = self.module.forward(params, batch[SB.OBS])
        dist = self.module.dist(dist_in)
        logp = dist.log_prob(batch[SB.ACTIONS])
        if cfg.beta > 0 and SB.ADVANTAGES in batch:
            adv = batch[SB.ADVANTAGES]
            norm = jnp.sqrt(cfg.moving_average_sqd_adv_norm)
            weights = jnp.exp(cfg.beta * adv / jnp.maximum(norm, 1e-8))
            loss = -jnp.mean(weights * logp)
            vf_loss = 0.5 * jnp.mean(jnp.square(
                values - batch.get(SB.VALUE_TARGETS, adv)))
            loss = loss + 0.5 * vf_loss
        else:
            loss = -jnp.mean(logp)
        acc = None
        if dist_in.ndim >= 1 and self.module.spec.action_kind == "discrete":
            acc = jnp.mean((dist_in.argmax(-1) ==
                            batch[SB.ACTIONS]).astype(jnp.float32))
        out = {"bc_logp": jnp.mean(logp)}
        if acc is not None:
            out["action_accuracy"] = acc
        return loss, out


class BC(Algorithm):
    def setup(self, config: BCConfig):
        data = config.offline_data
        if data is None:
            raise ValueError("BC needs config.offline_data")
        self._data = self._to_arrays(data)
        n = len(self._data[SB.OBS])
        obs_shape = self._data[SB.OBS].shape[1:]
        acts = self._data[SB.ACTIONS]
        if np.issubdtype(np.asarray(acts).dtype, np.integer):
            spec = ModuleSpec(obs_shape, "discrete", int(acts.max()) + 1,
                              tuple(config.model.get("hiddens", (256, 256))))
        else:
            spec = ModuleSpec(obs_shape, "continuous",
                              int(np.prod(np.asarray(acts).shape[1:])),
                              tuple(config.model.get("hiddens", (256, 256))))
        self.learner_group = make_learner_group(BCLearner, RLModule(spec),
                                                config, seed=config.seed)
        self.learner = self.learner_group.learner
        self._rng = np.random.default_rng(config.seed)
        self._n = n

    @staticmethod
    def _to_arrays(data) -> Dict[str, np.ndarray]:
        if isinstance(data, dict):
            return {k: np.asarray(v) for k, v in data.items()}
        if hasattr(data, "take_batch"):  # ray_tpu.data Dataset
            return data.take_batch(data.count(), batch_format="numpy")
        raise TypeError(f"unsupported offline data {type(data)}")

    def training_step(self) -> Dict:
        cfg = self.config
        idx = self._rng.integers(0, self._n, size=cfg.train_batch_size)
        minibatch = {k: v[idx] for k, v in self._data.items()}
        learn = _host_metrics([self.learner.update_once(minibatch)])
        return {"learner": learn,
                "num_env_steps_sampled_this_iter": 0}

    def evaluate(self):
        if self.config.env is None:
            return {}
        return super().evaluate()

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, weights):
        self.learner.set_weights(weights)


class MARWILConfig(BCConfig):
    def __init__(self):
        super().__init__()
        self.beta = 1.0


MARWIL = BC
