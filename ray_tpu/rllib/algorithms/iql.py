"""IQL — Implicit Q-Learning (reference: rllib/algorithms/iql/iql.py,
iql_learner.py; paper arXiv:2110.06169).

Offline RL that never queries Q on out-of-distribution actions:
- a value net V(s) is fit to the twin-target-Q by EXPECTILE regression
  (asymmetric L2, expectile tau > 0.5 biases V toward the upper envelope
  of behavior-supported Q values),
- the critics regress the one-step Bellman target r + gamma*(1-d)*V(s')
  (no action sampling at s' at all),
- the actor is advantage-weighted regression: maximize
  exp(beta * (Q_target(s,a) - V(s))) * log pi(a|s) with clipped weights.

tpu-first: all three fits live in ONE jitted update (value, critics, actor,
polyak) so XLA fuses the shared forward passes; data stays device-resident
between the train_intensity SGD steps.

Contrast with the reference: rllib's IQLLearner subclasses the MARWIL torch
learner and splits per-net optimizers across `actor_lr/critic_lr/value_lr`;
here one optax optimizer per net inside a single jit, same hyperparameters
(expectile, beta, twin_q, tau — iql.py:60-82).
"""

from typing import Dict

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models.torsos import MLPTorso
from .. import sample_batch as SB
from ..algorithm import Algorithm
from ..distributions import SquashedGaussian
from ..rl_module import ModuleSpec
from .offline_utils import (evaluate_continuous, load_continuous_dataset,
                            make_offline_optimizer, offline_training_step)
from .sac import SACConfig, SACModule


class _ValueNet(nn.Module):
    spec: ModuleSpec

    @nn.compact
    def __call__(self, obs):
        z = MLPTorso(self.spec.hiddens)(obs.reshape(obs.shape[0], -1))
        return nn.Dense(1, name="v")(z)[:, 0]


class IQLConfig(SACConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = IQL
        self.offline_data = None
        self.expectile = 0.8        # ref iql.py:69 (tau in the paper)
        self.beta = 0.1             # AWR temperature, ref iql.py:66
        self.awr_weight_cap = 100.0  # exp-advantage clip (paper appendix)
        self.train_intensity = 8
        self.action_low = None
        self.action_high = None

    def offline_data_source(self, data):
        self.offline_data = data
        return self


class IQL(Algorithm):
    _supports_eval_actors = False

    def setup(self, config: IQLConfig):
        if config.offline_data is None:
            raise ValueError("IQL needs config.offline_data")
        self._data, self._n, spec, low, high = \
            load_continuous_dataset(config)
        self.module = SACModule(spec, low, high)
        self.value = _ValueNet(spec)
        key = jax.random.PRNGKey(config.seed)
        self.weights = self.module.init(key)
        obs0 = jnp.zeros((1,) + spec.obs_shape, jnp.float32)
        self.weights["value"] = self.value.init(
            jax.random.fold_in(key, 7), obs0)
        self.opt, self._lr_schedule, self.opt_state = make_offline_optimizer(
            config, self.weights, ("actor", "q1", "q2", "value"))
        self._rng = np.random.default_rng(config.seed)
        self._updates = 0
        self._build_update()

    def _build_update(self):
        cfg = self.config
        mod = self.module
        val = self.value
        gamma, tau = cfg.gamma, cfg.tau
        expectile = cfg.expectile
        beta = cfg.beta
        w_cap = cfg.awr_weight_cap
        low, high = mod.low, mod.high

        def update(w, opt_state, batch):
            import optax
            obs, act = batch[SB.OBS], batch[SB.ACTIONS]
            nxt, rew = batch[SB.NEXT_OBS], batch[SB.REWARDS]
            done = batch[SB.TERMINATEDS]

            # -- value net: expectile regression toward min target-Q(s, a_data)
            q1_t = mod.critic.apply(w["q1_target"], obs, act)
            q2_t = mod.critic.apply(w["q2_target"], obs, act)
            q_t = jax.lax.stop_gradient(jnp.minimum(q1_t, q2_t))

            def v_loss(vp):
                v = val.apply(vp, obs)
                diff = q_t - v
                # L2^tau: weight tau where Q>V, (1-tau) where Q<V
                wgt = jnp.where(diff > 0, expectile, 1 - expectile)
                return jnp.mean(wgt * jnp.square(diff)), v

            (lv, v), gv = jax.value_and_grad(v_loss, has_aux=True)(w["value"])
            uv, opt_v = self.opt.update(gv, opt_state["value"], w["value"])
            value_p = optax.apply_updates(w["value"], uv)

            # -- critics: Bellman toward V(s') — no next-action sampling
            v_next = jax.lax.stop_gradient(val.apply(value_p, nxt))
            target = rew + gamma * (1 - done) * v_next

            def q_loss(qp):
                q = mod.critic.apply(qp, obs, act)
                return jnp.mean(jnp.square(q - target))

            l1, g1 = jax.value_and_grad(q_loss)(w["q1"])
            l2, g2 = jax.value_and_grad(q_loss)(w["q2"])
            u1, opt_q1 = self.opt.update(g1, opt_state["q1"], w["q1"])
            u2, opt_q2 = self.opt.update(g2, opt_state["q2"], w["q2"])
            q1p = optax.apply_updates(w["q1"], u1)
            q2p = optax.apply_updates(w["q2"], u2)

            # -- actor: AWR with exp-advantage weights (advantage from the
            # TARGET critics and the fresh V, both stop-gradiented)
            adv = q_t - jax.lax.stop_gradient(v)
            awr_w = jnp.minimum(jnp.exp(beta * adv), w_cap)

            def pi_loss(ap):
                mean, log_std = mod.actor.apply(ap, obs)
                dist = SquashedGaussian(mean, log_std, low, high)
                logp = dist.log_prob(act)
                return -jnp.mean(awr_w * logp), logp

            (la, logp), ga = jax.value_and_grad(
                pi_loss, has_aux=True)(w["actor"])
            ua, opt_a = self.opt.update(ga, opt_state["actor"], w["actor"])
            actor_p = optax.apply_updates(w["actor"], ua)

            polyak = lambda t, s: jax.tree_util.tree_map(
                lambda a_, b_: (1 - tau) * a_ + tau * b_, t, s)
            new_w = {"actor": actor_p, "q1": q1p, "q2": q2p,
                     "q1_target": polyak(w["q1_target"], q1p),
                     "q2_target": polyak(w["q2_target"], q2p),
                     "value": value_p,
                     "log_alpha": w["log_alpha"]}  # unused; kept for module
            new_opt = {"actor": opt_a, "q1": opt_q1, "q2": opt_q2,
                       "value": opt_v}
            metrics = {"value_loss": lv, "critic_loss": 0.5 * (l1 + l2),
                       "actor_loss": la, "adv_mean": jnp.mean(adv),
                       "awr_weight_mean": jnp.mean(awr_w),
                       "behavior_logp": jnp.mean(logp)}
            return new_w, new_opt, metrics

        self._update = jax.jit(update, donate_argnums=(0, 1))

    def training_step(self) -> Dict:
        return offline_training_step(
            self, lambda mb, i: self._update(self.weights, self.opt_state, mb))

    def evaluate(self) -> Dict:
        return evaluate_continuous(self)

    def get_weights(self):
        return jax.device_get(self.weights)

    def set_weights(self, weights):
        self.weights = weights
