"""TQC — Truncated Quantile Critics (reference: rllib/algorithms/tqc/tqc.py,
tqc_learner.py; paper arXiv:2005.04269).

SAC with distributional critics: an ensemble of `n_critics` nets each emits
`n_quantiles` atoms of the return distribution Z(s,a). The Bellman target
pools ALL target-net atoms at (s', a'~pi), sorts them, and DROPS the top
`top_quantiles_to_drop_per_net * n_critics` — truncating the right tail is
what controls overestimation (the ensemble-min trick of SAC, made granular).
Critics fit the kept atoms by quantile Huber regression; the actor maximizes
the mean over all atoms minus the entropy bonus; temperature auto-tunes as
in SAC.

tpu-first: the critic ensemble is a stacked-parameter vmap (one XLA program
evaluates all n_critics nets as a single batched matmul stack feeding the
MXU), and actor+critics+alpha+polyak live in ONE jitted update. Contrast:
the reference's torch learner (tqc_learner.py) loops the ensemble in python.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp

from ray_tpu.models.torsos import MLPTorso
from .. import sample_batch as SB
from ..distributions import SquashedGaussian
from ..rl_module import ModuleSpec
from .sac import SAC, SACConfig, SACModule


class _QuantileCritic(nn.Module):
    spec: ModuleSpec
    n_quantiles: int

    @nn.compact
    def __call__(self, obs, action):
        x = jnp.concatenate([obs.reshape(obs.shape[0], -1), action], -1)
        z = MLPTorso(self.spec.hiddens)(x)
        return nn.Dense(self.n_quantiles, name="z")(z)   # [B, M]


class TQCModule(SACModule):
    """SAC acting surface + stacked quantile-critic ensemble."""

    def __init__(self, spec: ModuleSpec, low: float, high: float,
                 n_quantiles: int = 25, n_critics: int = 2):
        super().__init__(spec, low, high)
        self.n_quantiles = n_quantiles
        self.n_critics = n_critics
        self.qcritic = _QuantileCritic(spec, n_quantiles)

    def init(self, key):
        k_actor, k_crit = jax.random.split(key)
        obs = jnp.zeros((1,) + self.spec.obs_shape, jnp.float32)
        act = jnp.zeros((1, self.spec.action_dim), jnp.float32)
        actor = self.actor.init(k_actor, obs)
        # stacked ensemble params: leaf shape [n_critics, ...] so one vmapped
        # apply evaluates every net in a single program
        crit_keys = jax.random.split(k_crit, self.n_critics)
        stack = jax.vmap(lambda k: self.qcritic.init(k, obs, act))(crit_keys)
        copy = lambda t: jax.tree_util.tree_map(jnp.copy, t)
        return {"actor": actor, "critics": stack,
                "critics_target": copy(stack),
                "log_alpha": jnp.asarray(0.0)}

    def z_all(self, critics_params, obs, action):
        """All atoms from all critics: [B, n_critics, n_quantiles]."""
        z = jax.vmap(lambda p: self.qcritic.apply(p, obs, action))(
            critics_params)                      # [n_critics, B, M]
        return jnp.transpose(z, (1, 0, 2))


class TQCConfig(SACConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = TQC
        self.n_quantiles = 25                    # ref tqc.py:52
        self.n_critics = 2
        self.top_quantiles_to_drop_per_net = 2


class TQC(SAC):
    """Subclasses SAC: setup, the replay/rollout loop (training_step) and the
    weight surface are inherited; only module construction, the opt_state
    layout, and the jitted update differ."""

    def _make_module(self, spec, low, high):
        cfg = self.config
        return TQCModule(spec, low, high, cfg.n_quantiles, cfg.n_critics)

    def _init_opt_state(self):
        return {
            "actor": self.opt.init(self.weights["actor"]),
            "critics": self.opt.init(self.weights["critics"]),
            "alpha": self.opt.init(self.weights["log_alpha"])}

    def _make_runner_kwargs(self):
        kw = super()._make_runner_kwargs()
        kw["module"] = TQCModule(self.module.spec, self.module.low,
                                 self.module.high, self.module.n_quantiles,
                                 self.module.n_critics)
        kw["record_next_obs"] = True
        return kw

    def _build_update(self):
        cfg = self.config
        mod = self.module
        gamma, tau = cfg.gamma, cfg.tau
        target_entropy = self.target_entropy
        m = cfg.n_quantiles
        n_crit = cfg.n_critics
        n_keep = n_crit * m - n_crit * cfg.top_quantiles_to_drop_per_net
        if n_keep <= 0:
            raise ValueError("top_quantiles_to_drop_per_net drops every atom")
        # quantile midpoints tau_i = (2i+1)/2M — one per predicted atom
        taus = (jnp.arange(m, dtype=jnp.float32) + 0.5) / m

        def quantile_huber(pred, target):
            """pred [B, M] vs target [B, K] → scalar (kappa=1 Huber)."""
            delta = target[:, None, :] - pred[:, :, None]     # [B, M, K]
            a = jnp.abs(delta)
            huber = jnp.where(a <= 1.0, 0.5 * delta * delta, a - 0.5)
            wgt = jnp.abs(taus[None, :, None] -
                          (delta < 0).astype(jnp.float32))
            return jnp.mean(jnp.sum(jnp.mean(wgt * huber, axis=2), axis=1))

        def update(w, opt_state, batch, key):
            import optax
            obs, act = batch[SB.OBS], batch[SB.ACTIONS]
            nxt, rew = batch[SB.NEXT_OBS], batch[SB.REWARDS]
            done = batch[SB.TERMINATEDS]
            alpha = jnp.exp(w["log_alpha"])
            k1, k2 = jax.random.split(key)

            # -- truncated distributional target
            dist_n, _ = mod._dist(w, nxt)
            a_n, logp_n = dist_n.sample_and_log_prob(k1)
            z_n = mod.z_all(w["critics_target"], nxt, a_n)   # [B, C, M]
            z_pool = jnp.sort(z_n.reshape(z_n.shape[0], -1), axis=-1)
            z_keep = z_pool[:, :n_keep]                      # drop top tail
            target = rew[:, None] + gamma * (1 - done)[:, None] * (
                z_keep - alpha * logp_n[:, None])
            target = jax.lax.stop_gradient(target)           # [B, K]

            def z_loss(cp):
                z = mod.z_all(cp, obs, act)                  # [B, C, M]
                per = jax.vmap(quantile_huber, in_axes=(1, None))(z, target)
                return jnp.sum(per)

            lz, gz = jax.value_and_grad(z_loss)(w["critics"])
            uz, opt_c = self.opt.update(gz, opt_state["critics"],
                                        w["critics"])
            critics_p = optax.apply_updates(w["critics"], uz)

            # -- actor: mean of ALL atoms (no truncation on the policy side)
            def pi_loss(ap):
                mean, log_std = mod.actor.apply(ap, obs)
                dist = SquashedGaussian(mean, log_std, mod.low, mod.high)
                a, logp = dist.sample_and_log_prob(k2)
                q = jnp.mean(mod.z_all(critics_p, obs, a), axis=(1, 2))
                return jnp.mean(alpha * logp - q), logp

            (la, logp), ga = jax.value_and_grad(
                pi_loss, has_aux=True)(w["actor"])
            ua, opt_a = self.opt.update(ga, opt_state["actor"], w["actor"])
            actor_p = optax.apply_updates(w["actor"], ua)

            def alpha_loss(log_alpha):
                return -jnp.mean(jnp.exp(log_alpha) *
                                 jax.lax.stop_gradient(logp + target_entropy))

            lt, gt = jax.value_and_grad(alpha_loss)(w["log_alpha"])
            ut, opt_t = self.opt.update(gt, opt_state["alpha"], w["log_alpha"])
            log_alpha = optax.apply_updates(w["log_alpha"], ut)

            polyak = lambda t, s: jax.tree_util.tree_map(
                lambda a_, b_: (1 - tau) * a_ + tau * b_, t, s)
            new_w = {"actor": actor_p, "critics": critics_p,
                     "critics_target": polyak(w["critics_target"], critics_p),
                     "log_alpha": log_alpha}
            new_opt = {"actor": opt_a, "critics": opt_c, "alpha": opt_t}
            metrics = {"critic_loss": lz / n_crit, "actor_loss": la,
                       "alpha": jnp.exp(log_alpha),
                       "entropy": -jnp.mean(logp),
                       "z_target_mean": jnp.mean(z_keep)}
            return new_w, new_opt, metrics

        self._update = jax.jit(update, donate_argnums=(0, 1))

    # training_step / get_weights / set_weights inherited from SAC
