"""PPO (reference: rllib/algorithms/ppo/ppo.py + torch policy losses).

Loss math matches the reference (clipped surrogate, clipped value loss,
entropy bonus); the mechanics are TPU-native — minibatch SGD steps are one
jitted fwd+bwd+adam program with donated params, epochs/minibatching are a
host loop over static shapes so nothing recompiles.
"""

from typing import Dict

import jax.numpy as jnp
import numpy as np

from ray_tpu.ops.losses import clipped_value_loss, ppo_surrogate
from .. import sample_batch as SB
from ..algorithm import Algorithm, AlgorithmConfig
from ..connectors import compute_gae, standardize_advantages
from ..learner import JaxLearner, LearnerGroup, _host_metrics
from ..rl_module import RLModule
from ..sample_batch import SampleBatch


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = PPO
        self.lr = 3e-4
        self.gamma = 0.99
        self.lambda_ = 0.95
        self.clip_param = 0.2
        self.vf_clip_param = 10.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0
        self.kl_coeff = 0.0          # 0 → pure clipping (reference default path)
        self.kl_target = 0.01
        self.num_epochs = 10
        self.minibatch_size = 128
        self.train_batch_size = 4000
        self.grad_clip = 0.5
        self.standardize_advantages = True


class PPOLearner(JaxLearner):
    def compute_loss(self, params, batch):
        cfg = self.config
        dist_in, values = self.module.forward(params, batch[SB.OBS])
        dist = self.module.dist(dist_in)
        logp = dist.log_prob(batch[SB.ACTIONS])
        pi_loss, clip_frac = ppo_surrogate(
            logp, batch[SB.LOGP], batch[SB.ADVANTAGES], cfg.clip_param)
        vf_loss = clipped_value_loss(
            values, batch[SB.VF_PREDS], batch[SB.VALUE_TARGETS],
            cfg.vf_clip_param)
        entropy = jnp.mean(dist.entropy())
        loss = (pi_loss + cfg.vf_loss_coeff * vf_loss
                - cfg.entropy_coeff * entropy)
        approx_kl = jnp.mean(batch[SB.LOGP] - logp)
        if cfg.kl_coeff:
            loss = loss + cfg.kl_coeff * approx_kl
        return loss, {
            "policy_loss": pi_loss, "vf_loss": vf_loss, "entropy": entropy,
            "clip_frac": clip_frac, "approx_kl": approx_kl,
        }

    _TRAIN_KEYS = (SB.OBS, SB.ACTIONS, SB.LOGP, SB.ADVANTAGES, SB.VF_PREDS,
                   SB.VALUE_TARGETS)

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        cfg = self.config
        rng = np.random.default_rng(cfg.seed + 1)
        # subset before flatten: per-env columns like BOOTSTRAP_VALUE have a
        # different length and must not ride into shuffle/minibatching
        flat = SampleBatch({k: batch[k] for k in self._TRAIN_KEYS}).flatten()
        steps = []
        for _ in range(cfg.num_epochs):
            shuffled = flat.shuffle(rng)
            for mb in shuffled.minibatches(cfg.minibatch_size):
                steps.append(self.update_once(dict(mb)))
        return _host_metrics(steps)


class PPO(Algorithm):
    def setup(self, config: PPOConfig):
        self._setup_runners()
        spec = self._local_runner.get_spec()
        self.learner = PPOLearner(RLModule(spec), config, seed=config.seed)
        self.learner_group = LearnerGroup(self.learner)

    def training_step(self) -> Dict:
        cfg = self.config
        weights = self.learner.get_weights()
        collected = []
        timesteps = 0
        runner_metrics = []
        while timesteps < cfg.train_batch_size:
            batch, rm = self._sample_all(weights)
            collected.append(batch)
            runner_metrics.append(rm)
            timesteps += batch[SB.REWARDS].size
        batch = (collected[0] if len(collected) == 1 else
                 SampleBatch.concat(collected, axis=1))
        batch = compute_gae(batch, cfg.gamma, cfg.lambda_)
        if cfg.standardize_advantages:
            batch = standardize_advantages(batch)
        learn = self.learner_group.update(batch)
        from ..algorithm import _merge_runner_metrics
        result = _merge_runner_metrics(runner_metrics)
        result["num_env_steps_sampled_this_iter"] = timesteps
        result["learner"] = learn
        return result

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, weights):
        self.learner.set_weights(weights)
