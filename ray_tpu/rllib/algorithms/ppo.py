"""PPO (reference: rllib/algorithms/ppo/ppo.py + torch policy losses).

Loss math matches the reference (clipped surrogate, clipped value loss,
entropy bonus); the mechanics are TPU-native — minibatch SGD steps are one
jitted fwd+bwd+adam program with donated params, epochs/minibatching are a
host loop over static shapes so nothing recompiles.
"""

from typing import Dict

import jax.numpy as jnp
import numpy as np

from ray_tpu.ops.losses import clipped_value_loss, ppo_surrogate
from .. import sample_batch as SB
from ..algorithm import Algorithm, AlgorithmConfig
from ..connectors import compute_gae, standardize_advantages
from ..learner import JaxLearner, LearnerGroup, _host_metrics, make_learner_group
from ..rl_module import RLModule
from ..sample_batch import SampleBatch


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = PPO
        self.lr = 3e-4
        self.gamma = 0.99
        self.lambda_ = 0.95
        self.clip_param = 0.2
        self.vf_clip_param = 10.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0
        self.kl_coeff = 0.0          # 0 → pure clipping (reference default path)
        self.kl_target = 0.01
        self.num_epochs = 10
        self.minibatch_size = 128
        self.train_batch_size = 4000
        self.grad_clip = 0.5
        self.standardize_advantages = True


class PPOLearner(JaxLearner):
    def compute_loss(self, params, batch):
        cfg = self.config
        dist_in, values = self.module.forward(params, batch[SB.OBS])
        dist = self.module.dist(dist_in)
        logp = dist.log_prob(batch[SB.ACTIONS])
        pi_loss, clip_frac = ppo_surrogate(
            logp, batch[SB.LOGP], batch[SB.ADVANTAGES], cfg.clip_param)
        vf_loss = clipped_value_loss(
            values, batch[SB.VF_PREDS], batch[SB.VALUE_TARGETS],
            cfg.vf_clip_param)
        entropy = jnp.mean(dist.entropy())
        loss = (pi_loss + cfg.vf_loss_coeff * vf_loss
                - cfg.entropy_coeff * entropy)
        approx_kl = jnp.mean(batch[SB.LOGP] - logp)
        if cfg.kl_coeff:
            loss = loss + cfg.kl_coeff * approx_kl
        return loss, {
            "policy_loss": pi_loss, "vf_loss": vf_loss, "entropy": entropy,
            "clip_frac": clip_frac, "approx_kl": approx_kl,
        }

    _TRAIN_KEYS = (SB.OBS, SB.ACTIONS, SB.LOGP, SB.ADVANTAGES, SB.VF_PREDS,
                   SB.VALUE_TARGETS)

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        cfg = self.config
        rng = np.random.default_rng(cfg.seed + 1)
        # subset before flatten: per-env columns like BOOTSTRAP_VALUE have a
        # different length and must not ride into shuffle/minibatching
        flat = SampleBatch({k: batch[k] for k in self._TRAIN_KEYS}).flatten()
        steps = []
        for _ in range(cfg.num_epochs):
            shuffled = flat.shuffle(rng)
            for mb in shuffled.minibatches(cfg.minibatch_size):
                steps.append(self.update_once(dict(mb)))
        return _host_metrics(steps)


class PPO(Algorithm):
    def setup(self, config: PPOConfig):
        if config.policies:
            return self._setup_multi_agent(config)
        self._setup_runners()
        spec = self._local_runner.get_spec()
        self.learner_group = make_learner_group(PPOLearner, RLModule(spec),
                                                config, seed=config.seed)
        self.learner = self.learner_group.learner

    # -- multi-agent mode (reference: rllib/env/multi_agent_env.py + policy
    # map; one PPOLearner per policy, agents batched per policy) -----------
    def _setup_multi_agent(self, config):
        from ..multi_agent import MultiAgentEnvRunner, module_specs_for
        mapping = config.policy_mapping_fn or (lambda aid: config.policies[0])
        probe_env = config.env() if callable(config.env) else config.env
        specs = module_specs_for(
            probe_env, mapping,
            hiddens=tuple(config.model.get("hiddens", (256, 256))))
        missing = set(specs) - set(config.policies)
        if missing:
            raise ValueError(f"policy_mapping_fn produced unknown policies "
                             f"{sorted(missing)}; declared {config.policies}")
        self.ma_learner_groups = {
            pid: make_learner_group(PPOLearner, RLModule(specs[pid]), config,
                                    seed=config.seed + i)
            for i, pid in enumerate(sorted(specs))}
        self._ma_runner = MultiAgentEnvRunner(
            (config.env if callable(config.env)
             else (lambda: probe_env)),
            policy_mapping_fn=mapping,
            modules={pid: g.learner.module
                     for pid, g in self.ma_learner_groups.items()},
            rollout_len=config.rollout_fragment_length,
            explore=config.explore, seed=config.seed)
        self._iteration_ma = 0

    def _training_step_multi_agent(self) -> Dict:
        cfg = self.config
        weights = {pid: g.get_weights()
                   for pid, g in self.ma_learner_groups.items()}
        timesteps = 0
        runner_metrics = []
        learn: Dict[str, Dict] = {}
        per_policy: Dict[str, list] = {pid: [] for pid in self.ma_learner_groups}
        while timesteps < cfg.train_batch_size:
            ma_batch, rm = self._ma_runner.sample(weights)
            runner_metrics.append(rm)
            timesteps += ma_batch.env_steps()
            for pid, batch in ma_batch.policy_batches.items():
                per_policy[pid].append(batch)
        for pid, batches in per_policy.items():
            if not batches:
                continue
            batch = (batches[0] if len(batches) == 1 else
                     SampleBatch.concat(batches, axis=1))
            batch = compute_gae(batch, cfg.gamma, cfg.lambda_)
            if cfg.standardize_advantages:
                batch = standardize_advantages(batch)
            learn[pid] = self.ma_learner_groups[pid].update(batch)
        from ..algorithm import _merge_runner_metrics
        result = _merge_runner_metrics(runner_metrics)
        result["num_env_steps_sampled_this_iter"] = timesteps
        result["learner"] = learn  # keyed per policy (reference layout)
        return result

    def training_step(self) -> Dict:
        if self.config.policies:
            return self._training_step_multi_agent()
        cfg = self.config
        weights = self.learner.get_weights()
        collected = []
        timesteps = 0
        runner_metrics = []
        while timesteps < cfg.train_batch_size:
            batch, rm = self._sample_all(weights)
            collected.append(batch)
            runner_metrics.append(rm)
            timesteps += batch[SB.REWARDS].size
        batch = (collected[0] if len(collected) == 1 else
                 SampleBatch.concat(collected, axis=1))
        batch = compute_gae(batch, cfg.gamma, cfg.lambda_)
        if cfg.standardize_advantages:
            batch = standardize_advantages(batch)
        learn = self.learner_group.update(batch)
        from ..algorithm import _merge_runner_metrics
        result = _merge_runner_metrics(runner_metrics)
        result["num_env_steps_sampled_this_iter"] = timesteps
        result["learner"] = learn
        return result

    def evaluate(self) -> Dict:
        if not self.config.policies:
            return super().evaluate()
        from ..multi_agent import MultiAgentEnvRunner
        cfg = self.config
        runner = MultiAgentEnvRunner(
            cfg.env if callable(cfg.env) else (lambda: cfg.env),
            policy_mapping_fn=cfg.policy_mapping_fn,
            modules={pid: g.learner.module
                     for pid, g in self.ma_learner_groups.items()},
            rollout_len=cfg.rollout_fragment_length,
            explore=False, seed=cfg.seed + 10_000)
        weights = {pid: g.get_weights()
                   for pid, g in self.ma_learner_groups.items()}
        episodes = 0
        merged: Dict = {}
        while episodes < cfg.evaluation_duration:
            _b, m = runner.sample(weights)
            episodes += m.get("episodes_this_iter", 0)
            if "episode_return_mean" in m:
                merged = m
        return merged

    def get_weights(self):
        if self.config.policies:
            return {pid: g.get_weights()
                    for pid, g in self.ma_learner_groups.items()}
        return self.learner.get_weights()

    def set_weights(self, weights):
        if self.config.policies:
            for pid, w in weights.items():
                self.ma_learner_groups[pid].set_weights(w)
            return
        self.learner.set_weights(weights)
