"""SAC (reference: rllib/algorithms/sac/*) — squashed-gaussian actor, twin
critics, auto-tuned temperature. One jitted update covers actor+critic+alpha;
target critics polyak-update inside the same program.
"""

from typing import Dict

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models.torsos import MLPTorso
from .. import sample_batch as SB
from ..algorithm import Algorithm, AlgorithmConfig, _merge_runner_metrics
from ..buffers import ReplayBuffer
from ..distributions import SquashedGaussian
from ..rl_module import ModuleSpec


class _Actor(nn.Module):
    spec: ModuleSpec

    @nn.compact
    def __call__(self, obs):
        z = MLPTorso(self.spec.hiddens)(obs)
        mean = nn.Dense(self.spec.action_dim, name="mean")(z)
        log_std = nn.Dense(self.spec.action_dim, name="log_std")(z)
        return mean, log_std


class _Critic(nn.Module):
    spec: ModuleSpec

    @nn.compact
    def __call__(self, obs, action):
        x = jnp.concatenate([obs.reshape(obs.shape[0], -1), action], -1)
        z = MLPTorso(self.spec.hiddens)(x)
        return nn.Dense(1, name="q")(z)[:, 0]


class SACModule:
    """RLModule-compatible acting surface over the SAC actor."""

    def __init__(self, spec: ModuleSpec, low: float = -1.0, high: float = 1.0):
        if spec.action_kind != "continuous":
            raise ValueError("SAC needs a continuous (Box) action space")
        self.spec = spec
        self.low, self.high = low, high
        self.actor = _Actor(spec)
        self.critic = _Critic(spec)

    def init(self, key):
        k1, k2, k3 = jax.random.split(key, 3)
        obs = jnp.zeros((1,) + self.spec.obs_shape, jnp.float32)
        act = jnp.zeros((1, self.spec.action_dim), jnp.float32)
        actor = self.actor.init(k1, obs)
        q1 = self.critic.init(k2, obs, act)
        q2 = self.critic.init(k3, obs, act)
        copy = lambda t: jax.tree_util.tree_map(jnp.copy, t)
        # targets are COPIES: sharing buffers with the online nets would make
        # the jitted update donate the same buffer twice
        return {"actor": actor, "q1": q1, "q2": q2,
                "q1_target": copy(q1), "q2_target": copy(q2),
                "log_alpha": jnp.asarray(0.0)}

    def _dist(self, weights, obs):
        flat = obs.reshape((-1,) + self.spec.obs_shape)
        mean, log_std = self.actor.apply(weights["actor"], flat)
        return SquashedGaussian(mean, log_std, self.low, self.high), flat.shape[0]

    def forward(self, weights, obs):
        lead = obs.shape[: obs.ndim - len(self.spec.obs_shape)]
        dist, _ = self._dist(weights, obs)
        zeros = jnp.zeros(lead)
        return dist.base.mean.reshape(lead + (self.spec.action_dim,)), zeros

    def explore_step(self, weights, obs, key):
        lead = obs.shape[: obs.ndim - len(self.spec.obs_shape)]
        dist, _ = self._dist(weights, obs)
        a, logp = dist.sample_and_log_prob(key)
        return (a.reshape(lead + (self.spec.action_dim,)),
                logp.reshape(lead), jnp.zeros(lead))

    def inference_step(self, weights, obs):
        lead = obs.shape[: obs.ndim - len(self.spec.obs_shape)]
        dist, _ = self._dist(weights, obs)
        return dist.mode().reshape(lead + (self.spec.action_dim,)), \
            jnp.zeros(lead)


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = SAC
        self.lr = 3e-4
        self.tau = 0.005
        self.train_batch_size = 256
        self.replay_buffer_capacity = 100_000
        self.num_steps_sampled_before_learning_starts = 1000
        self.train_intensity = 1
        self.target_entropy = None   # None → -action_dim
        self.rollout_fragment_length = 8


class SAC(Algorithm):
    # subclass hooks (TQC): everything else in setup is shared
    def _make_module(self, spec, low, high):
        return SACModule(spec, low, high)

    def _init_opt_state(self):
        return {
            "actor": self.opt.init(self.weights["actor"]),
            "q1": self.opt.init(self.weights["q1"]),
            "q2": self.opt.init(self.weights["q2"]),
            "alpha": self.opt.init(self.weights["log_alpha"])}

    def setup(self, config: SACConfig):
        import gymnasium as gym
        from ..env_runner import EnvRunner
        probe = EnvRunner(env_creator=config.env, num_envs=1, rollout_len=2,
                          env_config=config.env_config)
        spec = probe.get_spec()
        space = probe.envs.single_action_space
        low = float(np.min(space.low))
        high = float(np.max(space.high))
        probe.close()
        self.module = self._make_module(spec, low, high)
        self._setup_runners()
        key = jax.random.PRNGKey(config.seed)
        self.weights = self.module.init(key)
        import optax
        self.opt = optax.adam(config.lr)
        self.opt_state = self._init_opt_state()
        self.buffer = ReplayBuffer(config.replay_buffer_capacity,
                                   seed=config.seed)
        self.env_steps = 0
        self.target_entropy = (config.target_entropy
                               if config.target_entropy is not None
                               else -float(spec.action_dim))
        self._build_update()

    def _make_runner_kwargs(self):
        kw = super()._make_runner_kwargs()
        kw["module"] = SACModule(self.module.spec, self.module.low,
                                 self.module.high)
        kw["record_next_obs"] = True
        return kw

    def _build_update(self):
        cfg = self.config
        mod = self.module
        gamma, tau = cfg.gamma, cfg.tau
        target_entropy = self.target_entropy

        def update(w, opt_state, batch, key):
            import optax
            obs, act = batch[SB.OBS], batch[SB.ACTIONS]
            nxt, rew = batch[SB.NEXT_OBS], batch[SB.REWARDS]
            done = batch[SB.TERMINATEDS]
            alpha = jnp.exp(w["log_alpha"])
            k1, k2 = jax.random.split(key)

            # -- critic target
            dist_n, _ = mod._dist(w, nxt)
            a_n, logp_n = dist_n.sample_and_log_prob(k1)
            q1_n = mod.critic.apply(w["q1_target"], nxt, a_n)
            q2_n = mod.critic.apply(w["q2_target"], nxt, a_n)
            target = rew + gamma * (1 - done) * (
                jnp.minimum(q1_n, q2_n) - alpha * logp_n)
            target = jax.lax.stop_gradient(target)

            def q_loss(qp, which):
                q = mod.critic.apply(qp, obs, act)
                return jnp.mean(jnp.square(q - target))

            l1, g1 = jax.value_and_grad(q_loss)(w["q1"], 1)
            l2, g2 = jax.value_and_grad(q_loss)(w["q2"], 2)
            u1, opt_q1 = self.opt.update(g1, opt_state["q1"], w["q1"])
            u2, opt_q2 = self.opt.update(g2, opt_state["q2"], w["q2"])
            q1p = optax.apply_updates(w["q1"], u1)
            q2p = optax.apply_updates(w["q2"], u2)

            # -- actor
            def pi_loss(ap):
                mean, log_std = mod.actor.apply(ap, obs)
                dist = SquashedGaussian(mean, log_std, mod.low, mod.high)
                a, logp = dist.sample_and_log_prob(k2)
                q = jnp.minimum(mod.critic.apply(q1p, obs, a),
                                mod.critic.apply(q2p, obs, a))
                return jnp.mean(alpha * logp - q), logp

            (la, logp), ga = jax.value_and_grad(
                pi_loss, has_aux=True)(w["actor"])
            ua, opt_a = self.opt.update(ga, opt_state["actor"], w["actor"])
            actor_p = optax.apply_updates(w["actor"], ua)

            # -- temperature
            def alpha_loss(log_alpha):
                return -jnp.mean(jnp.exp(log_alpha) *
                                 jax.lax.stop_gradient(logp + target_entropy))

            lt, gt = jax.value_and_grad(alpha_loss)(w["log_alpha"])
            ut, opt_t = self.opt.update(gt, opt_state["alpha"], w["log_alpha"])
            log_alpha = optax.apply_updates(w["log_alpha"], ut)

            # -- polyak target update
            polyak = lambda t, s: jax.tree_util.tree_map(
                lambda a, b: (1 - tau) * a + tau * b, t, s)
            new_w = {"actor": actor_p, "q1": q1p, "q2": q2p,
                     "q1_target": polyak(w["q1_target"], q1p),
                     "q2_target": polyak(w["q2_target"], q2p),
                     "log_alpha": log_alpha}
            new_opt = {"actor": opt_a, "q1": opt_q1, "q2": opt_q2,
                       "alpha": opt_t}
            metrics = {"critic_loss": 0.5 * (l1 + l2), "actor_loss": la,
                       "alpha": jnp.exp(log_alpha),
                       "entropy": -jnp.mean(logp)}
            return new_w, new_opt, metrics

        self._update = jax.jit(update, donate_argnums=(0, 1))

    def training_step(self) -> Dict:
        cfg = self.config
        host_w = jax.device_get(self.weights)
        batch, rm = self._sample_all(host_w)
        flat = batch.flatten()
        self.env_steps += flat.count
        self.buffer.add_batch({
            SB.OBS: flat[SB.OBS], SB.ACTIONS: flat[SB.ACTIONS],
            SB.REWARDS: flat[SB.REWARDS], SB.NEXT_OBS: flat[SB.NEXT_OBS],
            SB.TERMINATEDS: flat[SB.TERMINATEDS]})
        metrics = _merge_runner_metrics([rm])
        metrics["num_env_steps_sampled_this_iter"] = flat.count
        if self.env_steps < cfg.num_steps_sampled_before_learning_starts:
            return metrics
        last = {}
        for i in range(cfg.train_intensity):
            sample = self.buffer.sample(cfg.train_batch_size)
            key = jax.random.PRNGKey(self.env_steps + i)
            self.weights, self.opt_state, last = self._update(
                self.weights, self.opt_state, sample, key)
        metrics["learner"] = {k: float(v) for k, v in
                              jax.device_get(last).items()}
        return metrics

    def get_weights(self):
        return jax.device_get(self.weights)

    def set_weights(self, weights):
        self.weights = weights
