"""APPO (reference: rllib/algorithms/appo/*) — PPO's clipped surrogate on
V-trace-corrected advantages, tolerating async/stale rollouts.
"""

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.ops.losses import vtrace
from .. import sample_batch as SB
from ..connectors import standardize_advantages
from ..rl_module import RLModule
from .ppo import PPO, PPOConfig, PPOLearner


class APPOConfig(PPOConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = APPO
        self.vtrace_clip_rho = 1.0
        self.vtrace_clip_c = 1.0
        self.num_epochs = 2          # fewer epochs: data is slightly stale
        self.use_kl_loss = False


class APPO(PPO):
    _supports_sebulba = True

    def setup(self, config: APPOConfig):
        super().setup(config)
        spec = self._local_runner.get_spec()
        self._vtrace_module = self.learner.module

        def targets(params, batch):
            """V-trace value targets + pg advantages under CURRENT params."""
            dist_in, values = self._vtrace_module.forward(
                params, batch[SB.OBS])
            tlp = self._vtrace_module.dist(dist_in).log_prob(
                batch[SB.ACTIONS])
            values_tb1 = jnp.concatenate(
                [values, batch[SB.BOOTSTRAP_VALUE][None]], axis=0)
            vt = jax.vmap(
                lambda blp, t, r, v, d: vtrace(
                    blp, t, r, v, d, config.gamma,
                    config.vtrace_clip_rho, config.vtrace_clip_c),
                in_axes=1, out_axes=1,
            )(batch[SB.LOGP], tlp, batch[SB.REWARDS], values_tb1,
              batch[SB.DONES])
            return vt.pg_advantages, vt.vs

        self._targets = jax.jit(targets)

    def _sebulba_update(self, batch) -> Dict:
        """Sebulba learn stage: V-trace targets under CURRENT params (the
        correction that absorbs the pipeline's off-policy gap), then the
        clipped-surrogate update."""
        adv, vs = self._targets(self.learner.params, dict(
            {k: batch[k] for k in (SB.OBS, SB.ACTIONS, SB.LOGP, SB.REWARDS,
                                   SB.DONES, SB.BOOTSTRAP_VALUE)}))
        batch[SB.ADVANTAGES] = np.asarray(adv)
        batch[SB.VALUE_TARGETS] = np.asarray(vs)
        if self.config.standardize_advantages:
            batch = standardize_advantages(batch)
        return self.learner_group.update(batch)

    def training_step(self) -> Dict:
        cfg = self.config
        weights = self.learner.get_weights()
        from ..sample_batch import SampleBatch
        from ..algorithm import _merge_runner_metrics
        collected, runner_metrics, timesteps = [], [], 0
        while timesteps < cfg.train_batch_size:
            batch, rm = self._sample_all(weights)
            collected.append(batch)
            runner_metrics.append(rm)
            timesteps += batch[SB.REWARDS].size
        batch = (collected[0] if len(collected) == 1 else
                 SampleBatch.concat(collected, axis=1))
        # V-trace instead of GAE (the reference's APPO learner path)
        adv, vs = self._targets(self.learner.params, dict(
            {k: batch[k] for k in (SB.OBS, SB.ACTIONS, SB.LOGP, SB.REWARDS,
                                   SB.DONES, SB.BOOTSTRAP_VALUE)}))
        batch[SB.ADVANTAGES] = np.asarray(adv)
        batch[SB.VALUE_TARGETS] = np.asarray(vs)
        if cfg.standardize_advantages:
            batch = standardize_advantages(batch)
        learn = self.learner_group.update(batch)
        result = _merge_runner_metrics(runner_metrics)
        result["num_env_steps_sampled_this_iter"] = timesteps
        result["learner"] = learn
        return result
