"""CQL — Conservative Q-Learning (reference: rllib/algorithms/cql/cql.py,
which layers a conservative penalty over SAC's twin critics and trains from
offline data only).

Re-uses this framework's SAC building blocks (SACModule actor/critics,
squashed-gaussian policy, auto-tuned temperature, polyak targets) with two
changes, both inside the ONE jitted update:
- critic loss gains the CQL(H) regularizer
  `alpha_cql * (logsumexp_a Q(s,a) - Q(s, a_data))`, where the logsumexp is
  estimated with importance-weighted uniform-random actions plus policy
  samples at s and s' (the standard CQL sampling scheme).
- no environment interaction: batches come from an offline SampleBatch /
  ray_tpu.data Dataset (rllib/offline.py reader).
"""

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from .. import sample_batch as SB
from ..algorithm import Algorithm
from ..distributions import SquashedGaussian
from .offline_utils import (evaluate_continuous, load_continuous_dataset,
                            make_offline_optimizer, offline_training_step)
from .sac import SACConfig, SACModule


class CQLConfig(SACConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = CQL
        self.offline_data = None
        self.cql_alpha = 1.0          # min_q_weight
        self.num_cql_actions = 4      # sampled actions per source (rand/pi/pi')
        self.bc_iters = 0             # actor log-prob warmstart updates
        self.train_intensity = 8      # SGD steps per train() call
        self.action_low = None        # None → inferred from the dataset
        self.action_high = None

    def offline_data_source(self, data):
        self.offline_data = data
        return self


class CQL(Algorithm):
    # SAC-style weight dict ({actor, q1, ...}) can't ride a generic EnvRunner;
    # evaluation uses this class's own inline loop below
    _supports_eval_actors = False

    def setup(self, config: CQLConfig):
        if config.offline_data is None:
            raise ValueError("CQL needs config.offline_data")
        self._data, self._n, spec, low, high = \
            load_continuous_dataset(config)
        action_dim = spec.action_dim
        self.module = SACModule(spec, low, high)
        key = jax.random.PRNGKey(config.seed)
        self.weights = self.module.init(key)
        self.opt, self._lr_schedule, self.opt_state = make_offline_optimizer(
            config, self.weights, ("actor", "q1", "q2"))
        self.opt_state["alpha"] = self.opt.init(self.weights["log_alpha"])
        self.target_entropy = (config.target_entropy
                               if config.target_entropy is not None
                               else -float(action_dim))
        self._rng = np.random.default_rng(config.seed)
        self._updates = 0
        self._build_update()

    # ------------------------------------------------------------ jit update
    def _build_update(self):
        cfg = self.config
        mod = self.module
        gamma, tau = cfg.gamma, cfg.tau
        target_entropy = self.target_entropy
        n_act = cfg.num_cql_actions
        cql_alpha = cfg.cql_alpha
        low, high = mod.low, mod.high
        d_act = mod.spec.action_dim
        # log-density of the uniform proposal, for importance weighting
        log_u = -d_act * float(np.log(max(high - low, 1e-8)))

        def policy_samples(w, obs_rep, key):
            mean, log_std = mod.actor.apply(w["actor"], obs_rep)
            dist = SquashedGaussian(mean, log_std, low, high)
            a, logp = dist.sample_and_log_prob(key)
            return jax.lax.stop_gradient(a), jax.lax.stop_gradient(logp)

        def update(w, opt_state, batch, key, bc_phase):
            import optax
            obs, act = batch[SB.OBS], batch[SB.ACTIONS]
            nxt, rew = batch[SB.NEXT_OBS], batch[SB.REWARDS]
            done = batch[SB.TERMINATEDS]
            b = obs.shape[0]
            alpha = jnp.exp(w["log_alpha"])
            k_t, k_pi, k_rand, k_spi, k_spin = jax.random.split(key, 5)

            # -- SAC bellman target (twin targets, entropy-regularized)
            dist_n, _ = mod._dist(w, nxt)
            a_n, logp_n = dist_n.sample_and_log_prob(k_t)
            q1_n = mod.critic.apply(w["q1_target"], nxt, a_n)
            q2_n = mod.critic.apply(w["q2_target"], nxt, a_n)
            target = rew + gamma * (1 - done) * (
                jnp.minimum(q1_n, q2_n) - alpha * logp_n)
            target = jax.lax.stop_gradient(target)

            # -- conservative term inputs (shared across both critics)
            rep = lambda x: jnp.repeat(x, n_act, axis=0)  # [N*B, ...]
            obs_rep, nxt_rep = rep(obs), rep(nxt)
            a_rand = jax.random.uniform(k_rand, (n_act * b, d_act),
                                        minval=low, maxval=high)
            a_pi, logp_pi = policy_samples(w, obs_rep, k_spi)
            a_pin, logp_pin = policy_samples(w, nxt_rep, k_spin)

            def q_loss(qp):
                q_data = mod.critic.apply(qp, obs, act)
                bellman = jnp.mean(jnp.square(q_data - target))
                # jnp.repeat lays rows out state-major (s0,s0,..,s1,s1,..), so
                # (b, n_act) keeps each row's samples with THEIR state; the
                # logsumexp runs over the sampled-action axis
                shape = (b, n_act)
                q_rand = mod.critic.apply(qp, obs_rep, a_rand).reshape(shape)
                q_pi = mod.critic.apply(qp, obs_rep, a_pi).reshape(shape)
                # CQL(H): actions sampled from pi(.|s') are still scored at
                # the CURRENT state — all logsumexp terms estimate
                # logsumexp_a Q(s, a) (ref: rllib cql cql_torch_policy)
                q_pin = mod.critic.apply(qp, obs_rep, a_pin).reshape(shape)
                cat = jnp.concatenate([
                    q_rand - log_u,
                    q_pi - logp_pi.reshape(shape),
                    q_pin - logp_pin.reshape(shape)], axis=1)   # [B, 3N]
                gap = jax.scipy.special.logsumexp(cat, axis=1) - q_data
                return bellman + cql_alpha * jnp.mean(gap), jnp.mean(gap)

            (l1, gap1), g1 = jax.value_and_grad(q_loss, has_aux=True)(w["q1"])
            (l2, _gap2), g2 = jax.value_and_grad(q_loss, has_aux=True)(w["q2"])
            u1, opt_q1 = self.opt.update(g1, opt_state["q1"], w["q1"])
            u2, opt_q2 = self.opt.update(g2, opt_state["q2"], w["q2"])
            q1p = optax.apply_updates(w["q1"], u1)
            q2p = optax.apply_updates(w["q2"], u2)

            # -- actor: SAC objective, or pure BC log-prob during warmstart
            def pi_loss(ap):
                mean, log_std = mod.actor.apply(ap, obs)
                dist = SquashedGaussian(mean, log_std, low, high)
                a, logp = dist.sample_and_log_prob(k_pi)
                q = jnp.minimum(mod.critic.apply(q1p, obs, a),
                                mod.critic.apply(q2p, obs, a))
                sac_obj = jnp.mean(alpha * logp - q)
                bc_obj = -jnp.mean(dist.log_prob(act))
                return jnp.where(bc_phase, bc_obj, sac_obj), logp

            (la, logp), ga = jax.value_and_grad(
                pi_loss, has_aux=True)(w["actor"])
            ua, opt_a = self.opt.update(ga, opt_state["actor"], w["actor"])
            actor_p = optax.apply_updates(w["actor"], ua)

            def alpha_loss(log_alpha):
                return -jnp.mean(jnp.exp(log_alpha) *
                                 jax.lax.stop_gradient(logp + target_entropy))

            lt, gt = jax.value_and_grad(alpha_loss)(w["log_alpha"])
            ut, opt_t = self.opt.update(gt, opt_state["alpha"], w["log_alpha"])
            log_alpha = optax.apply_updates(w["log_alpha"], ut)

            polyak = lambda t, s: jax.tree_util.tree_map(
                lambda a_, b_: (1 - tau) * a_ + tau * b_, t, s)
            new_w = {"actor": actor_p, "q1": q1p, "q2": q2p,
                     "q1_target": polyak(w["q1_target"], q1p),
                     "q2_target": polyak(w["q2_target"], q2p),
                     "log_alpha": log_alpha}
            new_opt = {"actor": opt_a, "q1": opt_q1, "q2": opt_q2,
                       "alpha": opt_t}
            metrics = {"critic_loss": 0.5 * (l1 + l2), "actor_loss": la,
                       "cql_penalty": gap1, "alpha": jnp.exp(log_alpha),
                       "entropy": -jnp.mean(logp)}
            return new_w, new_opt, metrics

        self._update = jax.jit(update, donate_argnums=(0, 1),
                               static_argnums=(4,))

    # --------------------------------------------------------------- training
    def training_step(self) -> Dict:
        cfg = self.config

        def step_once(mb, i):
            key = jax.random.PRNGKey(cfg.seed * 100_003 + i)
            return self._update(self.weights, self.opt_state, mb, key,
                                i < cfg.bc_iters)

        return offline_training_step(self, step_once)

    # -------------------------------------------------------------- eval/util
    def evaluate(self) -> Dict:
        return evaluate_continuous(self)

    def get_weights(self):
        return jax.device_get(self.weights)

    def set_weights(self, weights):
        self.weights = weights
