"""Model multiplexing (reference: python/ray/serve/multiplex.py:22
_ModelMultiplexWrapper + serve/api.py:926 @serve.multiplexed).

One replica serves MANY models: the decorated loader is called per
`model_id` and its results are LRU-cached (`max_num_models_per_replica`).
Requests carry their model id via
`handle.options(multiplexed_model_id=...)` (or the
`serve_multiplexed_model_id` HTTP header through the proxy), and the
deployment reads it with `serve.get_multiplexed_model_id()`.

Routing contrast with the reference: the reference's router tracks which
replicas hold which models cluster-wide; here each handle keeps model→
replica affinity locally (sticky after first use), which converges to the
same behavior without controller chatter on the request path.
"""

import asyncio
import collections
import contextvars
import functools
from typing import Any, Callable, Optional

_current_model_id: contextvars.ContextVar = contextvars.ContextVar(
    "rtpu_serve_multiplexed_model_id", default="")


def get_multiplexed_model_id() -> str:
    """The model id of the request being handled (ref:
    serve.get_multiplexed_model_id); "" outside a multiplexed request."""
    return _current_model_id.get()


def _set_current_model_id(model_id: str):
    _current_model_id.set(model_id)


class _ModelCache:
    """Per-replica LRU of loaded models; eviction calls the model's
    `unload()`/`__del__` like the reference's wrapper.

    In-use protection (r4 ADVICE): every get_model takes a LEASE bound to
    the calling asyncio task (the replica runs one task per request), and
    eviction skips models with live leases — a long request on model A no
    longer has A's device memory unloaded underneath it when other models
    load concurrently (the reference wrapper keeps per-model in-use counts
    the same way). If every cached model is leased, the cache temporarily
    overflows and re-enforces the cap as leases drain."""

    def __init__(self, loader: Callable, max_models: int):
        self.loader = loader
        self.max_models = max_models
        self.models: "collections.OrderedDict[str, Any]" = \
            collections.OrderedDict()
        self._loading: dict = {}  # model_id -> asyncio.Future
        self.in_use: collections.Counter = collections.Counter()

    def _lease(self, model_id: str):
        """Pin `model_id` until the calling request task finishes."""
        task = asyncio.current_task()
        if task is None:
            return
        self.in_use[model_id] += 1

        def _release(_t, mid=model_id):
            self.in_use[mid] -= 1
            if self.in_use[mid] <= 0:
                del self.in_use[mid]
                if len(self.models) > self.max_models:
                    # cap was overflowed while every model was leased; trim
                    # back to EXACTLY max_models (limit=max+1: _evict_to
                    # stops at len < limit — passing max here would land at
                    # max-1 and near-simultaneous releases could empty the
                    # cache entirely)
                    asyncio.get_running_loop().create_task(
                        self._evict_to(self.max_models + 1))

        task.add_done_callback(_release)

    async def _evict_to(self, limit: int):
        # LRU order, but never unload a model a live request still uses
        while len(self.models) >= limit:
            victim = next((mid for mid in self.models
                           if not self.in_use.get(mid)), None)
            if victim is None:
                return  # all leased: allow temporary overflow
            old = self.models.pop(victim)
            unload = getattr(old, "unload", None)
            if callable(unload):
                maybe = unload()
                if asyncio.iscoroutine(maybe):
                    await maybe
            del old

    async def get_model(self, owner, model_id: str):
        model = await self._get_or_load(owner, model_id)
        self._lease(model_id)
        return model

    async def _get_or_load(self, owner, model_id: str):
        if model_id in self.models:
            self.models.move_to_end(model_id)
            return self.models[model_id]
        fut = self._loading.get(model_id)
        if fut is not None:  # concurrent request for the same model: share
            return await fut
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._loading[model_id] = fut
        try:
            # evict BEFORE loading: if max_models models fill the device,
            # holding N+1 during the load would OOM exactly when the cap is
            # sized to the hardware
            await self._evict_to(self.max_models)
            out = self.loader(owner, model_id)
            if asyncio.iscoroutine(out):
                out = await out
            # concurrent loads of DISTINCT models can each pass the first
            # eviction check; re-enforce the cap before inserting
            await self._evict_to(self.max_models)
            self.models[model_id] = out
            fut.set_result(out)
            return out
        except BaseException as e:  # noqa: BLE001 - propagate to all waiters
            fut.set_exception(e)
            raise
        finally:
            self._loading.pop(model_id, None)


def should_rebalance_pin(inflight_by_idx, pinned_idx: int,
                         factor: float = 2.0, min_inflight: int = 2) -> bool:
    """Evict a model->replica pin when the pinned replica's handle-local
    inflight exceeds `factor`x the fleet median (ISSUE 20 satellite: sticky
    affinity previously never rebalanced, so one hot LoRA pinned its
    replica into the ground while the rest of the fleet idled).

    median_low, not the interpolated median: with two replicas the
    interpolated median of [hot, idle] is (hot+idle)/2, and hot > 2*that
    is algebraically impossible — the smallest fleet could never rebalance.
    `min_inflight` keeps single-digit blips from flapping pins."""
    import statistics
    n = len(inflight_by_idx)
    if n < 2 or pinned_idx >= n:
        return False
    q = inflight_by_idx[pinned_idx]
    if q < min_inflight:
        return False
    return q > factor * statistics.median_low(inflight_by_idx)


def multiplexed(func: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """Decorator for the model-loading method of a deployment:

        @serve.deployment
        class Translator:
            @serve.multiplexed(max_num_models_per_replica=4)
            async def get_model(self, model_id: str):
                return load_weights(model_id)

            async def __call__(self, request):
                model = await self.get_model(serve.get_multiplexed_model_id())
                return model(request.body)
    """
    def wrap(loader: Callable):
        cache = _ModelCache(loader, max_num_models_per_replica)

        @functools.wraps(loader)
        async def inner(self, model_id: str):
            return await cache.get_model(self, model_id)

        inner.__rtpu_multiplexed__ = cache
        return inner

    if func is not None:
        return wrap(func)
    return wrap
