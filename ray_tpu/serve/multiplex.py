"""Model multiplexing (reference: python/ray/serve/multiplex.py:22
_ModelMultiplexWrapper + serve/api.py:926 @serve.multiplexed).

One replica serves MANY models: the decorated loader is called per
`model_id` and its results are LRU-cached (`max_num_models_per_replica`).
Requests carry their model id via
`handle.options(multiplexed_model_id=...)` (or the
`serve_multiplexed_model_id` HTTP header through the proxy), and the
deployment reads it with `serve.get_multiplexed_model_id()`.

Routing contrast with the reference: the reference's router tracks which
replicas hold which models cluster-wide; here each handle keeps model→
replica affinity locally (sticky after first use), which converges to the
same behavior without controller chatter on the request path.
"""

import asyncio
import collections
import contextvars
import functools
from typing import Any, Callable, Optional

_current_model_id: contextvars.ContextVar = contextvars.ContextVar(
    "rtpu_serve_multiplexed_model_id", default="")


def get_multiplexed_model_id() -> str:
    """The model id of the request being handled (ref:
    serve.get_multiplexed_model_id); "" outside a multiplexed request."""
    return _current_model_id.get()


def _set_current_model_id(model_id: str):
    _current_model_id.set(model_id)


class _ModelCache:
    """Per-replica LRU of loaded models; eviction calls the model's
    `unload()`/`__del__` like the reference's wrapper."""

    def __init__(self, loader: Callable, max_models: int):
        self.loader = loader
        self.max_models = max_models
        self.models: "collections.OrderedDict[str, Any]" = \
            collections.OrderedDict()
        self._loading: dict = {}  # model_id -> asyncio.Future

    async def get_model(self, owner, model_id: str):
        if model_id in self.models:
            self.models.move_to_end(model_id)
            return self.models[model_id]
        fut = self._loading.get(model_id)
        if fut is not None:  # concurrent request for the same model: share
            return await fut
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._loading[model_id] = fut
        async def _evict_to(limit: int):
            while len(self.models) >= limit:
                _old_id, old = self.models.popitem(last=False)
                unload = getattr(old, "unload", None)
                if callable(unload):
                    maybe = unload()
                    if asyncio.iscoroutine(maybe):
                        await maybe
                del old

        try:
            # evict BEFORE loading: if max_models models fill the device,
            # holding N+1 during the load would OOM exactly when the cap is
            # sized to the hardware
            await _evict_to(self.max_models)
            out = self.loader(owner, model_id)
            if asyncio.iscoroutine(out):
                out = await out
            # concurrent loads of DISTINCT models can each pass the first
            # eviction check; re-enforce the cap before inserting
            await _evict_to(self.max_models)
            self.models[model_id] = out
            fut.set_result(out)
            return out
        except BaseException as e:  # noqa: BLE001 - propagate to all waiters
            fut.set_exception(e)
            raise
        finally:
            self._loading.pop(model_id, None)


def multiplexed(func: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """Decorator for the model-loading method of a deployment:

        @serve.deployment
        class Translator:
            @serve.multiplexed(max_num_models_per_replica=4)
            async def get_model(self, model_id: str):
                return load_weights(model_id)

            async def __call__(self, request):
                model = await self.get_model(serve.get_multiplexed_model_id())
                return model(request.body)
    """
    def wrap(loader: Callable):
        cache = _ModelCache(loader, max_num_models_per_replica)

        @functools.wraps(loader)
        async def inner(self, model_id: str):
            return await cache.get_model(self, model_id)

        inner.__rtpu_multiplexed__ = cache
        return inner

    if func is not None:
        return wrap(func)
    return wrap
