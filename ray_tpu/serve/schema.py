"""Declarative serve config (reference: python/ray/serve/schema.py
ServeDeploySchema / ServeApplicationSchema + `serve deploy`).

A config file (YAML or JSON) describes applications by import path plus
per-deployment overrides; `deploy_config` imports each target, applies the
overrides through `.options()`, and `serve.run`s it. The same schema
round-trips from `build_app_config`.

    applications:
      - name: summarizer
        route_prefix: /sum
        import_path: my_pkg.serving:app       # BoundDeployment or builder fn
        args: {model: t5-small}               # passed when target is a fn
        deployments:
          - name: Summarizer
            num_replicas: 2
            user_config: {beam: 4}
            max_ongoing_requests: 16

`user_config` reaches the replica through `instance.reconfigure(...)`
(replica.py) when the deployment class defines it — e.g. an LLMServer
deployment takes `user_config: {decode_chunk: 16}` to retune the fused
decode-chunk length at deploy time without a param reload (llm.py
LLMServer.reconfigure).
"""

import dataclasses
import importlib
import json
from typing import Any, Dict, List, Optional

from .deployment import BoundDeployment

_DEPLOYMENT_OVERRIDES = ("num_replicas", "user_config",
                         "max_ongoing_requests", "ray_actor_options",
                         "autoscaling_config")


@dataclasses.dataclass
class DeploymentSchema:
    name: str
    num_replicas: Optional[int] = None
    user_config: Optional[Dict] = None
    max_ongoing_requests: Optional[int] = None
    ray_actor_options: Optional[Dict] = None
    autoscaling_config: Optional[Dict] = None

    def overrides(self) -> Dict[str, Any]:
        out = {}
        for f in _DEPLOYMENT_OVERRIDES:
            v = getattr(self, f)
            if v is not None:
                out[f] = v
        return out


@dataclasses.dataclass
class ServeApplicationSchema:
    import_path: str
    name: str = "default"
    route_prefix: Optional[str] = None
    args: Optional[Dict] = None
    deployments: List[DeploymentSchema] = dataclasses.field(
        default_factory=list)

    @classmethod
    def from_dict(cls, d: Dict) -> "ServeApplicationSchema":
        deps = [DeploymentSchema(**x) for x in d.get("deployments", [])]
        return cls(import_path=d["import_path"], name=d.get("name", "default"),
                   route_prefix=d.get("route_prefix"),
                   args=d.get("args"), deployments=deps)


@dataclasses.dataclass
class ServeDeploySchema:
    applications: List[ServeApplicationSchema]
    http_options: Optional[Dict] = None

    @classmethod
    def from_dict(cls, d: Dict) -> "ServeDeploySchema":
        apps = [ServeApplicationSchema.from_dict(a)
                for a in d.get("applications", [])]
        if not apps:
            raise ValueError("config has no applications")
        return cls(applications=apps, http_options=d.get("http_options"))


def load_config(path_or_dict) -> ServeDeploySchema:
    if isinstance(path_or_dict, dict):
        return ServeDeploySchema.from_dict(path_or_dict)
    with open(path_or_dict) as f:
        text = f.read()
    try:
        import yaml
        data = yaml.safe_load(text)
    except ImportError:  # pragma: no cover - yaml is in the image
        data = json.loads(text)
    return ServeDeploySchema.from_dict(data)


def _import_target(import_path: str):
    """'pkg.module:attr' (or dotted fallback) → the object."""
    if ":" in import_path:
        mod_name, attr = import_path.split(":", 1)
    else:
        mod_name, _, attr = import_path.rpartition(".")
    mod = importlib.import_module(mod_name)
    obj = mod
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


def _apply_overrides(bound: BoundDeployment,
                     deployments: List[DeploymentSchema]) -> BoundDeployment:
    """Rebind the app graph with per-deployment option overrides by name."""
    by_name = {d.name: d.overrides() for d in deployments}
    if not by_name:
        return bound

    seen = {}

    def rebuild(node: BoundDeployment) -> BoundDeployment:
        if id(node) in seen:
            return seen[id(node)]
        args = tuple(rebuild(a) if isinstance(a, BoundDeployment) else a
                     for a in node.args)
        kwargs = {k: (rebuild(v) if isinstance(v, BoundDeployment) else v)
                  for k, v in node.kwargs.items()}
        dep = node.deployment
        ov = by_name.get(dep.name)
        if ov:
            dep = dep.options(**ov)
        out = dep.bind(*args, **kwargs)
        seen[id(node)] = out
        return out

    return rebuild(bound)


def deploy_config(path_or_dict, *, start_http: bool = True) -> Dict[str, Any]:
    """Deploy every application in a config (ref: `serve deploy` /
    serve.run_many). Returns {app_name: handle}."""
    from . import api as serve_api

    schema = load_config(path_or_dict)
    handles = {}
    for app in schema.applications:
        target = _import_target(app.import_path)
        if isinstance(target, BoundDeployment):
            bound = target
        elif callable(target):
            bound = target(**(app.args or {}))
        else:
            raise TypeError(
                f"{app.import_path} is neither a bound deployment nor a "
                f"builder function")
        if not isinstance(bound, BoundDeployment):
            raise TypeError(f"{app.import_path} did not produce a bound "
                            f"deployment")
        bound = _apply_overrides(bound, app.deployments)
        handles[app.name] = serve_api.run(
            bound, name=app.name, route_prefix=app.route_prefix)
    if start_http:
        serve_api.start(http_options=schema.http_options or None)
    return handles


def build_app_config(bound: BoundDeployment, import_path: str,
                     name: str = "default",
                     route_prefix: Optional[str] = None) -> Dict:
    """The config dict for a bound app (ref: `serve build`): callers write
    it to YAML and hand it to `deploy_config` / the CLI."""
    deps = []
    for node in bound.walk():
        d = node.deployment
        cfg = d.config
        deps.append({k: v for k, v in {
            "name": d.name,
            "num_replicas": cfg.num_replicas,
            "user_config": cfg.user_config,
            "max_ongoing_requests": cfg.max_ongoing_requests,
        }.items() if v is not None})
    return {"applications": [{
        "name": name, "import_path": import_path,
        **({"route_prefix": route_prefix} if route_prefix else {}),
        "deployments": deps,
    }]}
