"""Serve controller (reference: serve/_private/controller.py ServeController
actor) — registry of apps → deployments → replica actor handles, plus the
autoscaling decision loop.
"""

import asyncio
import math
import time
from typing import Dict, List, Optional

CONTROLLER_NAME = "SERVE_CONTROLLER"


class ServeController:
    def __init__(self):
        # {app: {deployment: {"replicas": [handles], "config": DeploymentConfig,
        #        "blob": bytes, "init": (args, kwargs), "version": int}}}
        self.apps: Dict[str, Dict[str, Dict]] = {}
        # route prefix -> (app, ingress deployment, is_streaming)
        self.routes: Dict[str, tuple] = {}
        self._autoscale_task = None
        # SLO-autoscale causality trail, same audited shape as the PR 17
        # node reconciler (reaction time = burst ts -> first record here)
        from ray_tpu.autoscaler.reconciler import ScaleLedger
        self._ledger = ScaleLedger(counter="serve_scale_events_total")

    # -- registry ------------------------------------------------------------
    def register_deployment(self, app: str, name: str, blob, init_args,
                            init_kwargs, config) -> None:
        existing = self.apps.get(app, {}).get(name)
        if existing is not None:
            # redeploy: retire old replicas first (their actor names would
            # collide, and dropping the handles would leak the processes)
            self._scale_to(app, name, 0)
        version = existing["version"] + 1 if existing else 0
        self.apps.setdefault(app, {})[name] = {
            "replicas": [], "config": config, "blob": blob,
            "init": (init_args, init_kwargs), "version": version,
            "next_idx": existing["next_idx"] if existing else 0,
            "last_scale_ts": 0.0,
            # prefix-affinity digests + windowed SLO snapshots, keyed by
            # replica index; refreshed off the autoscale stats gather (or
            # lazily, TTL-gated) and piggybacked to handles in
            # get_replica_state — never a request-path round trip
            "digests": {}, "replica_slo": {}, "digest_ts": 0.0,
        }
        self._scale_to(app, name, config.num_replicas)

    def list_apps(self) -> List[str]:
        return list(self.apps)

    def set_route(self, prefix: str, app: str, ingress: str,
                  is_streaming: bool = False) -> None:
        held_by = self.routes.get(prefix)
        if held_by is not None and held_by[0] != app:
            raise ValueError(
                f"route_prefix '{prefix}' is already used by app "
                f"'{held_by[0]}'; pick a different prefix or delete that app")
        # one route per app: re-registering moves the prefix
        self.routes = {p: t for p, t in self.routes.items() if t[0] != app}
        self.routes[prefix] = (app, ingress, is_streaming)

    def get_routes(self) -> Dict[str, tuple]:
        return dict(self.routes)

    def delete_app(self, app: str) -> None:
        import ray_tpu
        self.routes = {p: t for p, t in self.routes.items() if t[0] != app}
        for name, rec in self.apps.pop(app, {}).items():
            for h in rec["replicas"]:
                try:
                    ray_tpu.kill(h)
                except Exception:  # noqa: BLE001 - already dead
                    pass

    def list_deployments(self, app: str) -> List[str]:
        return list(self.apps.get(app, {}))

    def get_replicas(self, app: str, name: str):
        return self.apps[app][name]["replicas"]

    def get_version(self, app: str, name: str) -> int:
        rec = self.apps.get(app, {}).get(name)
        return -1 if rec is None else rec["version"]

    def num_replicas(self, app: str, name: str) -> int:
        return len(self.apps[app][name]["replicas"])

    # seconds a cached digest set stays fresh before get_replica_state
    # re-polls replica stats (RAY_TPU_PREFIX_DIGEST_TTL_S)
    @staticmethod
    def _digest_ttl_s() -> float:
        import os
        try:
            return float(os.environ.get("RAY_TPU_PREFIX_DIGEST_TTL_S", "1.0"))
        except ValueError:
            return 1.0

    def _gather_stats(self, rec) -> list:
        """Poll every replica's stats frame and refresh the digest/SLO
        cache from it — the ONE fan-out both the autoscaler and the lazy
        digest refresh share. Returns [(idx, stats), ...] for replicas
        that answered."""
        import ray_tpu
        refs = [(i, h.stats.remote()) for i, h in enumerate(rec["replicas"])]
        out = []
        digests, slo = {}, {}
        for i, ref in refs:
            try:
                s = ray_tpu.get(ref, timeout=5)
            except Exception:  # noqa: BLE001 - replica restarting/dead
                continue
            out.append((i, s))
            if s.get("prefix_digest"):
                digests[i] = s["prefix_digest"]
            if s.get("slo"):
                slo[i] = s["slo"]
        rec["digests"] = digests
        rec["replica_slo"] = slo
        rec["digest_ts"] = time.time()
        return out

    def get_replica_state(self, app: str, name: str) -> Dict:
        """Everything a handle refresh needs in ONE round trip: version,
        replica handles, and the cached prefix-affinity digests. Digests
        are refreshed TTL-gated from here (controller -> replica, off the
        request path) when the autoscaler loop isn't already doing it."""
        rec = self.apps.get(app, {}).get(name)
        if rec is None:
            return {"version": -1, "replicas": [], "digests": {}}
        if time.time() - rec["digest_ts"] > self._digest_ttl_s():
            self._gather_stats(rec)
        return {"version": rec["version"],
                "replicas": list(rec["replicas"]),
                "digests": dict(rec["digests"])}

    # -- scaling -------------------------------------------------------------
    _DRAIN_TIMEOUT_S = 3.0

    def _scale_to(self, app: str, name: str, target: int) -> None:
        import ray_tpu
        from .replica import Replica

        rec = self.apps[app][name]
        cfg = rec["config"]
        replicas = rec["replicas"]
        while len(replicas) < target:
            # monotonic replica index: names never collide with ones being
            # torn down (redeploy) or previously downscaled
            idx = rec.setdefault("next_idx", len(replicas))
            rec["next_idx"] = idx + 1
            opts = dict(cfg.ray_actor_options or {})
            opts.setdefault("max_concurrency", cfg.max_ongoing_requests)
            opts["name"] = f"SERVE::{app}::{name}#{idx}"
            actor_cls = ray_tpu.remote(**opts)(Replica)
            args, kwargs = rec["init"]
            replicas.append(actor_cls.remote(rec["blob"], args, kwargs,
                                             cfg.user_config,
                                             (app, name, f"{name}#{idx}")))
        doomed = []
        while len(replicas) > target:
            doomed.append(replicas.pop())
        if doomed:
            # bump version FIRST so handles re-route before the kill lands,
            # then drain: a doomed replica is only killed once its ongoing
            # count hits 0 (or the deadline passes — counted, so the
            # zero-failed-requests drain gate in fleet_bench can assert)
            rec["version"] += 1
            deadline = time.time() + self._DRAIN_TIMEOUT_S
            for h in doomed:
                drained = False
                while time.time() < deadline:
                    try:
                        if ray_tpu.get(h.stats.remote(),
                                       timeout=1)["ongoing"] == 0:
                            drained = True
                            break
                    except Exception:  # noqa: BLE001 - already dead
                        drained = True
                        break
                    time.sleep(0.05)
                if not drained:
                    self._ledger.record("drain_timeout", app=app,
                                        deployment=name)
                try:
                    ray_tpu.kill(h)
                except Exception:  # noqa: BLE001
                    pass
        rec["version"] += 1
        rec["last_scale_ts"] = time.time()
        # replica indices shifted: cached digests/SLO frames are stale
        rec["digests"], rec["replica_slo"], rec["digest_ts"] = {}, {}, 0.0

    def autoscale_once(self) -> Dict[str, int]:
        """One pass of the autoscaler over every deployment; returns the new
        replica counts. Policy (reference: serve autoscaling_policy.py):
        desired = ceil(total_ongoing / target_ongoing_requests), then the
        SLO overlay (ISSUE 20): a windowed TTFT/TPOT p99 breach or hot
        batch occupancy forces a one-step scale-up, and scale-down is held
        unless the fleet sits well inside target. Every replica-count
        change (and suppressed change) lands in the scale ledger with its
        reason — the audit trail fleet_bench measures reaction time from.
        The same stats gather refreshes the prefix-digest cache, so
        affinity hints ride the existing refresh for free."""
        decisions = {}
        now = time.time()
        for app, deps in self.apps.items():
            for name, rec in deps.items():
                auto = rec["config"].autoscaling_config
                if auto is None:
                    continue
                stats = self._gather_stats(rec)
                ongoing = sum(s["ongoing"] for _i, s in stats)
                cur = len(rec["replicas"])
                desired, reason = decide_num_replicas_slo(
                    ongoing, cur, auto,
                    aggregate_slo([s.get("slo") for _i, s in stats]))
                decisions[f"{app}:{name}"] = desired
                if desired == cur:
                    continue
                delay = (auto.upscale_delay_s if desired > cur
                         else auto.downscale_delay_s)
                if now - rec["last_scale_ts"] < delay:
                    self._ledger.record("scale_suppressed", app=app,
                                        deployment=name, reason=reason,
                                        cur=cur, desired=desired,
                                        cooldown_s=delay)
                    decisions[f"{app}:{name}"] = cur
                    continue
                self._ledger.record(
                    "scale_up" if desired > cur else "scale_down",
                    app=app, deployment=name, reason=reason,
                    cur=cur, desired=desired, ongoing=ongoing)
                self._scale_to(app, name, desired)
        return decisions

    def scale_events(self, n: int = 64):
        return self._ledger.tail(n)

    def report_replica_death(self, app: str, name: str, actor_id) -> int:
        """A handle hit ActorDiedError on this replica: prune the corpse
        from the fleet and bump the version, so every OTHER handle stops
        routing to it at its next refresh (<= one refresh interval) instead
        of paying a died-retry per request forever. Autoscaled deployments
        get a replacement on the next autoscale tick (len < desired).
        Returns the surviving replica count."""
        rec = self.apps.get(app, {}).get(name)
        if rec is None:
            return 0
        keep = [h for h in rec["replicas"]
                if getattr(h, "_actor_id", None) != actor_id]
        if len(keep) != len(rec["replicas"]):
            rec["replicas"][:] = keep
            rec["version"] += 1
            # replica indices shifted: cached digests/SLO frames are stale
            rec["digests"], rec["replica_slo"], rec["digest_ts"] = {}, {}, 0.0
            self._ledger.record("replica_dead", app=app, deployment=name,
                                actor=str(actor_id))
        return len(keep)

    async def run_autoscaler(self, interval_s: float = 2.0):
        while True:
            await asyncio.sleep(interval_s)
            self.autoscale_once()

    async def start_autoscaler(self, interval_s: float = 2.0):
        # async → runs on the actor's asyncio loop, so the task lives there
        if self._autoscale_task is None:
            self._autoscale_task = asyncio.get_running_loop().create_task(
                self.run_autoscaler(interval_s))
        return True

    def ping(self):
        return "pong"


def aggregate_slo(slo_frames) -> Optional[Dict]:
    """Fleet-level SLO view from per-replica windowed snapshots: worst-case
    (max) p99s — one overloaded replica IS an SLO problem even if the mean
    looks fine — and mean occupancy. None when no replica reported."""
    frames = [f for f in (slo_frames or []) if f]
    if not frames:
        return None
    out = {}
    for key in ("ttft_p99_s", "tpot_p99_ms"):
        vals = [f[key] for f in frames if f.get(key) is not None]
        out[key] = max(vals) if vals else None
    occ = [f["occupancy_mean"] for f in frames
           if f.get("occupancy_mean") is not None]
    out["occupancy_mean"] = sum(occ) / len(occ) if occ else None
    return out


def decide_num_replicas_slo(total_ongoing: float, current: int, auto,
                            slo: Optional[Dict]) -> tuple:
    """Pure SLO-aware scaling decision (unit-testable): start from the
    ongoing-count policy, then overlay the fleet SLO snapshot —

      * breach (windowed TTFT/TPOT p99 over target) or hot batch
        (occupancy >= occupancy_high): force at least current+1;
      * ongoing-count says shrink: only allow it when every tracked p99 is
        within downscale_slo_margin of its target (a fleet near the line
        keeps its headroom).

    Returns (desired, reason) clamped to [min_replicas, max_replicas]."""
    desired = decide_num_replicas(total_ongoing, current, auto)
    reason = "ongoing"
    if slo is not None and current > 0:
        ttft, tpot = slo.get("ttft_p99_s"), slo.get("tpot_p99_ms")
        occ = slo.get("occupancy_mean")
        t_ttft, t_tpot = auto.target_ttft_p99_s, auto.target_tpot_p99_ms
        breach = ((t_ttft is not None and ttft is not None and ttft > t_ttft)
                  or (t_tpot is not None and tpot is not None
                      and tpot > t_tpot))
        hot = occ is not None and occ >= auto.occupancy_high
        if breach or hot:
            desired = max(desired, current + 1)
            reason = "slo_breach" if breach else "occupancy"
        elif desired < current:
            margin = auto.downscale_slo_margin
            inside = ((t_ttft is None or ttft is None
                       or ttft <= margin * t_ttft)
                      and (t_tpot is None or tpot is None
                           or tpot <= margin * t_tpot))
            if not inside:
                desired, reason = current, "slo_hold"
    return (int(min(max(desired, auto.min_replicas), auto.max_replicas)),
            reason)


def decide_num_replicas(total_ongoing: float, current: int, auto) -> int:
    """Pure autoscaling decision (unit-testable): scale toward
    total_ongoing / target, clamped to [min_replicas, max_replicas].
    No special bootstrap branch: with min_replicas=0 and no demand the
    answer stays 0 (a forced floor of 1 would flap 0↔1 every interval)."""
    desired = math.ceil(total_ongoing / max(auto.target_ongoing_requests, 1e-9))
    return int(min(max(desired, auto.min_replicas), auto.max_replicas))


def get_controller():
    """The named controller actor, creating it on first use."""
    import ray_tpu
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        pass
    ctrl = ray_tpu.remote(num_cpus=0, max_concurrency=16,
                          name=CONTROLLER_NAME)(ServeController).remote()
    # materialize creation before handing out (racing callers get_actor)
    import ray_tpu as rt
    rt.get(ctrl.ping.remote())
    return ctrl
